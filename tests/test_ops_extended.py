"""Exscan, user-defined reduction ops (MPI_Op_create), and MAXLOC/MINLOC —
semantics vs numpy oracles on both the thread backend and the 8-device
virtual-CPU SPMD backend (SURVEY.md §4 items 1-2)."""

import numpy as np
import pytest

from mpi_tpu import ops
from mpi_tpu.transport.local import run_local
from mpi_tpu.tpu import run_spmd

P = 8


def _absmax(a, b):
    # associative + commutative, works on numpy arrays and jax tracers alike
    return ops._maximum(abs(a), abs(b))


ABSMAX = ops.make_op(_absmax, 0.0, name="absmax")


# -- exscan ----------------------------------------------------------------


@pytest.mark.parametrize("n", [1, 2, 5, 8])
def test_exscan_local(n):
    rng = np.random.RandomState(0)
    d = rng.randn(n, 6)

    def prog(comm):
        return comm.exscan(d[comm.rank], op=ops.SUM)

    res = run_local(prog, n)
    np.testing.assert_allclose(np.asarray(res[0]), np.zeros(6), atol=0)
    for r in range(1, n):
        np.testing.assert_allclose(res[r], d[:r].sum(0), rtol=1e-10)


def test_exscan_local_scalar_prod():
    def prog(comm):
        return comm.exscan(np.float64(comm.rank + 2), op=ops.PROD)

    res = run_local(prog, 4)
    expect = [1.0, 2.0, 6.0, 24.0]  # identity, 2, 2*3, 2*3*4
    for got, want in zip(res, expect):
        assert float(np.asarray(got)) == want


def test_exscan_spmd():
    rng = np.random.RandomState(1)
    d = np.asarray(rng.randn(P, 5), np.float32)

    def prog(comm, x):
        return comm.exscan(x[comm.rank], op=ops.SUM)

    out = np.asarray(run_spmd(prog, d))
    np.testing.assert_allclose(out[0], np.zeros(5), atol=0)
    for r in range(1, P):
        np.testing.assert_allclose(out[r], d[:r].sum(0), rtol=1e-5)


def test_scan_exscan_consistency_spmd():
    # scan == combine(exscan, local) on every rank
    d = np.asarray(np.random.RandomState(2).randn(P, 3), np.float32)

    def prog(comm, x):
        mine = x[comm.rank]
        return comm.scan(mine, ops.SUM) - comm.exscan(mine, ops.SUM) - mine

    out = np.asarray(run_spmd(prog, d))
    np.testing.assert_allclose(out, np.zeros((P, 3)), atol=1e-5)


# -- user-defined ops ------------------------------------------------------


@pytest.mark.parametrize("algo", ["auto", "ring", "reduce_bcast"])
def test_custom_op_local(algo):
    rng = np.random.RandomState(3)
    d = rng.randn(4, 7)

    def prog(comm):
        return comm.allreduce(d[comm.rank], op=ABSMAX, algorithm=algo)

    for got in run_local(prog, 4):
        np.testing.assert_allclose(got, np.abs(d).max(0), rtol=1e-10)


@pytest.mark.parametrize("algo", ["fused", "ring", "recursive_halving"])
def test_custom_op_spmd(algo):
    d = np.asarray(np.random.RandomState(4).randn(P, 6), np.float32)

    def prog(comm, x):
        return comm.allreduce(x[comm.rank], op=ABSMAX, algorithm=algo)

    out = np.asarray(run_spmd(prog, d))
    for r in range(P):
        np.testing.assert_allclose(out[r], np.abs(d).max(0), rtol=1e-5)


def test_custom_op_identity_callable():
    cap = ops.make_op(lambda a, b: ops._minimum(a + b, 100.0),
                      identity=lambda dt: np.dtype(dt).type(0), name="capsum")
    assert cap.identity(np.float32) == 0
    assert cap.combine(60.0, 70.0) == 100.0


# -- maxloc / minloc -------------------------------------------------------


def test_maxloc_minloc_local():
    d = np.array([[3.0, -1.0], [7.0, -5.0], [7.0, 2.0], [0.0, -5.0]])

    def prog(comm):
        return comm.maxloc(d[comm.rank]), comm.minloc(d[comm.rank])

    for (mx, mxr), (mn, mnr) in run_local(prog, 4):
        np.testing.assert_allclose(mx, [7.0, 2.0])
        np.testing.assert_array_equal(mxr, [1, 2])  # lowest rank wins the tie
        np.testing.assert_allclose(mn, [0.0, -5.0])
        np.testing.assert_array_equal(mnr, [3, 1])


def test_maxloc_scalar_local():
    def prog(comm):
        val = [5.0, 9.0, 1.0, 9.0][comm.rank]
        return comm.maxloc(val)

    for mx, r in run_local(prog, 4):
        assert float(mx) == 9.0 and int(r) == 1


def test_maxloc_minloc_spmd():
    d = np.asarray(np.random.RandomState(5).randn(P, 4), np.float32)

    def prog(comm, x):
        mx, mxr = comm.maxloc(x[comm.rank])
        mn, mnr = comm.minloc(x[comm.rank])
        return mx, mxr.astype(np.int32), mn, mnr.astype(np.int32)

    mx, mxr, mn, mnr = run_spmd(prog, d)
    for r in range(P):
        np.testing.assert_allclose(np.asarray(mx)[r], d.max(0), rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(mxr)[r], d.argmax(0))
        np.testing.assert_allclose(np.asarray(mn)[r], d.min(0), rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(mnr)[r], d.argmin(0))


# -- flat API --------------------------------------------------------------


def test_api_exports():
    from mpi_tpu import api

    for name in ("MPI_Exscan", "MPI_Op_create", "MPI_Maxloc", "MPI_Minloc",
                 "LAND", "BXOR"):
        assert hasattr(api, name)
    assert api.MPI_Op_create is ops.make_op
