"""Payload framing shared by the byte-stream transports (socket, shm).

Two frame formats ride the same length-prefixed stream, distinguished by
the top bit of the u64 length word (RAW_FLAG):

* pickle frames — arbitrary picklable envelopes ``(ctx, tag, obj)``; the
  reference's wire format (SURVEY.md §2 #2 [B: "socket/pickle path"]).
* raw-array frames — contiguous numpy arrays ship as a tiny pickled meta
  header ``(ctx, tag, dtype.str, shape)`` followed by the array's raw
  bytes.  The hot payload is never pickled: the sender hands the buffer
  pointer straight to the ring/socket (ONE copy, into the transport) and
  the receiver reads straight into the freshly-allocated result array
  (ONE copy, out) — this is what makes the native data plane actually
  faster than pickle-over-TCP at bandwidth sizes (VERDICT round 1,
  "what's weak" #2).

Eligibility for the raw path: any ``np.ndarray`` without Python-object
fields (object dtypes and structured/void dtypes fall back to pickle,
which handles them correctly).  Non-contiguous arrays are compacted with
``ascontiguousarray`` first — still cheaper than pickling.
"""

from __future__ import annotations

import pickle
import struct
import sys
import threading
import weakref
from typing import Any, Optional, Tuple

import numpy as np

# u64 length word: top bit = raw-array frame, low 63 bits = body length
RAW_FLAG = 1 << 63
LEN_MASK = RAW_FLAG - 1
META = struct.Struct("<I")  # meta-pickle length prefix inside a raw body

_PROTO = pickle.HIGHEST_PROTOCOL


def as_raw_array(payload: Any) -> Optional[np.ndarray]:
    """The contiguous ndarray to ship raw, or None → use pickle.

    Exact-type check: ndarray SUBCLASSES (MaskedArray, np.matrix, ...)
    carry state the raw frame cannot represent — they keep the pickle
    path, which round-trips them faithfully."""
    if (type(payload) is np.ndarray and not payload.dtype.hasobject
            and payload.dtype.kind != "V"):
        if payload.flags["C_CONTIGUOUS"]:
            return payload
        # compact a strided view (ascontiguousarray would also promote
        # 0-dim to 1-dim, but 0-dim arrays are always contiguous)
        return np.ascontiguousarray(payload)
    return None


def pack_raw_meta(ctx, tag: int, arr: np.ndarray) -> bytes:
    """``<u32 meta_len><meta pickle>`` — everything in the raw body except
    the array bytes themselves."""
    meta = pickle.dumps((ctx, tag, arr.dtype.str, arr.shape), protocol=_PROTO)
    return META.pack(len(meta)) + meta


class _BufferPool:
    """Recycles large receive buffers between messages.

    Why: at bandwidth sizes the receiver's dominant cost on this class of
    box is not the copy but the PAGE FAULTS of touching a freshly-mmapped
    destination — measured on the 16MB stream: 48.8k minor faults, 84ms
    system time of a 120ms wall (one fault per 4KB page, every message,
    because glibc munmaps large frees).  Handing each recv an
    already-faulted buffer removes that entire pass.

    Safety: the user owns the returned array indefinitely, so a buffer is
    recycled only when proven unreachable — a ``weakref.finalize`` on the
    handed-out view fires after the view is collected, and the callback
    re-checks the backing buffer's refcount so any still-alive user alias
    (numpy collapses ``.base`` chains to the backing buffer) vetoes the
    recycle."""

    def __init__(self, min_bytes: int = 1 << 20,
                 max_total: int = 256 << 20, max_per_size: int = 3):
        self._min, self._max_total = min_bytes, max_total
        self._max_per_size = max_per_size
        self._free: dict = {}      # nbytes -> [uint8 arrays]
        self._total = 0
        # RLock: _maybe_recycle runs inside weakref.finalize callbacks; a
        # cyclic-GC collection triggered while the lock is held can run
        # ANOTHER pooled array's finalizer on the same thread — a plain
        # Lock would self-deadlock there
        self._lock = threading.RLock()
        # Self-calibrate the no-alias refcount through the EXACT production
        # path (a hand-derived constant broke the alias veto: the finalize
        # registry's ref structure is an implementation detail).  CPython
        # fires the finalize synchronously when the probe's refcount hits
        # zero, so _maybe_recycle records the baseline inline.
        self._baseline: Optional[int] = None
        probe = self.empty((self._min,), np.dtype(np.uint8))
        del probe
        if self._baseline is None:  # pragma: no cover - non-refcount VM
            self._baseline = -1     # disables recycling (pool = plain empty)

    def empty(self, shape, dtype: np.dtype) -> np.ndarray:
        n = int(np.prod(shape)) if shape else 1
        nbytes = n * dtype.itemsize
        if nbytes < self._min:
            return np.empty(shape, dtype)
        with self._lock:
            stack = self._free.get(nbytes)
            buf = stack.pop() if stack else None
            if buf is not None:
                self._total -= nbytes
        if buf is None:
            buf = np.empty(nbytes, np.uint8)
        arr = buf.view(dtype).reshape(shape)
        weakref.finalize(arr, self._maybe_recycle, buf)
        return arr

    def _maybe_recycle(self, buf: np.ndarray) -> None:
        refs = sys.getrefcount(buf)
        if self._baseline is None:
            self._baseline = refs  # calibration probe, not recycled
            return
        # anything beyond the calibrated no-alias baseline is a live user
        # alias (numpy collapses subview .base chains onto the backing
        # buffer): drop the buffer instead of recycling aliased memory
        if self._baseline < 0 or refs > self._baseline:
            return
        nbytes = buf.nbytes
        with self._lock:
            stack = self._free.setdefault(nbytes, [])
            if (len(stack) < self._max_per_size
                    and self._total + nbytes <= self._max_total):
                stack.append(buf)
                self._total += nbytes


RECV_POOL = _BufferPool()


def unpack_raw_meta(meta: bytes) -> Tuple[Any, int, np.ndarray]:
    """Decode a raw frame's meta pickle; returns (ctx, tag, empty array to
    read the raw bytes into — pooled at bandwidth sizes, see _BufferPool)."""
    ctx, tag, dtype_str, shape = pickle.loads(meta)
    return ctx, tag, RECV_POOL.empty(shape, np.dtype(dtype_str))


def parse_raw_body(body: bytes) -> Tuple[Any, int, np.ndarray]:
    """Decode an entire small raw body pulled in one read: meta prefix +
    array bytes → (ctx, tag, array).  The .copy() both compacts and makes
    the result writable/owned."""
    (mlen,) = META.unpack_from(body)
    ctx, tag, dtype_str, shape = pickle.loads(body[META.size:META.size + mlen])
    dtype = np.dtype(dtype_str)
    arr = np.frombuffer(body, dtype=dtype, offset=META.size + mlen).reshape(
        shape).copy() if dtype.itemsize else np.empty(shape, dtype)
    return ctx, tag, arr


def pack_pickle_body(ctx, tag: int, obj: Any) -> bytes:
    return pickle.dumps((ctx, tag, obj), protocol=_PROTO)


def value_copy(payload: Any) -> Any:
    """Self-send copy with message (value) semantics: cheap ndarray copy,
    pickle round-trip for everything else."""
    if isinstance(payload, np.ndarray):
        return payload.copy()
    return pickle.loads(pickle.dumps(payload, protocol=_PROTO))
