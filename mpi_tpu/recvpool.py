"""Receive-side zero-copy (ISSUE 17): size-classed recv-pool +
posted-irecv registry for rendezvous steering.

PR 11 closed the send half of the socket hot path (refcounted
``BufRef`` retention, one vectored ``sendmsg`` per frame); this module
is the receive twin, in the UCX registration-cache / NCCL
receive-pool shape:

* :class:`RecvPool` — recycles large receive buffers between messages
  in POWER-OF-TWO SIZE CLASSES (floor ``min_bytes``), so a 3.5MB
  segment and a 4MB segment share the same already-faulted 4MB
  backing buffer instead of keying exact byte counts.  At bandwidth
  sizes the receiver's dominant cost on this class of box is not the
  copy but the PAGE FAULTS of touching a freshly-mmapped destination
  (measured on the 16MB stream: 48.8k minor faults, 84ms system time
  of a 120ms wall — glibc munmaps large frees, so every message pays
  one fault per 4KB page).  A buffer is recycled only when proven
  unreachable: a ``weakref.finalize`` on the handed-out view fires
  after collection and re-checks the backing buffer's refcount, so a
  still-alive user alias (numpy collapses ``.base`` chains onto the
  backing buffer) vetoes the recycle.  Priced by the
  ``recv_pool_hits`` / ``recv_pool_misses`` pvars.

* :class:`PostedRecvRegistry` — the rendezvous half.  Every INTERNAL
  receive (negative tag, specific source) is counted on its
  ``(source, context, tag)`` channel in program order: posted irecvs
  via :meth:`note_post` (which returns a token the collective can
  :meth:`attach` a destination view to), blocking recvs via
  :meth:`note_consume`.  The socket reader counts fresh data frames on
  the same channel — and because the resilient link delivers frames in
  sequence order and collectives consume a channel in program order,
  the Nth fresh frame on a channel belongs to the Nth counted
  consumer.  When that consumer is a posted irecv with an attached
  destination of matching geometry, :meth:`note_frame` returns the
  destination and the reader ``recv_into``s the body DIRECTLY into the
  posted buffer (``recv_bytes_steered`` / ``recv_pool_rendezvous``) —
  zero intermediate copy, and mailbox delivery of the very view object
  the fold site owns turns the final store into pointer-passing.
  Everything else (no posted buffer yet, geometry mismatch, compressed
  or multi-segment or pickled payloads, steering disabled) takes the
  pool-fallback path.

Correctness invariants (the reasons this is safe under replay/chaos):

* Counting is gated on ``LinkState.rx_fresh`` — a frame is counted
  only when it is the next in-sequence frame of the CURRENT stream
  generation, i.e. exactly the frames ``rx_gate`` will deliver, in
  delivery order.  Duplicates, stale generations, and out-of-order
  gap frames are never counted.
* A per-channel ``(generation, seq)`` watermark dedups the race where
  an old connection's drain and a new connection's replay present the
  same frame concurrently, and the case where a frame was counted but
  its connection died mid-body — the replay re-presentation is NOT
  recounted and takes the pool path, while the fold-site store
  overwrites any partial bytes the torn steer left behind (replay is
  bit-exact by the CoW retention contract, so even a completed-then-
  dropped duplicate steer writes the same bytes the consumer reads).
* ``purge_src`` (membership removal) clears a source's channels and
  resyncs arrivals to posts: the purged stream's in-flight frames
  died with it, and the watermark is fenced to the bumped generation
  so stragglers from the old incarnation can never count.
* A posted irecv that is cancelled (``_unpost``) removes its entry;
  an entry whose frame passed while it had no destination is dropped
  lazily.  A missed pairing therefore only ever costs steering (pool
  fallback), never correctness.

``recv_steering`` (cvar / MPI_TPU_RECV_STEERING) disables CLAIMING
only: channel accounting stays on so toggling mid-run cannot desync
the pairing, and the pre/post benches keep identical frame paths.
"""

from __future__ import annotations

import os
import sys
import threading
import weakref
from collections import deque
from typing import Any, Dict, Optional, Tuple

import numpy as np

from . import mpit as _mpit
from . import telemetry as _telemetry


def _env_flag(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None:
        return default
    try:
        return 1 if int(v) else 0
    except ValueError:
        return default


# Rendezvous claiming on/off (the ``recv_steering`` cvar seeds/reads
# this).  Accounting is NOT gated on it — see module docstring.
_STEERING = _env_flag("MPI_TPU_RECV_STEERING", 1)


class RecvPool:
    """Size-classed recycling pool for receive buffers (see module
    docstring).  API-compatible with the exact-size pool it replaces
    (``transport.codec._BufferPool``): ``empty(shape, dtype)`` returns
    a writable array the caller owns indefinitely."""

    def __init__(self, min_bytes: int = 1 << 20,
                 max_total: int = 256 << 20, max_per_size: int = 3):
        self._min, self._max_total = min_bytes, max_total
        self._max_per_size = max_per_size
        self._free: dict = {}      # class nbytes (pow2) -> [uint8 arrays]
        self._total = 0
        # RLock: _maybe_recycle runs inside weakref.finalize callbacks; a
        # cyclic-GC collection triggered while the lock is held can run
        # ANOTHER pooled array's finalizer on the same thread — a plain
        # Lock would self-deadlock there
        self._lock = threading.RLock()
        # Self-calibrate the no-alias refcount through the EXACT
        # production path (a hand-derived constant broke the alias veto:
        # the finalize registry's ref structure is an implementation
        # detail).  CPython fires the finalize synchronously when the
        # probe's refcount hits zero, so _maybe_recycle records the
        # baseline inline.  The probe is not priced in the pool pvars.
        self._baseline: Optional[int] = None
        self._counting = False
        probe = self.empty((self._min,), np.dtype(np.uint8))
        del probe
        if self._baseline is None:  # pragma: no cover - non-refcount VM
            self._baseline = -1     # disables recycling (pool = plain empty)
        self._counting = True

    @staticmethod
    def class_bytes(nbytes: int) -> int:
        """The pow2 size class a request of ``nbytes`` draws from."""
        return 1 << max(0, (int(nbytes) - 1).bit_length())

    def empty(self, shape, dtype: np.dtype) -> np.ndarray:
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = n * dtype.itemsize
        if nbytes < self._min:
            return np.empty(shape, dtype)
        cls = self.class_bytes(nbytes)
        with self._lock:
            stack = self._free.get(cls)
            buf = stack.pop() if stack else None
            if buf is not None:
                self._total -= cls
        hit = buf is not None
        if buf is None:
            buf = np.empty(cls, np.uint8)
        sub = buf if nbytes == cls else buf[:nbytes]
        arr = sub.view(dtype).reshape(shape)
        weakref.finalize(arr, self._maybe_recycle, buf)
        if self._counting:
            if hit:
                _mpit.count(recv_pool_hits=1)
            else:
                _mpit.count(recv_pool_misses=1)
        return arr

    def _maybe_recycle(self, buf: np.ndarray) -> None:
        refs = sys.getrefcount(buf)
        if self._baseline is None:
            self._baseline = refs  # calibration probe, not recycled
            return
        # anything beyond the calibrated no-alias baseline is a live user
        # alias (numpy collapses subview .base chains onto the backing
        # buffer): drop the buffer instead of recycling aliased memory
        if self._baseline < 0 or refs > self._baseline:
            return
        nbytes = buf.nbytes  # class size: pooled bufs are allocated per class
        with self._lock:
            stack = self._free.setdefault(nbytes, [])
            if (len(stack) < self._max_per_size
                    and self._total + nbytes <= self._max_total):
                stack.append(buf)
                self._total += nbytes


class _Entry:
    __slots__ = ("idx", "dest", "ds", "shape", "declined")

    def __init__(self, idx: int) -> None:
        self.idx = idx
        self.dest: Optional[np.ndarray] = None
        self.ds: Optional[str] = None
        self.shape: Tuple[int, ...] = ()
        # the poster looked at its destination and it was NOT steering
        # eligible (non-contiguous / read-only): a later dest-less
        # match is a decision, not a lost race — don't count it
        self.declined = False


class _Channel:
    __slots__ = ("posted", "arrived", "wm", "entries")

    def __init__(self) -> None:
        self.posted = 0    # consumers counted (posted irecvs + blocking recvs)
        self.arrived = 0   # fresh data frames counted (+ self-send deliveries)
        self.wm: Tuple[int, int] = (0, 0)   # (gen, seq) counting watermark
        self.entries: deque = deque()       # outstanding posted-irecv entries


class PostedRecvRegistry:
    """Pairs fresh inbound frames with posted internal irecvs by
    per-channel arrival/post order (see module docstring).  One per
    steering transport; all methods are thread-safe and cheap (one
    small critical section)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ch: Dict[Tuple[Any, Any, int], _Channel] = {}

    def _chan(self, src, ctx, tag) -> _Channel:
        key = (src, ctx, tag)
        ch = self._ch.get(key)
        if ch is None:
            ch = self._ch[key] = _Channel()
        return ch

    # -- consumer side (communicator / nbc) ---------------------------------

    def note_post(self, src, ctx, tag):
        """Count a posted internal irecv on its channel; returns a token
        for :meth:`attach` / :meth:`cancel`."""
        with self._lock:
            ch = self._chan(src, ctx, tag)
            ch.posted += 1
            e = _Entry(ch.posted)
            ch.entries.append(e)
            return ((src, ctx, tag), e)

    def note_consume(self, src, ctx, tag) -> None:
        """Count a BLOCKING internal recv (a consumer with nothing to
        steer into — keeps the channel indices aligned)."""
        with self._lock:
            self._chan(src, ctx, tag).posted += 1

    def attach(self, token, dest: np.ndarray) -> None:
        """Give a posted irecv's entry a destination view the reader may
        steer into.  Only store-destination views qualify (contiguous,
        writable, filled by a plain assignment at the fold site)."""
        _key, e = token
        if not (dest.flags.writeable and dest.flags.c_contiguous):
            with self._lock:
                e.declined = True
            return
        with self._lock:
            e.dest = dest
            e.ds = dest.dtype.str
            e.shape = tuple(dest.shape)

    def cancel(self, token) -> None:
        """Remove a posted irecv's entry (``_unpost`` / failure paths),
        so a frame that never came cannot leave a stale claimable entry."""
        if token is None:
            return
        key, e = token
        with self._lock:
            ch = self._ch.get(key)
            if ch is not None:
                try:
                    ch.entries.remove(e)
                except ValueError:
                    pass

    # -- producer side (socket reader / self-send) --------------------------

    def note_frame(self, src, ctx, tag, seq: int, gen: int,
                   plan=None) -> Optional[np.ndarray]:
        """Count one FRESH data frame (caller must have checked
        ``LinkState.rx_fresh``); returns the posted destination to steer
        into when the paired consumer has one of matching geometry,
        else None (pool path).  ``plan`` is the codec's parsed meta
        (``("arr", dtype_str, shape)`` for the steerable single-array
        frames, anything else for the rest).

        A steerable frame that found NO destination because it lost
        the reader-vs-poster race (the frame outran the post, or the
        post outran its ``attach``) folds through the pool and is
        counted in the ``recv_pool_fold_fallbacks`` pvar (+ a trace
        instant) — ISSUE 18 satellite, the ISSUE 17 residual (c).
        Visibility only: nothing about the fold path itself changes,
        and the deterministic ``payload_copies`` accounting is
        untouched."""
        fold_race = False
        try:
            with self._lock:
                ch = self._chan(src, ctx, tag)
                if (gen, seq) <= ch.wm:
                    return None   # replay re-presentation: already counted
                ch.wm = (gen, seq)
                ch.arrived += 1
                j = ch.arrived
                q = ch.entries
                while q and q[0].idx < j:
                    q.popleft()   # stale: their frames already passed
                steerable = (_STEERING and plan is not None
                             and plan[0] == "arr")
                if not q or q[0].idx != j:
                    # no entry for this arrival: a genuine lost race
                    # only when NO consumer was counted yet (posted <
                    # j — the reader beat the poster); an entry-less
                    # match with posted >= j is a blocking recv, which
                    # never steers by design
                    fold_race = steerable and ch.posted < j
                    return None
                e = q.popleft()
                if (e.dest is None or not _STEERING or plan is None
                        or plan[0] != "arr" or e.ds != plan[1]
                        or e.shape != tuple(plan[2])):
                    # dest-less entry: the irecv was POSTED but its
                    # attach() hadn't landed when the frame arrived —
                    # the other flavor of the same race (unless the
                    # poster explicitly declined an ineligible dest,
                    # which is a decision, not a race)
                    fold_race = (steerable and e.dest is None
                                 and not e.declined)
                    return None
                return e.dest
        finally:
            if fold_race:
                # outside the lock: pvar + trace instant
                _mpit.count(recv_pool_fold_fallbacks=1)
                rec = _telemetry.REC
                if rec is not None:
                    rec.emit("recvpool", "fold_fallback",
                             attrs={"src": src, "tag": tag})

    def note_local(self, src, ctx, tag) -> None:
        """Count a self-send delivery (value-copy path, never steered) so
        loopback traffic on a registered channel keeps indices aligned."""
        with self._lock:
            ch = self._chan(src, ctx, tag)
            ch.arrived += 1
            j = ch.arrived
            q = ch.entries
            while q and q[0].idx <= j:
                q.popleft()

    def purge_src(self, src, gen: int) -> None:
        """Membership removal of ``src``: its in-flight frames died with
        the purged stream, so resync arrivals to posts, drop entries,
        and fence the watermark to the bumped generation."""
        with self._lock:
            for key, ch in self._ch.items():
                if key[0] == src:
                    ch.entries.clear()
                    ch.arrived = ch.posted
                    ch.wm = (gen, 0)

    # -- introspection (tests / diagnostics) --------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "channels": len(self._ch),
                "entries": sum(len(c.entries) for c in self._ch.values()),
                "posted": sum(c.posted for c in self._ch.values()),
                "arrived": sum(c.arrived for c in self._ch.values()),
            }
