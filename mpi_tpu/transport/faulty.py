"""Fault-injection transport wrapper (SURVEY.md §5: 'a transport wrapper
that drops/permutes in the CPU simulator').

Wraps any Transport and injects configurable faults on the send path:

* ``drop_every`` — silently drop every k-th message (models a lossy link;
  the receiver's RecvTimeout then surfaces the hang the way a failure
  detector would);
* ``delay_s`` — sleep before delivering (models congestion; exposes
  ordering assumptions that only hold under low latency);
* ``duplicate_every`` — deliver every k-th message twice (models retry
  storms; exposes non-idempotent receive logic);
* ``kill_after_n`` — the n-th send KILLS this rank (crash-stop: the send
  and everything after it vanish, :class:`KilledRankError` is raised so
  the rank's program stops, and the liveness detector sees ``killed``
  and stops heartbeating — the in-process analogue of ``os._exit`` that
  makes the whole ULFM story testable in tier-1, see mpi_tpu/ft.py);
* ``crash_on_send_to`` — like ``kill_after_n`` but triggered by the
  first send addressed to a specific world rank (dies *before*
  delivering), for failure placement at an exact schedule edge.

Connection-level link faults (ISSUE 10) — distinct from the payload
faults above, these exercise the resilient link layer
(mpi_tpu/resilience.py) of transports with real connections (socket):

* ``link_reset_every`` — every k-th frame, hard-reset (RST) the cached
  connection to its destination BEFORE any byte of the frame is
  written (a reset between frames: the frame is lost whole and must be
  replayed);
* ``link_reset_midframe_every`` — every k-th frame, reset the
  connection AFTER the header but before the body (a reset mid-frame:
  the receiver holds a partial frame it must discard);
* ``link_stall_every`` / ``link_stall_s`` — every k-th frame, stall
  the link for ``link_stall_s`` seconds before sending (a slow link is
  NOT a fault: nothing may reconnect, suspect, or error);
* ``link_accept_drop`` — the ACCEPTOR drops this many incoming
  connections after reading the hello, without answering (exercises
  the connector's bounded retry).

Unlike the payload faults, link faults are INSTALLED into the wrapped
transport (``SocketTransport.install_link_faults``) and fire inside
its send path no matter which communicator handle triggered the send —
so a process-world rank can wrap its own live world transport purely
to inject, while its communicators keep using the inner transport
directly.  Transports without a connection-level link (local threads,
shm — memory is the link) reject the kwargs with ``ValueError``.
Injection tallies live on the wrapper (``link_resets`` /
``link_midframe_resets`` / ``link_stalls``).

The ``dropped``/``duplicated`` tallies are mpit pvars
(``faulty_dropped`` / ``faulty_duplicated``) as well as instance
attributes, so chaos sweeps can assert injection actually happened
without holding a reference to every wrapper.

FIFO order per channel is preserved for non-faulted messages.  Use with
``run_local(..., transport_wrapper=FaultyTransport.wrapper(...))`` and a
recv ``timeout`` (or fault_tolerance=True) to turn silent deadlocks into
diagnosable failures.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from .. import mpit as _mpit
from .base import Transport


class KilledRankError(RuntimeError):
    """Raised on the injected-death rank itself (and on any later use of
    its transport): the in-process spelling of 'this process is gone'.
    run_local treats it as a simulated crash — the rank's result slot
    records the death and the SURVIVORS' mailboxes stay open, so the
    failure is theirs to detect (unlike a real error, which closes every
    mailbox to unblock the world)."""


class FaultyTransport(Transport):
    def __init__(self, inner: Transport, drop_every: int = 0,
                 delay_s: float = 0.0, duplicate_every: int = 0,
                 kill_after_n: int = 0,
                 crash_on_send_to: Optional[int] = None,
                 link_reset_every: int = 0,
                 link_reset_midframe_every: int = 0,
                 link_stall_every: int = 0, link_stall_s: float = 0.0,
                 link_accept_drop: int = 0) -> None:
        self.inner = inner
        self.world_rank = inner.world_rank
        self.world_size = inner.world_size
        self.mailbox = inner.mailbox
        self.aliases_payloads = inner.aliases_payloads
        # decorate, don't re-tune: collectives through the fault injector
        # must segment exactly like the wrapped data plane
        self.coll_segment_hint = inner.coll_segment_hint
        self.drop_every = drop_every
        self.delay_s = delay_s
        self.duplicate_every = duplicate_every
        self.kill_after_n = kill_after_n
        self.crash_on_send_to = crash_on_send_to
        self._n = 0
        self._lock = threading.Lock()
        self.dropped = 0
        self.duplicated = 0
        self.killed = False  # read by the ft.py detector (stops beating)
        # connection-level link faults (installed INTO the inner
        # transport's send path — see module docstring)
        self.link_reset_every = link_reset_every
        self.link_reset_midframe_every = link_reset_midframe_every
        self.link_stall_every = link_stall_every
        self.link_stall_s = link_stall_s
        self.link_accept_drop = link_accept_drop
        self._link_n = 0
        self.link_resets = 0
        self.link_midframe_resets = 0
        self.link_stalls = 0
        if (link_reset_every or link_reset_midframe_every
                or link_stall_every or link_accept_drop):
            install = getattr(inner, "install_link_faults", None)
            if install is None:
                raise ValueError(
                    f"link-fault injection needs a transport with "
                    f"connection-level links (socket); "
                    f"{type(inner).__name__} has none — shm/local "
                    f"faults are process faults (memory is the link)")
            install(self)

    @classmethod
    def wrapper(cls, **kwargs):
        """For run_local's transport_wrapper hook."""
        return lambda inner: cls(inner, **kwargs)

    def _link_hook(self, dest: int, stage: str) -> None:
        """Fired by the inner transport's send path: ``pre`` before any
        byte of a frame, ``mid`` between header and body.  Frames are
        counted once (at ``pre``); each fault kind keys off the same
        counter so cadences compose deterministically."""
        if stage == "pre":
            with self._lock:
                self._link_n += 1
                n = self._link_n
            if (self.link_stall_every and self.link_stall_s
                    and n % self.link_stall_every == 0):
                self.link_stalls += 1
                time.sleep(self.link_stall_s)
            if self.link_reset_every and n % self.link_reset_every == 0:
                self.link_resets += 1
                self.inner._inject_link_reset(dest)
        elif stage == "mid":
            with self._lock:
                n = self._link_n
            if (self.link_reset_midframe_every
                    and n % self.link_reset_midframe_every == 0):
                self.link_midframe_resets += 1
                self.inner._inject_link_reset(dest)

    def _die(self, why: str) -> None:
        self.killed = True
        raise KilledRankError(
            f"rank {self.world_rank}: injected death ({why})")

    def send(self, dest: int, ctx, tag: int, payload: Any) -> None:
        if self.killed:
            self._die("already dead")
        if self.crash_on_send_to is not None and dest == self.crash_on_send_to:
            self._die(f"crash_on_send_to={dest}")
        with self._lock:
            self._n += 1
            n = self._n
        if self.kill_after_n and n >= self.kill_after_n:
            self._die(f"kill_after_n={self.kill_after_n}")
        if self.drop_every and n % self.drop_every == 0:
            self.dropped += 1
            _mpit.count(faulty_dropped=1)
            return
        if self.delay_s:
            time.sleep(self.delay_s)
        self.inner.send(dest, ctx, tag, payload)
        if self.duplicate_every and n % self.duplicate_every == 0:
            self.duplicated += 1
            _mpit.count(faulty_duplicated=1)
            self.inner.send(dest, ctx, tag, payload)

    def recv(self, source: int, ctx, tag: int, timeout: Optional[float] = None):
        if self.killed:
            self._die("already dead")
        return self.inner.recv(source, ctx, tag, timeout)

    def close(self) -> None:
        self.inner.close()
