"""Nonblocking collectives [S: MPI-3 MPI_Ibcast & co.].

Process backends: the blocking algorithm runs on a thread against an
isolated (ctx, "nbc", k) context, so overlapping nonblocking collectives
can never mix messages.  SPMD backend: XLA already overlaps; i* returns an
already-complete Request with the traced value (same program shape)."""

import numpy as np
import pytest

from mpi_tpu import ops
from mpi_tpu.transport.local import run_local
from mpi_tpu.tpu import run_spmd

P = 4


def test_two_overlapping_iallreduce_reverse_wait():
    """Issue two nonblocking allreduces, wait in REVERSE order — isolated
    contexts mean no mixing regardless of completion order."""

    def prog(comm):
        r1 = comm.iallreduce(np.float64(comm.rank))            # 0+1+2+3 = 6
        r2 = comm.iallreduce(np.float64(comm.rank * 10))       # 60
        v2 = r2.wait()
        v1 = r1.wait()
        return float(v1), float(v2)

    assert run_local(prog, P) == [(6.0, 60.0)] * P


def test_ibcast_ibarrier_igather():
    def prog(comm):
        req = comm.ibcast("hello" if comm.rank == 0 else None, root=0)
        b = comm.ibarrier()
        g = comm.igather(comm.rank, root=0)
        val = req.wait()
        b.wait()
        got = g.wait()
        if comm.rank == 0:
            assert got == list(range(P)), got
        return val

    assert run_local(prog, P) == ["hello"] * P


def test_nbc_overlaps_blocking_collective():
    """A blocking collective issued while a nonblocking one is in flight
    uses the base context; no interference."""

    def prog(comm):
        req = comm.iallreduce(np.float64(1.0))
        s = comm.allreduce(np.float64(comm.rank), op=ops.MAX)
        return float(req.wait()), float(s)

    assert run_local(prog, P) == [(4.0, 3.0)] * P


def test_nbc_test_polls():
    def prog(comm):
        req = comm.ibarrier()
        while True:
            done, _ = req.test()
            if done:
                return True

    assert all(run_local(prog, 2))


def test_nbc_error_surfaces_at_wait():
    def prog(comm):
        req = comm.ireduce(np.float64(1.0), root=99)  # invalid root
        try:
            req.wait()
            return False
        except ValueError:
            return True

    assert all(run_local(prog, 2))


def test_nbc_on_spmd_backend():
    def prog(comm):
        r1 = comm.iallreduce(comm.rank * np.float32(1.0))
        r2 = comm.ibcast(comm.rank * np.float32(1.0), root=2)
        return r1.wait(), r2.wait()

    out = run_spmd(prog, nranks=P)
    assert np.all(np.asarray(out[0]) == 6.0)
    assert np.all(np.asarray(out[1]) == 2.0)
