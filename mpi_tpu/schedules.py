"""Pure collective-schedule generators — the shared algorithm layer (L3).

These are pure functions from rank-geometry to message schedules, consumed by
BOTH backends: the CPU transports execute them with real send/recv
(mpi_tpu/communicator.py) and the TPU backend re-emits each round as a
(masked) ``lax.ppermute`` step (mpi_tpu/tpu/collectives.py).  Sharing L3 is a
deliberate structural decision: SURVEY.md §1 notes the reference's collective
algorithms are written against the Communicator boundary, not the transport,
and §7 Milestone 2 requires the same schedule generators to drive both
backends so the algorithm-vs-algorithm benchmark dimension (BASELINE.json:10:
ring-allreduce vs recursive-halving; BASELINE.json:8: tree bcast/reduce)
exists everywhere.

Conventions
-----------
* A *round* of pairwise traffic is a list of ``(src, dst)`` comm-rank pairs.
  Within one round every rank appears at most once as src and at most once as
  dst (a partial permutation) — validated by mpi_tpu.checker.validate_perm.
* Chunk-index helpers are written so ``rank`` may be a Python int (CPU
  backends) or a traced jax scalar (TPU backend): only ``+ - %`` on the rank.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

Pair = Tuple[int, int]
Span = Tuple[int, int]


def is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


# ---------------------------------------------------------------------------
# Segment schedules (the host segmented collective engine — ISSUE 1 tentpole)
# ---------------------------------------------------------------------------
#
# The engine slices ONE contiguous working buffer with these pure tables, so
# both sides of every exchange agree on message boundaries without any
# metadata traffic: chunk_offsets is a function of (n, parts) only and
# segment_spans of (range, max_elems) only — identical on every rank for the
# congruent payloads MPI reductions require.


def chunk_offsets(n: int, parts: int) -> List[int]:
    """``parts + 1`` monotone element offsets splitting ``n`` elements into
    ``parts`` chunks, np.array_split-compatible (the first ``n % parts``
    chunks get one extra element; trailing chunks may be empty when
    ``n < parts``).  Chunk ``i`` is the half-open range
    ``[offs[i], offs[i+1])`` and chunks ``[a, b)`` together are the single
    contiguous range ``[offs[a], offs[b])`` — which is what lets the
    recursive-halving path ship each round's half as ONE raw frame instead
    of a pickled list of chunk arrays."""
    if parts < 1:
        raise ValueError(f"need at least one chunk, got {parts}")
    base, extra = divmod(n, parts)
    offs = [0]
    for i in range(parts):
        offs.append(offs[-1] + base + (1 if i < extra else 0))
    return offs


def segment_spans(lo: int, hi: int, max_elems: int) -> List[Span]:
    """Split element range ``[lo, hi)`` into pipeline segments of at most
    ``max_elems`` elements.  Empty ranges produce NO spans (and therefore
    no messages) — symmetric, because both sides of an exchange derive
    their spans from the same global chunk table."""
    if max_elems < 1:
        raise ValueError(f"segments need >= 1 element, got {max_elems}")
    if hi <= lo:
        return []
    return [(s, min(s + max_elems, hi)) for s in range(lo, hi, max_elems)]


def binomial_tree_links(size: int, rank: int,
                        root: int = 0) -> Tuple[Optional[int], List[int]]:
    """``(parent, children-in-send-order)`` of ``rank`` in the binomial
    broadcast tree — the per-rank view of :func:`binomial_bcast_rounds`.
    The segmented pipelined bcast walks links instead of rounds: a rank
    forwards segment k to its children as soon as it lands, so segments
    stream through tree levels concurrently (cut-through instead of
    store-and-forward).  ``parent`` is None exactly at ``root``."""
    parent: Optional[int] = None
    children: List[int] = []
    for pairs in binomial_bcast_rounds(size, root):
        for s, d in pairs:
            if d == rank:
                parent = s
            elif s == rank:
                children.append(d)
    return parent, children


# ---------------------------------------------------------------------------
# Binomial trees (MPI_Bcast / MPI_Reduce — BASELINE.json:8)
# ---------------------------------------------------------------------------


def binomial_bcast_rounds(size: int, root: int = 0) -> List[List[Pair]]:
    """Binomial-tree broadcast: ceil(log2 P) rounds of (src, dst) pairs.

    Round k doubles the set of ranks holding the value.  Works for any P.
    Pairs are in comm-rank space; ``root`` is handled by virtual-rank rotation.
    """
    rounds: List[List[Pair]] = []
    k = 1
    while k < size:
        pairs = []
        for v in range(k):
            peer = v + k
            if peer < size:
                pairs.append(((v + root) % size, (peer + root) % size))
        rounds.append(pairs)
        k *= 2
    return rounds


def binomial_reduce_rounds(size: int, root: int = 0) -> List[List[Pair]]:
    """Binomial-tree reduction to ``root``: mirror of bcast, children → parents."""
    return [
        [(dst, src) for (src, dst) in pairs]
        for pairs in reversed(binomial_bcast_rounds(size, root))
    ]


# ---------------------------------------------------------------------------
# Ring schedules (ring-allreduce, ring-allgather — BASELINE.json:10)
# ---------------------------------------------------------------------------


def ring_perm(size: int, shift: int = 1, wrap: bool = True) -> List[Pair]:
    """The ring permutation: every rank sends to ``rank + shift``."""
    pairs = []
    for r in range(size):
        d = r + shift
        if wrap:
            pairs.append((r, d % size))
        elif 0 <= d < size:
            pairs.append((r, d))
    return pairs


# Ring-allreduce = reduce-scatter ring + allgather ring, 2(P-1) steps total
# [S: classic bandwidth-optimal schedule; SURVEY.md §3.3].  At reduce-scatter
# step s (0-based), rank r sends chunk (r - s) mod P to r+1 and receives chunk
# (r - s - 1) mod P from r-1, accumulating.  After P-1 steps rank r holds the
# fully reduced chunk (r + 1) mod P.  The allgather phase then rotates the
# reduced chunks around the ring.


def ring_rs_send_chunk(rank, step: int, size: int):
    return (rank - step) % size


def ring_rs_recv_chunk(rank, step: int, size: int):
    return (rank - step - 1) % size


def ring_ag_send_chunk(rank, step: int, size: int):
    return (rank - step + 1) % size


def ring_ag_recv_chunk(rank, step: int, size: int):
    return (rank - step) % size


# Reduce-scatter-to-rank variant: same ring, chunk indices shifted by one so
# that after P-1 steps rank r holds the fully reduced chunk r (MPI
# Reduce_scatter_block semantics) instead of chunk (r+1) mod P.


def ring_rs_block_send_chunk(rank, step: int, size: int):
    return (rank - step - 1) % size


def ring_rs_block_recv_chunk(rank, step: int, size: int):
    return (rank - step - 2) % size


# Allgather ring for block-distributed chunks: rank r starts holding
# chunk r (the state the block reduce-scatter above ends in) and rotates
# — at step s it sends chunk (r-s) mod P right and receives (r-s-1) mod P
# from the left.  Composing the two IS the Rabenseifner allreduce
# [S: Thakur et al.]: reduce_scatter + allgather over one buffer.


def ring_ag_block_send_chunk(rank, step: int, size: int):
    return (rank - step) % size


def ring_ag_block_recv_chunk(rank, step: int, size: int):
    return (rank - step - 1) % size


# ---------------------------------------------------------------------------
# Recursive halving / doubling (allreduce, allgather — BASELINE.json:10)
# ---------------------------------------------------------------------------


def halving_masks(size: int) -> List[int]:
    """Partner masks for recursive-halving reduce-scatter, high bit first.

    Power-of-two sizes only.  Round with mask m: partner = rank ^ m; each rank
    keeps the half of its active chunk-range whose bit ``m`` equals its own
    and sends the other half.  After all rounds rank r holds exactly chunk r.
    """
    if not is_pow2(size):
        raise ValueError(f"recursive halving requires power-of-two size, got {size}")
    masks = []
    m = size >> 1
    while m:
        masks.append(m)
        m >>= 1
    return masks


def doubling_masks(size: int) -> List[int]:
    """Partner masks for recursive-doubling allgather, low bit first (the
    exact reverse of :func:`halving_masks`)."""
    return list(reversed(halving_masks(size)))


def xor_perm(size: int, mask: int) -> List[Pair]:
    """The pairwise-exchange permutation rank ↔ rank^mask."""
    return [(r, r ^ mask) for r in range(size)]


# ---------------------------------------------------------------------------
# Pairwise all-to-all (BASELINE.json:9)
# ---------------------------------------------------------------------------


def alltoall_rounds(size: int) -> List[int]:
    """Offsets for the pairwise-exchange alltoall: P-1 rounds; in round with
    offset k, rank r sends block[(r+k)%P] to (r+k)%P and receives from
    (r-k)%P into block slot (r-k)%P.  Works for any P [S]."""
    return list(range(1, size))


# ---------------------------------------------------------------------------
# Dissemination barrier [S: Hensgen/Finkel/Manber]
# ---------------------------------------------------------------------------


def dissemination_offsets(size: int) -> List[int]:
    """Offsets 1, 2, 4, ... < P; at each round rank r signals (r+off)%P and
    waits on (r-off)%P; ceil(log2 P) rounds synchronize all ranks."""
    offs = []
    k = 1
    while k < size:
        offs.append(k)
        k *= 2
    return offs


# ---------------------------------------------------------------------------
# Compiled per-rank step plans (ISSUE 12 — engine-owned nonblocking
# collectives).  A *step plan* is this rank's whole collective as pure
# data: a list of steps, each ``(sends, recvs)`` where
#
#   sends = ((peer, lo, hi), ...)        element spans of the flat work
#   recvs = ((peer, lo, hi, fold), ...)  buffer; fold=True accumulates
#                                        (op.combine_into), False copies
#
# advanced by the progress engine's completion callbacks (mpi_tpu/nbc.py
# — the MPICH/libNBC shape) instead of a per-call thread running the
# blocking loops.  The tables mirror the blocking algorithms above
# EXACTLY (same chunk functions, same step order, same skip-empty-span
# rule as segment_spans), so each plan's wire traffic is the per-step
# frame sequence the blocking path would emit unsegmented.  Spans with
# ``hi <= lo`` produce no message on either side — both ranks derive
# them from the same global chunk table, the zero-metadata invariant the
# segmented engine already leans on.
# ---------------------------------------------------------------------------

SpanSend = Tuple[int, int, int]
SpanRecv = Tuple[int, int, int, bool]
SpanStep = Tuple[Tuple[SpanSend, ...], Tuple[SpanRecv, ...]]


def _span_step(sends, recvs) -> SpanStep:
    """Drop empty spans (the segment_spans symmetry rule)."""
    return (tuple((d, lo, hi) for d, lo, hi in sends if hi > lo),
            tuple((s, lo, hi, f) for s, lo, hi, f in recvs if hi > lo))


def ring_allreduce_steps(size: int, rank: int,
                         offs: Sequence[int]) -> List[SpanStep]:
    """The 2(P-1)-step segmented ring allreduce as a per-rank plan
    (reduce-scatter ring then allgather ring — _allreduce_ring's exact
    step order)."""
    right, left = (rank + 1) % size, (rank - 1) % size
    steps: List[SpanStep] = []
    for step in range(size - 1):
        si = ring_rs_send_chunk(rank, step, size)
        ri = ring_rs_recv_chunk(rank, step, size)
        steps.append(_span_step(((right, offs[si], offs[si + 1]),),
                                ((left, offs[ri], offs[ri + 1], True),)))
    for step in range(size - 1):
        si = ring_ag_send_chunk(rank, step, size)
        ri = ring_ag_recv_chunk(rank, step, size)
        steps.append(_span_step(((right, offs[si], offs[si + 1]),),
                                ((left, offs[ri], offs[ri + 1], False),)))
    return steps


def halving_allreduce_steps(size: int, rank: int,
                            offs: Sequence[int]) -> List[SpanStep]:
    """Recursive-halving reduce-scatter + recursive-doubling allgather
    (pow2 only) — _allreduce_halving's exact partner/range walk."""
    masks = halving_masks(size)
    steps: List[SpanStep] = []
    lo, hi = 0, size
    for mask in masks:
        partner = rank ^ mask
        mid = (lo + hi) // 2
        if rank & mask:
            mine, theirs = (mid, hi), (lo, mid)
        else:
            mine, theirs = (lo, mid), (mid, hi)
        steps.append(_span_step(
            ((partner, offs[theirs[0]], offs[theirs[1]]),),
            ((partner, offs[mine[0]], offs[mine[1]], True),)))
        lo, hi = mine
    for mask in reversed(masks):
        partner = rank ^ mask
        w = hi - lo
        rb = (lo - w, lo) if rank & mask else (hi, hi + w)
        steps.append(_span_step(
            ((partner, offs[lo], offs[hi]),),
            ((partner, offs[rb[0]], offs[rb[1]], False),)))
        lo, hi = (rb[0], hi) if rank & mask else (lo, rb[1])
    return steps


def rabenseifner_allreduce_steps(size: int, rank: int,
                                 offs: Sequence[int]) -> List[SpanStep]:
    """Block-ring reduce_scatter + ring allgather composition [S: Thakur
    et al.] — _allreduce_rabenseifner's exact step order, any P."""
    right, left = (rank + 1) % size, (rank - 1) % size
    steps: List[SpanStep] = []
    for step in range(size - 1):
        si = ring_rs_block_send_chunk(rank, step, size)
        ri = ring_rs_block_recv_chunk(rank, step, size)
        steps.append(_span_step(((right, offs[si], offs[si + 1]),),
                                ((left, offs[ri], offs[ri + 1], True),)))
    for step in range(size - 1):
        si = ring_ag_block_send_chunk(rank, step, size)
        ri = ring_ag_block_recv_chunk(rank, step, size)
        steps.append(_span_step(((right, offs[si], offs[si + 1]),),
                                ((left, offs[ri], offs[ri + 1], False),)))
    return steps


def reduce_bcast_allreduce_steps(size: int, rank: int,
                                 n: int) -> List[SpanStep]:
    """The naive reference composition as a plan: binomial reduce to
    rank 0 (whole-buffer folds) then binomial bcast of the result."""
    steps: List[SpanStep] = []
    for pairs in binomial_reduce_rounds(size, 0):
        sends, recvs = [], []
        for s, d in pairs:
            if rank == s:
                sends.append((d, 0, n))
            elif rank == d:
                recvs.append((s, 0, n, True))
        steps.append(_span_step(sends, recvs))
    for pairs in binomial_bcast_rounds(size, 0):
        sends, recvs = [], []
        for s, d in pairs:
            if rank == s:
                sends.append((d, 0, n))
            elif rank == d:
                recvs.append((s, 0, n, False))
        steps.append(_span_step(sends, recvs))
    return [st for st in steps if st[0] or st[1]]


def reduce_tree_steps(size: int, rank: int, root: int,
                      n: int) -> List[SpanStep]:
    """Binomial-tree reduce to ``root``: whole-buffer folds, children →
    parents in round order (reduce's exact wire pattern)."""
    steps: List[SpanStep] = []
    for pairs in binomial_reduce_rounds(size, root):
        sends, recvs = [], []
        for s, d in pairs:
            if rank == s:
                sends.append((d, 0, n))
            elif rank == d:
                recvs.append((s, 0, n, True))
        steps.append(_span_step(sends, recvs))
    return [st for st in steps if st[0] or st[1]]


def block_ring_reduce_scatter_steps(size: int, rank: int,
                                    bn: int) -> List[SpanStep]:
    """MPI_Reduce_scatter_block's P-1-step block ring over a flat [P*bn]
    working buffer — reduce_scatter's segmented path, unsegmented."""
    right, left = (rank + 1) % size, (rank - 1) % size
    steps: List[SpanStep] = []
    for step in range(size - 1):
        si = ring_rs_block_send_chunk(rank, step, size)
        ri = ring_rs_block_recv_chunk(rank, step, size)
        steps.append(_span_step(((right, si * bn, (si + 1) * bn),),
                                ((left, ri * bn, (ri + 1) * bn, True),)))
    return steps


# Value plans: the same step shape over OPAQUE payload slots instead of
# buffer spans — for the collectives that move whole (possibly pickled)
# payloads rather than folding arrays.  sends = ((peer, slot), ...) and
# recvs = ((peer, slot), ...) where slot indexes the state machine's
# value table; slot -1 sends/receives a bare None (barrier signals).

ValueStep = Tuple[Tuple[Tuple[int, int], ...], Tuple[Tuple[int, int], ...]]


def bcast_value_steps(size: int, rank: int, root: int) -> List[ValueStep]:
    """Binomial-tree bcast: one recv-from-parent step (non-root), then
    one send step per child in tree order — the cut-through walk of
    binomial_tree_links, whole payloads."""
    parent, children = binomial_tree_links(size, rank, root)
    steps: List[ValueStep] = []
    if parent is not None:
        steps.append(((), ((parent, 0),)))
    if children:
        steps.append((tuple((c, 0) for c in children), ()))
    return steps


def allgather_ring_value_steps(size: int, rank: int) -> List[ValueStep]:
    """The rotating allgather ring over P value slots (allgather's ring
    branch, whole payloads per step)."""
    right, left = (rank + 1) % size, (rank - 1) % size
    steps: List[ValueStep] = []
    for step in range(size - 1):
        si = ring_ag_send_chunk(rank, step + 1, size)
        ri = ring_ag_recv_chunk(rank, step + 1, size)
        steps.append((((right, si),), ((left, ri),)))
    return steps


def alltoall_value_steps(size: int, rank: int) -> List[ValueStep]:
    """Pairwise-exchange alltoall: P-1 independent rounds (slot k is the
    payload for / from the round-k partner)."""
    steps: List[ValueStep] = []
    for k in alltoall_rounds(size):
        steps.append(((((rank + k) % size, (rank + k) % size),),
                      (((rank - k) % size, (rank - k) % size),)))
    return steps


def barrier_value_steps(size: int, rank: int) -> List[ValueStep]:
    """Dissemination barrier: ceil(log2 P) signal rounds (slot -1 =
    None payloads, discarded on receive)."""
    steps: List[ValueStep] = []
    for off in dissemination_offsets(size):
        steps.append(((((rank + off) % size, -1),),
                      (((rank - off) % size, -1),)))
    return steps


def dedupe_edges(edges: Sequence[Pair], size: int) -> List[Pair]:
    """Validate a directed edge list and drop duplicates, keeping the
    FIRST occurrence's position (neighbor order is input order — the
    dist_graph contract).  Self-edges are rejected (keep local data
    local); shared by graph_rounds and topology.GraphComm."""
    seen = set()
    out: List[Pair] = []
    for s, d in edges:
        s, d = int(s), int(d)
        if not (0 <= s < size and 0 <= d < size):
            raise ValueError(f"edge ({s}, {d}) out of range for size {size}")
        if s == d:
            raise ValueError(f"self-edge ({s}, {d}): keep local data local")
        if (s, d) not in seen:
            seen.add((s, d))
            out.append((s, d))
    return out


def graph_rounds(edges: Sequence[Pair], size: int) -> List[List[Pair]]:
    """Decompose an arbitrary directed edge set into partial-permutation
    rounds (greedy edge coloring): within a round no rank sends twice and
    no rank receives twice — exactly ``lax.ppermute``'s precondition, so a
    graph-neighborhood collective lowers to one ppermute per round.  Round
    count ≤ 2·max(in_degree, out_degree) − 1 (bipartite greedy bound)."""
    remaining = dedupe_edges(edges, size)
    rounds: List[List[Pair]] = []
    while remaining:
        used_s, used_d = set(), set()
        this_round, rest = [], []
        for e in remaining:
            s, d = e
            if s in used_s or d in used_d:
                rest.append(e)
            else:
                used_s.add(s)
                used_d.add(d)
                this_round.append(e)
        rounds.append(this_round)
        remaining = rest
    return rounds
