"""Cartesian process topologies — MPI_Cart_create / shift / sub [S].

SURVEY.md §2 component #14 motivates this: the Jacobi stencil's natural
decomposition is an N-D grid of ranks with halo exchanges along each
dimension.  MPI spells that MPI_Cart_create + MPI_Cart_shift + Sendrecv; the
TPU-native spelling of the same shift is ONE ``lax.ppermute`` whose pairs are
a *static* permutation of the mesh axis.  ``CartComm`` therefore reduces
every topology operation to two portable Communicator primitives —
``exchange(obj, pairs, fill)`` (static-pattern p2p) and
``split_by_rank(color_fn, key_fn)`` (host-computable split) — and works
unchanged over the socket, thread, and SPMD backends.

Rank-to-coordinate numbering is row-major (C order), matching MPI's
MPI_Cart_coords convention [S].
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence, Tuple

from .communicator import Communicator

Pair = Tuple[int, int]


def dims_create(nnodes: int, ndims: int) -> List[int]:
    """MPI_Dims_create [S]: factor ``nnodes`` into ``ndims`` balanced,
    non-increasing dimensions."""
    if nnodes <= 0 or ndims <= 0:
        raise ValueError("nnodes and ndims must be positive")
    dims = [1] * ndims
    n = nnodes
    # repeatedly peel the largest prime factor onto the smallest dimension
    factors: List[int] = []
    f = 2
    while f * f <= n:
        while n % f == 0:
            factors.append(f)
            n //= f
        f += 1
    if n > 1:
        factors.append(n)
    for f in sorted(factors, reverse=True):
        dims[dims.index(min(dims))] *= f
    return sorted(dims, reverse=True)


class CartComm:
    """A communicator with an attached N-D Cartesian topology.

    Wraps (never mutates) an existing communicator whose size must equal
    ``prod(dims)`` — MPI_Cart_create's "allow fewer ranks" escape hatch is
    not portable to SPMD, where every device runs the program.
    """

    def __init__(self, comm: Communicator, dims: Sequence[int],
                 periods: Optional[Sequence[bool]] = None):
        dims = tuple(int(d) for d in dims)
        if any(d <= 0 for d in dims):
            raise ValueError(f"dims must be positive, got {dims}")
        if math.prod(dims) != comm.size:
            raise ValueError(
                f"prod(dims)={math.prod(dims)} must equal comm.size={comm.size}")
        periods = (tuple(bool(p) for p in periods) if periods is not None
                   else (False,) * len(dims))
        if len(periods) != len(dims):
            raise ValueError("periods must have one entry per dimension")
        self.comm = comm
        self.dims = dims
        self.periods = periods
        # row-major strides: stride[i] = prod(dims[i+1:])
        self._strides = tuple(
            math.prod(dims[i + 1:]) for i in range(len(dims)))

    # -- identity ----------------------------------------------------------

    @property
    def rank(self):
        return self.comm.rank

    @property
    def size(self) -> int:
        return self.comm.size

    @property
    def ndims(self) -> int:
        return len(self.dims)

    @property
    def coords(self):
        """This rank's coordinates.  Plain ints on process backends; traced
        scalars on the SPMD backend (pure arithmetic on the traced rank)."""
        r = self.comm.rank
        return tuple((r // s) % d for s, d in zip(self._strides, self.dims))

    # -- pure coordinate math (host-side, any rank) ------------------------

    def coords_of(self, rank: int) -> Tuple[int, ...]:
        """MPI_Cart_coords [S]."""
        if not (0 <= rank < self.size):
            raise ValueError(f"rank {rank} out of range for size {self.size}")
        return tuple((rank // s) % d for s, d in zip(self._strides, self.dims))

    def rank_of(self, coords: Sequence[int]) -> Optional[int]:
        """MPI_Cart_rank [S]: periodic dimensions wrap; out-of-range
        coordinates on non-periodic dimensions return None (MPI_PROC_NULL)."""
        if len(coords) != self.ndims:
            raise ValueError(f"need {self.ndims} coordinates, got {len(coords)}")
        rank = 0
        for c, d, p, s in zip(coords, self.dims, self.periods, self._strides):
            c = int(c)
            if p:
                c %= d
            elif not (0 <= c < d):
                return None
            rank += c * s
        return rank

    def shift(self, dim: int, disp: int = 1) -> Tuple[Optional[int], Optional[int]]:
        """MPI_Cart_shift [S]: (source, dest) ranks for a displacement along
        ``dim`` — the ranks this rank receives-from / sends-to.  None is
        MPI_PROC_NULL.  Needs a concrete integer rank, so on the SPMD backend
        (traced rank) use ``exchange`` / ``shift_perm`` instead."""
        if not (0 <= dim < self.ndims):
            raise ValueError(f"dim {dim} out of range for {self.ndims}-D topology")
        r = self.comm.rank
        if not isinstance(r, int):
            raise TypeError(
                "CartComm.shift needs a concrete rank; inside an SPMD trace "
                "the rank is traced — use cart.exchange(obj, dim, disp) "
                "(the whole-mesh halo exchange) instead")
        me = list(self.coords_of(r))
        me[dim] += disp
        dest = self.rank_of(me)
        me = list(self.coords_of(r))
        me[dim] -= disp
        src = self.rank_of(me)
        return src, dest

    def shift_perm(self, dim: int, disp: int = 1) -> List[Pair]:
        """The full static (src, dst) permutation of a shift along ``dim`` —
        exactly the pairs of the one ``lax.ppermute`` the exchange lowers to."""
        if not (0 <= dim < self.ndims):
            raise ValueError(f"dim {dim} out of range for {self.ndims}-D topology")
        pairs: List[Pair] = []
        for r in range(self.size):
            c = list(self.coords_of(r))
            c[dim] += disp
            dst = self.rank_of(c)
            if dst is not None:
                pairs.append((r, dst))
        return pairs

    # -- communication -----------------------------------------------------

    def exchange(self, obj: Any, dim: int, disp: int = 1, fill: Any = None) -> Any:
        """Halo exchange along one dimension: every rank sends ``obj`` to its
        ``+disp`` neighbor and returns the payload from its ``-disp``
        neighbor; boundary holes (non-periodic) are ``fill``."""
        return self.comm.exchange(obj, self.shift_perm(dim, disp), fill=fill)

    def sendrecv_shift(self, obj: Any, dim: int, disp: int = 1,
                       fill: Any = None) -> Any:
        """Alias of :meth:`exchange` under its MPI name (Cart_shift +
        Sendrecv fused)."""
        return self.exchange(obj, dim, disp, fill)

    # -- neighborhood collectives [S: MPI-3 MPI_Neighbor_*] ----------------

    def neighbors_of(self, rank: int) -> List[Optional[int]]:
        """Neighbor ranks of ``rank`` in MPI's Cartesian neighbor order:
        for each dimension, the −1 neighbor then the +1 neighbor
        (None = MPI_PROC_NULL at a non-periodic boundary)."""
        out: List[Optional[int]] = []
        for dim in range(self.ndims):
            for disp in (-1, +1):
                c = list(self.coords_of(rank))
                c[dim] += disp
                out.append(self.rank_of(c))
        return out

    def neighbor_allgather(self, obj: Any, fill: Any = None) -> List[Any]:
        """MPI_Neighbor_allgather [S]: every rank contributes ``obj``; each
        rank returns ``[from −dim0, from +dim0, from −dim1, ...]`` — one
        entry per neighbor (``fill`` at non-periodic boundaries).  Lowers to
        2·ndims ppermutes on the SPMD backend."""
        out: List[Any] = []
        for dim in range(self.ndims):
            # receive from the −dim neighbor = everyone ships one hop +dim
            out.append(self.exchange(obj, dim, +1, fill=fill))
            out.append(self.exchange(obj, dim, -1, fill=fill))
        return out

    def neighbor_alltoall(self, objs: Sequence[Any], fill: Any = None) -> List[Any]:
        """MPI_Neighbor_alltoall [S]: ``objs`` holds one distinct payload per
        neighbor in neighbor order (−dim0, +dim0, −dim1, ...); returns the
        payloads received from each neighbor, same order.  The item you
        address to your +dim neighbor arrives there as its −dim item."""
        if len(objs) != 2 * self.ndims:
            raise ValueError(
                f"need one payload per neighbor (2·ndims = {2 * self.ndims}), "
                f"got {len(objs)}")
        out: List[Any] = []
        for dim in range(self.ndims):
            # my item for the +dim neighbor rides the +1 shift; what lands
            # here on that shift is the −dim neighbor's +dim item
            out.append(self.exchange(objs[2 * dim + 1], dim, +1, fill=fill))
            out.append(self.exchange(objs[2 * dim], dim, -1, fill=fill))
        return out

    # -- topology management ----------------------------------------------

    def sub(self, remain_dims: Sequence[bool]) -> "CartComm":
        """MPI_Cart_sub [S]: drop the dimensions where ``remain_dims`` is
        False; ranks sharing the dropped coordinates form each new
        communicator, which keeps the remaining dimensions' topology."""
        remain = tuple(bool(k) for k in remain_dims)
        if len(remain) != self.ndims:
            raise ValueError(f"need {self.ndims} remain flags, got {len(remain)}")
        kept = [i for i, k in enumerate(remain) if k]
        dropped = [i for i, k in enumerate(remain) if not k]

        def color(rank: int) -> int:
            c = self.coords_of(rank)
            out = 0
            for i in dropped:
                out = out * self.dims[i] + c[i]
            return out

        def key(rank: int) -> int:
            c = self.coords_of(rank)
            out = 0
            for i in kept:
                out = out * self.dims[i] + c[i]
            return out

        sub = self.comm.split_by_rank(color, key)
        return CartComm(sub,
                        [self.dims[i] for i in kept] or [1],
                        [self.periods[i] for i in kept] or [False])

    def dup(self) -> "CartComm":
        return CartComm(self.comm.dup(), self.dims, self.periods)


def cart_create(comm: Communicator, dims: Sequence[int],
                periods: Optional[Sequence[bool]] = None) -> CartComm:
    """MPI_Cart_create [S] (reorder is meaningless here: ranks are mesh
    positions already)."""
    return CartComm(comm, dims, periods)


class GraphComm:
    """Arbitrary directed process graphs — MPI_(Dist_)graph topologies [S].

    SPMD-compatible spelling: the GLOBAL edge list is given (identical on
    every rank), so the whole neighborhood structure is static — exactly
    what one traced program needs.  ``dist_graph_create_adjacent`` builds
    it from MPI's per-rank adjacency spelling on the process backends (an
    allgather of local edges, as real MPI implementations do internally).

    Communication decomposes into partial-permutation rounds
    (``schedules.graph_rounds`` — greedy edge coloring), each lowering to
    one ``comm.exchange`` (= one ``lax.ppermute`` on the SPMD backend):
    the same portable-primitives-only recipe as :class:`CartComm`.

    Result convention (matches the vector collectives): the process
    backends return exact in-neighbor-ordered lists; the SPMD backend,
    whose shapes are static, returns a stacked ``[max_in_degree, ...]``
    array padded with ``fill`` — rows ``[:in_degree(r)]`` match the list.
    """

    def __init__(self, comm: Communicator, edges: Sequence[Pair],
                 in_order: Optional[Sequence[Sequence[int]]] = None,
                 out_order: Optional[Sequence[Sequence[int]]] = None):
        from . import schedules

        self.comm = comm
        size = comm.size
        # neighbor order is the INPUT edge-list order — never the
        # coloring's round order, which would silently permute results;
        # dist_graph_create_adjacent overrides with each rank's OWN
        # sources/destinations order (the MPI contract) via
        # in_order/out_order
        self.edges = schedules.dedupe_edges(edges, size)
        self._rounds = schedules.graph_rounds(self.edges, size)
        self._in: List[List[int]] = [[] for _ in range(size)]
        self._out: List[List[int]] = [[] for _ in range(size)]
        for s, d in self.edges:  # one O(E) pass
            self._in[d].append(s)
            self._out[s].append(d)
        for given, derived, what in ((in_order, self._in, "in_order"),
                                     (out_order, self._out, "out_order")):
            if given is None:
                continue
            for r in range(size):
                if sorted(given[r]) != sorted(derived[r]):
                    raise ValueError(
                        f"{what}[{r}]={list(given[r])} names a different "
                        f"neighbor set than the edges ({derived[r]})")
                derived[r] = [int(x) for x in given[r]]
        # round index of each (src, dst) edge
        self._round_of = {e: k for k, rnd in enumerate(self._rounds)
                          for e in rnd}

    # -- static queries (host-side) ----------------------------------------

    @property
    def size(self) -> int:
        return self.comm.size

    @property
    def rank(self):
        return self.comm.rank

    @property
    def n_rounds(self) -> int:
        return len(self._rounds)

    @property
    def max_in_degree(self) -> int:
        return max((len(n) for n in self._in), default=0)

    @property
    def max_out_degree(self) -> int:
        return max((len(n) for n in self._out), default=0)

    def in_neighbors_of(self, rank: int) -> List[int]:
        """MPI_Dist_graph_neighbors, incoming half (edge-list order)."""
        return list(self._in[rank])

    def out_neighbors_of(self, rank: int) -> List[int]:
        return list(self._out[rank])

    # -- neighborhood collectives [S: MPI-3 MPI_Neighbor_* over graphs] ----

    def _spmd(self) -> bool:
        return not isinstance(self.comm.rank, int)

    def _spmd_gather_receipts(self, receipts: List[Any], fill: Any):
        """Reorder per-round receipts into per-in-neighbor slots (SPMD
        result shape: stacked [max_in_degree, ...] padded with fill —
        slot k of rank r's output = the round its k-th in-edge ran in;
        padded rows point at round 0 and are overwritten with fill)."""
        import jax.numpy as jnp

        from jax import lax

        size, maxd = self.size, self.max_in_degree
        if not receipts or maxd == 0:  # edgeless graph: static empty stack
            shape = () if not receipts else jnp.asarray(receipts[0]).shape
            return jnp.zeros((0,) + shape)
        table = [[self._round_of[(s, r)] for s in self._in[r]]
                 + [0] * (maxd - len(self._in[r])) for r in range(size)]
        me = lax.axis_index(self.comm.axis_name)
        stacked = jnp.stack([jnp.asarray(x) for x in receipts])
        out = jnp.take(stacked, jnp.asarray(table)[me], axis=0)
        deg = jnp.asarray([len(self._in[r]) for r in range(size)])[me]
        mask = (jnp.arange(maxd) < deg).reshape(
            (maxd,) + (1,) * (out.ndim - 1))
        return jnp.where(mask, out, jnp.full_like(out, fill))

    def neighbor_allgather(self, obj: Any, fill: Any = 0):
        """Every rank contributes ``obj``; each rank receives one payload
        per IN-neighbor (see class docstring for the per-backend result
        shape).  ``n_rounds`` exchanges total."""
        receipts = [self.comm.exchange(obj, rnd, fill=fill)
                    for rnd in self._rounds]
        if not self._spmd():
            r = self.comm.rank
            return [receipts[self._round_of[(s, r)]] for s in self._in[r]]
        return self._spmd_gather_receipts(receipts, fill)

    def neighbor_alltoall(self, objs: Sequence[Any], fill: Any = 0):
        """One DISTINCT payload per OUT-neighbor (out-neighbor order;
        stacked [max_out_degree, ...] on the SPMD backend); returns the
        payloads received from each in-neighbor (allgather conventions)."""
        receipts = []
        if not self._spmd():
            r = self.comm.rank
            if len(objs) != len(self._out[r]):
                raise ValueError(
                    f"rank {r}: need one payload per out-neighbor "
                    f"({len(self._out[r])}), got {len(objs)}")
            for k, rnd in enumerate(self._rounds):
                mine = next((d for (s, d) in rnd if s == r), None)
                payload = (objs[self._out[r].index(mine)]
                           if mine is not None else None)
                receipts.append(self.comm.exchange(payload, rnd, fill=fill))
            return [receipts[self._round_of[(s, r)]] for s in self._in[r]]
        import jax.numpy as jnp

        from jax import lax

        x = jnp.asarray(objs)
        size, maxd = self.size, self.max_out_degree
        if x.shape[0] != maxd:
            raise ValueError(
                f"SPMD neighbor_alltoall payload needs leading dim == "
                f"max_out_degree ({maxd}), got {x.shape}")
        # which out-block each rank ships in round k (0 when idle: the
        # exchange pattern has no edge from an idle rank, so the payload
        # choice is irrelevant — nothing is sent)
        send_slot = [[next((self._out[r].index(d) for (s, d) in rnd
                            if s == r), 0) for r in range(size)]
                     for rnd in self._rounds]
        me = lax.axis_index(self.comm.axis_name)
        receipts = []
        for k, rnd in enumerate(self._rounds):
            slot = jnp.asarray(send_slot[k])[me]
            payload = lax.dynamic_index_in_dim(x, slot, 0, keepdims=False)
            receipts.append(self.comm.exchange(payload, rnd, fill=fill))
        return self._spmd_gather_receipts(receipts, fill)


def graph_create(comm: Communicator, edges: Sequence[Pair]) -> GraphComm:
    """MPI_Dist_graph_create with the global edge list [S] (the
    SPMD-compatible spelling; identical on every rank)."""
    return GraphComm(comm, edges)


def multihost_node_key(comm: Communicator):
    """Per-rank DCN node ids discovered from the multi-host jax runtime
    (tpu/multihost.py ``init_distributed``): each rank contributes its
    jax process index — the DCN granule, one per host — and the
    allgathered list becomes the pure ``node_key`` function the
    hierarchical splits need.  Single-process runtimes (and worlds
    without jax) collapse to one node, which is also the truth for the
    single-host worlds this library's launcher starts; tests inject
    synthetic keys instead to exercise multi-node shapes on one box."""
    try:
        import jax

        dom = (int(jax.process_index())
               if int(jax.process_count()) > 1 else 0)
    except Exception:  # noqa: BLE001 - no (initialized) jax: one node
        dom = 0
    domains = comm.allgather(dom)
    table = [int(d) for d in domains]
    return lambda r: table[r]


def split_hierarchical(comm: Communicator, node_key=None
                       ) -> Tuple[Communicator, Optional[Communicator],
                                  List[int]]:
    """The two-level split behind hierarchical collectives (Open MPI
    HAN's shape): ``(intra, leaders, node_of)`` where ``intra`` groups
    the ranks sharing ``node_key(rank)`` (ordered by old rank, so the
    node's lowest rank is intra rank 0 — the node leader), ``leaders``
    contains exactly the leaders (None on non-leader ranks), and
    ``node_of[r]`` is rank r's dense node id (nodes numbered in
    first-appearance order, which makes node n's rank in ``leaders``
    exactly n).

    ``node_key`` must be a pure function of the comm rank, identical on
    every rank (the split_by_rank contract).  Default: the shared-memory
    domain — worlds this library's launcher starts are single-host, so
    every rank shares node 0; mixed worlds pass their real host key, and
    tests pass synthetic keys to exercise the composition on one box."""
    if node_key is None:
        node_key = lambda r: 0  # noqa: E731 - the single-host domain
    keys = [node_key(r) for r in range(comm.size)]
    order: dict = {}
    for k in keys:
        order.setdefault(k, len(order))
    node_of = [order[k] for k in keys]
    my_node = node_of[comm.rank]
    intra = comm.split(my_node, key=comm.rank)
    is_leader = intra.rank == 0
    leaders = comm.split(0 if is_leader else None, key=comm.rank)
    return intra, leaders, node_of


def _dense(keys: List) -> List[int]:
    """Dense ids in first-appearance order (node n's leader — its lowest
    rank — is member n of any leader communicator keyed by old rank)."""
    order: dict = {}
    for k in keys:
        order.setdefault(k, len(order))
    return [order[k] for k in keys]


def split_hierarchical3(comm: Communicator, numa_key=None, node_key=None
                        ) -> Tuple[Communicator, Optional[Communicator],
                                   Optional[Communicator], List[int],
                                   List[int]]:
    """The THREE-level split (ISSUE 9): ``(numa, node_leaders,
    dcn_leaders, numa_of, node_of)``.

    * ``numa`` groups the ranks sharing ``(node_key(r), numa_key(r))``
      — one communicator per NUMA domain, ordered by old rank, so the
      domain's lowest rank is its leader (numa rank 0);
    * ``node_leaders`` groups each node's NUMA leaders (None on
      non-leader ranks) — the intra-node inter-NUMA tier, whose rank 0
      is the node leader (the node's lowest rank);
    * ``dcn_leaders`` groups the node leaders across nodes (None
      elsewhere) — the tier whose traffic crosses the data-center
      network; node n sits at dcn rank n (nodes numbered in
      first-appearance order = lowest-rank order).

    Both keys must be pure functions of the comm rank, identical on
    every rank (the split_by_rank contract).  ``node_key`` defaults to
    the single-node domain; pass :func:`multihost_node_key`'s result on
    a real multi-host runtime, or synthetic keys in tests.  ``numa_key``
    defaults to one NUMA domain per node (collapsing the middle tier to
    size-1 node_leaders — the degenerate spelling of the PR-4 two-level
    split)."""
    if numa_key is None:
        numa_key = lambda r: 0  # noqa: E731 - one NUMA domain per node
    if node_key is None:
        # "where available": a multi-host jax runtime supplies the real
        # DCN node ids (one allgather); everything else is one node
        node_key = multihost_node_key(comm)
    numa_of = _dense([(node_key(r), numa_key(r))
                      for r in range(comm.size)])
    node_of = _dense([node_key(r) for r in range(comm.size)])
    numa = comm.split(numa_of[comm.rank], key=comm.rank)
    numa_leader = numa.rank == 0
    node_leaders = comm.split(node_of[comm.rank] if numa_leader else None,
                              key=comm.rank)
    node_leader = node_leaders is not None and node_leaders.rank == 0
    dcn_leaders = comm.split(0 if node_leader else None, key=comm.rank)
    return numa, node_leaders, dcn_leaders, numa_of, node_of


class HierarchicalComm:
    """Hierarchical collective dispatch over a two- or THREE-level
    split: the intra tiers run on their own communicators — where the
    shm transport's collective arena (mpi_tpu/coll_sm.py) serves
    collectives by load/store — and the top tier runs the measured wire
    algorithms between the leaders only.  An allreduce therefore moves
    each payload once per node over the wire instead of once per rank:
    intra reduce → leaders allreduce → intra bcast.

    Two-level (the PR-4 shape, default): ``node_key`` partitions ranks
    into nodes; ``intra`` is the node communicator, ``leaders`` the
    inter-node tier.

    Three-level (ISSUE 9, selected by passing ``numa_key``): NUMA →
    node → DCN leaders.  ``numa_key(r)`` names rank r's NUMA domain
    WITHIN its node, ``node_key(r)`` its node (on a real multi-host
    runtime, :func:`multihost_node_key` derives it from
    tpu/multihost.py's process index; tests inject synthetic keys).
    An allreduce climbs ``numa.reduce`` → ``node_leaders.reduce`` →
    ``dcn_leaders.allreduce`` and descends by bcast — and every level's
    ``algorithm="auto"`` call consults the tuned-dispatch resolver
    (mpi_tpu/tuning) with ITS OWN (transport, size, payload) key, so a
    per-machine table steers each tier independently (the
    ``tuned_table_hits`` pvar counts one consult per level).

    Wraps (never mutates) an existing communicator, like CartComm."""

    def __init__(self, comm: Communicator, node_key=None,
                 inter_algorithm: str = "auto", numa_key=None):
        self.comm = comm
        self._inter = inter_algorithm
        if numa_key is None:
            # -- two-level (PR 4) — unchanged ------------------------------
            self.numa = self.node_leaders = self.dcn_leaders = None
            self.intra, self.leaders, self._node_of = split_hierarchical(
                comm, node_key)
        else:
            # -- three-level (ISSUE 9) -------------------------------------
            (self.numa, self.node_leaders, self.dcn_leaders,
             self._numa_of, self._node_of) = split_hierarchical3(
                comm, numa_key, node_key)
            # compatibility aliases: the finest tier and the top tier
            self.intra = self.numa
            self.leaders = self.dcn_leaders
            numa_members: List[List[int]] = [
                [] for _ in range(max(self._numa_of) + 1)]
            for r, n in enumerate(self._numa_of):
                numa_members[n].append(r)
            self._numa_leader_of = [m[0] for m in numa_members]
        self._members: List[List[int]] = [
            [] for _ in range(max(self._node_of) + 1)]
        for r, n in enumerate(self._node_of):
            self._members[n].append(r)
        self._leader_of = [m[0] for m in self._members]

    # -- identity ----------------------------------------------------------

    @property
    def rank(self):
        return self.comm.rank

    @property
    def size(self) -> int:
        return self.comm.size

    @property
    def n_nodes(self) -> int:
        return len(self._members)

    def _to_leader(self, obj: Any, root: int) -> Any:
        """Hop a payload from ``root`` to its node leader (identity when
        root IS the leader).  Rides ``comm.exchange`` — the static-pattern
        p2p primitive every backend provides — so bystander ranks no-op."""
        leader = self._leader_of[self._node_of[root]]
        if leader == root:
            return obj
        got = self.comm.exchange(obj, [(root, leader)])
        return got if self.comm.rank == leader else obj

    def _hop(self, obj: Any, src: int, dst: int) -> Any:
        """One point-to-point hop on the full communicator (identity
        when src == dst); bystanders keep their own payload."""
        if src == dst:
            return obj
        got = self.comm.exchange(obj, [(src, dst)])
        return got if self.comm.rank == dst else obj

    # -- collectives -------------------------------------------------------

    def barrier(self) -> None:
        """Gather phase up every tier, release phase back down."""
        if self.numa is not None:
            self.numa.barrier()
            if self.node_leaders is not None:
                self.node_leaders.barrier()
                if self.dcn_leaders is not None:
                    self.dcn_leaders.barrier()
                self.node_leaders.barrier()
            self.numa.barrier()
            return
        self.intra.barrier()
        if self.leaders is not None:
            self.leaders.barrier()
        self.intra.barrier()

    def allreduce(self, obj: Any, op: Any = None) -> Any:
        from . import ops as _ops

        op = op or _ops.SUM
        if self.numa is not None:
            # reduce up the tiers, allreduce once across the DCN, bcast
            # back down — each tier's auto call keys the tuned-dispatch
            # resolver with its own (transport, size, payload)
            part = self.numa.reduce(obj, op, root=0)
            if self.node_leaders is not None:
                part = self.node_leaders.reduce(part, op, root=0)
                if self.dcn_leaders is not None:
                    part = self.dcn_leaders.allreduce(
                        part, op, algorithm=self._inter)
                part = self.node_leaders.bcast(part, root=0)
            return self.numa.bcast(part, root=0)
        part = self.intra.reduce(obj, op, root=0)
        if self.leaders is not None:
            part = self.leaders.allreduce(part, op,
                                          algorithm=self._inter)
        return self.intra.bcast(part, root=0)

    def reduce(self, obj: Any, op: Any = None, root: int = 0) -> Any:
        from . import ops as _ops

        op = op or _ops.SUM
        if self.numa is not None:
            # three-level reduce rides the allreduce chain (every tier
            # already deduplicates wire traffic); only root keeps it
            val = self.allreduce(obj, op)
            return val if self.comm.rank == root else None
        part = self.intra.reduce(obj, op, root=0)
        rn = self._node_of[root]
        val = (self.leaders.reduce(part, op, root=rn)
               if self.leaders is not None else part)
        if self._node_of[self.comm.rank] != rn:
            return None
        # root's node: ship the total from the node leader to root
        # (intra bcast keeps it collective-only; non-roots drop it)
        val = self.intra.bcast(val, root=0)
        return val if self.comm.rank == root else None

    def bcast(self, obj: Any, root: int = 0) -> Any:
        if self.numa is not None:
            # climb: root -> its NUMA leader -> its node leader; fan
            # out: dcn bcast -> node bcast -> numa bcast
            nl = self._numa_leader_of[self._numa_of[root]]
            top = self._leader_of[self._node_of[root]]
            obj = self._hop(obj, root, nl)
            obj = self._hop(obj, nl, top)
            if self.dcn_leaders is not None:
                obj = self.dcn_leaders.bcast(obj,
                                             root=self._node_of[root])
            if self.node_leaders is not None:
                obj = self.node_leaders.bcast(obj, root=0)
            return self.numa.bcast(obj, root=0)
        obj = self._to_leader(obj, root)
        if self.leaders is not None:
            obj = self.leaders.bcast(obj, root=self._node_of[root])
        return self.intra.bcast(obj, root=0)

    def allgather(self, obj: Any) -> Any:
        from .communicator import _maybe_stack

        if self.numa is not None:
            # (rank, payload) pairs climb the tiers as object lists,
            # the assembled world list descends by bcast: per-rank wire
            # volume stays one copy of each payload per TIER edge
            got = self.numa.gather((self.comm.rank, obj), root=0)
            if self.node_leaders is not None:
                per = self.node_leaders.gather(got, root=0)
                if per is not None:
                    got = [pair for sub in per for pair in sub]
                if self.dcn_leaders is not None:
                    per_node = self.dcn_leaders.allgather([got])
                    got = [pair for (sub,) in per_node for pair in sub]
                got = self.node_leaders.bcast(got, root=0)
            got = self.numa.bcast(got, root=0)
            full: List[Any] = [None] * self.comm.size
            for rk, item in got:
                full[rk] = item
            return _maybe_stack(obj, full)
        node_items = self.intra.gather(obj, root=0)
        full = [None] * self.comm.size
        if self.leaders is not None:  # exactly the leaders (intra rank 0)
            per_node = self.leaders.allgather([list(node_items)])
            for n, (items,) in enumerate(per_node):
                for i, r in enumerate(self._members[n]):
                    full[r] = items[i]
        full = self.intra.bcast(full, root=0)
        return _maybe_stack(obj, full)


def dist_graph_create_adjacent(comm: Communicator,
                               sources: Sequence[int],
                               destinations: Sequence[int]) -> GraphComm:
    """MPI_Dist_graph_create_adjacent [S]: every rank names ITS incoming
    ``sources`` and outgoing ``destinations``; the global edge list is the
    allgathered union (what MPI implementations build internally).
    Process backends only — the allgather of per-rank Python lists has no
    SPMD analogue; use :func:`graph_create` there."""
    r = comm.rank
    if not isinstance(r, int):
        raise TypeError(
            "dist_graph_create_adjacent needs per-rank adjacency lists, "
            "which an SPMD trace cannot collect — pass the global edge "
            "list to graph_create instead")
    local = ([int(s) for s in sources], [int(d) for d in destinations])
    gathered = comm.allgather(local)  # [(sources, destinations)] per rank
    seen, edges = set(), []
    for rk, (srcs, dsts) in enumerate(gathered):
        for e in ([(s, rk) for s in srcs] + [(rk, d) for d in dsts]):
            if e not in seen:
                seen.add(e)
                edges.append(e)
    # each rank's neighbor ORDER is its own sources/destinations order
    # (the MPI contract), not the union scan order
    return GraphComm(comm, edges,
                     in_order=[srcs for srcs, _ in gathered],
                     out_order=[dsts for _, dsts in gathered])
