"""The Communicator — rank/size bookkeeping, p2p, collectives, split.

This is L2+L3+L4 of SURVEY.md §1: the abstract Communicator is the plugin
boundary the whole framework hangs off (BASELINE.json:5 — "Communicator
rank/size bookkeeping and comm.split() stay intact behind the existing
Communicator plugin boundary").  Concrete subclasses:

* :class:`P2PCommunicator` — any point-to-point Transport (socket, local
  threads); collectives are *executed* from the shared schedule generators in
  mpi_tpu/schedules.py (tree bcast/reduce, ring and recursive-halving
  allreduce, ring/doubling allgather, pairwise alltoall — BASELINE.json:8,10).
* mpi_tpu.tpu.TpuCommunicator — the headline backend: same API, re-emitted as
  XLA collectives / ppermute schedules over a device mesh (SURVEY.md §7).

API conventions (MPI-1.x semantics [S], pythonic spelling):
* comm-rank space everywhere; world ranks are an internal detail.
* user tags are ints >= 0; wildcards ANY_SOURCE / ANY_TAG = -1.  Internal
  traffic (collectives, barrier, shift) uses negative tags that user
  wildcards can never match (see transport/base.py).
* reductions accept numpy-convertible payloads; bcast/p2p/allgather/alltoall
  accept arbitrary picklable objects on CPU backends.
"""

from __future__ import annotations

import functools
import pickle
import threading
import time
from abc import ABC, abstractmethod
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from . import bufpool as _bufpool
from . import coll_sm as _coll_sm
from . import compress as _compress
from . import mpit as _mpit
from . import ops as _ops
from . import recvpool as _recvpool
from . import schedules
from . import telemetry as _telemetry
from . import tuning as _tuning
from .errors import ProcFailedError, RevokedError
from .transport import codec as _codec
from .transport.base import (ANY_SOURCE, ANY_TAG, RecvTimeout, Transport,
                             TransportError, payload_nbytes)

# Internal tags (never matched by user-level ANY_TAG — see Mailbox._matches).
# CPU-backend allreduce auto crossover (mpit cvar; re-derived from the
# segmented-engine host sweep, benchmarks/results/host_sweep_post.json).
# The seed's 64KB dated from the era when recursive halving PICKLED its
# chunk lists; on raw frames its latency edge reaches further: measured
# halving still wins both host transports at 256KB inclusive (2- and
# 4-rank legs; at 2 ranks the artifact's crossover derivation is null —
# halving moves the SAME volume as ring there, so it never durably
# loses).  Ring's 2(P-1)/P·N volume advantage at P>2 is what the MB+
# sizes keep it for; 512KB is the smallest pow2 above every size the
# sweep showed halving winning.
_RING_CROSSOVER_BYTES = 512 << 10

# Segmented collective engine (ISSUE 1 tentpole): element ranges larger
# than the segment size ship as multiple raw frames so the receiver's
# fold/copy of segment k overlaps the transport streaming segment k+1.
# The right granularity is a TRANSPORT property (shm: stay inside the
# ring; socket: amortize per-frame host work — see each transport's
# coll_segment_hint), so _SEGMENT_BYTES = 0 means "ask the transport";
# the mpit cvar collective_segment_bytes sets a nonzero engine-wide
# override.  _SEG_WINDOW bounds how many segments a rank sends AHEAD of
# its receive pointer: the credit that keeps window * segment
# comfortably inside the 4MB shm ring, so symmetric exchanges never
# stall on a full ring waiting for the 20Hz helper drainer (the seed
# engine's hidden bandwidth cliff).
_SEGMENT_BYTES = 0
_SEG_WINDOW = 4
# Arrays below this stay on the seed single-message bcast path: the
# segmented tree costs one header message per edge + an assemble copy,
# noise at bandwidth sizes but real at latency sizes.
_BCAST_SEGMENT_MIN_BYTES = 1 << 20

# Below this TOTAL payload size reduce_scatter keeps the seed's simple
# per-chunk ring: the segmented engine's working-buffer flatten, irecv
# posting and result copy-out are noise at bandwidth sizes but real at
# latency sizes.  Measured (host_sweep2_{pre,post}.json): the segmented
# ring wins from 1MB up on socket (1MB p50 646us -> 255us) and from 4MB
# up on shm (3700us -> 687us; the shm 1MB cell is a wash — 1148us ->
# 1279us, inside that box's 2-core noise band), and loses below 256KB
# on both.  The gate follows the socket signal; the shm 1MB tie is the
# accepted cost of one engine-wide constant.  A nonzero
# collective_segment_bytes cvar LOWERS the gate to payloads spanning
# more than one configured segment — steering the engine to, say, 64B
# segments says segmentation is wanted wherever it produces a pipeline
# (how the parity tests force multi-segment exchanges on tiny
# payloads), while a bandwidth-tuned 8MB segment leaves small
# reduce_scatters on the cheap per-chunk path.
_RS_SEGMENT_MIN_BYTES = 1 << 20

# Above this size, allreduce 'auto' hands the payload to the Rabenseifner
# composition (block-ring reduce_scatter + ring allgather [S: Thakur et
# al.]) instead of the classic ring.  Both move 2(P-1)/P·N per rank;
# unlike recursive halving the composition works for ANY group size,
# which is why it gets its own crossover rather than reusing
# _RING_CROSSOVER_BYTES.  Derived from the measured sweep: the smallest
# bandwidth-regime size from which the composition's p50 stays within
# 10% of ring's at every larger size AND strictly beats it in the tail,
# on BOTH host transports (benchmarks/results/host_sweep2_post.json
# "rabenseifner_crossover.combined_bytes" = 1MB; equal-volume schedules
# tie by construction, so the tolerant rule is what survives this box's
# 2-core noise — see benchmarks/host_sweep.py _RABEN_TIE).
# mpit cvar: allreduce_rabenseifner_crossover_bytes.
_RABENSEIFNER_CROSSOVER_BYTES = 1 << 20

_TAG_COLL = -2
_TAG_SHIFT = -3
_TAG_BARRIER = -4
_TAG_SPLIT = -5
# -6/-7/-8 are the fault-tolerance control tags (revoke / shrink /
# agree) — see mpi_tpu/ft.py TAG_REVOKE & co.; -9 is the runtime
# verifier's collective-signature ring (mpi_tpu/verify/collcheck.py).

# Default ``recv_timeout`` of newly created communicators (mpit cvar
# ``recv_timeout_s``; 0/None = wait forever).  The per-communicator
# attribute still overrides — this is the process-wide knob the failure
# story turns so a lost message surfaces as RecvTimeout everywhere.
_RECV_TIMEOUT_DEFAULT: Optional[float] = None

# Slice length of fault-tolerant AND verified blocking waits (detector/
# revocation/stall re-check cadence while blocked) — mirrors ft.POLL_S
# (kept as a literal so importing this module never pulls the ft
# machinery in; the two are asserted equal in tests/test_verify.py).
_FT_POLL_S = 0.05


class _SegHeader:
    """Wire announcement of a segmented tree broadcast (root's choice).

    Pickled by class identity, so no user payload can collide with it;
    carries the result geometry plus the segment count — each segment
    frame is self-describing (raw frames ship dtype+shape), so receivers
    never re-derive the root's segmentation, they just count it."""

    __slots__ = ("dtype_str", "shape", "nseg")

    def __init__(self, dtype_str: str, shape: Tuple[int, ...], nseg: int):
        self.dtype_str = dtype_str
        self.shape = shape
        self.nseg = nseg

    def __getstate__(self):
        return (self.dtype_str, self.shape, self.nseg)

    def __setstate__(self, state):
        self.dtype_str, self.shape, self.nseg = state


class Status:
    """Result metadata for a receive (MPI_Status analogue).

    ``count_bytes`` is the payload's size when it is a sized buffer
    (ndarray / bytes) — set by receives AND by probe/iprobe, which
    peek the queued message's size without consuming it (ADVICE r4
    #2); None for opaque pickled objects — the MPI_UNDEFINED analogue.
    MPI_Get_count/MPI_Get_elements (api.py) divide it by a datatype."""

    __slots__ = ("source", "tag", "count_bytes")

    def __init__(self) -> None:
        self.source = ANY_SOURCE
        self.tag = ANY_TAG
        self.count_bytes: Optional[int] = None

    def _set_count(self, obj: Any) -> None:
        # ONE sizing rule, shared with the transports' probe peek —
        # probe and the matching recv must never disagree on a count
        self.count_bytes = payload_nbytes(obj)

    def _fill(self, source: int, tag: int, payload: Any) -> None:
        """The one envelope-fill site (recv, mprobe/improbe, Mrecv)."""
        self.source = source
        self.tag = tag
        self._set_count(payload)

    def _fill_envelope(self, source: int, tag: int,
                       count_bytes: Optional[int] = None) -> None:
        """probe/iprobe: the envelope plus the QUEUED payload's size
        (the transports peek it without consuming — ADVICE r4 #2: the
        canonical probe+get_count+recv buffer-sizing idiom works).
        None (MPI_UNDEFINED) for opaque pickled payloads; a Status
        reused after a prior recv never leaks that recv's count
        (ADVICE r3 #1 — the field is overwritten either way)."""
        self.source = source
        self.tag = tag
        self.count_bytes = count_bytes

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"Status(source={self.source}, tag={self.tag})"


def _check_user_tag(tag: int) -> None:
    if tag != ANY_TAG and tag < 0:
        raise ValueError(f"user tags must be >= 0 (got {tag}); negative tags are reserved")


def seed_allreduce_algorithm(nbytes: int, size: int) -> str:
    """The seed constants' ``auto`` allreduce pick — the wire-algorithm
    policy that runs when no tuning-table row matches (mpi_tpu/tuning).
    The Rabenseifner composition once the measured sweep shows it
    stably at-or-below ring (checked FIRST so lowering its cvar below
    the ring crossover takes effect on pow2 groups too);
    latency-optimal recursive halving for small payloads on
    power-of-two groups; bandwidth-optimal ring otherwise (the
    crossover the reference benchmarks head-to-head, BASELINE.json:10).

    ``tools/tune.py`` reads THIS function for its tie-bias incumbent,
    so the sweep's recorded ``seed`` column can never structurally
    drift from real dispatch."""
    if nbytes >= _RABENSEIFNER_CROSSOVER_BYTES:
        return "rabenseifner"
    if schedules.is_pow2(size) and nbytes < _RING_CROSSOVER_BYTES:
        return "recursive_halving"
    return "ring"


def _resolve_algorithm(coll: str, algorithm: str, real: Tuple[str, ...],
                       aliases: dict) -> str:
    """The ONE ``algorithm=`` gate for the host collectives: aliases are
    EXPLICIT (e.g. ``'fused'`` — the TPU backend's XLA-collective tier —
    maps to the best process-backend schedule so portable programs run
    unchanged), real names pass through, and anything else raises the
    same-shaped error everywhere, listing every accepted value.  Before
    this helper each collective validated ad hoc: alltoall accepted
    'fused' but silently ran pairwise with no documentation, and the
    error messages never said what WAS accepted."""
    if algorithm in aliases:
        resolved = aliases[algorithm]
    elif algorithm in real:
        resolved = algorithm
    else:
        accepted = sorted(set(real) | set(aliases))
        raise ValueError(
            f"unknown {coll} algorithm {algorithm!r}; accepted: {accepted}")
    rec = _telemetry.REC
    if rec is not None:
        # flight recorder (ISSUE 13): the ONE gate every host collective
        # passes — stamp the RESOLVED algorithm into the open trace span
        # (the requested spelling may have been 'auto'/'fused')
        rec.note_algorithm(resolved)
    return resolved


def _trace_nbytes(obj: Any) -> Optional[int]:
    """Cheap payload-size guess for a collective trace span (tracing-on
    path only): arrays report nbytes, list payloads (alltoall/scatter)
    sum their sized elements, opaque objects report None."""
    n = getattr(obj, "nbytes", None)
    if n is not None:
        return int(n)
    if isinstance(obj, (bytes, bytearray)):
        return len(obj)
    if isinstance(obj, (list, tuple)):
        total = 0
        for item in obj:
            total += getattr(item, "nbytes", 0) or 0
        return total or None
    return None


def _note_alg(algorithm: str) -> str:
    """Stamp the FINAL concrete algorithm into the open trace span.
    The ``_resolve_algorithm`` gate passes ``'auto'`` through (tuning/
    arena/seed policy pick later), so each wire dispatch point calls
    this once the pick is real; an arena hit notes ``'sm'`` centrally
    in ``coll_sm._sm_coll``.  Returns its argument so assignment sites
    can wrap in place."""
    rec = _telemetry.REC
    if rec is not None:
        rec.note_algorithm(algorithm)
    return algorithm


def _traced_coll(fn):
    """Collective begin/end tracing (mpi_tpu/telemetry, ISSUE 13).  Off
    mode is ONE module-attribute None test before the undecorated call
    — the same shape as the ft/verify/progress gates, pvar-asserted by
    ``bench.py --verify-overhead --trace``.  On: a span carrying the
    collective name, requested->resolved algorithm (rewritten at the
    ``_resolve_algorithm`` gate and again at the concrete dispatch
    pick), payload bytes, duration, and the error class on a raising
    exit; completed spans also feed the ``coll_latency_s`` histogram
    pvar and profiling.CommStats."""
    name = fn.__name__

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        rec = _telemetry.REC
        if rec is None:
            return fn(self, *args, **kwargs)
        cell = rec.coll_begin(
            name, kwargs.get("algorithm"),
            _trace_nbytes(args[0]) if args else None)
        try:
            out = fn(self, *args, **kwargs)
        except BaseException as e:
            rec.coll_end(cell, error=type(e).__name__)
            raise
        rec.coll_end(cell)
        return out

    return wrapper


def _unpost(reqs: Sequence["_RecvRequest"]) -> None:
    """Failure path of a collective that posted internal irecvs: remove
    the not-yet-completed ones from their posted queues.  A stale queue
    head would silently absorb the first frames of any LATER collective
    on the same (source, _TAG_COLL) channel and misfold; un-posting at
    least fails the next operation loudly (in-flight peer bytes may
    still arrive — see _seg_exchange).  Under the progress engine the
    removal holds the completion lock — the engine thread may be
    completing one of these requests right now."""
    if not reqs:
        return
    reg = reqs[0]._comm._recv_reg
    if reg is not None:
        # cancel the steering entries too: a frame that never came must
        # not leave a claimable entry for a LATER collective's frame to
        # steer into (mpi_tpu/recvpool.py pairs by per-channel order)
        for req in reqs:
            reg.cancel(req._steer_token)
    eng = reqs[0]._comm._progress
    if eng is not None:
        with eng.cv:
            for req in reqs:
                if not req._done and req in req._queue:
                    req._queue.remove(req)
        return
    for req in reqs:
        if not req._done and req in req._queue:
            req._queue.remove(req)


class _SegSender:
    """Engine-advanced send window of one ``_seg_exchange`` step
    (``progress=thread`` only): the pipelined sends beyond the initial
    ``_SEG_WINDOW`` credit are posted by whoever completes the matching
    receives — usually the progress engine's thread, via each pipeline
    irecv's ``_on_complete`` callback — so the credit window advances
    without the caller being inside ``_seg_exchange`` at all.

    Sends happen UNDER the sender lock: two threads advancing
    concurrently must emit spans in table order (the receiver folds by
    position — an inverted pair would misfold silently).  A send
    failure on the engine thread is recorded, never raised there; the
    caller re-raises it at its next fold/drain step (``check``)."""

    __slots__ = ("_comm", "_work", "_spans", "_dest", "_si", "_lock",
                 "_wire", "error")

    def __init__(self, comm: "P2PCommunicator", work: np.ndarray,
                 spans, dest: int, wire=None):
        self._comm, self._work, self._spans = comm, work, spans
        self._dest = dest
        self._si = 0
        self._lock = threading.Lock()
        self._wire = wire  # wire-dtype codec (compress.py), None = plain
        self.error: Optional[BaseException] = None

    def post(self, n: int) -> None:
        with self._lock:
            while n > 0 and self._si < len(self._spans):
                lo, hi = self._spans[self._si]
                self._si += 1
                n -= 1
                view = self._work[lo:hi]
                # encode-on-send: the wire codec emits fresh buffers, so
                # the aliasing-transport snapshot is already paid
                payload = (self._wire.encode(view) if self._wire is not None
                           else self._comm._coll_payload(view))
                self._comm._send_internal(payload, self._dest, _TAG_COLL)

    def advance(self) -> None:
        """One receive completed: extend the credit window by one span.
        Runs on the completing thread (engine or caller), outside the
        engine's completion lock."""
        if self.error is not None:
            return
        try:
            self.post(1)
        except BaseException as e:  # noqa: BLE001 - re-raised by caller
            self.error = e

    def check(self) -> None:
        if self.error is not None:
            raise self.error

    def drain(self) -> None:
        """Caller, after every fold: post whatever the completion
        callbacks have not (receive range shorter than the send range),
        then surface any engine-side send failure."""
        self.check()
        self.post(len(self._spans))
        self.check()


def _as_array(obj: Any) -> Tuple[np.ndarray, bool]:
    """Coerce a reduction payload to an ndarray; remember scalar-ness."""
    arr = np.asarray(obj)
    return arr, arr.ndim == 0


def _unwrap(arr: np.ndarray, was_scalar: bool) -> Any:
    return arr[()] if was_scalar else arr


_JAX_ARRAY_TYPE: Optional[type] = None


def _is_jax_array(x: Any) -> bool:
    """jax Arrays are immutable by design — safe to alias, wasteful to
    deep-copy (a pickle round-trip would force a device→host transfer).
    The type is resolved once (failed imports are not cached by Python)."""
    global _JAX_ARRAY_TYPE
    if _JAX_ARRAY_TYPE is None:
        try:
            import jax
            _JAX_ARRAY_TYPE = jax.Array
        except Exception:  # noqa: BLE001 - no jax, no jax arrays
            _JAX_ARRAY_TYPE = ()  # falsy sentinel: never matches
    return isinstance(x, _JAX_ARRAY_TYPE) if _JAX_ARRAY_TYPE else False


def _maybe_stack(local_payload: Any, items: List[Any]) -> Any:
    """Stack gathered results into a [P, ...] array ONLY when the local
    payload was an array and every result agrees in shape/dtype — matching
    the TPU backend's stacked convention without giving up the pickle
    backends' heterogeneous-payload generality (a list otherwise)."""
    if not (hasattr(local_payload, "shape") and hasattr(local_payload, "dtype")):
        return items
    arrs = []
    for i in items:
        if not (hasattr(i, "shape") and hasattr(i, "dtype")):
            return items
        a = np.asarray(i)
        if arrs and (a.shape != arrs[0].shape or a.dtype != arrs[0].dtype):
            return items
        arrs.append(a)
    return np.stack(arrs)


class Message:
    """A matched-probe message handle (MPI_Message [S: MPI-3 ch.3.8]).

    Produced by ``comm.mprobe``/``comm.improbe``; the message is already
    OUT of the matching queues, so it can only be consumed here."""

    __slots__ = ("source", "tag", "_payload", "_consumed", "_comm")

    def __init__(self, payload: Any, source: int, tag: int, comm=None):
        self._payload = payload
        self.source = source
        self.tag = tag
        self._consumed = False
        self._comm = comm  # lets MPI_Mrecv honor the comm's errhandler

    def recv(self, status: Optional[Status] = None) -> Any:
        """MPI_Mrecv: consume the matched message (exactly once)."""
        if self._consumed:
            raise RuntimeError("MPI_Mrecv on an already-consumed message")
        self._consumed = True
        if status is not None:
            status._fill(self.source, self.tag, self._payload)
        payload, self._payload = self._payload, None
        return payload


def snapshot_payload(transport: Transport, payload: Any) -> Any:
    """Deep-copy ``payload`` iff the transport aliases payloads (local with
    copy_payloads=False) — the ONE site encoding the buffer-reuse snapshot
    rules for persistent sends AND partitioned pready.  Serializing
    transports copy in send() anyway, so snapshotting there would double
    the work.  ndarrays get a cheap .copy(); other mutable payloads a
    pickle round-trip; immutables (and immutable-by-design jax arrays)
    pass through."""
    if not transport.aliases_payloads:
        return payload
    if isinstance(payload, np.ndarray):
        return payload.copy()
    if isinstance(payload, (int, float, complex, bool, str, bytes,
                            type(None))) or _is_jax_array(payload):
        return payload
    return pickle.loads(pickle.dumps(payload,
                                     protocol=pickle.HIGHEST_PROTOCOL))


class Request:
    """Handle for a nonblocking operation (MPI_Request).

    ``wait()`` blocks until completion and returns the payload (None for
    sends); ``test()`` returns (done, payload-or-None) without blocking."""

    # Verifier tracking record (mpi_tpu/verify) — None when the request
    # was created with the verifier off, so _vnote is one attribute test.
    _vinfo = None

    def _vnote(self, completed: bool, blocking: bool = True) -> None:
        vi = self._vinfo
        if vi is not None:
            vi.note(completed, blocking)

    def wait(self) -> Any:
        raise NotImplementedError

    def test(self) -> Tuple[bool, Any]:
        raise NotImplementedError


class _CompletedRequest(Request):
    def __init__(self, value: Any = None):
        self._value = value

    def wait(self) -> Any:
        self._vnote(True)
        return self._value

    def test(self) -> Tuple[bool, Any]:
        self._vnote(True, blocking=False)
        return True, self._value


class _ReplaceRequest(Request):
    """isendrecv_replace's handle: delegates to the inner irecv on the
    caller's thread and applies the in-place refill exactly once at
    completion.  Refill failures (shape mismatch, read-only buffer)
    RAISE — a swallowed error would leave ``buf`` silently stale."""

    def __init__(self, inner: Request, buf: Any):
        self._inner = inner
        self._buf = buf
        self._done = False
        self._value: Any = None

    def _finish(self, got: Any) -> Any:
        import numpy as _np

        if isinstance(self._buf, _np.ndarray):
            # the refill mutates the caller's SEND buffer in place —
            # which a resilient link may still retain by reference
            # (copy-on-write before the write, mpi_tpu/bufpool.py)
            _bufpool.touch(self._buf)
            self._buf[...] = got
        self._done, self._value = True, got
        return got

    def wait(self) -> Any:
        if self._done:
            return self._value
        return self._finish(self._inner.wait())

    def test(self) -> Tuple[bool, Any]:
        if self._done:
            return True, self._value
        done, got = self._inner.test()
        if not done:
            return False, None
        return True, self._finish(got)


class _RecvRequest(Request):
    """Outstanding receive.  Requests posted on the same (source, tag) key
    complete in POSTED order regardless of wait()/test() call order (MPI
    matching rule): completing a later request first drains its earlier
    siblings from the shared posted-queue.  (Posted-order across *mixed*
    wildcard and specific envelopes is not modeled — each exact key orders
    independently.)

    With the async progress engine attached (mpi_tpu/progress.py,
    ``progress=thread``) completion is SHARED between the caller and the
    engine thread: both go through the engine's completion lock
    (``ProgressEngine.try_complete``), so a message is consumed exactly
    once and ``_done`` may flip in the background while the caller
    computes.  ``_on_complete`` is the engine's post-completion callback
    slot (segmented-engine send-window credit, _SegSender.advance)."""

    _on_complete = None  # set by _seg_exchange under the progress engine
    # recv-steering registry token of an internal posted irecv
    # (mpi_tpu/recvpool.py note_post) — cancelled by _unpost
    _steer_token = None
    # user-buffer rendezvous (ISSUE 19): the irecv(buf=...) destination
    # (ndarray or list of ndarrays, filled at completion) and whether it
    # was registered as a claimable steering entry — armed completions
    # whose payload is NOT the view take the named fallback below
    _user_buf = None
    _user_armed = False

    def __init__(self, comm: "P2PCommunicator", source: int, tag: int,
                 queue: List["_RecvRequest"]):
        self._comm, self._source, self._tag = comm, source, tag
        self._queue = queue
        self._done = False
        self._value: Any = None
        queue.append(self)

    def _complete(self, payload: Any) -> None:
        reg = self._comm._recv_reg
        if reg is not None and reg.live_count and self._tag >= -1:
            # USER-facing completion (every engine and queue-head path
            # funnels through here): a steered user view may be live in
            # the aliasing guard.  The owner's identity pop closes its
            # lifecycle zero-copy; any other consumer of a live view
            # gets a private copy (mpi_tpu/recvpool.py sanitize).
            payload = reg.sanitize(payload, self._user_buf)
        ub = self._user_buf
        if ub is not None:
            if payload is ub:
                # the frame's bytes were landed DIRECTLY in the
                # caller's buffer by the transport reader — the
                # zero-copy user rendezvous path
                _mpit.count(recv_user_inplace=1)
            else:
                if self._user_armed:
                    # the match raced the reader (or the frame was not
                    # steerable): rescue any still-unpopped claim
                    # first, then retire the entry so a LATER frame can
                    # never claim it and scribble the now-user-owned
                    # buffer
                    reg.pre_overwrite(ub)
                    reg.cancel(self._steer_token)
                    _mpit.count(recv_user_fallbacks=1)
                try:
                    if isinstance(ub, list):
                        for b, g in zip(ub, payload):
                            _bufpool.touch(b)
                            b[...] = g
                    else:
                        _bufpool.touch(ub)
                        ub[...] = payload
                except (TypeError, ValueError):
                    pass  # geometry mismatch: payload still returned
        self._value, self._done = payload, True
        if self in self._queue:
            self._queue.remove(self)

    def _poll_once(self):
        src_world = (ANY_SOURCE if self._source == ANY_SOURCE
                     else self._comm._world(self._source))
        if src_world == ANY_SOURCE and self._tag >= -1 \
                and self._comm._verify is not None:
            # wildcard irecv: attribute any race the consume scan finds
            # to the posting site (the consuming thread may be the
            # progress engine, whose own frames are meaningless here)
            vc = getattr(self._comm._t, "verify_clock", None)
            if vc is not None:
                vi = self._vinfo
                vc.set_site(vi.site if vi is not None else "<irecv>")
        return self._comm._t.poll(src_world, self._comm._ctx, self._tag)

    def wait(self) -> Any:
        if self._comm._progress is not None:
            # engine mode: completion is lock-serialized with the
            # background thread — a blocking consume here could swallow
            # a message the engine already matched to an earlier
            # sibling (or strand this thread after the engine consumed
            # ours), so the wait parks on the engine instead
            self._comm._progress_wait_request(self)
            self._vnote(True)
            return self._value
        while not self._done:
            head = self._queue[0]  # earliest posted request gets the message
            # _recv_internal, not recv: the posting entry point already
            # validated user tags, and internal (negative-tag) requests —
            # the segmented collective engine's pipelined irecvs — must
            # not trip the user-tag check at completion time
            head._complete(self._comm._recv_internal(
                head._source, head._tag, _posted=True))
        self._vnote(True)
        return self._value

    def test(self) -> Tuple[bool, Any]:
        eng = self._comm._progress
        if eng is not None:
            if not self._done:
                with eng.cv:
                    cbs = eng.try_complete(self)
                for cb in cbs:  # credit-window sends, outside the lock
                    cb()
            if not self._done:
                self._comm._empty_poll_check(self._source, self._tag)
                return False, None
            self._vnote(True, blocking=False)
            return True, self._value
        while not self._done:
            head = self._queue[0]
            hit = head._poll_once()
            if hit is None:
                # FT parity with wait(): a polling loop over a dead
                # peer (or a revoked communicator) must fail within the
                # detection bound, not spin forever returning (False,
                # None).  Checked only on the empty path — a message
                # already delivered stays receivable (MPI: completable
                # operations complete even after a peer death).
                self._comm._empty_poll_check(self._source, self._tag)
                return False, None
            head._complete(hit[0])
            if self._comm._verify is not None:
                # a poll hit is real progress: stamp it (and retract any
                # stale published entry) even though this completion
                # path bypasses _recv_internal
                self._comm._verify.world.note_progress()
        self._vnote(True, blocking=False)
        return True, self._value


class PersistentRequest(Request):
    """A persistent operation (MPI_Send_init / MPI_Recv_init) [S].

    Binds the argument list once; each ``start()`` launches one operation,
    ``wait()`` completes it and returns the request to the inactive state
    (ready to start again).  For sends the bound buffer is read at *start*
    time (numpy buffers may be refilled in place between starts, the MPI
    buffer-reuse idiom).  For receives, ``wait()`` returns the payload and
    additionally copies it into the bound ``buf`` if one was given.
    """

    def __init__(self, comm: "P2PCommunicator", kind: str, buf: Any,
                 peer: int, tag: int):
        self._comm, self._kind, self._buf = comm, kind, buf
        self._peer, self._tag = peer, tag
        self._inner: Optional[Request] = None  # active sub-request
        self._last: Any = None  # last completed payload (sticky, see wait)
        self._buf_key: Optional[int] = None  # verifier live-buffer handle

    @property
    def active(self) -> bool:
        return self._inner is not None

    def start(self) -> "PersistentRequest":
        if self._inner is not None:
            raise RuntimeError(
                "start() on an active persistent request (MPI: erroneous "
                "until the previous operation completes)")
        if self._kind == "send":
            # Snapshot at start() time: the MPI buffer-reuse idiom lets the
            # caller refill the bound buffer as soon as start() returns
            # (see snapshot_payload).
            payload = snapshot_payload(self._comm._t, self._buf)
            self._inner = self._comm.isend(payload, self._peer, self._tag)
        else:
            self._inner = self._comm.irecv(self._peer, self._tag)
            if self._buf is not None:
                # bind the bound buffer to THIS operation: the refill
                # happens at the inner completion (steered frames land
                # in it directly on steering transports — the
                # persistent-handle flavor of the ISSUE 19 user-buffer
                # rendezvous; everything else is copied in there)
                self._comm._arm_user_recv(
                    self._inner, self._peer, self._tag, self._buf)
            v = self._comm._verify
            if v is not None and isinstance(self._buf, np.ndarray):
                # live receive buffer: overlapping another pending
                # nonblocking op's buffer is the message-race lint
                from .verify.state import user_site

                self._buf_key = v.world.buffer_live(
                    self._buf,
                    f"rank {self._comm.rank}: recv_init(source="
                    f"{self._peer}, tag={self._tag}).start() at "
                    f"{user_site()}", writes=True)
        return self

    def wait(self) -> Any:
        # completed values stay readable until the next start() — wait()/
        # test() after completion keep returning the same payload, so
        # request-set helpers (MPI_Testall/Waitsome) that re-poll never
        # lose a value delivered on an earlier sweep
        if self._inner is None:
            return self._last  # [S] inactive: immediate, last completion
        value = self._inner.wait()
        self._complete(value)
        return value

    def test(self) -> Tuple[bool, Any]:
        if self._inner is None:
            return True, self._last  # [S] inactive: flag=true, last value
        done, value = self._inner.test()
        if done:
            self._complete(value)
        return done, value

    def _complete(self, value: Any) -> None:
        inner = self._inner
        self._inner = None
        self._last = value
        if self._buf_key is not None:
            self._comm._verify.world.buffer_release(self._buf_key)
            self._buf_key = None
        if (self._kind == "recv" and isinstance(self._buf, np.ndarray)
                and value is not self._buf
                and (inner is None
                     or getattr(inner, "_user_buf", None) is None)):
            # legacy refill for inner requests that could not carry the
            # buffer (non-_RecvRequest paths); _arm_user_recv-bound
            # buffers were already refilled — or steered in place — at
            # the inner completion (_RecvRequest._complete)
            _bufpool.touch(self._buf)  # ownership CoW before the refill
            self._buf[...] = value


def startall(requests: Sequence[PersistentRequest]) -> List[PersistentRequest]:
    """MPI_Startall [S]."""
    for r in requests:
        r.start()
    return list(requests)


class _ThreadRequest(Request):
    """Nonblocking collective in flight: the blocking algorithm runs on a
    thread against an isolated context (see P2PCommunicator._nbc_comm).

    This is the FALLBACK path (ISSUE 12): worlds running the async
    progress engine dispatch i-collectives as schedule state machines
    instead (mpi_tpu/nbc.py — zero per-call threads, pvar-asserted via
    ``nbc_threads_spawned``, which counts every spawn here)."""

    def __init__(self, fn):
        _mpit.count(nbc_threads_spawned=1)
        self._value: Any = None
        self._error: Optional[BaseException] = None

        def run():
            try:
                self._value = fn()
            except BaseException as e:  # noqa: BLE001 - re-raised at wait()
                self._error = e

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def wait(self) -> Any:
        self._thread.join()
        self._vnote(True)
        if self._error is not None:
            raise self._error
        return self._value

    def test(self) -> Tuple[bool, Any]:
        if self._thread.is_alive():
            return False, None
        self._vnote(True, blocking=False)
        if self._error is not None:
            raise self._error
        return True, self._value


class Keyval:
    """Attribute key (MPI_Comm_create_keyval [S]).

    ``copy_fn(comm, value) -> new value`` decides what a dup'd communicator
    inherits; return :data:`NO_COPY` (or set ``copy_fn=None``, the
    MPI_COMM_NULL_COPY_FN default) to not propagate.  ``delete_fn(comm,
    value)`` runs when the attribute is deleted or overwritten."""

    __slots__ = ("copy_fn", "delete_fn", "name")

    def __init__(self, copy_fn=None, delete_fn=None, name: str = ""):
        self.copy_fn = copy_fn
        self.delete_fn = delete_fn
        self.name = name

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"Keyval({self.name or hex(id(self))})"


NO_COPY = object()  # sentinel a copy_fn returns to veto propagation


def dup_fn(comm, value):
    """MPI_COMM_DUP_FN: propagate the value as-is on dup."""
    return value


def create_keyval(copy_fn=None, delete_fn=None, name: str = "") -> Keyval:
    """MPI_Comm_create_keyval.  The keyval OBJECT is the key (no integer
    handle table to leak); free_keyval is garbage collection."""
    return Keyval(copy_fn, delete_fn, name)


class Communicator(ABC):
    """Abstract communicator: the API user MPI programs are written against."""

    # -- attribute caching (MPI-1 §5.7 keyvals) ----------------------------
    # Host-side bookkeeping only (never touches the transport or device),
    # so it lives on the ABC and every backend inherits it.

    def set_attr(self, keyval: Keyval, value: Any) -> None:
        """MPI_Comm_set_attr; overwriting runs the old value's delete_fn."""
        attrs = self.__dict__.setdefault("_attrs", {})
        if keyval in attrs and keyval.delete_fn is not None:
            keyval.delete_fn(self, attrs[keyval])
        attrs[keyval] = value

    def get_attr(self, keyval: Keyval) -> Any:
        """MPI_Comm_get_attr: the value, or None when unset (the flag=false
        analogue)."""
        return self.__dict__.get("_attrs", {}).get(keyval)

    def delete_attr(self, keyval: Keyval) -> None:
        """MPI_Comm_delete_attr: remove + run delete_fn (no-op when unset)."""
        attrs = self.__dict__.get("_attrs", {})
        if keyval in attrs:
            value = attrs.pop(keyval)
            if keyval.delete_fn is not None:
                keyval.delete_fn(self, value)

    def _copy_attrs_to(self, new: "Communicator") -> "Communicator":
        """Dup-time attribute propagation per MPI copy-callback semantics
        (+ error-handler inheritance, which dup also owes)."""
        for keyval, value in self.__dict__.get("_attrs", {}).items():
            if keyval.copy_fn is None:
                continue
            copied = keyval.copy_fn(self, value)
            if copied is not NO_COPY:
                new.set_attr(keyval, copied)
        return self._inherit_errhandler(new)

    def _inherit_errhandler(self, new: "Communicator") -> "Communicator":
        """MPI: a newly created communicator inherits the parent's error
        handler [S, MPI-3.1 §8.3] — dup AND split/create (attributes, by
        contrast, propagate only through dup's copy callbacks)."""
        if "_errhandler" in self.__dict__:
            new._errhandler = self._errhandler
        return new

    # -- error handling (MPI-1 §7; mpi_tpu/errors.py) ----------------------
    # The object API always raises; the flat MPI_* layer consults this
    # handler at its boundary (ERRORS_ARE_FATAL default = propagate).

    def set_errhandler(self, handler) -> None:
        """MPI_Comm_set_errhandler: ERRORS_ARE_FATAL, ERRORS_RETURN, or a
        callable ``handler(comm, exc)``."""
        self._errhandler = handler

    def get_errhandler(self):
        from .errors import ERRORS_ARE_FATAL

        return getattr(self, "_errhandler", ERRORS_ARE_FATAL)

    # -- identity ----------------------------------------------------------

    @property
    @abstractmethod
    def rank(self):
        """This process's rank in this communicator (0..size-1)."""

    @property
    @abstractmethod
    def size(self) -> int:
        """Number of ranks in this communicator."""

    # -- point-to-point ----------------------------------------------------

    @abstractmethod
    def send(self, obj: Any, dest: int, tag: int = 0) -> None:
        """Blocking standard-mode send (buffered; completes locally)."""

    @abstractmethod
    def recv(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
             status: Optional[Status] = None) -> Any:
        """Blocking matched receive; returns the payload."""

    @abstractmethod
    def sendrecv(self, sendobj: Any, dest: int, source: int = ANY_SOURCE,
                 sendtag: int = 0, recvtag: int = ANY_TAG,
                 status: Optional[Status] = None) -> Any:
        """Combined send+receive (deadlock-free halo-exchange primitive)."""

    @abstractmethod
    def shift(self, obj: Any, offset: int = 1, wrap: bool = True, fill: Any = None) -> Any:
        """Portable neighbor exchange: every rank sends ``obj`` to
        ``rank+offset`` and returns the payload from ``rank-offset``.

        With ``wrap=False`` boundary ranks send/receive nothing and the
        receiver-side hole is filled with ``fill``.  This is the portable
        spelling of the Jacobi halo exchange (BASELINE.json:11): on CPU
        backends it is a sendrecv pair, on TPU it is exactly one
        ``lax.ppermute`` (SURVEY.md §3.2).
        """

    def exchange(self, obj: Any, pairs: Sequence[Tuple[int, int]],
                 fill: Any = None) -> Any:
        """Static-pattern point-to-point: every ``(src, dst)`` in ``pairs``
        ships src's payload to dst.  The portable spelling of a set of
        matched Send/Recv calls — one ``lax.ppermute`` on TPU, buffered
        send/recv pairs on process backends.  Ranks receiving nothing get
        ``fill`` (array payloads get an array-shaped fill; TPU defaults the
        hole to zeros)."""
        raise NotImplementedError(f"{type(self).__name__} does not implement exchange")

    def _verify_counts(self, coll: str, counts) -> None:
        """Vector-collective hook: with the runtime verifier on (P2P
        backends only — the attribute is never set elsewhere), cross-
        check the literal counts vector across ranks; divergence is the
        truncating-recv case (rank j sends counts_j[j] rows, rank i
        reads counts_i[j] of them)."""
        v = getattr(self, "_verify", None)
        if v is not None and self.size > 1:
            from .verify import collcheck as _vcc

            _vcc.check(self, coll, counts=tuple(
                tuple(int(c) for c in row) if hasattr(row, "__len__")
                else int(row) for row in counts))

    # -- collectives -------------------------------------------------------

    @abstractmethod
    def bcast(self, obj: Any, root: int = 0, algorithm: str = "auto") -> Any: ...

    @abstractmethod
    def reduce(self, obj: Any, op: _ops.ReduceOp = _ops.SUM, root: int = 0,
               algorithm: str = "auto") -> Any: ...

    @abstractmethod
    def allreduce(self, obj: Any, op: _ops.ReduceOp = _ops.SUM,
                  algorithm: str = "auto") -> Any: ...

    @abstractmethod
    def allgather(self, obj: Any, algorithm: str = "auto") -> Any: ...

    @abstractmethod
    def alltoall(self, objs: Sequence[Any], algorithm: str = "auto") -> Any: ...

    @abstractmethod
    def barrier(self) -> None: ...

    def localize(self, obj: Any) -> Any:
        """Mark ``obj`` as rank-local state (identity on process-backed
        backends).  On the TPU backend this brands replicated values as
        rank-varying, which matters for autodiff: jax's varying-axes-typed AD
        auto-psums the cotangent of a *replicated* value used in a varying
        computation, so MPI-style programs that take ``jax.grad`` w.r.t.
        replicated parameters and then ``allreduce`` the gradients would
        double-count by a factor of P.  Wrap per-rank model state in
        ``comm.localize(...)`` once at creation and gradients stay local,
        making the explicit allreduce the single point of synchronization on
        every backend (see examples/data_parallel.py)."""
        return obj

    def scan(self, obj: Any, op: _ops.ReduceOp = _ops.SUM) -> Any:
        """MPI_Scan [S]: inclusive prefix reduction — rank r gets the
        reduction of ranks 0..r."""
        raise NotImplementedError(f"{type(self).__name__} does not implement scan")

    def exscan(self, obj: Any, op: _ops.ReduceOp = _ops.SUM) -> Any:
        """MPI_Exscan [S]: exclusive prefix reduction — rank r gets the
        reduction of ranks 0..r-1.  Rank 0 gets the op identity (MPI leaves
        it undefined; a defined identity is the SPMD-portable choice and
        makes ``scan == combine(exscan, local)`` hold on every rank).

        Default implementation: inclusive scan + one boundary shift — works
        on every backend that provides ``scan`` and ``shift``."""
        scanned = self.scan(obj, op)
        dtype = getattr(scanned, "dtype", None)
        if dtype is None:
            dtype = np.asarray(scanned).dtype
        return self.shift(scanned, offset=1, wrap=False,
                          fill=op.identity(np.dtype(dtype)))

    def maxloc(self, obj: Any) -> Tuple[Any, Any]:
        """MPI_MAXLOC [S]: elementwise (max value, lowest rank attaining it)."""
        return self._allreduce_loc(obj, _ops.MAX)

    def minloc(self, obj: Any) -> Tuple[Any, Any]:
        """MPI_MINLOC [S]: elementwise (min value, lowest rank attaining it)."""
        return self._allreduce_loc(obj, _ops.MIN)

    def _allreduce_loc(self, obj: Any, op: _ops.ReduceOp) -> Tuple[Any, Any]:
        best = self.allreduce(obj, op=op)
        arr = np.asarray(obj)
        cand = np.where(arr == np.asarray(best), self.rank, self.size)
        loc = self.allreduce(cand.astype(np.int64), op=_ops.MIN)
        return best, _unwrap(np.asarray(loc), arr.ndim == 0)

    def reduce_scatter(self, blocks: Any, op: _ops.ReduceOp = _ops.SUM,
                       algorithm: str = "auto") -> Any:
        """MPI_Reduce_scatter_block [S]: ``blocks`` holds one block per rank
        (leading dimension == size); rank r gets the reduction of everyone's
        block r."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement reduce_scatter")

    def scatter(self, objs: Optional[Sequence[Any]], root: int = 0) -> Any:
        raise NotImplementedError(f"{type(self).__name__} does not implement scatter")

    def gather(self, obj: Any, root: int = 0) -> Optional[List[Any]]:
        raise NotImplementedError(f"{type(self).__name__} does not implement gather")

    # -- vector (variable-count) collectives -------------------------------
    #
    # MPI_*v semantics [S] with counts as *static* Python ints, the portable
    # common denominator: process backends have fully dynamic shapes, but the
    # SPMD backend traces one program, so per-rank counts must be known at
    # trace time.  Contract shared by all backends:
    #   * ``counts[i]`` is the number of leading-axis rows rank i contributes
    #     (or receives, for scatterv);
    #   * inputs may be padded to ``max(counts)`` rows — only the first
    #     ``counts[rank]`` rows of this rank's payload are used;
    #   * allgatherv/gatherv return the ragged concatenation
    #     [sum(counts), ...] (replicated everywhere on SPMD, root-only for
    #     gatherv on process backends).

    def allgatherv(self, obj: Any, counts: Sequence[int]) -> Any:
        """MPI_Allgatherv [S]: concatenation of every rank's first
        ``counts[rank]`` rows, in rank order."""
        self._check_counts(counts)
        self._verify_counts("allgatherv", counts)
        items = self.allgather(self._take_rows(obj, counts[self.rank]))
        return np.concatenate([np.asarray(it) for it in items], axis=0)

    def gatherv(self, obj: Any, counts: Sequence[int],
                root: int = 0) -> Optional[Any]:
        """MPI_Gatherv [S]: like allgatherv, result only guaranteed at root."""
        self._check_counts(counts)
        self._verify_counts("gatherv", counts)
        items = self.gather(self._take_rows(obj, counts[self.rank]), root)
        if items is None:
            return None
        return np.concatenate([np.asarray(it) for it in items], axis=0)

    def scatterv(self, obj: Any, counts: Sequence[int], root: int = 0) -> Any:
        """MPI_Scatterv [S]: root holds the [sum(counts), ...] concatenation;
        rank r receives its ``counts[r]``-row slice.  (The SPMD backend
        returns it padded to ``max(counts)`` rows — static shapes.)"""
        self._check_counts(counts)
        self._verify_counts("scatterv", counts)
        parts: Optional[List[Any]] = None
        if self.rank == root:
            offs = np.cumsum([0] + list(counts))
            arr = np.asarray(obj)
            if arr.shape[0] != offs[-1]:
                raise ValueError(
                    f"scatterv root payload needs sum(counts)={offs[-1]} rows, "
                    f"got {arr.shape[0]}")
            parts = [arr[offs[i]:offs[i + 1]] for i in range(self.size)]
        return self.scatter(parts, root)

    def alltoallv(self, blocks: Any, counts: Sequence[Sequence[int]]) -> Any:
        """MPI_Alltoallv [S]: ``counts[i][j]`` rows travel from rank i to
        rank j.  ``blocks[d]`` is the payload for rank d (first
        ``counts[rank][d]`` rows used).  Returns one entry per source rank j
        holding ``counts[j][rank]`` valid rows (exact on process backends;
        padded to the global max count on SPMD)."""
        self._check_counts_matrix(counts)
        self._verify_counts("alltoallv", counts)
        sendlist = [self._take_rows(blocks[d], counts[self.rank][d])
                    for d in range(self.size)]
        return self.alltoall(sendlist)

    def _take_rows(self, obj: Any, count: int) -> np.ndarray:
        arr = np.asarray(obj)
        if arr.shape[0] < count:
            raise ValueError(
                f"rank {self.rank}: payload has {arr.shape[0]} rows but its "
                f"declared count is {count}")
        return arr[:count]

    def _check_counts(self, counts: Sequence[int]) -> None:
        if len(counts) != self.size:
            raise ValueError(
                f"need one count per rank ({self.size}), got {len(counts)}")
        if any(int(c) < 0 for c in counts):
            raise ValueError(f"counts must be >= 0, got {list(counts)}")

    def _check_counts_matrix(self, counts: Sequence[Sequence[int]]) -> None:
        if len(counts) != self.size or any(len(row) != self.size for row in counts):
            raise ValueError(
                f"alltoallv counts must be a {self.size}x{self.size} matrix")
        if any(int(c) < 0 for row in counts for c in row):
            raise ValueError(
                f"alltoallv counts must be >= 0, got {[list(r) for r in counts]}")

    # -- communicator management ------------------------------------------

    @abstractmethod
    def split(self, color: Optional[int], key: int = 0) -> Optional["Communicator"]:
        """MPI_Comm_split [S]: ranks sharing ``color`` form a new communicator
        ordered by (key, old rank); ``color=None`` opts out (returns None)."""

    @abstractmethod
    def dup(self) -> "Communicator":
        """New communicator over the same group with isolated message space."""

    def split_by_rank(self, color_fn, key_fn=None) -> Optional["Communicator"]:
        """``split`` with color/key as pure functions of the group-local rank
        — the portable spelling (works on process backends, where each rank
        evaluates its own color, AND on the SPMD backend, where the host
        evaluates the functions for every rank — see TpuCommunicator)."""
        return self.split(color_fn(self.rank),
                          key_fn(self.rank) if key_fn else 0)

    def group(self):
        """MPI_Comm_group: this communicator's group (all ranks, in order)."""
        from .group import Group

        return Group(range(self.size))

    def split_type(self, split_type: str = "shared",
                   key: int = 0) -> Optional["Communicator"]:
        """MPI_Comm_split_type(COMM_TYPE_SHARED): ranks that share
        memory.  Process worlds this library launches are single-host
        (the launcher forks locally), so here the shared-memory split is
        the whole communicator reordered by key.  The multi-host SPMD
        backend overrides this with a real by-host split (ADVICE r3 #4)."""
        if split_type != "shared":
            raise ValueError(f"unknown split_type {split_type!r}")
        return self.split(0, key)

    def win_create(self, init: Any):
        """MPI_Win_create [S]: expose a local buffer for one-sided RMA
        (put/get/accumulate inside fence epochs — see mpi_tpu/window.py).
        Collective; every rank contributes its local window contents."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement one-sided RMA")

    def _check_group(self, group) -> None:
        """Shared validation for create(): non-empty, ranks in range."""
        ranks = list(group.ranks)
        if not ranks:
            raise ValueError(
                "create(group) needs a non-empty group (MPI_GROUP_EMPTY has "
                "no communicator)")
        bad = [r for r in ranks if not (0 <= r < self.size)]
        if bad:
            raise ValueError(
                f"group ranks {bad} out of range for a size-{self.size} communicator")

    def create(self, group) -> Optional["Communicator"]:
        """MPI_Comm_create_group [S]: members of ``group`` (ranks of THIS
        comm) get a new communicator ordered by group position; non-members
        get None.  Collective over this communicator.  (The SPMD backend
        can't return None — see TpuCommunicator.create.)"""
        self._check_group(group)
        pos = group.rank_of(self.rank)
        return self.split(0 if pos is not None else None,
                          pos if pos is not None else 0)

    def free(self) -> None:
        """Release resources (no-op for sub-communicators by default)."""


class P2PCommunicator(Communicator):
    """Communicator over any point-to-point Transport (socket / local threads).

    Collectives execute the shared schedules from mpi_tpu/schedules.py with
    real sends/receives — this is the reference's architecture (SURVEY.md §1:
    L3 composes L2 primitives).
    """

    def __init__(self, transport: Transport, group: Sequence[int], context=0,
                 recv_timeout: Optional[float] = None):
        self._t = transport
        self._group: Tuple[int, ...] = tuple(group)
        if transport.world_rank not in self._group:
            raise ValueError(
                f"world rank {transport.world_rank} not in group {self._group}"
            )
        self._rank = self._group.index(transport.world_rank)
        self._ctx = context
        self._nchildren = 0
        self._lock = threading.Lock()
        # Failure-detection knob: with a timeout, a lost message surfaces as
        # RecvTimeout (with the pending-message summary) instead of a hang —
        # see transport/faulty.py for the fault-injection counterpart.
        self.recv_timeout = (recv_timeout if recv_timeout is not None
                             else _RECV_TIMEOUT_DEFAULT)
        self._irecv_queues: dict = {}
        # ULFM fault-tolerance state (mpi_tpu/ft.py CommFT), attached by
        # ft.enable(); None = all FT machinery compiled out of the hot
        # path (a single attribute test per op).
        self._ft = None
        # Runtime-verifier state (mpi_tpu/verify CommVerify), attached by
        # verify.enable(); None = the whole verifier is a single
        # attribute test per op (the off-mode zero-cost contract,
        # asserted by tests/test_verify.py and bench.py
        # --verify-overhead).
        self._verify = None
        # Which collective's machinery is currently waiting on internal
        # tags — included in ProcFailedError diagnoses.  Set-and-forget
        # at each collective entry: it is only consulted for failures on
        # internal (negative) tags, which only occur inside collectives.
        self._coll_name: Optional[str] = None
        # Async progress engine (mpi_tpu/progress.py ProgressEngine),
        # inherited from the transport so split/dup/nbc children of an
        # enabled world share the one engine thread; None = the entire
        # feature is a single attribute test per operation
        # (progress=none, the off-mode zero-cost contract).
        self._progress = getattr(transport, "_progress_engine", None)
        # Recv-steering registry (mpi_tpu/recvpool.py), present only on
        # transports whose reader can steer frame bodies into posted
        # buffers (socket); None = all steering bookkeeping is a single
        # attribute test per internal receive.
        self._recv_reg = transport.recv_registry

    # -- identity ----------------------------------------------------------

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def size(self) -> int:
        return len(self._group)

    @property
    def context(self) -> int:
        return self._ctx

    def _world(self, comm_rank: int) -> int:
        if not (0 <= comm_rank < self.size):
            raise ValueError(f"rank {comm_rank} out of range for communicator of size {self.size}")
        return self._group[comm_rank]

    def _from_world(self, world_rank: int) -> int:
        return self._group.index(world_rank)

    # -- point-to-point ----------------------------------------------------

    def send(self, obj: Any, dest: int, tag: int = 0) -> None:
        _check_user_tag(tag)
        self._send_internal(obj, dest, tag)

    def _send_internal(self, obj: Any, dest: int, tag: int) -> None:
        nbytes = getattr(obj, "nbytes", None)
        if nbytes is None and isinstance(obj, (bytes, bytearray)):
            nbytes = len(obj)
        _mpit.count(sends=1, send_bytes=int(nbytes or 0))
        dest_world = self._world(dest)
        if self._ft is not None:
            self._ft.check(self)  # raises RevokedError on a revoked comm
            if dest_world in self._ft.world.failed:
                raise ProcFailedError(
                    f"rank {self._rank}: send to dead rank {dest}",
                    failed=(dest,),
                    collective=self._coll_name if tag < 0 else None)
            try:
                self._t.send(dest_world, self._ctx, tag, obj)
            except TransportError as e:
                # transport evidence beats the detector to the diagnosis
                self._ft.world.observe(dest_world, f"send failed: {e}")
                raise ProcFailedError(
                    f"rank {self._rank}: send to rank {dest} failed "
                    f"({e})", failed=(dest,),
                    collective=self._coll_name if tag < 0 else None) from e
            if self._verify is not None:
                self._verify.world.note_progress()
            return
        self._t.send(dest_world, self._ctx, tag, obj)
        if self._verify is not None:
            self._verify.world.note_progress()

    def recv(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
             status: Optional[Status] = None) -> Any:
        _check_user_tag(tag)
        return self._recv_internal(source, tag, status)

    def _plain_recv(self, src_world: int, tag: int):
        """The no-ft/no-verify blocking receive, with the same blocked-
        wait trace span `_sliced_wait` emits — "where was this rank
        stuck" must not require enabling a checker.  Off mode is the
        one attribute test."""
        rec = _telemetry.REC
        if rec is None:
            return self._t.recv(src_world, self._ctx, tag,
                                timeout=self.recv_timeout)
        t_trace = time.perf_counter_ns()
        out = self._t.recv(src_world, self._ctx, tag,
                           timeout=self.recv_timeout)
        dur = time.perf_counter_ns() - t_trace
        if dur >= _telemetry.WAIT_MIN_NS:
            rec.emit("wait", "recv", dur_ns=dur,
                     attrs={"src": src_world, "tag": tag,
                            "coll": self._coll_name if tag < 0 else None})
        return out

    def _recv_internal(self, source: int, tag: int,
                       status: Optional[Status] = None,
                       _posted: bool = False) -> Any:
        src_world = ANY_SOURCE if source == ANY_SOURCE else self._world(source)
        reg = self._recv_reg
        counted = False
        if (not _posted and src_world != ANY_SOURCE and reg is not None
                and (tag < 0 or (reg.user_count and reg.user_active(
                    src_world, self._ctx, tag)))):
            # a BLOCKING recv on a counted channel (internal, or a user
            # channel activated by irecv(buf=)) consumes a frame on the
            # same steering channel the posted irecvs pair on — count
            # it so the frame/consumer indices stay aligned (it has no
            # destination buffer, so it never claims).  _posted=True
            # marks the queue-head servicing call of an ALREADY-counted
            # posted request (_RecvRequest.wait) — its sanitize/refill
            # runs in _RecvRequest._complete instead.
            reg.note_consume(src_world, self._ctx, tag)
            counted = True
        if self._verify is not None and src_world == ANY_SOURCE and tag >= -1:
            # wildcard-race attribution: the consume scan merges clocks
            # under the mailbox lock and cannot walk user frames there,
            # so the receive records its own call site first
            vc = getattr(self._t, "verify_clock", None)
            if vc is not None:
                from .verify.state import user_site
                vc.set_site(user_site())
        if self._ft is not None or self._verify is not None:
            obj, src, t = self._sliced_wait(src_world, tag)
        else:
            obj, src, t = self._plain_recv(src_world, tag)
        if reg is not None and not _posted and t >= 0:
            if reg.live_count:
                # this pop may have taken a steered USER view some
                # armed irecv owns — the aliasing guard hands any
                # non-owner a private copy (mpi_tpu/recvpool.py)
                obj = reg.sanitize(obj)
            if not counted and reg.user_count:
                # an UNCOUNTED pop (wildcard envelope) that landed on
                # an active user channel shifts every later consumer
                # one message earlier — tell the pairing
                reg.note_steal(src, self._ctx, t)
        _mpit.count(recvs=1)
        if status is not None:
            status._fill(self._from_world(src), t, obj)
        return obj

    # -- sliced blocking waits (mpi_tpu/ft.py + mpi_tpu/verify) ------------

    def _sliced_wait(self, src_world: int, tag: int, consume: bool = True):
        """Every FT- or verifier-enabled blocking wait (recv, probe, and
        through _RecvRequest.wait the segmented engine's irecv drains):
        the transport wait runs in _FT_POLL_S slices, and between slices

        * (FT) a queued revocation raises RevokedError and a detector
          hit on a relevant peer raises ProcFailedError — a peer death
          is noticed within the detection bound no matter how long the
          communicator-level ``recv_timeout`` is;
        * (verify) past ``verify_stall_timeout_s`` the rank publishes
          its pending op on the out-of-band board and runs the wait-for
          deadlock analysis — a proven cycle/knot raises DeadlockError
          instead of hanging (mpi_tpu/verify/deadlock.py).

        One slice loop for both: the verifier deliberately reuses the FT
        slice-poll plumbing rather than stacking a second poller."""
        ft = self._ft
        vw = self._verify.world if self._verify is not None else None
        rec = _telemetry.REC
        t_trace = time.perf_counter_ns() if rec is not None else 0
        timeout = self.recv_timeout
        start = time.monotonic()
        deadline = None if timeout is None else start + timeout
        block_id = vw.begin_block() if vw is not None else 0
        if vw is not None:
            vw.wait_enter()  # board-entry ownership: engine stands down
        try:
            while True:
                if ft is not None:
                    ft.check(self)
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                slice_s = (_FT_POLL_S if remaining is None
                           else max(0.0, min(_FT_POLL_S, remaining)))
                try:
                    if consume:
                        hit = self._t.recv(src_world, self._ctx, tag,
                                           timeout=slice_s)
                    else:
                        hit = self._t.peek(src_world, self._ctx, tag,
                                           timeout=slice_s)
                except RecvTimeout:
                    if ft is not None:
                        suspects = self._ft_suspects(src_world, tag)
                        if suspects:
                            what = (f"collective {self._coll_name!r}"
                                    if tag < 0 else f"recv(tag={tag})")
                            raise ProcFailedError(
                                f"rank {self._rank}: peer death detected "
                                f"while blocked in {what}", failed=suspects,
                                collective=self._coll_name if tag < 0
                                else None)
                    if (vw is not None and
                            time.monotonic() - start >= vw.stall_timeout_s):
                        # may raise DeadlockError; the published entry is
                        # deliberately NOT cleared on the raise — peers
                        # confirming the same diagnosis need it stable
                        self._verify_stalled(vw, src_world, tag, block_id,
                                             consume)
                    if deadline is not None \
                            and time.monotonic() >= deadline:
                        # fresh exception: re-raising the SLICE's timeout
                        # would log a nonsensical "timed out after 0.05s"
                        # for a wait that honored the configured timeout
                        raise RecvTimeout(
                            f"recv(source={src_world}, ctx={self._ctx}, "
                            f"tag={tag}) timed out after {timeout}s; "
                            f"pending={self._t.mailbox.pending_summary()}")
                else:
                    if vw is not None:
                        vw.note_progress()  # clears the published entry
                    return hit
        except (RecvTimeout, ProcFailedError, RevokedError):
            # the rank exits this wait alive (the caller may catch and
            # continue): retract any published 'blocked' entry so a peer's
            # analysis cannot keep implicating a wait that is over.
            # DeadlockError is not in this list on purpose (see above).
            if vw is not None:
                vw.clear_published()
            raise
        finally:
            if vw is not None:
                vw.wait_exit()
            if rec is not None:
                # flight recorder: blocked waits past the noise floor
                # (WAIT_MIN_NS) become spans — the per-rank timeline's
                # "where was this rank stuck" row
                dur = time.perf_counter_ns() - t_trace
                if dur >= _telemetry.WAIT_MIN_NS:
                    rec.emit(
                        "wait", "recv" if consume else "probe",
                        dur_ns=dur,
                        attrs={"src": src_world, "tag": tag,
                               "coll": self._coll_name
                               if tag < 0 else None})

    def _verify_stalled(self, vw, src_world: int, tag: int, block_id: int,
                        consume: bool) -> None:
        from .verify import deadlock as _vdl
        from .verify.state import user_site

        if src_world == ANY_SOURCE:
            targets = tuple(w for w in self._group
                            if w != self._t.world_rank)
            mode = "OR"
        else:
            targets, mode = (src_world,), "AND"
        _vdl.check_stalled(
            vw, self, targets, mode, tag,
            "recv" if consume else "probe",
            self._coll_name if tag < 0 else None, user_site(), block_id)

    def _progress_wait_request(self, req: "_RecvRequest") -> None:
        """Blocking wait on a posted receive under the async progress
        engine (mpi_tpu/progress.py): completion is serialized with the
        engine thread through the engine's completion lock, and the
        caller PARKS on the engine's condition between slices instead
        of consuming from the transport (a blocking consume here could
        swallow a message the engine already matched to an earlier
        sibling, or strand this thread after the engine consumed ours).

        The slice structure mirrors _sliced_wait exactly — FT
        detector/revocation checks, verifier stall publication, and the
        communicator recv_timeout all keep their bounds — and each
        slice retries completion itself, so the wait stays
        caller-financed whenever the engine is busy elsewhere (or was
        stopped): liveness never depends on the engine thread."""
        eng = self._progress
        ft = self._ft
        vw = self._verify.world if self._verify is not None else None
        timeout = self.recv_timeout
        start = time.monotonic()
        deadline = None if timeout is None else start + timeout
        block_id = vw.begin_block() if vw is not None else 0
        src_world = (ANY_SOURCE if req._source == ANY_SOURCE
                     else self._world(req._source))
        if vw is not None:
            vw.wait_enter()  # board-entry ownership: engine stands down
        try:
            while True:
                if ft is not None:
                    ft.check(self)
                if not req._done:
                    with eng.cv:
                        cbs = eng.try_complete(req)
                    for cb in cbs:  # credit-window sends, lock released
                        cb()
                if req._done:
                    return
                if ft is not None:
                    suspects = self._ft_suspects(src_world, req._tag)
                    if suspects:
                        what = (f"collective {self._coll_name!r}"
                                if req._tag < 0
                                else f"irecv(tag={req._tag})")
                        raise ProcFailedError(
                            f"rank {self._rank}: peer death detected "
                            f"while waiting on {what}", failed=suspects,
                            collective=self._coll_name if req._tag < 0
                            else None)
                now = time.monotonic()
                if vw is not None and now - start >= vw.stall_timeout_s:
                    self._verify_stalled(vw, src_world, req._tag,
                                         block_id, True)
                if deadline is not None and now >= deadline:
                    raise RecvTimeout(
                        f"irecv wait(source={src_world}, ctx={self._ctx}, "
                        f"tag={req._tag}) timed out after {timeout}s; "
                        f"pending={self._t.mailbox.pending_summary()}")
                with eng.cv:
                    # _done flips under eng.cv, so this re-check cannot
                    # lose a wakeup; the bounded slice keeps FT/verify/
                    # timeout cadence even if the engine thread is gone
                    if not req._done:
                        eng.cv.wait(_FT_POLL_S)
        except (RecvTimeout, ProcFailedError, RevokedError):
            # same retraction rule as _sliced_wait: the rank exits this
            # wait alive, so a published 'blocked' entry must not keep
            # implicating it (DeadlockError deliberately excluded)
            if vw is not None:
                vw.clear_published()
            raise
        finally:
            if vw is not None:
                vw.wait_exit()

    def _empty_poll_check(self, source: int, tag: int, req=None) -> None:
        """FT gate of the NONBLOCKING completion paths (Request.test,
        iprobe, improbe) on their EMPTY path: apply queued revocations
        and convert a detector hit on a relevant peer into
        ProcFailedError — same rules as the sliced blocking wait, minus
        the blocking.  The runtime verifier deliberately does NOT treat
        an empty poll as a blocked state: a nonblocking call proves
        nothing about whether the rank is stuck (it may be polling
        opportunistically while doing useful work), so publishing it as
        'blocked' — let alone raising DeadlockError from it — would
        false-positive on correct programs.  Deadlock participation is
        restricted to the blocking waits (_sliced_wait), MUST-style —
        EXCEPT under ``progress=thread``: the engine observes sustained
        empty polls, publishes an OR-set entry on the rank's behalf, and
        parks a proven DeadlockError here for the polling loop to
        re-raise (the former pure-polling residual, closed by
        mpi_tpu/progress.py)."""
        eng = self._progress
        if eng is not None:
            eng.check_error()  # a proven Waitany-loop deadlock raises
            # ``req`` (state-machine requests, mpi_tpu/nbc.py) lets the
            # engine publish THAT call's exact pending OR-set instead
            # of the union over all tracked requests
            eng.note_empty_poll(req)
        if self._ft is not None:
            self._ft.check(self)
            src_world = (ANY_SOURCE if source == ANY_SOURCE
                         else self._world(source))
            suspects = self._ft_suspects(src_world, tag)
            if suspects:
                what = (f"collective {self._coll_name!r}" if tag < 0
                        else f"poll(tag={tag})")
                raise ProcFailedError(
                    f"rank {self._rank}: peer death detected while polling "
                    f"{what}", failed=suspects,
                    collective=self._coll_name if tag < 0 else None)

    # kept under its historical name for the faulty/chaos harnesses
    _ft_poll_check = _empty_poll_check

    def _ft_suspects(self, src_world: int, tag: int) -> Tuple[int, ...]:
        """Which known-dead comm ranks make THIS wait hopeless.  Internal
        (negative) tags are collective machinery: any member death dooms
        the collective, so every failed member is a suspect.  A user
        recv from a specific source fails only if THAT source is dead; a
        wildcard recv fails on any not-yet-acknowledged death (ULFM
        ANY_SOURCE semantics — ``failure_ack`` re-arms it)."""
        ft = self._ft
        failed_world = ft.world.failed_snapshot() & set(self._group)
        if not failed_world:
            return ()
        failed = sorted(self._group.index(w) for w in failed_world)
        if tag < 0:
            return tuple(failed)
        if src_world == ANY_SOURCE:
            return tuple(r for r in failed if r not in ft.acked)
        src = self._from_world(src_world)
        return (src,) if src in failed else ()

    def sendrecv(self, sendobj: Any, dest: int, source: int = ANY_SOURCE,
                 sendtag: int = 0, recvtag: int = ANY_TAG,
                 status: Optional[Status] = None) -> Any:
        # Deadlock-free because transports buffer sends and drain receives on
        # dedicated threads (SURVEY.md §2 component #2 internals).
        self.send(sendobj, dest, sendtag)
        return self.recv(source, recvtag, status)

    def _sendrecv_internal(self, sendobj: Any, dest: int, source: int, tag: int) -> Any:
        self._send_internal(sendobj, dest, tag)
        return self._recv_internal(source, tag)

    def isend(self, obj: Any, dest: int, tag: int = 0) -> Request:
        """Nonblocking send (MPI_Isend).  Our sends are buffered (complete
        locally once enqueued on the transport), so the request is
        immediately complete — standard-mode semantics with system buffering
        [S]."""
        self.send(obj, dest, tag)
        req: Request = _CompletedRequest()
        if self._verify is not None:
            self._track_request(req, "isend", dest, tag)
        return req

    def isendrecv(self, sendobj: Any, dest: int, source: int = ANY_SOURCE,
                  sendtag: int = 0, recvtag: int = ANY_TAG) -> Request:
        """MPI_Isendrecv [S: an MPI-4 addition]: nonblocking combined
        send+receive.  The send completes on enqueue (buffered, as
        isend); the returned request completes with the received
        payload — it IS an irecv posted after the send, which preserves
        sendrecv's deadlock-freedom without blocking the caller."""
        self.send(sendobj, dest, sendtag)
        return self.irecv(source, recvtag)

    def isendrecv_replace(self, buf, dest: int, source: int = ANY_SOURCE,
                          sendtag: int = 0, recvtag: int = ANY_TAG) -> Request:
        """MPI_Isendrecv_replace [S: MPI-4]: like isendrecv but the
        received payload overwrites ``buf`` in place at completion
        (ndarray buffers; the payload is also returned for non-buffer
        use).  The outgoing content is snapshotted NOW, so the in-place
        replace can never corrupt the send.  Completion runs on the
        CALLER's wait()/test() — no background thread may touch the
        shared posted-receive queue (it would race concurrent receives
        on the same (source, tag); review round 4)."""
        self.send(snapshot_payload(self._t, buf), dest, sendtag)
        inner = self.irecv(source, recvtag)
        if self._verify is not None and inner._vinfo is not None:
            # the replace writes ``buf`` in place at completion: a live
            # write buffer for the overlap (message-race) lint
            inner._vinfo.kind = "isendrecv_replace"
            self._verify.world.track_buffer(
                inner._vinfo, buf, inner._vinfo.describe(), writes=True)
        return _ReplaceRequest(inner, buf)

    def irecv(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
              buf: Any = None) -> Request:
        """Nonblocking receive (MPI_Irecv): returns a Request; ``test()``
        polls without blocking, ``wait()`` blocks.  Requests on the same
        (source, tag) complete in posted order.

        ``buf``: optional preallocated destination (ndarray, or a list
        of ndarrays for multi-segment payloads) filled in place at
        completion.  On a steering transport with a SPECIFIC envelope
        (source and tag) the matched frame's body bytes are landed
        directly in it — the user-buffer rendezvous (ISSUE 19), priced
        by the ``recv_user_inplace`` / ``recv_user_fallbacks`` pvars."""
        _check_user_tag(tag)
        req = self._irecv_internal(source, tag)
        if buf is not None:
            self._arm_user_recv(req, source, tag, buf)
        if self._verify is not None:
            self._track_request(req, "irecv", source, tag)
            if req._user_buf is not None and req._vinfo is not None:
                # live WRITE buffer until completion: overlapping any
                # other pending op's buffer is the message-race lint —
                # the aliasing surface user steering opens (ISSUE 19)
                bufs = buf if isinstance(buf, list) else [buf]
                for b in bufs:
                    self._verify.world.track_buffer(
                        req._vinfo, b, req._vinfo.describe(), writes=True)
        return req

    def _arm_user_recv(self, req: "_RecvRequest", source: int, tag: int,
                       buf: Any) -> None:
        """Bind a user destination buffer to a posted receive: the
        payload is copied in at completion, and — when the envelope is
        specific and the buffer steering-eligible — registered with the
        recv-steering registry so the transport reader can land the
        matched frame's bytes in it directly (mpi_tpu/recvpool.py
        note_post_user/attach; shared by irecv(buf=) and started
        recv_init handles)."""
        bufs = buf if isinstance(buf, list) else [buf]
        if not all(isinstance(b, np.ndarray) for b in bufs):
            return
        req._user_buf = buf
        reg = self._recv_reg
        if (reg is None or tag < 0 or source == ANY_SOURCE
                or not (0 <= source < self.size)
                or not all(b.flags.writeable and b.flags.c_contiguous
                           for b in bufs)):
            return
        src_world = self._world(source)
        tok = req._steer_token
        if tok is None:
            # frames delivered before this channel's FIRST posted user
            # buffer were never counted: seed the pairing lag with the
            # current mailbox backlog so the first counted frame pairs
            # with the right consumer (recvpool.note_post_user)
            backlog = self._t.mailbox.count_matching(
                src_world, self._ctx, tag)
            tok = reg.note_post_user(src_world, self._ctx, tag, backlog)
            req._steer_token = tok
        reg.attach(tok, buf)
        req._user_armed = True

    def _irecv_internal(self, source: int, tag: int) -> "_RecvRequest":
        """irecv without the user-tag gate — the collective engine posts
        pipelined receives on the internal _TAG_COLL tag through here."""
        with self._lock:
            queue = self._irecv_queues.setdefault((source, tag), [])
        req = _RecvRequest(self, source, tag, queue)
        if (tag < 0 and source != ANY_SOURCE
                and self._recv_reg is not None):
            # count the posted consumer on its steering channel; the
            # collective may attach a destination view to the returned
            # token, letting the socket reader steer the paired frame's
            # body straight into it (mpi_tpu/recvpool.py)
            req._steer_token = self._recv_reg.note_post(
                self._world(source), self._ctx, tag)
        elif (tag >= 0 and source != ANY_SOURCE
              and self._recv_reg is not None
              and self._recv_reg.user_count and 0 <= source < self.size
              and self._recv_reg.user_active(
                  self._world(source), self._ctx, tag)):
            # a BUFFERLESS user irecv on an ACTIVE user channel is
            # still a counted consumer (pairing alignment); claimable
            # only if irecv(buf=) attaches a destination right after
            req._steer_token = self._recv_reg.note_post_user(
                self._world(source), self._ctx, tag, claimable=False)
        if self._progress is not None and \
                not self.__dict__.get("_progress_registered"):
            # background completion: the engine scans this comm's posted
            # queues from its own thread.  The local flag keeps this to
            # ONE lock acquisition per communicator — the engine may
            # hold its completion lock through a long ring drain, and
            # posting pipelined irecvs must not queue behind that.
            self._progress.register(self)
            self._progress_registered = True
        return req

    def send_init(self, buf: Any, dest: int, tag: int = 0) -> PersistentRequest:
        """MPI_Send_init [S]: persistent send bound to ``buf``; each
        ``start()`` snapshots the buffer and launches one send."""
        _check_user_tag(tag)
        self._world(dest)  # validate now, not at first start
        return PersistentRequest(self, "send", buf, dest, tag)

    def recv_init(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
                  buf: Any = None) -> PersistentRequest:
        """MPI_Recv_init [S]: persistent receive; each completed operation
        returns the payload (and refills ``buf`` in place when given)."""
        _check_user_tag(tag)
        if source != ANY_SOURCE:
            self._world(source)
        return PersistentRequest(self, "recv", buf, source, tag)

    def probe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
              status: Optional[Status] = None) -> None:
        """Blocking MPI_Probe: wait until a matching message is enqueued
        (without consuming it); fills ``status`` with its envelope."""
        _check_user_tag(tag)
        src_world = ANY_SOURCE if source == ANY_SOURCE else self._world(source)
        if self._ft is not None or self._verify is not None:
            s, t, n = self._sliced_wait(src_world, tag, consume=False)
        else:
            s, t, n = self._t.peek(src_world, self._ctx, tag,
                                   timeout=self.recv_timeout)
        if status is not None:
            status._fill_envelope(self._from_world(s), t, n)

    def mprobe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
               status: Optional[Status] = None) -> "Message":
        """MPI_Mprobe [S: MPI-3 matched probe]: block for a matching
        message and REMOVE it from matching — no other receive (wildcard
        or not) can steal it; consume it later with ``message.recv()``.
        The thread-safe probe+recv idiom MPI_Probe cannot provide."""
        _check_user_tag(tag)
        src_world = ANY_SOURCE if source == ANY_SOURCE else self._world(source)
        if self._ft is not None or self._verify is not None:
            obj, src, t = self._sliced_wait(src_world, tag)
        else:
            obj, src, t = self._plain_recv(src_world, tag)
        obj = self._note_probe_steal(obj, src, t)
        msg = Message(obj, self._from_world(src), t, comm=self)
        if status is not None:
            status._fill(msg.source, msg.tag, obj)
        return msg

    def _note_probe_steal(self, obj: Any, src_world: int, t: int) -> Any:
        """A matched probe REMOVED a message from matching: run it
        through the user-steering aliasing guard (the popped payload
        may be a steered view some armed irecv owns — hand out a
        private copy) and shift the channel's pairing lag down
        (mpi_tpu/recvpool.py note_steal)."""
        reg = self._recv_reg
        if reg is None or t < 0:
            return obj
        if reg.live_count:
            obj = reg.sanitize(obj)
        if reg.user_count:
            reg.note_steal(src_world, self._ctx, t)
        return obj

    def improbe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
                status: Optional[Status] = None) -> Optional["Message"]:
        """MPI_Improbe: non-blocking mprobe — a Message, or None."""
        _check_user_tag(tag)
        src_world = ANY_SOURCE if source == ANY_SOURCE else self._world(source)
        hit = self._t.poll(src_world, self._ctx, tag)
        if hit is None:
            # empty-path FT gate: see _RecvRequest.test
            self._ft_poll_check(source, tag)
            return None
        if self._verify is not None:
            self._verify.world.note_progress()
        obj, src, t = hit
        obj = self._note_probe_steal(obj, src, t)
        msg = Message(obj, self._from_world(src), t, comm=self)
        if status is not None:
            status._fill(msg.source, msg.tag, obj)
        return msg

    def iprobe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
               status: Optional[Status] = None) -> bool:
        """Nonblocking MPI_Iprobe: True iff a matching message is queued."""
        _check_user_tag(tag)
        src_world = ANY_SOURCE if source == ANY_SOURCE else self._world(source)
        hit = self._t.peek_nowait(src_world, self._ctx, tag)
        if hit is None:
            # empty-path FT gate: see _RecvRequest.test
            self._ft_poll_check(source, tag)
            return False
        if self._verify is not None:
            self._verify.world.note_progress()
        if status is not None:
            status._fill_envelope(self._from_world(hit[0]), hit[1], hit[2])
        return True

    def shift(self, obj: Any, offset: int = 1, wrap: bool = True, fill: Any = None) -> Any:
        self._coll_name = "shift"
        p, r = self.size, self._rank
        d, s = r + offset, r - offset
        if wrap:
            return self._sendrecv_internal(obj, d % p, s % p, _TAG_SHIFT)
        if 0 <= d < p:
            self._send_internal(obj, d, _TAG_SHIFT)
        if 0 <= s < p:
            return self._recv_internal(s, _TAG_SHIFT)
        if fill is None:
            return None
        # array payloads get an array-shaped fill, matching the TPU backend's
        # ppermute-hole semantics so the same program sees the same types
        if hasattr(obj, "shape") and hasattr(obj, "dtype"):
            return np.full_like(np.asarray(obj), fill)
        return fill

    def exchange(self, obj: Any, pairs: Sequence[Tuple[int, int]],
                 fill: Any = None) -> Any:
        from .checker import validate_perm

        self._coll_name = "exchange"
        validate_perm(pairs, self.size)
        dsts = [d for s, d in pairs if s == self._rank]
        srcs = [s for s, d in pairs if d == self._rank]
        for d in dsts:
            self._send_internal(obj, d, _TAG_SHIFT)
        if srcs:
            return self._recv_internal(srcs[0], _TAG_SHIFT)
        if fill is not None and hasattr(obj, "shape") and hasattr(obj, "dtype"):
            return np.full_like(np.asarray(obj), fill)
        return fill

    # -- one-sided (RMA) ---------------------------------------------------

    def win_create(self, init: Any):
        from .window import P2PWindow

        return P2PWindow(self, init)

    def win_create_dynamic(self):
        """MPI_Win_create_dynamic: a window with no initial memory;
        attach/detach regions at runtime (mpi_tpu/window.py
        DynamicWindow — regions addressed by key in every op's loc)."""
        from .window import DynamicWindow

        return DynamicWindow(self)

    # -- collectives -------------------------------------------------------

    def _verify_coll(self, coll: str, root: Optional[int] = None,
                     op: Any = None, payload: Any = None,
                     algorithm: Optional[str] = None,
                     counts: Optional[Tuple] = None) -> None:
        """Collective-matching hook (mpi_tpu/verify/collcheck.py): with
        the verifier on, circulate this entry's signature on the
        TAG_VERIFY ring and raise CollectiveMismatchError on divergence
        BEFORE any collective data moves.  A single attribute test when
        the verifier is off."""
        if self._verify is not None and self.size > 1:
            if getattr(self, "_verify_sig_frozen", False):
                # persistent collective (mpi_tpu/nbc.py): the signature
                # was exchanged ONCE at init and MPI-4 binds the
                # argument list, so per-round re-checks are frozen —
                # the hoist the persistent handle exists for
                return
            from .verify import collcheck as _vcc

            _vcc.check(self, coll, root=root, op=op, payload=payload,
                       algorithm=algorithm, counts=counts)

    def _track_request(self, req: Request, kind: str, peer: int,
                       tag: int) -> Request:
        """Register a user-level nonblocking request with the verifier
        (leak / double-wait lints).  Caller checked self._verify."""
        from .verify.state import user_site

        self._verify.world.track_request(req, kind, self._rank, peer, tag,
                                         user_site())
        return req

    @_traced_coll
    def bcast(self, obj: Any, root: int = 0, algorithm: str = "auto") -> Any:
        """MPI_Bcast.  ``algorithm``: ``"tree"`` (binomial tree, log2(P)
        rounds — BASELINE.json:8); ``"sm"`` (shm transports only: the
        shared-memory collective arena — every rank reads the root's
        slot in place, mpi_tpu/coll_sm.py); ``"auto"`` tries the arena
        when the transport has one, the tree otherwise; ``"fused"`` (the
        TPU backend's XLA-collective tier, no socket analogue) aliases
        the tree.  Large contiguous arrays take the SEGMENTED pipelined
        tree: the root announces the geometry with a _SegHeader, then
        every rank forwards each segment to its children the moment it
        lands — cut-through through tree levels instead of the seed's
        store-and-forward whole frames."""
        _mpit.count(collectives=1)
        self._coll_name = "bcast"
        algorithm = _resolve_algorithm(
            "bcast", algorithm, ("auto", "tree") + _coll_sm.gate(self),
            {"fused": "tree"})
        self._world(root)  # validate
        self._verify_coll("bcast", root=root, algorithm=algorithm)
        if self.size == 1:
            return obj
        if algorithm in ("auto", "sm"):
            # the arena decides eligibility INTERNALLY (only the root
            # knows the payload) and keeps the group in lockstep on
            # fallback — safe for auto even with rank-local knowledge
            got = _coll_sm.bcast(self, obj, root)
            if got is not _coll_sm.FALLBACK:
                return got
        _note_alg("tree")
        parent, children = schedules.binomial_tree_links(
            self.size, self._rank, root)
        if self._rank == root:
            # Gate on eligibility+size BEFORE compacting: as_raw_array's
            # ascontiguousarray on a strided view is a full-buffer copy
            # (and a payload_copies count) that the single-message path
            # below would throw away — only pay it when the segmented
            # tree actually runs.  size >= 3: with a single leaf there is
            # no interior rank to overlap forwarding, so segmentation
            # would only add a header message and an assemble copy.
            if (_codec.raw_eligible(obj) and self.size >= 3
                    and obj.nbytes >= _BCAST_SEGMENT_MIN_BYTES):
                arr = _codec.as_raw_array(obj)
                flat = arr.reshape(-1)
                seg = self._seg_elems(arr.itemsize)
                spans = schedules.segment_spans(0, flat.size, seg)
                header = _SegHeader(arr.dtype.str, arr.shape, len(spans))
                for c in children:
                    self._send_internal(header, c, _TAG_COLL)
                for lo, hi in spans:
                    view = self._coll_payload(flat[lo:hi])
                    for c in children:
                        self._send_internal(view, c, _TAG_COLL)
                return obj
            for c in children:
                self._send_internal(obj, c, _TAG_COLL)
            return obj
        got = self._recv_internal(parent, _TAG_COLL)
        if isinstance(got, _SegHeader):
            # forward the header FIRST so the whole subtree allocates and
            # starts receiving before any payload bytes arrive
            for c in children:
                self._send_internal(got, c, _TAG_COLL)
            out = _codec.RECV_POOL.empty(got.shape, np.dtype(got.dtype_str))
            flat = out.reshape(-1)
            off = 0
            for _ in range(got.nseg):
                seg = np.asarray(self._recv_internal(parent, _TAG_COLL))
                n = seg.size
                flat[off:off + n] = seg.reshape(-1)
                if children:
                    view = self._coll_payload(flat[off:off + n])
                    for c in children:
                        self._send_internal(view, c, _TAG_COLL)
                off += n
            return out
        for c in children:
            self._send_internal(got, c, _TAG_COLL)
        return got

    @_traced_coll
    def reduce(self, obj: Any, op: _ops.ReduceOp = _ops.SUM, root: int = 0,
               algorithm: str = "auto") -> Any:
        """MPI_Reduce.  ``algorithm``: ``"tree"`` (binomial tree with
        in-place folds); ``"sm"`` (shm transports: the collective arena
        — ranks publish their payloads, the root folds them in place);
        ``"auto"`` tries the arena at eager sizes, the tree otherwise;
        ``"fused"`` aliases the tree on process backends."""
        _mpit.count(collectives=1)
        self._coll_name = "reduce"
        algorithm = _resolve_algorithm(
            "reduce", algorithm, ("auto", "tree") + _coll_sm.gate(self),
            {"fused": "tree"})
        self._world(root)  # validate
        arr, scalar = _as_array(obj)
        self._verify_coll("reduce", root=root, op=op, payload=arr,
                          algorithm=algorithm)
        if algorithm in ("auto", "sm") and self.size > 1:
            got = _coll_sm.reduce(self, arr, op, root)
            if got is not _coll_sm.FALLBACK:
                (out,) = got
                return (_unwrap(np.asarray(out), scalar)
                        if self._rank == root else None)
        _note_alg("tree")
        acc = arr.copy()
        for pairs in schedules.binomial_reduce_rounds(self.size, root):
            for s, d in pairs:
                if self._rank == s:
                    self._send_internal(self._coll_payload(acc), d, _TAG_COLL)
                elif self._rank == d:
                    # in place: no fresh array per fold (and a send of acc
                    # can only happen in a LATER round, after this fold)
                    op.combine_into(acc, self._recv_internal(s, _TAG_COLL))
        return _unwrap(acc, scalar) if self._rank == root else None

    @_traced_coll
    def allreduce(self, obj: Any, op: _ops.ReduceOp = _ops.SUM,
                  algorithm: str = "auto",
                  compress_key: Any = None) -> Any:
        """MPI_Allreduce.  ``algorithm``: ``"ring"`` (bandwidth-optimal
        reduce-scatter ring + allgather ring), ``"recursive_halving"``
        (latency-optimal, power-of-two groups only), ``"rabenseifner"``
        (block-ring reduce_scatter + ring allgather composition [S:
        Thakur et al.], any group size), ``"reduce_bcast"`` (naive
        reference), ``"sm"`` (shm transports only: the shared-memory
        collective arena, mpi_tpu/coll_sm.py), or ``"auto"`` — the
        arena first on shm transports, else halving below the measured
        _RING_CROSSOVER_BYTES on pow2 groups, rabenseifner at or above
        _RABENSEIFNER_CROSSOVER_BYTES, ring in between.  ``"fused"``
        (the TPU tier) aliases to ``"auto"`` on process backends.

        ``"compressed"`` / ``"compressed:bf16"`` / ``"compressed:int8"``
        / ``"compressed:topk"`` (mpi_tpu/compress.py) split the WIRE
        dtype from the FOLD dtype: bytes cross as bf16 / scaled-int8 /
        sparse (indices, values) top-k pairs while accumulation stays
        f32 (f64 payloads f64); the plain spelling follows the
        ``compress_wire_dtype`` cvar.  Ineligible payloads (non-float
        dtype, unsupported op) decline group-coherently to ``"auto"``
        (``compress_fallbacks`` pvar); the verifier signature carries
        the RESOLVED wire dtype so mixed groups raise
        CollectiveMismatchError instead of desynchronizing.

        ``compress_key`` (``compressed:topk`` only, process backends):
        caller-supplied TENSOR IDENTITY for the error-feedback residual
        slot.  Residuals default to keying by payload geometry
        (shape, dtype, op), so a program alternating two distinct
        same-geometry tensors through top-k cross-contaminates their
        residuals; passing a distinct ``compress_key`` per logical
        tensor (e.g. the parameter name) gives each its own slot.  Must
        agree across the group like every compression knob."""
        _mpit.count(collectives=1)
        self._coll_name = "allreduce"
        arr, scalar = _as_array(obj)
        algorithm = _resolve_algorithm(
            "allreduce", algorithm,
            ("auto", "ring", "recursive_halving", "rabenseifner",
             "reduce_bcast") + _compress.ALLREDUCE_NAMES
            + _coll_sm.gate(self),
            {"fused": "auto"})  # no fused path on sockets; best schedule
        wire = vcounts = None
        if _compress.is_compressed(algorithm):
            # resolve BEFORE the signature exchange: the ring must carry
            # "compressed:bf16" (and top-k's resolved k), never the
            # cvar-dependent "compressed" alias (ISSUE 8 satellite)
            wire, algorithm, vcounts = _compress.resolve(
                self, "allreduce", arr, op, algorithm)
            # the trace span follows the signature rule: resolved wire
            # spelling, never the "compressed" alias
            _note_alg(algorithm)
        self._verify_coll("allreduce", op=op, payload=arr,
                          algorithm=algorithm, counts=vcounts)
        if wire is not None:
            if self.size == 1:
                return _unwrap(arr.copy(), scalar)
            if wire is _compress.TOPK:
                return _unwrap(_compress.topk_allreduce(
                    self, arr, op, compress_key=compress_key), scalar)
            # shm transports: the arena's compressed eager path first
            # (encoded slot writes, fold-dtype folds) so compressed
            # requests route exactly like auto's arena tier
            got = _coll_sm.allreduce_wire(self, arr, op, wire)
            if got is not _coll_sm.FALLBACK:
                return _unwrap(np.asarray(got), scalar)
            fold = arr.astype(_compress.fold_dtype(arr.dtype), copy=False)
            out = self._allreduce_ring(fold, op, wire=wire)
            return _unwrap(out.astype(arr.dtype, copy=False), scalar)
        if algorithm == "auto" and self.size > 1:
            # Tuned dispatch (mpi_tpu/tuning): a measured table row for
            # (transport, P, allreduce, payload band) overrides the
            # seed policy below — including routing AWAY from the
            # arena-first tier ("ring" at >=1MB where the sweep showed
            # the wire ring beating the chunked arena fold) or INTO it
            # ("sm").  Payload geometry is congruent across ranks (the
            # reduction contract), so the band — like the table itself,
            # which must be identical group-wide — keys the same row
            # everywhere.  No matching row: exactly the seed constants.
            pick = _tuning.pick(
                self, "allreduce", arr.nbytes,
                ("ring", "rabenseifner", "reduce_bcast")
                + (("recursive_halving",)
                   if schedules.is_pow2(self.size) else ())
                + _coll_sm.gate(self))
            if pick is not None:
                algorithm = pick
        if algorithm in ("auto", "sm") and self.size > 1:
            # shm transports: the collective arena first — flat slot
            # folds at eager sizes, in-place chunk folds above
            # (mpi_tpu/coll_sm.py); on decline the wire auto policy
            # below picks the best classic schedule
            got = _coll_sm.allreduce(self, arr, op)
            if got is not _coll_sm.FALLBACK:
                return _unwrap(np.asarray(got), scalar)
            algorithm = "auto"
        if algorithm == "auto":
            algorithm = seed_allreduce_algorithm(arr.nbytes, self.size)
        _note_alg(algorithm)
        if self.size == 1:
            return _unwrap(arr.copy(), scalar)
        if algorithm == "ring":
            out = self._allreduce_ring(arr, op)
        elif algorithm == "recursive_halving":
            out = self._allreduce_halving(arr, op)
        elif algorithm == "rabenseifner":
            out = self._allreduce_rabenseifner(arr, op)
        else:  # reduce_bcast
            out = self.bcast(self.reduce(arr, op, root=0), root=0)
        return _unwrap(np.asarray(out), scalar)

    # -- segmented collective engine (ISSUE 1 tentpole) --------------------
    #
    # Every bandwidth-bound collective below operates on ONE contiguous
    # working buffer: chunk boundaries come from the shared pure tables in
    # schedules.py (chunk_offsets / segment_spans), payloads are VIEWS of
    # the buffer (contiguous, so they ride the codec raw frames with zero
    # host-side staging), accumulation is in-place (op.combine_into), and
    # each exchange step is pipelined — segments stream while earlier
    # segments fold.  The seed engine's per-step costs this removes:
    # a list of chunk copies, a fresh array per combine, a full-buffer
    # np.concatenate at the end, and (for recursive halving) a PICKLE of
    # the chunk list every round.

    def _coll_payload(self, view: np.ndarray) -> np.ndarray:
        """Aliasing transports (local copy_payloads=False) deliver by
        reference, and the engine mutates its working buffer in place —
        hand them a snapshot instead of a live view."""
        return view.copy() if self._t.aliases_payloads else view

    def _seg_elems(self, itemsize: int) -> int:
        """Pipeline segment size in ELEMENTS for this communicator's
        transport: the collective_segment_bytes cvar when set (nonzero),
        else the transport's own coll_segment_hint."""
        nbytes = _SEGMENT_BYTES or getattr(
            self._t, "coll_segment_hint", Transport.coll_segment_hint)
        return max(1, nbytes // max(1, itemsize))

    @staticmethod
    def _count_recv_store(dests) -> None:
        """Price a fold-site store whose destination WAS registered for
        rendezvous steering (mpi_tpu/recvpool.py) but whose payload
        arrived through the pool path anyway.  It ticks
        ``payload_copies`` only while steering is administratively off
        (recv_steering cvar): whether an individual frame steers is a
        reader-vs-poster thread race, and the zero-copy invariants the
        suite pins (tests/test_segmented_collectives2.py) must stay
        deterministic under the default mode.  With steering ON, the
        hit/miss split is reported by ``recv_pool_rendezvous`` /
        ``recv_bytes_steered`` and the recvpool fallback trace events
        instead — that asymmetry is what the pre/post OSU artifacts
        (benchmarks/results/recvpool_*.json) quantify."""
        if dests is not None and not _recvpool._STEERING:
            _mpit.count(copies=1)

    def _seg_exchange(self, work: np.ndarray, sbounds: Tuple[int, int],
                      rbounds: Tuple[int, int], dest: int, src: int,
                      op: Optional[_ops.ReduceOp] = None,
                      wire=None) -> None:
        """One pipelined exchange step: send ``work[sbounds]`` to ``dest``
        while receiving the same global element range ``rbounds`` from
        ``src``, folding (``op``) or copying (``op=None``) each segment
        into the working buffer as soon as it lands.

        Receives are posted as irecvs up front (they complete in posted
        order, matching the sender's FIFO channel), and sends are
        credit-limited to _SEG_WINDOW segments ahead of the receive
        pointer: enough in flight to keep the wire busy, little enough
        that a symmetric exchange can never fill the shm ring with
        nobody draining.  Both sides compute spans from the same global
        tables, so message boundaries agree with zero metadata traffic.

        ``wire`` (mpi_tpu/compress.py WireFormat) is the wire-dtype !=
        fold-dtype seam: each outgoing segment is ENCODED into a
        wire-tagged raw frame at send time and DECODED at its fold/copy
        site, so compression composes with the segment pipeline and the
        progress engine's credit callbacks unchanged — spans stay in
        fold-dtype elements (the encoded frames are self-describing)."""
        seg = self._seg_elems(work.itemsize)
        sspans = schedules.segment_spans(sbounds[0], sbounds[1], seg)
        rspans = schedules.segment_spans(rbounds[0], rbounds[1], seg)
        decode = None if wire is None else wire.decode
        # Rendezvous steering (mpi_tpu/recvpool.py): pure-copy spans
        # (op None, fold dtype on the wire) can land DIRECTLY in the
        # working buffer — register each posted receive's destination
        # view so the transport's reader steers the body bytes there
        # instead of staging them in a pool buffer.  Fold spans
        # (op != None) are never registered: an early arrival would
        # clobber the accumulator before combine_into reads it.  The
        # fold site recognises a steered segment by IDENTITY (the
        # delivered payload IS the registered view) and skips the
        # store — and its CoW touch, which the reader already did.
        dests = None
        if op is None and wire is None and self._recv_reg is not None:
            dests = [work[lo:hi] for lo, hi in rspans]
        eng = self._progress
        if eng is not None and len(sspans) > _SEG_WINDOW:
            # progress-engine mode: the sends beyond the initial credit
            # are posted by whoever COMPLETES each receive — normally
            # the engine thread, via _on_complete — so the window
            # advances while the caller is folding (or not here at
            # all); the caller only folds and, at the end, drains the
            # tail the callbacks didn't cover.  Requests are posted and
            # their callbacks attached UNDER the completion lock: the
            # engine may otherwise complete an early receive in the gap
            # between posting and attaching, silently losing that
            # receive's send credit — a stall both sides of a symmetric
            # exchange would share.
            sender = _SegSender(self, work, sspans, dest, wire)
            with eng.cv:
                reqs = []
                for i in range(len(rspans)):
                    req = self._irecv_internal(src, _TAG_COLL)
                    if dests is not None:
                        self._recv_reg.attach(req._steer_token, dests[i])
                    req._on_complete = sender.advance
                    reqs.append(req)
        else:
            sender = None
            reqs = []
            for i in range(len(rspans)):
                req = self._irecv_internal(src, _TAG_COLL)
                if dests is not None:
                    self._recv_reg.attach(req._steer_token, dests[i])
                reqs.append(req)
        try:
            if sender is not None:
                sender.post(_SEG_WINDOW)
                for seg_i, ((lo, hi), req) in enumerate(zip(rspans, reqs)):
                    sender.check()  # engine-side send failures surface
                    try:
                        got = req.wait()
                    except ProcFailedError as e:
                        if e.segment is None:  # name the stalled segment
                            e.segment = seg_i
                        raise
                    view = work[lo:hi] if dests is None else dests[seg_i]
                    if op is None:
                        if got is not view:
                            # ownership CoW (bufpool.py): the working
                            # buffer's spans were just SENT — retained
                            # frames must snapshot before this overwrite
                            _bufpool.touch(view)
                            view[...] = (got if decode is None
                                         else decode(got))
                            self._count_recv_store(dests)
                        # else: steered in place by the reader, which
                        # did the touch before scribbling — no store
                    else:
                        op.combine_into(view, got, decode)
                sender.drain()
                return

            def snd_payload(lo_: int, hi_: int):
                view_ = work[lo_:hi_]
                return (wire.encode(view_) if wire is not None
                        else self._coll_payload(view_))

            si = 0
            while si < min(len(sspans), _SEG_WINDOW):
                lo, hi = sspans[si]
                self._send_internal(snd_payload(lo, hi), dest, _TAG_COLL)
                si += 1
            for seg_i, ((lo, hi), req) in enumerate(zip(rspans, reqs)):
                try:
                    got = req.wait()
                except ProcFailedError as e:
                    if e.segment is None:  # name the stalled segment
                        e.segment = seg_i
                    raise
                view = work[lo:hi] if dests is None else dests[seg_i]
                if op is None:
                    if got is not view:  # see the engine path above
                        _bufpool.touch(view)
                        view[...] = got if decode is None else decode(got)
                        self._count_recv_store(dests)
                else:
                    op.combine_into(view, got, decode)
                if si < len(sspans):
                    slo, shi = sspans[si]
                    self._send_internal(snd_payload(slo, shi), dest,
                                        _TAG_COLL)
                    si += 1
            while si < len(sspans):  # recv range empty/shorter: drain tail
                slo, shi = sspans[si]
                self._send_internal(snd_payload(slo, shi), dest, _TAG_COLL)
                si += 1
        except BaseException:
            # Un-post OUR pending irecvs: a failed exchange (recv timeout,
            # transport error) must not leave stale queue heads on the
            # internal (src, _TAG_COLL) channel — they would silently
            # absorb the first segments of any later collective with the
            # same peer (the blocking seed path left no such residue).
            # In-flight peer bytes may still arrive; un-posting at least
            # fails the NEXT operation loudly instead of misfolding.
            _unpost(reqs)
            raise

    def _allreduce_ring(self, arr: np.ndarray, op: _ops.ReduceOp,
                        wire=None) -> np.ndarray:
        # Reduce-scatter ring + allgather ring, 2(P-1) steps (SURVEY.md
        # §3.3), segmented and in place: one flat working copy of the
        # input, every wire payload a contiguous view of it.  ``wire``
        # (compress.py) encodes BOTH phases — partial sums and the final
        # reduced chunks alike cross in the wire dtype, which is what
        # halves the bytes; the fold stays in work's (fold) dtype, and
        # quantization error therefore compounds ~linearly in P (bound
        # measured in tests/test_compress.py).
        p, r = self.size, self._rank
        shape = arr.shape
        work = arr.flatten()  # flatten always copies — our mutable buffer
        offs = schedules.chunk_offsets(work.size, p)
        right, left = (r + 1) % p, (r - 1) % p
        for step in range(p - 1):
            si = schedules.ring_rs_send_chunk(r, step, p)
            ri = schedules.ring_rs_recv_chunk(r, step, p)
            self._seg_exchange(work, (offs[si], offs[si + 1]),
                               (offs[ri], offs[ri + 1]), right, left, op,
                               wire=wire)
        for step in range(p - 1):
            si = schedules.ring_ag_send_chunk(r, step, p)
            ri = schedules.ring_ag_recv_chunk(r, step, p)
            self._seg_exchange(work, (offs[si], offs[si + 1]),
                               (offs[ri], offs[ri + 1]), right, left,
                               wire=wire)
        return work.reshape(shape)

    def _allreduce_halving(self, arr: np.ndarray, op: _ops.ReduceOp) -> np.ndarray:
        # Recursive-halving reduce-scatter + recursive-doubling allgather
        # (power-of-two only; latency-optimal [S]; BASELINE.json:10).
        # Chunks [a, b) of the flat buffer are the contiguous range
        # [offs[a], offs[b]), so each round's half ships as raw frames —
        # the seed path pickled a Python list of chunk arrays here,
        # copying every byte through the pickler on both ends.
        p, r = self.size, self._rank
        shape = arr.shape
        work = arr.flatten()
        offs = schedules.chunk_offsets(work.size, p)
        masks = schedules.halving_masks(p)
        lo, hi = 0, p
        for mask in masks:
            partner = r ^ mask
            mid = (lo + hi) // 2
            if r & mask:
                mine, theirs = (mid, hi), (lo, mid)
            else:
                mine, theirs = (lo, mid), (mid, hi)
            self._seg_exchange(work, (offs[theirs[0]], offs[theirs[1]]),
                               (offs[mine[0]], offs[mine[1]]),
                               partner, partner, op)
            lo, hi = mine
        # now [lo, hi) == [r, r+1): rank r holds reduced chunk r
        for mask in reversed(masks):
            partner = r ^ mask
            w = hi - lo
            rb = (lo - w, lo) if r & mask else (hi, hi + w)
            self._seg_exchange(work, (offs[lo], offs[hi]),
                               (offs[rb[0]], offs[rb[1]]), partner, partner)
            lo, hi = (rb[0], hi) if r & mask else (lo, rb[1])
        return work.reshape(shape)

    def _allreduce_rabenseifner(self, arr: np.ndarray,
                                op: _ops.ReduceOp) -> np.ndarray:
        # The Rabenseifner composition [S: Thakur et al.]: block-ring
        # reduce_scatter (rank r ends owning fully reduced chunk r, the
        # MPI_Reduce_scatter_block schedule) + ring allgather of the
        # reduced chunks — the same 2(P-1) segmented exchange steps and
        # 2(P-1)/P·N volume as _allreduce_ring, but phase one IS the
        # reduce_scatter collective's schedule, so allreduce and
        # reduce_scatter share one measured data plane.  Works for any
        # group size (recursive halving needs pow2).
        p, r = self.size, self._rank
        shape = arr.shape
        work = arr.flatten()  # flatten always copies — our mutable buffer
        offs = schedules.chunk_offsets(work.size, p)
        right, left = (r + 1) % p, (r - 1) % p
        for step in range(p - 1):
            si = schedules.ring_rs_block_send_chunk(r, step, p)
            ri = schedules.ring_rs_block_recv_chunk(r, step, p)
            self._seg_exchange(work, (offs[si], offs[si + 1]),
                               (offs[ri], offs[ri + 1]), right, left, op)
        for step in range(p - 1):
            si = schedules.ring_ag_block_send_chunk(r, step, p)
            ri = schedules.ring_ag_block_recv_chunk(r, step, p)
            self._seg_exchange(work, (offs[si], offs[si + 1]),
                               (offs[ri], offs[ri + 1]), right, left)
        return work.reshape(shape)

    @_traced_coll
    def allgather(self, obj: Any, algorithm: str = "auto") -> List[Any]:
        """MPI_Allgather.  ``algorithm``: ``"ring"`` (rotating row views
        of one [P, ...] buffer, raw frames), ``"doubling"`` (recursive
        doubling, log P rounds, pow2 groups only), ``"sm"`` (shm
        transports: the collective arena — every rank reads every slot
        in place), or ``"auto"`` — the arena first on shm transports,
        else doubling on pow2 groups, ring otherwise.  ``"fused"`` (the
        TPU tier) aliases to ``"auto"`` on process backends."""
        _mpit.count(collectives=1)
        self._coll_name = "allgather"
        p, r = self.size, self._rank
        algorithm = _resolve_algorithm(
            "allgather", algorithm,
            ("auto", "ring", "doubling") + _coll_sm.gate(self),
            {"fused": "auto"})  # no fused path on sockets
        self._verify_coll("allgather", algorithm=algorithm)
        if algorithm in ("auto", "sm") and p > 1:
            # Transport capability is group-uniform, so this keeps the
            # "pick may depend only on the group shape" rule: payload
            # raggedness (or non-array payloads) is resolved INSIDE the
            # arena, where every rank sees the same metas and falls
            # back together.
            got = _coll_sm.allgather(self, obj)
            if got is not _coll_sm.FALLBACK:
                (got_items,) = got
                return _maybe_stack(obj, got_items)
            algorithm = "auto"
        if algorithm == "auto":
            # The pick may depend ONLY on the group shape, never on the
            # rank-local payload: ragged allgather is supported, so a
            # size- or type-conditioned pick could choose wire-incompatible
            # algorithms on different ranks.  Doubling is latency-optimal
            # (log P rounds) on pow2 groups; bandwidth-bound array
            # workloads should request "ring" explicitly for the
            # raw-frame row buffer.
            algorithm = _note_alg("doubling" if schedules.is_pow2(p)
                                  else "ring")
        items: List[Any] = [None] * p
        items[r] = obj
        if p == 1:
            return items
        if algorithm == "ring":
            right, left = (r + 1) % p, (r - 1) % p
            # only the ring branch uses the compacted form — probing here
            # keeps doubling payloads from paying an ascontiguousarray
            # copy (and a payload_copies count) that is never sent
            arr = _codec.as_raw_array(obj)
            if arr is not None:
                # Contiguous row-buffer fast path: rows are views of ONE
                # [p, ...] working buffer — rotated payloads ship raw with
                # no per-step staging and the final stack costs zero
                # copies.  The wire protocol is IDENTICAL to the generic
                # path (one self-describing frame per step), so ranks
                # with mismatched payloads (ragged allgather) interoperate:
                # a row that doesn't fit the local geometry just falls
                # back to object storage for that slot.
                work = np.empty((p,) + arr.shape, arr.dtype)
                work[r] = arr
                ragged: dict = {}

                def slot(i: int) -> Any:
                    # membership, not .get: None is a legal ragged payload
                    if i in ragged:
                        return ragged[i]
                    return self._coll_payload(work[i])

                for step in range(p - 1):
                    si = schedules.ring_ag_send_chunk(r, step + 1, p)
                    ri = schedules.ring_ag_recv_chunk(r, step + 1, p)
                    self._send_internal(slot(si), right, _TAG_COLL)
                    got = self._recv_internal(left, _TAG_COLL)
                    # exact type, mirroring codec.raw_eligible: an ndarray
                    # SUBCLASS row (MaskedArray, ...) must stay a ragged
                    # object, not be flattened into the plain buffer with
                    # its subclass state stripped
                    if (type(got) is np.ndarray
                            and got.shape == arr.shape
                            and got.dtype == arr.dtype):
                        work[ri] = got
                    else:
                        ragged[ri] = got
                if not ragged:
                    return work
                items = [ragged[i] if i in ragged else work[i]
                         for i in range(p)]
                items[r] = obj
                return _maybe_stack(obj, items)
            for step in range(p - 1):
                si = schedules.ring_ag_send_chunk(r, step + 1, p)
                ri = schedules.ring_ag_recv_chunk(r, step + 1, p)
                items[ri] = self._sendrecv_internal(items[si], right, left, _TAG_COLL)
        elif algorithm == "doubling":
            # Each round exchanges the whole owned batch.  When every
            # owned value is raw-eligible the batch ships as a keyed LIST
            # — [int64 rank-index array, *values] — which the codec sends
            # as ONE multi-segment raw frame (zero pickled array bytes);
            # otherwise the seed's dict rides pickle.  The two forms are
            # distinguished per message by type, so each sender decides
            # from its own batch alone and mixed groups interoperate.
            owned = {r: obj}
            for mask in schedules.doubling_masks(p):
                partner = r ^ mask
                ks = sorted(owned)
                vals = [owned[k] for k in ks]
                if all(_codec.raw_eligible(v) for v in vals):
                    # values are never mutated after the send, so no
                    # aliasing snapshot is needed (matches the seed dict)
                    batch: Any = [np.asarray(ks, np.int64)] + vals
                else:
                    batch = owned
                recvd = self._sendrecv_internal(batch, partner, partner,
                                                _TAG_COLL)
                if isinstance(recvd, list):
                    owned.update(zip((int(k) for k in recvd[0]), recvd[1:]))
                else:
                    owned.update(recvd)
            for i, v in owned.items():
                items[i] = v
        else:
            raise ValueError(f"unknown allgather algorithm {algorithm!r}")
        return _maybe_stack(obj, items)

    @_traced_coll
    def alltoall(self, objs: Sequence[Any], algorithm: str = "auto") -> List[Any]:
        """MPI_Alltoall.  ``algorithm``: ``"pairwise"`` (windowed
        nonblocking pairwise exchange, P-1 rounds — BASELINE.json:9);
        ``"sm"`` (shm transports: the collective arena — write all P
        blocks, one flag round, read your column in place,
        mpi_tpu/coll_sm.py); ``"auto"`` tries the arena when the
        transport has one, pairwise otherwise; ``"fused"`` (the TPU
        tier) aliases pairwise.

        All P-1 receives are posted up front (each source is a distinct
        FIFO channel, so posted order is arrival order per peer) and the
        P-1 sends run at most _SEG_WINDOW rounds ahead of the completed
        receives: every payload is already in flight — as a raw (or
        multi-segment raw) frame for array payloads — while earlier
        rounds complete, instead of the seed's P-1 serialized blocking
        sendrecv rounds, and the window keeps a symmetric exchange from
        parking more than window payloads in the shm ring with nobody
        draining."""
        _mpit.count(collectives=1)
        self._coll_name = "alltoall"
        p, r = self.size, self._rank
        algorithm = _resolve_algorithm(
            "alltoall", algorithm, ("auto", "pairwise") + _coll_sm.gate(self),
            {"fused": "pairwise"})
        if len(objs) != p:
            raise ValueError(f"alltoall needs one payload per rank ({p}), got {len(objs)}")
        self._verify_coll("alltoall", algorithm=algorithm)
        tuned_wire = False
        if algorithm == "auto" and p > 1:
            # Tuned dispatch.  Unlike the reductions, alltoall payload
            # sizes may be RANK-VARYING (ragged/object payloads), so a
            # "pairwise" row must never skip the arena's group
            # negotiation outright — instead this rank enters the arena
            # with no payload, which lands the WHOLE group on pairwise
            # together even when peers' bands disagree (the in-arena
            # meta round is the coherence mechanism).  Unsizable
            # payloads skip the consult entirely.
            try:
                nb = self._blocks_nbytes(objs)
            except (ValueError, TypeError):
                nb = None
            if nb is not None:
                pick = _tuning.pick(self, "alltoall", nb,
                                    ("pairwise",) + _coll_sm.gate(self))
                tuned_wire = pick == "pairwise"
        if algorithm in ("auto", "sm") and p > 1:
            # Arena path: write the whole [P·n] stack once, read your
            # column in place.  Same eligibility discipline as the
            # reduce_scatter arena gate: the stacked view is built only
            # when the payload fits a slot (the stacking copy must not
            # be paid on the decline path), and the in-arena meta
            # negotiation lands every rank on pairwise together when
            # any rank's blocks are ragged/objects/oversized.
            arena = _coll_sm.arena_for(self)
            arr_sm = None
            if arena is not None and not tuned_wire:
                try:
                    # alltoall payloads may be ANY picklables — a ragged
                    # nested list makes even the size probe raise, which
                    # just means "cannot ride the arena"
                    if self._blocks_nbytes(objs) <= arena.capacity:
                        arr_sm = self._blocks_as_array(objs)
                except (ValueError, TypeError):
                    arr_sm = None
            got = _coll_sm.alltoall(self, arr_sm)
            if got is not _coll_sm.FALLBACK:
                (items,) = got
                return _maybe_stack(objs, items)
        _note_alg("pairwise")
        result: List[Any] = [None] * p
        result[r] = objs[r]
        rounds = schedules.alltoall_rounds(p)
        reqs = [self._irecv_internal((r - k) % p, _TAG_COLL) for k in rounds]
        done = 0
        try:
            for i, k in enumerate(rounds):
                dst = (r + k) % p
                self._send_internal(objs[dst], dst, _TAG_COLL)
                if i - done >= _SEG_WINDOW:
                    result[(r - rounds[done]) % p] = reqs[done].wait()
                    done += 1
            while done < len(reqs):
                result[(r - rounds[done]) % p] = reqs[done].wait()
                done += 1
        except BaseException:
            _unpost(reqs)
            raise
        return _maybe_stack(objs, result)

    @_traced_coll
    def barrier(self, algorithm: str = "auto") -> None:
        """MPI_Barrier.  ``algorithm``: ``"dissemination"`` (ceil(log2 P)
        message rounds [S]), ``"sm"`` (shm transports: one flag round in
        the collective arena — no messages at all), or ``"auto"`` — the
        arena on shm transports, dissemination otherwise."""
        _mpit.count(collectives=1)
        self._coll_name = "barrier"
        algorithm = _resolve_algorithm(
            "barrier", algorithm,
            ("auto", "dissemination") + _coll_sm.gate(self),
            {"fused": "dissemination"})
        self._verify_coll("barrier", algorithm=algorithm)
        p, r = self.size, self._rank
        if algorithm in ("auto", "sm") and p > 1:
            if _coll_sm.barrier(self) is not _coll_sm.FALLBACK:
                return
        _note_alg("dissemination")
        for off in schedules.dissemination_offsets(p):
            self._send_internal(None, (r + off) % p, _TAG_BARRIER)
            self._recv_internal((r - off) % p, _TAG_BARRIER)

    @_traced_coll
    def scan(self, obj: Any, op: _ops.ReduceOp = _ops.SUM,
             algorithm: str = "auto") -> Any:
        """MPI_Scan [S].  ``algorithm``: ``"doubling"`` (Hillis-Steele
        distance-doubling partial prefixes, log2(P) rounds); ``"sm"``
        (shm transports: the collective arena — write own payload, one
        flag round, rank r folds slots 0..r in place); ``"auto"`` tries
        the arena when the transport has one; ``"fused"`` aliases
        doubling."""
        _mpit.count(collectives=1)
        self._coll_name = "scan"
        # Hillis-Steele inclusive scan: log2(P) rounds of distance-doubling
        # partial prefixes [S].  The partial-prefix payload is always a
        # contiguous ndarray, so every round ships it as a raw frame —
        # never pickled (asserted in tests/test_segmented_collectives2.py).
        arr, scalar = _as_array(obj)
        algorithm = _resolve_algorithm(
            "scan", algorithm, ("auto", "doubling") + _coll_sm.gate(self),
            {"fused": "doubling"})
        self._verify_coll("scan", op=op, payload=arr, algorithm=algorithm)
        if algorithm in ("auto", "sm") and self.size > 1:
            # in-arena negotiation: object payloads / oversized /
            # geometry drift land every rank back on doubling together
            got = _coll_sm.scan(self, arr, op)
            if got is not _coll_sm.FALLBACK:
                (out,) = got
                return _unwrap(out, scalar)
        _note_alg("doubling")
        acc = arr.copy()
        p, r = self.size, self._rank
        d = 1
        while d < p:
            if r + d < p:
                self._send_internal(acc, r + d, _TAG_COLL)
            if r - d >= 0:
                recvd = self._recv_internal(r - d, _TAG_COLL)
                # received prefix goes LEFT.  On serializing transports
                # the received buffer is freshly allocated and private,
                # so the fold can run in place into it — one allocation
                # per round saved; aliasing transports (local
                # copy_payloads=False) hand us a reference to the
                # SENDER's accumulator, which must never be mutated.
                if (not self._t.aliases_payloads
                        and type(recvd) is np.ndarray
                        and recvd.shape == acc.shape
                        and recvd.dtype == acc.dtype):
                    acc = op.combine_into(recvd, acc)
                else:
                    acc = op.combine(recvd, acc)
            d *= 2
        return _unwrap(acc, scalar)

    @staticmethod
    def _blocks_nbytes(blocks: Any) -> int:
        """Total payload size of a reduce_scatter input, copy-free (for
        the segmentation gate): homogeneous blocks are assumed — the
        heterogeneous case never reaches the segmented path anyway."""
        if isinstance(blocks, np.ndarray):
            return int(blocks.nbytes)
        return int(np.asarray(blocks[0]).nbytes) * len(blocks)

    def _blocks_as_array(self, blocks: Any) -> Optional[np.ndarray]:
        """The [P, ...] array view of a reduce_scatter payload when every
        block agrees in dtype+shape and the dtype is raw-frame friendly —
        the eligibility test of the segmented ring.  None → the generic
        per-chunk path (heterogeneous block shapes, object dtypes)."""
        if isinstance(blocks, np.ndarray):
            arr = np.asarray(blocks)  # strips ndarray subclasses' state,
        else:                         # exactly like the per-chunk asarray
            first = np.asarray(blocks[0])
            for b in blocks[1:]:
                a = np.asarray(b)
                if a.dtype != first.dtype or a.shape != first.shape:
                    return None
            arr = np.asarray(blocks)
        if arr.dtype.hasobject or arr.dtype.kind == "V":
            return None
        return arr

    @_traced_coll
    def reduce_scatter(self, blocks: Any, op: _ops.ReduceOp = _ops.SUM,
                       algorithm: str = "auto") -> Any:
        """MPI_Reduce_scatter_block [S]: ``blocks`` holds one block per
        rank (leading dimension == size); rank r gets the reduction of
        everyone's block r.  ``algorithm``: ``"ring"`` (P-1 steps —
        segmented on one contiguous working buffer when the blocks are
        homogeneous arrays, generic per-chunk exchange otherwise);
        ``"sm"`` (shm transports: write-own-input → barrier → fold block
        ``rank`` reading peers in place from the collective arena);
        ``"auto"`` — the arena first on shm transports, the ring
        otherwise; ``"fused"`` (the TPU tier) aliases the ring.

        The segmented path is the same engine as the ring allreduce:
        every wire payload is a contiguous view of one flat [P·n]
        buffer, folds are in-place (op.combine_into), and each of the
        P-1 exchange steps pipelines via schedules.segment_spans — the
        seed path's per-step block copy, combine allocation, and
        blocking sendrecv serialization are all gone.

        ``"compressed"`` / ``"compressed:bf16"`` / ``"compressed:int8"``
        run the same block ring with the wire-dtype != fold-dtype seam
        (mpi_tpu/compress.py): segments cross encoded, folds stay f32
        (f64 payloads f64), the result block is cast back to the
        payload dtype.  No ``"compressed:topk"`` here — sparsified
        entries have no per-destination blockwise home."""
        _mpit.count(collectives=1)
        self._coll_name = "reduce_scatter"
        p, r = self.size, self._rank
        algorithm = _resolve_algorithm(
            "reduce_scatter", algorithm,
            ("auto", "ring") + _compress.REDUCE_SCATTER_NAMES
            + _coll_sm.gate(self),
            {"fused": "ring"})
        if len(blocks) != p:
            raise ValueError(
                f"reduce_scatter needs one block per rank ({p}), got {len(blocks)}")
        wire = None
        if _compress.is_compressed(algorithm):
            # resolved wire dtype into the signature (never the cvar-
            # dependent "compressed" alias) — see allreduce
            wire, algorithm, _ = _compress.resolve(
                self, "reduce_scatter", np.asarray(blocks[0]), op,
                algorithm)
        # geometry class of block 0 (cheap: no stacking copy) + the block
        # count — mismatched reduce geometry across ranks is flagged
        # before the ring/arena can misfold or truncate
        self._verify_coll("reduce_scatter", op=op,
                          payload=np.asarray(blocks[0]),
                          algorithm=algorithm, counts=(p,))
        if algorithm == "auto" and p > 1:
            # Tuned dispatch: the measured arena-vs-wire-ring axis
            # (host-engine residual (c)) — a "ring" row skips the
            # arena-first tier, an "sm" row keeps it.  reduce_scatter
            # blocks are geometry-congruent across ranks, so the band
            # keys the same row everywhere.
            pick = _tuning.pick(self, "reduce_scatter",
                                self._blocks_nbytes(blocks),
                                ("ring",) + _coll_sm.gate(self))
            if pick is not None:
                algorithm = pick
        if algorithm in ("auto", "sm") and p > 1:
            # Arena path: write the whole [P·n] input once, fold only
            # block ``rank`` reading peers in place.  The stacked-array
            # eligibility view is built only when the payload fits a
            # slot (the stacking copy must not be paid on the decline
            # path); an ineligible rank enters with no payload and the
            # in-arena negotiation lands everyone on the ring together.
            arena = _coll_sm.arena_for(self)
            arr_sm = (self._blocks_as_array(blocks)
                      if arena is not None
                      and self._blocks_nbytes(blocks) <= arena.capacity
                      else None)
            got = _coll_sm.reduce_scatter(self, arr_sm, op)
            if got is not _coll_sm.FALLBACK:
                (out,) = got
                return _unwrap(out, out.ndim == 0)
        # Size-gate BEFORE _blocks_as_array: for list payloads eligibility
        # stacks the blocks into the working buffer, a copy the per-chunk
        # path below would throw away (same discipline as the segmented
        # bcast's eligibility gate).
        nbytes = self._blocks_nbytes(blocks)
        use_seg = (wire is not None or nbytes >= _RS_SEGMENT_MIN_BYTES
                   or 0 < _SEGMENT_BYTES < nbytes)
        arr = self._blocks_as_array(blocks) if use_seg and p > 1 else None
        if wire is not None and arr is None:
            # heterogeneous/object blocks cannot ride the flat working
            # buffer the encoded exchange needs; block geometry is
            # congruent across ranks, so everyone declines together —
            # the wire-path analogue of the arena meta round
            _compress._decline()
            wire = None
        # span algorithm = what actually runs: the resolved compressed
        # spelling on the encoded ring, plain "ring" otherwise
        # (including a compressed request the decline above downgraded)
        _note_alg(algorithm if wire is not None else "ring")
        if arr is not None:
            was_scalar = arr.ndim == 1
            shape = arr.shape[1:]
            out_dtype = arr.dtype
            fdt = (_compress.fold_dtype(arr.dtype) if wire is not None
                   else arr.dtype)
            # list payloads: np.asarray already STACKED the blocks into a
            # fresh contiguous buffer nobody else holds — reshape is the
            # working buffer with zero extra copies; ndarray payloads
            # alias the caller's memory, so flatten's copy is mandatory
            # (a fold-dtype astype is itself the fresh copy)
            if fdt != arr.dtype:
                work = arr.astype(fdt).reshape(-1)
            elif not isinstance(blocks, np.ndarray):
                work = arr.reshape(-1)
            else:
                work = arr.flatten()
            bn = work.size // p
            right, left = (r + 1) % p, (r - 1) % p
            for step in range(p - 1):
                si = schedules.ring_rs_block_send_chunk(r, step, p)
                ri = schedules.ring_rs_block_recv_chunk(r, step, p)
                self._seg_exchange(work, (si * bn, (si + 1) * bn),
                                   (ri * bn, (ri + 1) * bn), right, left, op,
                                   wire=wire)
            # own block copied out so the P·n working buffer is released
            # (the fold-dtype cast back to the payload dtype IS a copy)
            mine = work[r * bn:(r + 1) * bn].reshape(shape)
            mine = (mine.astype(out_dtype) if mine.dtype != out_dtype
                    else mine.copy())
            return _unwrap(mine, was_scalar)
        # Generic path (per-destination block shapes/dtypes differ):
        # only the chunks this rank folds INTO need a private copy — the
        # ring's fold targets are every chunk except (r-1)%p, which is
        # sent in step 0 and never touched again, so it stays a view of
        # the caller's data (_coll_payload snapshots it iff the
        # transport delivers by reference).
        view_only = (r - 1) % p
        chunks = [np.asarray(b) if i == view_only and p > 1
                  else np.asarray(b).copy() for i, b in enumerate(blocks)]
        was_scalar = chunks[0].ndim == 0
        if p == 1:
            return _unwrap(chunks[0], was_scalar)
        right, left = (r + 1) % p, (r - 1) % p
        for step in range(p - 1):
            si = schedules.ring_rs_block_send_chunk(r, step, p)
            ri = schedules.ring_rs_block_recv_chunk(r, step, p)
            payload = self._coll_payload(chunks[si]) if step == 0 \
                else chunks[si]
            recvd = self._sendrecv_internal(payload, right, left, _TAG_COLL)
            mine = chunks[ri]
            # in-place fold only when the received chunk matches ours
            # exactly — cross-rank dtype/shape drift (tolerated by the
            # seed via numpy promotion) keeps the allocating combine,
            # the same guard scan applies (MPI requires congruent
            # payloads, but a silent semantics change is worse)
            if (type(recvd) is np.ndarray and recvd.shape == mine.shape
                    and recvd.dtype == mine.dtype):
                op.combine_into(mine, recvd)
            else:
                chunks[ri] = np.asarray(op.combine(mine, recvd))
        return _unwrap(chunks[r], was_scalar)

    @_traced_coll
    def scatter(self, objs: Optional[Sequence[Any]], root: int = 0) -> Any:
        """MPI_Scatter: rank d receives ``objs[d]`` from ``root``.  The
        root's fan-out is nonblocking — every payload is enqueued on the
        transport (a raw frame for array payloads, never pickled array
        bytes) before any peer's receive completes, so one slow child
        cannot serialize the others."""
        _mpit.count(collectives=1)
        self._coll_name = "scatter"
        self._world(root)  # validate
        self._verify_coll("scatter", root=root)
        if self._rank == root:
            if objs is None or len(objs) != self.size:
                raise ValueError(f"scatter root needs one payload per rank ({self.size})")
            for d in range(self.size):
                if d != root:
                    self._send_internal(objs[d], d, _TAG_COLL)
            return objs[root]
        return self._recv_internal(root, _TAG_COLL)

    @_traced_coll
    def gather(self, obj: Any, root: int = 0) -> Optional[List[Any]]:
        """MPI_Gather: root returns ``[payload_0, ..., payload_{P-1}]``.
        The root posts every receive up front (nonblocking fan-in): each
        source is its own FIFO channel, so arrivals buffer concurrently
        instead of the seed's serialized rank-order recv loop, and array
        payloads ride raw frames end to end."""
        _mpit.count(collectives=1)
        self._coll_name = "gather"
        self._world(root)  # validate
        self._verify_coll("gather", root=root)
        if self._rank == root:
            items: List[Any] = [None] * self.size
            items[root] = obj
            srcs = [s for s in range(self.size) if s != root]
            reqs = [self._irecv_internal(s, _TAG_COLL) for s in srcs]
            try:
                for s, req in zip(srcs, reqs):
                    items[s] = req.wait()
            except BaseException:
                _unpost(reqs)
                raise
            return items
        self._send_internal(obj, root, _TAG_COLL)
        return None

    # -- fault tolerance (ULFM; mpi_tpu/ft.py) -----------------------------

    def _require_ft(self, what: str):
        if self._ft is None:
            raise RuntimeError(
                f"{what}() needs fault tolerance enabled on this "
                f"communicator: mpi_tpu.ft.enable(comm), MPI_TPU_FT=1 "
                f"under the launcher, or run_local(..., "
                f"fault_tolerance=True)")
        return self._ft

    @property
    def revoked(self) -> bool:
        """True once this communicator has been revoked (locally or by a
        delivered remote revocation)."""
        return self._ft is not None and self._ft.revoked

    def revoke(self) -> None:
        """MPIX_Comm_revoke [S: ULFM]: mark this communicator dead
        everywhere.  Best-effort notification to every other rank on the
        reserved control tag; every rank entering or blocked inside a
        p2p/collective call on this communicator raises RevokedError —
        including survivors who were not talking to the failed rank.
        Not collective; callable from exactly one rank."""
        from . import ft as _ftm

        ft = self._require_ft("revoke")
        if not ft.revoked:
            ft.revoked = True
            _mpit.count(revokes=1)
        for peer in range(self.size):
            if peer == self._rank:
                continue
            try:
                self._t.send(self._group[peer], ft.home_ctx,
                             _ftm.TAG_REVOKE, None)
            except (TransportError, ValueError):
                pass  # dead/unreachable peers need no revocation

    def get_failed(self) -> List[int]:
        """MPIX_Comm_failure_get_acked's sibling: the comm ranks this
        process currently believes dead (sorted; empty without FT)."""
        from . import ft as _ftm

        return _ftm.failed_comm_ranks(self)

    def failure_ack(self) -> List[int]:
        """MPIX_Comm_failure_ack [S: ULFM]: acknowledge every currently
        known failure — wildcard (ANY_SOURCE) receives stop raising for
        these ranks, and ``agree`` stops treating them as fatal.
        Returns the acknowledged comm ranks."""
        ft = self._require_ft("failure_ack")
        failed = set(self.get_failed())
        ft.acked |= failed
        # world-level record: the membership layer's re-admission gate
        # (an ousted-but-live incarnation may rejoin only once acked)
        ft.world.ack_world(self._group[r] for r in failed)
        return sorted(ft.acked)

    def failure_get_acked(self) -> List[int]:
        """MPIX_Comm_failure_get_acked [S: ULFM]."""
        return sorted(self._require_ft("failure_get_acked").acked)

    def shrink(self) -> "P2PCommunicator":
        """MPIX_Comm_shrink [S: ULFM]: survivors agree on the failed set
        (fault-tolerant all-reduce over liveness bitmaps — ft._agreement)
        and return a dense sub-communicator of the survivors, ordered by
        old rank, able to run the full collective family.  Valid on a
        revoked communicator (the agreement runs on the raw transport,
        below the revocation check)."""
        from . import ft as _ftm

        ft = self._require_ft("shrink")
        view, _ = _ftm._agreement(self, _ftm.TAG_SHRINK, True)
        if (view >> self._rank) & 1:
            raise ProcFailedError(
                f"rank {self._rank}: suspected dead by the survivors "
                f"during shrink (false suspicion — this rank stalled "
                f"past the detection bound)", failed=(self._rank,),
                collective="shrink")
        survivors = [q for q in range(self.size) if not (view >> q) & 1]
        # Deterministic from AGREED state with no further communication:
        # every survivor derives the same context.  The agreement epoch
        # is part of it — shrink is collective and epochs advance in
        # lockstep, so two successive shrinks with the SAME failed set
        # still get distinct, non-cross-matching contexts (the Mailbox
        # matches by (src, ctx, tag) alone).
        ctx = (self._ctx, "shrink", ft.current_epoch(_ftm.TAG_SHRINK),
               tuple(survivors))
        new = P2PCommunicator(self._t, [self._group[q] for q in survivors],
                              ctx, recv_timeout=self.recv_timeout)
        new._ft = _ftm.CommFT(ft.world, ctx)
        # Membership epoch transition (mpi_tpu/membership.py): every
        # survivor performs shrink in lockstep (it rides the agreement),
        # so the bump is agreed by construction; the ousted rank raised
        # above and stays on the OLD epoch — its future re-handshakes
        # are rejected as EpochSkewError instead of cross-wiring.  The
        # bumped epoch is what accept_rejoin announces a vacancy under.
        # Only world-GENERATION comms bump (the full world at creation,
        # or a prior generation's shrink result — chained shrinks are
        # successive world transitions); a sub-communicator's shrink is
        # not a world-membership change.
        if self._ctx in getattr(self._t, "_gen_ctxs", ()):
            self._t.epoch += 1
            self._t._gen_ctxs.add(ctx)
        _mpit.count(shrinks=1)
        return self._inherit_errhandler(new)

    def _mark_generation(self) -> "P2PCommunicator":
        """Register this communicator as a world-GENERATION comm
        (mpi_tpu/membership.py): its ``shrink()`` is a world-membership
        transition and bumps the membership epoch.  Marked EXPLICITLY
        at the world-creation sites (init(), run_local, rejoin,
        accept_rejoin) and propagated by shrink — never inferred from
        group size, which would also match per-call nbc clones and
        per-lease serve comms (unbounded registry growth, and a user
        shrink on a lease comm silently bumping the pool's epoch)."""
        if not hasattr(self._t, "_gen_ctxs"):
            self._t._gen_ctxs = set()
        self._t._gen_ctxs.add(self._ctx)
        return self

    @property
    def membership_epoch(self) -> int:
        """The monotone membership epoch of this communicator's world
        (mpi_tpu/membership.py): 0 at creation, bumped by every
        ``shrink()`` (in survivor lockstep) and by the resident world
        server's healing transitions.  Stamped into transport hellos so
        generations can never cross-wire."""
        return self._t.epoch

    def accept_rejoin(self, timeout: Optional[float] = None
                      ) -> "P2PCommunicator":
        """Elastic recovery, the grow-back half of ULFM (mpi_tpu/
        membership.py): collective over the SURVIVORS (call it on the
        communicator ``shrink()`` returned), announces the vacant world
        slots under the current (post-shrink) membership epoch on the
        rendezvous dir, admits claims from fresh processes (refusing an
        ousted-but-live incarnation until its failure was
        ``failure_ack``ed — RejoinRefusedError on the claimer), waits
        for every replacement to publish epoch-stamped endpoints, and
        returns a FULL-SIZE communicator over the original world group
        under the new epoch.  The matching joiner-side call is
        ``mpi_tpu.membership.rejoin()`` (module-level: the fresh process
        has no communicator yet)."""
        from . import membership as _membership

        return _membership.accept_rejoin(self, timeout=timeout)

    def agree(self, value: bool = True) -> bool:
        """MPIX_Comm_agree [S: ULFM]: fault-tolerant agreement on the
        logical AND of every live rank's ``value`` — the primitive for
        app-level commit decisions (checkpoint.save(..., agree=True)).
        Completes despite failures; raises ProcFailedError *after* the
        agreement when a member is dead and not yet acknowledged via
        ``failure_ack`` (the exception carries the agreed value as
        ``.value``), so survivors decide consistently whether to treat
        the result as trustworthy."""
        from . import ft as _ftm

        ft = self._require_ft("agree")
        view, anded = _ftm._agreement(self, _ftm.TAG_AGREE, value)
        failed = [q for q in range(self.size) if (view >> q) & 1]
        if set(failed) - ft.acked:
            exc = ProcFailedError(
                f"rank {self._rank}: agreement completed but members "
                f"are dead and unacknowledged", failed=failed,
                collective="agree")
            exc.value = anded
            raise exc
        return anded

    # -- communicator management ------------------------------------------

    def _alloc_context(self):
        # Deterministic across ranks: split/dup are collective, so every rank
        # performs the same sequence of allocations on this communicator.
        # Tree-path tuples (parent_ctx, n) are collision-free across
        # generations by construction (unlike any fixed-width arithmetic
        # encoding) and transports treat contexts as opaque hashables.
        with self._lock:
            self._nchildren += 1
            return (self._ctx, self._nchildren)

    def split(self, color: Optional[int], key: int = 0) -> Optional["P2PCommunicator"]:
        # control-plane exchange pinned to the wire ring: the (color,
        # key) tuple can never ride the coll/sm arena, and letting it
        # try would lazily map the PARENT's multi-MB arena segment as a
        # side effect of every split on an shm world
        infos = self.allgather((color, key), algorithm="ring")
        ctx = self._alloc_context()
        if color is None:
            return None
        members = sorted(
            (k, cr) for cr, (c, k) in enumerate(infos) if c == color
        )
        group = [self._group[cr] for _, cr in members]
        return self._inherit_errhandler(self._inherit_ft(self._inherit_verify(
            P2PCommunicator(self._t, group, ctx,
                            recv_timeout=self.recv_timeout), "split")))

    def dup(self) -> "P2PCommunicator":
        self.barrier()  # collectiveness check + sync, like MPI_Comm_dup
        ctx = self._alloc_context()
        return self._copy_attrs_to(self._inherit_ft(self._inherit_verify(
            P2PCommunicator(self._t, self._group, ctx,
                            recv_timeout=self.recv_timeout), "dup")))

    def _inherit_ft(self, new: "P2PCommunicator") -> "P2PCommunicator":
        """A split/dup child of an FT-enabled communicator is FT-enabled
        too (same detector world, FRESH revocation state — MPI:
        revocation does not propagate across communicator creation)."""
        if self._ft is not None:
            from . import ft as _ftm

            new._ft = _ftm.CommFT(self._ft.world, new._ctx)
        return new

    def _inherit_verify(self, new: "P2PCommunicator",
                        how: str) -> "P2PCommunicator":
        """A split/dup child of a verified communicator is verified too
        (same world board, fresh collective sequence) and joins the
        unfreed-communicator registry — ``free()`` checks it out, the
        finalize report lists the leftovers."""
        if self._verify is not None:
            from .verify.state import CommVerify, user_site

            cv = CommVerify(self._verify.world)
            cv.comm_key = self._verify.world.track_comm(new, how,
                                                        user_site())
            new._verify = cv
        return new

    # -- nonblocking collectives [S: MPI-3 MPI_Ibcast & co.] ---------------

    def _nbc_comm(self) -> "P2PCommunicator":
        """Isolated-context clone for ONE nonblocking collective.  MPI
        requires every rank to issue nonblocking collectives on a comm in
        the same order, so the per-comm counter yields the same context on
        every rank without communication; the "nbc" marker keeps the space
        disjoint from split/dup's (ctx, int) children."""
        with self._lock:
            self._nbc_count = getattr(self, "_nbc_count", 0) + 1
            k = self._nbc_count
        c = P2PCommunicator(self._t, self._group, (self._ctx, "nbc", k),
                            recv_timeout=self.recv_timeout)
        # SHARE the parent's FT state (not a fresh one): revoking the
        # parent must unblock its nonblocking collectives in flight, and
        # the clone polls the parent's home_ctx for remote revocations.
        c._ft = self._ft
        if self._verify is not None:
            # fresh per-comm sequence (the clone's ctx isolates its
            # TAG_VERIFY traffic); NOT in the unfreed-comm registry —
            # nbc clones are single-use internal machinery
            from .verify.state import CommVerify

            c._verify = CommVerify(self._verify.world)
        # No collective arena on nbc clones: each clone is single-use,
        # so routing it to coll_sm would map a fresh multi-MB segment
        # PER CALL; the wire algorithms serve the threaded collective.
        c._no_coll_sm = True
        return c

    def _nbc_request(self, kind: str, fn, root: int = -1) -> Request:
        req = _ThreadRequest(fn)
        if self._verify is not None:
            self._track_request(req, kind, root, _TAG_COLL)
        return req

    def _nbc_sm(self, kind: str, *args: Any, **kwargs: Any) -> Optional[Request]:
        """Engine-owned attempt of one i-collective (mpi_tpu/nbc.py,
        ISSUE 12): a Request when this call compiled into a schedule
        state machine on the progress engine, None for the per-call-
        thread fallback below.  Verified worlds keep the thread — the
        per-call signature exchange is a blocking ring the state
        machine deliberately skips (persistent collectives hoist it to
        init instead).  Eligibility depends only on group-congruent
        facts (world engine/verifier/mode, kind, root, reduction
        geometry), so every rank takes the same path and the plan's
        wire traffic stays the blocking algorithm's frame sequence."""
        if self._progress is None or self._verify is not None:
            return None
        from . import nbc as _nbc

        if _nbc.mode() != "auto":
            return None
        return _nbc.try_state_machine(self, kind, *args, **kwargs)

    def ibcast(self, obj: Any, root: int = 0) -> Request:
        req = self._nbc_sm("ibcast", obj, root=root)
        if req is not None:
            return req
        c = self._nbc_comm()
        return self._nbc_request("ibcast", lambda: c.bcast(obj, root), root)

    def ireduce(self, obj: Any, op: _ops.ReduceOp = _ops.SUM,
                root: int = 0) -> Request:
        req = self._nbc_sm("ireduce", obj, op=op, root=root)
        if req is not None:
            return req
        c = self._nbc_comm()
        return self._nbc_request("ireduce", lambda: c.reduce(obj, op, root),
                                 root)

    def iallreduce(self, obj: Any, op: _ops.ReduceOp = _ops.SUM,
                   algorithm: str = "auto",
                   compress_key: Any = None) -> Request:
        req = self._nbc_sm("iallreduce", obj, op=op, algorithm=algorithm,
                           compress_key=compress_key)
        if req is not None:
            return req
        c = self._nbc_comm()
        return self._nbc_request(
            "iallreduce",
            lambda: c.allreduce(obj, op, algorithm,
                                compress_key=compress_key))

    def iallgather(self, obj: Any) -> Request:
        req = self._nbc_sm("iallgather", obj)
        if req is not None:
            return req
        c = self._nbc_comm()
        return self._nbc_request("iallgather", lambda: c.allgather(obj))

    def ialltoall(self, objs: Sequence[Any]) -> Request:
        req = self._nbc_sm("ialltoall", objs)
        if req is not None:
            return req
        c = self._nbc_comm()
        return self._nbc_request("ialltoall", lambda: c.alltoall(objs))

    def ibarrier(self) -> Request:
        req = self._nbc_sm("ibarrier")
        if req is not None:
            return req
        c = self._nbc_comm()
        return self._nbc_request("ibarrier", c.barrier)

    def iscatter(self, objs: Optional[Sequence[Any]], root: int = 0) -> Request:
        req = self._nbc_sm("iscatter", objs, root=root)
        if req is not None:
            return req
        c = self._nbc_comm()
        return self._nbc_request("iscatter", lambda: c.scatter(objs, root),
                                 root)

    def igather(self, obj: Any, root: int = 0) -> Request:
        req = self._nbc_sm("igather", obj, root=root)
        if req is not None:
            return req
        c = self._nbc_comm()
        return self._nbc_request("igather", lambda: c.gather(obj, root),
                                 root)

    # -- persistent collectives (MPI_Allreduce_init & co. [S: MPI-4
    # ch.6.11], mpi_tpu/nbc.py) — plan once, start() every step --------------

    def allreduce_init(self, obj: Any, op: _ops.ReduceOp = _ops.SUM,
                       algorithm: str = "auto",
                       compress_key: Any = None):
        """MPI_Allreduce_init: returns a PersistentColl handle that
        hoists child-context creation, tuned-table resolution, schedule
        compilation, and the verifier signature exchange out of the
        per-iteration path; ``start()`` re-reads ``obj`` (the MPI
        buffer-reuse idiom) and re-fires the compiled plan."""
        from . import nbc as _nbc

        return _nbc.persistent_init(self, "allreduce", obj, op, algorithm,
                                    compress_key)

    def bcast_init(self, obj: Any, root: int = 0, algorithm: str = "auto"):
        """MPI_Bcast_init [S: MPI-4]: planned broadcast (binomial-tree
        plan on the engine; the blocking algorithm per round off it)."""
        from . import nbc as _nbc

        return _nbc.persistent_init(self, "bcast", obj, root, algorithm)

    def alltoall_init(self, objs: Sequence[Any], algorithm: str = "auto"):
        """MPI_Alltoall_init [S: MPI-4]: planned pairwise exchange."""
        from . import nbc as _nbc

        return _nbc.persistent_init(self, "alltoall", objs, algorithm)

    def reduce_scatter_init(self, blocks: Any,
                            op: _ops.ReduceOp = _ops.SUM,
                            algorithm: str = "auto"):
        """MPI_Reduce_scatter_init [S: MPI-4]: planned block-ring
        reduce_scatter."""
        from . import nbc as _nbc

        return _nbc.persistent_init(self, "reduce_scatter", blocks, op,
                                    algorithm)

    def free(self) -> None:
        """Sub-communicators share the world transport: no-op (plus the
        verifier's unfreed-comm checkout).  A comm flagged as OWNING its
        transport (the spawn bridge, which has a dedicated socket world)
        closes it — otherwise every comm_spawn would leak a listener fd
        + reader threads."""
        if self._verify is not None and self._verify.comm_key is not None:
            self._verify.world.free_comm(self._verify.comm_key)
            self._verify.comm_key = None
        if getattr(self, "_owns_transport", False):
            self._owns_transport = False
            self.close_transport()

    def close_transport(self) -> List[Tuple[int, int, int]]:
        """Finalize-time shutdown: returns any unexpected pending messages
        (the 'unreceived message' sanitizer check, SURVEY.md §5)."""
        if self._ft is not None:
            self._ft.world.stop()
        if self._progress is not None:
            self._progress.stop()
        pending = self._t.mailbox.drain()
        self._t.close()
        return pending
