"""Collective-matching verification: per-entry signatures, cross-checked
in-band before any collective data moves.

Every verified collective entry computes a signature — (sequence number,
collective name, root, reduce op, payload-geometry class, algorithm,
vector counts) — and circulates it around the communicator's ring on the
reserved TAG_VERIFY channel (P-1 pipelined sendrecv steps, so EVERY rank
sees EVERY signature).  Any divergence — different collective order
across ranks, mismatched roots or reduce ops, mismatched reduce
geometry, truncating vector counts — raises
:class:`~mpi_tpu.errors.CollectiveMismatchError` on every rank, naming
the lowest divergent rank pair, both signatures, and both call sites,
BEFORE the mismatched schedules can exchange a byte (the hang/misfold
never happens).

Geometry is compared only for the collectives whose contract requires
congruent payloads (reduce / allreduce / reduce_scatter / scan); ragged
allgather and root-only-knowledge bcast/scatter deliberately skip it.
A rank that diverged in collective COUNT (entered one fewer collective,
or exited) leaves its peers blocked in this exchange — which the
deadlock detector then diagnoses, naming the enclosing collective.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from .. import mpit as _mpit
from ..errors import CollectiveMismatchError
from .state import report_add, user_site

# Reserved control tag of the signature exchange (negative: user
# wildcards can never match it — transport/base.py Mailbox._matches;
# -6/-7/-8 are ft.py's, -2..-5 the communicator's).
TAG_VERIFY = -9

# Collectives whose payload geometry must be congruent across ranks.
_GEOM_COLLS = frozenset({"reduce", "allreduce", "reduce_scatter", "scan"})


def geom_of(coll: str, payload: Any) -> Optional[Tuple]:
    """Geometry class of a reduction payload: (dtype, shape) for array
    payloads, a type marker otherwise; None = not compared (non-uniform
    collective, or rank-local knowledge only)."""
    if coll not in _GEOM_COLLS or payload is None:
        return None
    if hasattr(payload, "dtype") and hasattr(payload, "shape"):
        return (str(payload.dtype), tuple(int(s) for s in payload.shape))
    return (type(payload).__name__,)


def signature(seq: int, coll: str, root: Optional[int], op: Optional[str],
              geom: Optional[Tuple], algorithm: Optional[str],
              counts: Optional[Tuple]) -> Tuple:
    return (seq, coll, root, op, geom, algorithm, counts)


def _render(sig: Tuple) -> str:
    seq, coll, root, op, geom, algorithm, counts = sig
    bits = [f"#{seq} {coll}"]
    if root is not None:
        bits.append(f"root={root}")
    if op is not None:
        bits.append(f"op={op}")
    if geom is not None:
        bits.append(f"geom={geom}")
    if algorithm is not None:
        bits.append(f"algorithm={algorithm}")
    if counts is not None:
        bits.append(f"counts={list(counts)}")
    return " ".join(bits)


def check(comm, coll: str, root: Optional[int] = None, op: Any = None,
          payload: Any = None, algorithm: Optional[str] = None,
          counts: Optional[Tuple] = None) -> None:
    """The collective-entry hook (size>1, verifier on): exchange this
    rank's signature around the ring and compare everyone's."""
    v = comm._verify
    seq = v.next_seq()
    opname = getattr(op, "name", None) if op is not None else None
    sig = signature(seq, coll, root, opname, geom_of(coll, payload),
                    algorithm, counts)
    site = user_site()
    p, r = comm.size, comm.rank
    entries = {r: (r, sig, site)}
    cur = entries[r]
    for _ in range(p - 1):
        cur = comm._sendrecv_internal(cur, (r + 1) % p, (r - 1) % p,
                                      TAG_VERIFY)
        entries[cur[0]] = cur
    ranks = sorted(entries)
    base_rank = ranks[0]
    _, base_sig, base_site = entries[base_rank]
    for q in ranks[1:]:
        _, q_sig, q_site = entries[q]
        if _differs(base_sig, q_sig):
            _mpit.count(verify_mismatches=1)
            msg = (f"collective mismatch on comm ctx={comm._ctx!r}:\n"
                   f"  rank {base_rank}: {_render(base_sig)} at {base_site}\n"
                   f"  rank {q}: {_render(q_sig)} at {q_site}")
            report_add(msg)
            raise CollectiveMismatchError(
                msg, ranks=(base_rank, q), signatures=(base_sig, q_sig),
                sites=(base_site, q_site))


def _differs(a: Tuple, b: Tuple) -> bool:
    # geometry (index 4) is only compared when BOTH ranks computed one:
    # a root-only payload (bcast) legitimately publishes None elsewhere
    for i in range(len(a)):
        if i == 4 and (a[i] is None or b[i] is None):
            continue
        if a[i] != b[i]:
            return True
    return False
