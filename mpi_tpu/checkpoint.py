"""Checkpoint / resume — the slice-restart half of the fault story.

SURVEY.md §5: the reference has no checkpoint capability (socket EOF ⇒
crash); the TPU-native failure model is *slice restart + checkpoint* —
detection surfaces through ``recv_timeout`` / ``FaultyTransport`` (see
transport/faulty.py), and recovery is relaunch + restore.  Two surfaces:

* process backends — ``save(path, state, comm)`` / ``load(path, comm)``:
  each save writes a fresh generation ``gen{k}/rank{r}/state.pkl`` under
  ``path`` and commits it by atomically swinging ``manifest.json`` to
  ``gen`` k once every rank's state is on disk — so ``path`` always holds
  either the previous complete checkpoint or the new one, never a torn
  mix (format-1 checkpoints, rank dirs directly under ``path``, are still
  loadable).  Save is collective (barrier'd).
* SPMD/TPU backend — ``save_sharded`` / ``load_sharded`` wrap orbax
  (async-capable, TPU-native sharded IO): global jax Arrays are written
  per-shard by the process that owns them and restored to the SAME
  sharding layout, so a pod-scale training state round-trips without
  ever being gathered to one host.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
from typing import Any, Optional

import numpy as np

_MANIFEST = "manifest.json"
_STATE = "state.pkl"


def _read_manifest(path: str) -> Optional[dict]:
    try:
        with open(os.path.join(path, _MANIFEST)) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def _gen_dir(path: str, manifest: dict) -> str:
    """State root of a committed checkpoint (format-1 compat: rank dirs
    live directly under ``path``)."""
    gen = manifest.get("gen")
    return path if gen is None else os.path.join(path, f"gen{gen}")


def save(path: str, state: Any, comm=None, agree: bool = False) -> None:
    """Collective checkpoint on a process-backend communicator.

    Crash-safe re-save (generation scheme): every rank writes its state
    pytree into a FRESH ``gen{k}/`` subdirectory, and only after all ranks
    have finished does rank 0 atomically swing the manifest to the new
    generation — so the previous good checkpoint at ``path`` stays
    restorable through every instant of the save.  A crash before the
    manifest swap leaves the old generation committed; a crash after it
    leaves the new one (the orphaned directory is swept on the next save).

    ``agree=True`` (needs ULFM fault tolerance, mpi_tpu/ft.py) replaces
    the pre-commit barrier with fault-tolerant agreement: if any rank
    died before its state reached disk, ``comm.agree`` raises
    ProcFailedError on every survivor and the manifest is NOT swung —
    the old checkpoint stays committed, and the caller can ``shrink()``
    / relaunch and retry.  A plain barrier would instead either hang on
    the corpse or (FT enabled) raise on *some* ranks while rank 0 may
    already have committed — agreement makes the commit/no-commit
    decision consistent across survivors.  An exception from the
    post-commit agreement means the checkpoint IS committed
    (``exists(path)`` disambiguates).
    """
    from . import init

    comm = comm or init()

    def _sync(committed: bool):
        if not agree:
            comm.barrier()
            return
        # The agreed value is "no survivor knows of any dead member"
        # — NOT just this rank's view, and independent of
        # failure_ack: an acknowledged death re-arms ANY_SOURCE
        # receives, but a full-world checkpoint with a member's
        # state file missing must never commit (the manifest sweep
        # would destroy the last good generation).  agree() itself
        # still raises for unacknowledged deaths.  The exception text
        # states which side of the commit point the death landed on —
        # the recovery decision differs (retry vs accept).
        if not comm.agree(not comm.get_failed()):
            from .errors import ProcFailedError

            raise ProcFailedError(
                "checkpoint IS committed, but a member died before "
                "every survivor returned from save (exists(path) "
                "confirms the new generation)" if committed else
                "checkpoint commit withheld: a member died before "
                "every rank's state reached disk",
                failed=tuple(comm.get_failed()), collective="agree")
    prev = _read_manifest(path) if comm.rank == 0 else None
    if comm.rank == 0:
        prev_gen = -1 if prev is None else int(prev.get("gen", -1))
        next_gen = prev_gen + 1
    else:
        next_gen = None
    next_gen = comm.bcast(next_gen, root=0)
    gen_dir = os.path.join(path, f"gen{next_gen}")
    rank_dir = os.path.join(gen_dir, f"rank{comm.rank}")
    os.makedirs(rank_dir, exist_ok=True)
    with open(os.path.join(rank_dir, _STATE), "wb") as f:
        pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
    _sync(committed=False)  # every rank's state on disk, or NO commit
    if comm.rank == 0:
        tmp = os.path.join(path, "." + _MANIFEST)
        with open(tmp, "w") as f:
            json.dump({"nranks": comm.size, "format": 2, "gen": next_gen}, f)
        os.replace(tmp, os.path.join(path, _MANIFEST))  # the commit point
        # everything but the committed generation is now unreferenced —
        # sweep it ALL best-effort: older generations, orphans from saves
        # that crashed after their own commit, and format-1 rank{r}/ dirs
        keep = f"gen{next_gen}"
        for entry in os.listdir(path):
            if entry == keep or not (entry.startswith("gen")
                                     or entry.startswith("rank")):
                continue
            victim = os.path.join(path, entry)
            if os.path.isdir(victim):
                shutil.rmtree(victim, ignore_errors=True)
    _sync(committed=True)  # nobody returns before the commit is visible


def exists(path: str) -> bool:
    """True iff ``path`` holds a COMPLETE checkpoint (manifest present)."""
    return os.path.exists(os.path.join(path, _MANIFEST))


def load(path: str, comm=None) -> Any:
    """Restore this rank's state from a complete checkpoint; raises
    FileNotFoundError on a missing/partial one, ValueError on a world-size
    mismatch (a resumed job must match the checkpoint's geometry)."""
    from . import init

    comm = comm or init()
    mpath = os.path.join(path, _MANIFEST)
    if not os.path.exists(mpath):
        raise FileNotFoundError(
            f"no complete checkpoint at {path!r} (manifest missing — the "
            f"save was interrupted before commit)")
    with open(mpath) as f:
        manifest = json.load(f)
    if manifest["nranks"] != comm.size:
        raise ValueError(
            f"checkpoint was taken with {manifest['nranks']} ranks; this "
            f"world has {comm.size}")
    state_dir = _gen_dir(path, manifest)
    with open(os.path.join(state_dir, f"rank{comm.rank}", _STATE), "rb") as f:
        return pickle.load(f)


# ---- SPMD / sharded (orbax) ----------------------------------------------


def save_sharded(path: str, state: Any) -> None:
    """Write a pytree of (possibly sharded, possibly multi-host) jax
    Arrays via orbax; call OUTSIDE jit, same args on every process."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckpt:
        ckpt.save(os.path.abspath(path), state, force=True)


def load_sharded(path: str, template: Any) -> Any:
    """Restore a pytree saved by :func:`save_sharded`.  ``template`` is a
    pytree of arrays or jax.ShapeDtypeStruct(shape, dtype, sharding=...)
    giving the target shardings — restored shards land directly on the
    right devices (no host-side gather)."""
    import jax
    import orbax.checkpoint as ocp

    abstract_tree = jax.tree.map(
        lambda x: (x if isinstance(x, jax.ShapeDtypeStruct)
                   else jax.ShapeDtypeStruct(
                       np.shape(x), np.asarray(x).dtype if not hasattr(x, "dtype")
                       else x.dtype,
                       sharding=getattr(x, "sharding", None))),
        template)
    with ocp.StandardCheckpointer() as ckpt:
        return ckpt.restore(os.path.abspath(path), abstract_tree)
