"""Prometheus text rendering for the serve metrics endpoint.

Pure functions from a ``WorldServer.stats()`` document (plus the mpit
histogram pvars) to Prometheus exposition format, so the HTTP endpoint
in serve.py is a ten-line thread and the rendering is unit-testable
without a server.  The shape follows the Prometheus conventions:
counters get ``_total``, histograms emit ``_bucket{le=...}`` +
``_sum`` + ``_count``, labels for the per-worker rows.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .. import mpit as _mpit

# stats() keys rendered as monotone counters (name -> _total metric)
_COUNTER_KEYS = ("leases_granted", "leases_denied", "jobs_ok",
                 "jobs_failed", "heals_completed", "workers_lost")

# stats() keys rendered as gauges
_GAUGE_KEYS = ("epoch", "pool_size", "idle", "leases_active",
               "worlds_per_s", "uptime_s")

_PREFIX = "mpi_tpu_serve"


def _fmt(v) -> str:
    return repr(float(v)) if isinstance(v, float) else str(int(v))


def render_histogram(name: str, metric: str,
                     lines: List[str]) -> None:
    """One mpit histogram pvar as a Prometheus histogram series."""
    snap = _mpit.pvar_hist_read(name)
    lines.append(f"# TYPE {metric} histogram")
    for le, cum in _mpit.hist_cumulative(name):
        lines.append(f'{metric}_bucket{{le="{le:.9g}"}} {cum}')
    lines.append(f'{metric}_bucket{{le="+Inf"}} {snap["count"]}')
    lines.append(f"{metric}_sum {snap['sum_s']:.9g}")
    lines.append(f"{metric}_count {snap['count']}")


def prometheus_text(stats: Dict,
                    hists: Optional[Dict[str, str]] = None) -> str:
    """Render a serve stats document (see ``WorldServer.stats()``) as
    Prometheus exposition text.  ``hists`` maps mpit histogram pvar
    names to metric names; the default exports the lease-acquire
    distribution (the p50/p99 the acceptance names)."""
    lines: List[str] = []
    for key in _GAUGE_KEYS:
        if key in stats:
            metric = f"{_PREFIX}_{key}"
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {_fmt(stats[key])}")
    for key in _COUNTER_KEYS:
        if key in stats:
            metric = f"{_PREFIX}_{key}_total"
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {_fmt(stats[key])}")
    workers = stats.get("workers") or {}
    if workers:
        metric = f"{_PREFIX}_worker_state"
        lines.append(f"# TYPE {metric} gauge")
        for slot, state in sorted(workers.items()):
            lines.append(
                f'{metric}{{slot="{slot}",state="{state}"}} 1')
    healing = stats.get("healing")
    if healing is not None:
        metric = f"{_PREFIX}_healing_slots"
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {len(healing)}")
    # aggregated worker pvars (piggybacked on job_done replies): the
    # pool's data-plane story — link reconnects, arena hits, detected
    # failures — summed over the latest snapshot of each slot
    agg = stats.get("worker_pvars") or {}
    if agg:
        metric = "mpi_tpu_worker_pvar"
        lines.append(f"# TYPE {metric} gauge")
        for name in sorted(agg):
            lines.append(f'{metric}{{name="{name}"}} {_fmt(agg[name])}')
    for name, metric in (hists if hists is not None
                         else {"lease_acquire_s":
                               "mpi_tpu_lease_acquire_seconds"}).items():
        render_histogram(name, metric, lines)
    # the quantile gauges the acceptance scrapes directly (estimated
    # from the log buckets — see mpit.hist_quantile's error bound)
    for q, label in ((0.5, "p50"), (0.99, "p99")):
        est = _mpit.hist_quantile("lease_acquire_s", q)
        if est is not None:
            metric = f"{_PREFIX}_lease_acquire_{label}_seconds"
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {est:.9g}")
    return "\n".join(lines) + "\n"
