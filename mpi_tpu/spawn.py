"""Dynamic process management — MPI_Comm_spawn [S: MPI-2 ch.5].

Parents collectively spawn a NEW world of child rank processes and get an
:class:`~mpi_tpu.intercomm.InterComm` to it; children find their side with
:func:`comm_get_parent`.  The classic master/worker elasticity primitive:
a running job grows itself without restarting the launcher.

Wiring (all file-rendezvous TCP, like the launcher's worlds):

* the CHILD WORLD is an ordinary socket world of ``maxprocs`` ranks over a
  fresh rendezvous dir — children just call ``mpi_tpu.init()`` (or touch
  ``COMM_WORLD``) exactly like launcher-started programs;
* the PARENT-CHILD BRIDGE is a second socket transport over its own
  rendezvous dir spanning P parents + C children: parents take bridge
  ranks 0..P-1 (their ``comm`` rank order), children P..P+C-1.  Rank
  discovery is lazy (port files + polling), so parents can build their
  bridge endpoint before any child has started.

The spawning communicator can be any process-backend comm (world or a
split subset) — the bridge binds to ITS members.  SPMD communicators
cannot spawn OS processes; the diagnostic points to the launcher.
"""

from __future__ import annotations

import atexit
import os
import shutil
import subprocess
import sys
import tempfile
from typing import Any, List, Optional, Sequence, Tuple

from .communicator import Communicator, P2PCommunicator
from .intercomm import InterComm

ENV_PARENT_RDV = "MPI_TPU_PARENT_RDV"
ENV_PARENT_SIZE = "MPI_TPU_PARENT_SIZE"
ENV_PARENT_TOTAL = "MPI_TPU_PARENT_TOTAL"

# Popen handles of everything this process spawned: children are
# independent jobs (MPI semantics: spawn does not wait), but keeping the
# handles lets atexit reap finished ones instead of leaving zombies.
_spawned: List[subprocess.Popen] = []
_tmpdirs: List[str] = []
_parent_intercomm: Optional[InterComm] = None


def _cleanup() -> None:  # pragma: no cover - exit path
    for p in _spawned:
        p.poll()
    for d in _tmpdirs:
        shutil.rmtree(d, ignore_errors=True)


atexit.register(_cleanup)


def _bridge_comm(bridge_rank: int, total: int, rdv: str) -> P2PCommunicator:
    from .transport.socket import SocketTransport

    t = SocketTransport(bridge_rank, total, rdv)
    comm = P2PCommunicator(t, range(total))
    comm._owns_transport = True  # intercomm.free() closes the bridge socket
    return comm


def comm_spawn(argv: Sequence[str], maxprocs: int,
               comm: Optional[Communicator] = None, root: int = 0,
               env_extra: Optional[dict] = None,
               info: Optional[dict] = None) -> InterComm:
    """MPI_Comm_spawn: start ``maxprocs`` ranks of ``python argv...`` as a
    new world; returns the parent side of the parent-child intercomm.
    Collective over ``comm`` (default: this process's world); only
    ``root`` actually forks the children."""
    del info  # MPI_Info hints: accepted, advisory no-ops
    segments = [(list(argv), int(maxprocs))]
    return _spawn_segments(segments, comm, root, env_extra)


def comm_spawn_multiple(segments: Sequence[Tuple[Sequence[str], int]],
                        comm: Optional[Communicator] = None, root: int = 0,
                        env_extra: Optional[dict] = None) -> InterComm:
    """MPI_Comm_spawn_multiple: one child WORLD running different
    executables — ``segments`` is [(argv, maxprocs), ...]; child ranks are
    assigned segment by segment, in order [S]."""
    segs = [(list(a), int(n)) for a, n in segments]
    return _spawn_segments(segs, comm, root, env_extra)


def _spawn_segments(segments: List[Tuple[List[str], int]],
                    comm: Optional[Communicator], root: int,
                    env_extra: Optional[dict]) -> InterComm:
    if comm is None:
        from . import init

        comm = init()
    if not isinstance(comm, P2PCommunicator):
        raise NotImplementedError(
            "comm_spawn forks OS processes — a process-backend feature; "
            "an SPMD program's world is a device mesh, not a process pool "
            "(start more ranks with mpi_tpu.launcher instead)")
    nchildren = sum(n for _, n in segments)
    if nchildren < 1:
        raise ValueError("maxprocs must total >= 1")
    p = comm.size
    total = p + nchildren
    # root makes the rendezvous dirs; everyone learns them collectively
    if comm.rank == root:
        bridge_rdv = tempfile.mkdtemp(prefix="mpi_tpu_spawn_bridge_")
        child_rdv = tempfile.mkdtemp(prefix="mpi_tpu_spawn_world_")
        _tmpdirs.extend([bridge_rdv, child_rdv])
        dirs = (bridge_rdv, child_rdv)
    else:
        dirs = None
    bridge_rdv, child_rdv = comm.bcast(dirs, root)
    # every parent opens its bridge endpoint BEFORE children are forked:
    # port files are published immediately, connections form lazily
    union = _bridge_comm(comm.rank, total, bridge_rdv)
    if comm.rank == root:
        from .launcher import ENV_BACKEND, ENV_RANK, ENV_RDV, ENV_SIZE

        child_rank = 0
        for argv, n in segments:
            for _ in range(n):
                env = dict(os.environ)
                env.update({
                    ENV_RANK: str(child_rank),
                    ENV_SIZE: str(nchildren),
                    ENV_RDV: child_rdv,
                    ENV_BACKEND: "socket",
                    ENV_PARENT_RDV: bridge_rdv,
                    ENV_PARENT_SIZE: str(p),
                    ENV_PARENT_TOTAL: str(total),
                })
                if env_extra:
                    env.update(env_extra)
                _spawned.append(
                    subprocess.Popen([sys.executable, *argv], env=env))
                child_rank += 1
    return InterComm(union, list(range(p)), list(range(p, total)))


def comm_get_parent() -> Optional[InterComm]:
    """MPI_Comm_get_parent: in a spawned child, the intercomm to the
    spawning parents (cached); None in a world that was not spawned."""
    global _parent_intercomm
    if _parent_intercomm is not None:
        return _parent_intercomm
    rdv = os.environ.get(ENV_PARENT_RDV)
    if rdv is None:
        return None
    from . import init

    world = init()  # my child world: rank/size from the launcher-style env
    psize = int(os.environ[ENV_PARENT_SIZE])
    total = int(os.environ[ENV_PARENT_TOTAL])
    union = _bridge_comm(psize + world.rank, total, rdv)
    _parent_intercomm = InterComm(union, list(range(psize, total)),
                                  list(range(psize)))
    return _parent_intercomm
