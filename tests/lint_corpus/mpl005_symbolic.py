"""Seeded bug: the request IS waited — but only on one CFG path; the
``else`` path leaks it.  Literal scanning cannot see paths."""


def main(comm, flag):
    req = comm.irecv(0, tag=1)
    if flag:
        return req.wait()
    return None
