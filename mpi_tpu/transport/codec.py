"""Payload framing shared by the byte-stream transports (socket, shm).

Two frame formats ride the same length-prefixed stream, distinguished by
the top bit of the u64 length word (RAW_FLAG):

* pickle frames — arbitrary picklable envelopes ``(ctx, tag, obj)``; the
  reference's wire format (SURVEY.md §2 #2 [B: "socket/pickle path"]).
* raw-array frames — contiguous numpy arrays ship as a tiny pickled meta
  header ``(ctx, tag, dtype.str, shape)`` followed by the array's raw
  bytes.  The hot payload is never pickled: the sender hands the buffer
  pointer straight to the ring/socket (ONE copy, into the transport) and
  the receiver reads straight into the freshly-allocated result array
  (ONE copy, out) — this is what makes the native data plane actually
  faster than pickle-over-TCP at bandwidth sizes (VERDICT round 1,
  "what's weak" #2).
* multi-segment raw frames — a LIST of contiguous numpy arrays ships as
  one length-prefixed raw body: meta ``(ctx, tag, [(dtype.str, shape),
  ...])`` followed by every segment's raw bytes back to back.  List
  payloads of arrays (chunked collectives, user batches) previously fell
  off the raw path into a pickle of the whole list — silently copying
  every array byte through the pickler twice (ISSUE 1 tentpole #2).  The
  receiver reads each segment into its own pooled destination
  (``RECV_POOL``) and delivers the reassembled list.
* wire-tagged raw frames (ISSUE 8, the wire-dtype ≠ fold-dtype seam) —
  an :class:`Encoded` payload ships its segments exactly like the
  multi-segment frame but the meta grows a WIRE-DTYPE HEADER field:
  ``(ctx, tag, [(dtype.str, shape), ...], wire)`` (a 4-tuple whose third
  element is a LIST, vs the single-array meta's 4-tuple whose third
  element is a str — both frame kinds keep sharing RAW_FLAG).  The
  receiver reconstructs an ``Encoded`` carrying the same wire tag, so
  the payload stays in its wire encoding all the way to the FOLD site
  (encode-on-send / decode-on-fold — mpi_tpu/compress.py names the
  encodings); compression therefore composes with segment pipelining
  and the progress engine's credit callbacks with zero extra copies.

Eligibility for the raw path: any ``np.ndarray`` without Python-object
fields (object dtypes and structured/void dtypes fall back to pickle,
which handles them correctly).  Non-contiguous arrays are compacted with
``ascontiguousarray`` first — still cheaper than pickling.  For the
multi-segment frame, a plain ``list`` whose EVERY element passes the
same test; tuples and mixed lists keep pickle (type fidelity).

Byte-level observability: every frame build counts into the mpit pvars
``bytes_raw_sent`` / ``bytes_pickled_sent``; host-side payload copies
(self-send value copies, non-contiguous compactions) count into
``payload_copies`` — the counters that prove a hot path stayed on the
one-copy plane.  Asserted for allreduce/bcast/allgather in
tests/test_segmented_collectives.py and for the rest of the family
(alltoall, reduce_scatter, the Rabenseifner composition, scatter/
gather, scan) in tests/test_segmented_collectives2.py — on BOTH
byte-stream transports.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Optional, Tuple, Union

import numpy as np

from .. import mpit as _mpit
from .. import recvpool as _recvpool

# u64 length word: top bit = raw-array frame, low 63 bits = body length
RAW_FLAG = 1 << 63
LEN_MASK = RAW_FLAG - 1
META = struct.Struct("<I")  # meta-pickle length prefix inside a raw body

_PROTO = pickle.HIGHEST_PROTOCOL


def raw_eligible(payload: Any) -> bool:
    """Whether a payload can ship as raw bytes.  Exact-type check:
    ndarray SUBCLASSES (MaskedArray, np.matrix, ...) carry state the raw
    frame cannot represent — they keep the pickle path, which
    round-trips them faithfully."""
    return (type(payload) is np.ndarray and not payload.dtype.hasobject
            and payload.dtype.kind != "V")


def _contiguous(arr: np.ndarray) -> np.ndarray:
    if arr.flags["C_CONTIGUOUS"]:
        return arr
    # compact a strided view (ascontiguousarray would also promote
    # 0-dim to 1-dim, but 0-dim arrays are always contiguous)
    _mpit.count(copies=1)
    return np.ascontiguousarray(arr)


def as_raw_array(payload: Any) -> Optional[np.ndarray]:
    """The contiguous ndarray to ship raw, or None → use pickle."""
    if raw_eligible(payload):
        return _contiguous(payload)
    return None


def as_raw_segments(payload: Any) -> Optional[List[np.ndarray]]:
    """The contiguous ndarrays of a list payload to ship as ONE
    multi-segment raw frame, or None → use pickle.

    Only plain (non-empty) ``list`` payloads whose every element passes
    the raw-array test qualify; tuples, empty lists, and mixed lists
    keep the pickle path so arbitrary payload types round-trip with
    full fidelity.  So does a list holding the SAME array object twice:
    pickle's memo preserves that identity on the receiver (``got[0] is
    got[1]``), which independent raw segments cannot — and a program
    relying on it would silently read stale data after mutating one."""
    if not _is_plain_raw_list(payload):
        return None
    return [_contiguous(item) for item in payload]


class Encoded:
    """A payload in a WIRE encoding distinct from its fold dtype
    (ISSUE 8): ``segs`` are the contiguous raw-eligible arrays that ship
    back to back in one wire-tagged raw frame, ``wire`` names the
    encoding (a mpi_tpu/compress.py codec name) so the receiving fold
    site knows how to decode.  Deliberately dumb — the codec layer moves
    it; compress.py owns what the bytes mean."""

    __slots__ = ("wire", "segs")

    def __init__(self, wire: str, segs: List[np.ndarray]):
        self.wire = wire
        self.segs = segs

    @property
    def nbytes(self) -> int:
        """Wire payload size (probe/Status sizing, transport.base
        ``payload_nbytes`` duck-types on this attribute)."""
        return sum(int(s.nbytes) for s in self.segs)

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return (f"Encoded({self.wire!r}, "
                f"{[(s.dtype.str, s.shape) for s in self.segs]})")


def _is_plain_raw_list(payload: Any) -> bool:
    """Whether a list payload gets element-wise array treatment — the ONE
    predicate behind both the wire path (as_raw_segments) and the
    self-send path (value_copy), so a self-send always mirrors what a
    peer-send would do: plain non-empty list, every element raw-eligible,
    no duplicate objects (pickle's memo must keep receiver-side
    aliasing)."""
    return (type(payload) is list and bool(payload)
            and all(raw_eligible(item) for item in payload)
            and len({id(item) for item in payload}) == len(payload))


def _meta_nbytes(arr) -> int:
    """Payload bytes from dtype+shape alone — the same duck-typed contract
    the meta pickle itself uses (test_codec drives >2^31-element frame
    arithmetic through stand-ins that carry only those two fields)."""
    n = 1
    for s in arr.shape:
        n *= int(s)
    return n * np.dtype(arr.dtype).itemsize


def pack_raw_frame(ctx, tag: int,
                   payload: Any) -> Optional[Tuple[bytes, Tuple[np.ndarray, ...]]]:
    """The raw-frame plan for ``payload``: ``(head, bufs)`` where ``head``
    is the length-prefixed meta and ``bufs`` the contiguous arrays whose
    bytes follow it on the wire (single-array or multi-segment frame) —
    or None → the payload must ride pickle.  The ONE place both
    byte-stream transports decide a payload's frame kind, so their wire
    behavior cannot diverge."""
    if type(payload) is Encoded:
        segs = [_contiguous(s) for s in payload.segs]
        return pack_raw_wire_meta(ctx, tag, segs, payload.wire), tuple(segs)
    arr = as_raw_array(payload)
    if arr is not None:
        return pack_raw_meta(ctx, tag, arr), (arr,)
    segs = as_raw_segments(payload)
    if segs is not None:
        return pack_raw_segs_meta(ctx, tag, segs), tuple(segs)
    return None


def pack_raw_meta(ctx, tag: int, arr: np.ndarray) -> bytes:
    """``<u32 meta_len><meta pickle>`` — everything in the raw body except
    the array bytes themselves."""
    meta = pickle.dumps((ctx, tag, arr.dtype.str, arr.shape), protocol=_PROTO)
    _mpit.count(bytes_raw=_meta_nbytes(arr))
    return META.pack(len(meta)) + meta


def pack_raw_segs_meta(ctx, tag: int, segs: List[np.ndarray]) -> bytes:
    """Multi-segment meta: ``(ctx, tag, [(dtype.str, shape), ...])`` — a
    3-tuple, distinguished from the single-array meta (a 4-tuple) by
    arity, so both frame kinds share RAW_FLAG and the wire stays
    backward compatible."""
    meta = pickle.dumps((ctx, tag, [(a.dtype.str, a.shape) for a in segs]),
                        protocol=_PROTO)
    _mpit.count(bytes_raw=sum(int(a.nbytes) for a in segs))
    return META.pack(len(meta)) + meta


def pack_raw_wire_meta(ctx, tag: int, segs: List[np.ndarray],
                       wire: str) -> bytes:
    """Wire-tagged meta (ISSUE 8): the multi-segment descriptor list plus
    the wire-encoding name — a 4-tuple whose third element is a LIST,
    disambiguated from the single-array 4-tuple (third element a str) by
    type, so all three raw frame kinds keep sharing RAW_FLAG."""
    meta = pickle.dumps(
        (ctx, tag, [(a.dtype.str, a.shape) for a in segs], wire),
        protocol=_PROTO)
    _mpit.count(bytes_raw=sum(int(a.nbytes) for a in segs))
    return META.pack(len(meta)) + meta


# The receive pool lives in mpi_tpu/recvpool.py since ISSUE 17, where
# it grew pow2 SIZE CLASSES (the old pool keyed exact byte counts).
# The old name stays importable here — tests and callers construct
# ``_BufferPool(min_bytes=...)`` — and the process-wide instance every
# byte-stream transport allocates from is still ``codec.RECV_POOL``.
_BufferPool = _recvpool.RecvPool

RECV_POOL = _BufferPool()


RawPayload = Union[np.ndarray, List[np.ndarray], "Encoded"]


def parse_raw_meta(meta: bytes) -> Tuple[Any, int, tuple]:
    """Decode a raw frame's meta pickle WITHOUT allocating destinations:
    (ctx, tag, plan), where plan is ``("arr", dtype_str, shape)`` for
    the single-array frame, ``("segs", descs)`` for multi-segment, and
    ``("wire", descs, wire)`` for wire-tagged.  The socket reader
    consults the steering registry with the plan BEFORE any allocation
    — the rendezvous path needs no intermediate buffer at all."""
    tup = pickle.loads(meta)
    if len(tup) == 4 and isinstance(tup[2], str):
        return tup[0], tup[1], ("arr", tup[2], tuple(tup[3]))
    if len(tup) == 4:
        return tup[0], tup[1], ("wire", tup[2], tup[3])
    return tup[0], tup[1], ("segs", tup[2])


def plan_nbytes(plan: tuple) -> int:
    """Total body bytes a parsed plan describes (frame-length check)."""
    def one(ds, shape):
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        return n * np.dtype(ds).itemsize
    if plan[0] == "arr":
        return one(plan[1], plan[2])
    return sum(one(ds, shape) for ds, shape in plan[1])


def alloc_raw(plan: tuple) -> RawPayload:
    """Pool-allocate a parsed plan's destination payload — the fallback
    when a frame was not steered into a posted receive buffer.  A
    multi-segment plan yields a LIST of destination arrays, each pooled
    independently, filled in order from the frame body; a wire-tagged
    plan yields an :class:`Encoded` wrapping its destination segments,
    so the wire encoding survives to the fold site."""
    if plan[0] == "arr":
        return RECV_POOL.empty(plan[2], np.dtype(plan[1]))
    segs = [RECV_POOL.empty(shape, np.dtype(ds)) for ds, shape in plan[1]]
    return Encoded(plan[2], segs) if plan[0] == "wire" else segs


def unpack_raw_meta(meta: bytes) -> Tuple[Any, int, RawPayload]:
    """Decode a raw frame's meta pickle; returns (ctx, tag, empty
    destination payload to read the raw bytes into — pooled at
    bandwidth sizes, see :class:`mpi_tpu.recvpool.RecvPool`).  The
    shm transport's whole-frame path; the socket reader uses the
    parse/alloc halves separately to give steering first refusal."""
    ctx, tag, plan = parse_raw_meta(meta)
    return ctx, tag, alloc_raw(plan)


def raw_destinations(payload: RawPayload) -> List[np.ndarray]:
    """The fill/drain order of a raw payload's buffers (single array,
    multi-segment list, or wire-tagged Encoded) — the one place both
    transports iterate it."""
    if type(payload) is Encoded:
        return payload.segs
    return payload if isinstance(payload, list) else [payload]


def parse_raw_body(body: bytes) -> Tuple[Any, int, RawPayload]:
    """Decode an entire small raw body pulled in one read: meta prefix +
    array bytes → (ctx, tag, array-or-list).  The .copy() both compacts
    and makes the result writable/owned."""
    (mlen,) = META.unpack_from(body)
    tup = pickle.loads(body[META.size:META.size + mlen])
    off = META.size + mlen

    def take(dtype_str, shape):
        nonlocal off
        dtype = np.dtype(dtype_str)
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        if not (n and dtype.itemsize):
            return np.empty(shape, dtype)
        arr = np.frombuffer(body, dtype=dtype, count=n,
                            offset=off).reshape(shape).copy()
        off += n * dtype.itemsize
        return arr

    if len(tup) == 4 and isinstance(tup[2], str):
        ctx, tag, dtype_str, shape = tup
        return ctx, tag, take(dtype_str, shape)
    if len(tup) == 4:
        ctx, tag, descs, wire = tup
        return ctx, tag, Encoded(wire,
                                 [take(ds, shape) for ds, shape in descs])
    ctx, tag, descs = tup
    return ctx, tag, [take(ds, shape) for ds, shape in descs]


def pack_pickle_body(ctx, tag: int, obj: Any) -> bytes:
    blob = pickle.dumps((ctx, tag, obj), protocol=_PROTO)
    _mpit.count(bytes_pickled=len(blob))
    return blob


def value_copy(payload: Any) -> Any:
    """Self-send copy with message (value) semantics: cheap ndarray copy
    (also elementwise for all-ndarray lists, the multi-segment shape),
    pickle round-trip for everything else."""
    if isinstance(payload, np.ndarray):
        _mpit.count(copies=1)
        return payload.copy()
    if type(payload) is Encoded:
        _mpit.count(copies=len(payload.segs))
        return Encoded(payload.wire, [s.copy() for s in payload.segs])
    if _is_plain_raw_list(payload):
        # the shared predicate, not a bare type check: an object-dtype
        # element's .copy() would be shallow, and a duplicate-object
        # list must keep pickle's receiver-side aliasing — both cases
        # ride the pickle deep copy below, exactly as a peer-send would
        _mpit.count(copies=len(payload))
        return [item.copy() for item in payload]
    _mpit.count(copies=1)
    return pickle.loads(pickle.dumps(payload, protocol=_PROTO))
