"""Transport plugin boundary (L1) + the shared message-matching engine.

SURVEY.md §1/§2: the load-bearing seam of the reference is the Communicator
plugin boundary — collectives are written against Communicator, Communicators
own a swappable Transport.  A Transport moves opaque payloads between world
ranks and supports MPI-style matching by (source, context, tag) with FIFO
ordering per (src, dst) channel [S].

The matching engine (Mailbox) is shared by every CPU transport so matching
semantics — including wildcard rules — are identical across them:
* ANY_SOURCE matches any source rank.
* ANY_TAG matches only *user* tags (>= 0); internal negative tags (used by
  collectives/barrier, see mpi_tpu/communicator.py) must be matched exactly,
  so user wildcard receives can never steal collective traffic.
"""

from __future__ import annotations

import threading
import time
from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional, Sequence, Tuple

ANY_SOURCE = -1
ANY_TAG = -1


class TransportError(RuntimeError):
    pass


class RecvTimeout(TransportError):
    pass


def payload_nbytes(obj: Any) -> Optional[int]:
    """Size of a sized payload (ndarray / bytes-like), None for opaque
    objects — the count a probe can report without consuming (Status
    applies the same rule after a receive)."""
    if hasattr(obj, "nbytes"):
        return int(obj.nbytes)
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return len(obj)
    return None


class Mailbox:
    """Thread-safe matching queue of (src, ctx, tag, payload, stamp)
    messages.  ``stamp`` is the sender's vector-clock stamp under verify
    mode and None otherwise (mpi_tpu/verify/vclock.py)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._items: List[Tuple[int, int, int, Any, Any]] = []
        self._closed = False
        # lifetime delivery count: the runtime verifier's cheap progress
        # stamp (mpi_tpu/verify/deadlock.py) — a "blocked" rank whose
        # mailbox keeps receiving is matching-starved, not deadlocked,
        # and the confirm pass uses the stamp to tell the two apart
        self.deliveries = 0
        # receiver-side vector clock, attached by verify.enable(): the
        # consume scan merges each consumed stamp and runs the wildcard
        # race check against the pending alternates it can see under
        # this lock.  None outside verify mode (zero cost).
        self.clock = None

    def deliver(self, src: int, ctx: int, tag: int, payload: Any,
                stamp: Any = None) -> None:
        with self._cv:
            self._items.append((src, ctx, tag, payload, stamp))
            self.deliveries += 1
            self._cv.notify_all()

    def nudge(self) -> None:
        """Wake every waiter without delivering anything — the progress
        engine's stop path pops its parked thread out of wait_activity."""
        with self._cv:
            self._cv.notify_all()

    def wait_activity(self, seen: int, timeout: float) -> int:
        """Park until the delivery count moves past ``seen`` (or timeout);
        returns the current count.  The progress engine's doorbell on
        transports whose deliveries arrive from other threads (socket
        reader threads, local-world peer sends).  Raises TransportError
        once closed so a parked engine exits instead of spinning."""
        with self._cv:
            if self.deliveries == seen and not self._closed:
                self._cv.wait(timeout)
            if self._closed:
                raise TransportError("transport closed while parked")
            return self.deliveries

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    @staticmethod
    def _matches(item, source: int, ctx, tag: int) -> bool:
        s, c, t = item[0], item[1], item[2]
        if c != ctx:
            return False
        if source != ANY_SOURCE and s != source:
            return False
        if tag == ANY_TAG:
            return t >= 0  # wildcards never match internal (negative) tags
        return t == tag

    def _scan_locked(self, source: int, ctx, tag: int,
                     consume: bool) -> Optional[Tuple[Any, int, int]]:
        """Oldest matching message as (payload, src, tag); pops iff consume.
        Caller holds the lock."""
        for i, item in enumerate(self._items):
            if self._matches(item, source, ctx, tag):
                s, _, t, payload, stamp = item
                if consume:
                    self._items.pop(i)
                    if self.clock is not None and stamp is not None:
                        # verify mode: merge the consumed stamp; for a
                        # USER wildcard receive, every other pending
                        # message this scan could equally have matched
                        # is a race candidate (internal negative tags
                        # are exact-matched and never race)
                        wild = (source == ANY_SOURCE
                                and (tag >= 0 or tag == ANY_TAG))
                        alts = ([(it[0], it[4]) for it in self._items
                                 if self._matches(it, ANY_SOURCE, ctx, tag)
                                 and it[0] != s and it[4] is not None]
                                if wild else ())
                        self.clock.note_consume(s, t, stamp, alts, wild)
                return payload, s, t
        return None

    def _blocking_scan(self, source: int, ctx, tag: int, consume: bool,
                       timeout: Optional[float], what: str) -> Tuple[Any, int, int]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                hit = self._scan_locked(source, ctx, tag, consume)
                if hit is not None:
                    return hit
                if self._closed:
                    raise TransportError(
                        f"transport closed while waiting for {what}"
                        f"(source={source}, ctx={ctx}, tag={tag})"
                    )
                if deadline is None:
                    self._cv.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        pending = [(s, c, t) for s, c, t, _, _ in
                                   self._items[:16]]
                        raise RecvTimeout(
                            f"{what}(source={source}, ctx={ctx}, tag={tag}) timed "
                            f"out after {timeout}s; pending={pending}"
                        )
                    self._cv.wait(remaining)

    def match(
        self, source: int, ctx, tag: int, timeout: Optional[float] = None
    ) -> Tuple[Any, int, int]:
        """Block until the oldest message matching (source, ctx, tag) arrives;
        return (payload, src, tag)."""
        return self._blocking_scan(source, ctx, tag, True, timeout, "recv")

    def poll(self, source: int, ctx, tag: int) -> Optional[Tuple[Any, int, int]]:
        """Non-blocking match: pop and return the oldest matching message, or
        None if nothing matches right now (MPI_Test substrate).  Raises
        TransportError on a closed, unmatched mailbox so polling loops fail
        like blocking receives do instead of spinning forever."""
        with self._lock:
            hit = self._scan_locked(source, ctx, tag, True)
            if hit is None and self._closed:
                raise TransportError(
                    f"transport closed while polling recv(source={source}, "
                    f"ctx={ctx}, tag={tag})"
                )
            return hit

    def peek_nowait(
        self, source: int, ctx, tag: int
    ) -> Optional[Tuple[int, int, Optional[int]]]:
        """Non-blocking, non-consuming scan: (src, tag, nbytes) of the
        oldest match, or None (MPI_Iprobe substrate — keeps FIFO
        intact).  ``nbytes`` is the queued payload's size when it is a
        sized buffer (the message IS local at peek time, so the probe
        can honor the probe+get_count+recv sizing idiom — ADVICE r4
        #2), None for opaque objects."""
        with self._lock:
            hit = self._scan_locked(source, ctx, tag, False)
            if hit is None and self._closed:
                raise TransportError(
                    f"transport closed while probing (source={source}, "
                    f"ctx={ctx}, tag={tag})"
                )
            return (None if hit is None
                    else (hit[1], hit[2], payload_nbytes(hit[0])))

    def peek(self, source: int, ctx, tag: int,
             timeout: Optional[float] = None
             ) -> Tuple[int, int, Optional[int]]:
        """Like match() but WITHOUT consuming: block until a matching message
        is queued and return its (src, tag, nbytes) — MPI_Probe
        semantics (see peek_nowait for the count)."""
        p, s, t = self._blocking_scan(source, ctx, tag, False, timeout,
                                      "probe")
        return s, t, payload_nbytes(p)

    def count_matching(self, source: int, ctx, tag: int) -> int:
        """Number of queued messages matching (source, ctx, tag) right
        now — the recv-steering registry's activation BACKLOG: frames
        delivered before a user channel was activated were never
        counted, so the first posted user buffer seeds its pairing lag
        with this count (mpi_tpu/recvpool.py note_post_user)."""
        with self._lock:
            return sum(1 for item in self._items
                       if self._matches(item, source, ctx, tag))

    def pending_summary(self) -> List[Tuple[int, int, int]]:
        with self._lock:
            return [(s, c, t) for s, c, t, _, _ in self._items[:16]]

    def drain(self) -> List[Tuple[int, int, int]]:
        """Return and clear all pending (src, ctx, tag) — used by the finalize
        'unexpected message' check (sanitizer analogue, SURVEY.md §5)."""
        with self._lock:
            items = [(s, c, t) for s, c, t, _, _ in self._items]
            self._items.clear()
            return items


class Transport(ABC):
    """Moves payloads between world ranks; owns a Mailbox for incoming
    traffic.

    Fault taxonomy (ISSUE 10): transports distinguish LINK faults (a
    connection-level hiccup between two live processes — healed
    transparently where the transport has connections to heal, see
    transport/socket.py + mpi_tpu/resilience.py) from PEER faults (the
    process on the other end is gone — surfaced as TransportError and
    wrapped into ProcFailedError by the FT layer).  Transports without
    a connection link have no link-fault class: shm's "link" is a
    mapped ring (memory does not reset mid-frame), the local transport's
    is a queue append."""

    # True only for transports that deliver payloads BY REFERENCE (the
    # in-process local transport with copy_payloads=False): callers that
    # honor MPI's buffer-reuse idiom (persistent requests) must snapshot
    # mutable payloads themselves.  Serializing transports copy anyway.
    aliases_payloads = False

    # Preferred pipeline-segment size of the segmented collective engine
    # for THIS transport's data plane (communicator._seg_exchange), used
    # when the ``collective_segment_bytes`` mpit cvar is 0 (= auto).  The
    # right value is a transport property: shm must keep window*segment
    # inside its fixed ring capacity, while loopback TCP already overlaps
    # via kernel socket buffers and instead wants few, large frames (the
    # per-frame host costs dominate it at bandwidth sizes).
    coll_segment_hint = 256 << 10

    # True only for transports whose ranks share a POSIX shared-memory
    # domain (the shm transport): unlocks the coll/sm collective arena
    # (mpi_tpu/coll_sm.py — ``algorithm="sm"`` and the ``auto`` routing).
    # Deliberately NOT inherited by wrappers like FaultyTransport, whose
    # point is to exercise the wire paths.
    supports_coll_sm = False

    # Receive-side rendezvous steering (mpi_tpu/recvpool.py, ISSUE 17):
    # True only for transports whose reader can land a frame's body
    # directly in a posted irecv's buffer (the socket transport).  Such
    # transports also expose ``recv_registry`` (a PostedRecvRegistry);
    # the communicator registers posted internal receives with it and
    # prices the recv-side store copies steering removes
    # (payload_copies).  Deliberately NOT inherited by wrappers like
    # FaultyTransport: message-level chaos rewrites delivery order, so
    # the wrapper must never advertise the inner reader's pairing.
    recv_steering = False
    recv_registry = None

    # Verify-mode vector clock (mpi_tpu/verify/vclock.py), attached by
    # verify.enable() together with mailbox.clock.  Send paths test
    # exactly ``verify_clock is None`` (the off-mode cost contract) and
    # under verify either wrap the wire ctx (remote framing) or pass
    # tick_send()'s stamp straight to mailbox.deliver (same-process
    # deliveries, which never reserialize the ctx).
    verify_clock = None

    def __init__(self, world_rank: int, world_size: int) -> None:
        self.world_rank = world_rank
        self.world_size = world_size
        self.mailbox = Mailbox()
        # Elastic-membership generation (mpi_tpu/membership.py): the
        # monotone epoch this process believes its world is in.  Bumped
        # by shrink() (in survivor lockstep, riding the agreement) and
        # by membership.survivor_transition(); stamped into transport
        # hellos so a stale-epoch straggler is rejected loudly
        # (EpochSkewError) instead of cross-wiring two generations.
        self.epoch = 0
        # world rank -> minimum endpoint epoch acceptable when (re)
        # connecting to that peer: set to the transition epoch for
        # REPLACED slots, so a survivor re-handshaking can never adopt
        # the dead incarnation's leftover endpoints.
        self.min_peer_epoch: Dict[int, int] = {}

    @abstractmethod
    def send(self, dest: int, ctx, tag: int, payload: Any) -> None:
        """Buffered (non-blocking w.r.t. the receiver) send to world rank
        ``dest``.  FIFO order per (self, dest) channel is guaranteed.
        ``ctx`` is any hashable communicator-context id (the tree-path tuples
        allocated by Communicator.split/dup — collision-free by construction)."""

    def recv(
        self, source: int, ctx, tag: int, timeout: Optional[float] = None
    ) -> Tuple[Any, int, int]:
        return self.mailbox.match(source, ctx, tag, timeout=timeout)

    # Nonblocking/probe entry points live on the Transport (not reached into
    # the mailbox by callers) so decorator transports (tracing, fault
    # injection) see every completion path.

    def poll(self, source: int, ctx, tag: int) -> Optional[Tuple[Any, int, int]]:
        return self.mailbox.poll(source, ctx, tag)

    def peek(self, source: int, ctx, tag: int,
             timeout: Optional[float] = None
             ) -> Tuple[int, int, Optional[int]]:
        return self.mailbox.peek(source, ctx, tag, timeout=timeout)

    def peek_nowait(
        self, source: int, ctx, tag: int
    ) -> Optional[Tuple[int, int, Optional[int]]]:
        return self.mailbox.peek_nowait(source, ctx, tag)

    def membership_invalidate(self, dead: Sequence[int]) -> None:
        """Epoch-transition hook (mpi_tpu/membership.py): drop every
        cached endpoint to the given world ranks so the next send
        re-handshakes against the rendezvous dir (where a replacement
        publishes fresh endpoints under the new epoch).  Base: nothing
        cached per peer.  Transports with per-peer connections/rings
        override; the override must exclude in-flight senders (take the
        per-dest send lock) before tearing an endpoint down — and a
        transport with per-peer LINK-RESILIENCE state (sequenced
        streams, retained replay windows: the socket transport,
        mpi_tpu/resilience.py) must purge that state too, because a
        replaced slot's rejoiner starts fresh streams at seq 1 and must
        never be handed the corpse's replay or dedup horizon."""

    def progress_park(self, timeout: float) -> bool:
        """Progress-engine park hook (mpi_tpu/progress.py): block until
        incoming activity or ``timeout``; True iff anything arrived.
        Base implementation parks on the Mailbox condition variable —
        correct for every transport whose deliveries are pushed by
        other threads (socket reader threads, local-world peer sends).
        Transports that need a consumer to PULL data (shm rings)
        override this to drive their own progress machinery, parked on
        a real doorbell instead of spinning.  Raises TransportError
        once the transport closes, which is the engine's exit signal."""
        seen = self.mailbox.deliveries
        return self.mailbox.wait_activity(seen, timeout) != seen

    def close(self) -> None:
        self.mailbox.close()
