"""backend=tpu — MPI semantics over a jax.sharding.Mesh (SURVEY.md §7 M1-M2).

Public surface:
* :func:`run_spmd` / :func:`default_mesh` — run a portable MPI program as one
  SPMD trace over the device mesh.
* :class:`TpuCommunicator` — the Communicator bound to a mesh axis; fused XLA
  collectives plus hand-scheduled ppermute algorithms (ring /
  recursive-halving / tree / doubling / pairwise).
"""

from .communicator import SpmdSemanticsError, TpuCommunicator
from .runner import default_mesh, run_spmd
from . import collectives

__all__ = [
    "TpuCommunicator",
    "SpmdSemanticsError",
    "run_spmd",
    "default_mesh",
    "collectives",
]
