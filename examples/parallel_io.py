"""MPI-IO demo: ranks cooperatively write one matrix file.

Each rank owns a column block of an 8x8 float32 matrix, described by a
subarray filetype view; a collective write_at_all assembles the file in
one aggregated sweep; every rank then reads the full matrix back and
checks it, and appends a log line through the shared file pointer.  Run:

    python -m mpi_tpu.launcher -n 4 examples/parallel_io.py
"""

import os
import sys

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mpi_tpu
from mpi_tpu import datatypes as dt
from mpi_tpu import io as mio

N = 8
comm = mpi_tpu.COMM_WORLD
cols = N // comm.size
path = os.path.join(os.environ.get("MPI_TPU_RDV", "/tmp"), "matrix.bin")

# write my column block through a subarray view, collectively
ft = dt.type_create_subarray([N, N], [N, cols], [0, cols * comm.rank],
                             np.float32)
f = mio.file_open(comm, path, mio.MODE_CREATE | mio.MODE_RDWR, shared=True)
f.set_view(etype=np.float32, filetype=ft)
mine = np.full(N * cols, float(comm.rank + 1), np.float32)
f.write_at_all(0, mine)

# read the whole matrix back through a plain view and check every block
f.set_view(etype=np.float32)
m = f.read_at_all(0, N * N).reshape(N, N)
for r in range(comm.size):
    assert np.all(m[:, r * cols:(r + 1) * cols] == r + 1), m
comm.barrier()

# shared-pointer log records: disjoint by construction, any order
f.set_view(disp=N * N * 4, etype=np.uint8)
f.write_shared(np.frombuffer(f"rank{comm.rank} ok;".encode(), np.uint8))
comm.barrier()
if comm.rank == 0:
    tail = bytes(f.read_at(0, f.get_size() - N * N * 4))
    assert tail.count(b"ok;") == comm.size
    print(f"matrix verified by all ranks; log = {tail.decode()}")
f.close()
