"""Dedicated async progress engine — background completion for
nonblocking operations (MPICH ``MPICH_ASYNC_PROGRESS`` analogue).

Why: the segmented zero-copy engine and the i-collectives exist to
overlap compute with communication, but without this module nonblocking
operations progress only while some caller thread polls or waits —
overlap is *caller-financed*.  Concretely, on the shm transport a rank
whose threads are all computing drains its incoming rings at the helper
thread's 20Hz last-resort cadence, so a symmetric exchange larger than
the ring stalls in ~50ms quanta; and a posted ``irecv`` never completes
(``req._done`` never flips) until somebody calls ``wait``/``test``.

``progress=thread`` starts ONE daemon progress thread per world
(:class:`ProgressEngine`, attached to the Transport) that

* **parks on the transport's doorbell** instead of spinning — the
  ``Transport.progress_park`` hook: the shared Mailbox condition
  variable on socket/local worlds (reader threads / peer sends are the
  doorbell), the native futex doorbell + inline ring drain on shm
  (``ShmTransport.progress_park``), so incoming frames are drained into
  the unexpected-message queue with ~µs latency even when no thread of
  this rank is receiving;
* **completes outstanding nonblocking requests** in the background:
  every posted ``_RecvRequest`` queue of every registered communicator
  is matched against the transport under the engine's completion lock
  (the one lock that serializes engine-side and caller-side completion
  — see ``try_complete``), so ``req._done`` flips without the caller;
* **advances the segmented engine's credit windows**: a completed
  pipeline receive runs its ``_on_complete`` callback
  (``communicator._SegSender.advance``) posting the next windowed send
  — ``_SEG_WINDOW`` credit advances without the caller being inside
  ``_seg_exchange``;
* **is itself a blocking waiter for the runtime verifier**: a rank
  stuck in a pure-polling drain loop (``MPI_Waitany`` over ``test()``)
  never enters a blocking wait, so it never published a pending-op
  entry and escaped deadlock detection (the PR-5 residual).  The engine
  observes the sustained empty polls, publishes an OR-set entry over
  the pending requests' sources on the rank's behalf, runs the wait-for
  analysis, and parks the resulting :class:`DeadlockError` where the
  polling paths (``Request.test``, ``iprobe``) re-raise it.

Off (the default, ``progress=none``) nothing here is imported on the
hot path: the entire feature is one ``_progress is None`` attribute
test per operation and the ``progress_*`` pvars stay 0 — asserted by
tests/test_progress.py and ``bench.py --verify-overhead --progress``.

Link faults (ISSUE 10): the engine is oblivious to socket link healing
by construction — engine-owned completions consume from the MAILBOX,
and the resilient link layer (mpi_tpu/resilience.py) delivers into the
mailbox only full, deduplicated, in-order frames regardless of how
many reconnect/replay rounds the wire needed.  A posted irecv whose
sender's connection is torn and rebuilt mid-flight completes normally
(tests/test_resilience.py::test_engine_owned_recv_survives_reconnect).

Cost model (README "Async progress"): the engine's wakeups are priced
by the ``progress_wakeups`` / ``progress_completions`` /
``progress_idle_parks`` pvars.  On a box with spare cores the engine
converts idle communication latency into compute/comm overlap; on an
oversubscribed box it competes with ranks for cycles and adds one
thread hop to blocking-receive latency — opt in per workload.

Enable: ``MPI_TPU_PROGRESS=thread`` in the environment (read by
``mpi_tpu.init``), ``run_local(..., progress="thread")``,
``python -m mpi_tpu.launcher --progress thread``, or the ``progress``
mpit cvar (the default mode new worlds pick up when none of the above
say otherwise).
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import List, Optional, Tuple

from . import mpit as _mpit
from .transport.base import ANY_SOURCE, TransportError

# Accepted modes of the ``progress`` cvar / MPI_TPU_PROGRESS env var /
# run_local(progress=...) / launcher --progress.
MODES = ("none", "thread")

# Process-wide default mode (mpit cvar ``progress``): what init() and
# run_local() use when neither the explicit argument nor the
# MPI_TPU_PROGRESS environment variable picks a mode.
_DEFAULT_MODE = "none"

# Longest idle park between bookkeeping passes: bounds how stale the
# engine's view of newly posted requests / stalled-poll episodes can be
# even if the transport doorbell never rings.
_PARK_SLICE_S = 0.25

# A pure-polling episode is "live" while the newest empty poll is at
# most this old; a caller that stopped polling (gave up, went back to
# computing) stops being published within one slice — an opportunistic
# poll between real work must never read as a blocked rank.
_POLL_FRESH_S = 1.0


def resolve_mode(explicit: Optional[str] = None) -> str:
    """The mode a new world should run: explicit argument beats the
    MPI_TPU_PROGRESS environment variable beats the ``progress`` cvar
    default."""
    import os

    mode = explicit or os.environ.get("MPI_TPU_PROGRESS") or _DEFAULT_MODE
    if mode not in MODES:
        raise ValueError(
            f"unknown progress mode {mode!r}; accepted: {list(MODES)}")
    return mode


def enable(comm):
    """Attach the per-world progress engine to ``comm`` (idempotent per
    transport — one engine, one thread, shared by every communicator
    derived from the transport; children created after this pick it up
    at construction)."""
    eng = getattr(comm._t, "_progress_engine", None)
    if eng is None:
        eng = ProgressEngine(comm._t)
        comm._t._progress_engine = eng
    comm._progress = eng
    eng.register(comm)
    return comm


class ProgressEngine:
    """One background progress thread per world (per Transport).

    Lock discipline: ``self.cv`` (one condition + lock) serializes ALL
    request completion — the engine's background pass and the callers'
    opportunistic ``try_complete`` both hold it around the
    poll-and-complete step, so a message can never be consumed twice
    and a request can never be completed by two threads.  Completion
    callbacks (segmented-engine send-window credit) run OUTSIDE the
    lock: a callback may block in a ring-full send, and the engine must
    never make callers wait on that.  The zero-copy pvar contracts are
    untouched by construction — completion consumes already-delivered
    mailbox payloads; the engine adds no wire traffic and no copies.
    """

    def __init__(self, transport) -> None:
        self.t = transport
        self.cv = threading.Condition(threading.RLock())
        self._comms: "weakref.WeakSet" = weakref.WeakSet()
        self._stop = threading.Event()
        # Sticky verifier verdict from a stalled-poll analysis: polling
        # completion paths (Request.test / iprobe / improbe via
        # _empty_poll_check) re-raise it — a deadlock is permanent, so
        # every later poll on this rank deserves the same diagnosis.
        self.pending_error: Optional[BaseException] = None
        self._last_progress = time.monotonic()
        # pure-polling episode state (verifier publication on behalf of
        # Waitany-style drain loops); _poll_req remembers the specific
        # state-machine collective the freshest empty poll was FOR, so
        # publication can use that call's exact OR-set (weakref: the
        # episode must never keep a completed request alive)
        self._last_empty_poll = 0.0
        self._poll_req: Optional["weakref.ref"] = None
        # Explicit drain-loop scope (MPI_Waitany / MPI_Waitsome): the
        # exact request set the caller's poll loop is spinning over.
        # When set, stalled-poll publication names THIS set's pending
        # sources — never the union over every tracked request in the
        # world (which would accuse ranks the drain loop isn't even
        # waiting for).  A tuple snapshot, not the caller's list: the
        # caller may mutate its list while the engine reads.
        self._poll_scope: Optional[tuple] = None
        self._episode_start: Optional[float] = None
        self._episode_block = 0
        self._published = False
        self.thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"mpi-tpu-progress-{transport.world_rank}")
        self.thread.start()

    # -- registration / caller-side hooks ----------------------------------

    def register(self, comm) -> None:
        """Track a communicator whose posted irecv queues the engine
        completes.  Called at enable() and from _irecv_internal (cheap:
        WeakSet.add is idempotent)."""
        with self.cv:
            self._comms.add(comm)

    def note_empty_poll(self, req=None) -> None:
        """A nonblocking completion path came up empty (Request.test /
        iprobe / improbe): the evidence a pure-polling drain loop
        exists.  Publication on the rank's behalf needs recent AND
        sustained polls — a single opportunistic poll never starts an
        episode on its own (see _maybe_publish_stalled).

        ``req`` (a schedule-state-machine collective, mpi_tpu/nbc.py)
        identifies WHICH call is being polled: the engine then
        publishes that call's exact pending OR-set — the sources this
        Waitany-style poll is actually stuck on — instead of the union
        over ALL tracked requests (ISSUE 12 verifier residual (d)).
        State-machine internals are untracked (no _vinfo), so without
        ``req`` a pure SM drain loop would otherwise have NO pending
        evidence at all and escape publication entirely."""
        self._last_empty_poll = time.monotonic()
        self._poll_req = None if req is None else weakref.ref(req)

    def enter_poll_scope(self, requests):
        """Scope stalled-poll publication to ONE drain call's request
        set (MPI_Waitany/Waitsome).  While a scope is installed, a
        published 'waitany-poll' entry's OR-set is computed from these
        requests only — their exact pending sources — instead of the
        union over every tracked request in the world.  Returns the
        previous scope so nested drains restore it (try/finally)."""
        prev = self._poll_scope
        self._poll_scope = tuple(requests)
        return prev

    def exit_poll_scope(self, prev) -> None:
        self._poll_scope = prev

    def check_error(self) -> None:
        if self.pending_error is not None:
            raise self.pending_error

    def stop(self) -> None:
        self._stop.set()
        with self.cv:
            self.cv.notify_all()
        # the nonblocking-collective fold pool (mpi_tpu/nbc.py) is
        # engine-owned machinery: its workers die with the engine, or a
        # process churning many worlds would accumulate 2 parked
        # threads per finalized world
        pool = getattr(self.t, "_nbc_fold_pool", None)
        if pool is not None:
            self.t._nbc_fold_pool = None
            pool.stop()
        # pop the thread out of its transport park promptly: closing
        # the transport does this too, but explicit stops (run_local
        # teardown) may keep the transport alive for other use
        try:
            self.t.mailbox.nudge()
        except Exception:  # noqa: BLE001 - teardown best effort
            pass

    # -- completion (the one locked step) ----------------------------------

    def try_complete(self, req) -> List:
        """Caller-side completion attempt for ``req``'s queue: complete
        posted requests head-first (MPI posted-order matching) while
        the transport has matching traffic.  Caller holds self.cv.
        Returns the completion callbacks to run after RELEASING the
        lock."""
        cbs: List = []
        q = req._queue
        while not req._done and q:
            head = q[0]
            hit = head._poll_once()
            if hit is None:
                break
            head._complete(hit[0])
            self._note_complete(head, cbs)
        return cbs

    def _note_complete(self, req, cbs: List) -> None:
        self._last_progress = time.monotonic()
        vw = getattr(req._comm._t, "_verify_world", None)
        if vw is not None:
            # a background completion is real progress: stamp it so a
            # published 'blocked'/'polling' entry retracts promptly
            vw.note_progress()
        cb = req._on_complete
        if cb is not None:
            cbs.append(cb)

    def _complete_pass(self) -> Tuple[List, int]:
        """One background pass over every registered communicator's
        posted irecv queues.  Returns (callbacks, completed_count)."""
        cbs: List = []
        done = 0
        with self.cv:
            for comm in list(self._comms):
                with comm._lock:
                    queues = [q for q in comm._irecv_queues.values() if q]
                for q in queues:
                    while q:
                        head = q[0]
                        hit = head._poll_once()
                        if hit is None:
                            break
                        head._complete(hit[0])
                        self._note_complete(head, cbs)
                        done += 1
            if done:
                self.cv.notify_all()
        return cbs, done

    # -- the loop ----------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                cbs, done = self._complete_pass()
            except TransportError:
                return  # transport closed under us: world is exiting
            _mpit.count(progress_wakeups=1,
                        progress_completions=done)
            for cb in cbs:
                # credit-window advancement; send failures are recorded
                # on the _SegSender and re-raised at the caller's next
                # fold/drain step, never swallowed here
                cb()
            if done:
                continue  # drained something: immediately look again
            self._maybe_publish_stalled(time.monotonic())
            if self._stop.is_set():
                return
            try:
                if not self.t.progress_park(_PARK_SLICE_S):
                    _mpit.count(progress_idle_parks=1)
            except TransportError:
                return

    # -- verifier publication on behalf of pure-polling drain loops --------

    def _pending_tracked(self) -> List[Tuple[object, object]]:
        """(comm, request) pairs for every posted-and-incomplete
        USER-level request (the ones with a verifier tracking record):
        the wait set a polling drain loop is spinning on.  Caller holds
        self.cv."""
        out = []
        for comm in list(self._comms):
            with comm._lock:
                queues = [q for q in comm._irecv_queues.values() if q]
            for q in queues:
                for req in list(q):
                    if req._vinfo is not None:
                        out.append((comm, req))
        return out

    def _maybe_publish_stalled(self, now: float) -> None:
        vw = getattr(self.t, "_verify_world", None)
        if vw is None or self.pending_error is not None:
            return
        if vw.active_waiters > 0:
            # a REAL blocking wait is in flight: the rank's single board
            # entry is that wait's to publish (it will stall-publish and
            # analyze itself) — two publishers alternating entries would
            # flap the stamps and peers' confirm pass could never close
            self._end_episode(vw)
            return
        if now - self._last_empty_poll > _POLL_FRESH_S:
            # nobody is polling (computing, or gave up): never publish
            # — an idle posted irecv proves nothing about being stuck,
            # and publishing it would false-positive on compute-overlap
            # programs (the same rule _empty_poll_check documents for
            # single polls)
            self._end_episode(vw)
            return
        # Precedence of pending-set evidence (most exact first):
        # 1. an installed poll scope (MPI_Waitany's own request list) —
        #    the drain loop told us exactly what it is spinning on;
        # 2. the freshest poll's own request when it is a schedule state
        #    machine (mpi_tpu/nbc.py) — that call's exact pending
        #    OR-set, whose internal receives are untracked below;
        # 3. the union over all tracked posted requests (the legacy
        #    conservative fallback for anonymous polling loops).
        sm = None
        scope_info = None
        scope = self._poll_scope
        if scope is not None:
            with self.cv:  # serialize _done reads with completion
                live = [r for r in scope
                        if not getattr(r, "_retired", False)
                        and not getattr(r, "_done", False)
                        and getattr(r, "_error", None) is None]
                scope_targets = set()
                for r in live:
                    if hasattr(r, "_pending_world_srcs"):
                        scope_targets.update(r._pending_world_srcs())
                    elif hasattr(r, "_source"):
                        c = r._comm
                        if r._source == ANY_SOURCE:
                            scope_targets.update(
                                w for w in c._group
                                if w != c._t.world_rank)
                        else:
                            scope_targets.add(c._world(r._source))
            if not scope_targets:
                self._end_episode(vw)
                return
            scope_info = (live, scope_targets)
        if scope_info is None:
            ref = self._poll_req
            if ref is not None:
                cand = ref()
                if (cand is not None and not cand._done
                        and cand._error is None):
                    sm = cand
                else:
                    self._poll_req = None
            if sm is not None:
                with self.cv:  # serialize the _done reads with completion
                    sm_targets = sm._pending_world_srcs()
                if not sm_targets:
                    self._end_episode(vw)
                    return
            else:
                with self.cv:
                    pending = self._pending_tracked()
                if not pending:
                    self._end_episode(vw)
                    return
        if self._episode_start is None:
            self._episode_start = now
            self._episode_block = vw.begin_block()
            return
        if now - self._episode_start < vw.stall_timeout_s:
            return
        if scope_info is not None:
            live, targets = scope_info
            anchor = next((r for r in live if hasattr(r, "_comm")), None)
            if anchor is None:
                return
            comm = anchor._comm
            tag = getattr(anchor, "_tag", -1)  # ANY_TAG when unknowable
            coll = getattr(anchor, "kind", None)
            site = "<waitany drain>"
            for r in live:
                vi = getattr(r, "_vinfo", None)
                if vi is not None and vi.site:
                    site = vi.site
                    break
        elif sm is not None:
            comm, tag, coll = sm._comm, sm._tag, sm.kind
            site = f"<nbc:{sm.kind} state machine>"
            targets = set(sm_targets)
        else:
            comm, first = pending[0]
            tag, coll = first._tag, None
            site = (first._vinfo.site if first._vinfo is not None
                    else "<polling loop>")
            targets = set()
            for c, req in pending:
                if req._source == ANY_SOURCE:
                    targets.update(w for w in c._group
                                   if w != c._t.world_rank)
                else:
                    targets.add(c._world(req._source))
        if not targets:
            return
        if vw.published and not self._published:
            # a REAL blocking wait owns this rank's board entry (and its
            # own analysis cadence): publishing over it would flap the
            # entry and a later _end_episode would retract a live wait's
            # entry mid-confirmation
            return
        from .verify import deadlock as _vdl

        self._published = True
        try:
            # publishes the entry (OR semantics: ANY pending source
            # progressing would release the drain loop) and runs the
            # wait-for analysis + confirm pass, exactly like a blocking
            # wait's slice — the engine IS this rank's blocking waiter
            _vdl.check_stalled(
                vw, comm, tuple(sorted(targets)), "OR", tag,
                "waitany-poll", coll, site, self._episode_block)
        except _vdl.DeadlockError as e:
            self.pending_error = e
            with self.cv:
                self.cv.notify_all()

    def _end_episode(self, vw) -> None:
        if self._published:
            self._published = False
            vw.clear_published()
        self._episode_start = None
