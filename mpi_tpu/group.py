"""Process groups — MPI_Group and the group→communicator constructors [S].

A :class:`Group` is an ordered, duplicate-free list of ranks *of a parent
communicator* (MPI's "group of processes", anchored to the comm it was taken
from).  Group operations are pure host-side bookkeeping on every backend —
exactly the "rank/size bookkeeping stays intact above the plugin boundary"
property of the reference (BASELINE.json:5); only ``Communicator.create``
(MPI_Comm_create_group) communicates.

MPI spelling map:
    comm.group()                → MPI_Comm_group
    g.incl / g.excl             → MPI_Group_incl / MPI_Group_excl
    g.union / g.intersection / g.difference
                                → MPI_Group_union / _intersection / _difference
    g.rank_of(comm_rank)        → MPI_Group_rank (via translate)
    g.translate(positions, g2)  → MPI_Group_translate_ranks
    comm.create(g)              → MPI_Comm_create_group
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class Group:
    """Ordered set of parent-communicator ranks (MPI_Group analogue)."""

    __slots__ = ("ranks",)

    def __init__(self, ranks: Sequence[int]):
        ranks = tuple(int(r) for r in ranks)
        if len(set(ranks)) != len(ranks):
            raise ValueError(f"group ranks must be distinct, got {list(ranks)}")
        if any(r < 0 for r in ranks):
            raise ValueError(f"group ranks must be >= 0, got {list(ranks)}")
        self.ranks: Tuple[int, ...] = ranks

    @property
    def size(self) -> int:
        return len(self.ranks)

    def __eq__(self, other) -> bool:
        return isinstance(other, Group) and self.ranks == other.ranks

    def __hash__(self) -> int:
        return hash(self.ranks)

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"Group({list(self.ranks)})"

    # -- MPI_Group_* constructors -----------------------------------------

    def incl(self, positions: Sequence[int]) -> "Group":
        """MPI_Group_incl: the listed *positions of this group*, in the
        listed order (also the reorder primitive)."""
        return Group([self.ranks[self._check_pos(p)] for p in positions])

    def excl(self, positions: Sequence[int]) -> "Group":
        """MPI_Group_excl: drop the listed positions, keep the rest in order."""
        drop = {self._check_pos(p) for p in positions}
        return Group([r for i, r in enumerate(self.ranks) if i not in drop])

    def union(self, other: "Group") -> "Group":
        """MPI_Group_union: self's ranks, then other's not already present."""
        seen = set(self.ranks)
        return Group(list(self.ranks) + [r for r in other.ranks if r not in seen])

    def intersection(self, other: "Group") -> "Group":
        """MPI_Group_intersection: self's ranks also in other, self's order."""
        keep = set(other.ranks)
        return Group([r for r in self.ranks if r in keep])

    def difference(self, other: "Group") -> "Group":
        """MPI_Group_difference: self's ranks not in other, self's order."""
        drop = set(other.ranks)
        return Group([r for r in self.ranks if r not in drop])

    # -- queries -----------------------------------------------------------

    def rank_of(self, comm_rank: int) -> Optional[int]:
        """Position of a parent-comm rank in this group (MPI_Group_rank for
        the calling process when passed ``comm.rank``); None = MPI_UNDEFINED."""
        if not isinstance(comm_rank, (int, np.integer)):
            raise TypeError(
                "Group.rank_of needs a concrete integer rank; inside an SPMD "
                "trace the rank is traced — group membership is per-rank "
                "control flow, which has no SPMD analogue (use host-side "
                "bookkeeping or comm.create(group) instead)")
        try:
            return self.ranks.index(int(comm_rank))
        except ValueError:
            return None

    def translate(self, positions: Sequence[int],
                  other: "Group") -> List[Optional[int]]:
        """MPI_Group_translate_ranks: map positions in this group to positions
        in ``other`` (None where absent)."""
        return [other.rank_of(self.ranks[self._check_pos(p)]) for p in positions]

    def _check_pos(self, p: int) -> int:
        p = int(p)
        if not (0 <= p < self.size):
            raise ValueError(f"position {p} out of range for group size {self.size}")
        return p
