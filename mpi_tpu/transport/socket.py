"""TCP socket + pickle transport — the reference's L1, reimplemented.

SURVEY.md §2 component #2 [B: "the existing socket/pickle path",
BASELINE.json:5]: per-pair TCP connections, length-prefixed pickle frames,
blocking matched receive.  This backend exists for two reasons (SURVEY.md §4
item 4): it is the CPU fallback, and it is the source-compatibility proof —
the same user program must run here and on backend=tpu.

Wire format per message: a fixed header ``!QQQ`` = (flags|payload_len,
seq, ack) followed by ``payload_len`` body bytes — either a pickle of
the envelope ``(ctx, tag, obj)``, or (RAW_FLAG set, see
transport/codec.py) a raw-array frame whose numpy payload is sent
straight from / received straight into the array buffer, never pickled.
``seq`` is the per-destination sequence number of the resilient link
layer (mpi_tpu/resilience.py): the sender retains a bounded window of
unacked frames, the receiver delivers contiguously and dedups replays,
and ``ack`` piggybacks the cumulative delivery high-water mark of the
REVERSE stream on every frame (a header-only ``_ACK_FLAG`` control
frame carries it when no data flows the other way).  A torn connection
is therefore rebuilt without losing or duplicating frames: the hello
handshake answers with ``resume(last delivered seq)`` and the sender
replays only what the receiver never got.  The context id is an
arbitrary hashable (tree-path tuple), so it rides inside the meta
pickle rather than a fixed-width header field.  The sender's world rank
is established once per connection by a hello frame, not repeated per
message.  Rank discovery is file-based rendezvous: each rank binds an
OS-assigned port and publishes it as ``<rdv>/port.<rank>``; peers poll.
The launcher (mpi_tpu/launcher.py) provides the rendezvous directory.

Fault classification (ISSUE 10): a send-path ``OSError`` is a PEER
fault when the destination is in the FT suspect set or past its
heartbeat bound (``ft.WorldFT.link_suspect``) — that keeps today's
TransportError -> ProcFailedError path — and a LINK fault otherwise,
healed by a reconnect loop with exponential backoff + jitter bounded
by the ``link_retry_timeout_s`` cvar (default BELOW
``fault_detect_timeout_s``, so a dead peer still resolves to
ProcFailedError rather than a masked hang).  The receive side needs no
classification: a reader whose connection dies simply exits and keeps
the rx stream state — the sender reconnects and replays.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Dict, Optional, Tuple

from .. import bufpool as _bufpool
from .. import mpit as _mpit
from .. import recvpool as _recvpool
from .. import resilience as _resilience
from .. import telemetry as _telemetry
from ..errors import EpochSkewError
from ..resilience import LinkState, backoff_delays
from . import codec
from .base import Transport, TransportError

# Connection handshake: the connector sends (world rank, membership
# epoch), the acceptor answers with ITS epoch plus the last sequence
# number it contiguously delivered from this connector — the RESUME
# round of the resilient link layer (a fresh world answers 0; a
# reconnect prunes the retained window to that mark and replays the
# rest).  The epoch stamp is the elastic-membership guard
# (mpi_tpu/membership.py): after a shrink + rejoin every survivor
# requires replaced slots to present the new epoch, and a stale-epoch
# straggler (the falsely-suspected ousted rank) is rejected LOUDLY —
# EpochSkewError on the stale side — instead of cross-wiring two world
# generations through recycled rendezvous files.
_HELLO = struct.Struct("!iq")       # rank, epoch
_HELLO_ACK = struct.Struct("!qQ")   # acceptor's epoch, resume(last delivered)
_HEADER = struct.Struct("!QQQ")     # flags|payload_len, seq, cumulative ack
# Header word bit 62: a standalone cumulative-ack control frame (no
# body, seq 0, rides OUTSIDE the sequenced stream).  codec.RAW_FLAG is
# bit 63, so body lengths live in the low 62 bits.
_ACK_FLAG = 1 << 62
_LEN_MASK = _ACK_FLAG - 1
_HOST = "127.0.0.1"
# Grace window before an ahead-of-us peer epoch is declared a SKEW: an
# epoch transition is broadcast, and a healthy member whose reader/
# control thread is scheduler-starved may see a peer's new epoch
# milliseconds before applying its own bump.  A genuinely ousted
# straggler's epoch never catches up, so the diagnosis still fires —
# just one grace later.  mpit cvar: epoch_grace_s (sets the shm
# transport's twin too); env default: MPI_TPU_EPOCH_GRACE_S.
_EPOCH_GRACE_S = float(os.environ.get("MPI_TPU_EPOCH_GRACE_S", "2.0"))

# Ack-flusher cadence: once woken by a pending ack, batch for this long
# before flushing (coalesces a burst of deliveries into one control
# frame); the park itself is condition-variable based, so an idle
# transport costs a wakeup only every _ACK_IDLE_S — which is also the
# scan cadence of the idle-link keepalive probe (ISSUE 11 satellite).
_ACK_BATCH_S = 0.002
_ACK_IDLE_S = 0.25

# Scatter-gather batching (ISSUE 11): header + meta + body segments go
# out in ONE socket.sendmsg call instead of one sendall per part.
# Linux caps an iovec at IOV_MAX (1024) entries; frames with more
# segments simply take one extra syscall per batch.
_IOV_MAX = 1024
_HAS_SENDMSG = hasattr(socket.socket, "sendmsg")


def _sendmsg_views(conn: socket.socket, views) -> None:
    """Stream ``views`` (zero-copy byte buffers: memoryviews/bytes) with
    vectored ``sendmsg`` — one syscall per IOV_MAX batch in the common
    case, looping on partial writes (the kernel may accept fewer bytes
    than the iovec carries).  Counted in ``link_send_syscalls`` so the
    fewer-syscalls-per-frame contract is pvar-assertable."""
    if not _HAS_SENDMSG:  # pragma: no cover - non-sendmsg platform
        for v in views:
            conn.sendall(v)
            _mpit.count(link_send_syscalls=1)
        return
    idx, off = 0, 0
    n = len(views)
    while idx < n:
        if off:
            batch = [memoryview(views[idx])[off:]]
            batch.extend(views[idx + 1:idx + _IOV_MAX])
        else:
            batch = views[idx:idx + _IOV_MAX]
        sent = conn.sendmsg(batch)
        _mpit.count(link_send_syscalls=1)
        while sent > 0:
            rem = memoryview(views[idx]).nbytes - off
            if sent < rem:
                off += sent
                sent = 0
            else:
                sent -= rem
                idx += 1
                off = 0


class _LinkAbort(TransportError):
    """Internal healing-loop abort (transport closing / peer became a
    failure suspect mid-retry) — distinguishes the classified verdicts
    from an ordinary dial failure inside ``_establish_locked``."""


def _recv_exact2(sock: socket.socket,
                 n: int) -> Tuple[Optional[bytes], bool]:
    """``(data, torn)``: data is None on EOF/error; ``torn`` is True
    iff the stream died MID-READ (partial bytes already consumed) — a
    torn frame the resilient link must heal, as opposed to a clean
    between-reads close (graceful shutdown, membership departure).
    ISSUE 17 small fix: the old single-value spelling could not tell
    the two apart, so a mid-header disconnect was silently classified
    as a clean EOF."""
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None, len(buf) > 0
        if not chunk:
            return None, len(buf) > 0
        buf += chunk
    return bytes(buf), False


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Whole-read-or-None spelling (handshake callers, where a partial
    hello and a clean refusal are handled identically)."""
    return _recv_exact2(sock, n)[0]


def _recv_into_exact(sock: socket.socket, view: memoryview) -> bool:
    """Fill ``view`` completely from the socket — the receive-side
    zero-copy path (bytes land straight in the final array)."""
    got = 0
    n = len(view)
    while got < n:
        try:
            r = sock.recv_into(view[got:])
        except OSError:
            return False
        if r == 0:
            return False
        got += r
    return True


_HAS_RECVMSG_INTO = hasattr(socket.socket, "recvmsg_into")


def _recvmsg_into_views(sock: socket.socket, views) -> bool:
    """Fill every view in ``views`` completely with vectored
    ``recvmsg_into`` — the receive-side mirror of :func:`_sendmsg_views`
    (ISSUE 19 scatter-gather): one syscall per IOV_MAX batch in the
    common case, resuming mid-view on partial reads.  Counted in
    ``link_recv_syscalls``.  False on EOF/error (torn frame)."""
    views = [memoryview(v).cast("B") for v in views if v.nbytes]
    if not _HAS_RECVMSG_INTO:  # pragma: no cover - non-recvmsg platform
        for v in views:
            if not _recv_into_exact(sock, v):
                return False
        return True
    idx, off = 0, 0
    n = len(views)
    while idx < n:
        if off:
            batch = [views[idx][off:]]
            batch.extend(views[idx + 1:idx + _IOV_MAX])
        else:
            batch = views[idx:idx + _IOV_MAX]
        try:
            got = sock.recvmsg_into(batch)[0]
        except OSError:
            return False
        _mpit.count(link_recv_syscalls=1)
        if got == 0:
            return False
        while got > 0:
            rem = views[idx].nbytes - off
            if got < rem:
                off += got
                got = 0
            else:
                got -= rem
                idx += 1
                off = 0
    return True


class SocketTransport(Transport):
    # Loopback/intra-host TCP gets its exchange overlap from the kernel
    # socket buffers; what the engine's segmentation costs it is per-frame
    # host work (header + meta pickle + reader-thread delivery, all under
    # the GIL).  Measured on the host sweep (benchmarks/results/
    # host_sweep_post.json): 4MB segments beat 256KB by >3x at the 16MB
    # allreduce point, so prefer few, large frames here.
    coll_segment_hint = 4 << 20

    # Tuned-dispatch table key (mpi_tpu/tuning): rows measured on this
    # data plane.
    tuning_transport = "socket"

    # Receive-side rendezvous steering is live on this transport
    # (mpi_tpu/recvpool.py): the communicator registers posted internal
    # irecvs with ``recv_registry`` and prices the recv-side store
    # copies it can therefore remove.  Deliberately NOT inherited by
    # wrappers (transport/faulty.py) — see base.Transport.recv_steering.
    recv_steering = True

    def __init__(
        self,
        rank: int,
        size: int,
        rdv_dir: str,
        connect_timeout: float = 60.0,
        epoch: int = 0,
    ) -> None:
        super().__init__(rank, size)
        self.epoch = epoch  # a rejoiner is BORN into the current epoch
        self._rdv = rdv_dir
        self._connect_timeout = connect_timeout
        self._closing = False
        self._send_locks: Dict[int, threading.Lock] = {}
        self._conns: Dict[int, socket.socket] = {}
        self._conn_lock = threading.Lock()
        self._reader_threads = []
        # inbound connections by source rank: membership_invalidate
        # closes a replaced slot's readers so a stale incarnation (or a
        # reader accepted moments BEFORE the purge, whose captured
        # stream generation just went stale) dies promptly — the new
        # incarnation's sender then heals by reconnect + replay onto a
        # fresh-generation reader, losing nothing
        self._reader_conns: Dict[int, list] = {}
        # Resilient link layer (mpi_tpu/resilience.py): per-dest
        # sequenced streams + retained replay windows + cumulative acks.
        self._link = LinkState(size)
        # Posted-irecv registry (mpi_tpu/recvpool.py): pairs fresh
        # inbound frames with posted internal receives so the reader
        # can steer body bytes straight into the posted buffer.
        self.recv_registry = _recvpool.PostedRecvRegistry()
        # last successful data/probe write per destination — what the
        # idle-link keepalive (ISSUE 11, link_keepalive_s cvar) scans
        # to find connections worth probing
        self._last_send: Dict[int, float] = {}
        # Chaos hooks (transport/faulty.py link-fault injection): a
        # callable (dest, stage) fired on the send path ('pre' = before
        # any byte of a frame, 'mid' = between header and body), and a
        # countdown of incoming connections the acceptor drops after
        # reading the hello (exercises the connector's retry).
        self._link_fault_hook = None
        self._accept_drop_n = 0

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((_HOST, 0))
        self._listener.listen(size + 4)
        port = self._listener.getsockname()[1]
        tmp = os.path.join(rdv_dir, f".port.{rank}.tmp")
        with open(tmp, "w") as f:
            f.write(str(port))
        os.replace(tmp, os.path.join(rdv_dir, f"port.{rank}"))

        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"mpi-tpu-accept-{rank}", daemon=True
        )
        self._accept_thread.start()
        # Ack flusher: cumulative acks ride every data frame for free
        # (piggyback), but a one-way stream (gather fan-in, a pure
        # producer) would never ack — and the peer's retained window
        # would fill.  This daemon parks on the link state's condition
        # and flushes standalone ACK control frames for sources whose
        # delivery mark moved past the last ack on the wire.
        self._ack_thread = threading.Thread(
            target=self._ack_flush_loop,
            name=f"mpi-tpu-linkack-{rank}", daemon=True)
        self._ack_thread.start()

    # -- incoming ----------------------------------------------------------

    def _accept_loop(self) -> None:
        # accept ONLY; the hello/ack handshake runs in the per-
        # connection thread — a connector that stalls mid-hello (or a
        # scheduler-starved handshake on a loaded box) must never
        # serialize every OTHER peer's connection setup behind it
        while not self._closing:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._handshake_and_read, args=(conn,),
                name=f"mpi-tpu-reader-{self.world_rank}", daemon=True)
            # prune finished readers while appending: resident-server
            # worlds accept reconnects at every epoch transition, and
            # an append-only list would grow for the process lifetime
            self._reader_threads = [r for r in self._reader_threads
                                    if r.is_alive()]
            self._reader_threads.append(t)
            t.start()

    def _handshake_and_read(self, conn: socket.socket) -> None:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        hello = _recv_exact(conn, _HELLO.size)
        if hello is None:
            conn.close()
            return
        src, peer_epoch = _HELLO.unpack(hello)
        if self._accept_drop_n > 0:
            # injected accept-side drop (link chaos): vanish without an
            # ack — the connector's bounded retry loop must recover
            self._accept_drop_n -= 1
            conn.close()
            return
        try:
            # always answer with our epoch FIRST: a rejected stale
            # connector needs it to diagnose (EpochSkewError) rather
            # than see an unexplained dead channel.  The resume field
            # tells a RE-connecting peer what we already delivered, so
            # it replays only the frames we never got.
            conn.sendall(_HELLO_ACK.pack(self.epoch,
                                         self._link.delivered(src)))
        except OSError:
            conn.close()
            return
        if peer_epoch < self.min_peer_epoch.get(src, 0):
            # a dead-and-replaced slot's OLD incarnation dialing in:
            # admitting its reader would cross-wire two generations
            _mpit.count(epoch_skews=1)
            conn.close()
            return
        # capture the stream generation: if this slot is purged while
        # we read (membership replacement), every later ack/frame on
        # this connection no-ops instead of poisoning the fresh streams
        # — and the purge CLOSES this connection (see _reader_conns),
        # so a legitimate new incarnation whose hello raced the purge
        # reconnects and replays instead of streaming into the fence
        with self._conn_lock:
            conns = self._reader_conns.setdefault(src, [])
            conns[:] = [c for c in conns if c.fileno() >= 0]
            conns.append(conn)
        try:
            self._reader_loop(conn, src, self._link.peer_gen(src))
        finally:
            with self._conn_lock:
                try:
                    self._reader_conns.get(src, []).remove(conn)
                except ValueError:
                    pass

    def _note_torn(self, src: int) -> None:
        """A connection died MID-FRAME (partial header/meta/body):
        count it — resilience heals it by replay, but a silent drop
        here would hide the class of fault from diagnosis entirely."""
        _mpit.count(link_torn_frames=1)
        rec = _telemetry.REC
        if rec is not None:
            rec.emit("link", "torn_frame", attrs={"src": src})

    def _reader_loop(self, conn: socket.socket, src: int,
                     gen: int) -> None:
        reg = self.recv_registry
        while True:
            head, torn = _recv_exact2(conn, _HEADER.size)
            if head is None:
                # link fault (reset / sender gone): keep the rx stream
                # state — the sender reconnects and replays unacked
                # frames.  A PARTIAL header is a torn frame (the stream
                # died mid-frame, resilience territory), distinguished
                # from a clean between-frames close (graceful shutdown)
                if torn:
                    self._note_torn(src)
                conn.close()
                return
            word, seq, ack = _HEADER.unpack(head)
            if ack:
                # piggybacked cumulative ack for OUR stream toward src
                self._link.tx_ack(src, ack, gen)
            if word & _ACK_FLAG:
                continue  # header-only control frame
            plen = word & _LEN_MASK
            if word & codec.RAW_FLAG:
                # raw frame: tiny meta pickle, then the bytes stream
                # straight into the destination array(s) — the posted
                # irecv's own buffer on the rendezvous path, pooled
                # allocations otherwise
                mhead, _ = _recv_exact2(conn, codec.META.size)
                if mhead is None:
                    self._note_torn(src)  # past the header: always torn
                    conn.close()
                    return
                (mlen,) = codec.META.unpack(mhead)
                meta, _ = _recv_exact2(conn, mlen)
                if meta is None:
                    self._note_torn(src)
                    conn.close()
                    return
                ctx, tag, plan = codec.parse_raw_meta(meta)
                vc = self.verify_clock
                stamp = None
                if vc is not None:
                    # unwrap BEFORE the steering consult: the posted-recv
                    # registry keys on the real ctx
                    ctx, stamp = vc.unwrap(ctx)
                total = codec.plan_nbytes(plan)
                if codec.META.size + mlen + total != plen:
                    # a frame whose meta disagrees with the length word
                    # would desync the byte stream (the remainder of the
                    # body parses as the next header) — kill the channel
                    # and fail loudly instead (threading excepthook),
                    # mirroring the shm receive path's mismatch check
                    conn.close()
                    raise ValueError(
                        f"raw frame length mismatch from rank {src}: "
                        f"header says {plen}, meta implies "
                        f"{codec.META.size + mlen + total}")
                # Rendezvous steering (ISSUE 17): count a FRESH
                # internal-tag frame on its (src, ctx, tag) channel —
                # rx_fresh admits exactly the frames rx_gate will
                # deliver, in delivery order, so the pairing with
                # posted receives survives replay and reconnects.  A
                # matching posted destination takes the body DIRECTLY
                # (zero intermediate copy; delivery becomes pointer-
                # passing of the very view the fold site owns).
                out = None
                # user channels (ISSUE 19): a frame whose envelope was
                # activated by an irecv(buf=...) counts exactly like an
                # internal frame; everything else with tag >= 0 stays
                # off the registry entirely
                fresh = (tag < 0 or (reg.user_count
                                     and reg.user_active(src, ctx, tag))) \
                    and self._link.rx_fresh(src, seq, gen)
                if fresh:
                    out = reg.note_frame(src, ctx, tag, seq, gen, plan)
                rec = _telemetry.REC
                if out is not None:
                    # CoW-protect any retained frame still referencing
                    # the destination region BEFORE scribbling on it —
                    # a replay must stay bit-exact (mpi_tpu/bufpool.py)
                    dests = codec.raw_destinations(out)
                    for arr in dests:
                        _bufpool.touch(arr)
                    if len(dests) > 1:
                        ok = _recvmsg_into_views(conn, dests)
                    else:
                        ok = not total or _recv_into_exact(
                            conn, memoryview(out).cast("B"))
                    if not ok:
                        # torn mid-steer: the entry is consumed, the
                        # watermark keeps the replay re-presentation
                        # uncounted — it takes the pool path and the
                        # fold-site store (or the user request's
                        # fallback refill) overwrites the partial bytes
                        if tag >= 0:
                            reg.steer_abort(out)
                        self._note_torn(src)
                        conn.close()
                        return
                    if tag >= 0:
                        reg.steer_done(out)
                    _mpit.count(recv_pool_rendezvous=1,
                                recv_bytes_steered=total)
                    if rec is not None:
                        rec.emit("recvpool", "steer",
                                 attrs={"src": src, "seq": seq,
                                        "tag": tag, "nbytes": total})
                else:
                    out = codec.alloc_raw(plan)
                    dests = codec.raw_destinations(out)
                    if len(dests) > 1:
                        # scatter-gather across the pooled segments too:
                        # one vectored read per frame, not per segment
                        ok = _recvmsg_into_views(conn, dests)
                    else:
                        ok = True
                        for arr in dests:
                            if arr.nbytes and not _recv_into_exact(
                                    conn, memoryview(arr).cast("B")):
                                ok = False
                                break
                    if not ok:
                        self._note_torn(src)
                        conn.close()
                        return
                    if fresh and plan[0] in ("arr", "segs") \
                            and rec is not None:
                        rec.emit("recvpool", "fallback",
                                 attrs={"src": src, "seq": seq,
                                        "tag": tag, "nbytes": total})
                self._deliver_seq(conn, src, seq, ctx, tag, out, gen,
                                  stamp)
                continue
            payload, _ = _recv_exact2(conn, plen)
            if payload is None:
                self._note_torn(src)  # past the header: always torn
                conn.close()
                return
            ctx, tag, obj = pickle.loads(payload)
            vc = self.verify_clock
            stamp = None
            if vc is not None:
                ctx, stamp = vc.unwrap(ctx)
            if (tag < 0 or (reg.user_count
                            and reg.user_active(src, ctx, tag))) \
                    and self._link.rx_fresh(src, seq, gen):
                # pickle frames on counted channels still count (never
                # steerable) so the frame/consumer pairing stays aligned
                reg.note_frame(src, ctx, tag, seq, gen, None)
            self._deliver_seq(conn, src, seq, ctx, tag, obj, gen, stamp)

    def _deliver_seq(self, conn: socket.socket, src: int, seq: int,
                     ctx, tag: int, obj: Any, gen: int,
                     stamp: Any = None) -> None:
        """Sequenced delivery: contiguous frames reach the mailbox,
        replay duplicates (and frames from a since-purged incarnation's
        connection) are dropped, a gap is a loud protocol error
        (resilience.LinkState.rx_gate).  The gate + deliver are atomic
        per source, so a dying connection's reader racing its
        replacement's cannot reorder the mailbox FIFO.  A gate error
        kills the channel first (close-then-raise, like the raw-length
        mismatch) so the sender discovers a dead channel instead of
        streaming into kernel buffers nobody drains."""
        try:
            delivered = self._link.rx_gate(
                src, seq,
                lambda: self.mailbox.deliver(src, ctx, tag, obj, stamp),
                gen)
        except TransportError:
            conn.close()
            raise
        rec = _telemetry.REC
        if rec is not None and delivered:
            rec.emit("frame", "recv",
                     attrs={"src": src, "seq": seq, "tag": tag})

    # -- cumulative-ack flusher (mpi_tpu/resilience.py) --------------------

    @staticmethod
    def _dial_ok(dest: int, fails: Dict[int, int],
                 next_try: Dict[int, float]) -> None:
        """Reset one peer's flusher dial-backoff state after a
        successful write/redial."""
        fails.pop(dest, None)
        next_try.pop(dest, None)

    @staticmethod
    def _dial_backoff(dest: int, fails: Dict[int, int],
                      next_try: Dict[int, float]) -> None:
        """One failed flusher dial: exponential per-peer cool-down
        (5s cap) — the single spelling of the policy shared by the
        standalone-ack path and the keepalive probe."""
        fails[dest] = fails.get(dest, 0) + 1
        next_try[dest] = time.monotonic() + min(
            5.0, 0.25 * (2.0 ** fails[dest]))

    def _ack_flush_loop(self) -> None:
        link = self._link
        # per-peer dial cool-down: a vanished-but-unsuspected peer (FT
        # off, or the detector not yet fired) must not let its 2s dial
        # fuse serially starve standalone acks to every OTHER source —
        # consecutive failures back the peer off exponentially (5s cap)
        # while the data path's piggyback stays instant for everyone
        next_try: Dict[int, float] = {}
        fails: Dict[int, int] = {}
        while not self._closing:
            try:
                srcs = link.wait_ack_pending(_ACK_IDLE_S)
            except Exception:  # pragma: no cover - teardown race
                return
            if self._closing:
                return
            # idle-link keepalive (ISSUE 11 satellite): runs every park
            # wakeup, whether or not acks are pending — a fully idle
            # transport still probes its cached connections
            self._keepalive_probe(next_try, fails)
            if not srcs:
                continue
            time.sleep(_ACK_BATCH_S)  # coalesce a delivery burst
            for src in srcs:
                if self._closing:
                    return
                value = link.peek_ack(src)
                if value is None:
                    continue  # a piggyback beat us to it
                if self._suspect(src):
                    # dead peer: nobody is waiting on these acks, and
                    # redialing its corpse every round would spin
                    link.note_ack_sent(src, value)
                    continue
                if time.monotonic() < next_try.get(src, 0.0):
                    continue  # cooling down after failed dials
                try:
                    with self._send_lock(src):
                        with self._conn_lock:
                            conn = self._conns.get(src)
                        if conn is None:
                            # short-fused dial (the peer published a
                            # port at world start): an unreachable peer
                            # is retried next round, not camped on
                            conn = self._establish_locked(
                                src, time.monotonic() + 2.0,
                                backoff_delays())
                        conn.sendall(_HEADER.pack(_ACK_FLAG, 0, value))
                    link.note_ack_sent(src, value)
                    self._dial_ok(src, fails, next_try)
                except (OSError, TransportError, EpochSkewError):
                    # best-effort: drop a broken conn so a later round
                    # re-dials (the peer's window depends on these acks
                    # when no data flows back); real diagnosis belongs
                    # to the data path / membership layer
                    self._drop_conn(src)
                    self._dial_backoff(src, fails, next_try)

    def _keepalive_probe(self, next_try: Dict[int, float],
                         fails: Dict[int, int]) -> None:
        """Idle-link keepalive (link_keepalive_s cvar, closes PR-10
        residual (b)): probe every CACHED connection that sent nothing
        for the keepalive period with a header-only ack frame.  A link
        torn while idle (peer-side reset after our last write returned)
        fails the probe, and the flusher heals it HERE — reconnect +
        resume-replay on a short fuse — so the next real send finds a
        live link instead of paying the reconnect spike itself.  Probes
        never block behind an in-flight send (non-blocking lock try: a
        busy link is by definition not idle) and honor the same per-dest
        cool-down as failed ack dials.  No-op when probing is disabled
        or healing is off (a probe failure would be terminal — worse
        than leaving the fault to the send path's classified raise)."""
        ka = _resilience._KEEPALIVE_S
        if ka <= 0 or _resilience._RETRY_TIMEOUT_S <= 0:
            return
        now = time.monotonic()
        with self._conn_lock:
            idle = [d for d in self._conns
                    if now - self._last_send.get(d, 0.0) >= ka]
        for dest in idle:
            if self._closing:
                return
            if self._suspect(dest) or now < next_try.get(dest, 0.0):
                continue
            lock = self._send_lock(dest)
            if not lock.acquire(blocking=False):
                continue  # a send is mid-frame: the link is not idle
            try:
                with self._conn_lock:
                    conn = self._conns.get(dest)
                if conn is None:
                    continue
                try:
                    conn.sendall(_HEADER.pack(
                        _ACK_FLAG, 0, self._link.piggyback_ack(dest)))
                    self._last_send[dest] = time.monotonic()
                    self._dial_ok(dest, fails, next_try)
                except OSError:
                    self._drop_conn(dest)
                    try:
                        self._establish_locked(
                            dest, time.monotonic() + 2.0,
                            backoff_delays())
                        _mpit.count(link_faults_masked=1)
                        self._dial_ok(dest, fails, next_try)
                    except (OSError, TransportError, EpochSkewError):
                        # unreachable right now: back off, the next
                        # probe round (or the send path) retries
                        self._dial_backoff(dest, fails, next_try)
            finally:
                lock.release()

    # -- outgoing ----------------------------------------------------------

    def _peer_port_once(self, dest: int) -> Optional[int]:
        """Current content of the peer's rendezvous port file, or None.
        Re-read on every connection retry: a REPLACED slot's rejoiner
        re-publishes this file (atomic rename), and connecting to the
        stale port forever would turn an epoch transition into a hang."""
        try:
            with open(os.path.join(self._rdv, f"port.{dest}")) as f:
                text = f.read().strip()
            return int(text) if text else None
        except (FileNotFoundError, ValueError):
            return None

    def _peer_port(self, dest: int) -> int:
        deadline = time.monotonic() + self._connect_timeout
        while True:
            port = self._peer_port_once(dest)
            if port is not None:
                return port
            if time.monotonic() > deadline:
                raise TransportError(
                    f"rank {self.world_rank}: peer {dest} did not publish a port "
                    f"within {self._connect_timeout}s (rendezvous dir {self._rdv})"
                )
            time.sleep(0.005)

    def _send_lock(self, dest: int) -> threading.Lock:
        # _conn_lock guards only the dict lookups; the (possibly slow)
        # rendezvous poll + connect happens under the per-dest lock so sends
        # to other, already-connected peers are never stalled behind it.
        with self._conn_lock:
            lock = self._send_locks.get(dest)
            if lock is None:
                lock = self._send_locks[dest] = threading.Lock()
            return lock

    def _drop_conn(self, dest: int) -> None:
        """Forget + close the cached connection to ``dest`` (link-fault
        teardown / failed ack flush).  The retained window and seq
        state survive — that is the whole point."""
        with self._conn_lock:
            conn = self._conns.pop(dest, None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def _suspect(self, dest: int) -> bool:
        """PEER-fault verdict for link classification: the FT detector's
        suspect set, or a heartbeat stale past the detection bound
        (ft.WorldFT.link_suspect).  Without fault tolerance enabled
        there is no peer-death authority, so every fault is a link
        fault and only the bounded retry budget decides."""
        world = getattr(self, "_ft_world", None)
        return world is not None and world.link_suspect(dest)

    def _get_conn_locked(self, dest: int) -> socket.socket:
        """Return the connection to ``dest``; caller holds the per-dest
        lock.  First connection of a world: bounded by
        ``connect_timeout`` at a polite poll cadence."""
        with self._conn_lock:
            conn = self._conns.get(dest)
        if conn is not None:
            return conn
        self._peer_port(dest)  # bounded wait for a first publication
        deadline = time.monotonic() + self._connect_timeout

        def abort() -> None:
            # the initial-connect loop honors the same classification
            # as healing: a peer the FT layer declares dead mid-dial
            # surfaces as a peer fault NOW (TransportError -> wrapped
            # ProcFailedError), not after connect_timeout's 60s camp
            if self._closing:
                raise _LinkAbort(
                    f"rank {self.world_rank}: transport closed while "
                    f"connecting to rank {dest}")
            if self._suspect(dest):
                raise _LinkAbort(
                    f"rank {self.world_rank}: peer {dest} declared "
                    f"failed while connecting to it")

        return self._establish_locked(dest, deadline,
                                      iter(lambda: 0.01, None),
                                      abort=abort)

    def _establish_locked(self, dest: int, deadline: float, delays,
                          abort=None) -> socket.socket:
        """Dial + handshake + resume-replay loop; caller holds the
        per-dest send lock.  The handshake is hello(rank, epoch) →
        ack(peer epoch, last delivered seq):

        * ack epoch NEWER than ours — WE are the stale straggler (shrunk
          out while we stalled past the detection bound): EpochSkewError
          after the epoch grace, the diagnosed spelling of the
          false-suspicion group split.
        * ack epoch below ``min_peer_epoch[dest]`` — the PEER is a stale
          incarnation still squatting on the old rendezvous endpoint of a
          replaced slot: drop it and retry against a re-read port file
          until the replacement publishes.
        * otherwise — prune the retained window to the peer's resume
          mark and REPLAY the frames beyond it (the peer's rx gate
          drops any the teardown raced through), then register the
          connection.
        """
        skew_since = None
        while True:
            if abort is not None:
                abort()  # healing-path closing/suspect checks may raise
            port = self._peer_port_once(dest)
            conn = None
            if port is not None:
                try:
                    conn = socket.create_connection((_HOST, port),
                                                    timeout=5.0)
                except OSError:
                    conn = None
            if conn is not None:
                try:
                    if conn.getsockname() == conn.getpeername():
                        # Linux loopback SELF-CONNECT: dialing a port
                        # nobody listens on can land the ephemeral
                        # SOURCE port on the destination port itself
                        # (TCP simultaneous open) — the socket is then
                        # connected to US, and the handshake would
                        # misparse our own hello as the peer's ack.  A
                        # reconnect loop against a dead peer's stale
                        # port hits this reliably; treat as a failed
                        # dial.
                        conn.close()
                        conn = None
                except OSError:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    conn = None
            if conn is not None:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # generous ack window (an abandoned attempt just
                # retries): on an oversubscribed box the acceptor's
                # handshake thread can be scheduler-starved for whole
                # seconds, and hair-trigger ack timeouts turn that into
                # connect churn
                conn.settimeout(10.0)
                try:
                    conn.sendall(_HELLO.pack(self.world_rank, self.epoch))
                    ack = _recv_exact(conn, _HELLO_ACK.size)
                except OSError:
                    ack = None
                if ack is not None:
                    peer_epoch, resume_seq = _HELLO_ACK.unpack(ack)
                    if peer_epoch > self.epoch:
                        conn.close()
                        # grace before the skew verdict: our own epoch
                        # bump may be milliseconds behind a broadcast
                        # transition (self.epoch is re-read each retry)
                        if skew_since is None:
                            skew_since = time.monotonic()
                        if time.monotonic() - skew_since \
                                > _EPOCH_GRACE_S:
                            _mpit.count(epoch_skews=1)
                            raise EpochSkewError(
                                f"rank {self.world_rank}: peer {dest} is "
                                f"at membership epoch {peer_epoch}, this "
                                f"process at {self.epoch} — this process "
                                f"was shrunk out of the world "
                                f"(stale-epoch straggler)",
                                local_epoch=self.epoch,
                                peer_epoch=peer_epoch, peer=dest)
                        time.sleep(0.01)
                        continue
                    skew_since = None
                    if peer_epoch >= self.min_peer_epoch.get(dest, 0):
                        if self._replay_locked(dest, conn, resume_seq):
                            conn.settimeout(None)
                            with self._conn_lock:
                                self._conns[dest] = conn
                            # a fresh connection needs no probe for a
                            # full keepalive period
                            self._last_send[dest] = time.monotonic()
                            if self._link.mark_connected(dest):
                                _mpit.count(link_reconnects=1)
                                rec = _telemetry.REC
                                if rec is not None:
                                    rec.emit("link", "reconnect",
                                             attrs={"peer": dest})
                            return conn
                        conn = None  # replay tripped: count as a miss
                if conn is not None:
                    conn.close()  # stale incarnation (or torn handshake)
            if time.monotonic() > deadline:
                raise TransportError(
                    f"rank {self.world_rank}: cannot connect to rank "
                    f"{dest} at epoch >= "
                    f"{self.min_peer_epoch.get(dest, 0)} within the "
                    f"connection deadline")
            time.sleep(next(delays))

    def _replay_locked(self, dest: int, conn: socket.socket,
                       resume_seq: int) -> bool:
        """Resume round of a fresh handshake: prune the retained window
        to the peer's delivery mark, replay every frame beyond it in
        seq order (with a fresh piggyback ack — the retained header
        word/seq are authoritative, the ack field is not).  False on a
        mid-replay socket error (caller retries the whole dial)."""
        pending = self._link.resume(dest, resume_seq)
        rec = _telemetry.REC
        if rec is not None and pending:
            rec.emit("link", "replay",
                     attrs={"peer": dest, "frames": len(pending),
                            "resume_seq": resume_seq})
        for seq, word, body in pending:
            views = body.pin()
            if views is None:
                # released mid-replay (acked on another path / purge):
                # an acked frame was delivered — the receiver's rx gate
                # dedups a replay anyway, so skipping loses nothing
                continue
            try:
                _sendmsg_views(conn, [
                    _HEADER.pack(word, seq,
                                 self._link.piggyback_ack(dest)),
                    *views])
            except OSError:
                try:
                    conn.close()
                except OSError:
                    pass
                return False
            finally:
                body.unpin()
            _mpit.count(link_frames_replayed=1)
        return True

    def _heal_link_locked(self, dest: int, err: OSError) -> None:
        """A send-path OSError, classified (ISSUE 10): peer fault →
        TransportError now (the communicator wraps it into
        ProcFailedError and the detector records the evidence); link
        fault → reconnect with exponential backoff + jitter bounded by
        ``link_retry_timeout_s``.  On success the retained-window
        replay already resent the failed frame — the caller's send is
        complete.  Caller holds the per-dest send lock."""
        self._drop_conn(dest)
        retry_s = _resilience._RETRY_TIMEOUT_S
        if retry_s <= 0:
            raise TransportError(
                f"rank {self.world_rank}: send to rank {dest} failed: "
                f"{err} (link healing disabled)") from err
        if self._suspect(dest):
            raise TransportError(
                f"rank {self.world_rank}: send to rank {dest} failed "
                f"({err}); peer is failure-suspected — not retrying a "
                f"dead peer's link") from err

        def abort() -> None:
            if self._closing:
                raise _LinkAbort(
                    f"rank {self.world_rank}: transport closed while "
                    f"healing link to rank {dest}")
            if self._suspect(dest):
                raise _LinkAbort(
                    f"rank {self.world_rank}: peer {dest} declared "
                    f"failed while re-establishing its link "
                    f"(original fault: {err})")

        rec = _telemetry.REC
        t_heal = time.perf_counter_ns()
        try:
            self._establish_locked(
                dest, time.monotonic() + retry_s, backoff_delays(),
                abort=abort)
        except EpochSkewError:
            raise  # membership diagnosis outranks link healing
        except _LinkAbort as e:
            if rec is not None:
                rec.emit("link", "heal",
                         dur_ns=time.perf_counter_ns() - t_heal,
                         attrs={"peer": dest, "ok": False,
                                "error": "aborted"})
            raise TransportError(str(e)) from err
        except (OSError, TransportError):
            if rec is not None:
                rec.emit("link", "heal",
                         dur_ns=time.perf_counter_ns() - t_heal,
                         attrs={"peer": dest, "ok": False,
                                "error": "retry_timeout"})
            raise TransportError(
                f"rank {self.world_rank}: link to rank {dest} not "
                f"re-established within link_retry_timeout_s="
                f"{retry_s} (original fault: {err})") from err
        heal_s = (time.perf_counter_ns() - t_heal) / 1e9
        # link-heal latency distribution (ISSUE 13): always recorded —
        # a heal is already a multi-ms reconnect, the histogram add is
        # noise on it (unlike the per-collective hot path, which gates
        # its histogram on the flight recorder)
        _mpit.hist_record("link_heal_s", heal_s)
        if rec is not None:
            rec.emit("link", "heal", dur_ns=int(heal_s * 1e9),
                     attrs={"peer": dest, "ok": True})
        _mpit.count(link_faults_masked=1)

    def send(self, dest: int, ctx, tag: int, payload: Any) -> None:
        if not (0 <= dest < self.world_size):
            raise ValueError(f"dest {dest} out of range for world size {self.world_size}")
        if dest == self.world_rank:
            # value-semantics copy (cheap .copy() for arrays).  Count
            # the delivery on its steering channel first: loopback
            # traffic on an internal tag consumes posted slots like any
            # other arrival (its own (self, ctx, tag) channel — never
            # interleaved with a peer's sequenced stream)
            reg = self.recv_registry
            if tag < 0 or (reg.user_count
                           and reg.user_active(dest, ctx, tag)):
                reg.note_local(dest, ctx, tag)
            vc = self.verify_clock
            stamp = vc.tick_send() if vc is not None else None
            self.mailbox.deliver(dest, ctx, tag, codec.value_copy(payload),
                                 stamp)
            return
        vc = self.verify_clock
        if vc is not None:
            # stamp rides inside the frame (the ctx slot of the meta /
            # pickle body); the reader unwraps right after parse, so
            # replays of retained frames deliver the stamp exactly once
            # through the rx_gate dedup
            ctx = vc.wrap(ctx)
        frame = codec.pack_raw_frame(ctx, tag, payload)
        if frame is not None:
            # the ndarrays ride whole (not pre-cast to memoryviews):
            # the ownership layer needs the OWNER objects to register
            # live address ranges and keep pooled buffers unrecycled
            # while their frames are retained (mpi_tpu/bufpool.py)
            head, bufs = frame
            self._send_parts(dest, codec.RAW_FLAG,
                             [head, *(b for b in bufs if b.nbytes)])
            return
        blob = codec.pack_pickle_body(ctx, tag, payload)
        self._send_parts(dest, 0, [blob])

    def _send_parts(self, dest: int, flags: int, parts) -> None:
        """Sequenced frame send.  With healing ENABLED: wait for
        retained-window room, retain the body BY REFERENCE as a
        :class:`bufpool.BufRef` over the caller's buffers (ISSUE 11 —
        replacing ISSUE 10's flat ``bytes`` snapshot, a full memcpy per
        frame), stream it with one vectored ``sendmsg``, heal on
        OSError.  A replay after a reset is bit-exact because every
        internal mutation site notifies the ownership layer, which
        copy-on-writes any overlapping retained frame BEFORE the write
        lands (``link_retain_copy`` = 1 restores the eager snapshot;
        ``link_bytes_retained`` still prices retention, the cow pvars
        price exactly the copies reuse forced).  With healing DISABLED
        (``link_retry_timeout_s`` = 0): no refs, no window, no
        retention — the buffers stream directly (the pre-resilience
        zero-copy path, now also one sendmsg), seqs still assigned so
        the receiver's contiguity gate keeps holding."""
        link = self._link
        healing = _resilience._RETRY_TIMEOUT_S > 0
        body: Any = None
        if healing:
            body = _bufpool.BufRef(
                parts, register=not _resilience._RETAIN_COPY)
            if _resilience._RETAIN_COPY:
                body.snapshot()  # ISSUE 10 semantics wholesale
            elif body.ranges:
                # reuse-on-send: a region already sitting unacked in
                # the retained window is about to ship again — the
                # OLDER frames lose their claim to the shared mutable
                # views (snapshot) so later mutation notifications
                # cannot race two refs over one region
                _bufpool.touch_ranges(body.ranges, exclude=body)
            nbytes = body.nbytes
        else:
            views = [memoryview(p).cast("B")
                     if not isinstance(p, (bytes, bytearray, memoryview))
                     else memoryview(p) for p in parts]
            nbytes = sum(v.nbytes for v in views)
        word = flags | nbytes
        hook = self._link_fault_hook
        try:
            if healing:
                # outside the send lock: a window-full wait must not
                # hold the lock the ack flusher needs for this dest
                link.wait_window(dest, nbytes, self._suspect,
                                 lambda: self._closing)
            lock = self._send_lock(dest)
            lock.acquire()
            try:
                conn = self._get_conn_locked(dest)
                seq = (link.tx_retain(dest, word, body) if healing
                       else link.tx_next_seq(dest))
            except BaseException:
                lock.release()
                raise
        except BaseException:
            # until tx_retain hands the ref to the window (which then
            # owns its release on ack/purge/close), every raise on this
            # path — window stall verdict, failed connect, peer-fault
            # classification — must release it, or the live-range index
            # leaks a ref that CoW-snapshots unrelated later buffers
            # landing at the same address
            if healing:
                body.release()
            raise
        try:
            header = _HEADER.pack(word, seq, link.piggyback_ack(dest))
            if healing:
                pinned = body.pin()
                if pinned is None:
                    return  # ref released: window torn down (closing)
            else:
                pinned = views
            rec = _telemetry.REC
            # stamped at send START: the matching pass in tracecat.py
            # needs send <= recv in real time, and an emit placed after
            # the syscall loses that ordering whenever the receiver
            # delivers before this thread is rescheduled
            t_send = time.perf_counter_ns() if rec is not None else 0
            try:
                if hook is None:
                    # the hot path: header + meta + every segment in
                    # ONE scatter-gather syscall (IOV_MAX batched)
                    _sendmsg_views(conn, [header, *pinned])
                else:
                    # chaos instrumentation: the header/body split is
                    # load-bearing ('mid' = reset between header and
                    # body), so the hooked path keeps two stages —
                    # body still vectored
                    hook(dest, "pre")  # chaos: reset between frames
                    conn.sendall(header)
                    _mpit.count(link_send_syscalls=1)
                    hook(dest, "mid")  # chaos: reset mid-frame
                    _sendmsg_views(conn, pinned)
                self._last_send[dest] = time.monotonic()
                if rec is not None:
                    rec.emit("frame", "send",
                             dur_ns=time.perf_counter_ns() - t_send,
                             attrs={"dest": dest, "seq": seq,
                                    "nbytes": nbytes})
            except OSError as e:
                # classification + healing; the retained window replays
                # this frame on a successful reconnect (with healing
                # off this raises terminally — pre-resilience behavior)
                self._heal_link_locked(dest, e)
            finally:
                if healing:
                    body.unpin()
        finally:
            lock.release()

    # -- chaos hooks (transport/faulty.py link-fault injection) ------------

    def install_link_faults(self, injector) -> None:
        """Attach a connection-level fault injector (see FaultyTransport
        link_* kwargs): its hook fires inside this transport's send
        path regardless of which communicator handle triggered the
        send, and its accept-drop budget is consumed by the acceptor."""
        self._link_fault_hook = injector._link_hook
        self._accept_drop_n += int(
            getattr(injector, "link_accept_drop", 0))

    def _inject_link_reset(self, dest: int) -> None:
        """Chaos primitive: tear down the cached connection to ``dest``
        NOW (RST — SO_LINGER 0 — so the peer sees a hard reset, not a
        polite FIN).  Called synchronously from the send-path hook, so
        the in-flight sendall fails on the closed descriptor and the
        healing path takes over; the retained window is untouched."""
        with self._conn_lock:
            conn = self._conns.pop(dest, None)
        if conn is not None:
            try:
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                struct.pack("ii", 1, 0))
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
            rec = _telemetry.REC
            if rec is not None:
                # the chaos timeline's cause marker: reset HERE, then
                # the heal/reconnect/replay events that answer it
                rec.emit("link", "reset_injected", attrs={"peer": dest})

    # -- membership (mpi_tpu/membership.py) --------------------------------

    def membership_invalidate(self, dead) -> None:
        """Drop cached connections to replaced slots so the next send
        re-handshakes (port-file re-read + epoch-checked hello).  Takes
        each per-dest send lock: a send streaming a frame on the old
        connection must finish (or fail) before its socket vanishes.
        The per-dest RESILIENCE state goes with it (purge_peer): the
        dead incarnation's retained replay window and seq/delivery
        marks belong to ITS streams — a rejoiner starts at seq 1 and
        must never see a stale replay or inherit the corpse's dedup
        horizon."""
        for dest in dead:
            with self._send_lock(dest):
                with self._conn_lock:
                    conn = self._conns.pop(dest, None)
                if conn is not None:
                    try:
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        conn.close()
                    except OSError:
                        pass
                self._link.purge_peer(dest)
                # resync the steering registry to the bumped generation:
                # the purged stream's in-flight frames died with it, and
                # the fenced watermark keeps old-incarnation stragglers
                # from ever counting (mpi_tpu/recvpool.py)
                self.recv_registry.purge_src(
                    dest, self._link.peer_gen(dest))
            # kill the slot's INBOUND readers too: their captured
            # stream generation just went stale, so every frame they
            # read would be fence-dropped — for the corpse that is the
            # point, and for a replacement whose hello RACED this
            # transition the close makes its sender reconnect and
            # replay the (unacked) fence-dropped frames onto a reader
            # that captures the fresh generation
            with self._conn_lock:
                readers = self._reader_conns.pop(dest, [])
            for rc in readers:
                try:
                    rc.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    rc.close()
                except OSError:
                    pass

    # -- shutdown ----------------------------------------------------------

    def close(self) -> None:
        self._closing = True
        try:
            self._listener.close()
        except OSError:
            pass
        self._link.close()  # frees window waiters + parks the flusher out
        with self._conn_lock:
            for conn in self._conns.values():
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    conn.close()
                except OSError:
                    pass
            self._conns.clear()
        self.mailbox.close()
