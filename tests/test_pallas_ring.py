"""Pallas RDMA ring allreduce vs numpy oracle (interpreter on the virtual
CPU mesh; the same kernel compiles for real ICI on a slice)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mpi_tpu.tpu import TpuCommunicator, default_mesh
from mpi_tpu.tpu.pallas_ring import pallas_ring_allreduce


def _run(nranks, n, tile_rows=8, seed=0):
    mesh = default_mesh(nranks)
    data = np.asarray(np.random.RandomState(seed).randn(nranks, n), np.float32)

    def f(x):
        return pallas_ring_allreduce(x.reshape(-1), "world", nranks,
                                     tile_rows=tile_rows, interpret=True)[None]

    out = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("world"), out_specs=P("world"),
        check_vma=False))(jnp.asarray(data.reshape(-1)))
    return np.asarray(out).reshape(nranks, n), data


@pytest.mark.parametrize("nranks,n", [(2, 128), (4, 1000), (8, 4096), (3, 77)])
def test_pallas_ring_allreduce(nranks, n):
    out, data = _run(nranks, n)
    for r in range(nranks):
        np.testing.assert_allclose(out[r], data.sum(0), rtol=1e-4, atol=1e-5)


def test_pallas_ring_via_communicator():
    from mpi_tpu.tpu import run_spmd

    data = np.asarray(np.random.RandomState(1).randn(8, 300), np.float32)

    def prog(comm, x):
        return comm.allreduce(x[comm.rank], algorithm="pallas_ring")

    out = np.asarray(run_spmd(prog, data, check_vma=False))
    for r in range(8):
        np.testing.assert_allclose(out[r], data.sum(0), rtol=1e-4, atol=1e-5)


def test_pallas_ring_under_check_vma():
    """algorithm='pallas_ring' works under the DEFAULT check_vma=True
    (VERDICT r2 next-step #7): on the interpreter the ring executes as
    vma-typed ppermute steps; compiled, the kernel itself declares its
    result varying (real-TPU AOT tier covers that leg)."""
    from mpi_tpu.tpu import run_spmd

    data = np.asarray(np.random.RandomState(5).randn(8, 48), np.float32)

    def prog(comm, x):
        return comm.allreduce(x[comm.rank], algorithm="pallas_ring")

    out = np.asarray(run_spmd(prog, data))  # default check_vma=True
    for r in range(8):
        np.testing.assert_allclose(out[r], data.sum(0), rtol=1e-4, atol=1e-5)


def test_pallas_reduce_scatter_under_check_vma():
    from mpi_tpu.tpu import run_spmd

    P_, block = 4, 96
    data = np.asarray(np.random.RandomState(6).randn(P_, P_, block),
                      np.float32)

    def prog(comm, x):
        return comm.reduce_scatter(x[comm.rank], algorithm="pallas_ring")

    out = np.asarray(run_spmd(prog, data, nranks=P_))
    np.testing.assert_allclose(out, data.sum(0), rtol=1e-4, atol=1e-5)


def test_pallas_ring_diagnostics():
    mesh = default_mesh()
    comm = TpuCommunicator("world", mesh)
    from mpi_tpu import ops

    with pytest.raises(NotImplementedError, match="built-in"):
        comm.allreduce(jnp.zeros(8), op=ops.PROD, algorithm="pallas_ring")
    with pytest.raises(NotImplementedError, match="float32"):
        pallas_ring_allreduce(jnp.zeros(8, jnp.int32), "world", 8)


@pytest.mark.parametrize("opname,npop", [("max", np.max), ("min", np.min)])
@pytest.mark.parametrize("check_vma", [False, True])
def test_pallas_ring_max_min(opname, npop, check_vma):
    """MAX/MIN ride the same kernel with a swapped combiner (positions
    only combine with the same position, so zero padding can't leak)."""
    from mpi_tpu import ops
    from mpi_tpu.tpu import run_spmd

    data = np.asarray(np.random.RandomState(21).randn(8, 130), np.float32)
    op = getattr(ops, opname.upper())

    def prog(comm, x):
        return comm.allreduce(x[comm.rank], op=op, algorithm="pallas_ring")

    out = np.asarray(run_spmd(prog, data, check_vma=check_vma))
    expect = npop(data, axis=0)
    for r in range(8):
        np.testing.assert_allclose(out[r], expect, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("check_vma", [False, True])
def test_pallas_ring_grouped(check_vma):
    """A split communicator selects pallas_ring: one independent ring per
    group, driven by the SMEM (grank, left, right) params (VERDICT r2
    missing #4 — previously the one algorithm a split comm couldn't use)."""
    from mpi_tpu.tpu import run_spmd

    data = np.asarray(np.random.RandomState(11).randn(8, 200), np.float32)
    mesh = default_mesh()
    world = TpuCommunicator("world", mesh)
    # interleaved groups: evens and odds (non-contiguous world indices)
    sub = world.split_by(lambda i: i % 2)

    def prog(comm, x):
        return sub.allreduce(x[comm.rank], algorithm="pallas_ring")

    out = np.asarray(run_spmd(prog, data, mesh=mesh, check_vma=check_vma))
    evens, odds = data[0::2].sum(0), data[1::2].sum(0)
    for r in range(8):
        np.testing.assert_allclose(out[r], evens if r % 2 == 0 else odds,
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("check_vma", [False, True])
def test_pallas_reduce_scatter_grouped(check_vma):
    from mpi_tpu.tpu import run_spmd

    block = 72
    data = np.asarray(np.random.RandomState(12).randn(8, 4, block),
                      np.float32)
    mesh = default_mesh()
    world = TpuCommunicator("world", mesh)
    rows = world.split_by(lambda i: i // 4)  # [[0..3], [4..7]]

    def prog(comm, x):
        return rows.reduce_scatter(x[comm.rank], algorithm="pallas_ring")

    out = np.asarray(run_spmd(prog, data, mesh=mesh, check_vma=check_vma))
    lo, hi = data[:4].sum(0), data[4:].sum(0)  # [4, block] each
    for r in range(8):
        expect = lo[r % 4] if r < 4 else hi[r % 4]
        np.testing.assert_allclose(out[r], expect, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("nranks,n", [(2, 4096), (4, 20000)])
def test_pallas_ring_multi_segment(nranks, n):
    """Sizes large enough that each chunk splits into >1 pipeline segment
    (tile_rows=8 → 4 segments at these sizes)."""
    out, data = _run(nranks, n)
    for r in range(nranks):
        np.testing.assert_allclose(out[r], data.sum(0), rtol=1e-4, atol=1e-5)


def test_pallas_ring_bf16():
    nranks, n = 4, 512
    mesh = default_mesh(nranks)
    data = np.asarray(np.random.RandomState(3).randn(nranks, n), np.float32)
    bf = jnp.asarray(data, jnp.bfloat16)

    def f(x):
        return pallas_ring_allreduce(x.reshape(-1), "world", nranks,
                                     tile_rows=16, interpret=True)[None]

    out = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("world"), out_specs=P("world"),
        check_vma=False))(bf.reshape(-1))
    assert out.dtype == jnp.bfloat16
    # bf16 ring-order sums: loose tolerance vs the f32 oracle
    np.testing.assert_allclose(
        np.asarray(out, np.float32).reshape(nranks, n)[0], data.sum(0),
        rtol=0.05, atol=0.05)


@pytest.mark.parametrize("nranks,block", [(2, 256), (4, 1000), (8, 128)])
def test_pallas_ring_reduce_scatter(nranks, block):
    from mpi_tpu.tpu.pallas_ring import pallas_ring_reduce_scatter

    mesh = default_mesh(nranks)
    # every rank holds a DIFFERENT full [P, block] stack
    data = np.asarray(
        np.random.RandomState(7).randn(nranks, nranks * block), np.float32)

    def f(x):
        return pallas_ring_reduce_scatter(
            x.reshape(nranks, block), "world", nranks, tile_rows=8,
            interpret=True).reshape(1, block)

    out = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("world"), out_specs=P("world"),
        check_vma=False))(jnp.asarray(data.reshape(-1)))
    out = np.asarray(out).reshape(nranks, block)
    oracle = data.reshape(nranks, nranks, block).sum(0)  # [P, block]
    for r in range(nranks):
        np.testing.assert_allclose(out[r], oracle[r], rtol=1e-4, atol=1e-5)


def test_pallas_ring_rejects_bad_dtype_and_shape():
    from mpi_tpu.tpu.pallas_ring import pallas_ring_reduce_scatter

    with pytest.raises(NotImplementedError, match="float32/bfloat16"):
        pallas_ring_allreduce(jnp.zeros(8, jnp.int32), "world", 2)
    with pytest.raises(ValueError, match="leading dimension"):
        pallas_ring_reduce_scatter(jnp.zeros(7, jnp.float32), "world", 2)


def test_pallas_ring_reduce_scatter_via_communicator():
    from mpi_tpu.tpu import run_spmd

    P_ = 4
    block = 100
    data = np.asarray(
        np.random.RandomState(9).randn(P_, P_, block), np.float32)

    def prog(comm, x):
        return comm.reduce_scatter(x[comm.rank], algorithm="pallas_ring")

    out = np.asarray(run_spmd(prog, data, nranks=P_, check_vma=False))
    oracle = data.sum(0)  # [P, block]
    np.testing.assert_allclose(out, oracle, rtol=1e-4, atol=1e-5)


def _mesh2d(dp=2, mp=4):
    devs = np.array(jax.devices()[:dp * mp]).reshape(dp, mp)
    return Mesh(devs, ("dp", "mp"))


@pytest.mark.parametrize("ring_axis,other", [("mp", "dp"), ("dp", "mp")])
def test_pallas_ring_multiaxis_interpreter_parity(ring_axis, other):
    """pallas_ring on ONE axis of a 2-D mesh (VERDICT r3 missing #2).
    The interpreter cannot discharge remote DMAs on a multi-axis mesh,
    so these calls execute the ppermute ring fallback — numerically the
    same per-(other-axis slice) reduction the compiled kernel performs;
    the kernel's own multi-axis lowering is covered by the TPU-export
    test below."""
    mesh = _mesh2d()
    ring_size = dict(mesh.shape)[ring_axis]
    comm = TpuCommunicator(ring_axis, mesh)
    data = np.asarray(np.random.RandomState(7).randn(8, 256), np.float32)

    def f(x):
        return comm.allreduce(x, algorithm="pallas_ring")

    out = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("dp", "mp"), out_specs=P("dp", "mp"),
        check_vma=False))(jnp.asarray(data))
    # oracle: reduce over the ring axis only, within each other-axis slice
    grid = data.reshape(2, 4, 4, 64)  # [dp, rows/dp=4][mp, cols/mp=64]
    axis = 0 if ring_axis == "dp" else 2
    want = grid.sum(axis=axis, keepdims=True)
    want = np.broadcast_to(want, grid.shape).reshape(8, 256)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4, atol=1e-5)
    assert ring_size in (2, 4)


def test_pallas_ring_multiaxis_fallback_warns_and_counts():
    """The interpreter fallback must be LOUD (VERDICT r3 weak #4 / next
    #7): a RuntimeWarning at trace time plus a pallas_ring_fallbacks
    mpit pvar bump, so a sim benchmark can't silently measure the
    ppermute ring while reporting 'pallas_ring'."""
    from mpi_tpu import mpit

    mesh = _mesh2d()
    comm = TpuCommunicator("mp", mesh)

    def f(x):
        return comm.allreduce(x, algorithm="pallas_ring")

    before = mpit.pvar_read("pallas_ring_fallbacks")
    with pytest.warns(RuntimeWarning, match="ppermute ring fallback"):
        jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=P("dp", "mp"), out_specs=P("dp", "mp"),
            check_vma=False))(jnp.zeros((8, 256), jnp.float32))
    assert mpit.pvar_read("pallas_ring_fallbacks") > before


def test_pallas_ring_vma_fallback_warns():
    """The vma-typed interpreter fallback (1-D mesh, check_vma=True)
    warns the same way."""
    from mpi_tpu.tpu import run_spmd

    data = np.zeros((8, 48), np.float32)

    def prog(comm, x):
        return comm.allreduce(x[comm.rank], algorithm="pallas_ring")

    with pytest.warns(RuntimeWarning, match="ppermute ring fallback"):
        run_spmd(prog, data)  # check_vma defaults to True


@pytest.mark.parametrize("ring_axis", ["mp", "dp"])
def test_pallas_ring_multiaxis_export_tpu(ring_axis):
    """AOT-lower the KERNEL (not the fallback) for TPU on a 2-D
    AbstractMesh via cross-platform jax.export: pushes the dict-MESH
    RDMA addressing through the full Mosaic pipeline with no chip
    attached — the machine-checkable half of VERDICT r3 missing #2.
    Both axis choices lower (major and minor mesh strides)."""
    from jax.sharding import AbstractMesh

    mesh = AbstractMesh((2, 4), ("dp", "mp"))
    size = dict(zip(mesh.axis_names, mesh.axis_sizes))[ring_axis]

    def f(x):
        return pallas_ring_allreduce(x, ring_axis, size, tile_rows=8,
                                     interpret=False)

    jf = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P("dp", "mp"),
                               out_specs=P("dp", "mp"), check_vma=False))
    exp = jax.export.export(jf, platforms=["tpu"])(
        jax.ShapeDtypeStruct((8, 256), jnp.float32))
    assert exp.platforms == ("tpu",)
    assert "tpu_custom_call" in exp.mlir_module()


def test_pallas_ring_multiaxis_export_tpu_rs_and_ag():
    """reduce_scatter and allgather kernels also lower for TPU on the
    2-D mesh (same dict-MESH addressing, different kernel modes: rot=-1
    half-ring and the land-direct ag-only mode)."""
    from jax.sharding import AbstractMesh

    from mpi_tpu.tpu.pallas_ring import (pallas_ring_allgather,
                                         pallas_ring_reduce_scatter)

    mesh = AbstractMesh((2, 4), ("dp", "mp"))

    def rs(x):
        # x: [1(dp shard), 4 blocks, 256] — drop the dp dim, ring over mp
        return pallas_ring_reduce_scatter(x[0], "mp", 4, tile_rows=8,
                                          interpret=False)[None]

    def ag(x):
        return pallas_ring_allgather(x[0], "mp", 4, tile_rows=8,
                                     interpret=False)[0][None]

    for f, shape, ispec in (
            (rs, (2, 4, 256), P("dp", None, None)),
            (ag, (2, 256), P("dp", None))):
        jf = jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=ispec, out_specs=P("dp", None),
            check_vma=False))
        exp = jax.export.export(jf, platforms=["tpu"])(
            jax.ShapeDtypeStruct(shape, jnp.float32))
        assert "tpu_custom_call" in exp.mlir_module()


def test_pallas_ring_1d_export_tpu():
    """The validated 1-D (LOGICAL device id) path also lowers for TPU
    from this CPU host — the same Mosaic pipeline the real-TPU tier
    exercises on silicon."""
    mesh = default_mesh(8)

    def f(x):
        return pallas_ring_allreduce(x, "world", 8, tile_rows=8,
                                     interpret=False)

    jf = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P("world"),
                               out_specs=P("world"), check_vma=False))
    exp = jax.export.export(jf, platforms=["tpu"])(
        jax.ShapeDtypeStruct((1024,), jnp.float32))
    assert exp.platforms == ("tpu",)
    assert "tpu_custom_call" in exp.mlir_module()


@pytest.mark.parametrize("opname,npop", [("max", np.max), ("min", np.min)])
@pytest.mark.parametrize("check_vma", [False, True])
def test_pallas_reduce_scatter_max_min(opname, npop, check_vma):
    from mpi_tpu import ops
    from mpi_tpu.tpu import run_spmd

    P_, block = 4, 96
    data = np.asarray(np.random.RandomState(22).randn(P_, P_, block),
                      np.float32)
    op = getattr(ops, opname.upper())

    def prog(comm, x):
        return comm.reduce_scatter(x[comm.rank], op=op,
                                   algorithm="pallas_ring")

    out = np.asarray(run_spmd(prog, data, nranks=P_, check_vma=check_vma))
    np.testing.assert_allclose(out, npop(data, axis=0), rtol=1e-6, atol=1e-6)


def test_pallas_ring_rejects_user_op_with_builtin_name():
    """A make_op combiner named 'max' must NOT silently run jnp.maximum
    (code-review regression: identity gate, not name gate)."""
    from mpi_tpu import ops
    from mpi_tpu.tpu import run_spmd

    fake_max = ops.make_op(lambda a, b: a + b, name="max", identity=0.0)

    def prog(comm, x):
        return comm.allreduce(x[comm.rank], op=fake_max,
                              algorithm="pallas_ring")

    with pytest.raises(NotImplementedError, match="built-in"):
        run_spmd(prog, np.zeros((8, 16), np.float32))


# -- allgather-only mode (round 3) ------------------------------------------


@pytest.mark.parametrize("nranks,n", [(2, 128), (4, 1000), (8, 4096), (3, 77)])
def test_pallas_ring_allgather(nranks, n):
    from mpi_tpu.tpu.pallas_ring import pallas_ring_allgather

    mesh = default_mesh(nranks)
    data = np.asarray(np.random.RandomState(7).randn(nranks, n), np.float32)

    def f(x):
        return pallas_ring_allgather(x.reshape(-1), "world", nranks,
                                     tile_rows=8, interpret=True)[None]

    out = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("world"), out_specs=P("world"),
        check_vma=False))(jnp.asarray(data.reshape(-1)))
    out = np.asarray(out).reshape(nranks, nranks, n)
    for r in range(nranks):
        np.testing.assert_array_equal(out[r], data)


def test_pallas_ring_allgather_bf16_and_2d_blocks():
    from mpi_tpu.tpu.pallas_ring import pallas_ring_allgather

    mesh = default_mesh(4)
    data = np.asarray(np.random.RandomState(9).randn(4, 6, 50), np.float32)
    bf = jnp.asarray(data, jnp.bfloat16)

    def f(x):
        return pallas_ring_allgather(x[0], "world", 4, tile_rows=16,
                                     interpret=True)[None]

    out = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("world"), out_specs=P("world"),
        check_vma=False))(bf)
    out = np.asarray(out.astype(jnp.float32))
    for r in range(4):
        np.testing.assert_allclose(out[r], data.astype(jnp.bfloat16)
                                   .astype(np.float32), rtol=1e-2)


def test_pallas_ring_allgather_via_communicator_and_vma():
    """algorithm='pallas_ring' on allgather under the default
    check_vma=True (interpreter: vma-typed ppermute fallback) and with a
    split communicator's groups."""
    from mpi_tpu.tpu import run_spmd

    data = np.asarray(np.random.RandomState(3).randn(8, 40), np.float32)

    def prog(comm, x):
        return comm.allgather(x[comm.rank], algorithm="pallas_ring")

    out = np.asarray(run_spmd(prog, data))
    for r in range(8):
        np.testing.assert_array_equal(out[r], data)

    def prog_split(comm, x):
        half = comm.split_by(lambda w: w // 4)
        return half.allgather(x[comm.rank], algorithm="pallas_ring")

    out = np.asarray(run_spmd(prog_split, data, check_vma=False))
    for r in range(8):
        base = (r // 4) * 4
        np.testing.assert_array_equal(out[r], data[base:base + 4])
