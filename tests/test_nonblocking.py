"""Nonblocking p2p (Isend/Irecv/Request), Probe/Iprobe semantics."""

import time

import numpy as np
import pytest

from mpi_tpu import Status
from mpi_tpu.transport.local import run_local


def test_irecv_wait():
    def prog(comm):
        if comm.rank == 0:
            req = comm.isend({"k": 1}, dest=1, tag=3)
            assert req.test() == (True, None)
            assert req.wait() is None
            return None
        req = comm.irecv(source=0, tag=3)
        return req.wait()

    res = run_local(prog, 2)
    assert res[1] == {"k": 1}


def test_irecv_test_polls_without_blocking():
    def prog(comm):
        if comm.rank == 0:
            time.sleep(0.15)
            comm.send("late", dest=1, tag=1)
            return None
        req = comm.irecv(source=0, tag=1)
        done, _ = req.test()
        assert not done, "message cannot have arrived yet"
        deadline = time.monotonic() + 5
        while True:
            done, val = req.test()
            if done:
                return val
            assert time.monotonic() < deadline
            time.sleep(0.01)

    res = run_local(prog, 2)
    assert res[1] == "late"


def test_multiple_outstanding_irecvs_fifo():
    def prog(comm):
        if comm.rank == 0:
            for i in range(3):
                comm.isend(i, dest=1, tag=7)
            return None
        reqs = [comm.irecv(source=0, tag=7) for _ in range(3)]
        return [r.wait() for r in reqs]

    res = run_local(prog, 2)
    assert res[1] == [0, 1, 2]


def test_probe_status_then_recv():
    def prog(comm):
        if comm.rank == 0:
            comm.send(np.arange(5), dest=1, tag=42)
            return None
        st = Status()
        comm.probe(source=-1, tag=-1, status=st)
        assert (st.source, st.tag) == (0, 42)
        # probe must not consume
        got = comm.recv(source=st.source, tag=st.tag)
        return got.sum()

    res = run_local(prog, 2)
    assert res[1] == 10


def test_iprobe_preserves_fifo():
    def prog(comm):
        if comm.rank == 0:
            comm.send("first", dest=1, tag=1)
            comm.send("second", dest=1, tag=1)
            return None
        # wait for both to arrive
        deadline = time.monotonic() + 5
        while not comm.iprobe(source=0, tag=1):
            assert time.monotonic() < deadline
            time.sleep(0.005)
        time.sleep(0.05)  # let the second arrive too
        st = Status()
        assert comm.iprobe(source=0, tag=1, status=st)
        assert st.source == 0
        a = comm.recv(source=0, tag=1)
        b = comm.recv(source=0, tag=1)
        return a, b

    res = run_local(prog, 2)
    assert res[1] == ("first", "second")


def test_posted_order_completion_out_of_order_test():
    """MPI matching rule: the first-POSTED request gets the first message,
    even when a later request is tested/completed first."""

    def prog(comm):
        if comm.rank == 0:
            comm.send("a", dest=1, tag=7)
            comm.send("b", dest=1, tag=7)
            return None
        r1 = comm.irecv(source=0, tag=7)
        r2 = comm.irecv(source=0, tag=7)
        deadline = time.monotonic() + 5
        while not r2.test()[0]:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        done, v1 = r1.test()
        assert done
        return v1, r2.wait()

    res = run_local(prog, 2)
    assert res[1] == ("a", "b")


def test_trace_records_polled_receives():
    """Receives completed via Request.test() polling must be visible to the
    matching verifier (they flow through Transport.poll, not the mailbox)."""
    from mpi_tpu.trace import verify_run

    def prog(comm):
        if comm.rank == 0:
            comm.send(1, dest=1, tag=0)
            return None
        req = comm.irecv(source=0, tag=0)
        while not req.test()[0]:
            time.sleep(0.002)

    _, problems = verify_run(prog, 2)
    assert problems == []


def test_poll_on_closed_transport_raises():
    from mpi_tpu.transport.base import Mailbox, TransportError

    mb = Mailbox()
    mb.close()
    with pytest.raises(TransportError):
        mb.poll(0, 0, 1)
    with pytest.raises(TransportError):
        mb.peek_nowait(0, 0, 1)


def test_tpu_nonblocking_diagnostics():
    from mpi_tpu.tpu import SpmdSemanticsError, TpuCommunicator, default_mesh

    comm = TpuCommunicator("world", default_mesh())
    for call in (lambda: comm.isend(1, 0), comm.irecv, comm.probe, comm.iprobe):
        with pytest.raises(SpmdSemanticsError):
            call()


def test_iprobe_false_when_empty():
    def prog(comm):
        assert not comm.iprobe(source=-1, tag=-1)
        comm.barrier()

    run_local(prog, 2)


# -- persistent requests [S: MPI_Send_init / MPI_Recv_init] ------------------


def test_persistent_ping_pong_buffer_reuse():
    """The classic persistent pattern: bind once, refill the numpy buffer in
    place, start/wait in a loop."""

    def prog(comm):
        peer = 1 - comm.rank
        sbuf = np.zeros(2, np.float64)
        rbuf = np.zeros(2, np.float64)
        sreq = comm.send_init(sbuf, peer, tag=7)
        rreq = comm.recv_init(peer, tag=7, buf=rbuf)
        got = []
        for it in range(3):
            sbuf[...] = comm.rank * 100 + it  # refill in place
            sreq.start()
            rreq.start()
            rreq.wait()
            sreq.wait()
            got.append(float(rbuf[0]))
        return got

    res = run_local(prog, 2)
    assert res[0] == [100.0, 101.0, 102.0]
    assert res[1] == [0.0, 1.0, 2.0]


def test_persistent_snapshot_at_start():
    """The send buffer is read at start(), not at wait() — mutating it after
    start must not affect the in-flight message."""

    def prog(comm):
        peer = 1 - comm.rank
        sbuf = np.array([1.0])
        sreq = comm.send_init(sbuf, peer)
        sreq.start()
        sbuf[...] = 99.0  # too late for the in-flight send
        val = comm.recv(peer)
        sreq.wait()
        return float(val[0])

    assert run_local(prog, 2) == [1.0, 1.0]


def test_persistent_state_machine_errors():
    def prog(comm):
        peer = 1 - comm.rank
        req = comm.send_init(np.zeros(1), peer)
        # [S] wait/test on an inactive persistent request: immediate no-op
        assert req.wait() is None
        assert req.test() == (True, None)
        req.start()
        try:
            req.start()  # already active
            return False
        except RuntimeError:
            pass
        comm.recv(peer)
        req.wait()
        return True

    assert all(run_local(prog, 2))


def test_startall():
    from mpi_tpu.communicator import startall

    def prog(comm):
        peer = 1 - comm.rank
        sreq = comm.send_init(np.array([float(comm.rank)]), peer, tag=1)
        rreq = comm.recv_init(peer, tag=1)
        startall([sreq, rreq])
        val = rreq.wait()
        sreq.wait()
        return float(val[0])

    assert run_local(prog, 2) == [1.0, 0.0]


def test_persistent_rejected_on_spmd():
    from mpi_tpu.tpu import SpmdSemanticsError, run_spmd

    def prog(comm):
        try:
            comm.send_init(np.zeros(1, np.float32), 0)
        except SpmdSemanticsError:
            return comm.rank * 0 + 1
        return comm.rank * 0

    assert np.all(np.asarray(run_spmd(prog, nranks=2)) == 1)


# -- Waitany / Waitsome / Testall / Testany (MPI-3 request-set ops) --------


def test_waitany_returns_first_completed():
    from mpi_tpu.api import MPI_Waitany

    def prog(comm):
        if comm.rank == 0:
            comm.send("fast", dest=1, tag=2)   # tag 2 first
            time.sleep(0.1)
            comm.send("slow", dest=1, tag=1)
            return None
        reqs = [comm.irecv(source=0, tag=1), comm.irecv(source=0, tag=2)]
        i, v = MPI_Waitany(reqs)
        assert (i, v) == (1, "fast")
        return reqs[0].wait()

    res = run_local(prog, 2)
    assert res[1] == "slow"


def test_waitsome_collects_all_ready():
    from mpi_tpu.api import MPI_Waitsome

    def prog(comm):
        if comm.rank == 0:
            comm.send(10, dest=1, tag=1)
            comm.send(20, dest=1, tag=2)
            return None
        # give both messages time to arrive so Waitsome sees them together
        time.sleep(0.2)
        reqs = [comm.irecv(source=0, tag=1), comm.irecv(source=0, tag=2)]
        idx, vals = MPI_Waitsome(reqs)
        return idx, vals

    idx, vals = run_local(prog, 2)[1]
    assert idx == [0, 1] and vals == [10, 20]


def test_testall_and_testany():
    from mpi_tpu.api import MPI_Testall, MPI_Testany

    def prog(comm):
        if comm.rank == 0:
            comm.send("a", dest=1, tag=1)
            time.sleep(0.15)
            comm.send("b", dest=1, tag=2)
            return None
        reqs = [comm.irecv(source=0, tag=1), comm.irecv(source=0, tag=2)]
        deadline = time.monotonic() + 5
        while True:  # first message only: Testall must report not-done
            done1, i, v = MPI_Testany(reqs)
            if done1:
                assert (i, v) == (0, "a")
                break
            assert time.monotonic() < deadline
            time.sleep(0.01)
        all_done, vals = MPI_Testall(reqs)
        if not all_done:
            assert vals is None
        while True:
            all_done, vals = MPI_Testall(reqs)
            if all_done:
                # completed request values are sticky across re-polls
                return vals
            assert time.monotonic() < deadline
            time.sleep(0.01)

    assert run_local(prog, 2)[1] == ["a", "b"]


def test_waitany_empty_raises():
    from mpi_tpu.api import MPI_Waitany

    with pytest.raises(ValueError):
        MPI_Waitany([])


def test_testall_keeps_persistent_request_values():
    """Completed persistent requests stay readable across Testall sweeps:
    a value delivered on an early sweep must not be replaced by None when
    later sweeps re-poll (code-review regression)."""
    from mpi_tpu.api import MPI_Testall

    def prog(comm):
        if comm.rank == 0:
            comm.send("a", dest=1, tag=1)
            time.sleep(0.2)
            comm.send("b", dest=1, tag=2)
            return None
        r0 = comm.recv_init(source=0, tag=1).start()
        r1 = comm.recv_init(source=0, tag=2).start()
        deadline = time.monotonic() + 5
        saw_partial = False
        while True:
            all_done, vals = MPI_Testall([r0, r1])
            if all_done:
                return saw_partial, vals
            saw_partial = saw_partial or r0.test()[0]
            assert time.monotonic() < deadline
            time.sleep(0.01)

    saw_partial, vals = run_local(prog, 2)[1]
    assert vals == ["a", "b"], vals
    assert saw_partial  # the early completion really was polled first


def test_waitany_drain_loop_visits_each_request_once():
    """MPI_REQUEST_NULL analogue: a returned request is retired, so the
    canonical drain loop never returns the same completion twice nor
    starves the slower request (code-review regression)."""
    from mpi_tpu.api import MPI_Waitany

    def prog(comm):
        if comm.rank == 0:
            comm.send("first", dest=1, tag=1)
            time.sleep(0.15)
            comm.send("second", dest=1, tag=2)
            return None
        reqs = [comm.irecv(source=0, tag=1), comm.irecv(source=0, tag=2)]
        got = [MPI_Waitany(reqs) for _ in range(2)]
        exhausted = MPI_Waitany(reqs)
        return got, exhausted

    got, exhausted = run_local(prog, 2)[1]
    assert got == [(0, "first"), (1, "second")], got
    assert exhausted == (None, None)


# -- matched probe (MPI-3 Mprobe/Mrecv, round 3) ----------------------------


def test_mprobe_removes_from_matching():
    """After mprobe, a wildcard recv CANNOT steal the matched message —
    the guarantee plain probe lacks."""
    import numpy as np

    from mpi_tpu import Status

    def prog(comm):
        if comm.rank == 0:
            comm.send(np.arange(4.0), dest=1, tag=5)
            comm.send("other", dest=1, tag=6)
            return None
        st = Status()
        msg = comm.mprobe(source=0, tag=5, status=st)
        assert st.tag == 5 and st.count_bytes == 32
        # the tag-5 message is out of matching: ANY_TAG sees only tag 6
        assert comm.recv(source=0, tag=-1) == "other"
        got = msg.recv()
        assert np.array_equal(got, np.arange(4.0))
        with pytest.raises(RuntimeError, match="already-consumed"):
            msg.recv()
        return True

    assert run_local(prog, 2)[1] is True


def test_improbe_nonblocking():
    def prog(comm):
        if comm.rank == 0:
            assert comm.improbe(source=1, tag=9) is None  # nothing yet
            comm.barrier()
            comm.barrier()
            # message definitely delivered between the barriers
            for _ in range(2000):
                m = comm.improbe(source=1, tag=9)
                if m is not None:
                    return m.recv()
                import time

                time.sleep(0.001)
            raise AssertionError("improbe never matched")
        comm.barrier()
        comm.send({"x": 1}, dest=0, tag=9)
        comm.barrier()
        return None

    assert run_local(prog, 2)[0] == {"x": 1}
