"""Execution evidence for the pipelined Pallas ring protocol (VERDICT r2
next-step #2).

The pipelined path of ``pallas_ring._kernel`` cannot execute without a
multi-chip slice (interpreter = serial fallback; one real chip = P=1 early
return), so its credit flow-control protocol is verified here against the
discrete-event model in ``mpi_tpu/tpu/ring_model.py``:

* **exhaustively** — every interleaving of device ops and split DMA
  completions for the small (P, K) where the state space is enumerable;
* **adversarially** — randomized + worst-case schedules (max-latency,
  out-of-order LIFO completion, zero-latency) for P up to 8, K up to 4,
  with full payload tracking;
* **sensitively** — mutated protocols (credits removed, drain removed,
  accumulation skipped) must be CAUGHT, proving the checker can fail.

No jax involved: this is a pure-Python semaphore-level simulation.
"""

import pytest

from mpi_tpu.tpu.ring_model import (
    Accum, DmaStart, ProtocolViolation, RingSim, Signal, Wait,
    device_program, explore_all,
)

ALLREDUCE = dict(rot=0, allgather=True)
REDUCE_SCATTER = dict(rot=-1, allgather=False)
ALLGATHER = dict(rot=0, allgather=True, rs=False)  # ag-only kernel mode


# -- exhaustive: every interleaving of the small configs --------------------


@pytest.mark.parametrize("P,K,coll", [
    (2, 1, ALLREDUCE), (2, 1, REDUCE_SCATTER),
    (2, 2, ALLREDUCE), (2, 2, REDUCE_SCATTER),
    (3, 1, ALLREDUCE), (3, 1, REDUCE_SCATTER),
    (2, 2, ALLGATHER), (3, 1, ALLGATHER),
], ids=["ar2x1", "rs2x1", "ar2x2", "rs2x2", "ar3x1", "rs3x1",
        "ag2x2", "ag3x1"])
def test_exhaustive_no_deadlock_and_drain(P, K, coll):
    """DFS over the full interleaving space: no reachable state deadlocks,
    every terminal state has drained semaphores."""
    visited = explore_all(P, K, **coll)
    assert visited > 10  # the search actually explored something


# -- adversarial schedules at scale, with payload tracking ------------------


@pytest.mark.parametrize("policy", ["random", "eager_compute", "lazy_lifo",
                                    "dma_first"])
@pytest.mark.parametrize("coll", [ALLREDUCE, REDUCE_SCATTER, ALLGATHER],
                         ids=["allreduce", "reduce_scatter", "allgather"])
def test_schedules_all_P_K(policy, coll):
    for P in (2, 3, 4, 5, 8):
        for K in (1, 2, 3, 4):
            for seed in range(4):
                sim = RingSim(P, K, **coll)
                sim.run(policy=policy, seed=seed)
                # run() calls check_final: drained sems + exact payloads


def test_many_random_seeds_largest_config():
    for seed in range(50):
        RingSim(8, 4, **ALLREDUCE).run(policy="random", seed=seed)


# -- bidirectional (counter-rotating) flows ---------------------------------


@pytest.mark.parametrize("P,coll", [
    (2, ALLREDUCE), (2, REDUCE_SCATTER), (2, ALLGATHER),
], ids=["ar2", "rs2", "ag2"])
def test_exhaustive_bidirectional(P, coll):
    """Full interleaving space with one flow per direction.  (P=3
    exhaustive takes minutes — the adversarial sweeps below cover it.)"""
    visited = explore_all(P, 2, dirs=(1, -1), **coll)
    assert visited > 10


@pytest.mark.parametrize("policy", ["random", "eager_compute", "lazy_lifo",
                                    "dma_first"])
@pytest.mark.parametrize("coll", [ALLREDUCE, REDUCE_SCATTER],
                         ids=["allreduce", "reduce_scatter"])
def test_bidirectional_schedules(policy, coll):
    """Counter-rotating flow layouts (including asymmetric tile splits,
    mirroring pallas_ring._flows for odd tile counts) across P and seeds."""
    for P in (2, 3, 4, 5, 8):
        for dirs in [(1, -1), (1, 1, -1), (1, 1, -1, -1),
                     (1, 1, 1, 1, -1, -1, -1, -1)]:
            for seed in range(3):
                sim = RingSim(P, len(dirs), dirs=dirs, **coll)
                sim.run(policy=policy, seed=seed)


# -- bidirectional OVERLAP: the full-duplex claim, checked ------------------


@pytest.mark.parametrize("coll", [ALLREDUCE, REDUCE_SCATTER],
                         ids=["allreduce", "reduce_scatter"])
def test_bidirectional_steady_state_overlap(coll):
    """The 'twice the usable line-rate' claim (pallas_ring.py header)
    requires the two directions to actually carry traffic CONCURRENTLY —
    not merely to split it (VERDICT r3 missing #4).  Checked property:
    whenever RDMAs have nonzero wire time (every latency-bearing
    schedule), right- and left-going RDMAs are simultaneously in flight
    for the overwhelming majority of the busy window, and EVERY physical
    link carries both directions at once at some point (full duplex).

    Thresholds are far below observed values (eager_compute: both-dir
    overlap ≈ 89-98% of ticks across P∈{3,4,8}) but far above what a
    serialized alternation (overlap ≈ 0) could produce."""
    for P in (3, 4, 8):
        for dirs in [(1, -1), (1, 1, -1, -1)]:
            sim = RingSim(P, len(dirs), dirs=dirs, **coll)
            sim.run(policy="eager_compute", seed=0)
            s = sim.occupancy_summary()
            busy = max(s["right_busy_ticks"], s["left_busy_ticks"])
            assert s["both_dir_ticks"] >= 0.6 * busy, (P, dirs, s)
            assert s["links_with_duplex_overlap"] == s["n_links"], (P, dirs, s)
        # random schedule: overlap must still be commonplace, not a fluke
        sim = RingSim(P, 2, dirs=(1, -1), **coll)
        sim.run(policy="random", seed=1)
        s = sim.occupancy_summary()
        assert s["both_dir_ticks"] > 0.1 * s["ticks"], (P, s)


def test_unidirectional_never_uses_left_direction():
    """Control: the unidirectional layout must put ZERO traffic on the
    left direction under every schedule — otherwise the overlap metric
    above would be measuring an artifact of the tracker."""
    for policy in ("random", "eager_compute", "lazy_lifo", "dma_first"):
        sim = RingSim(4, 2, **ALLREDUCE)  # dirs defaults to all-right
        sim.run(policy=policy, seed=0)
        s = sim.occupancy_summary()
        assert s["left_busy_ticks"] == 0, (policy, s)
        assert s["both_dir_ticks"] == 0, (policy, s)
        assert s["right_busy_ticks"] > 0, (policy, s)


def test_zero_latency_control_shows_no_overlap():
    """dma_first completes every RDMA the moment it starts (zero wire
    time) — the overlap tracker must then report NO concurrency in
    either layout, confirming it measures genuine in-flight windows
    rather than bookkeeping noise."""
    sim = RingSim(4, 2, dirs=(1, -1), **ALLREDUCE)
    sim.run(policy="dma_first", seed=0)
    assert sim.occupancy_summary()["both_dir_ticks"] == 0


def test_bidirectional_detector_catches_swapped_credit_direction():
    """Crediting the wrong neighbor on the mirror ring must deadlock or
    corrupt: a -1 flow's writer is its RIGHT neighbor."""
    def prog(my, P_, K_, *, rot, allgather, dirs=None):
        ops = device_program(my, P_, K_, rot=rot, allgather=allgather,
                             dirs=dirs)
        fixed = []
        for op in ops:
            if isinstance(op, Signal) and op.sem[0] == "credit" \
                    and dirs[op.sem[2]] < 0:
                # mis-send the mirror ring's credit to the left neighbor
                fixed.append(Signal((my - 1) % P_, op.sem, op.inc))
            else:
                fixed.append(op)
        return fixed

    caught = []
    for policy in ("random", "eager_compute"):
        for seed in range(5):
            sim = RingSim(4, 2, dirs=(1, -1), **ALLREDUCE,
                          program_override=prog)
            try:
                sim.run(policy=policy, seed=seed)
            except ProtocolViolation as e:
                caught.append(str(e))
    assert caught, "swapped credit direction ran clean"


# -- sensitivity: broken protocols must be caught ---------------------------


def _mutate(drop, P=4, K=2, coll=ALLREDUCE):
    """Run all policies × seeds against a mutated program; return the
    violations caught."""
    def prog(my, P_, K_, *, rot, allgather, dirs=None):
        ops = device_program(my, P_, K_, rot=rot, allgather=allgather,
                             dirs=dirs)
        return [op for op in ops if not drop(op)]

    caught = []
    for policy in ("random", "eager_compute", "lazy_lifo", "dma_first"):
        for seed in range(10):
            sim = RingSim(P, K, **coll, program_override=prog)
            try:
                sim.run(policy=policy, seed=seed)
            except ProtocolViolation as e:
                caught.append(str(e))
    return caught


def test_detector_catches_missing_credit_protocol():
    """Without the credit handshake a sender can overwrite an unconsumed
    landing slot — the model must observe it under some schedule."""
    caught = _mutate(drop=lambda op: (
        (isinstance(op, Wait) and op.sem[0] == "credit")
        or (isinstance(op, Signal) and op.sem[0] == "credit")))
    assert caught, "credit-free protocol ran clean under every schedule"
    assert any("invariant 2" in c or "landing slot" in c for c in caught)


def test_detector_catches_missing_credit_signal_deadlock():
    """Credits waited on but never signalled: the ring must deadlock."""
    caught = _mutate(drop=lambda op: (
        isinstance(op, Signal) and op.sem[0] == "credit"))
    assert caught and all("DEADLOCK" in c for c in caught)


def test_detector_catches_missing_drain():
    """Without the final wait_send drain, send semaphores survive kernel
    exit (invariant 4) — or the run ends with DMAs in flight."""
    def prog(my, P_, K_, *, rot, allgather, dirs=None):
        ops = device_program(my, P_, K_, rot=rot, allgather=allgather,
                             dirs=dirs)
        # drain = the block of ("send",...) waits before the exit barrier
        exit_bar = len(ops) - 3
        body = [op for i, op in enumerate(ops)
                if not (i < exit_bar and i >= exit_bar - 2 * K_
                        and isinstance(op, Wait) and op.sem[0] == "send")]
        return body

    K = 2
    caught = []
    for policy in ("eager_compute", "random"):
        for seed in range(10):
            sim = RingSim(4, K, **ALLREDUCE, program_override=prog)
            try:
                sim.run(policy=policy, seed=seed)
            except ProtocolViolation as e:
                caught.append(str(e))
    assert caught, "drain-free protocol ran clean under every schedule"


def test_detector_catches_skipped_accumulation():
    """Dropping an Accum leaves its landing slot full → the next arrival
    on that slot trips invariant 2, or the data check trips invariant 5."""
    caught = _mutate(drop=lambda op: isinstance(op, Accum) and op.u == 1
                     and op.seg == 0)
    assert caught
    assert any("invariant 2" in c or "invariant 5" in c
               or "landing slot" in c or "data wrong" in c for c in caught)


def test_detector_catches_wrong_chunk_schedule():
    """An off-by-one in the chunk rotation lands the reduced block on the
    wrong rank.  (A uniform rot shift is a *symmetry* of the full
    allreduce, so the detectable mutation is the reduce-scatter layout:
    rot=0 instead of the required rot=-1.)"""
    caught = []
    for seed in range(5):
        sim = RingSim(4, 1, rot=0, allgather=False)
        try:
            sim.run(policy="random", seed=seed)
        except ProtocolViolation as e:
            caught.append(str(e))
    assert caught and any("data wrong" in c or "invariant 5" in c
                          for c in caught)


# -- the model's schedule matches the kernel's chunk indexing ---------------


def test_program_shape_matches_kernel_counts():
    """Structural cross-check: op counts follow the kernel's loop bounds."""
    for P in (2, 3, 4, 8):
        for K in (1, 2, 4):
            n_steps = 2 * (P - 1)
            ops = device_program(0, P, K, rot=0, allgather=True)
            dmas = [op for op in ops if isinstance(op, DmaStart)]
            # one warm-up send per segment + one per (step, seg) except last
            assert len(dmas) == K * n_steps
            accums = [op for op in ops if isinstance(op, Accum)]
            assert len(accums) == K * (P - 1)
            credits = [op for op in ops
                       if isinstance(op, Signal) and op.sem[0] == "credit"]
            # credits stop 2 steps before the end
            assert len(credits) == K * max(0, n_steps - 2)


# -- ring-attention circulation protocol (pallas_attention) ------------------


@pytest.mark.parametrize("P", [2, 3])
def test_attention_exhaustive(P):
    """Full interleaving space of the K/V circulation protocol: no
    deadlock, no slot overwrite, no read-while-landing, sems drain,
    every device folds every block once in ring order.  (P=4 ≈ 143k
    states passes too — run by the round-4 build log; minutes-long, so
    the suite keeps P≤3 and covers P≤8 adversarially below.)"""
    from mpi_tpu.tpu.ring_model import explore_attention

    assert explore_attention(P) > 10


@pytest.mark.parametrize("policy", ["random", "eager_compute", "lazy_lifo",
                                    "dma_first"])
def test_attention_schedules(policy):
    from mpi_tpu.tpu.ring_model import AttentionSim

    for P in (2, 3, 4, 5, 8):
        for seed in range(3):
            AttentionSim(P).run(policy=policy, seed=seed)


def test_attention_detector_catches_missing_wait_send_before_credit():
    """Mutation: crediting BEFORE the forward has read the slot out lets
    the writer land arrival a+2 on top of the in-flight read — the
    checker must catch it (proving it can fail)."""
    from mpi_tpu.tpu.ring_model import (AttentionSim, DmaStart,
                                        ProtocolViolation, Signal, Wait,
                                        attention_program)

    def mutated(my, P):
        ops = attention_program(my, P)
        # move each credit signal to IMMEDIATELY after the fold by
        # deleting the wait_send that precedes it
        out = []
        skip_next_wait_send = False
        for i, op in enumerate(ops):
            if (isinstance(op, Wait) and op.sem[0] == "send"
                    and i + 1 < len(ops)
                    and isinstance(ops[i + 1], Signal)
                    and ops[i + 1].sem[0] == "credit"):
                continue  # drop the wait_send guarding the credit
            out.append(op)
        return out

    caught = 0
    for P in (5, 6, 8):
        for policy in ("eager_compute", "random", "lazy_lifo"):
            for seed in range(6):
                sim = AttentionSim(P)
                sim.progs = [mutated(d, P) for d in range(P)]
                try:
                    sim.run(policy=policy, seed=seed)
                except ProtocolViolation:
                    caught += 1
    assert caught > 0, "mutated protocol was never caught"


def test_attention_detector_catches_missing_credit_wait():
    """Mutation: a sender that skips the credit wait can overwrite an
    unconsumed slot — must be caught (deadlock or slot overwrite)."""
    from mpi_tpu.tpu.ring_model import (AttentionSim, ProtocolViolation,
                                        Wait, attention_program)

    def mutated(my, P):
        return [op for op in attention_program(my, P)
                if not (isinstance(op, Wait) and op.sem[0] == "credit")]

    caught = 0
    for P in (5, 6, 8):
        for seed in range(6):
            sim = AttentionSim(P)
            sim.progs = [mutated(d, P) for d in range(P)]
            try:
                sim.run(policy="eager_compute", seed=seed)
            except ProtocolViolation:
                caught += 1
    assert caught > 0


def test_attention_fold_order_is_checked():
    """Mutation: folding a block out of order must be caught by the
    final fold-log check (payload tracking is real, not vacuous)."""
    from mpi_tpu.tpu.ring_model import AttentionSim, ProtocolViolation

    sim = AttentionSim(3)
    sim.run(policy="random", seed=0)
    sim.folded[1] = list(reversed(sim.folded[1]))
    with pytest.raises(ProtocolViolation, match="ring order"):
        sim.check_final()


# -- multi-head / causal variants of the forward model (round 5) -------------


@pytest.mark.parametrize("hq,hkv,causal", [(4, 2, False), (4, 1, True),
                                           (2, 2, True)])
def test_attention_exhaustive_variants(hq, hkv, causal):
    """VERDICT r4 weak #3: the GQA payload layout and the causal
    fold-skip as EXECUTED model checks, not relabeling arguments —
    every head plane must ride one RDMA, causal folds exactly the
    non-future blocks, full interleaving space.  (P=4 GQA+causal =
    143,112 states passes too — round-5 build log.)"""
    from mpi_tpu.tpu.ring_model import explore_attention

    for P in (2, 3):
        assert explore_attention(P, hq=hq, hkv=hkv, causal=causal) > 10


def test_attention_gqa_plane_split_is_caught():
    """Mutation: a payload that drops a head plane (half the RDMA) must
    be caught by the plane-completeness check."""
    from mpi_tpu.tpu.ring_model import AttentionSim, ProtocolViolation

    class Mutated(AttentionSim):
        def _mk_dma(self, d, u, fi):
            dma = super()._mk_dma(d, u, fi)
            if u == 0 and d == 1:
                dma.payload = frozenset(
                    e for e in dma.payload if e[0][0] != "v")
            return dma

    with pytest.raises(ProtocolViolation, match="head planes"):
        Mutated(3, hq=4, hkv=2).run(policy="random", seed=0)


def test_attention_causal_fold_log_checked():
    """Mutation: a causal run that folds a FUTURE block must fail the
    final log check (the fold-skip is verified, not assumed)."""
    from mpi_tpu.tpu.ring_model import AttentionSim, ProtocolViolation

    sim = AttentionSim(3, causal=True)
    sim.run(policy="random", seed=1)
    sim.folded[0].append(2)  # device 0 "folded" future block 2
    with pytest.raises(ProtocolViolation, match="ring order"):
        sim.check_final()


# -- backward circulation protocol (pallas_attention._bwd_kernel) ------------


@pytest.mark.parametrize("P", [2, 3])
def test_attention_bwd_exhaustive(P):
    """Full interleaving space of the [K,V,dK,dV] backward circulation:
    no deadlock, no slot overwrite, fold-before-forward, sems drain,
    home arrival carries every rank's contribution.  (P=4 = 24,066
    states passes too — run by the round-5 build log; the suite keeps
    P<=3 and covers P<=8 adversarially below.)"""
    from mpi_tpu.tpu.ring_model import explore_attention_bwd

    assert explore_attention_bwd(P) > 10


@pytest.mark.parametrize("policy", ["random", "eager_compute", "lazy_lifo",
                                    "dma_first"])
def test_attention_bwd_schedules(policy):
    from mpi_tpu.tpu.ring_model import AttentionBwdSim

    for P in (2, 3, 4, 5, 8):
        for seed in range(3):
            AttentionBwdSim(P).run(policy=policy, seed=seed)
            AttentionBwdSim(P, hq=4, hkv=2, causal=True).run(
                policy=policy, seed=seed)


def test_attention_bwd_first_ordering_deadlocks():
    """REGRESSION (review round 5): the first backward ordering put the
    previous hop's retire+credit AFTER this hop's credit wait — every
    rank's credit[1] wait at a=2 could only be fed by a signal emitted
    after the neighbor's identical wait: a ring-wide circular wait.
    The model must catch that deadlock at P>=3 (and the shipped
    ordering, with retire+credit FIRST, must not)."""
    from mpi_tpu.tpu.ring_model import (AttentionBwdSim, DmaStart,
                                        ProtocolViolation, Signal, Wait,
                                        attention_bwd_program)

    def buggy(my, P):
        """attention_bwd_program with the pre-review order: credit-wait,
        DmaStart(a), THEN wait_send(a-1) + credit signal."""
        ops = attention_bwd_program(my, P)
        out, i = [], 0
        while i < len(ops):
            op = ops[i]
            # pattern: Wait(send) [Signal credit] [Wait credit] DmaStart
            if (isinstance(op, Wait) and op.sem[0] == "send"
                    and any(isinstance(o, DmaStart)
                            for o in ops[i + 1:i + 4])):
                j = i + 1
                retire = [op]
                while j < len(ops) and isinstance(ops[j], Signal) \
                        and ops[j].sem[0] == "credit":
                    retire.append(ops[j])
                    j += 1
                rest = []
                while j < len(ops) and not isinstance(ops[j], DmaStart):
                    rest.append(ops[j])
                    j += 1
                if j < len(ops) and isinstance(ops[j], DmaStart):
                    # reorder: credit-wait, start, THEN retire+credit
                    out += rest + [ops[j]] + retire
                    i = j + 1
                    continue
            out.append(op)
            i += 1
        return out

    deadlocked = 0
    for P in (3, 4, 5):
        sim = AttentionBwdSim(P)
        sim.progs = [buggy(d, P) for d in range(P)]
        try:
            sim.run(policy="dma_first", seed=0)
        except ProtocolViolation as e:
            assert "DEADLOCK" in str(e) or "invariant" in str(e)
            deadlocked += 1
    assert deadlocked == 3, "the buggy ordering was never caught"
    # and P=2 (no credits) is fine either way
    sim = AttentionBwdSim(2)
    sim.progs = [buggy(d, 2) for d in range(2)]
    sim.run(policy="dma_first", seed=0)


def test_attention_bwd_fold_before_forward_is_caught():
    """Mutation: forwarding a block BEFORE folding my contribution into
    it (DmaStart hoisted above Accum) must trip invariant 5b."""
    from mpi_tpu.tpu.ring_model import (Accum, AttentionBwdSim, DmaStart,
                                        ProtocolViolation,
                                        attention_bwd_program)

    def mutated(my, P):
        ops = attention_bwd_program(my, P)
        for a in range(1, P):
            # swap so DmaStart(a) precedes Accum(a)
            i = next(i for i, op in enumerate(ops)
                     if isinstance(op, Accum) and op.u == a)
            j = next(j for j, op in enumerate(ops)
                     if isinstance(op, DmaStart) and op.u == a)
            if i < j:
                ops[i], ops[j] = ops[j], ops[i]
        return ops

    caught = 0
    for P in (3, 4):
        sim = AttentionBwdSim(P)
        sim.progs = [mutated(d, P) for d in range(P)]
        try:
            sim.run(policy="random", seed=2)
        except ProtocolViolation as e:
            assert "5b" in str(e) or "EMPTY" in str(e)
            caught += 1
    assert caught > 0


def test_attention_bwd_missing_credit_wait_caught():
    """Mutation: a backward sender skipping credit waits can overwrite
    an unconsumed slot — must be caught."""
    from mpi_tpu.tpu.ring_model import (AttentionBwdSim, ProtocolViolation,
                                        Wait, attention_bwd_program)

    def mutated(my, P):
        return [op for op in attention_bwd_program(my, P)
                if not (isinstance(op, Wait) and op.sem[0] == "credit")]

    caught = 0
    for P in (5, 6, 8):
        for seed in range(6):
            sim = AttentionBwdSim(P)
            sim.progs = [mutated(d, P) for d in range(P)]
            try:
                sim.run(policy="eager_compute", seed=seed)
            except ProtocolViolation:
                caught += 1
    assert caught > 0


def test_attention_bwd_home_grads_checked():
    """Mutation: dropping one rank's contribution from a home payload
    must trip invariant 5d (the full-cycle accumulation is verified)."""
    from mpi_tpu.tpu.ring_model import AttentionBwdSim, ProtocolViolation

    class Mutated(AttentionBwdSim):
        def _accum(self, d, u, seg):
            if u == self.P and d == 0:
                slot = (u % 2, seg)
                state, payload = self.comm[d][slot]
                self.comm[d][slot] = (
                    state, frozenset(e for e in payload
                                     if e != ("g", 1)))
            super()._accum(d, u, seg)

    with pytest.raises(ProtocolViolation, match="5d"):
        Mutated(3).run(policy="random", seed=0)
