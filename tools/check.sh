#!/usr/bin/env bash
# CI/tooling gate: compile everything, lint the shipped tree, and (when a
# tier-1 log is supplied) enforce the committed DOTS_PASSED floor.
#
# Usage:
#   bash tools/check.sh                 # compileall + mpilint
#   bash tools/check.sh /tmp/_t1.log    # ... + tier1_guard on that log
#
# The tier-1 log comes from the ROADMAP verify line (tee /tmp/_t1.log);
# without one the guard step is skipped with a note, so the gate stays
# runnable as a fast pre-commit check.
#
# Lint scope: the WHOLE tree, including tests/ and benchmarks/.  The
# deliberately-broken programs in tests/ (verifier fixtures, the
# tests/lint_corpus/ seeded-bug set) are enumerated with rationales in
# tools/lint_baseline.json; the gate fails on any finding OUTSIDE that
# allowance.  examples/ and mpi_tpu/ have no baseline entries and must
# lint clean outright.

set -euo pipefail
cd "$(dirname "$0")/.."

echo "check.sh: python -m compileall (syntax gate)"
python -m compileall -q mpi_tpu tools examples benchmarks tests bench.py

echo "check.sh: mpilint (v2 engine) over examples/ + mpi_tpu/ + tests/ + benchmarks/ vs tools/lint_baseline.json"
python tools/mpilint.py --baseline tools/lint_baseline.json \
    examples mpi_tpu tests benchmarks

echo "check.sh: tune.py --check over committed tuning tables"
tables=$(ls benchmarks/results/tuning/*.json 2>/dev/null || true)
if [ -n "$tables" ]; then
    # shellcheck disable=SC2086 - word-splitting the glob is the point
    python tools/tune.py --check $tables
else
    echo "check.sh: no committed tuning tables — step skipped" \
         "(generate one with: python bench.py --tune)"
fi

if [ "${1:-}" != "" ]; then
    echo "check.sh: tier1_guard on $1"
    python tools/tier1_guard.py "$1"
else
    echo "check.sh: no tier-1 log supplied — guard step skipped" \
         "(run the ROADMAP verify line with tee, then:" \
         "bash tools/check.sh /tmp/_t1.log)"
fi

echo "check.sh: OK"
