"""Fused ring attention as a Pallas TPU kernel (RDMA over ICI).

Long-context exact attention over a sequence-sharded axis (SURVEY.md §2
strategy table — the long-context strategy is first-class).  The
ppermute spelling lives in ``examples/ring_attention.py``; this module
is its TPU-first hot path: ONE kernel in which the K/V blocks circulate
the ring as RDMAs while the MXU computes attention against the block
that just landed — transfer hidden behind compute, the same
communication/compute overlap argument as ``pallas_ring``.

Protocol (a sibling of pallas_ring's — verified by the discrete-event
model ``ring_model.AttentionSim``, tests/test_pallas_protocol.py):

* Each device holds Q, K, V blocks of the sequence ([Sb, d] each).  At
  step 0 it computes attention of its Q against its OWN K/V and starts
  forwarding that K/V (one stacked [2*Sb, d] RDMA) to its right
  neighbor's landing slot.
* Arrival ``a`` (1..P-1) lands K/V block ``(rank - a) mod P`` in the
  double-buffered comm slot ``a % 2``; the device folds it into the
  online-softmax state (running rowmax ``m``, denominator ``l``,
  weighted accumulator ``o`` — all f32), and, while the fold runs,
  forwards the same block from the slot to the next neighbor.
* **Credit flow control** recycles the slots: arrival ``a+2`` re-uses
  slot ``a % 2``, so after consuming arrival ``a`` (fold done AND the
  forwarding RDMA has left the slot — ``wait_send`` precedes the
  credit) the device signals one credit to its LEFT neighbor, which
  gates that neighbor's send ``a+1``.  Sends 0 and 1 are credit-free
  (their target slots are virgin).
* Entry/exit neighbor barriers bracket the kernel, as in pallas_ring.

Numerics: the online-softmax recurrence
``m' = max(m, rowmax(S)); l' = l·e^{m-m'} + rowsum(e^{S-m'});
o' = o·e^{m-m'} + e^{S-m'}·V`` is an exact (not approximate) attention
— the standard flash/ring-attention algebra.  Accumulation is float32
for bf16 inputs.  Full OR causal attention (``causal=True`` masks by
global position — block indices come from the SMEM params, so the same
compiled kernel serves every rank); scale = 1/sqrt(d) by default.

**VMEM planning** (``attention_vmem_plan`` — VERDICT r4 missing #2):
the fold is executed in one of two modes chosen at trace time from a
VMEM budget:

* *resident* — Q, the K/V staging buffer, and the m/l/o state all live
  in VMEM and each fold materializes one [Sb, Sb] score block.  The
  fast path for blocks up to ~1-2k rows at d=128/f32.
* *tiled* — flash-attention-style inner tiling: the m/l/o state lives
  in HBM scratch; each arrival loops over [tq]-row query tiles and
  [tk]-row K/V tiles (``lax.fori_loop``), staging each tile through
  small VMEM buffers, so the live score block is [tq, tk] and the
  block size is bounded by HBM, not VMEM.  Tile sizes are the largest
  sublane-aligned divisors of Sb that fit the budget.

Either way the RDMA circulation (slots, credits, barriers) is
IDENTICAL — the fold is a local subroutine between protocol events, so
``AttentionSim``'s verification covers both modes.  An impossible
budget (no tile fits) is diagnosed at trace time with the math shown.

**Fused backward** (``_bwd_kernel`` — VERDICT r4 missing #3): under
differentiation the forward also emits the per-row logsumexp
``L = m + log l`` (skipped entirely on inference/fallback paths); the
backward is its own ring kernel in which [K, V] circulate in the INPUT
dtype and [dK, dV] in f32 (the wire-dtype != fold-dtype split, ISSUE
8 / VERDICT r5 #5: pristine K/V inputs lose nothing below f32 — bf16
halves their wire bytes — while the dK/dV partial sums keep full
precision; two RDMAs per hop on per-plane semaphore columns, protocol
otherwise unchanged) for a FULL cycle of P sends — each device
recomputes its block pair's probabilities from (Q, L), accumulates dQ
locally, adds its dK/dV contribution into the circulating payload, and
forwards; after P hops the accumulators land back home.  Fold-before-forward ordering
(the payload is mutated before it moves on) with the same
double-buffer + credit discipline — model-checked separately by
``ring_model.AttentionBwdSim``.  The backward fold is VMEM-planned
like the forward: resident, or flash-tiled (dQ accumulating in its
HBM output; a K/V-tile outer loop carries dK/dV accumulators over a
Q-tile inner loop), so long-context training stays on the fused
kernels; only an impossible budget falls back to recomputing through
the pure-jax ppermute ring (correct at any size).

Under the interpreter (CPU tier) RDMAs run serially (start+wait, no
credits/barriers) — same data path, no overlap; under vma typing or a
multi-axis mesh the interpreter executes a ppermute ring fallback
(same online-softmax algebra as jax ops) with the shared loud-fallback
warning.  The compiled multi-axis path addresses neighbors by mesh
coordinate exactly like pallas_ring.

Restrictions (diagnosed): f32/bf16; head dim ``d`` a multiple of 128
(lane width); block rows ``Sb`` a multiple of 8 (sublane tile); a
VMEM budget no tile size can satisfy raises with the numbers.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_ring import _check_args, _fallback, _world_pairs_of

_LANES = 128


_MASKED = -1e30  # large-negative finite (an -inf mask would NaN through exp)

# Conservative default VMEM budget: 16 MiB/core on current TPUs, minus
# headroom for Mosaic's own spills/semaphores/metadata.
_VMEM_BUDGET = 12 * 2 ** 20


def _online_fold(q, k, v, m, l, o, scale, mask=None):
    """One block's online-softmax fold (shared by kernel and fallback).
    q:[Sq,d] k,v:[Sb,d] m,l:[Sq,1] o:[Sq,d] (f32 state) → new (m,l,o).
    ``mask``: optional [Sq,Sb] bool, True = attend (False → _MASKED;
    a fully-masked block folds as exactly zero contribution)."""
    s = jnp.dot(q.astype(jnp.float32), k.astype(jnp.float32).T,
                preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, _MASKED)
    m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
    o_new = o * alpha + jnp.dot(p, v.astype(jnp.float32),
                                preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


def _causal_mask(my, kv_idx, sb: int, i0=0, j0=0,
                 tq: Optional[int] = None, tk: Optional[int] = None):
    """[tq,tk] causal mask for rows ``i0..`` of query block ``my`` vs
    rows ``j0..`` of key block ``kv_idx`` (block indices traced, tile
    offsets traced or static): global key position must not exceed
    global query position.  Defaults cover the whole [Sb,Sb] block."""
    tq = sb if tq is None else tq
    tk = sb if tk is None else tk
    qi = my * sb + i0 + lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
    kj = kv_idx * sb + j0 + lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
    return kj <= qi


def _divisors_desc(n: int):
    """Divisors of n, descending."""
    out = set()
    i = 1
    while i * i <= n:
        if n % i == 0:
            out.add(i)
            out.add(n // i)
        i += 1
    return sorted(out, reverse=True)


# Shared kernel helpers (one definition serves forward and backward —
# review round 5: protocol-critical code must not exist in two copies).


def _mk_dev_kw(mesh_ids: bool, axis_name: str):
    """device_id kwargs for an RDMA/signal aimed at axis index
    ``target`` (1-D logical ids, or dict-MESH coordinates on a
    multi-axis mesh — same scheme as pallas_ring)."""
    def dev_kw(target):
        if mesh_ids:
            return dict(device_id={axis_name: target},
                        device_id_type=pltpu.DeviceIdType.MESH)
        return dict(device_id=target,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)

    return dev_kw


def _mk_barrier(pipelined: bool, dev_kw, left, right):
    """Entry/exit neighbor barrier (no-op on the serial interpreter)."""
    def neighbor_barrier():
        if not pipelined:
            return
        bar = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(bar, inc=1, **dev_kw(left))
        pltpu.semaphore_signal(bar, inc=1, **dev_kw(right))
        pltpu.semaphore_wait(bar, 2)

    return neighbor_barrier


def _mk_copy_sync(copy_sem):
    """Local start+wait DMA through the shared copy semaphore."""
    def copy_sync(src, dst):
        cp = pltpu.make_async_copy(src, dst, copy_sem)
        cp.start()
        cp.wait()

    return copy_sync


def _mk_copy_par(par_sems):
    """Start INDEPENDENT local DMAs together, then wait them all — the
    tiled folds stage several disjoint tiles per step (k+v; the m/l/o
    state; the backward's five residuals) and serializing them exposes
    every transfer's full HBM latency on chip (round 5).  Each copy
    gets its own semaphore by POSITION (all indices Python-static)."""
    def copy_par(*pairs):
        cps = [pltpu.make_async_copy(src, dst, par_sems.at[i])
               for i, (src, dst) in enumerate(pairs)]
        for cp in cps:
            cp.start()
        for cp in cps:
            cp.wait()

    return copy_par


def _pair_grad_tile(qh, doh, lse1, delta1, kb, vb, scale, mask=None):
    """ONE copy of the flash-backward algebra (review round 5: the
    resident and tiled folds must not carry separate copies of it):
    given f32 Q/dO rows, their lse/delta columns, and a K/V tile,
    return (dq, dk, dv) contributions.  ``mask``: optional [rows, cols]
    bool, True = attend (probabilities zeroed elsewhere)."""
    s = jnp.dot(qh, kb.T, preferred_element_type=jnp.float32) * scale
    p = jnp.exp(s - lse1)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    dp = jnp.dot(doh, vb.T, preferred_element_type=jnp.float32)
    ds_ = p * (dp - delta1) * scale
    return (jnp.dot(ds_, kb, preferred_element_type=jnp.float32),
            jnp.dot(ds_.T, qh, preferred_element_type=jnp.float32),
            jnp.dot(p.T, doh, preferred_element_type=jnp.float32))


def _mk_snd(first_src, comm_hbm, send_sem, recv_sem, dev_kw, right,
            col=None):
    """Send-descriptor factory shared by both ring kernels: send ``u``
    forwards from ``first_src`` (u == 0: the block that never landed in
    a slot) or comm slot u%2, into the right neighbor's slot (u+1)%2,
    on the (parity)-indexed send/recv semaphores.  One definition —
    the slot/sem indexing IS the protocol the models check.

    ``col`` selects a PLANE column of (parity, plane)-shaped semaphores:
    the split-dtype backward (wire-dtype K/V + f32 dK/dV, ISSUE 8 /
    VERDICT r5 #5) circulates two buffers per hop, each on its own
    semaphore column but the SAME slot parity — the protocol schedule is
    untouched, only the payload is split."""
    def snd(u):
        dst_slot = (u + 1) % 2
        src = first_src if u == 0 else comm_hbm.at[u % 2]
        if col is None:
            ss, rs = send_sem.at[dst_slot], recv_sem.at[dst_slot]
        else:
            ss, rs = send_sem.at[dst_slot, col], recv_sem.at[dst_slot, col]
        return pltpu.make_async_remote_copy(
            src_ref=src, dst_ref=comm_hbm.at[dst_slot],
            send_sem=ss, recv_sem=rs,
            **dev_kw(right))

    return snd


class _SndPair:
    """Both planes of one split-dtype circulation hop as one descriptor:
    every protocol call fans out to the K/V-plane and dK/dV-plane RDMAs
    (same hop, same slot parity, per-plane semaphore columns), so the
    backward's send/credit schedule reads — and model-checks — exactly
    like the single-buffer version."""

    __slots__ = ("kv", "dkv")

    def __init__(self, kv, dkv):
        self.kv, self.dkv = kv, dkv

    def start(self):
        self.kv.start()
        self.dkv.start()

    def wait(self):
        self.kv.wait()
        self.dkv.wait()

    def wait_send(self):
        self.kv.wait_send()
        self.dkv.wait_send()

    def wait_recv(self):
        self.kv.wait_recv()
        self.dkv.wait_recv()


def attention_vmem_plan(sb: int, d: int, hq: int, hkv: int, dtype,
                        vmem_limit_bytes: Optional[int] = None,
                        for_backward: bool = False):
    """Choose the fold execution mode from a VMEM budget (trace time).

    Returns ``("resident", None)`` when the whole-block fold fits, or
    ``("tiled", (tq, tk))`` with the largest sublane-aligned divisor
    tile that fits — for the forward AND (round 5) the backward.  A
    backward no tile can satisfy returns ``("fallback", None)`` (→
    ppermute recompute, correct at any size); the forward instead
    raises NotImplementedError with the arithmetic — which the caller
    (pallas_ring_attention) converts into the loud ppermute-ring
    fallback (warning + ``attention_fallbacks`` pvar), so an over-tight
    budget degrades instead of failing (ROADMAP r5 #4).

    The estimates are deliberately generous (temporaries counted at
    f32, a spare plane for Mosaic's fusions) so a "resident" or
    "tiled" verdict holds on real hardware with headroom."""
    from .pallas_ring import _SUBLANES

    esz = jnp.dtype(dtype).itemsize
    limit = _VMEM_BUDGET if vmem_limit_bytes is None else vmem_limit_bytes
    sub = _SUBLANES.get(jnp.dtype(dtype), 8)
    if for_backward:
        resident = (hq * sb * d * esz          # Q
                    + hq * sb * d * esz        # dOut
                    + 2 * hq * sb * _LANES * 4  # lse, delta staging
                    + 2 * hkv * sb * d * esz   # K/V staging (wire dtype)
                    + 2 * hkv * sb * d * 4     # dK/dV staging
                    + hq * sb * d * 4          # dQ accumulator
                    + 4 * sb * sb * 4          # s/p/dp/ds temporaries
                    + 2 * sb * d * 4)          # fold temporaries
        if resident <= limit:
            return ("resident", None)
        for mdiv in _divisors_desc(sb // sub):
            t = sub * mdiv
            tiled = (2 * t * d * esz           # q/do tiles
                     + 2 * t * _LANES * 4      # lse/delta tiles
                     + 2 * t * d * esz         # k/v tiles (wire dtype)
                     + 2 * t * d * 4           # dk/dv staging buffers
                     + t * d * 4               # dq tile
                     + 2 * t * d * 4           # dk/dv loop carries
                     + 4 * t * t * 4           # s/p/dp/ds temporaries
                     + 2 * t * d * 4)          # fold temporaries
            if tiled <= limit:
                return ("tiled", (t, t))
        return ("fallback", None)  # recompute always works
    resident = (hq * sb * d * esz              # Q
                + 2 * hkv * sb * d * esz       # K/V staging
                + 2 * hq * sb * _LANES * 4     # m, l ([.., 1] buffers are
                #                                lane-padded to 128 lanes)
                + hq * sb * _LANES * 4         # lse staging
                + hq * sb * d * 4              # o accumulator
                + 2 * sb * sb * 4              # score + exp temporaries
                + 2 * sb * d * 4)              # fold temporaries
    if resident <= limit:
        return ("resident", None)
    for mdiv in _divisors_desc(sb // sub):
        t = sub * mdiv
        tiled = (3 * t * d * esz               # q/k/v tiles
                 + 2 * t * _LANES * 4          # m, l tiles
                 + t * d * 4                   # o tile
                 + 2 * t * t * 4               # score tile temporaries
                 + 2 * t * d * 4)              # fold temporaries
        if tiled <= limit:
            return ("tiled", (t, t))
    t = sub
    need = 3 * t * d * esz + 2 * t * _LANES * 4 + t * d * 4 \
        + 2 * t * t * 4 + 2 * t * d * 4
    raise NotImplementedError(
        f"ring attention cannot fit VMEM budget {limit} bytes: even the "
        f"minimal {t}-row tile at d={d} needs ~{need} bytes "
        f"(Sb={sb}, Hq={hq}, Hkv={hkv}, {jnp.dtype(dtype).name}). "
        f"Raise vmem_limit_bytes or shrink the head dim.")


def _kernel(params_smem, q_hbm, kv_hbm, *refs,
            axis_name: str, size: int, sb: int, d: int,
            scale: float, pipelined: bool, mesh_ids: bool,
            causal: bool = False, hq: int = 1, hkv: int = 1,
            tiles: Optional[Tuple[int, int]] = None,
            with_lse: bool = False):
    """See module docstring for the step/slot/credit schedule.

    Multi-head layout (``hq`` query heads, ``hkv`` K/V heads — GQA when
    hkv < hq): the per-head [Sb, dh] planes are stacked along rows —
    q/out/m/l/o rows [h*Sb, (h+1)*Sb) belong to query head h; the
    circulating buffer stacks all K planes then all V planes
    ([hkv*Sb] + [hkv*Sb] rows), so ONE RDMA moves every head's K/V and
    the circulation/credit protocol is byte-identical to the
    single-head case (pure payload relabeling — verified by the GQA
    AttentionSim runs, tests/test_pallas_protocol.py).

    ``tiles=None`` → resident fold; ``tiles=(tq, tk)`` → flash-style
    inner tiling with the m/l/o state in HBM scratch (module
    docstring).  The protocol events are identical in both modes.

    ``with_lse`` adds a second output ref carrying L = m + log l (the
    fused backward's residual); inference/fallback-backward paths skip
    its VMEM broadcast and HBM write entirely."""
    if with_lse:
        out_hbm, lse_hbm = refs[0], refs[1]
        refs = refs[2:]
    else:
        out_hbm, lse_hbm = refs[0], None
        refs = refs[1:]
    if tiles is None:
        if with_lse:
            (comm_hbm, q_vmem, kv_vmem, m_vmem, l_vmem, o_vmem, lse_vmem,
             copy_sem, send_sem, recv_sem, credit_sem,
             par_sems) = refs
        else:
            (comm_hbm, q_vmem, kv_vmem, m_vmem, l_vmem, o_vmem,
             copy_sem, send_sem, recv_sem, credit_sem,
             par_sems) = refs
    else:
        (comm_hbm, m_hbm, l_hbm, o_hbm, qt_vmem, kt_vmem, vt_vmem,
         mt_vmem, lt_vmem, ot_vmem,
         copy_sem, send_sem, recv_sem, credit_sem, par_sems) = refs
        tq, tk = tiles
    left = params_smem[0]
    right = params_smem[1]
    my = params_smem[2]
    P = size
    g = hq // hkv  # query heads per K/V head (GQA group size)
    dev_kw = _mk_dev_kw(mesh_ids, axis_name)
    neighbor_barrier = _mk_barrier(pipelined, dev_kw, left, right)
    copy_sync = _mk_copy_sync(copy_sem)
    copy_par = _mk_copy_par(par_sems)
    # send u (0..P-2): the block computed at step u moves on
    fwd_rdma = _mk_snd(kv_hbm, comm_hbm, send_sem, recv_sem, dev_kw, right)

    # -- resident fold: whole block staged in VMEM --------------------------

    def load_kv(src_ref):
        copy_sync(src_ref, kv_vmem)

    def fold_resident(a):
        def body(mask):
            for h in range(hq):
                kvh = h // g
                rows = pl.ds(h * sb, sb)
                k = kv_vmem[pl.ds(kvh * sb, sb), :]
                v = kv_vmem[pl.ds((hkv + kvh) * sb, sb), :]
                m, l, o = _online_fold(q_vmem[rows, :], k, v,
                                       m_vmem[rows, :], l_vmem[rows, :],
                                       o_vmem[rows, :], scale, mask)
                m_vmem[rows, :] = m
                l_vmem[rows, :] = l
                o_vmem[rows, :] = o

        if not causal:
            body(None)
            return
        # arrival a carries K/V block (my - a) mod P; the first fold
        # (a=0, own block) always has its diagonal unmasked, so the
        # running max is finite from step 0 on.  Blocks entirely in the
        # future (kv_idx > my) contribute exactly zero — skip their MXU
        # passes outright (the circulation/credit schedule above is
        # untouched, so the model-checked protocol is unchanged).
        kv_idx = lax.rem(my - a + P, P)

        @pl.when(kv_idx <= my)
        def _():
            body(_causal_mask(my, kv_idx, sb))

    # -- tiled fold: state in HBM, flash-style [tq, tk] inner loop ----------

    def fold_tiled(a, src):
        """Fold arrival ``a`` whose K/V block sits in HBM ref ``src``
        ([2*hkv*sb, d]).  Reads never conflict with the concurrent
        forwarding RDMA (read/read); the credit still follows both the
        fold and wait_send in program order, so slot recycling is
        exactly the resident protocol."""
        nq, nk = sb // tq, sb // tk

        def run(kv_idx):
            for h in range(hq):
                kvh = h // g
                base = h * sb

                def q_body(i, _, h=h, kvh=kvh, base=base):
                    r0 = base + i * tq
                    if a == 0:
                        copy_sync(q_hbm.at[pl.ds(r0, tq)], qt_vmem)
                        m0 = jnp.full((tq, 1), -jnp.inf, jnp.float32)
                        l0 = jnp.zeros((tq, 1), jnp.float32)
                        o0 = jnp.zeros((tq, d), jnp.float32)
                    else:
                        # the q tile rides the same parallel batch as
                        # the state tiles (review round 5)
                        copy_par((q_hbm.at[pl.ds(r0, tq)], qt_vmem),
                                 (m_hbm.at[pl.ds(r0, tq)], mt_vmem),
                                 (l_hbm.at[pl.ds(r0, tq)], lt_vmem),
                                 (o_hbm.at[pl.ds(r0, tq)], ot_vmem))
                        m0 = mt_vmem[:, :1]
                        l0 = lt_vmem[:, :1]
                        o0 = ot_vmem[:]

                    def k_body(j, carry):
                        m, l, o = carry
                        copy_par((src.at[pl.ds(kvh * sb + j * tk, tk)],
                                  kt_vmem),
                                 (src.at[pl.ds((hkv + kvh) * sb + j * tk,
                                               tk)], vt_vmem))
                        mask = None
                        if causal:
                            mask = _causal_mask(my, kv_idx, sb,
                                                i * tq, j * tk, tq, tk)
                        return _online_fold(qt_vmem[:], kt_vmem[:],
                                            vt_vmem[:], m, l, o, scale,
                                            mask)

                    nk_eff = nk
                    if causal:
                        # on the DIAGONAL block (kv_idx == my) k-tiles
                        # past this q-tile's last row are fully masked
                        # — skip their DMAs and MXU passes (roughly
                        # half the tile grid; review round 5).  Earlier
                        # blocks (kv_idx < my) need every tile.
                        nk_eff = jnp.where(
                            kv_idx == my,
                            (i * tq + tq + tk - 1) // tk, nk)
                    m, l, o = lax.fori_loop(0, nk_eff, k_body,
                                            (m0, l0, o0))
                    mt_vmem[:] = jnp.broadcast_to(m, (tq, _LANES))
                    lt_vmem[:] = jnp.broadcast_to(l, (tq, _LANES))
                    ot_vmem[:] = o
                    copy_par((mt_vmem, m_hbm.at[pl.ds(r0, tq)]),
                             (lt_vmem, l_hbm.at[pl.ds(r0, tq)]),
                             (ot_vmem, o_hbm.at[pl.ds(r0, tq)]))
                    return 0

                lax.fori_loop(0, nq, q_body, 0)

        if causal and a > 0:
            kv_idx = lax.rem(my - a + P, P)

            @pl.when(kv_idx <= my)
            def _():
                run(kv_idx)
        else:
            run(my)  # a == 0 → kv_idx == my; mask unused when not causal

    def fold(a, src):
        if tiles is None:
            fold_resident(a)
        else:
            fold_tiled(a, src)

    # init: Q to VMEM; online-softmax state (resident mode only — the
    # tiled state is written by the a=0 fold, which loads no prior state)
    if tiles is None:
        copy_sync(q_hbm, q_vmem)
        m_vmem[:] = jnp.full((hq * sb, 1), -jnp.inf, jnp.float32)
        l_vmem[:] = jnp.zeros((hq * sb, 1), jnp.float32)
        o_vmem[:] = jnp.zeros((hq * sb, d), jnp.float32)

    neighbor_barrier()

    # step 0: my own block computes and starts circulating
    if tiles is None:
        load_kv(kv_hbm)
    fold(0, kv_hbm)
    if P >= 2:
        fwd_rdma(0).start()
        if pipelined:
            fwd_rdma(0).wait_send()  # sem hygiene, as in attention_program
        else:
            fwd_rdma(0).wait()

    for a in range(1, P):
        slot = a % 2
        if pipelined:
            fwd_rdma(a - 1).wait_recv()  # arrival a lands in comm[slot]
        if tiles is None:
            load_kv(comm_hbm.at[slot])
        if a <= P - 2:
            # forward while the fold below runs; send a >= 2 first
            # waits for the credit arming its destination slot
            if pipelined:
                if a >= 2:
                    pltpu.semaphore_wait(credit_sem.at[(a + 1) % 2], 1)
                fwd_rdma(a).start()
            else:
                fwd_rdma(a).start()
                fwd_rdma(a).wait()
        fold(a, comm_hbm.at[slot])
        if pipelined and a <= P - 2:
            # slot free only after the forward READ it out (wait_send),
            # then credit the writer for arrival a+2's reuse
            fwd_rdma(a).wait_send()
        if pipelined and a + 2 <= P - 1:
            pltpu.semaphore_signal(credit_sem.at[slot], inc=1,
                                   **dev_kw(left))

    # output: out = o / l and (with_lse) the logsumexp L = m + log l —
    # the fused backward kernel's residual
    if tiles is None:
        out = o_vmem[:] / l_vmem[:]
        if with_lse:
            lse_vmem[:] = jnp.broadcast_to(
                m_vmem[:] + jnp.log(l_vmem[:]), (hq * sb, _LANES))
        o_vmem[:] = out
        if with_lse:
            copy_par((o_vmem, out_hbm), (lse_vmem, lse_hbm))
        else:
            copy_sync(o_vmem, out_hbm)
    else:
        def out_body(i, _):
            r0 = i * tq
            copy_par((m_hbm.at[pl.ds(r0, tq)], mt_vmem),
                     (l_hbm.at[pl.ds(r0, tq)], lt_vmem),
                     (o_hbm.at[pl.ds(r0, tq)], ot_vmem))
            ot_vmem[:] = ot_vmem[:] / lt_vmem[:, :1]
            copy_sync(ot_vmem, out_hbm.at[pl.ds(r0, tq)])
            if with_lse:
                mt_vmem[:] = mt_vmem[:] + jnp.log(lt_vmem[:])
                copy_sync(mt_vmem, lse_hbm.at[pl.ds(r0, tq)])
            return 0

        lax.fori_loop(0, (hq * sb) // tq, out_body, 0)

    neighbor_barrier()


def _bwd_kernel(params_smem, q_hbm, kv_hbm, do_hbm, lse_hbm, delta_hbm,
                dq_hbm, dkv_hbm, own_kv_hbm, own_dkv_hbm,
                comm_kv_hbm, comm_dkv_hbm, *refs,
                axis_name: str, size: int, sb: int, d: int, scale: float,
                pipelined: bool, mesh_ids: bool, causal: bool,
                hq: int, hkv: int,
                tiles: Optional[Tuple[int, int]] = None):
    """Fused ring-attention backward: [K, V] and [dK, dV] circulate for
    a FULL cycle of P sends; dQ accumulates locally; dK/dV accumulate
    in the circulating payload and land home at arrival P.
    Fold-BEFORE-forward (the payload is mutated, then moves on),
    double-buffered slots, credits gating sends u >= 2; the retire +
    credit of hop u-1 comes BEFORE hop u's credit wait — a signal must
    precede, in program order, any wait it transitively feeds, or the
    ring deadlocks at P >= 3 (review round 5 caught exactly that bug
    in the first ordering).  The schedule is model-checked by
    ``ring_model.AttentionBwdSim`` (sends 0..P-1, arrivals 1..P, the
    home arrival consumed without forwarding — exhaustive interleaving
    search + adversarial schedules, tests/test_pallas_protocol.py).

    SPLIT-DTYPE circulation (ISSUE 8 / VERDICT r5 #5 — the TPU side of
    the wire-dtype != fold-dtype seam): the K/V planes are PRISTINE
    INPUTS, so they circulate in the input dtype (bf16 inputs: half the
    wire bytes, bit-identical values — bf16→f32 is exact, so nothing is
    lost versus the old f32 circulation); the dK/dV planes are PARTIAL
    SUMS, so they circulate f32.  Each hop is two RDMAs on per-plane
    semaphore columns sharing one slot parity (_SndPair): the
    send/credit/barrier protocol — and therefore the model check — is
    unchanged, only the payload is split.

    Per-pair algebra (flash backward, exact):  P_ = exp(S - L) (the
    saved logsumexp — no rescaling pass), dP = dO·Vᵀ,
    dS = P_∘(dP - D)·scale with D = rowsum(dO∘Out) precomputed,
    dQ += dS·K, dK += dSᵀ·Q, dV = P_ᵀ·dO.  The MXU folds are f32
    regardless of the circulation dtype (staged K/V tiles upcast at the
    matmul).

    ``tiles=None`` → resident fold (everything staged whole in VMEM);
    ``tiles=(tq, tk)`` → flash-style tiling (round 5: the fused
    backward must not fall off to the ppermute recompute exactly where
    long contexts need it): dQ accumulates in its HBM output, each
    arrival loops K/V-tiles (outer, dK/dV tile carried as values) over
    Q-tiles (inner, residuals + dQ staged per tile) — the circulation
    protocol is byte-identical in both modes."""
    if tiles is None:
        (q_vmem, do_vmem, lse_vmem, delta_vmem, kv_vmem, dkv_vmem,
         dq_vmem, copy_sem, send_sem, recv_sem, credit_sem,
         par_sems) = refs
    else:
        (qt_vmem, dot_vmem, lset_vmem, deltat_vmem, kt_vmem, vt_vmem,
         accb_vmem, accb2_vmem, dqt_vmem, copy_sem, send_sem, recv_sem,
         credit_sem, par_sems) = refs
        tq, tk = tiles
    left = params_smem[0]
    right = params_smem[1]
    my = params_smem[2]
    P = size
    g = hq // hkv
    kv_rows = 2 * hkv * sb  # K+V planes; dK+dV planes follow
    dev_kw = _mk_dev_kw(mesh_ids, axis_name)
    neighbor_barrier = _mk_barrier(pipelined, dev_kw, left, right)
    copy_sync = _mk_copy_sync(copy_sem)
    copy_par = _mk_copy_par(par_sems)

    # send u (0..P-1): the block folded at step u moves on; send 0
    # reads the assembled own-block scratch, not a comm slot.  Two
    # planes per hop (split dtypes), one protocol (_SndPair).
    snd_kv = _mk_snd(own_kv_hbm, comm_kv_hbm, send_sem, recv_sem, dev_kw,
                     right, col=0)
    snd_dkv = _mk_snd(own_dkv_hbm, comm_dkv_hbm, send_sem, recv_sem,
                      dev_kw, right, col=1)

    def snd(u):
        return _SndPair(snd_kv(u), snd_dkv(u))

    def pair_grads(kv_idx, masked):
        """dQ/dK/dV contributions of my Q rows against the K/V block in
        kv_vmem; dK/dV accumulate into dkv_vmem (all heads).  ``masked``
        (static) applies the causal mask — only the DIAGONAL block
        (kv_idx == my) needs it: strictly-past blocks are all-True and
        future blocks are skipped by the caller's pl.when, so the mask
        materialization stays off the P-2 hot arrivals (review round
        5)."""
        for h in range(hq):
            kvh = h // g
            rows = pl.ds(h * sb, sb)
            mask = _causal_mask(my, kv_idx, sb) if masked else None
            dq_c, dk_c, dv_c = _pair_grad_tile(
                q_vmem[rows, :].astype(jnp.float32),
                do_vmem[rows, :].astype(jnp.float32),
                lse_vmem[rows, :][:, :1], delta_vmem[rows, :][:, :1],
                kv_vmem[pl.ds(kvh * sb, sb), :].astype(jnp.float32),
                kv_vmem[pl.ds((hkv + kvh) * sb, sb), :]
                .astype(jnp.float32), scale, mask)
            dq_vmem[rows, :] = dq_vmem[rows, :] + dq_c
            krows = pl.ds(kvh * sb, sb)
            vrows = pl.ds((hkv + kvh) * sb, sb)
            dkv_vmem[krows, :] = dkv_vmem[krows, :] + dk_c
            dkv_vmem[vrows, :] = dkv_vmem[vrows, :] + dv_c

    def pair_grads_tiled(kv_idx, kv_at, dkv_at, init_zero, masked):
        """Flash-tiled pair gradients: dK/dV tiles ride the inner-loop
        carry (loaded from — or, ``init_zero``, started at zero in —
        the dK/dV planes addressed by ``dkv_at(row0, n)``), residuals
        and the dQ accumulator stage per Q-tile straight from/to their
        HBM refs (dQ lives in its OUTPUT ref between arrivals).
        ``kv_at(row0, n)`` addresses the arrived K/V planes.  The
        protocol sees the exact same consume window as the resident
        fold."""
        nq, nk = sb // tq, sb // tk
        for h in range(hq):
            kvh = h // g

            # zero the dK/dV tiles only for the FIRST query head of
            # each K/V group: later heads of the group must accumulate
            # into (not overwrite) what earlier heads stored — review
            # round 5 caught the per-head re-zeroing dropping all but
            # the last head's own-block contribution under GQA
            zero_here = init_zero and (h % g == 0)

            def j_body(j, _, h=h, kvh=kvh, zero_here=zero_here):
                kr = kvh * sb + j * tk
                copy_par((kv_at(kr, tk), kt_vmem),
                         (kv_at(hkv * sb + kr, tk), vt_vmem))
                if zero_here:
                    dk0 = jnp.zeros((tk, d), jnp.float32)
                    dv0 = jnp.zeros((tk, d), jnp.float32)
                else:
                    copy_par((dkv_at(kr, tk), accb_vmem),
                             (dkv_at(hkv * sb + kr, tk), accb2_vmem))
                    dk0 = accb_vmem[:]
                    dv0 = accb2_vmem[:]

                def i_body(i, carry, h=h):
                    dk, dv = carry
                    r0 = h * sb + i * tq
                    copy_par((q_hbm.at[pl.ds(r0, tq)], qt_vmem),
                             (do_hbm.at[pl.ds(r0, tq)], dot_vmem),
                             (lse_hbm.at[pl.ds(r0, tq)], lset_vmem),
                             (delta_hbm.at[pl.ds(r0, tq)], deltat_vmem),
                             (dq_hbm.at[pl.ds(r0, tq)], dqt_vmem))
                    mask = (_causal_mask(my, kv_idx, sb, i * tq, j * tk,
                                         tq, tk) if masked else None)
                    dq_c, dk_c, dv_c = _pair_grad_tile(
                        qt_vmem[:].astype(jnp.float32),
                        dot_vmem[:].astype(jnp.float32),
                        lset_vmem[:, :1], deltat_vmem[:, :1],
                        kt_vmem[:].astype(jnp.float32),
                        vt_vmem[:].astype(jnp.float32), scale, mask)
                    dqt_vmem[:] = dqt_vmem[:] + dq_c
                    copy_sync(dqt_vmem, dq_hbm.at[pl.ds(r0, tq)])
                    return dk + dk_c, dv + dv_c

                # on the DIAGONAL block, q-tiles strictly above this
                # k-tile are fully masked — skip them (mirrors the
                # forward's diagonal tile-skip)
                i_lo = (j * tk) // tq if masked else 0
                dk, dv = lax.fori_loop(i_lo, nq, i_body, (dk0, dv0))
                accb_vmem[:] = dk
                accb2_vmem[:] = dv
                copy_par((accb_vmem, dkv_at(kr, tk)),
                         (accb2_vmem, dkv_at(hkv * sb + kr, tk)))
                return 0

            lax.fori_loop(0, nk, j_body, 0)

    if tiles is None:
        # stage the rank-local residuals once (independent → parallel)
        copy_par((q_hbm, q_vmem), (do_hbm, do_vmem),
                 (lse_hbm, lse_vmem), (delta_hbm, delta_vmem))
        dq_vmem[:] = jnp.zeros((hq * sb, d), jnp.float32)
    else:
        # dQ accumulates in its output ref: zero it tile by tile
        def zq_body(i, _):
            dqt_vmem[:] = jnp.zeros((tq, d), jnp.float32)
            copy_sync(dqt_vmem, dq_hbm.at[pl.ds(i * tq, tq)])
            return 0

        lax.fori_loop(0, (hq * sb) // tq, zq_body, 0)

    # fold 0 (own block) and assemble the circulating payload: K/V
    # planes straight from the input (IN the input/wire dtype), dK/dV
    # planes = my own f32 contribution (every other rank's accumulates
    # en route)
    copy_sync(kv_hbm, own_kv_hbm)
    if tiles is None:
        copy_sync(kv_hbm, kv_vmem)
        dkv_vmem[:] = jnp.zeros((kv_rows, d), jnp.float32)
        pair_grads(my, masked=causal)  # a=0 is the diagonal block
        copy_sync(dkv_vmem, own_dkv_hbm)
    else:
        pair_grads_tiled(
            my, kv_at=lambda r0, n: kv_hbm.at[pl.ds(r0, n)],
            dkv_at=lambda r0, n: own_dkv_hbm.at[pl.ds(r0, n)],
            init_zero=True, masked=causal)

    neighbor_barrier()

    if P >= 2:
        snd(0).start()
        if not pipelined:
            snd(0).wait()

    for a in range(1, P + 1):
        slot = a % 2
        if pipelined:
            snd(a - 1).wait_recv()  # arrival a lands in comm[slot]
        if a < P:
            # fold BEFORE forward: the dK/dV planes must carry my
            # contribution when the block moves on
            def consume(kv_idx, masked, slot=slot):
                if tiles is None:
                    copy_sync(comm_kv_hbm.at[slot], kv_vmem)
                    copy_sync(comm_dkv_hbm.at[slot], dkv_vmem)
                    pair_grads(kv_idx, masked)
                    copy_sync(dkv_vmem, comm_dkv_hbm.at[slot])
                else:
                    pair_grads_tiled(
                        kv_idx,
                        kv_at=lambda r0, n: comm_kv_hbm.at[
                            slot, pl.ds(r0, n)],
                        dkv_at=lambda r0, n: comm_dkv_hbm.at[
                            slot, pl.ds(r0, n)],
                        init_zero=False, masked=masked)

            if causal:
                # the diagonal block is always arrival 0 (kv_idx == my
                # iff a ≡ 0 mod P), so arrivals 1..P-1 are either
                # strictly past (mask provably all-True — skip its
                # materialization) or future (skip everything)
                kv_idx = lax.rem(my - a + P, P)

                @pl.when(kv_idx < my)
                def _():
                    consume(kv_idx, masked=False)
            else:
                consume(lax.rem(my - a + P, P), masked=False)
            if pipelined:
                # FIRST retire the previous hop and credit its slot —
                # this signal transitively feeds the right neighbor's
                # credit wait below; emitting it after our own wait
                # would close a ring-wide cycle (deadlock at P >= 3)
                snd(a - 1).wait_send()
                if 1 <= a - 1 <= P - 2:
                    pltpu.semaphore_signal(credit_sem.at[(a - 1) % 2],
                                           inc=1, **dev_kw(left))
                if a >= 2:
                    pltpu.semaphore_wait(credit_sem.at[(a + 1) % 2], 1)
                snd(a).start()
            else:
                snd(a).start()
                snd(a).wait()
        else:
            # home arrival: my block returns with every rank's dK/dV
            if pipelined:
                snd(a - 1).wait_send()
            copy_sync(comm_dkv_hbm.at[slot], dkv_hbm)

    if tiles is None:
        copy_sync(dq_vmem, dq_hbm)  # tiled mode accumulated in place
    neighbor_barrier()


def _ring_neighbors(axis_name: str, size: int) -> jnp.ndarray:
    """[left, right, my] int32 SMEM params (my = causal block index)."""
    idx = lax.axis_index(axis_name)
    return jnp.stack([lax.rem(idx - 1 + size, size),
                      lax.rem(idx + 1, size), idx]).astype(jnp.int32)


def _fallback_attention(q, k, v, axis_name: str, size: int, scale: float,
                        causal: bool = False):
    """The same online-softmax ring as jax ops over ppermute — the
    vma/multi-axis interpreter path, and the recompute body of the
    out-of-budget custom-vjp backward.  Accepts both layouts ([Sb, d]
    and [H, Sb, d]); the multi-head ring rotates the WHOLE [Hkv, Sb, d]
    K/V stacks once per step (one ppermute pair per step, exactly like
    the kernel's single circulating RDMA) with per-head folds inside —
    NOT one ring per head (review round 4)."""
    multihead = q.ndim == 3
    q3 = q if multihead else q[None]
    k3 = k if multihead else k[None]
    v3 = v if multihead else v[None]
    hq, sb, d = q3.shape
    hkv = k3.shape[0]
    g = hq // hkv
    world_pairs = _world_pairs_of(size, None)
    perm = world_pairs([(r, (r + 1) % size) for r in range(size)])
    my = lax.axis_index(axis_name)
    m = [jnp.full((sb, 1), -jnp.inf, jnp.float32) for _ in range(hq)]
    l = [jnp.zeros((sb, 1), jnp.float32) for _ in range(hq)]
    o = [jnp.zeros((sb, d), jnp.float32) for _ in range(hq)]
    kb, vb = k3, v3
    for step in range(size):
        mask = None
        if causal:
            kv_idx = lax.rem(my - step + size, size)
            mask = _causal_mask(my, kv_idx, sb)  # shared by every head
        for h in range(hq):
            m[h], l[h], o[h] = _online_fold(q3[h], kb[h // g], vb[h // g],
                                            m[h], l[h], o[h], scale, mask)
        if step < size - 1:  # the last fold's blocks need no rotation
            kb = lax.ppermute(kb, axis_name, perm)
            vb = lax.ppermute(vb, axis_name, perm)
    out = jnp.stack([(o[h] / l[h]) for h in range(hq)]).astype(q.dtype)
    return out if multihead else out[0]


def pallas_ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          axis_name: str, size: int, *,
                          scale: float = None, causal: bool = False,
                          interpret: bool = False,
                          vmem_limit_bytes: Optional[int] = None
                          ) -> jnp.ndarray:
    """Exact attention (full, or causal with ``causal=True``) over a
    sequence-sharded axis.  Two shapes:

    * single-head: ``q``/``k``/``v`` = this device's [Sb, dh] blocks;
    * multi-head / GQA: ``q`` = [Hq, Sb, dh], ``k``/``v`` =
      [Hkv, Sb, dh] with ``Hq % Hkv == 0`` — query head h attends K/V
      head ``h // (Hq//Hkv)`` (Hkv == Hq is classic multi-head,
      Hkv == 1 is MQA).  ALL heads ride ONE circulating RDMA per step.

    Returns this device's output block, shaped like ``q``.  Call inside
    shard_map over a mesh with ``axis_name``; the global sequence is
    the concatenation of the blocks in rank order.

    The compiled path is the in-kernel RDMA circulation described in
    the module docstring, with the fold mode (resident / tiled) chosen
    by ``attention_vmem_plan`` from ``vmem_limit_bytes`` (default ~12
    MiB); ``interpret=True`` (the CPU tier) runs the serial same-kernel
    path, or — under vma typing / a multi-axis mesh — the ppermute
    fallback with the shared loud warning.

    Differentiable: the forward emits the logsumexp residual and the
    backward runs its own fused ring kernel ([K,V,dK,dV] circulation)
    in resident or flash-tiled mode per its VMEM plan; only an
    impossible budget recomputes through the pure-jax ring."""
    if q.ndim not in (2, 3):
        raise ValueError(
            f"ring attention wants [Sb, dh] or [H, Sb, dh] blocks, got "
            f"q {q.shape}")
    if k.shape != v.shape or q.shape[-2:] != k.shape[-2:] or \
            q.ndim != k.ndim:
        raise ValueError(
            f"ring attention wants equal [.., rows, d] blocks for q/k/v "
            f"(k/v may differ from q only in the HEAD count), got "
            f"{q.shape}/{k.shape}/{v.shape}")
    if k.dtype != q.dtype or v.dtype != q.dtype:
        raise ValueError(
            f"ring attention wants one dtype for q/k/v (the circulating "
            f"K/V buffer is allocated as q's), got "
            f"{q.dtype}/{k.dtype}/{v.dtype}")
    multihead = q.ndim == 3
    hq = q.shape[0] if multihead else 1
    hkv = k.shape[0] if multihead else 1
    if hkv < 1 or hq % hkv or hkv > hq:
        raise ValueError(
            f"GQA wants Hq a positive multiple of Hkv, got Hq={hq} "
            f"Hkv={hkv}")
    sb, d = q.shape[-2:]
    if d % _LANES:
        raise NotImplementedError(
            f"head dim must be a multiple of {_LANES} (lane width), got {d}")
    from .pallas_ring import _SUBLANES

    sub = _SUBLANES.get(jnp.dtype(q.dtype), 8)
    if sb % sub:
        raise NotImplementedError(
            f"block rows must be a multiple of {sub} ({jnp.dtype(q.dtype)} "
            f"sublane tile), got {sb}")
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    # shared dtype/vma/mesh probing with the ring collectives (f32/bf16)
    vma_on, multi_axis = _check_args(q, axis_name, size, sub, "sum")
    # Fold mode from the VMEM budget.  A forward NO tile can satisfy
    # degrades to the ppermute ring (graceful degradation, ROADMAP r5
    # #4): primal-identical and correct at any size, just without the
    # kernel's RDMA overlap — so the substitution is LOUD (warning +
    # ``attention_fallbacks`` mpit pvar), exactly like the vma/multi-
    # axis interpreter fallback, instead of the former
    # NotImplementedError that made an over-tight budget fatal.
    try:
        _, tiles = attention_vmem_plan(sb, d, hq, hkv, q.dtype,
                                       vmem_limit_bytes)
    except NotImplementedError as e:
        import warnings

        from .. import mpit

        warnings.warn(
            f"ring attention forward out of VMEM budget — executing the "
            f"ppermute ring fallback; timings will not reflect the RDMA "
            f"kernel. ({e})", RuntimeWarning, stacklevel=2)
        mpit.count(attention_oob=1)
        return _fallback_attention(q, k, v, axis_name, size, scale, causal)
    bwd_mode, bwd_tiles = attention_vmem_plan(
        sb, d, hq, hkv, q.dtype, vmem_limit_bytes, for_backward=True)
    bwd_fused = bwd_mode in ("resident", "tiled")

    def _per_head(fn, q_, k_, v_):
        """Apply a [Sb,dh]-block function per query head (GQA maps
        query head h to K/V head h // (Hq//Hkv))."""
        if not multihead:
            return fn(q_, k_, v_)
        return jnp.stack([fn(q_[h], k_[h // (hq // hkv)],
                             v_[h // (hq // hkv)]) for h in range(hq)])

    def _local_one(qh, kh, vh):
        m0 = jnp.full((sb, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((sb, 1), jnp.float32)
        o0 = jnp.zeros((sb, d), jnp.float32)
        mask = (_causal_mask(jnp.int32(0), jnp.int32(0), sb)
                if causal else None)
        _, l1, o1 = _online_fold(qh, kh, vh, m0, l0, o0, scale, mask)
        return (o1 / l1).astype(q.dtype)

    def _reference(q_, k_, v_):
        """Pure-jax ring (differentiable) — primal-identical to the
        kernel; the out-of-budget custom-vjp backward recomputes
        through it.  Only reached with size >= 2 (size == 1 returns
        below, before any _reference call site)."""
        return _fallback_attention(q_, k_, v_, axis_name, size, scale,
                                   causal)

    if size == 1:
        return _per_head(_local_one, q, k, v)
    if (vma_on or multi_axis) and interpret:
        _fallback("ring_attention", axis_name, vma_on, multi_axis)
        return _reference(q, k, v)

    def _out_structs(shapes):
        if vma_on:
            try:
                in_vma = frozenset(jax.typeof(q).vma)
            except (AttributeError, NameError):
                in_vma = frozenset()
            return tuple(jax.ShapeDtypeStruct(s, jnp.float32,
                                              vma=in_vma | {axis_name})
                         for s in shapes)
        return tuple(jax.ShapeDtypeStruct(s, jnp.float32) for s in shapes)

    def _kernel_call(q_, k_, v_, with_lse):
        """→ out [shaped like q], or (out, lse [hq*sb, _LANES] f32)
        when ``with_lse`` (the fused-backward residual; inference and
        fallback-backward paths skip its cost entirely)."""
        # flat multi-head layout (see _kernel docstring): q/out stack
        # query heads along rows; the circulating buffer stacks all K
        # planes then all V planes so one RDMA carries every head
        qf = q_.reshape(hq * sb, d) if multihead else q_
        kf = k_.reshape(hkv * sb, d) if multihead else k_
        vf = v_.reshape(hkv * sb, d) if multihead else v_
        kv = jnp.concatenate([kf, vf], axis=0)
        params = _ring_neighbors(axis_name, size)
        kern = functools.partial(
            _kernel, axis_name=axis_name, size=size, sb=sb, d=d,
            scale=scale, pipelined=not interpret, mesh_ids=multi_axis,
            causal=causal, hq=hq, hkv=hkv, tiles=tiles,
            with_lse=with_lse)
        compiler_params = None if interpret else pltpu.CompilerParams(
            collective_id=16, has_side_effects=True)
        if tiles is None:
            scratch = [
                pl.ANY((2, 2 * hkv * sb, d), q.dtype),   # landing slots
                pltpu.VMEM((hq * sb, d), q.dtype),       # Q (all heads)
                pltpu.VMEM((2 * hkv * sb, d), q.dtype),  # K/V staging
                pltpu.VMEM((hq * sb, 1), jnp.float32),   # m
                pltpu.VMEM((hq * sb, 1), jnp.float32),   # l
                pltpu.VMEM((hq * sb, d), jnp.float32),   # o
            ]
            if with_lse:
                scratch.append(
                    pltpu.VMEM((hq * sb, _LANES), jnp.float32))  # lse
        else:
            tq, tk = tiles
            scratch = [
                pl.ANY((2, 2 * hkv * sb, d), q.dtype),   # landing slots
                pl.ANY((hq * sb, _LANES), jnp.float32),  # m state (HBM)
                pl.ANY((hq * sb, _LANES), jnp.float32),  # l state (HBM)
                pl.ANY((hq * sb, d), jnp.float32),       # o state (HBM)
                pltpu.VMEM((tq, d), q.dtype),            # q tile
                pltpu.VMEM((tk, d), q.dtype),            # k tile
                pltpu.VMEM((tk, d), q.dtype),            # v tile
                pltpu.VMEM((tq, _LANES), jnp.float32),   # m tile
                pltpu.VMEM((tq, _LANES), jnp.float32),   # l tile
                pltpu.VMEM((tq, d), jnp.float32),        # o tile
            ]
        scratch += [
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2,)),               # send (parity)
            pltpu.SemaphoreType.DMA((2,)),               # recv (parity)
            pltpu.SemaphoreType.REGULAR((2,)),           # slot credits
            pltpu.SemaphoreType.DMA((8,)),               # parallel tiles
        ]
        shapes = [(hq * sb, d)]
        if with_lse:
            shapes.append((hq * sb, _LANES))
        res = pl.pallas_call(
            kern,
            out_shape=_out_structs(shapes),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pl.ANY),
                      pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=tuple(pl.BlockSpec(memory_space=pl.ANY)
                            for _ in shapes),
            scratch_shapes=scratch,
            compiler_params=compiler_params,
            interpret=interpret,
        )(params, qf, kv)
        out = res[0].astype(q_.dtype)
        out = out.reshape(hq, sb, d) if multihead else out
        return (out, res[1]) if with_lse else out

    def _bwd_kernel_call(q_, k_, v_, out, lse, ct):
        """Fused backward (resident or tiled mode): → (dq, dk, dv)
        like q/k/v."""
        qf = q_.reshape(hq * sb, d) if multihead else q_
        kf = k_.reshape(hkv * sb, d) if multihead else k_
        vf = v_.reshape(hkv * sb, d) if multihead else v_
        dof = ct.reshape(hq * sb, d) if multihead else ct
        outf = out.reshape(hq * sb, d) if multihead else out
        # the K/V planes circulate in the INPUT dtype (split-dtype seam:
        # pristine inputs lose nothing below f32, and bf16 halves their
        # wire bytes); only the dK/dV partial sums ride f32
        kv = jnp.concatenate([kf, vf], axis=0)
        delta = jnp.sum(dof.astype(jnp.float32) * outf.astype(jnp.float32),
                        axis=1, keepdims=True)
        delta = jnp.broadcast_to(delta, (hq * sb, _LANES))
        params = _ring_neighbors(axis_name, size)
        kern = functools.partial(
            _bwd_kernel, axis_name=axis_name, size=size, sb=sb, d=d,
            scale=scale, pipelined=not interpret, mesh_ids=multi_axis,
            causal=causal, hq=hq, hkv=hkv, tiles=bwd_tiles)
        compiler_params = None if interpret else pltpu.CompilerParams(
            collective_id=17, has_side_effects=True)
        kv_rows = 2 * hkv * sb
        scratch = [
            pl.ANY((kv_rows, d), q.dtype),               # own [K,V] (wire)
            pl.ANY((kv_rows, d), jnp.float32),           # own [dK,dV]
            pl.ANY((2, kv_rows, d), q.dtype),            # K/V landing slots
            pl.ANY((2, kv_rows, d), jnp.float32),        # dK/dV landing slots
        ]
        if bwd_tiles is None:
            scratch += [
                pltpu.VMEM((hq * sb, d), q.dtype),           # Q
                pltpu.VMEM((hq * sb, d), q.dtype),           # dOut
                pltpu.VMEM((hq * sb, _LANES), jnp.float32),  # lse
                pltpu.VMEM((hq * sb, _LANES), jnp.float32),  # delta
                pltpu.VMEM((kv_rows, d), q.dtype),           # K/V staging
                pltpu.VMEM((kv_rows, d), jnp.float32),       # dK/dV staging
                pltpu.VMEM((hq * sb, d), jnp.float32),       # dQ accum
            ]
        else:
            tqb, tkb = bwd_tiles
            scratch += [
                pltpu.VMEM((tqb, d), q.dtype),               # q tile
                pltpu.VMEM((tqb, d), q.dtype),               # dOut tile
                pltpu.VMEM((tqb, _LANES), jnp.float32),      # lse tile
                pltpu.VMEM((tqb, _LANES), jnp.float32),      # delta tile
                pltpu.VMEM((tkb, d), q.dtype),               # k tile
                pltpu.VMEM((tkb, d), q.dtype),               # v tile
                pltpu.VMEM((tkb, d), jnp.float32),           # dk buffer
                pltpu.VMEM((tkb, d), jnp.float32),           # dv buffer
                pltpu.VMEM((tqb, d), jnp.float32),           # dq tile
            ]
        scratch += [
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2, 2)),             # send (parity, plane)
            pltpu.SemaphoreType.DMA((2, 2)),             # recv (parity, plane)
            pltpu.SemaphoreType.REGULAR((2,)),           # slot credits
            pltpu.SemaphoreType.DMA((8,)),               # parallel tiles
        ]
        dq, dkv = pl.pallas_call(
            kern,
            out_shape=_out_structs([(hq * sb, d), (kv_rows, d)]),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] +
                     [pl.BlockSpec(memory_space=pl.ANY)] * 5,
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY)),
            scratch_shapes=scratch,
            compiler_params=compiler_params,
            interpret=interpret,
        )(params, qf, kv, dof, lse, delta)
        dq = dq.astype(q_.dtype)
        dk = dkv[:hkv * sb].astype(k_.dtype)
        dv = dkv[hkv * sb:].astype(v_.dtype)
        if multihead:
            return (dq.reshape(hq, sb, d), dk.reshape(hkv, sb, d),
                    dv.reshape(hkv, sb, d))
        return dq, dk, dv

    def _primal(q_, k_, v_):
        return _kernel_call(q_, k_, v_, with_lse=False)

    # Differentiable wrapper: jax cannot autodiff through the kernel's
    # remote DMAs, so the backward is either the fused [K,V,dK,dV]
    # ring kernel above (resident or tiled plan) or a recompute
    # through the pure-jax ring (out-of-budget fallback; ppermutes
    # transpose to the inverse rotation) — either way the fused kernel
    # stays the forward hot path and training can jax.grad straight
    # through it.
    attn = jax.custom_vjp(_primal)

    def _fwd(q_, k_, v_):
        if not bwd_fused:
            # the recompute backward needs only the inputs — skip the
            # lse output and do not pin out/lse across fwd..bwd
            return _kernel_call(q_, k_, v_, with_lse=False), (q_, k_, v_)
        out, lse = _kernel_call(q_, k_, v_, with_lse=True)
        return out, (q_, k_, v_, out, lse)

    def _bwd(res, ct):
        if not bwd_fused:
            q_, k_, v_ = res
            _, vjp = jax.vjp(_reference, q_, k_, v_)
            return vjp(ct)
        q_, k_, v_, out, lse = res
        return _bwd_kernel_call(q_, k_, v_, out, lse, ct)

    attn.defvjp(_fwd, _bwd)
    return attn(q, k, v)
