"""Master/worker task farm — the canonical dynamic MPI-1 pattern [S].

Rank 0 hands out work items one at a time; whichever worker returns a
result first gets the next item (self-balancing under uneven task costs).
This is the textbook use of tags + MPI_Waitany, and it is deliberately
rank-dynamic: a master branching on *which* worker answered cannot be one
SPMD trace, so this example is PROCESS-BACKENDS ONLY (socket/shm/local) —
the framework's designed division of labor (SURVEY.md §7 hard part 1):
dynamic orchestration runs host-side; the per-item compute can itself be
a jitted TPU program.

Run::

    python -m mpi_tpu.launcher -n 4 examples/master_worker.py
    python examples/master_worker.py --backend local -n 4
"""

from __future__ import annotations

import argparse
import math
import os
import sys

try:
    import mpi_tpu  # noqa: F401
except ModuleNotFoundError:  # fresh checkout without install
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

TAG_WORK, TAG_RESULT, TAG_STOP = 1, 2, 3


def _task(x: int) -> float:
    # deliberately uneven cost: larger x → more iterations
    acc = 0.0
    for k in range(1, 50 * (x % 7 + 1)):
        acc += math.sin(x * k) / k
    return acc


def run(comm, n_tasks: int = 40):
    """Returns (on rank 0) the list of all task results, task-indexed."""
    from mpi_tpu.api import MPI_Waitany

    if comm.size < 2:
        return [_task(i) for i in range(n_tasks)]

    if comm.rank == 0:
        results = [None] * n_tasks
        next_task = 0
        # prime workers with one item each; surplus workers (more workers
        # than tasks) are stopped immediately and get NO result irecv —
        # a pending receive from a stopped worker could never complete
        primed = []
        for w in range(1, comm.size):
            if next_task < n_tasks:
                comm.send(next_task, dest=w, tag=TAG_WORK)
                next_task += 1
                primed.append(w)
            else:
                comm.send(None, dest=w, tag=TAG_STOP)
        # one outstanding irecv per ACTIVE worker; Waitany picks whichever
        # finishes first, and its slot index maps back through `primed`
        reqs = [comm.irecv(source=w, tag=TAG_RESULT) for w in primed]
        outstanding = len(primed)
        while outstanding:
            i, payload = MPI_Waitany(reqs)
            task_id, value = payload
            results[task_id] = value
            worker = primed[i]
            if next_task < n_tasks:
                comm.send(next_task, dest=worker, tag=TAG_WORK)
                next_task += 1
                reqs[i] = comm.irecv(source=worker, tag=TAG_RESULT)
            else:
                comm.send(None, dest=worker, tag=TAG_STOP)
                outstanding -= 1
        return results

    # worker loop: task ids arrive with TAG_WORK until a TAG_STOP
    from mpi_tpu import ANY_TAG
    from mpi_tpu.communicator import Status

    while True:
        status = Status()
        item = comm.recv(source=0, tag=ANY_TAG, status=status)
        if status.tag == TAG_STOP:
            return None
        comm.send((item, _task(item)), dest=0, tag=TAG_RESULT)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default=None)
    ap.add_argument("-n", "--nranks", type=int, default=None)
    ap.add_argument("--tasks", type=int, default=40)
    args = ap.parse_args()

    import mpi_tpu

    if args.backend == "tpu":
        raise SystemExit(
            "master_worker is rank-dynamic by design (the master branches "
            "on which worker answered) — that has no SPMD spelling, so the "
            "tpu backend is not supported; run it on socket/shm/local and "
            "jit the per-task compute instead (module docstring)")
    if args.backend in (None, "socket", "shm"):
        comm = mpi_tpu.init(args.backend)
        res = run(comm, args.tasks)
        if comm.rank == 0:
            done = sum(r is not None for r in res)
            print(f"master_worker: {done}/{args.tasks} tasks done, "
                  f"sum={sum(res):.4f}")
        mpi_tpu.finalize()
    else:
        out = mpi_tpu.run(lambda c: run(c, args.tasks),
                          backend=args.backend, nranks=args.nranks)
        res = out[0]
        print(f"master_worker: {sum(r is not None for r in res)}/"
              f"{args.tasks} tasks done, sum={sum(res):.4f}")


if __name__ == "__main__":
    main()
