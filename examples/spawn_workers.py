"""Dynamic process management demo: a master grows its own worker pool.

The master (started alone) spawns a fresh 3-rank worker world at runtime
(MPI_Comm_spawn), scatters work over the parent-child intercommunicator,
and reduces the partial results — no launcher restart, the job resizes
itself.  Run:

    python -m mpi_tpu.launcher -n 1 examples/spawn_workers.py
"""

import os
import sys

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mpi_tpu
from mpi_tpu import spawn

NWORKERS = 3
SAMPLES = 40_000

if spawn.comm_get_parent() is None:
    # ---- parent side (any -n: the spawn is collective, rank 0 masters) ----
    comm = mpi_tpu.COMM_WORLD
    inter = spawn.comm_spawn([os.path.abspath(__file__)], NWORKERS, comm=comm)
    if comm.rank == 0:
        for j in range(NWORKERS):
            inter.send(("pi-samples", SAMPLES, j), dest=j)
        hits, total = 0, 0
        for j in range(NWORKERS):
            h, n = inter.recv(source=j)
            hits, total = hits + h, total + n
        print(f"master: pi ~= {4.0 * hits / total:.6f} from {total} samples "
              f"across {NWORKERS} spawned workers")
    comm.barrier()  # workers answered before rank 0 releases the world
    inter.free()
else:
    # ---- spawned worker side ----
    import numpy as np

    comm = mpi_tpu.COMM_WORLD          # the worker world
    parent = spawn.comm_get_parent()
    kind, n, seed = parent.recv(source=0)
    assert kind == "pi-samples"
    rng = np.random.default_rng(seed)
    xy = rng.random((n, 2))
    hits = int(((xy * xy).sum(axis=1) <= 1.0).sum())
    comm.barrier()                     # worker-world collective works too
    parent.send((hits, n), dest=0)
