"""Seeded bug: head-to-head blocking sends between literal ranks."""


def main(comm):
    if comm.rank == 0:
        comm.send(b"a", 1, tag=0)
        got = comm.recv(1, tag=0)
    elif comm.rank == 1:
        comm.send(b"b", 0, tag=0)
        got = comm.recv(0, tag=0)
    else:
        got = None
    return got
