"""MPI_T tool interface (mpi_tpu/mpit.py): cvars steer real knobs,
pvars count real traffic, sessions are reset-relative."""

import numpy as np
import pytest

from mpi_tpu import api, mpit
from mpi_tpu.transport.local import run_local


def test_pvars_count_real_traffic():
    s = mpit.session_create()

    def prog(comm):
        if comm.rank == 0:
            s.reset_all()
            comm.send(np.zeros(1000, np.float64), dest=1)
            comm.barrier()
            return (s.read("msgs_sent"), s.read("bytes_sent"))
        comm.recv(source=0)
        comm.barrier()
        return None

    res = run_local(prog, 2)
    sent, nbytes = res[0]
    assert sent >= 1 and nbytes >= 8000  # the payload + barrier traffic


def test_collectives_counter():
    before = mpit.pvar_read("collectives_started")
    run_local(lambda c: c.allreduce(1), 4)
    assert mpit.pvar_read("collectives_started") >= before + 4


def test_cvar_steers_allreduce_crossover():
    from mpi_tpu import trace

    old = mpit.cvar_read("allreduce_ring_crossover_bytes")
    try:
        # force ring even for tiny payloads by dropping the crossover
        mpit.cvar_write("allreduce_ring_crossover_bytes", 0)

        def prog(comm):
            return comm.allreduce(np.ones(4, np.float32))

        out = run_local(prog, 4)
        assert all(np.array_equal(o, np.full(4, 4.0)) for o in out)
    finally:
        mpit.cvar_write("allreduce_ring_crossover_bytes", old)
    assert mpit.cvar_read("allreduce_ring_crossover_bytes") == old


def test_cvar_io_limit_roundtrip_and_unknown():
    old = mpit.cvar_read("io_collective_buffer_limit_bytes")
    mpit.cvar_write("io_collective_buffer_limit_bytes", 1234)
    assert mpit.cvar_read("io_collective_buffer_limit_bytes") == 1234
    mpit.cvar_write("io_collective_buffer_limit_bytes", old)
    with pytest.raises(KeyError, match="unknown cvar"):
        mpit.cvar_read("nope")
    with pytest.raises(KeyError, match="unknown pvar"):
        mpit.pvar_read("nope")
    assert "io_collective_buffer_limit_bytes" in api.MPI_T_cvar_list()
    assert "msgs_sent" in api.MPI_T_pvar_list()


def test_session_relative_reads():
    s = api.MPI_T_pvar_session_create()
    s.reset("msgs_sent")
    base_abs = mpit.pvar_read("msgs_sent")
    run_local(lambda c: c.send("x", dest=(c.rank + 1) % 2) or c.recv(), 2)
    assert s.read("msgs_sent") == mpit.pvar_read("msgs_sent") - base_abs
