"""Socket/pickle transport tests (SURVEY.md §2 component #2; §4: loopback TCP
makes every test 'multi-node' in the sense that matters to a socket
transport).  Fast paths run the real socket stack in threads within one
process; one end-to-end test goes through the launcher with real rank
processes (component #1)."""

import os
import subprocess
import sys
import tempfile
import textwrap
import threading

import numpy as np
import pytest

from mpi_tpu import ops
from mpi_tpu.communicator import P2PCommunicator
from mpi_tpu.transport.socket import SocketTransport

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_socket_world(fn, nranks, timeout=60.0):
    """Run fn(comm) on nranks socket transports living in threads (real TCP)."""
    rdv = tempfile.mkdtemp(prefix="mpi_tpu_test_rdv_")
    results = [None] * nranks
    errors = []
    transports = [None] * nranks

    def runner(r):
        try:
            t = SocketTransport(r, nranks, rdv)
            transports[r] = t
            comm = P2PCommunicator(t, range(nranks))
            results[r] = fn(comm)
        except BaseException as e:  # noqa: BLE001
            import traceback

            errors.append((r, e, traceback.format_exc()))

    threads = [threading.Thread(target=runner, args=(r,), daemon=True) for r in range(nranks)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    alive = [i for i, t in enumerate(threads) if t.is_alive()]
    for t in transports:
        if t is not None:
            t.close()
    if errors:
        r, e, tb = errors[0]
        raise RuntimeError(f"rank {r} failed:\n{tb}") from e
    if alive:
        raise TimeoutError(f"socket ranks did not finish: {alive}")
    return results


def test_socket_p2p_roundtrip():
    def prog(comm):
        if comm.rank == 0:
            comm.send(np.arange(1000), dest=1, tag=3)
            return comm.recv(source=1, tag=4)
        got = comm.recv(source=0, tag=3)
        comm.send(got.sum(), dest=0, tag=4)
        return None

    res = run_socket_world(prog, 2)
    assert res[0] == np.arange(1000).sum()


def test_socket_large_message_framing():
    big = np.random.RandomState(0).bytes(3 * 1024 * 1024)  # multi-frame sendall

    def prog(comm):
        if comm.rank == 0:
            comm.send(big, dest=1)
            return None
        return comm.recv(source=0)

    res = run_socket_world(prog, 2)
    assert res[1] == big


def test_socket_self_send():
    def prog(comm):
        comm.send("to-myself", dest=comm.rank, tag=1)
        return comm.recv(source=comm.rank, tag=1)

    assert run_socket_world(prog, 2) == ["to-myself", "to-myself"]


@pytest.mark.parametrize("algo", ["ring", "recursive_halving"])
def test_socket_allreduce(algo):
    data = np.random.RandomState(1).randn(4, 50)

    def prog(comm):
        return comm.allreduce(data[comm.rank], op=ops.SUM, algorithm=algo)

    for got in run_socket_world(prog, 4):
        np.testing.assert_allclose(got, data.sum(axis=0), rtol=1e-10)


def test_socket_bcast_alltoall_barrier():
    def prog(comm):
        v = comm.bcast("payload" if comm.rank == 0 else None, root=0)
        blocks = comm.alltoall([(comm.rank, d) for d in range(comm.size)])
        comm.barrier()
        return v, blocks

    res = run_socket_world(prog, 3)
    for dst, (v, blocks) in enumerate(res):
        assert v == "payload"
        assert blocks == [(src, dst) for src in range(3)]


def test_socket_split():
    def prog(comm):
        sub = comm.split(color=comm.rank % 2, key=comm.rank)
        return sub.allreduce(comm.rank)

    res = run_socket_world(prog, 4)
    assert res == [2, 4, 2, 4]


@pytest.mark.slow
def test_launcher_end_to_end(tmp_path):
    """Full L0 path: launcher spawns real rank processes; ranks talk over
    loopback TCP and write their allreduce result to files."""
    script = tmp_path / "prog.py"
    out = tmp_path / "out"
    out.mkdir()
    script.write_text(textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import mpi_tpu

        comm = mpi_tpu.init()
        got = comm.allreduce(np.full(10, comm.rank + 1.0))
        (rank_total := got.sum())
        with open({str(out)!r} + f"/rank{{comm.rank}}.txt", "w") as f:
            f.write(str(float(rank_total)))
        mpi_tpu.finalize()
    """))
    from mpi_tpu.launcher import launch

    rc = launch(3, [str(script)], timeout=90.0)
    assert rc == 0
    expect = 10 * (1.0 + 2.0 + 3.0)
    for r in range(3):
        assert float((out / f"rank{r}.txt").read_text()) == expect


@pytest.mark.slow
def test_launcher_propagates_failure(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(7)\n")
    from mpi_tpu.launcher import launch

    assert launch(2, [str(script)], timeout=60.0) == 7
