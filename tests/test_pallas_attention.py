"""Fused Pallas ring attention (mpi_tpu/tpu/pallas_attention.py):
interpreter parity vs a dense-softmax oracle, loud fallbacks, and
cross-platform TPU export of the RDMA kernel (1-D + multi-axis meshes,
f32/bf16, vma on/off).  The circulation protocol itself is verified by
ring_model.AttentionSim (tests/test_pallas_protocol.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import AbstractMesh, PartitionSpec as P

from mpi_tpu.tpu import default_mesh
from mpi_tpu.tpu import runner as _runner
from mpi_tpu.tpu.pallas_attention import pallas_ring_attention

# jax-0.4.37 vintage (the pl.ANY memory-space shim active): a handful of
# tiled-interpret programs trip a FATAL XLA-CPU CHECK (array.h reshape of
# a 0-element buffer) at compile time — a process abort, not a test
# failure — so they must skip rather than take the whole suite down.
tiled_interpret_aborts = pytest.mark.skipif(
    getattr(_runner, "_PALLAS_MEMSPACE_SHIMMED", False),
    reason="XLA CPU aborts (array.h 0-element reshape CHECK) compiling "
           "this tiled interpret fold on the pre-0.5 jax vintage")


def _oracle(q, k, v, scale=None):
    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[1])
    s = (q.astype(np.float32) @ k.astype(np.float32).T) * scale
    p = np.exp(s - s.max(axis=1, keepdims=True))
    p /= p.sum(axis=1, keepdims=True)
    return p @ v.astype(np.float32)


def _run(Pn, Sb, d, dtype=np.float32, seed=0, **kw):
    rng = np.random.RandomState(seed)
    q = rng.randn(Pn * Sb, d).astype(dtype)
    k = rng.randn(Pn * Sb, d).astype(dtype)
    v = rng.randn(Pn * Sb, d).astype(dtype)
    mesh = default_mesh(Pn)

    def f(qb, kb, vb):
        return pallas_ring_attention(qb, kb, vb, "world", Pn,
                                     interpret=True, **kw)

    jf = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=(P("world"),) * 3,
                               out_specs=P("world"), check_vma=False))
    got = np.asarray(jf(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)),
                     np.float32)
    return got, _oracle(q, k, v, kw.get("scale"))


@pytest.mark.parametrize("Pn,Sb,d", [(2, 8, 128), (3, 8, 128),
                                     (4, 16, 128), (8, 8, 256)])
def test_interpreter_parity(Pn, Sb, d):
    """The kernel's serial-RDMA interpreter path is EXACT attention:
    online-softmax over circulating K/V blocks == dense softmax."""
    got, want = _run(Pn, Sb, d)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_interpreter_parity_bf16():
    got, want = _run(4, 16, 128, dtype=jnp.bfloat16)
    np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)


def test_custom_scale():
    got, want = _run(2, 8, 128, scale=0.25)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_size_one_degenerates_to_local_attention():
    rng = np.random.RandomState(3)
    q = rng.randn(8, 128).astype(np.float32)
    mesh = default_mesh(1)

    def f(qb):
        return pallas_ring_attention(qb, qb, qb, "world", 1, interpret=True)

    got = np.asarray(jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P("world"), out_specs=P("world"),
        check_vma=False))(jnp.asarray(q)))
    np.testing.assert_allclose(got, _oracle(q, q, q), rtol=2e-4, atol=2e-5)


def test_vma_fallback_warns_and_matches():
    """Under the default check_vma=True the interpreter takes the
    ppermute online-softmax fallback — loudly, and numerically
    identically."""
    Pn, Sb, d = 4, 8, 128
    rng = np.random.RandomState(5)
    q = rng.randn(Pn * Sb, d).astype(np.float32)
    mesh = default_mesh(Pn)

    def f(qb):
        return pallas_ring_attention(qb, qb, qb, "world", Pn,
                                     interpret=True)

    jf = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P("world"),
                               out_specs=P("world")))  # check_vma default
    with pytest.warns(RuntimeWarning, match="ppermute ring fallback"):
        got = np.asarray(jf(jnp.asarray(q)))
    np.testing.assert_allclose(got, _oracle(q, q, q), rtol=2e-4, atol=2e-5)


def test_multiaxis_interpreter_fallback_parity():
    """Ring over the sp axis of a 2-D (dp×sp) mesh on the interpreter:
    the fallback reduces per-dp-slice, matching a per-slice oracle."""
    import numpy as np_

    from jax.sharding import Mesh

    devs = np_.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dp", "sp"))
    Sb, d = 8, 128
    rng = np_.random.RandomState(7)
    # [dp=2 slices, sp-sharded sequence of 4*Sb rows, d]
    q = rng.randn(2, 4 * Sb, d).astype(np_.float32)
    k = rng.randn(2, 4 * Sb, d).astype(np_.float32)
    v = rng.randn(2, 4 * Sb, d).astype(np_.float32)

    def f(qb, kb, vb):
        return pallas_ring_attention(qb[0], kb[0], vb[0], "sp", 4,
                                     interpret=True)[None]

    jf = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P("dp", "sp", None),) * 3,
        out_specs=P("dp", "sp", None), check_vma=False))
    with pytest.warns(RuntimeWarning, match="ppermute ring fallback"):
        got = np.asarray(jf(*(jnp.asarray(a) for a in (q, k, v))))
    for sl in range(2):
        np.testing.assert_allclose(
            got[sl], _oracle(q[sl], k[sl], v[sl]), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("dtype,vma", [(jnp.float32, False),
                                       (jnp.float32, True),
                                       (jnp.bfloat16, False)])
def test_export_tpu_1d(dtype, vma):
    """The compiled RDMA kernel (credits, slot circulation, online fold)
    lowers through Mosaic for the TPU target from this host."""
    mesh = AbstractMesh((8,), ("s",))

    def f(q, k, v):
        return pallas_ring_attention(q, k, v, "s", 8, interpret=False)

    jf = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=(P("s"),) * 3,
                               out_specs=P("s"), check_vma=vma))
    aval = jax.ShapeDtypeStruct((8 * 64, 128), dtype)
    exp = jax.export.export(jf, platforms=["tpu"])(aval, aval, aval)
    assert "tpu_custom_call" in exp.mlir_module()


def test_export_tpu_multiaxis():
    """Sequence parallelism inside a 2-D training mesh: the kernel
    addresses its ring neighbors by mesh coordinate (same dict-MESH
    scheme as pallas_ring) and lowers for TPU."""
    mesh = AbstractMesh((2, 4), ("dp", "sp"))

    def f(q, k, v):
        return pallas_ring_attention(q, k, v, "sp", 4, interpret=False)

    jf = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=(P(("dp", "sp")),) * 3,
                               out_specs=P(("dp", "sp")), check_vma=False))
    aval = jax.ShapeDtypeStruct((8 * 64, 128), jnp.float32)
    exp = jax.export.export(jf, platforms=["tpu"])(aval, aval, aval)
    assert "tpu_custom_call" in exp.mlir_module()


def test_shape_diagnostics():
    mesh = default_mesh(2)

    def run(q_shape, kv_shape=None, **kw):
        kv_shape = kv_shape or q_shape

        def f(qb):
            q = jnp.zeros(q_shape, jnp.float32)
            kv = jnp.zeros(kv_shape, jnp.float32)
            return pallas_ring_attention(q, kv, kv, "world", 2,
                                         interpret=True, **kw)

        jax.jit(jax.shard_map(lambda x: f(x)[:0], mesh=mesh,
                              in_specs=P("world"), out_specs=P("world"),
                              check_vma=False))(jnp.zeros(2, jnp.float32))

    with pytest.raises(NotImplementedError, match="multiple of 128"):
        run((8, 64))
    with pytest.raises(NotImplementedError, match="sublane"):
        run((9, 128))
    with pytest.raises(ValueError, match="equal"):
        run((8, 128), (16, 128))


def test_mixed_dtype_diagnosed():
    mesh = default_mesh(2)

    def f(x):
        q = jnp.zeros((8, 128), jnp.float32)
        k = jnp.zeros((8, 128), jnp.bfloat16)
        return pallas_ring_attention(q, k, k, "world", 2, interpret=True)

    with pytest.raises(ValueError, match="one dtype"):
        jax.jit(jax.shard_map(lambda x: f(x)[:0], mesh=mesh,
                              in_specs=P("world"), out_specs=P("world"),
                              check_vma=False))(jnp.zeros(2, jnp.float32))


def _causal_oracle(q, k, v):
    s = (q.astype(np.float32) @ k.astype(np.float32).T) / np.sqrt(q.shape[1])
    n = s.shape[0]
    s = np.where(np.tril(np.ones((n, n), bool)), s, -np.inf)
    p = np.exp(s - s.max(axis=1, keepdims=True))
    p /= p.sum(axis=1, keepdims=True)
    return p @ v.astype(np.float32)


@pytest.mark.parametrize("Pn,Sb,d", [(2, 8, 128), (4, 16, 128),
                                     (3, 8, 128)])
def test_causal_interpreter_parity(Pn, Sb, d):
    """causal=True masks by GLOBAL position across the sharded sequence
    (block indices from the SMEM params): kernel == dense causal
    oracle.  The first fold is the own (diagonal) block, so the running
    max is finite from step 0 — no NaN path through the -1e30 mask."""
    rng = np.random.RandomState(Pn)
    q = rng.randn(Pn * Sb, d).astype(np.float32)
    k = rng.randn(Pn * Sb, d).astype(np.float32)
    v = rng.randn(Pn * Sb, d).astype(np.float32)
    mesh = default_mesh(Pn)
    jf = jax.jit(jax.shard_map(
        lambda qb, kb, vb: pallas_ring_attention(
            qb, kb, vb, "world", Pn, causal=True, interpret=True),
        mesh=mesh, in_specs=(P("world"),) * 3, out_specs=P("world"),
        check_vma=False))
    got = np.asarray(jf(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(got, _causal_oracle(q, k, v), rtol=2e-4,
                               atol=2e-5)


def test_causal_fallback_and_size1():
    """The ppermute fallback (vma on) and the P=1 degenerate path apply
    the same causal mask."""
    Pn, Sb, d = 4, 8, 128
    rng = np.random.RandomState(11)
    q = rng.randn(Pn * Sb, d).astype(np.float32)
    mesh = default_mesh(Pn)
    jf = jax.jit(jax.shard_map(
        lambda qb: pallas_ring_attention(qb, qb, qb, "world", Pn,
                                         causal=True, interpret=True),
        mesh=mesh, in_specs=P("world"), out_specs=P("world")))
    with pytest.warns(RuntimeWarning, match="ppermute ring fallback"):
        got = np.asarray(jf(jnp.asarray(q)))
    np.testing.assert_allclose(got, _causal_oracle(q, q, q), rtol=2e-4,
                               atol=2e-5)

    q1 = q[:Sb]
    mesh1 = default_mesh(1)
    got1 = np.asarray(jax.jit(jax.shard_map(
        lambda qb: pallas_ring_attention(qb, qb, qb, "world", 1,
                                         causal=True, interpret=True),
        mesh=mesh1, in_specs=P("world"), out_specs=P("world"),
        check_vma=False))(jnp.asarray(q1)))
    np.testing.assert_allclose(got1, _causal_oracle(q1, q1, q1), rtol=2e-4,
                               atol=2e-5)


def test_causal_export_tpu():
    mesh = AbstractMesh((8,), ("s",))
    jf = jax.jit(jax.shard_map(
        lambda q, k, v: pallas_ring_attention(q, k, v, "s", 8, causal=True,
                                              interpret=False),
        mesh=mesh, in_specs=(P("s"),) * 3, out_specs=P("s"),
        check_vma=False))
    aval = jax.ShapeDtypeStruct((8 * 64, 128), jnp.float32)
    exp = jax.export.export(jf, platforms=["tpu"])(aval, aval, aval)
    assert "tpu_custom_call" in exp.mlir_module()


# -- multi-head / GQA --------------------------------------------------------


@pytest.mark.parametrize("Hq,Hkv", [(2, 2), (4, 2), (4, 1)])
@pytest.mark.parametrize("causal", [False, True])
def test_multihead_gqa_parity(Hq, Hkv, causal):
    """[H, Sb, dh] blocks: query head h attends K/V head h//(Hq//Hkv);
    all heads ride ONE circulating RDMA.  Kernel == per-head dense
    oracle, full and causal, MHA/GQA/MQA layouts."""
    Pn, Sb, d = 4, 8, 128
    rng = np.random.RandomState(Hq * 10 + Hkv)
    q = rng.randn(Hq, Pn * Sb, d).astype(np.float32)
    k = rng.randn(Hkv, Pn * Sb, d).astype(np.float32)
    v = rng.randn(Hkv, Pn * Sb, d).astype(np.float32)
    mesh = default_mesh(Pn)
    jf = jax.jit(jax.shard_map(
        lambda qb, kb, vb: pallas_ring_attention(
            qb, kb, vb, "world", Pn, causal=causal, interpret=True),
        mesh=mesh, in_specs=(P(None, "world"),) * 3,
        out_specs=P(None, "world"), check_vma=False))
    got = np.asarray(jf(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    g = Hq // Hkv
    orc = _causal_oracle if causal else _oracle
    for h in range(Hq):
        np.testing.assert_allclose(got[h], orc(q[h], k[h // g], v[h // g]),
                                   rtol=2e-4, atol=2e-5)


def test_multihead_fallback_and_size1():
    """The vma/multi-axis fallback and P=1 path honor the GQA head
    mapping too."""
    Hq, Hkv, Sb, d = 4, 2, 8, 128
    rng = np.random.RandomState(21)
    q = rng.randn(Hq, 4 * Sb, d).astype(np.float32)
    k = rng.randn(Hkv, 4 * Sb, d).astype(np.float32)
    v = rng.randn(Hkv, 4 * Sb, d).astype(np.float32)
    mesh = default_mesh(4)
    jf = jax.jit(jax.shard_map(
        lambda qb, kb, vb: pallas_ring_attention(qb, kb, vb, "world", 4,
                                                 interpret=True),
        mesh=mesh, in_specs=(P(None, "world"),) * 3,
        out_specs=P(None, "world")))  # check_vma default → fallback
    with pytest.warns(RuntimeWarning, match="ppermute ring fallback"):
        got = np.asarray(jf(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    for h in range(Hq):
        np.testing.assert_allclose(got[h], _oracle(q[h], k[h // 2], v[h // 2]),
                                   rtol=2e-4, atol=2e-5)

    mesh1 = default_mesh(1)
    q1, k1, v1 = q[:, :Sb], k[:, :Sb], v[:, :Sb]
    got1 = np.asarray(jax.jit(jax.shard_map(
        lambda qb, kb, vb: pallas_ring_attention(qb, kb, vb, "world", 1,
                                                 interpret=True),
        mesh=mesh1, in_specs=(P(None, "world"),) * 3,
        out_specs=P(None, "world"), check_vma=False))(
        jnp.asarray(q1), jnp.asarray(k1), jnp.asarray(v1)))
    for h in range(Hq):
        np.testing.assert_allclose(got1[h], _oracle(q1[h], k1[h // 2],
                                                    v1[h // 2]),
                                   rtol=2e-4, atol=2e-5)


def test_multihead_export_tpu():
    mesh = AbstractMesh((8,), ("s",))
    jf = jax.jit(jax.shard_map(
        lambda q, k, v: pallas_ring_attention(q, k, v, "s", 8, causal=True,
                                              interpret=False),
        mesh=mesh, in_specs=(P(None, "s"),) * 3, out_specs=P(None, "s"),
        check_vma=False))
    a_q = jax.ShapeDtypeStruct((4, 8 * 32, 128), jnp.float32)
    a_kv = jax.ShapeDtypeStruct((2, 8 * 32, 128), jnp.float32)
    exp = jax.export.export(jf, platforms=["tpu"])(a_q, a_kv, a_kv)
    assert "tpu_custom_call" in exp.mlir_module()


def test_gqa_shape_diagnostics():
    mesh = default_mesh(2)

    def run(qs, kvs):
        def f(x):
            q = jnp.zeros(qs, jnp.float32)
            kv = jnp.zeros(kvs, jnp.float32)
            return pallas_ring_attention(q, kv, kv, "world", 2,
                                         interpret=True)

        jax.jit(jax.shard_map(lambda x: jnp.ravel(f(x))[:0], mesh=mesh,
                              in_specs=P("world"), out_specs=P("world"),
                              check_vma=False))(jnp.zeros(2, jnp.float32))

    with pytest.raises(ValueError, match="multiple of Hkv"):
        run((3, 8, 128), (2, 8, 128))
    with pytest.raises(ValueError, match="multiple of Hkv"):
        run((2, 8, 128), (4, 8, 128))  # more kv heads than q heads


# -- differentiability (custom_vjp: fused forward, recompute backward) -------


@pytest.mark.parametrize("causal", [False, True])
def test_grad_matches_reference(causal):
    """jax.grad flows through the KERNEL path (custom_vjp: backward
    recomputes via the pure-jax ring): gradients equal those of the
    reference implementation differentiated directly."""
    from mpi_tpu.tpu.pallas_attention import _fallback_attention

    Pn, Sb, d = 4, 8, 128
    rng = np.random.RandomState(13)
    q = rng.randn(Pn * Sb, d).astype(np.float32)
    k = rng.randn(Pn * Sb, d).astype(np.float32)
    v = rng.randn(Pn * Sb, d).astype(np.float32)
    ct = rng.randn(Pn * Sb, d).astype(np.float32)  # nontrivial cotangent
    mesh = default_mesh(Pn)

    def loss_kernel(qb, kb, vb, ctb):
        out = pallas_ring_attention(qb, kb, vb, "world", Pn,
                                    causal=causal, interpret=True)
        return jnp.sum(out * ctb)

    def loss_ref(qb, kb, vb, ctb):
        out = _fallback_attention(qb, kb, vb, "world", Pn,
                                  1.0 / np.sqrt(d), causal)
        return jnp.sum(out * ctb)

    grads = {}
    for name, fn in (("kernel", loss_kernel), ("ref", loss_ref)):
        g = jax.jit(jax.shard_map(
            jax.grad(fn, argnums=(0, 1, 2)), mesh=mesh,
            in_specs=(P("world"),) * 4, out_specs=(P("world"),) * 3,
            check_vma=False))(*map(jnp.asarray, (q, k, v, ct)))
        grads[name] = [np.asarray(x) for x in g]
    for gk, gr in zip(grads["kernel"], grads["ref"]):
        np.testing.assert_allclose(gk, gr, rtol=2e-4, atol=2e-5)
    assert any(np.abs(g).max() > 0 for g in grads["kernel"])


def test_grad_gqa_accumulates_over_group():
    """GQA backward: dK/dV for one K/V head accumulate contributions
    from every query head in its group (jax.vjp does the summing)."""
    Pn, Hq, Hkv, Sb, d = 2, 4, 2, 8, 128
    rng = np.random.RandomState(17)
    q = rng.randn(Hq, Pn * Sb, d).astype(np.float32)
    k = rng.randn(Hkv, Pn * Sb, d).astype(np.float32)
    v = rng.randn(Hkv, Pn * Sb, d).astype(np.float32)
    mesh = default_mesh(Pn)

    def loss(qb, kb, vb):
        out = pallas_ring_attention(qb, kb, vb, "world", Pn,
                                    interpret=True)
        return jnp.sum(out ** 2)

    gq, gk, gv = jax.jit(jax.shard_map(
        jax.grad(loss, argnums=(0, 1, 2)), mesh=mesh,
        in_specs=(P(None, "world"),) * 3,
        out_specs=(P(None, "world"),) * 3,
        check_vma=False))(*map(jnp.asarray, (q, k, v)))
    assert np.asarray(gq).shape == q.shape
    assert np.asarray(gk).shape == k.shape
    assert np.abs(np.asarray(gk)).max() > 0
    assert np.abs(np.asarray(gv)).max() > 0


def test_grad_export_tpu():
    """value_and_grad of the kernel path lowers for TPU: fused Mosaic
    forward + XLA-collective backward in one exported program."""
    mesh = AbstractMesh((8,), ("s",))

    def loss(q, k, v):
        out = pallas_ring_attention(q, k, v, "s", 8, causal=True,
                                    interpret=False)
        return jnp.sum(out ** 2)

    jf = jax.jit(jax.shard_map(
        lambda q, k, v: jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v),
        mesh=mesh, in_specs=(P("s"),) * 3,
        out_specs=(P(), (P("s"),) * 3), check_vma=False))
    aval = jax.ShapeDtypeStruct((8 * 32, 128), jnp.float32)
    exp = jax.export.export(jf, platforms=["tpu"])(aval, aval, aval)
    assert "tpu_custom_call" in exp.mlir_module()


def test_zero_kv_heads_diagnosed():
    mesh = default_mesh(2)

    def f(x):
        q = jnp.zeros((4, 8, 128), jnp.float32)
        kv = jnp.zeros((0, 8, 128), jnp.float32)
        return pallas_ring_attention(q, kv, kv, "world", 2, interpret=True)

    with pytest.raises(ValueError, match="positive multiple"):
        jax.jit(jax.shard_map(lambda x: jnp.ravel(f(x))[:0], mesh=mesh,
                              in_specs=P("world"), out_specs=P("world"),
                              check_vma=False))(jnp.zeros(2, jnp.float32))


# -- VMEM planning: tiled fold + fused backward (round 5) --------------------


def test_vmem_plan_modes():
    """attention_vmem_plan: small blocks → resident; big blocks → the
    largest sublane-aligned divisor tile that fits; impossible budgets
    → a diagnostic with the arithmetic."""
    from mpi_tpu.tpu.pallas_attention import attention_vmem_plan

    mode, tiles = attention_vmem_plan(64, 128, 1, 1, jnp.float32)
    assert mode == "resident" and tiles is None
    mode, tiles = attention_vmem_plan(4096, 128, 1, 1, jnp.float32)
    assert mode == "tiled"
    tq, tk = tiles
    assert tq == tk and 4096 % tq == 0 and tq % 8 == 0
    # the chosen tile really is the largest fitting divisor
    assert tq >= 256
    # the backward tiles too (round 5): big blocks stay on the fused
    # ring kernel instead of falling back to the ppermute recompute
    mode, bt = attention_vmem_plan(4096, 128, 1, 1, jnp.float32,
                                   for_backward=True)
    assert mode == "tiled" and 4096 % bt[0] == 0 and bt[0] % 8 == 0
    # only an impossible budget forces the recompute fallback
    mode, _ = attention_vmem_plan(4096, 128, 1, 1, jnp.float32,
                                  vmem_limit_bytes=30_000,
                                  for_backward=True)
    assert mode == "fallback"
    with pytest.raises(NotImplementedError, match="VMEM budget"):
        attention_vmem_plan(64, 128, 1, 1, jnp.float32,
                            vmem_limit_bytes=1024)


@pytest.mark.parametrize("causal", [False, True])
def test_tiled_parity_forced(causal):
    """A small vmem_limit_bytes forces the tiled fold (state in HBM,
    [tq,tk] inner loop) at test-friendly sizes: parity with the dense
    oracle, full and causal."""
    if not causal and getattr(_runner, "_PALLAS_MEMSPACE_SHIMMED", False):
        pytest.skip("non-causal tiled interpret fold aborts XLA CPU on "
                    "the pre-0.5 jax vintage (array.h reshape CHECK)")
    Pn, Sb, d = 4, 32, 128
    rng = np.random.RandomState(23)
    q = rng.randn(Pn * Sb, d).astype(np.float32)
    k = rng.randn(Pn * Sb, d).astype(np.float32)
    v = rng.randn(Pn * Sb, d).astype(np.float32)
    mesh = default_mesh(Pn)
    from mpi_tpu.tpu.pallas_attention import attention_vmem_plan

    limit = 100_000  # forces tiling at Sb=32 (score = 32*32*4 fits, but
    # resident staging of Q+KV+o does not)
    mode, tiles = attention_vmem_plan(Sb, d, 1, 1, jnp.float32,
                                      vmem_limit_bytes=limit)
    assert mode == "tiled" and tiles[0] < Sb, (mode, tiles)
    jf = jax.jit(jax.shard_map(
        lambda qb, kb, vb: pallas_ring_attention(
            qb, kb, vb, "world", Pn, causal=causal, interpret=True,
            vmem_limit_bytes=limit),
        mesh=mesh, in_specs=(P("world"),) * 3, out_specs=P("world"),
        check_vma=False))
    got = np.asarray(jf(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    want = (_causal_oracle if causal else _oracle)(q, k, v)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


@tiled_interpret_aborts
def test_tiled_parity_gqa_bf16():
    """Tiled fold with multi-head GQA layout and bf16 inputs (16-row
    sublane tiles): parity per head."""
    Pn, Hq, Hkv, Sb, d = 2, 4, 2, 32, 128
    rng = np.random.RandomState(29)
    q = rng.randn(Hq, Pn * Sb, d).astype(np.float32)
    k = rng.randn(Hkv, Pn * Sb, d).astype(np.float32)
    v = rng.randn(Hkv, Pn * Sb, d).astype(np.float32)
    mesh = default_mesh(Pn)
    limit = 100_000
    jf = jax.jit(jax.shard_map(
        lambda qb, kb, vb: pallas_ring_attention(
            qb.astype(jnp.bfloat16), kb.astype(jnp.bfloat16),
            vb.astype(jnp.bfloat16), "world", Pn, interpret=True,
            vmem_limit_bytes=limit),
        mesh=mesh, in_specs=(P(None, "world"),) * 3,
        out_specs=P(None, "world"), check_vma=False))
    got = np.asarray(jf(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)),
                     np.float32)
    for h in range(Hq):
        np.testing.assert_allclose(got[h], _oracle(q[h], k[h // 2],
                                                   v[h // 2]),
                                   rtol=5e-2, atol=5e-2)


@tiled_interpret_aborts
def test_tiled_parity_large_block():
    """The VERDICT r4 ask: Sb >= 4096 f32 green on the interpreter —
    the default budget picks the tiled fold (the resident score matrix
    alone would be 64 MB) and the result still matches the dense
    oracle.  P=2, global sequence 8192."""
    Pn, Sb, d = 2, 4096, 128
    from mpi_tpu.tpu.pallas_attention import attention_vmem_plan

    mode, tiles = attention_vmem_plan(Sb, d, 1, 1, jnp.float32)
    assert mode == "tiled", (mode, tiles)
    rng = np.random.RandomState(31)
    q = rng.randn(Pn * Sb, d).astype(np.float32)
    k = rng.randn(Pn * Sb, d).astype(np.float32)
    v = rng.randn(Pn * Sb, d).astype(np.float32)
    mesh = default_mesh(Pn)
    jf = jax.jit(jax.shard_map(
        lambda qb, kb, vb: pallas_ring_attention(qb, kb, vb, "world", Pn,
                                                 interpret=True),
        mesh=mesh, in_specs=(P("world"),) * 3, out_specs=P("world"),
        check_vma=False))
    got = np.asarray(jf(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    np.testing.assert_allclose(got, _oracle(q, k, v), rtol=2e-4, atol=2e-3)


def test_tiled_export_tpu():
    """The tiled fold (fori_loop over HBM-state tiles) lowers through
    Mosaic for TPU at a block size the resident mode could never hold
    (Sb=8192 per device: a 256 MB score matrix resident)."""
    mesh = AbstractMesh((8,), ("s",))

    def f(q, k, v):
        return pallas_ring_attention(q, k, v, "s", 8, causal=True,
                                     interpret=False)

    jf = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=(P("s"),) * 3,
                               out_specs=P("s"), check_vma=False))
    aval = jax.ShapeDtypeStruct((8 * 8192, 128), jnp.float32)
    exp = jax.export.export(jf, platforms=["tpu"])(aval, aval, aval)
    assert "tpu_custom_call" in exp.mlir_module()


def test_bwd_kernel_export_tpu():
    """value_and_grad lowers BOTH rings through Mosaic: the forward
    kernel and the fused [K,V,dK,dV] backward kernel appear as two
    tpu_custom_calls in the exported module (VERDICT r4 missing #3 —
    the backward is fused, not a ppermute recompute)."""
    mesh = AbstractMesh((8,), ("s",))

    def loss(q, k, v):
        out = pallas_ring_attention(q, k, v, "s", 8, causal=True,
                                    interpret=False)
        return jnp.sum(out ** 2)

    jf = jax.jit(jax.shard_map(
        lambda q, k, v: jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v),
        mesh=mesh, in_specs=(P("s"),) * 3,
        out_specs=(P(), (P("s"),) * 3), check_vma=False))
    aval = jax.ShapeDtypeStruct((8 * 32, 128), jnp.float32)
    exp = jax.export.export(jf, platforms=["tpu"])(aval, aval, aval)
    assert exp.mlir_module().count("tpu_custom_call") >= 2
    # no ppermute ring in the backward: the recompute fallback would
    # show up as collective-permute ops
    assert "collective_permute" not in exp.mlir_module()


@pytest.mark.parametrize("causal", [False, True])
def test_bwd_kernel_matches_reference_multihead(causal):
    """The fused backward kernel (serial interpreter mode) against the
    differentiated pure-jax ring, GQA layout + nontrivial cotangent —
    dQ/dK/dV all match, causal and full."""
    from mpi_tpu.tpu.pallas_attention import _fallback_attention

    Pn, Hq, Hkv, Sb, d = 4, 4, 2, 8, 128
    rng = np.random.RandomState(37)
    q = rng.randn(Hq, Pn * Sb, d).astype(np.float32)
    k = rng.randn(Hkv, Pn * Sb, d).astype(np.float32)
    v = rng.randn(Hkv, Pn * Sb, d).astype(np.float32)
    ct = rng.randn(Hq, Pn * Sb, d).astype(np.float32)
    mesh = default_mesh(Pn)

    def loss_kernel(qb, kb, vb, ctb):
        out = pallas_ring_attention(qb, kb, vb, "world", Pn,
                                    causal=causal, interpret=True)
        return jnp.sum(out * ctb)

    def loss_ref(qb, kb, vb, ctb):
        out = _fallback_attention(qb, kb, vb, "world", Pn,
                                  1.0 / np.sqrt(d), causal)
        return jnp.sum(out * ctb)

    grads = {}
    for name, fn in (("kernel", loss_kernel), ("ref", loss_ref)):
        g = jax.jit(jax.shard_map(
            jax.grad(fn, argnums=(0, 1, 2)), mesh=mesh,
            in_specs=(P(None, "world"),) * 4,
            out_specs=(P(None, "world"),) * 3,
            check_vma=False))(*map(jnp.asarray, (q, k, v, ct)))
        grads[name] = [np.asarray(x) for x in g]
    for gk, gr in zip(grads["kernel"], grads["ref"]):
        np.testing.assert_allclose(gk, gr, rtol=2e-4, atol=2e-5)
    assert all(np.abs(g).max() > 0 for g in grads["kernel"])


@pytest.mark.parametrize("causal", [False, True])
def test_bwd_tiled_parity(causal):
    """Forced-tiled FUSED backward (round 5): with a budget that rules
    out the resident plan but admits backward tiles, grads from the
    tiled [K,V,dK,dV] ring kernel (dQ in HBM, per-tile staging, dK/dV
    carried through the inner loop, diagonal tile-skip) match the
    differentiated reference, full and causal."""
    from mpi_tpu.tpu.pallas_attention import (_fallback_attention,
                                              attention_vmem_plan)

    Pn, Sb, d = 3, 32, 128
    limit = 100_000
    mode, bt = attention_vmem_plan(Sb, d, 1, 1, jnp.float32,
                                   vmem_limit_bytes=limit,
                                   for_backward=True)
    assert mode == "tiled" and bt[0] < Sb, (mode, bt)
    rng = np.random.RandomState(43)
    q = rng.randn(Pn * Sb, d).astype(np.float32)
    k = rng.randn(Pn * Sb, d).astype(np.float32)
    v = rng.randn(Pn * Sb, d).astype(np.float32)
    ct = rng.randn(Pn * Sb, d).astype(np.float32)
    mesh = default_mesh(Pn)

    def loss_kernel(qb, kb, vb, ctb):
        out = pallas_ring_attention(qb, kb, vb, "world", Pn,
                                    causal=causal, interpret=True,
                                    vmem_limit_bytes=limit)
        return jnp.sum(out * ctb)

    def loss_ref(qb, kb, vb, ctb):
        out = _fallback_attention(qb, kb, vb, "world", Pn,
                                  1.0 / np.sqrt(d), causal)
        return jnp.sum(out * ctb)

    grads = {}
    for name, fn in (("kernel", loss_kernel), ("ref", loss_ref)):
        g = jax.jit(jax.shard_map(
            jax.grad(fn, argnums=(0, 1, 2)), mesh=mesh,
            in_specs=(P("world"),) * 4, out_specs=(P("world"),) * 3,
            check_vma=False))(*map(jnp.asarray, (q, k, v, ct)))
        grads[name] = [np.asarray(x) for x in g]
    for gk, gr in zip(grads["kernel"], grads["ref"]):
        np.testing.assert_allclose(gk, gr, rtol=2e-4, atol=1e-4)
    assert all(np.abs(g).max() > 0 for g in grads["kernel"])


@tiled_interpret_aborts
def test_bwd_fallback_out_of_budget():
    """When even the minimal backward tile exceeds the budget the
    custom-vjp recomputes through the pure-jax ring — gradients still
    match the reference (the forward stays on the tiled kernel)."""
    from mpi_tpu.tpu.pallas_attention import (_fallback_attention,
                                              attention_vmem_plan)

    Pn, Sb, d = 2, 32, 128
    limit = 40_000  # tiled forward fits; no backward tile does
    assert attention_vmem_plan(Sb, d, 1, 1, jnp.float32,
                               vmem_limit_bytes=limit)[0] == "tiled"
    assert attention_vmem_plan(Sb, d, 1, 1, jnp.float32,
                               vmem_limit_bytes=limit,
                               for_backward=True)[0] == "fallback"
    rng = np.random.RandomState(41)
    q = rng.randn(Pn * Sb, d).astype(np.float32)
    k = rng.randn(Pn * Sb, d).astype(np.float32)
    v = rng.randn(Pn * Sb, d).astype(np.float32)
    mesh = default_mesh(Pn)

    def loss_kernel(qb, kb, vb):
        out = pallas_ring_attention(qb, kb, vb, "world", Pn,
                                    interpret=True,
                                    vmem_limit_bytes=limit)
        return jnp.sum(out ** 2)

    def loss_ref(qb, kb, vb):
        out = _fallback_attention(qb, kb, vb, "world", Pn,
                                  1.0 / np.sqrt(d))
        return jnp.sum(out ** 2)

    gk = jax.jit(jax.shard_map(
        jax.grad(loss_kernel, argnums=(0, 1, 2)), mesh=mesh,
        in_specs=(P("world"),) * 3, out_specs=(P("world"),) * 3,
        check_vma=False))(*map(jnp.asarray, (q, k, v)))
    gr = jax.jit(jax.shard_map(
        jax.grad(loss_ref, argnums=(0, 1, 2)), mesh=mesh,
        in_specs=(P("world"),) * 3, out_specs=(P("world"),) * 3,
        check_vma=False))(*map(jnp.asarray, (q, k, v)))
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_bwd_tiled_export_tpu():
    """The TILED fused backward lowers through Mosaic at a block size
    the resident plan could never hold (Sb=2048/device: s/p/dp/ds
    temporaries alone would be 64 MB) — long-context training stays on
    the fused ring kernels, no ppermute recompute in the module."""
    from mpi_tpu.tpu.pallas_attention import attention_vmem_plan

    assert attention_vmem_plan(2048, 128, 1, 1, jnp.float32,
                               for_backward=True)[0] == "tiled"
    mesh = AbstractMesh((8,), ("s",))

    def loss(q, k, v):
        out = pallas_ring_attention(q, k, v, "s", 8, causal=True,
                                    interpret=False)
        return jax.lax.psum(jnp.sum(out ** 2), "s")

    jf = jax.jit(jax.shard_map(
        lambda q, k, v: jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v),
        mesh=mesh, in_specs=(P("s"),) * 3,
        out_specs=(P(), (P("s"),) * 3), check_vma=False))
    aval = jax.ShapeDtypeStruct((8 * 2048, 128), jnp.float32)
    exp = jax.export.export(jf, platforms=["tpu"])(aval, aval, aval)
    assert exp.mlir_module().count("tpu_custom_call") >= 2
    assert "collective_permute" not in exp.mlir_module()


@pytest.mark.parametrize("Hq,Hkv,causal", [(2, 1, False), (4, 2, False),
                                           (2, 1, True), (4, 2, True)])
def test_bwd_tiled_parity_gqa(Hq, Hkv, causal):
    """GQA through the TILED fused backward: dK/dV tiles must
    ACCUMULATE across the query heads of one K/V group (review round
    5: per-head re-zeroing dropped all but the last head's own-block
    contribution) — including under causal masking, where the diagonal
    i_lo tile-skip interacts with the per-group zeroing."""
    from mpi_tpu.tpu.pallas_attention import (_fallback_attention,
                                              attention_vmem_plan)

    Pn, Sb, d = 2, 32, 128
    limit = 250_000
    mode, bt = attention_vmem_plan(Sb, d, Hq, Hkv, jnp.float32,
                                   vmem_limit_bytes=limit,
                                   for_backward=True)
    assert mode == "tiled", (mode, bt)
    rng = np.random.RandomState(47)
    q = rng.randn(Hq, Pn * Sb, d).astype(np.float32)
    k = rng.randn(Hkv, Pn * Sb, d).astype(np.float32)
    v = rng.randn(Hkv, Pn * Sb, d).astype(np.float32)
    ct = rng.randn(Hq, Pn * Sb, d).astype(np.float32)
    mesh = default_mesh(Pn)

    def loss_kernel(qb, kb, vb, ctb):
        out = pallas_ring_attention(qb, kb, vb, "world", Pn,
                                    causal=causal, interpret=True,
                                    vmem_limit_bytes=limit)
        return jnp.sum(out * ctb)

    def loss_ref(qb, kb, vb, ctb):
        out = _fallback_attention(qb, kb, vb, "world", Pn,
                                  1.0 / np.sqrt(d), causal)
        return jnp.sum(out * ctb)

    grads = {}
    for name, fn in (("kernel", loss_kernel), ("ref", loss_ref)):
        g = jax.jit(jax.shard_map(
            jax.grad(fn, argnums=(0, 1, 2)), mesh=mesh,
            in_specs=(P(None, "world"),) * 4,
            out_specs=(P(None, "world"),) * 3,
            check_vma=False))(*map(jnp.asarray, (q, k, v, ct)))
        grads[name] = [np.asarray(x) for x in g]
    for gk, gr in zip(grads["kernel"], grads["ref"]):
        np.testing.assert_allclose(gk, gr, rtol=2e-4, atol=1e-4)


def test_forward_oob_falls_back_loudly():
    """An un-tileable FORWARD budget degrades to the ppermute ring
    (ROADMAP r5 #4 graceful degradation) — numerically exact, with the
    shared loud-substitution contract: a RuntimeWarning AND the
    ``attention_fallbacks`` mpit pvar, never NotImplementedError."""
    from mpi_tpu import mpit
    from mpi_tpu.tpu.pallas_attention import attention_vmem_plan

    Pn, Sb, d = 2, 8, 128
    # a budget even the minimal tile can't satisfy (the plan still
    # raises — the CALLER owns the substitution)
    with pytest.raises(NotImplementedError):
        attention_vmem_plan(Sb, d, 1, 1, np.float32, vmem_limit_bytes=1)
    before = mpit.pvar_read("attention_fallbacks")
    with pytest.warns(RuntimeWarning, match="out of VMEM budget"):
        got, want = _run(Pn, Sb, d, vmem_limit_bytes=1)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
    assert mpit.pvar_read("attention_fallbacks") == before + 1
