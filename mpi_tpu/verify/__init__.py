"""MUST-style runtime correctness verifier + static MPI linter.

The correctness-tooling layer of SURVEY.md §5: the framework's failure
modes are hangs (mismatched blocking cycles), silently divergent
collective schedules, and leaked/raced nonblocking requests — exactly
the bug classes MUST-class MPI verifiers and message-race detectors
catch.  This package grows the repo's seed (mpi_tpu/checker.py schedule
validation + mpi_tpu/trace.py matching verification, both re-exported
here) into a real subsystem:

* **Deadlock detection** (:mod:`.deadlock`): every verified blocking
  wait runs in slices (the FT slice-poll plumbing); past
  ``verify_stall_timeout_s`` the rank publishes its pending op
  out-of-band and the AND-OR wait-for analysis
  (:func:`mpi_tpu.checker.find_deadlock`) turns a closed blocking
  picture into :class:`~mpi_tpu.errors.DeadlockError` naming every
  rank, its pending op, and its call site — instead of a hang.
* **Collective matching** (:mod:`.collcheck`): per-entry signatures
  (sequence, name, root, reduce op, geometry class, algorithm, vector
  counts) cross-checked in-band on the reserved TAG_VERIFY ring before
  any data moves; divergence raises
  :class:`~mpi_tpu.errors.CollectiveMismatchError` on every rank.
* **Request/resource lints** (:mod:`.state`): leaked requests
  (GC'd/finalized unwaited), double-wait, overlapping live buffers
  across pending nonblocking ops (the message-race case), and unfreed
  communicators — reported through ``verify_*`` pvars and the
  finalize-time report (:func:`take_report` / :func:`finalize_report`).
* **Static lint** (:mod:`.lint` + ``tools/mpilint.py``): an AST pass
  flagging rank-conditional collectives, send-send cycles between
  literal rank pairs, literal count truncation, and operations on
  possibly-revoked comms without an error handler.

Enable with ``MPI_TPU_VERIFY=1`` under the launcher (or
``python -m mpi_tpu.launcher --verify``), ``run_local(...,
verify=True)``, or :func:`enable` on any P2P communicator.  Off (the
default) the entire subsystem is a single ``is None`` attribute test
per operation — the zero-copy hot path's pvar contracts and bench p50s
are untouched (``bench.py --verify-overhead`` proves it).
"""

from __future__ import annotations

import os
from typing import Optional

from ..checker import ScheduleError, find_deadlock, validate_perm, \
    validate_rounds, verify_matching
from ..errors import CollectiveMismatchError, DeadlockError
from ..trace import TracingTransport, verify_run
from . import state as _state
from .collcheck import TAG_VERIFY
from .lint import Finding, lint_file, lint_paths, lint_source
from .state import (CommVerify, FileBoard, MemoryBoard, WorldVerify,
                    finalize_report, peek_report, take_report, user_site)

__all__ = [
    "enable", "is_enabled", "take_report", "peek_report", "finalize_report",
    "user_site",
    "MemoryBoard", "FileBoard", "WorldVerify", "CommVerify",
    "DeadlockError", "CollectiveMismatchError", "TAG_VERIFY",
    "Finding", "lint_source", "lint_file", "lint_paths",
    # the folded-in seed: schedule checking + trace-based matching
    "ScheduleError", "validate_perm", "validate_rounds", "verify_matching",
    "find_deadlock", "verify_run", "TracingTransport",
]


def is_enabled(comm) -> bool:
    return getattr(comm, "_verify", None) is not None


def enable(comm, board=None, rdv_dir: Optional[str] = None,
           stall_timeout_s: Optional[float] = None):
    """Enable the runtime verifier on a P2P communicator (idempotent per
    transport; split/dup children inherit).  Process worlds default to
    ``pending.<rank>`` files under the rendezvous dir (``rdv_dir`` or
    the launcher's MPI_TPU_RDV); in-process worlds pass the shared
    :class:`MemoryBoard` (``run_local(..., verify=True)`` does this for
    you)."""
    if getattr(comm, "_verify", None) is not None:
        return comm
    world = getattr(comm._t, "_verify_world", None)
    if world is None:
        if board is None:
            rdv = rdv_dir or os.environ.get("MPI_TPU_RDV")
            if rdv is None:
                raise ValueError(
                    "the verifier needs an out-of-band board: pass board= "
                    "(in-process worlds) or rdv_dir= / set MPI_TPU_RDV "
                    "(process worlds)")
            board = FileBoard(rdv, comm._t.world_rank, comm._t.world_size)
        world = WorldVerify(
            comm._t, board,
            _state._STALL_TIMEOUT_S if stall_timeout_s is None
            else stall_timeout_s)
        comm._t._verify_world = world
    comm._verify = CommVerify(world)
    return comm
