"""Intercommunicators — two-group MPI communication [S: MPI_Intercomm_*].

An intercommunicator binds a LOCAL group and a REMOTE group: point-to-point
ranks address the remote group, and collectives exchange between the groups
(MPI's rooted "MPI_ROOT / MPI_PROC_NULL" convention).  The classic use is
coupling two independently-sized programs — e.g. an ocean model feeding an
atmosphere model, or a producer pool feeding a consumer pool.

Construction here is the host-side spelling consistent with the rest of the
framework (``split_all`` philosophy): every rank names BOTH groups
explicitly, so no leader/bridge negotiation is needed and the same call is
meaningful for an SPMD program's host setup.  MPI's leader-based
``MPI_Intercomm_create(local_comm, local_leader, bridge, remote_leader,
tag)`` is a wire protocol for discovering exactly this information; with a
global view it collapses to the explicit form.

Process backends only: rank-dynamic cross-group p2p is the designed home of
the CPU transports.  On the SPMD backend, express two-group patterns as a
split plus ``exchange``/grouped collectives (the diagnostics point there).

Internals: one child communicator over the UNION of the groups (fresh
context from the parent, so intercomm traffic can never match intracomm
traffic), plus the two orderings.  Collective semantics are implemented on
top of union-group primitives.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

from .communicator import Communicator, P2PCommunicator, Status

# Rooted-collective sentinels [S]: on the root's SIDE, the one root rank
# passes ROOT and its peers pass PROC_NULL; the opposite group passes the
# root's rank within that opposite (remote-to-them) group.
ROOT = -3
PROC_NULL = -2


class InterComm:
    """Two-group communicator; see module docstring.

    ``rank``/``size`` describe the LOCAL group, ``remote_size`` the other
    side; p2p ``dest``/``source`` are REMOTE-group ranks [S]."""

    def __init__(self, union_comm: P2PCommunicator,
                 local_pos: Sequence[int], remote_pos: Sequence[int]):
        self._u = union_comm
        self._local = list(local_pos)    # union-rank of each local member
        self._remote = list(remote_pos)  # union-rank of each remote member
        self._rank = self._local.index(union_comm.rank)

    # -- identity ----------------------------------------------------------

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def size(self) -> int:
        return len(self._local)

    @property
    def remote_size(self) -> int:
        """MPI_Comm_remote_size [S]."""
        return len(self._remote)

    @property
    def is_inter(self) -> bool:
        """MPI_Comm_test_inter [S]."""
        return True

    # -- point-to-point (remote-group addressing) --------------------------

    def _remote_union(self, r: int) -> int:
        if not (0 <= r < len(self._remote)):
            raise ValueError(
                f"remote rank {r} out of range (remote_size="
                f"{len(self._remote)})")
        return self._remote[r]

    def send(self, obj: Any, dest: int, tag: int = 0) -> None:
        self._u.send(obj, self._remote_union(dest), tag)

    def recv(self, source: int = -1, tag: int = -1,
             status: Optional[Status] = None) -> Any:
        src = -1 if source == -1 else self._remote_union(source)
        st = Status() if status is not None else None
        obj = self._u.recv(src, tag, st)
        if status is not None and st is not None:
            # st.source is a union-comm rank; report the REMOTE-group rank
            status.tag = st.tag
            status.source = self._remote.index(st.source)
            status.count_bytes = st.count_bytes
        return obj

    def isend(self, obj: Any, dest: int, tag: int = 0):
        return self._u.isend(obj, self._remote_union(dest), tag)

    def irecv(self, source: int = -1, tag: int = -1):
        src = -1 if source == -1 else self._remote_union(source)
        return self._u.irecv(src, tag)

    def sendrecv(self, sendobj: Any, dest: int, source: int = -1,
                 sendtag: int = 0, recvtag: int = -1) -> Any:
        req = self.isend(sendobj, dest, sendtag)
        out = self.recv(source, recvtag)
        req.wait()
        return out

    # -- collectives (inter-group semantics [S]) ---------------------------

    def barrier(self) -> None:
        self._u.barrier()

    def bcast(self, obj: Any, root: int):
        """Rooted: on the root's side pass ``root=ROOT`` (the root rank) or
        ``root=PROC_NULL`` (its peers, obj ignored); on the receiving side
        pass the root's REMOTE rank.  Receiving side returns the payload;
        the root's side returns ``obj`` unchanged."""
        if root == ROOT:
            for u in self._remote:
                self._u._send_internal(obj, u, _TAG_IBCAST)
            return obj
        if root == PROC_NULL:
            return obj
        return self._u._recv_internal(self._remote_union(root),
                                      _TAG_IBCAST, None)

    def allgather(self, obj: Any) -> List[Any]:
        """Each side contributes; each rank returns the REMOTE group's
        contributions in remote rank order [S]."""
        everything = self._u.allgather(obj)
        return [everything[u] for u in self._remote]

    def alltoall(self, objs: Sequence[Any]) -> List[Any]:
        """``objs[j]`` goes to remote rank j; returns one payload from each
        remote rank, in remote rank order."""
        if len(objs) != len(self._remote):
            raise ValueError(
                f"need one payload per remote rank ({len(self._remote)}), "
                f"got {len(objs)}")
        for j in range(len(self._remote)):
            self._u._send_internal(objs[j], self._remote[j], _TAG_IA2A)
        return [self._u._recv_internal(u, _TAG_IA2A, None)
                for u in self._remote]

    def allreduce(self, obj: Any, op=None):
        """MPI inter-allreduce [S]: every rank returns the reduction of the
        REMOTE group's contributions."""
        from . import ops as _ops

        op = op or _ops.SUM
        theirs = self.allgather(obj)
        acc = theirs[0]
        for v in theirs[1:]:
            acc = op.combine(acc, v)
        return acc

    # -- management --------------------------------------------------------

    def merge(self, high: bool = False) -> Communicator:
        """MPI_Intercomm_merge [S]: one intracommunicator over both groups;
        the group passing ``high=False`` gets the lower ranks.  Every rank
        of both groups calls it (collectively) with its side's flag."""
        # order key: (side_is_high, position within side) — computed
        # locally, made total by split's (key, rank) ordering
        key = (1 << 20 if high else 0) + self._rank
        merged = self._u.split(0, key)
        assert merged is not None
        return merged

    def free(self) -> None:
        self._u.free()


# Internal tags: NEGATIVE, like every collective in communicator.py —
# user-level ANY_TAG never matches them (Mailbox._matches), so a wildcard
# recv can never steal a collective payload (code-review finding: positive
# internal tags were stealable).
_TAG_IBCAST = -20
_TAG_IA2A = -21


def create_intercomm(parent: Communicator, group_a: Sequence[int],
                     group_b: Sequence[int]) -> Optional[InterComm]:
    """Collectively build an intercommunicator from two disjoint groups of
    ``parent`` (parent-comm ranks, identical arguments on every rank).
    Members of A see B as the remote group and vice versa; ranks in
    neither group get None (they still participate in the collective
    context allocation, like MPI_Comm_split with MPI_UNDEFINED)."""
    if not isinstance(parent, P2PCommunicator):
        raise NotImplementedError(
            "intercommunicators are a process-backend feature; on the SPMD "
            "backend express two-group patterns with comm.split_by + "
            "exchange/grouped collectives")
    group_a = getattr(group_a, "ranks", group_a)  # accept Group objects
    group_b = getattr(group_b, "ranks", group_b)
    a, b = [int(r) for r in group_a], [int(r) for r in group_b]
    if not a or not b:
        raise ValueError("both groups must be non-empty")
    if len(set(a)) != len(a) or len(set(b)) != len(b):
        raise ValueError(f"duplicate ranks in a group: {a} / {b}")
    if set(a) & set(b):
        raise ValueError(f"groups must be disjoint: {sorted(set(a) & set(b))}")
    for r in a + b:
        if not (0 <= r < parent.size):
            raise ValueError(f"rank {r} out of range for parent size "
                             f"{parent.size}")
    me = parent.rank
    member = me in a or me in b
    # ONE collective split call on the parent (everyone participates)
    union = a + b
    color = 0 if member else None
    key = union.index(me) if member else 0
    child = parent.split(color, key)
    if not member:
        return None
    assert child is not None
    # child rank order == union order (split sorts by (key, parent rank))
    a_pos = list(range(len(a)))
    b_pos = list(range(len(a), len(a) + len(b)))
    if me in a:
        return InterComm(child, a_pos, b_pos)
    return InterComm(child, b_pos, a_pos)
