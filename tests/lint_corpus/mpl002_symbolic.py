"""Seeded bug: the same rendezvous deadlock with a COMPUTED peer —
``peer = 1 - comm.rank`` resolves to the 0<->1 pair only under
dataflow."""


def main(comm):
    peer = 1 - comm.rank
    if comm.rank < 2:
        comm.send(b"x", peer, tag=3)
        return comm.recv(peer, tag=3)
    return None
