"""Flight-recorder telemetry (ISSUE 13): the off-mode zero-cost
contract, ring wraparound, Chrome export, cross-rank clock alignment
(tools/tracecat.py), histogram pvars, the Prometheus renderer, and
``client.stats()``/the metrics scrape staying live while the pool
heals under a kill.

The off-mode contract mirrors ft/verify/progress: with no recorder
enabled every instrumented seam is one ``telemetry.REC is None``
attribute test — mechanically asserted here by the ``trace_events``
pvar staying 0 and the wire-accounting pvars matching a traced run's
(``bench.py --verify-overhead --trace`` prices the same contract on
the CLI).
"""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from mpi_tpu import mpit, serve, telemetry
from mpi_tpu.telemetry import Recorder
from mpi_tpu.telemetry import metrics as tmetrics
from mpi_tpu.transport.local import run_local

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

sys.path.insert(0, os.path.join(REPO, "tools"))
try:
    import tracecat
finally:
    sys.path.pop(0)

# serve pools on this 2-core box: mirror tests/test_serve.py's margins
DETECT_S = 1.5
LOAD_MARGIN_S = 25.0 if (os.cpu_count() or 1) < 4 else 8.0


@pytest.fixture(autouse=True)
def _no_leaked_recorder():
    """A test that enables tracing must not leak the recorder into the
    rest of the tier-1 run (the off-mode contract of every OTHER test
    depends on REC staying None)."""
    telemetry.disable()
    yield
    telemetry.disable()


def _coll_mix(comm):
    comm.allreduce(np.arange(8.0))
    comm.barrier()
    comm.allgather(np.arange(4.0))
    comm.alltoall([np.arange(2.0)] * comm.size)


# -- off-mode contract --------------------------------------------------------


def test_off_mode_zero_events_zero_hot_path_change():
    """Tracing off: zero events recorded (pvar-asserted) and the wire
    accounting — payload copies, pickled array bytes — IDENTICAL to a
    traced run of the same program: the recorder observes the hot path,
    never participates in it."""
    ses = mpit.session_create()
    ses.reset_all()
    run_local(_coll_mix, 2)
    assert telemetry.REC is None
    assert ses.read("trace_events") == 0
    off_copies = ses.read("payload_copies")
    off_pickled = ses.read("bytes_pickled_sent")

    ses.reset_all()
    run_local(_coll_mix, 2, trace=True)
    telemetry.disable()
    assert ses.read("trace_events") > 0
    assert ses.read("payload_copies") == off_copies
    assert ses.read("bytes_pickled_sent") == off_pickled


def test_trace_events_pvar_zero_across_module_surface():
    """No recorder -> the emitting seams (collective wrapper, arena,
    serve, nbc, links) never fire: one pvar proves it for whatever ran
    before this test in the session."""
    assert telemetry.REC is None
    before = mpit.pvar_read("trace_events")
    run_local(_coll_mix, 2)
    assert mpit.pvar_read("trace_events") == before


# -- the recorder -------------------------------------------------------------


def test_ring_wraparound_keeps_newest():
    rec = Recorder(capacity=4)
    for i in range(10):
        rec.emit("test", f"e{i}")
    assert rec.events_total == 10
    assert rec.dropped == 6
    assert [e["name"] for e in rec.dump()] == ["e6", "e7", "e8", "e9"]
    # partial ring: oldest-first without wrap
    rec2 = Recorder(capacity=8)
    rec2.emit("test", "a")
    rec2.emit("test", "b")
    assert [e["name"] for e in rec2.dump()] == ["a", "b"]
    assert rec2.dropped == 0


def test_enable_disable_lifecycle():
    rec = telemetry.enable(rank=7)
    assert telemetry.enable() is rec  # idempotent, first call wins
    rec.emit("test", "x")
    got = telemetry.disable()
    assert got is rec and telemetry.REC is None
    # the just-disabled recorder stays inspectable/exportable
    assert telemetry.recorder() is rec
    assert rec.find("test", "x")


def test_traced_collectives_record_resolved_algorithm():
    """Every collective span carries the CONCRETE algorithm — the
    ``auto`` spelling is rewritten at the dispatch pick (and ``sm`` on
    an arena hit), never recorded as-is."""
    run_local(_coll_mix, 2, trace=True)
    rec = telemetry.disable()
    colls = rec.find("coll")
    assert {e["name"] for e in colls} == {
        "allreduce", "barrier", "allgather", "alltoall"}
    for e in colls:
        assert e["attrs"].get("algorithm") not in (None, "auto"), e
        assert e["dur_ns"] >= 0


def test_blocked_wait_span_past_noise_floor():
    """A recv blocked well past WAIT_MIN_NS becomes a ``wait`` span
    naming the source; an unblocked healthy exchange adds none."""
    def body(comm):
        if comm.rank == 0:
            comm.recv(source=1, tag=5)
        else:
            time.sleep(0.08)
            comm.send(b"x", 0, tag=5)

    run_local(body, 2, trace=True)
    rec = telemetry.disable()
    waits = rec.find("wait", "recv")
    assert waits, "blocked recv recorded no wait span"
    assert max(e["dur_ns"] for e in waits) >= 50_000_000
    assert any(e["attrs"].get("src") == 1 for e in waits)


def test_chrome_export_shape(tmp_path):
    run_local(_coll_mix, 2, trace=True)
    rec = telemetry.disable()
    path = telemetry.export_chrome(str(tmp_path / "t.json"), rec)
    doc = json.load(open(path))
    assert doc["mpi_tpu"]["events_total"] == rec.events_total
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert spans and all("dur" in e and "ts" in e for e in spans)
    assert any(e.get("ph") == "M" for e in doc["traceEvents"])
    # export_to_dir: the per-rank filename contract tracecat globs
    rec.trace_dir = str(tmp_path / "d")
    out = rec.export_to_dir()
    assert os.path.basename(out).startswith("trace.r")
    assert tracecat.load_traces([str(tmp_path / "d")])


def test_export_chrome_without_recorder_raises(monkeypatch):
    monkeypatch.setattr(telemetry, "_LAST", None)  # nothing ever traced
    with pytest.raises(RuntimeError, match="enable tracing"):
        telemetry.export_chrome("/tmp/never.json")


# -- cross-rank clock alignment (tools/tracecat.py) ---------------------------


def _frame_evt(name, ts, **args):
    return {"pid": 0, "tid": 1, "name": name, "cat": "frame",
            "ph": "i", "ts": ts, "args": args}


def _mk_doc(rank, events):
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "_path": f"trace.r{rank}.json",
            "mpi_tpu": {"rank": rank, "pid": 1000 + rank,
                        "wall_anchor_ns": 0, "mono_anchor_ns": 0,
                        "events_total": len(events), "dropped": 0,
                        "capacity": 64}}


def test_alignment_recovers_known_offsets():
    """Two ranks whose exported clocks disagree by a known constant:
    matched frames recover the offset and no aligned frame arrives
    before it was sent."""
    true_off1 = 500.0  # rank 1's clock reads 500us BEHIND rank 0's
    lat = 10.0
    d0, d1 = [], []
    for seq, t in ((1, 1000.0), (2, 2000.0)):
        d0.append(_frame_evt("send", t, dest=1, seq=seq))
        d1.append(_frame_evt("recv", t + lat - true_off1, src=0, seq=seq))
    for seq, t in ((1, 1500.0), (2, 2500.0)):
        d1.append(_frame_evt("send", t - true_off1, dest=0, seq=seq))
        d0.append(_frame_evt("recv", t + lat, src=1, seq=seq))
    docs = [_mk_doc(0, d0), _mk_doc(1, d1)]
    offsets = tracecat.estimate_offsets(docs)
    assert offsets[0] == 0.0
    assert offsets[1] == pytest.approx(true_off1, abs=lat)
    assert tracecat.negative_latency_frames(docs, offsets) == 0


def test_alignment_monotone_and_triangle_repair():
    """Three ranks with ASYMMETRIC latencies: the pairwise midpoints
    are triangle-inconsistent, the projection pass still lands inside
    every bracket (zero negative-latency frames), and each rank's own
    event ORDER survives the merge (constant per-rank shift)."""
    true_off = {0: 0.0, 1: 300.0, 2: -200.0}
    docs_ev = {0: [], 1: [], 2: []}
    lat_ab, lat_ba = 5.0, 80.0  # asymmetric: midpoints disagree
    seq = 0
    for a in range(3):
        for b in range(3):
            if a == b:
                continue
            for k in range(3):
                seq += 1
                t = 1000.0 * seq
                lat = lat_ab if a < b else lat_ba
                docs_ev[a].append(_frame_evt(
                    "send", t - true_off[a], dest=b, seq=seq))
                docs_ev[b].append(_frame_evt(
                    "recv", t + lat - true_off[b], src=a, seq=seq))
    docs = [_mk_doc(r, evs) for r, evs in docs_ev.items()]
    merged = tracecat.merge(docs)
    meta = merged["mpi_tpu"]
    assert meta["negative_latency_frames"] == 0
    assert len(meta["ranks"]) == 3
    # per-rank monotonicity: a constant shift preserves each rank's
    # own event order
    ts_by_rank = {}
    for doc in docs:
        r = doc["mpi_tpu"]["rank"]
        ts_by_rank[r] = [e["ts"] for e in doc["traceEvents"]]
    off = {int(k): v for k, v in meta["offsets_us"].items()}
    for r, series in ts_by_rank.items():
        shifted = [t + off[r] for t in series]
        assert shifted == sorted(shifted)


def test_tracecat_cli_report_and_merge(tmp_path):
    d0 = [_frame_evt("send", 100.0, dest=1, seq=1)]
    d1 = [_frame_evt("recv", 105.0, src=0, seq=1)]
    for r, evs in ((0, d0), (1, d1)):
        doc = _mk_doc(r, evs)
        doc.pop("_path")
        with open(tmp_path / f"trace.r{r}.1.json", "w") as f:
            json.dump(doc, f)
    assert tracecat.main([str(tmp_path), "--report"]) == 0
    out = tmp_path / "merged.json"
    assert tracecat.main([str(tmp_path), "-o", str(out)]) == 0
    doc = json.load(open(out))
    assert len(doc["mpi_tpu"]["ranks"]) == 2
    # re-running does not double events (merged.json not globbed)
    assert tracecat.main([str(tmp_path), "-o", str(out)]) == 0
    assert len(json.load(open(out))["traceEvents"]) == len(
        doc["traceEvents"])


# -- histogram pvars ----------------------------------------------------------


def test_histogram_record_read_quantile():
    name = "t_test_hist_s"
    mpit.pvar_hist_reset(name)
    for _ in range(100):
        mpit.hist_record(name, 1e-3)
    mpit.hist_record(name, 1.0)
    snap = mpit.pvar_hist_read(name)
    assert snap["count"] == 101
    assert snap["sum_s"] == pytest.approx(1.1, rel=0.05)
    assert snap["min_s"] == pytest.approx(1e-3, rel=0.01)
    assert snap["max_s"] == pytest.approx(1.0, rel=0.01)
    # log-bucket estimate: within the documented ~41% relative error
    p50 = mpit.hist_quantile(name, 0.5)
    assert 0.5e-3 <= p50 <= 2e-3, p50
    p100 = mpit.hist_quantile(name, 1.0)
    assert 0.5 <= p100 <= 1.0, p100
    cum = mpit.hist_cumulative(name)
    counts = [c for _, c in cum]
    assert counts == sorted(counts) and counts[-1] == 101
    bounds = [b for b, _ in cum]
    assert bounds == sorted(bounds)
    mpit.pvar_hist_reset(name)
    assert mpit.hist_quantile(name, 0.5) is None


def test_histogram_unknown_name_raises():
    with pytest.raises(KeyError, match="unknown histogram"):
        mpit.pvar_hist_read("no_such_hist")
    with pytest.raises(ValueError, match="quantile"):
        mpit.hist_quantile("coll_latency_s", 1.5)


def test_histogram_preseeded_names_stable():
    for name in ("coll_latency_s", "lease_acquire_s", "link_heal_s"):
        assert name in mpit.pvar_hist_list()


def test_coll_latency_histogram_fed_by_traced_run():
    mpit.pvar_hist_reset("coll_latency_s")
    run_local(_coll_mix, 2, trace=True)
    telemetry.disable()
    assert mpit.pvar_hist_read("coll_latency_s")["count"] == 8  # 4 x 2


# -- profiling.CommStats (satellite: no longer dead API) ----------------------


def test_comm_stats_filled_by_traced_run():
    from mpi_tpu import profiling

    run_local(_coll_mix, 3, trace=True)
    telemetry.disable()
    stats = profiling.comm_stats()
    assert stats is not None
    assert stats.ops["allreduce"] == 3 and stats.ops["barrier"] == 3
    assert stats.bytes["allreduce"] == 3 * 8 * 8
    json.loads(stats.to_json())


# -- Prometheus renderer ------------------------------------------------------


def test_prometheus_text_render():
    mpit.pvar_hist_reset("lease_acquire_s")
    mpit.hist_record("lease_acquire_s", 2e-3)
    mpit.hist_record("lease_acquire_s", 4e-3)
    stats = {"epoch": 3, "pool_size": 4, "idle": 2, "leases_active": 1,
             "worlds_per_s": 12.5, "uptime_s": 60.0,
             "leases_granted": 9, "jobs_ok": 7, "jobs_failed": 2,
             "heals_completed": 1, "workers_lost": 1,
             "workers": {0: "idle", 1: "leased"},
             "healing": [2], "worker_pvars": {"link_reconnects": 5}}
    text = tmetrics.prometheus_text(stats)
    assert "mpi_tpu_serve_epoch 3" in text
    assert "mpi_tpu_serve_worlds_per_s 12.5" in text
    assert "mpi_tpu_serve_jobs_ok_total 7" in text
    assert 'mpi_tpu_serve_worker_state{slot="1",state="leased"} 1' in text
    assert "mpi_tpu_serve_healing_slots 1" in text
    assert 'mpi_tpu_worker_pvar{name="link_reconnects"} 5' in text
    assert 'mpi_tpu_lease_acquire_seconds_bucket{le="+Inf"} 2' in text
    assert "mpi_tpu_lease_acquire_seconds_count 2" in text
    assert "mpi_tpu_serve_lease_acquire_p99_seconds" in text
    # every line is exposition-format shaped
    for line in text.strip().splitlines():
        assert line.startswith("#") or " " in line


def test_verify_overhead_trace_leg_quick_smoke():
    """The CLI overhead contract (``bench.py --verify-overhead --trace
    --quick``): trace-off is pvar-zero, trace-on keeps 0 pickled array
    bytes and an unchanged payload-copy count — asserted inside the
    bench itself."""
    from benchmarks import verify_overhead

    assert verify_overhead.main(["--quick", "--trace"]) == 0


# -- serve: stats + scrape stay live under a kill -----------------------------


def test_stats_and_scrape_survive_kill_mid_lease():
    """THE endpoint acceptance: while a leased worker dies and the pool
    heals, a SECOND client's ``stats()`` keeps answering promptly
    (the monitor thread never wedges behind a scrape) and the HTTP
    metrics endpoint keeps serving worlds/s + lease p99 + pool epoch."""
    srv = serve.WorldServer(pool_size=3, backend="socket",
                            detect_timeout_s=DETECT_S, heartbeat_s=0.2,
                            rejoin_timeout_s=20.0, metrics_port=0)
    with srv:
        assert srv.metrics_addr
        worker = serve.connect(srv)
        watcher = serve.connect(srv)
        try:
            stop = threading.Event()
            stats_lat, stats_errs = [], []

            def hammer():
                while not stop.is_set():
                    t0 = time.monotonic()
                    try:
                        st = watcher.stats()
                        assert "epoch" in st
                    except Exception as e:  # noqa: BLE001
                        stats_errs.append(repr(e))
                    stats_lat.append(time.monotonic() - t0)
                    time.sleep(0.05)

            th = threading.Thread(target=hammer, daemon=True)
            th.start()
            lease = worker.acquire(2, timeout=10.0)
            from mpi_tpu.errors import ProcFailedError
            with pytest.raises(ProcFailedError):
                lease.run(serve.job_kill_rank, 1, 2048,
                          timeout=3 * DETECT_S + LOAD_MARGIN_S)
            lease.release()
            # scrape WHILE healing (and after): always answers
            deadline = time.monotonic() + 30.0 + LOAD_MARGIN_S
            healed = False
            while time.monotonic() < deadline:
                body = urllib.request.urlopen(
                    f"http://{srv.metrics_addr}/metrics",
                    timeout=5).read().decode()
                assert "mpi_tpu_serve_epoch" in body
                assert "mpi_tpu_serve_worlds_per_s" in body
                assert "mpi_tpu_serve_lease_acquire_p99_seconds" in body
                st = watcher.stats()
                if st["idle"] == 3 and not st["healing"]:
                    healed = True
                    break
                time.sleep(0.25)
            stop.set()
            th.join(10.0)
            assert healed, "pool did not heal under the watcher"
            assert not stats_errs, stats_errs
            assert stats_lat and max(stats_lat) < 10.0, max(stats_lat)
            final = watcher.stats()
            assert final["epoch"] >= 1
            assert final["lease_acquire_p99_ms"] is not None
        finally:
            worker.close()
            watcher.close()
