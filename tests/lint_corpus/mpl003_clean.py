"""Near-miss twin: counts agree through the same variable dataflow."""


def main(comm, buf, b, dt):
    n = 8
    if comm.rank == 0:
        MPI_Send(buf, dest=1, datatype=dt, count=n)
    if comm.rank == 1:
        return MPI_Recv(source=0, datatype=dt, buf=b, count=n)
    return None
