"""MPI_Scan + MPI_Reduce_scatter semantics on both backends vs numpy
oracles, including cross-backend parity."""

import numpy as np
import pytest

from mpi_tpu import ops
from mpi_tpu.tpu import TpuCommunicator, default_mesh, run_spmd
from mpi_tpu.transport.local import run_local

P = 8


@pytest.mark.parametrize("n", [1, 2, 3, 4, 8])
def test_scan_local(n):
    data = np.random.RandomState(0).randn(n, 5)

    def prog(comm):
        return comm.scan(data[comm.rank], op=ops.SUM)

    res = run_local(prog, n)
    for r in range(n):
        np.testing.assert_allclose(res[r], data[: r + 1].sum(0), rtol=1e-10)


def test_scan_local_max():
    data = np.random.RandomState(1).randn(4, 3)

    def prog(comm):
        return comm.scan(data[comm.rank], op=ops.MAX)

    res = run_local(prog, 4)
    for r in range(4):
        np.testing.assert_allclose(res[r], data[: r + 1].max(0))


@pytest.mark.parametrize("op,oracle", [
    (ops.SUM, lambda d, r: d[: r + 1].sum(0)),
    (ops.MAX, lambda d, r: d[: r + 1].max(0)),
])
def test_scan_tpu(op, oracle):
    data = np.asarray(np.random.RandomState(2).randn(P, 5), np.float32)

    def prog(comm, x):
        return comm.scan(x[comm.rank], op=op)

    out = np.asarray(run_spmd(prog, data))
    for r in range(P):
        np.testing.assert_allclose(out[r], oracle(data, r), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("n", [2, 3, 4])
def test_reduce_scatter_local(n):
    data = np.random.RandomState(3).randn(n, n, 4)  # [src, block, k]

    def prog(comm):
        return comm.reduce_scatter(data[comm.rank], op=ops.SUM)

    res = run_local(prog, n)
    for r in range(n):
        np.testing.assert_allclose(res[r], data[:, r].sum(0), rtol=1e-10)


@pytest.mark.parametrize("algo", ["fused", "ring"])
def test_reduce_scatter_tpu(algo):
    data = np.asarray(np.random.RandomState(4).randn(P, P, 3), np.float32)

    def prog(comm, x):
        return comm.reduce_scatter(x[comm.rank], op=ops.SUM, algorithm=algo)

    out = np.asarray(run_spmd(prog, data))
    for r in range(P):
        np.testing.assert_allclose(out[r], data[:, r].sum(0), rtol=1e-4, atol=1e-5)


def test_reduce_scatter_tpu_max_fused():
    data = np.asarray(np.random.RandomState(5).randn(P, P, 2), np.float32)

    def prog(comm, x):
        return comm.reduce_scatter(x[comm.rank], op=ops.MAX, algorithm="fused")

    out = np.asarray(run_spmd(prog, data))
    for r in range(P):
        np.testing.assert_allclose(out[r], data[:, r].max(0), rtol=1e-5)


def test_reduce_scatter_grouped():
    mesh = default_mesh()
    world = TpuCommunicator("world", mesh)
    rows = world.split_by(lambda i: i // 4)
    data = np.asarray(np.random.RandomState(6).randn(P, 4, 3), np.float32)

    def prog(comm, x):
        return rows.reduce_scatter(x[comm.rank], op=ops.SUM, algorithm="ring")

    out = np.asarray(run_spmd(prog, data, mesh=mesh))
    for r in range(P):
        grp = slice(0, 4) if r < 4 else slice(4, 8)
        np.testing.assert_allclose(out[r], data[grp, r % 4].sum(0),
                                   rtol=1e-4, atol=1e-5)


def test_allgather_alltoall_cpu_stack_arrays():
    """Array payloads stack on CPU backends, matching TPU's [P, ...] result."""

    def prog(comm):
        g = comm.allgather(np.full(2, float(comm.rank)))
        blocks = np.arange(comm.size * 3.0).reshape(comm.size, 3) + comm.rank * 100
        a = comm.alltoall(blocks)
        return g, a

    res = run_local(prog, 4)
    g0, a0 = res[0]
    assert isinstance(g0, np.ndarray) and g0.shape == (4, 2)
    assert isinstance(a0, np.ndarray) and a0.shape == (4, 3)
    np.testing.assert_array_equal(g0[:, 0], [0, 1, 2, 3])
    # a0[src] = src's block 0 = [0,1,2] + src*100
    for src in range(4):
        np.testing.assert_array_equal(a0[src], np.arange(3.0) + src * 100)
