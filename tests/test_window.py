"""One-sided RMA (Window / put / get / accumulate / fence) on both backends.

Contract [S]: MPI-2 active-target RMA (mpi_tpu/window.py module docstring
for the deterministic refinements).  Parity: the same portable program must
produce identical windows on the process backends and the SPMD backend.
"""

import time

import numpy as np
import pytest

import mpi_tpu
from mpi_tpu import ops
from mpi_tpu.transport.local import run_local
from mpi_tpu.tpu import SpmdSemanticsError, run_spmd

P = 4


# -- portable programs (run on every backend) ------------------------------


def ring_put_prog(comm):
    """Each rank puts its rank-stamped vector into its right neighbor."""
    win = comm.win_create(np.zeros(3, np.float32))
    data = np.ones(3, np.float32) * (comm.rank + 1)  # rank-varying on TPU
    pairs = [(r, (r + 1) % P) for r in range(P)]
    win.put(data, pairs)
    win.fence()
    return win.local


def accumulate_prog(comm):
    """All ranks accumulate into rank pattern; two calls stack in issue order."""
    win = comm.win_create(np.ones(2, np.float32))
    mine = np.ones(2, np.float32) * comm.rank
    pairs = [(r, (r + 1) % P) for r in range(P)]
    win.accumulate(mine, pairs, op=ops.SUM)
    win.accumulate(mine, pairs, op=ops.SUM)
    win.fence()
    return win.local


def get_after_put_prog(comm):
    """A get in the same epoch observes the epoch's puts (the documented
    refinement)."""
    win = comm.win_create(np.zeros((), np.float32))
    val = np.float32(10.0) * comm.rank
    put_pairs = [(r, (r + 1) % P) for r in range(P)]
    get_pairs = [((r + 1) % P, r) for r in range(P)]  # read it back
    win.put(val, put_pairs)
    fut = win.get(get_pairs, fill=-1.0)
    win.fence()
    return fut.value


def multi_epoch_prog(comm):
    """Fences separate epochs; window state persists across them."""
    win = comm.win_create(np.zeros(2, np.float32))
    one = comm.localize(np.ones(2, np.float32))
    all_self = [(r, r) for r in range(P)]
    win.accumulate(one, all_self)
    win.fence()
    win.accumulate(one, all_self)
    win.fence()
    return win.local


def loc_prog(comm):
    """Sub-window addressing with a static loc."""
    win = comm.win_create(np.zeros(4, np.float32))
    v = np.ones(2, np.float32) * (comm.rank + 1)
    pairs = [(r, (r + 1) % P) for r in range(P)]
    win.put(v, pairs, loc=np.s_[1:3])
    win.fence()
    return win.local


RING_PUT_EXPECT = np.stack(
    [np.full(3, float((r - 1) % P) + 1.0, np.float32) for r in range(P)])


@pytest.mark.parametrize("prog,expect", [
    (ring_put_prog, RING_PUT_EXPECT),
    (accumulate_prog, np.stack(
        [1.0 + 2.0 * float((r - 1) % P) * np.ones(2, np.float32)
         for r in range(P)])),
    (get_after_put_prog, np.array(
        [float(r) * 10.0 for r in range(P)], np.float32)),
    (multi_epoch_prog, np.full((P, 2), 2.0, np.float32)),
    (loc_prog, np.stack(
        [np.array([0, (r - 1) % P + 1, (r - 1) % P + 1, 0], np.float32)
         for r in range(P)])),
])
def test_rma_parity_local_vs_spmd(prog, expect):
    got_local = np.stack([np.asarray(x) for x in run_local(prog, P)])
    got_spmd = np.stack([np.asarray(x) for x in run_spmd(prog, nranks=P)])
    np.testing.assert_allclose(got_local, np.asarray(expect), rtol=0, atol=0)
    np.testing.assert_allclose(got_spmd, np.asarray(expect), rtol=0, atol=0)


# -- process-backend-only behaviors ----------------------------------------


def test_rma_dynamic_int_target_local():
    """Classic rank-dynamic MPI RMA (int target) on the process backend."""

    def prog(comm):
        win = comm.win_create(np.zeros(1, np.float64))
        if comm.rank != 0:
            win.accumulate(np.array([float(comm.rank)]), 0)  # all into rank 0
        win.fence()
        return win.local[0]

    res = run_local(prog, P)
    assert res[0] == sum(range(1, P))
    assert all(res[r] == 0.0 for r in range(1, P))


def test_rma_dynamic_get_local():
    def prog(comm):
        win = comm.win_create(np.array([comm.rank * 2.0]))
        fut = win.get((comm.rank + 1) % comm.size)  # read right neighbor
        win.fence()
        return fut.value[0]

    res = run_local(prog, P)
    assert res == [((r + 1) % P) * 2.0 for r in range(P)]


def test_get_future_before_fence_raises():
    def prog(comm):
        win = comm.win_create(np.zeros(1))
        fut = win.get((comm.rank + 1) % comm.size)
        with pytest.raises(RuntimeError, match="closing fence"):
            _ = fut.value
        win.fence()
        return fut.value is not None

    assert all(run_local(prog, 2))


def test_freed_window_rejected():
    def prog(comm):
        win = comm.win_create(np.zeros(1))
        win.fence()
        win.free()
        with pytest.raises(RuntimeError, match="freed"):
            win.fence()
        return True

    assert all(run_local(prog, 2))


# -- SPMD-only diagnostics --------------------------------------------------


def test_spmd_rejects_dynamic_int_target():
    def prog(comm):
        win = comm.win_create(np.zeros(1, np.float32))
        try:
            win.put(np.ones(1, np.float32), 0)
        except SpmdSemanticsError:
            return comm.rank * 0 + 1
        return comm.rank * 0

    assert np.all(np.asarray(run_spmd(prog, nranks=P)) == 1)


def test_spmd_rma_inside_jit_compiles_once():
    """The whole epoch lowers into one jitted program."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as Pspec

    from mpi_tpu.tpu import TpuCommunicator, default_mesh

    mesh = default_mesh(P)
    comm = TpuCommunicator("world", mesh)

    def step(x):
        win = comm.win_create(x)
        win.accumulate(x, [(r, (r + 1) % P) for r in range(P)])
        win.fence()
        return win.local

    f = jax.jit(jax.shard_map(step, mesh=mesh, in_specs=Pspec("world"),
                              out_specs=Pspec("world")))
    x = jnp.arange(P * 2, dtype=jnp.float32).reshape(P, 2)
    out = np.asarray(f(x))
    expect = x + np.roll(np.asarray(x), 1, axis=0)
    np.testing.assert_allclose(out, expect)


def test_two_windows_interleaved_epochs_race():
    """Regression: a fast rank's next fence (second window, same epoch
    number) must not be consumed by a slow peer's current fence — phase-2
    receives are source-specific, not any-source."""
    import time

    def prog(comm):
        win1 = comm.win_create(np.zeros(2, np.float64))
        win2 = comm.win_create(np.zeros(2, np.float64))
        Pn = comm.size
        ring = [(r, (r + 1) % Pn) for r in range(Pn)]
        win1.put(np.full(2, comm.rank + 1.0), ring)
        win1.fence()
        if comm.rank == 1:
            time.sleep(0.05)  # skew: rank 1 lags between the two fences
        win2.put(np.full(2, comm.rank + 10.0), ring)
        win2.fence()
        return float(win1.local[0]), float(win2.local[0])

    res = run_local(prog, P)
    for r in range(P):
        assert res[r] == ((r - 1) % P + 1.0, (r - 1) % P + 10.0), (r, res[r])


# -- passive target (MPI_Win_lock/unlock) -----------------------------------


def test_passive_put_get_without_target_participation():
    """True one-sided: the target NEVER calls a window op while the origin
    locks, writes, reads, unlocks — the per-window server thread services
    everything."""
    import time

    def prog(comm):
        win = comm.win_create(np.zeros(4, np.float32))
        comm.barrier()
        if comm.rank == 0:
            win.lock(1)
            win.put_at(1, np.arange(4.0, dtype=np.float32))
            win.accumulate_at(1, np.ones(4, np.float32))
            got = win.get_at(1)
            win.unlock(1)
            comm.barrier()  # release the passive target
            return got
        # rank 1 (and others): computing, never touching the window
        comm.barrier()
        return np.copy(win.local)

    res = run_local(prog, 3)
    np.testing.assert_allclose(res[0], np.arange(4.0) + 1)
    np.testing.assert_allclose(res[1], np.arange(4.0) + 1)  # target sees it
    np.testing.assert_allclose(res[2], 0.0)


def test_exclusive_lock_serializes_accumulates():
    """N ranks × K lock/acc/unlock epochs on one target: the counter ends
    exactly N*K — no lost updates under mutual exclusion."""
    def prog(comm):
        win = comm.win_create(np.zeros((), np.int64))
        comm.barrier()
        K = 10
        for _ in range(K):
            win.lock(0)
            cur = win.get_at(0)
            win.put_at(0, cur + 1)  # read-modify-write needs the lock
            win.unlock(0)
        comm.barrier()
        return int(win.local)

    res = run_local(prog, 4)
    assert res[0] == 4 * 10, res


def test_shared_locks_admit_concurrent_readers():
    def prog(comm):
        win = comm.win_create(np.full(2, comm.rank, np.float32))
        comm.barrier()
        target = (comm.rank + 1) % comm.size
        win.lock(target, exclusive=False)
        got = win.get_at(target)
        win.unlock(target)
        comm.barrier()
        return got

    res = run_local(prog, 4)
    for r in range(4):
        np.testing.assert_allclose(res[r], (r + 1) % 4)


def test_self_lock_epoch():
    def prog(comm):
        win = comm.win_create(np.zeros(2, np.float32))
        win.lock(comm.rank)
        win.put_at(comm.rank, np.full(2, 7.0, np.float32))
        got = win.get_at(comm.rank)
        win.unlock(comm.rank)
        win.free()
        return got

    res = run_local(prog, 2)
    np.testing.assert_allclose(res[0], 7.0)


def test_passive_and_fence_epochs_coexist_sequentially():
    def prog(comm):
        win = comm.win_create(np.zeros(2, np.float32))
        # fence epoch first
        win.accumulate(np.ones(2, np.float32), [(r, (r + 1) % comm.size)
                                                for r in range(comm.size)])
        win.fence()
        comm.barrier()
        # then a passive epoch
        if comm.rank == 0:
            win.lock(1)
            win.accumulate_at(1, np.full(2, 10.0, np.float32))
            win.unlock(1)
        comm.barrier()
        return np.copy(win.local)

    res = run_local(prog, 3)
    np.testing.assert_allclose(res[1], 11.0)
    np.testing.assert_allclose(res[0], 1.0)


def test_tpu_window_passive_diagnostic():
    import jax.numpy as jnp

    from mpi_tpu.tpu import TpuCommunicator, default_mesh

    comm = TpuCommunicator("world", default_mesh())
    win = comm.win_create(jnp.zeros(2))
    with pytest.raises(NotImplementedError, match="fence epochs"):
        win.lock(0)


def test_passive_op_failure_surfaces_at_unlock_and_server_survives():
    """A bad op (shape mismatch) must re-raise at the ORIGIN's unlock —
    and the target's server must keep serving later epochs (code-review
    regression: a dead server turned one bad put into a permanent hang)."""
    def prog(comm):
        win = comm.win_create(np.zeros(4, np.float32))
        comm.barrier()
        if comm.rank == 0:
            win.lock(1)
            win.put_at(1, np.ones(3, np.float32), loc=slice(0, 2))  # bad
            try:
                win.unlock(1)
                failed = False
            except RuntimeError as e:
                failed = "failed at target" in str(e)
            # the server must still serve a SECOND, clean epoch
            win.lock(1)
            win.put_at(1, np.full(4, 5.0, np.float32))
            win.unlock(1)
            comm.barrier()
            return failed
        comm.barrier()
        return np.copy(win.local)

    res = run_local(prog, 2)
    assert res[0] is True
    np.testing.assert_allclose(res[1], 5.0)


def test_passive_get_failure_raises_at_origin():
    def prog(comm):
        win = comm.win_create(np.zeros(4, np.float32))
        comm.barrier()
        out = None
        if comm.rank == 0:
            win.lock(1, exclusive=False)
            try:
                win.get_at(1, loc=slice(0, 99, 0))  # zero step: bad loc
            except RuntimeError as e:
                out = "get failed" in str(e)
            win.unlock(1)
        comm.barrier()
        return out

    assert run_local(prog, 2)[0] is True


def test_self_lock_queues_fairly_with_remote():
    """Self-locks join the same FIFO queue as remote requesters: under
    contention on rank 0's window, rank 0's own lock(0) completes."""
    def prog(comm):
        win = comm.win_create(np.zeros((), np.int64))
        comm.barrier()
        for _ in range(8):
            win.lock(0)
            win.put_at(0, win.get_at(0) + 1)
            win.unlock(0)
        comm.barrier()
        return int(win.local)

    res = run_local(prog, 4)  # rank 0 self-locks while 1-3 hammer it
    assert res[0] == 4 * 8


def test_self_target_errors_follow_remote_contract():
    """Self-targeted op failures defer to unlock as RuntimeError — same
    type, same call site as the remote path (code-review regression)."""
    def prog(comm):
        win = comm.win_create(np.zeros(4, np.float32))
        win.lock(comm.rank)
        win.put_at(comm.rank, np.ones(3, np.float32), loc=slice(0, 2))
        try:
            win.unlock(comm.rank)
            return False
        except RuntimeError as e:
            return "failed at target" in str(e)

    assert all(run_local(prog, 2))


def test_passive_reply_waits_honor_recv_timeout():
    """A crashed target surfaces as RecvTimeout at the origin's get/unlock
    (the failure-detection contract), not a hang."""
    from mpi_tpu.transport.base import RecvTimeout

    def prog(comm):
        comm.recv_timeout = 0.5
        win = comm.win_create(np.zeros(2, np.float32))
        if comm.rank == 0:
            win.lock(1)               # grant while the server is alive
            comm.send(b"locked", dest=1, tag=98)
            comm.recv(source=1, tag=99)  # rank 1's server is now dead
            try:
                win.get_at(1)
                return False
            except (RecvTimeout, RuntimeError) as e:
                return isinstance(e, RecvTimeout) or "timed out" in str(e)
        else:
            comm.recv(source=0, tag=98)  # rank 0 holds the lock
            # rank 1 "crashes": stop its server so nothing replies
            win._srv_comm._send_internal(("stop",), comm.rank, -8)
            win._srv_thread.join(timeout=5)
            comm.send(b"dead", dest=0, tag=99)
            import time
            time.sleep(1.2)  # stay alive while rank 0 times out
            return True

    res = run_local(prog, 2)
    assert res[0] is True


# -- PSCW generalized active target (round 3) -------------------------------


def test_pscw_put_visible_after_wait():
    """Origin start/put/complete; target post/wait — the put is applied
    before wait returns (the completion rides the op channel FIFO)."""
    def prog(comm):
        win = comm.win_create(np.zeros(2, np.float64))
        if comm.rank == 0:
            win.post([1])          # expose to origin 1
            win.wait()             # returns only after 1's complete
            out = win.local.copy()
        else:
            win.start([0])
            win.put_at(0, np.array([3.5, 4.5]))
            win.accumulate_at(0, np.array([0.5, 0.5]))
            win.complete()
            out = None
        comm.barrier()
        win.free()
        return out

    res = run_local(prog, 2)
    assert np.array_equal(res[0], [4.0, 5.0])


def test_pscw_multiple_origins_and_test():
    def prog(comm):
        win = comm.win_create(np.zeros(1, np.float64))
        if comm.rank == 0:
            win.post([1, 2])
            while not win.test():
                time.sleep(0.001)
            win.wait()  # already closed: returns immediately
            out = float(win.local[0])
        else:
            win.start([0])
            win.accumulate_at(0, np.array([float(comm.rank)]))
            win.complete()
            out = None
        comm.barrier()
        win.free()
        return out

    res = run_local(prog, 3)
    assert res[0] == 3.0  # 1 + 2


def test_pscw_epoch_discipline_errors():
    def prog(comm):
        win = comm.win_create(np.zeros(1))
        with pytest.raises(RuntimeError, match="without MPI_Win_start"):
            win.complete()
        assert win.test()  # no epoch: trivially closed
        win.wait()         # no epoch: returns immediately
        win.post([])       # empty exposure epoch
        win.wait()
        win.start([])      # empty access epoch
        with pytest.raises(RuntimeError, match="previous access"):
            win.start([])
        win.complete()
        comm.barrier()
        win.free()
        return True

    assert run_local(prog, 1)[0] is True


def test_pscw_wait_times_out_on_dead_origin():
    """An origin that never completes surfaces as RecvTimeout at the
    target's wait (the failure-detection contract), not a hang."""
    from mpi_tpu.transport.base import RecvTimeout

    def prog(comm):
        win = comm.win_create(np.zeros(1))
        if comm.rank == 0:
            comm.recv_timeout = 0.5  # rank 0 only: rank 1's barrier must
            # not race the deliberate 0.5s wait-timeout window
            win.post([1])
            with pytest.raises(RecvTimeout, match="never completed"):
                win.wait()
        comm.barrier()  # rank 1 never starts/completes — by design
        win.free()
        return True

    run_local(prog, 2)


def test_pscw_complete_raises_target_op_errors():
    """A bad op inside a start/complete epoch raises AT complete() —
    and must not leak into a later, clean lock/unlock epoch."""
    def prog(comm):
        win = comm.win_create(np.zeros(2, np.float64))
        if comm.rank == 0:
            win.post([1])
            win.wait()
            # later clean passive epoch from rank 1 must not re-raise
            comm.barrier()
            comm.barrier()
        else:
            win.start([0])
            win.put_at(0, np.zeros(3))  # wrong shape: fails at target
            with pytest.raises(RuntimeError, match="PSCW op"):
                win.complete()
            comm.barrier()
            win.lock(0)
            win.put_at(0, np.ones(2))
            win.unlock(0)  # clean epoch: no stale error resurfaces
            comm.barrier()
        win.free()
        return True

    run_local(prog, 2)


# -- MPI-3 atomics + flush (round 3) ----------------------------------------


def test_fetch_and_op_is_atomic_counter():
    """Concurrent fetch-adds from all ranks: every rank gets a distinct
    previous value — the atomicity a lock/get/put/unlock has to work
    around."""
    def prog(comm):
        win = comm.win_create(np.zeros(1, np.int64))
        comm.barrier()
        old = [int(win.fetch_and_op(0, np.ones(1, np.int64))[0])
               for _ in range(5)]
        comm.barrier()
        total = int(win.local[0]) if comm.rank == 0 else None
        comm.barrier()
        win.free()
        return old, total

    res = run_local(prog, 4)
    assert res[0][1] == 20  # 4 ranks x 5 increments
    seen = [v for olds, _ in res for v in olds]
    assert sorted(seen) == list(range(20))  # all distinct: atomic


def test_compare_and_swap():
    def prog(comm):
        win = comm.win_create(np.zeros(1, np.int64))
        comm.barrier()
        if comm.rank == 1:
            # succeed: 0 -> 7, then fail: compare 0 != 7
            a = win.compare_and_swap(0, np.zeros(1, np.int64),
                                     np.full(1, 7, np.int64))
            b = win.compare_and_swap(0, np.zeros(1, np.int64),
                                     np.full(1, 9, np.int64))
            out = (int(a[0]), int(b[0]))
        else:
            out = None
        comm.barrier()
        final = int(win.local[0]) if comm.rank == 0 else None
        comm.barrier()
        win.free()
        return out, final

    res = run_local(prog, 2)
    assert res[1][0] == (0, 7)
    assert res[0][1] == 7  # the failed CAS did not write


def test_win_flush_surfaces_error_inside_epoch():
    def prog(comm):
        win = comm.win_create(np.zeros(2))
        comm.barrier()
        if comm.rank == 1:
            win.lock(0)
            win.put_at(0, np.zeros(5))  # wrong shape
            with pytest.raises(RuntimeError, match="failed at target"):
                win.flush(0)
            win.put_at(0, np.ones(2))  # epoch continues after flush
            win.flush(0)               # clean: no stale error
            win.unlock(0)              # clean too
        comm.barrier()
        out = win.local.copy() if comm.rank == 0 else None
        comm.barrier()
        win.free()
        return out

    res = run_local(prog, 2)
    assert np.array_equal(res[0], [1.0, 1.0])


def test_atomics_respect_exclusive_lock():
    """A fetch_and_op issued while another rank holds the exclusive lock
    is DEFERRED to lock release — it cannot pierce the epoch (review
    round 3: the read-modify-write under exclusive lock must not lose
    updates)."""
    def prog(comm):
        win = comm.win_create(np.zeros(1, np.int64))
        comm.barrier()
        if comm.rank == 1:
            win.lock(0, exclusive=True)
            comm.send("locked", dest=2, tag=1)
            old = int(np.asarray(win.get_at(0))[0])
            time.sleep(0.15)  # window for rank 2's atomic to sneak in
            win.put_at(0, np.asarray([old + 100], np.int64))
            win.unlock(0)
            out = None
        elif comm.rank == 2:
            comm.recv(source=1, tag=1)
            # recv_timeout SHORTER than the lock hold: the immediate
            # 'deferred' notice must keep this from false-positive
            # timing out while the final reply stays application-bound
            win._ensure_server()
            win._org_comm.recv_timeout = 0.05
            # issued mid-epoch: must apply only after rank 1's unlock
            prev = int(win.fetch_and_op(0, np.ones(1, np.int64))[0])
            out = prev
        else:
            out = None
        comm.barrier()
        final = int(win.local[0]) if comm.rank == 0 else None
        comm.barrier()
        win.free()
        return out, final

    res = run_local(prog, 3)
    assert res[2][0] == 100   # atomic saw the epoch's result, not 0
    assert res[0][1] == 101   # and its increment was not lost


def test_atomic_self_path_error_parity():
    def prog(comm):
        win = comm.win_create(np.zeros(2))
        with pytest.raises(RuntimeError, match="failed at target 0"):
            win.fetch_and_op(0, np.zeros(5))  # self target, wrong shape
        comm.barrier()
        win.free()
        return True

    run_local(prog, 1)


def test_tpu_window_atomics_diagnosed():
    import mpi_tpu

    def prog(comm):
        win = comm.win_create(np.zeros(2, np.float32))
        for fn in (lambda: win.fetch_and_op(0, 1.0),
                   lambda: win.flush(0),
                   lambda: win.post([0])):
            with pytest.raises(NotImplementedError, match="SPMD"):
                fn()
        return 0

    mpi_tpu.run(prog, backend="tpu", nranks=None)


def test_lock_all_flush_all_and_request_rma():
    def prog(comm):
        win = comm.win_create(np.zeros(1, np.float64))
        comm.barrier()
        if comm.rank == 1:
            win.lock_all()
            reqs = [win.raccumulate(t, np.ones(1)) for t in range(comm.size)]
            win.flush_all()
            for r in reqs:
                r.wait()  # already flushed: no-op
            got = win.rget(0).wait()
            win.unlock_all()
            out = float(np.asarray(got)[0])
        else:
            out = None
        comm.barrier()
        local = float(win.local[0])
        comm.barrier()
        win.free()
        return out, local

    res = run_local(prog, 3)
    assert res[1][0] == 1.0          # rget after the accumulate epoch
    assert all(r[1] == 1.0 for r in res)  # every window accumulated once


def test_get_accumulate_array_payload():
    def prog(comm):
        win = comm.win_create(np.arange(3, dtype=np.float64))
        comm.barrier()
        if comm.rank == 1:
            old = win.get_accumulate(0, np.full(3, 10.0))
            out = np.asarray(old)
        else:
            out = None
        comm.barrier()
        final = win.local.copy() if comm.rank == 0 else None
        comm.barrier()
        win.free()
        return out, final

    res = run_local(prog, 2)
    assert np.array_equal(res[1][0], [0, 1, 2])     # fetched pre-image
    assert np.array_equal(res[0][1], [10, 11, 12])  # accumulated


def test_rma_request_test_makes_progress():
    """A request-set poll over an Rput request terminates (review:
    test() returned pending forever)."""
    from mpi_tpu import api

    def prog(comm):
        win = comm.win_create(np.zeros(1))
        comm.barrier()
        if comm.rank == 1:
            req = win.rput(0, np.ones(1))
            idx, _ = api.MPI_Waitany([req])
            assert idx == 0
            done, _ = api.MPI_Test(win.rput(0, np.ones(1)))
            assert done
        comm.barrier()
        win.free()
        return True

    run_local(prog, 2)


def test_tpu_window_mpi3_helpers_diagnosed():
    import mpi_tpu

    def prog(comm):
        win = comm.win_create(np.zeros(1, np.float32))
        for fn in (win.lock_all, win.flush_all,
                   lambda: win.get_accumulate(0, 1.0),
                   lambda: win.rput(0, 1.0)):
            with pytest.raises(NotImplementedError, match="SPMD"):
                fn()
        return 0

    mpi_tpu.run(prog, backend="tpu", nranks=None)


def test_rma_request_wait_local_after_flush_all():
    """Requests stamped before a flush complete LOCALLY afterwards —
    the drain does not re-flush per request (review round 3)."""
    def prog(comm):
        win = comm.win_create(np.zeros(1))
        comm.barrier()
        if comm.rank == 1:
            reqs = [win.raccumulate(0, np.ones(1)) for _ in range(4)]
            win.flush_all()
            before = win._flush_epoch(0)
            for r in reqs:
                r.wait()
            assert win._flush_epoch(0) == before  # no extra round-trips
            # a NEW request after the flush still flushes once
            r2 = win.rput(0, np.full(1, 7.0))
            r2.wait()
            assert win._flush_epoch(0) == before + 1
        comm.barrier()
        final = win.local.copy() if comm.rank == 0 else None
        comm.barrier()
        win.free()
        return final

    res = run_local(prog, 2)
    assert np.array_equal(res[0], [7.0])


# -- shared-memory windows (MPI_Win_allocate_shared, round 3) ---------------


def test_shared_window_load_store_across_ranks():
    """Every rank stores into its region; neighbors LOAD it directly —
    no messages, the MPI-3 shared-memory window model."""
    import mpi_tpu

    def prog(comm):
        win = mpi_tpu.win_allocate_shared(comm, 4, np.float64)
        win.local[:] = comm.rank * 10 + np.arange(4)
        win.fence()                       # publish + sync
        left = (comm.rank - 1) % comm.size
        got = win.remote(left).copy()     # plain load of the neighbor
        win.fence()
        # direct remote STORE: rank 0 pokes everyone's first element
        if comm.rank == 0:
            for r in range(comm.size):
                win.remote(r)[0] = -1.0
        win.fence()
        poked = float(win.local[0])
        win.free()
        return got, poked

    res = run_local(prog, 3)
    for r, (got, poked) in enumerate(res):
        left = (r - 1) % 3
        assert np.array_equal(got, left * 10 + np.arange(4))
        assert poked == -1.0


def test_shared_window_ragged_sizes_and_whole():
    import mpi_tpu

    def prog(comm):
        n = comm.rank + 1  # ragged: 1, 2, 3
        win = mpi_tpu.win_allocate_shared(comm, n, np.int32)
        win.local[:] = comm.rank
        win.fence()
        whole = win.whole.copy() if comm.rank == 0 else None
        sz, view = (len(win.remote(2)), None) if comm.rank == 0 else (None, None)
        win.fence()
        win.free()
        return whole, sz

    res = run_local(prog, 3)
    assert np.array_equal(res[0][0], [0, 1, 1, 2, 2, 2])
    assert res[0][1] == 3


def test_shared_window_rejected_on_spmd():
    import mpi_tpu

    def prog(comm):
        with pytest.raises(NotImplementedError, match="process-backend"):
            mpi_tpu.win_allocate_shared(comm, 4)
        return 0

    mpi_tpu.run(prog, backend="tpu", nranks=None)


def test_win_sync_valid_on_any_window():
    import mpi_tpu

    def prog(comm):
        win = comm.win_create(np.zeros(1))
        win.sync()  # MPI-3: valid on ANY window (no-op here, not a crash)
        comm.barrier()
        win.free()
        return True

    run_local(prog, 2)

    def tpu_prog(comm):
        win = comm.win_create(np.zeros(1, np.float32))
        win.sync()
        return 0

    mpi_tpu.run(tpu_prog, backend="tpu", nranks=None)


# -- dynamic windows (MPI_Win_create_dynamic, round 3) ----------------------


def test_dynamic_window_attach_rma_detach():
    def prog(comm):
        win = comm.win_create_dynamic()
        comm.barrier()
        if comm.rank == 0:
            win.attach("grid", np.zeros(4))
            win.attach("halo", np.zeros(2))
        comm.barrier()
        if comm.rank == 1:
            win.lock(0)
            win.put_at(0, np.arange(4.0), loc="grid")
            win.accumulate_at(0, np.ones(2), loc="halo")
            win.put_at(0, np.asarray([-1.0]), loc=("grid", slice(0, 1)))
            got = win.get_at(0, loc="halo")
            win.unlock(0)
            out = np.asarray(got)
        else:
            out = None
        comm.barrier()
        if comm.rank == 0:
            grid = win.detach("grid")
            halo = win.detach("halo")
            final = (grid, halo)
        else:
            final = None
        comm.barrier()
        win.free()
        return out, final

    res = run_local(prog, 2)
    assert np.array_equal(res[1][0], [1.0, 1.0])
    grid, halo = res[0][1]
    assert np.array_equal(grid, [-1.0, 1.0, 2.0, 3.0])
    assert np.array_equal(halo, [1.0, 1.0])


def test_dynamic_window_unattached_region_diagnosed():
    def prog(comm):
        win = comm.win_create_dynamic()
        comm.barrier()
        if comm.rank == 1:
            win.lock(0)
            win.put_at(0, np.ones(2), loc="nope")
            # unhashable loc targeting an unattached region must give the
            # same diagnostic, not TypeError (review round 3)
            win.put_at(0, np.ones(1), loc=(["un", "hashable"], 0))
            with pytest.raises(RuntimeError, match="not attached"):
                win.unlock(0)  # op errors surface at completion
            with pytest.raises(RuntimeError, match="need loc"):
                win.fetch_and_op(1, np.ones(1))  # self, no region
        comm.barrier()
        win.free()
        return True

    run_local(prog, 2)
