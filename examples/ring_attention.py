"""Ring attention: exact long-context attention over sequence-sharded ranks.

SURVEY.md §2 strategy table: the reference is a message-passing primitive
library, so sequence parallelism is *expressible through it* rather than a
built-in — and this example is the proof.  Each rank holds one sequence
block of Q/K/V; K/V blocks rotate around the ring (``comm.shift`` — exactly
one ``lax.ppermute`` per hop on TPU, riding ICI), and attention is
accumulated block-by-block with the online-softmax recurrence, so the full
[S, S] score matrix never materializes on any device.  Memory per device is
O(S/P), enabling contexts P× longer than a single chip holds.

The same program runs on the CPU backends (shift = sendrecv) and the TPU
SPMD backend; tests check it against a single-device full-attention oracle.

    python examples/ring_attention.py --backend tpu -n 8 --seq-per-rank 128
"""

import argparse
import math
import os
import sys

try:
    import mpi_tpu
except ModuleNotFoundError:  # running from a fresh checkout without install
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import mpi_tpu

import jax
import jax.numpy as jnp
import numpy as np


def ring_attention(comm, q, k, v, causal: bool = False):
    """Exact attention (full, or causal) over the sequence sharded on
    the ring.

    q, k, v: [block, d] local blocks.  Returns the local [block, d] output.
    2(P-1) ppermutes total (K and V), overlapping compute with the rotation.
    ``causal`` masks by GLOBAL position (rank r's block covers rows
    [r*block, (r+1)*block)); the step-0 block is the diagonal one, so
    every query row is unmasked at least once from the start.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    m = jnp.full(q.shape[:1], -jnp.inf, q.dtype)       # running row max
    l = jnp.zeros(q.shape[:1], q.dtype)                # running denominator
    acc = jnp.zeros_like(q)                            # running numerator
    k_cur, v_cur = k, v
    b = q.shape[0]
    for step in range(comm.size):
        scores = (q @ k_cur.T) * scale                 # [b, b] one block pair
        if causal:
            kv_idx = (comm.rank - step) % comm.size    # block now held
            qi = comm.rank * b + jnp.arange(b)[:, None]
            kj = kv_idx * b + jnp.arange(b)[None, :]
            scores = jnp.where(kj <= qi, scores, -1e30)
        blk_max = scores.max(axis=-1)
        new_m = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(scores - new_m[:, None])
        acc = acc * corr[:, None] + p @ v_cur
        l = l * corr + p.sum(axis=-1)
        m = new_m
        if step < comm.size - 1:
            k_cur = comm.shift(k_cur, offset=1, wrap=True)
            v_cur = comm.shift(v_cur, offset=1, wrap=True)
    return acc / l[:, None]


def ring_attention_program(comm, seq_per_rank: int = 64, d: int = 32,
                           kernel: bool = False, causal: bool = False):
    """``kernel=True`` (TPU backend, d a multiple of 128, block rows a
    multiple of 8) swaps the shift-based loop for the fused Pallas
    kernel ``mpi_tpu.tpu.pallas_ring_attention`` — K/V circulate as
    in-kernel RDMAs behind the online-softmax compute (the hot path;
    same algebra, protocol model-checked in ring_model.AttentionSim)."""
    key = jax.random.fold_in(jax.random.PRNGKey(7), comm.rank)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (seq_per_rank, d), jnp.float32)
    k = jax.random.normal(kk, (seq_per_rank, d), jnp.float32)
    v = jax.random.normal(kv, (seq_per_rank, d), jnp.float32)
    if kernel:
        if not hasattr(comm, "axis_name"):
            raise NotImplementedError(
                "--kernel is the fused Pallas TPU path: it needs the SPMD "
                "backend (run with --backend tpu); the process backends "
                "use the portable shift-based loop (drop --kernel)")
        from mpi_tpu.tpu import pallas_ring_attention

        out = pallas_ring_attention(q, k, v, comm.axis_name, comm.size,
                                    causal=causal,
                                    interpret=comm._pallas_interp)
    else:
        out = ring_attention(comm, q, k, v, causal=causal)
    return out, q, k, v


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--backend", default=None, choices=[None, "socket", "local", "tpu"])
    ap.add_argument("-n", "--nranks", type=int, default=None)
    ap.add_argument("--seq-per-rank", type=int, default=64)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--kernel", action="store_true",
                    help="use the fused Pallas RDMA kernel "
                         "(TPU backend; --dim multiple of 128)")
    ap.add_argument("--causal", action="store_true",
                    help="causal (autoregressive) masking by global position")
    args = ap.parse_args()

    out = mpi_tpu.run(ring_attention_program, backend=args.backend,
                      nranks=args.nranks, seq_per_rank=args.seq_per_rank,
                      d=args.dim, kernel=args.kernel, causal=args.causal)
    first = out[0] if isinstance(out, list) else out
    o = np.asarray(jax.device_get(first[0] if isinstance(first, tuple) else first))
    print(f"ring attention OK: local block {o.shape[-2:]}, |out| = {np.abs(o).mean():.4f}")


if __name__ == "__main__":
    main()
