"""MPI-4 previews (mpi_tpu/mpi4.py): persistent collectives and
partitioned point-to-point."""

import threading

import numpy as np
import pytest

import mpi_tpu
from mpi_tpu import api, mpi4
from mpi_tpu.transport.local import run_local


# -- persistent collectives --------------------------------------------------


def test_persistent_allreduce_many_rounds():
    """One plan, many starts; buffer CONTENT is read at start time."""
    def prog(comm):
        x = np.ones(4)
        plan = mpi4.persistent_collective(comm, "allreduce", x)
        outs = []
        for round_ in range(3):
            x[:] = round_ + 1  # mutate between starts: start sees it
            outs.append(plan.start().wait())
        return outs

    res = run_local(prog, 3)
    for outs in res:
        for round_, out in enumerate(outs):
            assert np.array_equal(out, np.full(4, 3.0 * (round_ + 1)))


def test_persistent_bcast_and_barrier_api():
    def prog(comm):
        plan = api.MPI_Bcast_init({"v": comm.rank}, root=1, comm=comm)
        got = plan.start().wait()
        bar = api.MPI_Barrier_init(comm=comm)
        bar.start().wait()
        return got

    res = run_local(prog, 3)
    assert all(r == {"v": 1} for r in res)


def test_persistent_collective_discipline():
    def prog(comm):
        plan = mpi4.persistent_collective(comm, "barrier")
        with pytest.raises(RuntimeError, match="before start"):
            plan.wait()
        with pytest.raises(ValueError, match="unknown collective"):
            mpi4.persistent_collective(comm, "frobnicate")
        plan.start()
        plan.wait()
        plan.start()  # restart after completion is the whole point
        plan.wait()
        return True

    run_local(prog, 2)


def test_persistent_rejected_on_spmd():
    def prog(comm):
        with pytest.raises(NotImplementedError, match="already a plan"):
            mpi4.persistent_collective(comm, "allreduce", 1)
        return 0

    mpi_tpu.run(prog, backend="tpu", nranks=None)


# -- partitioned point-to-point ----------------------------------------------


def test_partitioned_out_of_order_pready():
    """Partitions readied out of order arrive and assemble in partition
    order; parrived polls without blocking."""
    def prog(comm):
        n = 4
        if comm.rank == 0:
            buf = np.arange(n * 3.0).reshape(n, 3)
            ps = mpi4.psend_init(comm, buf, n, dest=1, tag=5)
            ps.start()
            for i in (2, 0, 3, 1):
                ps.pready(i)
            ps.wait()
            return None
        pr = mpi4.precv_init(comm, n, source=0, tag=5)
        pr.start()
        parts = pr.wait()
        return np.stack(parts)

    res = run_local(prog, 2)
    assert np.array_equal(res[1], np.arange(12.0).reshape(4, 3))


def test_partitioned_producer_threads():
    """The MPI-4 use case: different producer threads contribute
    different partitions of ONE message."""
    def prog(comm):
        n = 6
        if comm.rank == 0:
            buf = [None] * n
            ps = mpi4.psend_init(comm, buf, n, dest=1)
            ps.start()

            def producer(lo, hi):
                for i in range(lo, hi):
                    buf[i] = ("part", i)
                    ps.pready(i)

            t1 = threading.Thread(target=producer, args=(0, 3))
            t2 = threading.Thread(target=producer, args=(3, 6))
            t1.start(); t2.start(); t1.join(); t2.join()
            ps.wait()
            return None
        pr = mpi4.precv_init(comm, n, source=0)
        pr.start()
        return pr.wait()

    res = run_local(prog, 2)
    assert res[1] == [("part", i) for i in range(6)]


def test_partitioned_parrived_and_partition():
    def prog(comm):
        if comm.rank == 0:
            comm.recv(source=1, tag=9)  # wait for "ready 1 shipped"
            ps = mpi4.psend_init(comm, [10, 20], 2, dest=1)
            ps.start()
            ps.pready(1)
            comm.send("shipped-1", dest=1, tag=9)
            comm.recv(source=1, tag=9)
            ps.pready(0)
            ps.wait()
            return None
        pr = mpi4.precv_init(comm, 2, source=0)
        pr.start()
        comm.send("go", dest=0, tag=9)
        comm.recv(source=0, tag=9)
        # partition 1 shipped; partition 0 not yet
        for _ in range(2000):
            if pr.parrived(1):
                break
        assert pr.parrived(1) and pr.partition(1) == 20
        assert not pr.parrived(0)
        comm.send("more", dest=0, tag=9)
        out = pr.wait()
        assert out == [10, 20]
        return True

    run_local(prog, 2)


def test_partitioned_multiple_pairs_same_tag_isolated():
    """Two psend/precv pairs on the SAME (peer, tag) match in init order
    (private contexts): payloads can never interleave."""
    def prog(comm):
        if comm.rank == 0:
            a = mpi4.psend_init(comm, ["a0", "a1"], 2, dest=1, tag=1)
            b = mpi4.psend_init(comm, ["b0", "b1"], 2, dest=1, tag=1)
            a.start(); b.start()
            b.pready(0); a.pready(1); b.pready(1); a.pready(0)
            a.wait(); b.wait()
            return None
        a = mpi4.precv_init(comm, 2, source=0, tag=1)
        b = mpi4.precv_init(comm, 2, source=0, tag=1)
        a.start(); b.start()
        return a.wait(), b.wait()

    res = run_local(prog, 2)
    assert res[1] == (["a0", "a1"], ["b0", "b1"])


def test_partitioned_wait_names_missing_partitions():
    def prog(comm):
        ps = mpi4.psend_init(comm, [1, 2, 3], 3, dest=0)
        ps.start()
        ps.pready(1)
        with pytest.raises(RuntimeError, match="never marked ready"):
            ps.wait()
        # drain so finalize's sanitizer stays quiet: complete the round
        ps.pready(0); ps.pready(2); ps.wait()
        pr = mpi4.precv_init(comm, 3, source=0)
        pr.start()
        pr.wait()
        return True

    run_local(prog, 1)


def test_partitioned_rounds_do_not_cross():
    """Round 2's partitions must not be drained into round 1 (review
    round 3 — reproduced corruption before the bounded drain)."""
    def prog(comm):
        if comm.rank == 0:
            ps = mpi4.psend_init(comm, ["r1p0", "r1p1"], 2, dest=1)
            ps.start(); ps.pready(0); ps.pready(1); ps.wait()
            # race straight into round 2 before the receiver drains
            ps.start()
            ps2buf = ["r2p0", "r2p1"]
            ps._buf = ps2buf
            ps.pready(0); ps.pready(1); ps.wait()
            return None
        pr = mpi4.precv_init(comm, 2, source=0)
        pr.start()
        import time
        time.sleep(0.1)  # let BOTH rounds land in the mailbox
        for _ in range(1000):
            done, res = pr.test()
            if done:
                break
        assert res == ["r1p0", "r1p1"], res
        pr.start()
        assert pr.wait() == ["r2p0", "r2p1"]
        return True

    run_local(prog, 2)


def test_partitioned_test_completes_round():
    """test() returning True deactivates (MPI semantics): start() may
    follow without wait(); wait() after test returns the cached result."""
    def prog(comm):
        if comm.rank == 0:
            ps = mpi4.psend_init(comm, [1, 2], 2, dest=1)
            ps.start(); ps.pready(0); ps.pready(1)
            done, _ = ps.test()
            assert done
            ps.start()  # no wait() needed after a successful test
            ps.pready(0); ps.pready(1); ps.wait()
            return None
        pr = mpi4.precv_init(comm, 2, source=0)
        assert pr.test() == (True, None)  # inactive tests True
        pr.start()
        while True:
            done, res = pr.test()
            if done:
                break
        assert res == [1, 2]
        assert pr.wait() == [1, 2]  # cached result after test-completion
        pr.start()
        assert pr.wait() == [1, 2]
        return True

    run_local(prog, 2)


def test_partitioned_snapshot_on_aliasing_transport():
    """pready snapshots on by-reference transports: refilling the buffer
    after pready must not mutate what the receiver sees."""
    def prog(comm):
        if comm.rank == 0:
            buf = np.zeros((2, 3))
            ps = mpi4.psend_init(comm, buf, 2, dest=1)
            ps.start()
            buf[0] = 1.0
            ps.pready(0)
            buf[0] = 99.0  # refill immediately — receiver must see 1.0
            buf[1] = 2.0
            ps.pready(1)
            ps.wait()
            return None
        pr = mpi4.precv_init(comm, 2, source=0)
        pr.start()
        parts = pr.wait()
        return np.stack(parts)

    res = run_local(prog, 2, copy_payloads=False)
    assert np.array_equal(res[1], [[1.0] * 3, [2.0] * 3])
