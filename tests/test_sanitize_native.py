"""Sanitizer builds of the native shm layer (ISSUE 5 satellite).

``MPI_TPU_SANITIZE=address|undefined|thread`` makes native/build.py add
the matching ``-fsanitize=`` flags under a separate build-cache name.
These smoke tests build the sanitized .so and exercise the shmring +
shmarena ops under it in a subprocess (an instrumented .so loaded into
an un-instrumented python needs the sanitizer runtime LD_PRELOADed,
which only a fresh process can do) — a leak/overflow/UB in the ring or
arena paths fails the subprocess loudly.

Not tier-1 (``slow``): spawns subprocesses and depends on the host
toolchain shipping the sanitizer runtimes; self-skips where it doesn't.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow

_DRIVER = r"""
import os, sys
sys.path.insert(0, {repo!r})
from mpi_tpu.native.build import load_shmring, ensure_built

lib = load_shmring()
assert ensure_built().endswith({so_tail!r}), ensure_built()
name = f"mpi-tpu-sanitize-{{os.getpid()}}".encode()

# ring: create -> write -> read back -> close -> unlink
ring = lib.shmring_create(name, 1 << 16)
assert ring, "shmring_create failed"
payload = bytes(range(256)) * 8
assert lib.shmring_write(ring, payload, len(payload), 5.0) == 0
out = bytearray(len(payload))
import ctypes
buf = (ctypes.c_char * len(out)).from_buffer(out)
assert lib.shmring_read(ring, buf, len(out), 5.0) == 0
assert bytes(out) == payload
lib.shmring_close(ring)
lib.shmring_unlink(name)

# arena: create -> flag post/read/wait -> close -> unlink
aname = name + b".arena"
arena = lib.shmarena_create(aname, 1 << 12)
assert arena, "shmarena_create failed"
addr = lib.shmarena_addr(arena)
assert lib.shmarena_size(arena) >= (1 << 12)
lib.shmflag_post(addr, 7)
assert lib.shmflag_read(addr) == 7
assert lib.shmflag_wait_ge(addr, 7, 1.0) == 7
lib.shmarena_close(arena)
lib.shmarena_unlink(aname)
print("sanitized native ops OK")
"""


def _runtime_lib(name: str) -> str:
    try:
        out = subprocess.run(["gcc", f"-print-file-name={name}"],
                             capture_output=True, text=True, timeout=30)
    except (FileNotFoundError, subprocess.TimeoutExpired):
        return ""
    path = out.stdout.strip()
    return path if os.path.sep in path and os.path.exists(path) else ""


def _sanitized_smoke(tmp_path, mode: str, so_tail: str, runtime: str):
    runtime_path = _runtime_lib(runtime)
    if not runtime_path:
        pytest.skip(f"toolchain has no {runtime}")
    env = dict(os.environ)
    env["MPI_TPU_SANITIZE"] = mode
    # build first (no preload needed to compile)
    build = subprocess.run(
        [sys.executable, "-c",
         f"import sys; sys.path.insert(0, {REPO!r}); "
         f"from mpi_tpu.native.build import ensure_built; "
         f"print(ensure_built())"],
        capture_output=True, text=True, env=env, timeout=300)
    if build.returncode != 0:
        pytest.skip(f"sanitized build unavailable: {build.stderr[-500:]}")
    assert so_tail in build.stdout, build.stdout
    script = tmp_path / f"drv_{mode}.py"
    script.write_text(_DRIVER.format(repo=REPO, so_tail=so_tail))
    env["LD_PRELOAD"] = runtime_path
    # leak check off: python itself leaks by ASan's standards
    env["ASAN_OPTIONS"] = "detect_leaks=0"
    proc = subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, env=env,
                          timeout=300)
    assert proc.returncode == 0, (proc.stdout[-500:], proc.stderr[-2000:])
    assert "sanitized native ops OK" in proc.stdout


def test_asan_smoke(tmp_path):
    _sanitized_smoke(tmp_path, "address", "_shmring.asan.so", "libasan.so")


def test_ubsan_smoke(tmp_path):
    _sanitized_smoke(tmp_path, "undefined", "_shmring.ubsan.so",
                     "libubsan.so")


def test_unknown_mode_rejected():
    from mpi_tpu.native.build import NativeBuildError, sanitize_mode

    os.environ["MPI_TPU_SANITIZE"] = "bogus"
    try:
        with pytest.raises(NativeBuildError):
            sanitize_mode()
    finally:
        del os.environ["MPI_TPU_SANITIZE"]
