"""Dynamic process management — MPI_Comm_spawn [S: MPI-2 ch.5].

Parents collectively spawn a NEW world of child rank processes and get an
:class:`~mpi_tpu.intercomm.InterComm` to it; children find their side with
:func:`comm_get_parent`.  The classic master/worker elasticity primitive:
a running job grows itself without restarting the launcher.

Wiring (all file-rendezvous TCP, like the launcher's worlds):

* the CHILD WORLD is an ordinary socket world of ``maxprocs`` ranks over a
  fresh rendezvous dir — children just call ``mpi_tpu.init()`` (or touch
  ``COMM_WORLD``) exactly like launcher-started programs;
* the PARENT-CHILD BRIDGE is a second socket transport over its own
  rendezvous dir spanning P parents + C children: parents take bridge
  ranks 0..P-1 (their ``comm`` rank order), children P..P+C-1.  Rank
  discovery is lazy (port files + polling), so parents can build their
  bridge endpoint before any child has started.

The spawning communicator can be any process-backend comm (world or a
split subset) — the bridge binds to ITS members.  SPMD communicators
cannot spawn OS processes; the diagnostic points to the launcher.
"""

from __future__ import annotations

import atexit
import os
import shutil
import subprocess
import sys
import tempfile
from typing import Any, List, Optional, Sequence, Tuple

from .communicator import Communicator, P2PCommunicator
from .intercomm import InterComm

ENV_PARENT_RDV = "MPI_TPU_PARENT_RDV"
ENV_PARENT_SIZE = "MPI_TPU_PARENT_SIZE"
ENV_PARENT_TOTAL = "MPI_TPU_PARENT_TOTAL"

# Popen handles of everything this process spawned: children are
# independent jobs (MPI semantics: spawn does not wait), but keeping the
# handles lets atexit reap finished ones instead of leaving zombies.
_spawned: List[subprocess.Popen] = []
_bridge_dirs: List[str] = []
_child_dirs: List[str] = []
_parent_intercomm: Optional[InterComm] = None


def _cleanup() -> None:  # pragma: no cover - exit path
    alive = False
    for p in _spawned:
        if p.poll() is None:
            alive = True
    # the bridge dies with this (parent) process either way — its rdv
    # dir is safe to remove.  The CHILD WORLD's rdv dir is NOT ours to
    # delete while children still run: spawn does not wait, and late
    # child ranks discover each other lazily through those port files
    # (ADVICE r3 #3 — a prompt parent exit would break their wiring).
    # Reap it only once every spawned child has exited; otherwise leave
    # it to the OS tempdir lifecycle.
    for d in _bridge_dirs:
        shutil.rmtree(d, ignore_errors=True)
    if not alive:
        for d in _child_dirs:
            shutil.rmtree(d, ignore_errors=True)


atexit.register(_cleanup)


def _bridge_comm(bridge_rank: int, total: int, rdv: str) -> P2PCommunicator:
    from .transport.socket import SocketTransport

    t = SocketTransport(bridge_rank, total, rdv)
    comm = P2PCommunicator(t, range(total))
    comm._owns_transport = True  # intercomm.free() closes the bridge socket
    return comm


def comm_spawn(argv: Sequence[str], maxprocs: int,
               comm: Optional[Communicator] = None, root: int = 0,
               env_extra: Optional[dict] = None,
               info: Optional[dict] = None) -> InterComm:
    """MPI_Comm_spawn: start ``maxprocs`` ranks of ``python argv...`` as a
    new world; returns the parent side of the parent-child intercomm.
    Collective over ``comm`` (default: this process's world); only
    ``root`` actually forks the children."""
    del info  # MPI_Info hints: accepted, advisory no-ops
    segments = [(list(argv), int(maxprocs))]
    return _spawn_segments(segments, comm, root, env_extra)


def comm_spawn_multiple(segments: Sequence[Tuple[Sequence[str], int]],
                        comm: Optional[Communicator] = None, root: int = 0,
                        env_extra: Optional[dict] = None) -> InterComm:
    """MPI_Comm_spawn_multiple: one child WORLD running different
    executables — ``segments`` is [(argv, maxprocs), ...]; child ranks are
    assigned segment by segment, in order [S]."""
    segs = [(list(a), int(n)) for a, n in segments]
    return _spawn_segments(segs, comm, root, env_extra)


def _spawn_segments(segments: List[Tuple[List[str], int]],
                    comm: Optional[Communicator], root: int,
                    env_extra: Optional[dict]) -> InterComm:
    if comm is None:
        from . import init

        comm = init()
    if not isinstance(comm, P2PCommunicator):
        raise NotImplementedError(
            "comm_spawn forks OS processes — a process-backend feature; "
            "an SPMD program's world is a device mesh, not a process pool "
            "(start more ranks with mpi_tpu.launcher instead)")
    nchildren = sum(n for _, n in segments)
    if nchildren < 1:
        raise ValueError("maxprocs must total >= 1")
    p = comm.size
    total = p + nchildren
    # root makes the rendezvous dirs; everyone learns them collectively
    if comm.rank == root:
        bridge_rdv = tempfile.mkdtemp(prefix="mpi_tpu_spawn_bridge_")
        child_rdv = tempfile.mkdtemp(prefix="mpi_tpu_spawn_world_")
        _bridge_dirs.append(bridge_rdv)
        _child_dirs.append(child_rdv)
        dirs = (bridge_rdv, child_rdv)
    else:
        dirs = None
    bridge_rdv, child_rdv = comm.bcast(dirs, root)
    # every parent opens its bridge endpoint BEFORE children are forked:
    # port files are published immediately, connections form lazily
    union = _bridge_comm(comm.rank, total, bridge_rdv)
    if comm.rank == root:
        from .launcher import ENV_BACKEND, ENV_RANK, ENV_RDV, ENV_SIZE

        child_rank = 0
        for argv, n in segments:
            for _ in range(n):
                from .launcher import cpu_pinned_env

                env = dict(os.environ)
                # same CPU pinning as the launcher (shared helper)
                cpu_pinned_env(
                    env, (env_extra or {}).get("MPI_TPU_RANK_JAX_PLATFORMS"))
                env.update({
                    ENV_RANK: str(child_rank),
                    ENV_SIZE: str(nchildren),
                    ENV_RDV: child_rdv,
                    ENV_BACKEND: "socket",
                    ENV_PARENT_RDV: bridge_rdv,
                    ENV_PARENT_SIZE: str(p),
                    ENV_PARENT_TOTAL: str(total),
                })
                if env_extra:
                    env.update(env_extra)
                _spawned.append(
                    subprocess.Popen([sys.executable, *argv], env=env))
                child_rank += 1
    return InterComm(union, list(range(p)), list(range(p, total)))


def comm_get_parent() -> Optional[InterComm]:
    """MPI_Comm_get_parent: in a spawned child, the intercomm to the
    spawning parents (cached); None in a world that was not spawned."""
    global _parent_intercomm
    if _parent_intercomm is not None:
        return _parent_intercomm
    rdv = os.environ.get(ENV_PARENT_RDV)
    if rdv is None:
        return None
    from . import init

    world = init()  # my child world: rank/size from the launcher-style env
    psize = int(os.environ[ENV_PARENT_SIZE])
    total = int(os.environ[ENV_PARENT_TOTAL])
    union = _bridge_comm(psize + world.rank, total, rdv)
    _parent_intercomm = InterComm(union, list(range(psize, total)),
                                  list(range(psize)))
    return _parent_intercomm


# -- establishing communication between independent jobs --------------------
# (MPI-2 ch.5.4: MPI_Open_port / MPI_Comm_accept / MPI_Comm_connect [S])
#
# Protocol (port dir = a mailbox of handshake files; every round gets its
# OWN fresh bridge rendezvous so ports are reusable and close_port after
# establishment cannot break lazy peer discovery — round-3 review):
#
#   connect root:  writes  connect.<uuid>.json  {size: B, reply_dir: D}
#                  (D is a CLIENT-owned tempdir — the reply must not live
#                  in the port dir, where a server's close_port right
#                  after accept() returns could delete it before the
#                  client reads it)
#   accept root:   CLAIMS one request by atomic rename to claimed.<uuid>,
#                  makes a fresh bridge rdv dir, writes D/accept.json
#                  {size: A, rdv: <bridge dir>}
#   connect root:  polls D/accept.json, then cleans D up itself
#
# Both sides then build the bridge world (acceptors 0..A-1, connectors
# A..A+B-1) over the per-round rdv.  Concurrent clients queue naturally
# (one claim per accept call); meta files are consumed by the rename.


def open_port() -> str:
    """MPI_Open_port: a name another, independently started job can
    connect to.  Spelled as a rendezvous directory (the same file-based
    discovery the transports use); pass it out of band (argv, env, a
    file) like an MPI port string.  NOT auto-deleted: the port must
    outlive its creator until :func:`close_port`."""
    return tempfile.mkdtemp(prefix="mpi_tpu_port_")


def close_port(port_name: str) -> None:
    """MPI_Close_port.  Safe after accept/connect returned: each round's
    bridge uses its own rendezvous dir, not the port dir."""
    shutil.rmtree(port_name, ignore_errors=True)


def _publish(path: str, payload: dict) -> None:
    import json

    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)  # atomic publish


def _poll_for(fn, timeout: float, what: str):
    import time

    deadline = time.monotonic() + timeout
    while True:
        got = fn()
        if got is not None:
            return got
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"no peer {what} within {timeout}s (is the other side "
                f"running?)")
        time.sleep(0.02)


def _root_exchange(comm, root: int, fn):
    """Run ``fn`` at root, broadcast (outcome, value) so a root failure
    raises on EVERY rank instead of deadlocking peers in the bcast (the
    io.py collective-open pattern)."""
    if comm.rank == root:
        try:
            result = ("ok", fn())
        except Exception as e:  # noqa: BLE001 - re-raised everywhere below
            result = ("err", f"{type(e).__name__}: {e}")
    else:
        result = None
    kind, value = comm.bcast(result, root)
    if kind == "err":
        raise TimeoutError(f"port handshake failed at root: {value}")
    return value


def comm_accept(port_name: str, comm: Optional[Communicator] = None,
                root: int = 0, timeout: float = 120.0) -> InterComm:
    """MPI_Comm_accept: collective over the server job's ``comm``; blocks
    until a client job calls :func:`comm_connect` on the same port, then
    returns the intercommunicator (clients are the remote group).
    Reusable: call it again on the same port for the next client."""
    comm = _require_process_comm(comm, "comm_accept")

    def handshake():
        import json

        def try_claim():
            for name in sorted(os.listdir(port_name)):
                if name.startswith("connect.") and name.endswith(".json"):
                    token = name[len("connect."):-len(".json")]
                    claimed = os.path.join(port_name, f"claimed.{token}")
                    try:
                        os.rename(os.path.join(port_name, name), claimed)
                    except OSError:
                        continue  # another round won the race
                    with open(claimed) as f:
                        meta = json.load(f)
                    os.unlink(claimed)
                    # publish INSIDE the claim step: a timed-out client's
                    # stale request (its reply_dir already deleted) must
                    # be skipped, not poison the port for live clients
                    rdv = tempfile.mkdtemp(prefix="mpi_tpu_bridge_")
                    try:
                        _publish(os.path.join(meta["reply_dir"],
                                              "accept.json"),
                                 {"size": comm.size, "rdv": rdv})
                    except OSError:
                        shutil.rmtree(rdv, ignore_errors=True)
                        continue  # dead requester; keep scanning
                    # an accept/connect bridge: both sides are live jobs,
                    # and this (server) process exiting kills the bridge
                    # anyway — safe to reap unconditionally at exit
                    _bridge_dirs.append(rdv)
                    return int(meta["size"]), rdv
            return None

        return _poll_for(try_claim, timeout,
                         f"connected to port {port_name!r}")

    remote, rdv = _root_exchange(comm, root, handshake)
    total = comm.size + remote
    union = _bridge_comm(comm.rank, total, rdv)
    return InterComm(union, list(range(comm.size)),
                     list(range(comm.size, total)))


def comm_connect(port_name: str, comm: Optional[Communicator] = None,
                 root: int = 0, timeout: float = 120.0) -> InterComm:
    """MPI_Comm_connect: the client side of :func:`comm_accept`."""
    comm = _require_process_comm(comm, "comm_connect")

    def handshake():
        import json
        import uuid

        token = uuid.uuid4().hex
        reply_dir = tempfile.mkdtemp(prefix="mpi_tpu_reply_")
        _publish(os.path.join(port_name, f"connect.{token}.json"),
                 {"size": comm.size, "reply_dir": reply_dir})
        reply = os.path.join(reply_dir, "accept.json")

        def read_reply():
            try:
                with open(reply) as f:
                    meta = json.load(f)
                return int(meta["size"]), meta["rdv"]
            except (OSError, ValueError, KeyError):
                return None

        try:
            accept_size, rdv = _poll_for(read_reply, timeout,
                                         f"accepted at port {port_name!r}")
        finally:
            shutil.rmtree(reply_dir, ignore_errors=True)
        return accept_size, rdv

    accept_size, rdv = _root_exchange(comm, root, handshake)
    total = accept_size + comm.size
    union = _bridge_comm(accept_size + comm.rank, total, rdv)
    return InterComm(union, list(range(accept_size, total)),
                     list(range(accept_size)))


def _require_process_comm(comm, what: str) -> P2PCommunicator:
    if comm is None:
        from . import init

        comm = init()
    if not isinstance(comm, P2PCommunicator):
        raise NotImplementedError(
            f"{what} is a process-backend feature (it binds OS sockets); "
            "SPMD worlds cannot establish socket connections")
    return comm


# -- name service (MPI_Publish_name / MPI_Lookup_name [S: MPI-2 ch.5.4.4]) --
# A registry directory maps service names to port strings.  Default:
# a fixed per-user dir under the system tempdir; override with
# MPI_TPU_NAMESERVICE for cluster-shared filesystems.

ENV_NAMESERVICE = "MPI_TPU_NAMESERVICE"


def _name_dir() -> str:
    d = os.environ.get(ENV_NAMESERVICE)
    if d is None:
        d = os.path.join(tempfile.gettempdir(),
                         f"mpi_tpu_names_{os.getuid()}")
    import stat as _stat

    os.makedirs(d, mode=0o700, exist_ok=True)
    # the ssh-agent pattern: a pre-existing dir (or SYMLINK — lstat, not
    # stat, or a planted link re-targets the registry into a victim-owned
    # directory) another user created could spoof published ports —
    # require a real directory we own with no group/other write
    st = os.lstat(d)
    if not _stat.S_ISDIR(st.st_mode) or st.st_uid != os.getuid() \
            or (st.st_mode & 0o022):
        raise PermissionError(
            f"name-service registry {d!r} is not a directory owned by "
            f"uid {os.getuid()} with mode 0700 — refusing (set "
            f"{ENV_NAMESERVICE} to a trusted directory)")
    return d


def _name_path(service_name: str) -> str:
    if "/" in service_name or service_name.startswith("."):
        raise ValueError(f"service names must be plain tokens, got "
                         f"{service_name!r}")
    return os.path.join(_name_dir(), service_name)


def publish_name(service_name: str, port_name: str) -> None:
    """MPI_Publish_name: make ``port_name`` discoverable as
    ``service_name`` (atomic; re-publishing overwrites)."""
    path = _name_path(service_name)
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(port_name)
    os.replace(tmp, path)


def unpublish_name(service_name: str) -> None:
    """MPI_Unpublish_name."""
    try:
        os.unlink(_name_path(service_name))
    except FileNotFoundError:
        pass


def lookup_name(service_name: str, timeout: float = 0.0) -> str:
    """MPI_Lookup_name: the port published under ``service_name``.
    ``timeout > 0`` waits for the service to appear (the usual
    client-starts-first race)."""
    path = _name_path(service_name)

    def read():
        try:
            with open(path) as f:
                return f.read().strip() or None
        except OSError:
            return None

    if timeout <= 0:
        got = read()
        if got is None:
            raise LookupError(f"no service published under "
                              f"{service_name!r} (registry: {_name_dir()})")
        return got
    return _poll_for(read, timeout, f"published service {service_name!r}")
