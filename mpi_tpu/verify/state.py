"""Verifier state: pending-op boards, per-rank world state, lint registry.

The runtime verifier (MUST-style, SURVEY.md §5) needs one out-of-band
channel: when a rank has been blocked past ``verify_stall_timeout_s`` it
publishes WHAT it is blocked in (source set, AND/OR semantics, tag,
collective, call site) and reads every peer's published entry, so the
wait-for-graph analysis (mpi_tpu/checker.find_deadlock) can run on the
full cross-rank picture without any rank being able to answer a message.
Two substrates behind one Board interface, mirroring ft.py's liveness
split:

* :class:`MemoryBoard` — a shared in-process table for the local thread
  world (``run_local(..., verify=True)`` creates one per world).
* :class:`FileBoard` — ``pending.<rank>`` JSON files under the launcher
  rendezvous dir for process worlds (socket/shm; ``MPI_TPU_VERIFY=1``).

Everything else here is rank-local bookkeeping: the live-request set
(leak / double-wait lints), live nonblocking buffer ranges (the
message-race overlap lint), created-communicator registry (unfreed-comm
lint), and the process-wide diagnostic report the finalize check and
``take_report()`` drain.
"""

from __future__ import annotations

import json
import os
import threading
import weakref
from typing import Dict, List, Optional, Tuple

from .. import mpit as _mpit

# Default stall bound before a blocked wait publishes its pending op and
# starts running deadlock analysis.  mpit cvar: verify_stall_timeout_s.
_STALL_TIMEOUT_S = 5.0

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# -- the process-wide diagnostic report --------------------------------------

_report_lock = threading.Lock()
_REPORT: List[str] = []
_WORLDS: "weakref.WeakSet" = weakref.WeakSet()


def report_add(msg: str) -> None:
    with _report_lock:
        _REPORT.append(msg)


def take_report() -> List[str]:
    """Drain and return every diagnostic the verifier has recorded in
    this process (lints are REPORTED, not raised — MUST-style; deadlock
    and collective mismatch raise in addition to reporting)."""
    with _report_lock:
        out, _REPORT[:] = list(_REPORT), []
    return out


def peek_report() -> List[str]:
    with _report_lock:
        return list(_REPORT)


def user_site(skip_dir: str = _PKG_DIR) -> str:
    """file:lineno of the nearest caller OUTSIDE mpi_tpu — the call site
    every diagnostic names.  Only ever invoked with the verifier on."""
    import sys

    try:
        f = sys._getframe(1)
    except ValueError:  # pragma: no cover - no frames
        return "<unknown>"
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(skip_dir):
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "<mpi_tpu internals>"


# -- out-of-band pending-op boards -------------------------------------------


class MemoryBoard:
    """Shared pending-op table for one in-process world (thread ranks).

    ``read_all`` attaches each entry's age since publish (``_age_s``):
    a genuinely stalled rank refreshes its entry every analysis slice,
    so the deadlock analysis EXPIRES un-refreshed 'blocked' entries —
    the last-resort guard against a stale entry left behind by a rank
    that died mid-stall (ended waits retract their entries promptly)."""

    def __init__(self, size: int) -> None:
        self._entries: List[Optional[Tuple[float, dict]]] = [None] * size
        self._lock = threading.Lock()

    def publish(self, rank: int, entry: Optional[dict]) -> None:
        import time

        with self._lock:
            self._entries[rank] = (None if entry is None
                                   else (time.monotonic(), entry))

    def read_all(self) -> Dict[int, dict]:
        import time

        now = time.monotonic()
        with self._lock:
            out = {}
            for r, slot in enumerate(self._entries):
                if slot is None:
                    continue
                at, e = slot
                d = dict(e)
                d["_age_s"] = now - at
                out[r] = d
            return out


class FileBoard:
    """``pending.<rank>`` JSON files under the rendezvous dir.  Writes
    are atomic (tmp + rename) so a reader never sees a torn entry; a
    missing/corrupt file reads as 'no entry' (= running), which the
    analysis treats as able-to-progress — crash-safe in the direction
    that never false-positives.

    Scaling (the PR-5 FileBoard residual): a naive ``read_all`` is O(P)
    file read+parse per check slice, which at O(100) ranks puts real
    I/O on every stalled wait's 0.25s cadence.  Readers therefore keep
    a compacted ``pending.summary.json`` beside the per-rank files:
    ``read_all`` stats each per-rank file (cheap) and re-reads ONLY the
    ones whose ``(mtime_ns, size)`` identity moved past the summary's
    record — AND any file touched within the last ``_MTIME_TRUST_S``,
    because on a coarse-mtime filesystem two distinct publishes inside
    one mtime tick with equal sizes would alias, and serving the stale
    stamps could CONFIRM a false deadlock (the one direction this board
    must never err).  A genuinely stalled rank republishes every check
    slice, so 'recently touched' ≈ 'the blocked ranks': the compaction
    still saves the parses for the quiet majority.  Each ``publish``
    stamps a per-rank monotonic ``_seq`` into the entry as forensic
    ordering evidence.  A stale or corrupt summary only costs fallback
    reads — correctness never depends on it.

    Compaction is SERIALIZED behind ``pending.summary.lock`` (atomic
    ``O_EXCL`` create; stale locks from a reader that died mid-
    compaction are taken over past ``_LOCK_STALE_S``): the summary used
    to be last-writer-wins, so N concurrently-stalled readers would
    each redo the same fallback reads and overwrite each other's
    compactions.  Now exactly one reader compacts at a time; a reader
    that loses the lock race RELOADS the holder's freshly-written
    summary instead of re-parsing unchanged files, performs only the
    fallback reads correctness still requires (its dirtiness is
    remembered and flushed under the lock on a later slice), and never
    writes.  Lock unavailability can only ever cost duplicate reads —
    never a wrong entry."""

    SUMMARY = "pending.summary.json"
    LOCK = "pending.summary.lock"
    # a compaction lock untouched this long belongs to a dead reader
    # (a live one holds it for one json dump); take it over
    _LOCK_STALE_S = 5.0
    # Cache-trust horizon: a file whose mtime is younger than this is
    # always re-read (coarse-mtime aliasing guard, see class docstring).
    # Must STRICTLY exceed the worst plausible mtime granularity (1-2s
    # on ext3/NFS/FAT-class filesystems): mtimes floor DOWN, so a file
    # can look up to one granule older than its newest write — only an
    # apparent age past granularity + margin proves its mtime granule
    # is really over and no same-identity rewrite can still be hiding.
    _MTIME_TRUST_S = 2.5

    def __init__(self, rdv_dir: str, rank: int, size: int) -> None:
        self._rdv = rdv_dir
        self._rank = rank
        self._size = size
        self._seq = 0
        # summary cache: rank(str) -> {"id": [mtime_ns, size, seq],
        # "entry": {...}}; loaded lazily from SUMMARY, refreshed on use
        self._cache: Dict[str, dict] = {}
        self._cache_loaded = False
        self._dirty = False  # cache moved past the on-disk summary
        self.fallback_reads = 0   # test/tool introspection
        self.summary_writes = 0   # compactions this reader performed
        self.lock_takeovers = 0   # stale locks reclaimed

    def _path(self, rank: int) -> str:
        return os.path.join(self._rdv, f"pending.{rank}")

    def publish(self, rank: int, entry: Optional[dict]) -> None:
        path = self._path(rank)
        try:
            if entry is None:
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass
                return
            self._seq += 1
            entry = dict(entry)
            entry["_seq"] = self._seq
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(entry, f)
            os.replace(tmp, path)
        except OSError:
            pass  # rendezvous dir tearing down — world is exiting

    def _load_summary(self, force: bool = False) -> None:
        if self._cache_loaded and not force:
            return
        self._cache_loaded = True
        try:
            with open(os.path.join(self._rdv, self.SUMMARY)) as f:
                data = json.load(f)
            if isinstance(data, dict):
                loaded = {
                    r: rec for r, rec in data.items()
                    if isinstance(rec, dict) and "id" in rec
                    and "entry" in rec}
                if force:
                    # adopting a CONCURRENT compactor's summary: merge —
                    # keep whichever record is newer per rank (ours may
                    # hold a fallback read the holder hasn't seen)
                    for r, rec in loaded.items():
                        mine = self._cache.get(r)
                        if mine is None or mine["id"][:2] < rec["id"][:2]:
                            self._cache[r] = rec
                else:
                    self._cache = loaded
        except (OSError, ValueError):
            if not force:
                self._cache = {}  # absent/corrupt summary = just fall back

    # -- compaction lock ---------------------------------------------------

    def _lock_path(self) -> str:
        return os.path.join(self._rdv, self.LOCK)

    def _try_lock(self) -> bool:
        """One non-blocking attempt on the compaction lock, with
        stale-lock takeover: unlink a lock whose mtime is past
        _LOCK_STALE_S and retry the O_EXCL create ONCE — two racing
        takeovers both unlink (idempotent) and the create arbitrates."""
        path = self._lock_path()
        for attempt in (0, 1):
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                             0o600)
            except FileExistsError:
                if attempt:
                    return False
                import time

                try:
                    if time.time() - os.stat(path).st_mtime \
                            < self._LOCK_STALE_S:
                        return False
                    os.unlink(path)
                    self.lock_takeovers += 1
                except OSError:
                    return False  # vanished/unreadable: holder is live
                continue
            except OSError:
                return False  # rendezvous dir tearing down
            with os.fdopen(fd, "w") as f:
                f.write(f"{os.getpid()}.{self._rank}")
            return True
        return False  # pragma: no cover - loop always returns

    def _unlock(self) -> None:
        path = self._lock_path()
        try:
            # ownership check: if WE were descheduled past the stale
            # bound mid-compaction, another reader legitimately took
            # the lock over — unlinking ITS lock would re-enable the
            # concurrent-writer races this lock exists to prevent.
            # (A check-then-unlink window remains; it requires TWO
            # takeovers inside one scheduling gap — accepted.)
            with open(path) as f:
                if f.read() != f"{os.getpid()}.{self._rank}":
                    return
            os.unlink(path)
        except OSError:
            pass

    def _read_entry(self, path: str) -> Optional[dict]:
        self.fallback_reads += 1
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None  # mid-replace / torn dir: treat as no entry

    def _cache_hit(self, r: int, st, now: float) -> Optional[dict]:
        """The summary record for rank ``r`` iff it is trustworthy:
        identity unchanged AND outside the mtime-aliasing horizon."""
        rec = self._cache.get(str(r))
        if (rec is not None
                and rec["id"][:2] == [st.st_mtime_ns, st.st_size]
                and now - st.st_mtime_ns / 1e9 >= self._MTIME_TRUST_S):
            return dict(rec["entry"])
        return None

    def _scan_pending(self) -> Dict[int, os.stat_result]:
        """One ``os.scandir`` pass over the rendezvous dir → rank ->
        stat of its ``pending.<rank>`` file.  The per-rank ``os.stat``
        loop this replaces cost O(P) path lookups per check slice —
        mostly ENOENT misses, because running ranks have NO pending
        file; one directory read finds exactly the files that exist
        (ISSUE 8 satellite / PR-5 FileBoard residual (d) tail).  The
        summary/lock/tmp siblings fail the integer-suffix test and are
        skipped; a file vanishing between scandir and DirEntry.stat
        reads as 'no entry', same as before."""
        found: Dict[int, os.stat_result] = {}
        try:
            with os.scandir(self._rdv) as it:
                for de in it:
                    suffix = de.name[8:] if de.name.startswith("pending.") \
                        else ""
                    if not suffix.isdigit():
                        continue
                    r = int(suffix)
                    if 0 <= r < self._size:
                        try:
                            found[r] = de.stat()
                        except OSError:
                            pass  # vanished mid-scan: no entry
        except OSError:
            pass  # rendezvous dir tearing down: everything reads absent
        return found

    def read_all(self) -> Dict[int, dict]:
        import time

        self._load_summary()
        now = time.time()
        out: Dict[int, dict] = {}
        stats = self._scan_pending()
        need: List[int] = []
        for r in range(self._size):
            st = stats.get(r)
            if st is None:
                if self._cache.pop(str(r), None) is not None:
                    self._dirty = True
                continue
            entry = self._cache_hit(r, st, now)
            if entry is not None:
                out[r] = entry
            else:
                need.append(r)
        locked = False
        if need or self._dirty:
            locked = self._try_lock()
            if not locked and need:
                # a concurrent reader is compacting: adopt whatever it
                # already wrote instead of redoing its fallback reads —
                # only ranks the fresh summary STILL cannot answer get
                # parsed here
                self._load_summary(force=True)
                still: List[int] = []
                for r in need:
                    entry = self._cache_hit(r, stats[r], now)
                    if entry is None:
                        still.append(r)
                    else:
                        out[r] = entry
                need = still
        try:
            for r in need:
                entry = self._read_entry(self._path(r))
                if entry is None:
                    continue
                st = stats[r]
                new_rec = {
                    "id": [st.st_mtime_ns, st.st_size,
                           entry.get("_seq", 0)],
                    "entry": entry}
                # recency re-reads of an UNCHANGED file must not churn
                # the summary — only a moved identity rewrites it
                if self._cache.get(str(r), {}).get("id") != new_rec["id"]:
                    self._dirty = True
                self._cache[str(r)] = new_rec
                out[r] = dict(entry)
            if locked and self._dirty:
                self._write_summary()
                self._dirty = False
        finally:
            if locked:
                self._unlock()
        for r, entry in out.items():
            # wall-clock mtime: the one cross-process-comparable
            # stamp (monotonic clocks don't compare across ranks)
            entry["_age_s"] = max(0.0, now - stats[r].st_mtime_ns / 1e9)
        return out

    def _write_summary(self) -> None:
        path = os.path.join(self._rdv, self.SUMMARY)
        tmp = f"{path}.tmp.{os.getpid()}.{self._rank}"
        try:
            with open(tmp, "w") as f:
                json.dump(self._cache, f)
            os.replace(tmp, path)
            self.summary_writes += 1
        except OSError:
            pass  # rendezvous dir tearing down — summary is best effort


# -- request / buffer lint bookkeeping ---------------------------------------


class VInfo:
    """Tracking record of one user-level nonblocking request."""

    __slots__ = ("kind", "rank", "peer", "tag", "site", "wait_count",
                 "world", "buf_key", "reported_leak", "__weakref__")

    def __init__(self, world: "WorldVerify", kind: str, rank: int, peer: int,
                 tag: int, site: str) -> None:
        self.world = world
        self.kind = kind
        self.rank = rank
        self.peer = peer
        self.tag = tag
        self.site = site
        self.wait_count = 0
        self.buf_key: Optional[int] = None
        self.reported_leak = False

    def describe(self) -> str:
        return (f"rank {self.rank}: {self.kind}(peer={self.peer}, "
                f"tag={self.tag}) at {self.site}")

    # called from Request._vnote (communicator.py) on every wait()/test()
    def note(self, completed: bool, blocking: bool) -> None:
        w = self.world
        if blocking:
            self.wait_count += 1
            if self.wait_count == 2 and self.kind != "persistent":
                _mpit.count(verify_double_waits=1)
                report_add(f"double-wait: second wait() on the same "
                           f"request — {self.describe()}")
        if completed:
            w.retire_request(self)


class WorldVerify:
    """Per-rank verifier state (one per transport, like ft.WorldFT):
    the shared board plus every rank-local registry the lints need."""

    def __init__(self, transport, board, stall_timeout_s: float) -> None:
        self.t = transport
        self.board = board
        self.stall_timeout_s = float(stall_timeout_s)
        self.rank = transport.world_rank
        self.size = transport.world_size
        self._lock = threading.Lock()
        self.ops = 0          # completed sends+recvs: the progress stamp
        self.block_id = 0     # increments at every blocking-wait entry
        # threads currently INSIDE a verified blocking wait: while any
        # exist, the rank's board entry belongs to them — the progress
        # engine's on-behalf-of-pollers publication stands down (two
        # publishers alternating entries would flap the stamps and the
        # confirm pass could never close)
        self.active_waiters = 0
        self.published = False
        self._last_check = 0.0
        self._live: set = set()          # VInfos not yet completed/waited
        # live nonblocking buffer ranges: key -> (start, end, writes, desc)
        self._bufs: Dict[int, Tuple[int, int, bool, str]] = {}
        self._buf_key = 0
        # (ctx-repr, site, kind) of comms created while verifying
        self.comms: Dict[int, Tuple[str, str, bool]] = {}
        self._comm_key = 0
        _WORLDS.add(self)

    # -- progress / board --------------------------------------------------

    def note_progress(self) -> None:
        self.ops += 1
        if self.published:
            self.published = False
            self.board.publish(self.rank, None)

    def clear_published(self) -> None:
        """Retract a published 'blocked' entry without claiming progress
        — the exit path of a stalled wait that raised (RecvTimeout,
        ProcFailedError, RevokedError): the rank is no longer in that
        wait, and a lingering entry could falsely implicate it in a
        peer's wait-for analysis until the TTL expires.  DeadlockError
        deliberately does NOT retract: peers confirming the same
        diagnosis need the entry stable."""
        if self.published:
            self.published = False
            self.board.publish(self.rank, None)

    def begin_block(self) -> int:
        self.block_id += 1
        return self.block_id

    def wait_enter(self) -> None:
        with self._lock:
            self.active_waiters += 1

    def wait_exit(self) -> None:
        with self._lock:
            self.active_waiters -= 1

    def mark_exited(self) -> None:
        """Published when the rank's program returns/finalizes: a peer
        blocked on this rank can then be diagnosed (wait-on-exited) the
        way MUST reports 'waiting for a terminated process'."""
        self.board.publish(self.rank, {"state": "exited", "rank": self.rank})

    # -- request lints -----------------------------------------------------

    def track_request(self, req, kind: str, rank: int, peer: int, tag: int,
                      site: str) -> VInfo:
        info = VInfo(self, kind, rank, peer, tag, site)
        req._vinfo = info
        with self._lock:
            self._live.add(info)
        # finalize objects keep themselves alive until the request dies
        weakref.finalize(req, _request_gc, info)
        return info

    def retire_request(self, info: VInfo) -> None:
        with self._lock:
            self._live.discard(info)
        self.release_buffer(info)

    # -- buffer overlap lint (the message-race case) -----------------------

    def buffer_live(self, arr, desc: str, writes: bool) -> Optional[int]:
        """Register a buffer as live under a pending nonblocking op;
        returns the release key.  Overlap with another live range where
        either side WRITES is the message race MUST flags."""
        try:
            start = int(arr.__array_interface__["data"][0])
            nbytes = int(arr.nbytes)
        except (AttributeError, KeyError, TypeError):
            return None  # not a buffer-backed payload: nothing to race on
        end = start + nbytes
        if writes:
            # buffer-ownership notification (mpi_tpu/bufpool.py,
            # ISSUE 11): a write-mode registration means a pending op
            # WILL mutate this region — a resilient link still
            # retaining it by reference must snapshot first (the same
            # interval-overlap rule as the race lint below)
            from .. import bufpool as _bufpool

            _bufpool.touch_ranges(((start, end),))
        with self._lock:
            for (s, e, w, d) in self._bufs.values():
                if s < end and start < e and (w or writes):
                    _mpit.count(verify_buffer_overlaps=1)
                    report_add(
                        f"overlapping live buffers across pending "
                        f"nonblocking ops (message race): {desc} overlaps "
                        f"{d} (bytes [{max(s, start)}, {min(e, end)}))")
                    break
            self._buf_key += 1
            self._bufs[self._buf_key] = (start, end, writes, desc)
            return self._buf_key

    def buffer_release(self, key: Optional[int]) -> None:
        if key is None:
            return
        with self._lock:
            self._bufs.pop(key, None)

    def track_buffer(self, info: VInfo, arr, desc: str, writes: bool) -> None:
        info.buf_key = self.buffer_live(arr, desc, writes)

    def release_buffer(self, info: VInfo) -> None:
        self.buffer_release(info.buf_key)
        info.buf_key = None

    # -- unfreed-communicator lint ----------------------------------------

    def track_comm(self, comm, how: str, site: str) -> int:
        with self._lock:
            self._comm_key += 1
            key = self._comm_key
            self.comms[key] = (repr(comm._ctx), site, how)
        return key

    def free_comm(self, key: int) -> None:
        with self._lock:
            self.comms.pop(key, None)

    # -- finalize sweep ----------------------------------------------------

    def finalize_sweep(self) -> None:
        """Fold every still-pending lint into the report: live requests
        never waited, communicators never freed.  Each finding is
        reported ONCE (the registries drain), so repeated sweeps — one
        per test, say — never re-report old findings."""
        with self._lock:
            live = list(self._live)
            self._live.clear()
            comms = list(self.comms.values())
            self.comms.clear()
        for info in live:
            if info.wait_count == 0 and not info.reported_leak:
                info.reported_leak = True
                _mpit.count(verify_requests_leaked=1)
                report_add(f"leaked request (never waited/tested): "
                           f"{info.describe()}")
        for ctx, site, how in comms:
            _mpit.count(verify_comms_unfreed=1)
            report_add(f"rank {self.rank}: communicator from {how}() at "
                       f"{site} (ctx={ctx}) never freed before finalize")


class CommVerify:
    """Per-communicator verifier state: the shared WorldVerify plus this
    communicator's collective sequence counter (the matching check's
    ordering evidence) and, for split/dup children, the unfreed-comm
    registry key."""

    __slots__ = ("world", "_seq", "_seq_lock", "comm_key")

    def __init__(self, world: WorldVerify) -> None:
        self.world = world
        self._seq = 0
        self._seq_lock = threading.Lock()
        self.comm_key: Optional[int] = None

    def next_seq(self) -> int:
        with self._seq_lock:
            self._seq += 1
            return self._seq


def _request_gc(info: VInfo) -> None:
    """weakref.finalize callback: the request object was garbage
    collected.  An unwaited request at GC is the leak MUST flags —
    isend/irecv whose completion nobody ever observed."""
    if info.wait_count == 0 and not info.reported_leak:
        info.reported_leak = True
        _mpit.count(verify_requests_leaked=1)
        report_add(f"leaked request (garbage-collected without wait/test): "
                   f"{info.describe()}")
    info.world.retire_request(info)


def finalize_report() -> List[str]:
    """Sweep every live verifier world's pending lints into the report,
    then drain it — the finalize-time report (called by
    ``mpi_tpu.finalize()``; tests call it directly)."""
    for world in list(_WORLDS):
        world.finalize_sweep()
    return take_report()
