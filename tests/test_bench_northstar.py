"""The north-star measurement path (bench.py NORTHSTAR_PROG) must run in
CPU-sim rehearsal mode on every box — a trivial bug in it must never wait
for hardware day to surface (VERDICT round 1, missing #1)."""

import json

import pytest

import bench


@pytest.mark.slow
def test_northstar_prog_runs_on_8dev_sim():
    # 4MB: big enough that each chunk has >=2 row-tiles, engaging the
    # bidirectional split (at tiny sizes it correctly degrades to one ring)
    out = bench._run_sub(
        bench.NORTHSTAR_PROG.format(repo=bench.REPO),
        {"NS_BYTES": str(4 << 20), "NS_ITERS": "2"},
        env_base=bench._cpu_env(8))
    r = json.loads(out)
    assert r["nranks"] == 8
    assert r["nbytes"] == 4 << 20
    assert r["ici_linerate_gbps_per_link"] > 0, r.get("linerate_error")
    for algo in ("ring", "fused", "pallas_ring", "pallas_ring_unidir"):
        assert isinstance(r.get(algo), dict), r.get(algo + "_error")
        assert r[algo]["busbw_gbps"] > 0
    assert "pct_of_linerate" in r["pallas_ring"]
    # the counter-rotating split really puts traffic on both directions
    fl = r["pallas_ring_flows"]
    assert fl["right_bytes_per_chunk"] > 0
    assert fl["left_bytes_per_chunk"] > 0
