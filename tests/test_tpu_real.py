"""Real-TPU test tier (SURVEY.md §4 item 3; VERDICT round 1 next-step #2).

Run with::

    MPI_TPU_TEST_TPU=1 python -m pytest -m tpu tests/test_tpu_real.py

(the env var stops conftest.py pinning the CPU platform).  Two families:

* **P=1 degenerate semantics** — every collective × algorithm executes on
  the single real chip and returns the mathematically-degenerate result.
* **AOT lowering for P=8** — the 8-device SPMD programs (every hand
  schedule AND the pipelined Pallas ring, ``interpret=False``) are traced
  and lowered against an 8-device AbstractMesh on the TPU backend.  This
  exercises the pallas→Mosaic lowering of the pipelined path — the code
  the interpreter tier never touches — without needing 8 chips.

Without a TPU attached every test here self-skips.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import AbstractMesh, Mesh, PartitionSpec as P

from mpi_tpu import ops
from mpi_tpu.tpu import TpuCommunicator

pytestmark = pytest.mark.tpu

_HAS_TPU = any(d.platform == "tpu" for d in jax.devices())
if not _HAS_TPU:
    pytestmark = [pytest.mark.tpu,
                  pytest.mark.skip(reason="no real TPU attached "
                                   "(run with MPI_TPU_TEST_TPU=1)")]


def _mesh1() -> Mesh:
    return Mesh(np.array(jax.devices()[:1]), ("world",))


def _run1(fn):
    """Run fn(comm, x) on the real chip, P=1."""
    mesh = _mesh1()
    comm = TpuCommunicator("world", mesh)
    x = jnp.arange(8.0, dtype=jnp.float32)
    f = jax.jit(jax.shard_map(lambda v: fn(comm, v), mesh=mesh,
                              in_specs=P(), out_specs=P("world")))
    return np.asarray(f(x)), np.arange(8.0, dtype=np.float32)


# ---- P=1 degenerate semantics on the real chip ---------------------------


@pytest.mark.parametrize("algorithm", ["fused", "ring", "recursive_halving",
                                       "reduce_bcast"])
def test_allreduce_degenerate(algorithm):
    got, x = _run1(lambda c, v: c.allreduce(v, algorithm=algorithm)[None])
    np.testing.assert_allclose(got[0], x)


@pytest.mark.parametrize("algorithm", ["fused", "tree"])
def test_bcast_reduce_degenerate(algorithm):
    got, x = _run1(lambda c, v: c.bcast(v, 0, algorithm)[None])
    np.testing.assert_allclose(got[0], x)
    got, x = _run1(lambda c, v: c.reduce(v, ops.MAX, 0, algorithm)[None])
    np.testing.assert_allclose(got[0], x)


@pytest.mark.parametrize("algorithm", ["fused", "ring", "doubling"])
def test_allgather_degenerate(algorithm):
    got, x = _run1(lambda c, v: c.allgather(v, algorithm=algorithm))
    np.testing.assert_allclose(got.reshape(-1), x)


@pytest.mark.parametrize("algorithm", ["fused", "pairwise"])
def test_alltoall_degenerate(algorithm):
    got, x = _run1(
        lambda c, v: c.alltoall(v.reshape(1, 8), algorithm=algorithm))
    np.testing.assert_allclose(got.reshape(-1), x)


def test_reduce_scatter_scan_degenerate():
    got, x = _run1(lambda c, v: c.reduce_scatter(v.reshape(1, 8))[None])
    np.testing.assert_allclose(got[0], x)
    got, x = _run1(lambda c, v: c.scan(v)[None])
    np.testing.assert_allclose(got[0], x)


def test_entry_compiles_on_chip():
    """The driver's single-chip compile check, as a test."""
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)


# ---- AOT lowering of the 8-device programs (1 chip is enough) ------------


def _lower8(fn, *avals, check_vma=True):
    """Trace + lower an 8-device shard_map program for the TPU backend."""
    amesh = AbstractMesh((8,), ("world",))
    comm = TpuCommunicator("world", amesh)
    f = jax.jit(jax.shard_map(lambda *a: fn(comm, *a), mesh=amesh,
                              in_specs=P("world"), out_specs=P("world"),
                              check_vma=check_vma))
    return f.lower(*avals)


@pytest.mark.parametrize("algorithm", ["fused", "ring", "recursive_halving"])
def test_allreduce8_lowers(algorithm):
    _lower8(lambda c, v: c.allreduce(v, algorithm=algorithm),
            jax.ShapeDtypeStruct((8, 1024), jnp.float32))


@pytest.mark.parametrize("algorithm", ["tree", "fused"])
def test_tree8_lowers(algorithm):
    _lower8(lambda c, v: c.bcast(v, 3, algorithm),
            jax.ShapeDtypeStruct((8, 256), jnp.float32))
    _lower8(lambda c, v: c.reduce(v, ops.SUM, 2, algorithm),
            jax.ShapeDtypeStruct((8, 256), jnp.float32))


@pytest.mark.parametrize("algorithm", ["pairwise", "fused"])
def test_alltoall8_lowers(algorithm):
    _lower8(lambda c, v: c.alltoall(v.reshape(8, 32), algorithm=algorithm),
            jax.ShapeDtypeStruct((8, 8 * 32), jnp.float32))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_pallas_ring8_lowers_pipelined(dtype):
    """THE coverage the interpreter tier cannot give: the pipelined
    (interpret=False) Pallas kernel — credits, wait_send hygiene, segment
    RDMAs — lowers through Mosaic for an 8-device ring."""
    from mpi_tpu.tpu import pallas_ring as pr
    from mpi_tpu.tpu.pallas_ring import pallas_ring_allreduce

    # per-rank 32768 elems → 256 rows → 4 tiles of 64 → 4 SEGMENTS, so the
    # per-(parity, seg) semaphore indexing and cross-segment credits all
    # go through Mosaic (a 1-segment shape would skip that machinery)
    n = 8 * 256 * 128
    rows = pr._geometry(n, 8, 64)[0]
    assert len(pr._segments(rows // 64)) == 4, "shape no longer multi-segment"
    for check_vma in (False, True):
        _lower8(lambda c, v: pallas_ring_allreduce(v.reshape(-1), "world", 8,
                                                   tile_rows=64),
                jax.ShapeDtypeStruct((8, n // 8), dtype),
                check_vma=check_vma)


def test_pallas_reduce_scatter8_lowers_pipelined():
    from mpi_tpu.tpu.pallas_ring import pallas_ring_reduce_scatter

    for check_vma in (False, True):
        _lower8(lambda c, v: pallas_ring_reduce_scatter(
                    v.reshape(8, 1024), "world", 8),
                jax.ShapeDtypeStruct((8, 8 * 1024), jnp.float32),
                check_vma=check_vma)


def test_dryrun_step8_lowers():
    """The driver's multichip dryrun program lowers for 8 TPU devices."""
    import __graft_entry__ as ge

    lowered = ge.lower_multichip(8)
    assert lowered is not None


def test_pallas_ring8_grouped_lowers_pipelined():
    """The grouped (split-communicator) pipelined kernel — SMEM neighbor
    params, per-group rings — lowers through Mosaic."""
    from mpi_tpu.tpu.pallas_ring import pallas_ring_allreduce

    groups = [[0, 2, 4, 6], [1, 3, 5, 7]]
    _lower8(lambda c, v: pallas_ring_allreduce(
                v.reshape(-1), "world", 4, tile_rows=64, groups=groups),
            jax.ShapeDtypeStruct((8, 64 * 128), jnp.float32),
            check_vma=False)


def test_pallas_ring8_max_lowers_pipelined():
    """The swapped-combiner (MAX) pipelined kernel lowers through Mosaic."""
    from mpi_tpu.tpu.pallas_ring import pallas_ring_allreduce

    _lower8(lambda c, v: pallas_ring_allreduce(
                v.reshape(-1), "world", 8, tile_rows=64, op="max"),
            jax.ShapeDtypeStruct((8, 64 * 128), jnp.float32),
            check_vma=False)


def test_pallas_allgather8_lowers_pipelined():
    """The allgather-only kernel mode (rs=False: zero RS steps, P-1
    land-direct steps) lowers through Mosaic for an 8-device ring."""
    from mpi_tpu.tpu.pallas_ring import pallas_ring_allgather

    for check_vma in (False, True):
        _lower8(lambda c, v: pallas_ring_allgather(
                    v.reshape(-1), "world", 8, tile_rows=64),
                jax.ShapeDtypeStruct((8, 64 * 128 * 4), jnp.float32),
                check_vma=check_vma)


@pytest.mark.parametrize("ring_axis", ["mp", "dp"])
def test_pallas_ring_multiaxis_lowers_on_tpu_backend(ring_axis):
    """Round 4 (VERDICT r3 missing #2): the multi-axis kernel —
    dict-MESH RDMA addressing over one axis of a 2-D (dp×mp) mesh —
    lowers through Mosaic ON THE TPU BACKEND (the CPU tier proves the
    same via cross-platform jax.export; this is the silicon-side twin)."""
    from mpi_tpu.tpu.pallas_ring import pallas_ring_allreduce

    amesh = AbstractMesh((2, 4), ("dp", "mp"))
    size = dict(zip(amesh.axis_names, amesh.axis_sizes))[ring_axis]
    f = jax.jit(jax.shard_map(
        lambda v: pallas_ring_allreduce(v, ring_axis, size, tile_rows=64),
        mesh=amesh, in_specs=P("dp", "mp"), out_specs=P("dp", "mp"),
        check_vma=False))
    f.lower(jax.ShapeDtypeStruct((16, 4 * 64 * 128), jnp.float32))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_pallas_attention8_lowers_pipelined(dtype):
    """The fused ring-attention kernel (round 4: K/V circulation with
    slot credits + in-kernel online-softmax folds) lowers through
    Mosaic for an 8-device ring on the TPU backend."""
    from mpi_tpu.tpu.pallas_attention import pallas_ring_attention

    amesh = AbstractMesh((8,), ("s",))
    for check_vma in (False, True):
        f = jax.jit(jax.shard_map(
            lambda q, k, v: pallas_ring_attention(q, k, v, "s", 8,
                                                  interpret=False),
            mesh=amesh, in_specs=(P("s"),) * 3, out_specs=P("s"),
            check_vma=check_vma))
        aval = jax.ShapeDtypeStruct((8 * 64, 128), dtype)
        f.lower(aval, aval, aval)


def test_pallas_attention_size1_executes_on_chip():
    """P=1 degenerate ring attention executes on the real chip and
    matches local attention."""
    from mpi_tpu.tpu.pallas_attention import pallas_ring_attention

    mesh = _mesh1()
    rng = np.random.RandomState(2)
    q = rng.randn(8, 128).astype(np.float32)
    f = jax.jit(jax.shard_map(
        lambda qb: pallas_ring_attention(qb, qb, qb, "world", 1),
        mesh=mesh, in_specs=P("world"), out_specs=P("world")))
    got = np.asarray(f(jnp.asarray(q)))
    s = (q @ q.T) / np.sqrt(128)
    p = np.exp(s - s.max(axis=1, keepdims=True))
    p /= p.sum(axis=1, keepdims=True)
    np.testing.assert_allclose(got, p @ q, rtol=2e-4, atol=2e-5)


def test_dryrun_step8_pallas_ring_lowers():
    """The multichip dryrun variant whose dp gradient ring runs the
    in-kernel RDMA pallas_ring lowers for 8 TPU devices — the VERDICT
    r3 done-criterion, on the real backend."""
    import __graft_entry__ as ge

    lowered = ge.lower_multichip(8, dp_algorithm="pallas_ring")
    assert lowered is not None
