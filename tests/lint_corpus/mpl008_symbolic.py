"""Seeded bug: the rank-dependent trip count flows through a local
(``n = comm.size - comm.rank``) before reaching the loop."""


def main(comm):
    n = comm.size - comm.rank
    for _ in range(n):
        comm.allreduce(1)
