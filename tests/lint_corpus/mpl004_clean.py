"""Near-miss twin: same alias shape, but the post-revoke operation is
guarded by a try/except recovery path."""


def recover(comm, x):
    c2 = comm
    c2.revoke()
    try:
        comm.allreduce(x)
    except Exception:
        pass
