"""Test fixture environment (SURVEY.md §4 item 2): force an 8-device virtual
CPU platform BEFORE jax initializes, so every SPMD/mesh test runs multi-device
on any machine.  CPU-backend tests don't touch jax and are unaffected.

Real-TPU tier (SURVEY.md §4 item 3): ``MPI_TPU_TEST_TPU=1 pytest -m tpu``
leaves the platform alone so tests/test_tpu_real.py runs on the actual
chip; without the env var those tests see the CPU platform and self-skip."""

import os

if not os.environ.get("MPI_TPU_TEST_TPU"):
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("JAX_ENABLE_X64", "0")

    # The axon site hook (this machine's TPU tunnel) force-registers its
    # platform via jax.config, overriding JAX_PLATFORMS — override it back
    # before any backend initializes so the suite runs on the 8 virtual CPU
    # devices.
    import jax

    jax.config.update("jax_platforms", "cpu")
