"""Semantic tests of the Communicator layer over the in-process thread
transport (SURVEY.md §4: collective results must match a single-process numpy
oracle; split/dup isolation; MPI matching semantics)."""

import numpy as np
import pytest

import mpi_tpu
from mpi_tpu import ANY_SOURCE, ANY_TAG, Status, ops
from mpi_tpu.transport.local import run_local

NRANKS = [1, 2, 3, 4, 5, 8]
POW2 = [1, 2, 4, 8]


# -- point to point --------------------------------------------------------


def test_send_recv_basic():
    def prog(comm):
        if comm.rank == 0:
            comm.send({"hello": [1, 2, 3]}, dest=1, tag=7)
            return None
        st = Status()
        obj = comm.recv(source=0, tag=7, status=st)
        assert st.source == 0 and st.tag == 7
        return obj

    res = run_local(prog, 2)
    assert res[1] == {"hello": [1, 2, 3]}


def test_fifo_ordering_and_tag_matching():
    def prog(comm):
        if comm.rank == 0:
            for i in range(5):
                comm.send(("a", i), dest=1, tag=1)
            comm.send("late-tag2", dest=1, tag=2)
            return None
        # out-of-order tag match first: tag=2 must skip queued tag=1 messages
        assert comm.recv(source=0, tag=2) == "late-tag2"
        got = [comm.recv(source=0, tag=1) for _ in range(5)]
        assert got == [("a", i) for i in range(5)]

    run_local(prog, 2)


def test_any_source_any_tag():
    def prog(comm):
        if comm.rank == 3:
            seen = set()
            for _ in range(3):
                st = Status()
                obj = comm.recv(source=ANY_SOURCE, tag=ANY_TAG, status=st)
                assert obj == ("from", st.source)
                seen.add(st.source)
            assert seen == {0, 1, 2}
            return None
        comm.send(("from", comm.rank), dest=3, tag=comm.rank + 10)

    run_local(prog, 4)


def test_sendrecv_ring_rotation():
    def prog(comm):
        right = (comm.rank + 1) % comm.size
        left = (comm.rank - 1) % comm.size
        return comm.sendrecv(comm.rank, dest=right, source=left)

    for n in [2, 3, 5]:
        res = run_local(prog, n)
        assert res == [(r - 1) % n for r in range(n)]


def test_shift_wrap_and_boundary():
    def prog(comm):
        wrapped = comm.shift(comm.rank, offset=1, wrap=True)
        bounded = comm.shift(comm.rank, offset=1, wrap=False, fill=-99)
        return wrapped, bounded

    res = run_local(prog, 4)
    assert [w for w, _ in res] == [3, 0, 1, 2]
    assert [b for _, b in res] == [-99, 0, 1, 2]


def test_negative_user_tag_rejected():
    def prog(comm):
        with pytest.raises(ValueError):
            comm.send(1, dest=0, tag=-5)

    run_local(prog, 1)


# -- collectives vs numpy oracle ------------------------------------------


@pytest.mark.parametrize("n", NRANKS)
def test_bcast(n):
    payload = {"w": np.arange(5), "k": "v"}

    def prog(comm):
        obj = payload if comm.rank == 2 % comm.size else None
        return comm.bcast(obj, root=2 % comm.size)

    for got in run_local(prog, n):
        assert got["k"] == "v"
        np.testing.assert_array_equal(got["w"], np.arange(5))


@pytest.mark.parametrize("n", NRANKS)
def test_reduce_sum(n):
    rng = np.random.RandomState(0)
    data = rng.randn(n, 7)

    def prog(comm):
        return comm.reduce(data[comm.rank], op=ops.SUM, root=0)

    res = run_local(prog, n)
    np.testing.assert_allclose(res[0], data.sum(axis=0), rtol=1e-12)
    assert all(r is None for r in res[1:])


@pytest.mark.parametrize("algo", ["ring", "recursive_halving", "reduce_bcast", "auto"])
@pytest.mark.parametrize("n", POW2)
def test_allreduce_algorithms(n, algo):
    rng = np.random.RandomState(1)
    data = rng.randn(n, 33)  # 33 not divisible by n: exercises uneven chunks

    def prog(comm):
        return comm.allreduce(data[comm.rank], op=ops.SUM, algorithm=algo)

    for got in run_local(prog, n):
        np.testing.assert_allclose(got, data.sum(axis=0), rtol=1e-10)


@pytest.mark.parametrize("n", [3, 5, 6])
def test_allreduce_ring_non_pow2(n):
    rng = np.random.RandomState(2)
    data = rng.randn(n, 17)

    def prog(comm):
        return comm.allreduce(data[comm.rank], op=ops.SUM, algorithm="ring")

    for got in run_local(prog, n):
        np.testing.assert_allclose(got, data.sum(axis=0), rtol=1e-10)


@pytest.mark.parametrize(
    "op,oracle",
    [
        (ops.SUM, lambda d: d.sum(0)),
        (ops.PROD, lambda d: d.prod(0)),
        (ops.MAX, lambda d: d.max(0)),
        (ops.MIN, lambda d: d.min(0)),
    ],
)
def test_allreduce_ops(op, oracle):
    rng = np.random.RandomState(3)
    data = rng.randn(4, 9)

    def prog(comm):
        return comm.allreduce(data[comm.rank], op=op)

    for got in run_local(prog, 4):
        np.testing.assert_allclose(got, oracle(data), rtol=1e-10)


def test_allreduce_logical_ops():
    data = np.array([[True, False, True], [True, True, False],
                     [True, False, False], [True, True, True]])

    def prog(comm):
        return (
            comm.allreduce(data[comm.rank], op=ops.LAND),
            comm.allreduce(data[comm.rank], op=ops.LOR),
        )

    for land, lor in run_local(prog, 4):
        np.testing.assert_array_equal(land, data.all(axis=0))
        np.testing.assert_array_equal(lor, data.any(axis=0))


def test_allreduce_scalar():
    def prog(comm):
        return comm.allreduce(comm.rank + 1, op=ops.SUM)

    res = run_local(prog, 4)
    assert all(r == 10 for r in res)
    assert all(np.ndim(r) == 0 for r in res)


@pytest.mark.parametrize("algo", ["ring", "doubling"])
@pytest.mark.parametrize("n", POW2)
def test_allgather(n, algo):
    def prog(comm):
        return comm.allgather(("rank", comm.rank), algorithm=algo)

    for got in run_local(prog, n):
        assert got == [("rank", r) for r in range(n)]


@pytest.mark.parametrize("n", [3, 5, 7])
def test_allgather_non_pow2(n):
    def prog(comm):
        return comm.allgather(comm.rank * 2, algorithm="ring")

    for got in run_local(prog, n):
        assert got == [r * 2 for r in range(n)]


@pytest.mark.parametrize("n", NRANKS)
def test_alltoall(n):
    def prog(comm):
        objs = [(comm.rank, dst) for dst in range(comm.size)]
        return comm.alltoall(objs)

    res = run_local(prog, n)
    for dst, got in enumerate(res):
        assert got == [(src, dst) for src in range(n)]


@pytest.mark.parametrize("n", [1, 2, 5, 8])
def test_barrier_completes(n):
    def prog(comm):
        for _ in range(3):
            comm.barrier()
        return True

    assert all(run_local(prog, n))


def test_scatter_gather():
    def prog(comm):
        mine = comm.scatter([f"item{d}" for d in range(comm.size)] if comm.rank == 1 else None,
                            root=1)
        assert mine == f"item{comm.rank}"
        return comm.gather(mine.upper(), root=2)

    res = run_local(prog, 4)
    assert res[2] == [f"ITEM{r}" for r in range(4)]
    assert res[0] is None and res[1] is None and res[3] is None


# -- split / dup -----------------------------------------------------------


def test_split_by_parity():
    def prog(comm):
        sub = comm.split(color=comm.rank % 2, key=comm.rank)
        total = sub.allreduce(comm.rank, op=ops.SUM)
        return sub.rank, sub.size, total

    res = run_local(prog, 6)
    for world_rank, (sub_rank, sub_size, total) in enumerate(res):
        assert sub_size == 3
        assert sub_rank == world_rank // 2
        assert total == (0 + 2 + 4 if world_rank % 2 == 0 else 1 + 3 + 5)


def test_split_key_reorders():
    def prog(comm):
        # reverse the ordering via key
        sub = comm.split(color=0, key=-comm.rank)
        return sub.rank

    res = run_local(prog, 4)
    assert res == [3, 2, 1, 0]


def test_split_color_none_opts_out():
    def prog(comm):
        sub = comm.split(color=None if comm.rank == 0 else 7)
        if comm.rank == 0:
            assert sub is None
            return None
        return sub.size

    res = run_local(prog, 4)
    assert res[1:] == [3, 3, 3]


def test_nested_split():
    def prog(comm):
        row = comm.split(color=comm.rank // 2, key=comm.rank)
        col = comm.split(color=comm.rank % 2, key=comm.rank)
        return (row.allreduce(comm.rank), col.allreduce(comm.rank))

    res = run_local(prog, 4)
    assert res == [(1, 2), (1, 4), (5, 2), (5, 4)]


def test_dup_isolates_message_space():
    def prog(comm):
        dup = comm.dup()
        if comm.rank == 0:
            comm.send("on-parent", dest=1, tag=0)
            dup.send("on-dup", dest=1, tag=0)
            return None
        # receive in the opposite order: contexts must keep them apart
        got_dup = dup.recv(source=0, tag=0)
        got_parent = comm.recv(source=0, tag=0)
        return got_parent, got_dup

    res = run_local(prog, 2)
    assert res[1] == ("on-parent", "on-dup")


def test_error_in_one_rank_propagates():
    def prog(comm):
        if comm.rank == 1:
            raise RuntimeError("boom")
        comm.recv(source=1)  # would deadlock without error propagation

    with pytest.raises(RuntimeError, match="rank 1 failed"):
        run_local(prog, 2)
