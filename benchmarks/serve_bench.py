#!/usr/bin/env python
"""World-churn benchmark: resident world server vs cold ``launch()``.

The resident server's whole thesis (ROADMAP direction #1) is that the
"many small worlds at high rate" workload should not pay fork + import
+ transport handshake per world.  This harness prices both paths on the
same box and the same job (a correctness-checked 2-rank allreduce):

* **cold** (``serve_pre.json``): each world is a full
  ``launcher.launch(2, script)`` — fork two interpreters, import
  numpy/mpi_tpu, rendezvous, run the allreduce, tear down.  The
  world-acquire latency IS the launch wall time.
* **serve** (``serve_post.json``): one warm pool, then
  ``acquire → run → release`` cycles; world-acquire latency is the
  acquire round-trip (a reservation in server memory), and worlds/sec
  counts completed cycles.

Output rows carry ``oversubscribed`` like every bench artifact (this
box runs pool + driver on 2 cores).  Acceptance (ISSUE 7): lease p99
acquire >= 10x faster than cold launch.

Usage::

    python benchmarks/serve_bench.py [--quick] [--backend socket|shm]
        [--out-pre PATH] [--out-post PATH]
    python bench.py --serve-bench [--quick]    # the CI spelling
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time
from typing import Dict, List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_COLD_SCRIPT = """
import sys
sys.path.insert(0, {repo!r})
import numpy as np
import mpi_tpu
comm = mpi_tpu.init()
out = comm.allreduce(np.full(256, comm.rank + 1.0, np.float32))
assert float(out[0]) == 3.0, out[0]
"""


def _pctl(xs: List[float], q: float) -> float:
    if not xs:
        return float("nan")
    ordered = sorted(xs)
    return ordered[min(len(ordered) - 1, int(q * len(ordered)))]


def _stats_ms(xs: List[float]) -> Dict:
    return {"n": len(xs),
            "p50_ms": round(statistics.median(xs) * 1e3, 3),
            "p99_ms": round(_pctl(xs, 0.99) * 1e3, 3),
            "min_ms": round(min(xs) * 1e3, 3),
            "max_ms": round(max(xs) * 1e3, 3)}


def cold_leg(nworlds: int, backend: str) -> Dict:
    from mpi_tpu import launcher

    script = os.path.join(tempfile.mkdtemp(prefix="serve_bench_"),
                          "world.py")
    with open(script, "w") as f:
        f.write(_COLD_SCRIPT.format(repo=REPO))
    times: List[float] = []
    for _ in range(nworlds):
        t0 = time.monotonic()
        rc = launcher.launch(2, [script], timeout=120.0, backend=backend)
        times.append(time.monotonic() - t0)
        assert rc == 0, f"cold world failed with exit code {rc}"
    return {"mode": "cold_launch", "nranks": 2,
            "worlds": nworlds,
            "worlds_per_s": round(nworlds / sum(times), 3),
            "acquire": _stats_ms(times),  # a cold acquire IS the launch
            "world_total": _stats_ms(times)}


def serve_leg(ncycles: int, backend: str) -> Dict:
    from mpi_tpu import serve

    acquire_s: List[float] = []
    cycle_s: List[float] = []
    with serve.WorldServer(pool_size=3, backend=backend,
                           detect_timeout_s=2.0) as srv:
        client = serve.connect(srv)
        t_pool0 = srv._workers  # pool brought up inside WorldServer.start
        for _ in range(ncycles):
            t0 = time.monotonic()
            lease = client.acquire(2, timeout=30.0)
            acquire_s.append(time.monotonic() - t0)
            got = lease.run(serve.job_allreduce, 256, timeout=30.0)
            assert got == 3.0, got
            lease.release()
            cycle_s.append(time.monotonic() - t0)
        stats = client.stats()
    assert len(t_pool0) == 3
    return {"mode": "resident_serve", "nranks": 2, "pool_size": 3,
            "worlds": ncycles,
            "worlds_per_s": round(ncycles / sum(cycle_s), 3),
            "acquire": _stats_ms(acquire_s),
            "world_total": _stats_ms(cycle_s),
            "server_stats": {k: stats[k] for k in
                             ("epoch", "leases_granted", "jobs_ok",
                              "jobs_failed", "heals_completed")}}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="smoke mode: few worlds, stdout only")
    ap.add_argument("--backend", choices=("socket", "shm"),
                    default="socket")
    ap.add_argument("--out-pre", default=None)
    ap.add_argument("--out-post", default=None)
    args = ap.parse_args(argv)
    nworlds = 3 if args.quick else 7
    ncycles = 25 if args.quick else 300
    common = {
        "backend": args.backend,
        "payload_f32": 256,
        # pool/world procs + the pytest/bench driver exceed this box's
        # cores: latency tails here carry scheduler noise
        "oversubscribed": 4 > (os.cpu_count() or 1),
        "cpu_count": os.cpu_count(),
    }
    pre = {**common, **cold_leg(nworlds, args.backend)}
    post = {**common, **serve_leg(ncycles, args.backend)}
    ratio = (pre["acquire"]["p99_ms"] / post["acquire"]["p99_ms"]
             if post["acquire"]["p99_ms"] else float("inf"))
    summary = {
        "pre": pre, "post": post,
        "cold_p99_over_lease_p99_acquire": round(ratio, 1),
        "acceptance_lease_10x_faster": ratio >= 10.0,
    }
    print(json.dumps(summary, indent=2))
    if not args.quick:
        for path, payload in ((args.out_pre, pre), (args.out_post, post)):
            if path:
                with open(path, "w") as f:
                    json.dump(payload, f, indent=2)
    return 0 if ratio >= 10.0 else 1


if __name__ == "__main__":
    sys.exit(main())
