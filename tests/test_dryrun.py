"""Regression tier for the driver's multichip entry points (VERDICT r2 #1).

Round 2's red real-TPU test was a grouped ``lax.psum`` of an axis-invariant
operand: jax 0.9's vma typing has NO grouped psum (``bind_psum_invariant``
raises ``NotImplementedError`` for any ``axis_index_groups``), and the CPU
sim never noticed because ``_fused_allreduce`` detoured grouped sums there —
so ``dryrun_multichip ok`` was CPU-only evidence.  These tests *lower* (not
just run) the same program on the CPU mesh, through the exact code path the
TPU toolchain compiles (the detour is gone: grouped fused SUM is now
``psum_scatter + all_gather`` on every platform,
mpi_tpu/tpu/communicator.py ``_grouped_psum``).
"""

import numpy as np
import pytest

from mpi_tpu.tpu import TpuCommunicator, default_mesh, run_spmd

import __graft_entry__ as ge


def test_lower_multichip_8():
    """The FULL dryrun step traces + lowers (AbstractMesh, 8 devices)."""
    lowered = ge.lower_multichip(8)
    text = lowered.as_text()
    assert "stablehlo" in text or "module" in text


def test_dryrun_runs():
    ge.dryrun_multichip(8)


def test_dryrun_step_pallas_ring_dp_parity():
    """The dryrun step with the dp ring on ``pallas_ring`` (VERDICT r3
    missing #2) executes on the concrete 2-D CPU mesh — via the loud
    ppermute fallback — and produces the SAME loss/weights as the
    default 'ring' variant (the two dp allreduces are the same
    reduction)."""
    import jax
    import jax.numpy as jnp
    import numpy as np_

    from jax.sharding import Mesh

    devs = np_.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dp", "mp"))
    rng = np_.random.RandomState(0)
    sx, sy, s1, s2 = ge._shapes(2, 4)
    args = [jnp.asarray(rng.randn(*s), jnp.float32) * (0.1 if i >= 2 else 1)
            for i, s in enumerate((sx, sy, s1, s2))]

    outs = {}
    for alg in ("ring", "pallas_ring"):
        step, in_specs, out_specs = ge._build_step(mesh, 2, 4,
                                                   dp_algorithm=alg)
        f = jax.jit(jax.shard_map(step, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs))
        if alg == "pallas_ring":
            with pytest.warns(RuntimeWarning, match="ppermute ring fallback"):
                outs[alg] = f(*args)
        else:
            outs[alg] = f(*args)
    for a, b in zip(outs["ring"], outs["pallas_ring"]):
        np_.testing.assert_allclose(np_.asarray(a), np_.asarray(b),
                                    rtol=1e-5, atol=1e-6)


def test_export_multichip_tpu_pallas_ring():
    """Cross-platform AOT (VERDICT r3 missing #1 + #2): the FULL dryrun
    step — dp gradient ring on the in-kernel RDMA ``pallas_ring``, 2-D
    (dp×mp) mesh, check_vma on — exports for the TPU target from this
    CPU host.  jax.export runs the entire TPU lowering pipeline
    including Mosaic, so this is machine-checkable evidence the
    multichip program (kernel included) compiles for silicon without a
    chip attached."""
    exp = ge.export_multichip_tpu(8)
    assert exp.platforms == ("tpu",)
    assert "tpu_custom_call" in exp.mlir_module()


@pytest.mark.parametrize("invariant", [True, False])
def test_grouped_fused_allreduce_of_any_vma(invariant):
    """Grouped fused SUM accepts both replicated and varying operands.

    The replicated case is the round-2 red test (loss replicated over 'mp'
    after a tp-allreduce, then grouped-allreduced on the split comm)."""
    mesh = default_mesh()
    world = TpuCommunicator("world", mesh)
    halves = world.split_by(lambda i: i // 4)

    def prog(comm, x):
        mine = x[comm.rank]
        v = comm.allreduce(mine, algorithm="fused") if invariant else mine
        return halves.allreduce(v, algorithm="fused")

    x = np.arange(8.0, dtype=np.float32)
    out = np.asarray(run_spmd(prog, x, mesh=mesh)).ravel()
    if invariant:
        # v = full-axis sum (replicated), then ×4 per half-group
        np.testing.assert_allclose(out, np.full(8, x.sum() * 4, np.float32))
    else:
        lo, hi = x[:4].sum(), x[4:].sum()
        np.testing.assert_allclose(out, [lo] * 4 + [hi] * 4)


def test_grouped_fused_bcast_and_replicate_lower():
    """bcast('fused') and replicate() on a split comm trace under
    check_vma=True (both previously emitted the unimplementable grouped
    psum)."""
    mesh = default_mesh()
    world = TpuCommunicator("world", mesh)
    halves = world.split_by(lambda i: i // 4)

    def prog(comm, x):
        mine = x[comm.rank]
        b = halves.bcast(mine, root=1, algorithm="fused")
        r = halves.replicate(halves.allreduce(mine, algorithm="ring"))
        return b + r

    x = np.arange(8.0, dtype=np.float32)
    out = np.asarray(run_spmd(prog, x, mesh=mesh)).ravel()
    lo, hi = x[:4].sum(), x[4:].sum()
    np.testing.assert_allclose(out, [x[1] + lo] * 4 + [x[5] + hi] * 4)


def test_grouped_psum_scalar_and_odd_shapes():
    """_grouped_psum pads non-multiples of the group size correctly."""
    mesh = default_mesh()
    world = TpuCommunicator("world", mesh)
    quarters = world.split_by(lambda i: i // 2)  # 4 groups of 2

    rng = np.random.RandomState(3)
    for shape in [(), (1,), (3,), (5, 3)]:
        def prog(comm, x):
            return quarters.allreduce(x[comm.rank], algorithm="fused")

        x = rng.randn(8, *shape).astype(np.float32)
        out = np.asarray(run_spmd(prog, x, mesh=mesh)).reshape((8,) + shape)
        for r in range(8):
            g0 = (r // 2) * 2
            np.testing.assert_allclose(out[r], x[g0] + x[g0 + 1],
                                       rtol=1e-5, atol=1e-6)


def test_unwedge_guard_flips_to_cpu_on_probe_timeout(monkeypatch):
    """A wedged device pool (probe subprocess timeout) must pin the live
    jax config to CPU instead of letting entry() hang the driver."""
    import subprocess

    import jax

    import __graft_entry__ as ge

    calls = {}

    def fake_run(*a, **k):
        calls["probed"] = True
        raise subprocess.TimeoutExpired(cmd=a[0], timeout=k.get("timeout"))

    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    monkeypatch.setattr(subprocess, "run", fake_run)
    old = jax.config.jax_platforms
    try:
        ge._unwedge_guard()
        assert calls.get("probed")
        import os

        assert os.environ["JAX_PLATFORMS"] == "cpu"
        assert "PALLAS_AXON_POOL_IPS" not in os.environ
        assert jax.config.jax_platforms == "cpu"
    finally:
        jax.config.update("jax_platforms", old)


def test_unwedge_guard_noop_on_cpu_env(monkeypatch):
    import subprocess

    import __graft_entry__ as ge

    def boom(*a, **k):
        raise AssertionError("probe must not run when cpu is requested")

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setattr(subprocess, "run", boom)
    ge._unwedge_guard()  # returns without probing
