"""MPI-IO (mpi_tpu/io.py): explicit offsets, views over datatype maps,
individual/shared pointers, two-phase collective writes."""

import os

import numpy as np
import pytest

import mpi_tpu
from mpi_tpu import datatypes as dt
from mpi_tpu import io as mio
from mpi_tpu.transport.local import run_local


def _self():
    return mpi_tpu.comm_self()


# -- independent explicit-offset I/O ----------------------------------------


def test_write_read_at_roundtrip(tmp_path):
    path = str(tmp_path / "a.bin")
    with mio.file_open(_self(), path, mio.MODE_CREATE | mio.MODE_RDWR) as f:
        f.set_view(etype=np.float64)
        data = np.arange(8.0)
        assert f.write_at(2, data) == 8
        out = f.read_at(2, 8)
        assert np.array_equal(out, data)
        assert f.get_size() == 10 * 8


def test_short_read_at_eof(tmp_path):
    path = str(tmp_path / "b.bin")
    with mio.file_open(_self(), path, mio.MODE_CREATE | mio.MODE_RDWR) as f:
        f.set_view(etype=np.int32)
        f.write_at(0, np.arange(4, dtype=np.int32))
        assert f.read_at(2, 10).size == 2  # short, not an error
        assert f.read_at(9, 5).size == 0


def test_individual_pointer_and_seek(tmp_path):
    path = str(tmp_path / "c.bin")
    with mio.file_open(_self(), path, mio.MODE_CREATE | mio.MODE_RDWR) as f:
        f.set_view(etype=np.int16)
        f.write(np.arange(5, dtype=np.int16))
        assert f.get_position() == 5
        f.seek(-2, mio.SEEK_CUR)
        assert np.array_equal(f.read(2), [3, 4])
        f.seek(0, mio.SEEK_END)
        assert f.get_position() == 5
        f.seek(1, mio.SEEK_SET)
        assert np.array_equal(f.read(1), [1])


def test_open_modes(tmp_path):
    path = str(tmp_path / "d.bin")
    with pytest.raises(OSError, match="does not exist"):
        mio.file_open(_self(), path, mio.MODE_RDONLY)
    f = mio.file_open(_self(), path, mio.MODE_CREATE | mio.MODE_WRONLY |
                      mio.MODE_DELETE_ON_CLOSE)
    f.write_at(0, np.zeros(4, np.uint8))
    f.close()
    assert not os.path.exists(path)  # DELETE_ON_CLOSE
    with pytest.raises(ValueError, match="amode"):
        mio.file_open(_self(), path, mio.MODE_CREATE)


def test_set_size_and_append(tmp_path):
    path = str(tmp_path / "e.bin")
    with mio.file_open(_self(), path, mio.MODE_CREATE | mio.MODE_RDWR) as f:
        f.set_size(16)
        assert f.get_size() == 16
    with mio.file_open(_self(), path, mio.MODE_RDWR | mio.MODE_APPEND) as f:
        assert f.get_position() == 16  # APPEND starts at EOF


# -- views (the datatype integration) ----------------------------------------


def test_strided_view_partitions_file(tmp_path):
    """Two ranks with complementary vector filetypes interleave records
    without overlap — the canonical MPI-IO view demo."""
    path = str(tmp_path / "view.bin")

    def prog(comm):
        ft = dt.type_vector(4, 1, 2, np.float64)  # every other element
        shifted = dt.Datatype(ft.base_dtype, ft.indices + comm.rank,
                              ft.extent)
        f = mio.file_open(comm, path, mio.MODE_CREATE | mio.MODE_RDWR)
        f.set_view(disp=0, etype=np.float64, filetype=shifted)
        f.write_at(0, np.full(4, float(comm.rank + 1)))
        f.close()
        return None

    run_local(prog, 2)
    whole = np.fromfile(path, dtype=np.float64)
    assert np.array_equal(whole, [1, 2] * 4)


def test_view_displacement_and_coalescing(tmp_path):
    path = str(tmp_path / "disp.bin")
    with mio.file_open(_self(), path, mio.MODE_CREATE | mio.MODE_RDWR) as f:
        # header of 3 bytes, then a contiguous float32 block: one run
        f.set_view(disp=3, etype=np.float32)
        runs = f._byte_runs(0, 5)
        assert runs == [(3, 20)]
        sub = dt.type_vector(2, 2, 3, np.float32)  # 2 elems, skip 1
        f.set_view(disp=3, etype=np.float32, filetype=sub)
        assert f._byte_runs(0, 4) == [(3, 8), (3 + 12, 8)]


def test_subarray_view_tiled_matrix(tmp_path):
    """Each rank owns a column block of a 4x4 row-major matrix file via a
    subarray filetype."""
    path = str(tmp_path / "mat.bin")

    def prog(comm):
        ft = dt.type_create_subarray([4, 4], [4, 2], [0, 2 * comm.rank],
                                     np.float32)
        f = mio.file_open(comm, path, mio.MODE_CREATE | mio.MODE_RDWR)
        f.set_view(etype=np.float32, filetype=ft)
        f.write_at(0, np.full(8, float(comm.rank + 1), np.float32))
        f.close()
        return None

    run_local(prog, 2)
    m = np.fromfile(path, dtype=np.float32).reshape(4, 4)
    assert np.all(m[:, :2] == 1.0) and np.all(m[:, 2:] == 2.0)


# -- collective I/O ----------------------------------------------------------


def test_write_at_all_two_phase(tmp_path):
    """Interleaved strided collective write aggregates at rank 0 and the
    file comes out bit-exact."""
    path = str(tmp_path / "coll.bin")
    n = 16

    def prog(comm):
        ft = dt.type_vector(n, 1, comm.size, np.int64)
        mine = dt.Datatype(ft.base_dtype, ft.indices + comm.rank, ft.extent)
        f = mio.file_open(comm, path, mio.MODE_CREATE | mio.MODE_RDWR)
        f.set_view(etype=np.int64, filetype=mine)
        f.write_at_all(0, np.arange(n, dtype=np.int64) * comm.size + comm.rank)
        out = f.read_at_all(0, n)
        f.close()
        return out

    res = run_local(prog, 4)
    whole = np.fromfile(path, dtype=np.int64)
    assert np.array_equal(whole, np.arange(4 * n))
    for r, out in enumerate(res):
        assert np.array_equal(out, np.arange(n) * 4 + r)


def test_write_at_all_large_falls_back(tmp_path):
    """Above the collective-buffer limit every rank writes directly; the
    result is identical."""
    path = str(tmp_path / "big.bin")
    nbytes = mio._COLLECTIVE_BUFFER_LIMIT  # total 2x limit over 2 ranks

    def prog(comm):
        f = mio.file_open(comm, path, mio.MODE_CREATE | mio.MODE_RDWR)
        block = np.full(nbytes, comm.rank + 1, np.uint8)
        f.write_at_all(comm.rank * nbytes, block)
        f.close()
        return None

    run_local(prog, 2)
    whole = np.fromfile(path, dtype=np.uint8)
    assert whole.size == 2 * nbytes
    assert np.all(whole[:nbytes] == 1) and np.all(whole[nbytes:] == 2)


# -- shared file pointer -----------------------------------------------------


def test_write_shared_claims_disjoint_regions(tmp_path):
    path = str(tmp_path / "shared.bin")
    per = 64

    def prog(comm):
        f = mio.file_open(comm, path, mio.MODE_CREATE | mio.MODE_RDWR,
                          shared=True)
        f.write_shared(np.full(per, comm.rank, np.uint8))
        comm.barrier()
        size = f.get_size()
        f.close()
        return size

    res = run_local(prog, 3)
    assert all(s == 3 * per for s in res)
    whole = np.fromfile(path, dtype=np.uint8)
    # every rank's record is contiguous and intact, in SOME order
    seen = sorted(int(whole[i * per]) for i in range(3))
    assert seen == [0, 1, 2]
    for i in range(3):
        assert np.all(whole[i * per:(i + 1) * per] == whole[i * per])


def test_shared_requires_flag(tmp_path):
    path = str(tmp_path / "noshared.bin")
    with mio.file_open(_self(), path, mio.MODE_CREATE | mio.MODE_RDWR) as f:
        with pytest.raises(RuntimeError, match="shared=True"):
            f.write_shared(np.zeros(4, np.uint8))


# -- API layer + TPU gating --------------------------------------------------


def test_api_layer_roundtrip(tmp_path):
    from mpi_tpu.api import (MPI_File_close, MPI_File_open, MPI_File_read_at,
                             MPI_File_set_view, MPI_File_write_at,
                             MPI_MODE_CREATE, MPI_MODE_RDWR)

    path = str(tmp_path / "api.bin")
    fh = MPI_File_open(path, MPI_MODE_CREATE | MPI_MODE_RDWR, comm=_self())
    MPI_File_set_view(fh, etype=np.float32)
    MPI_File_write_at(fh, 0, np.arange(4, dtype=np.float32))
    assert np.array_equal(MPI_File_read_at(fh, 0, 4), np.arange(4))
    MPI_File_close(fh)


def test_io_rejects_spmd_comm(tmp_path):
    def prog(comm):
        with pytest.raises(NotImplementedError, match="orbax"):
            mio.file_open(comm, "/tmp/x.bin", mio.MODE_CREATE | mio.MODE_RDWR)
        return 0

    mpi_tpu.run(prog, backend="tpu", nranks=None)


# -- round-3 review regressions ---------------------------------------------


def test_collective_open_failure_raises_everywhere(tmp_path):
    """A create/existence failure at rank 0 must raise on ALL ranks, not
    deadlock the others in the open barrier."""
    path = str(tmp_path / "excl.bin")
    open(path, "wb").close()

    def prog(comm):
        comm.recv_timeout = 20.0
        with pytest.raises(OSError, match="rank 0"):
            mio.file_open(comm, path,
                          mio.MODE_CREATE | mio.MODE_EXCL | mio.MODE_RDWR)
        with pytest.raises(OSError, match="rank 0"):
            mio.file_open(comm, str(tmp_path / "missing.bin"),
                          mio.MODE_RDONLY)
        return "ok"

    assert run_local(prog, 2) == ["ok", "ok"]


def test_overlapping_tiled_view_rejected(tmp_path):
    path = str(tmp_path / "ovl.bin")
    bad = dt.type_create_resized(dt.type_contiguous(2, np.int32), 0, 1)
    # indices [0,2] at extent 1: instances 0 and 2 collide — a shift-2
    # overlap the adjacent-instance check used to miss (review round 3)
    gap = dt.type_create_resized(dt.type_vector(2, 1, 2, np.int32), 0, 1)
    with mio.file_open(_self(), path, mio.MODE_CREATE | mio.MODE_RDWR) as f:
        for ft in (bad, gap):
            with pytest.raises(ValueError, match="overlap|congruent"):
                f.set_view(etype=np.int32, filetype=ft)
        # non-overlapping strided view still accepted (residues distinct)
        f.set_view(etype=np.int32, filetype=dt.type_vector(4, 1, 2, np.int32))


def test_seek_end_respects_view(tmp_path):
    path = str(tmp_path / "seekend.bin")
    with mio.file_open(_self(), path, mio.MODE_CREATE | mio.MODE_RDWR) as f:
        f.set_view(disp=4, etype=np.int32)
        f.write_at(0, np.arange(3, dtype=np.int32))
        f.seek(0, mio.SEEK_END)
        assert f.get_position() == 3  # not (16 bytes)//4 == 4
        # strided view: only MY elements count
        ft = dt.type_vector(8, 1, 2, np.int32)
        f.set_view(disp=0, etype=np.int32, filetype=ft)
        f.seek(0, mio.SEEK_END)
        assert f.get_position() == 2  # elements 0 and 2 of 16 bytes


def test_seek_failure_leaves_position_intact(tmp_path):
    path = str(tmp_path / "seekfail.bin")
    with mio.file_open(_self(), path, mio.MODE_CREATE | mio.MODE_RDWR) as f:
        f.set_view(etype=np.uint8)
        f.seek(5)
        with pytest.raises(ValueError, match="negative"):
            f.seek(-9, mio.SEEK_CUR)
        assert f.get_position() == 5


def test_spawn_bridge_transport_closed_on_free(tmp_path):
    """intercomm.free() on a spawn bridge closes its dedicated socket
    transport (review: fd/thread leak per spawn wave)."""
    from mpi_tpu import spawn as sp

    script = tmp_path / "noop_worker.py"
    script.write_text(
        "import sys\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})\n"
        "import mpi_tpu\nfrom mpi_tpu import spawn\n"
        "comm = mpi_tpu.COMM_WORLD\n"
        "parent = spawn.comm_get_parent()\n"
        "parent.send('done', dest=0)\n")
    inter = sp.comm_spawn([str(script)], 1, comm=mpi_tpu.comm_self())
    assert inter.recv(source=0) == "done"
    t = inter._u._t
    inter.free()
    assert t._closing  # transport actually closed (no vacuous default)


def test_overlapping_view_allowed_readonly(tmp_path):
    """MPI-2: an overlapping filetype is legal on a MODE_RDONLY file —
    only writes through an overlap are erroneous."""
    path = str(tmp_path / "ro.bin")
    np.arange(8, dtype=np.int32).tofile(path)
    ovl = dt.type_create_resized(dt.type_contiguous(2, np.int32), 0, 1)
    with mio.file_open(_self(), path, mio.MODE_RDONLY) as f:
        f.set_view(etype=np.int32, filetype=ovl)  # accepted
        # visible elements walk the overlapped tiling: 0,1,1,2,...
        assert np.array_equal(f.read_at(0, 4), [0, 1, 1, 2])


def test_write_read_ordered_rank_order(tmp_path):
    """write_ordered records land in RANK order (vs write_shared's race
    order), with ragged per-rank sizes."""
    path = str(tmp_path / "ordered.bin")

    def prog(comm):
        f = mio.file_open(comm, path, mio.MODE_CREATE | mio.MODE_RDWR,
                          shared=True)
        n = comm.rank + 1  # ragged: 1, 2, 3 elements
        f.write_ordered(np.full(n, comm.rank, np.uint8))
        comm.barrier()
        back = f.read_ordered(n)  # second epoch starts after the first
        f.close()
        return back

    res = run_local(prog, 3)
    whole = np.fromfile(path, dtype=np.uint8)
    assert np.array_equal(whole, [0, 1, 1, 2, 2, 2])
    # the ordered read consumed nothing new (EOF): per-rank shorts
    for r, back in enumerate(res):
        assert back.size == 0


# -- data representations (MPI_Register_datarep, VERDICT r3 missing #5) ------


def test_external32_datarep_roundtrip_and_wire_format(tmp_path):
    """set_view(datarep='external32') stores big-endian on disk (the
    portable interchange format, matching datatypes.pack_external) and
    converts back on read."""
    path = str(tmp_path / "e32.bin")
    data = np.arange(6, dtype=np.float32) * 1.5
    with mio.file_open(_self(), path, mio.MODE_CREATE | mio.MODE_RDWR) as f:
        f.set_view(etype=np.float32, datarep="external32")
        assert f.write_at(0, data) == 6
        assert np.array_equal(f.read_at(0, 6), data)
        assert f.get_view()[3] == "external32"
    # on-disk bytes are big-endian regardless of host endianness
    raw = np.fromfile(path, dtype=np.dtype(np.float32).newbyteorder(">"))
    assert np.array_equal(raw.astype(np.float32), data)


def test_register_custom_datarep_roundtrip(tmp_path):
    """A user-registered representation (float32 in memory, fixed-point
    int16 in the file — extent 2 != itemsize 4) is honored by typed IO
    through a strided filetype view, offsets scaled by the FILE extent."""
    scale = 256.0

    def rd(raw, et, n, extra):
        return (np.frombuffer(raw, dtype=">i2", count=n) / extra).astype(et)

    def wr(arr, et, extra):
        return np.round(arr * extra).astype(">i2").tobytes()

    mio.register_datarep("fix16", rd, wr,
                         extent_fn=lambda et, _: 2, extra_state=scale)
    try:
        path = str(tmp_path / "fix16.bin")
        data = np.asarray([0.5, -1.25, 3.75, 2.0], np.float32)
        with mio.file_open(_self(), path,
                           mio.MODE_CREATE | mio.MODE_RDWR) as f:
            # every-other-element filetype: file extent pattern exercises
            # the byte-run scaling at 2 bytes/element
            ft = dt.type_vector(4, 1, 2, np.float32)
            f.set_view(etype=np.float32, filetype=ft, datarep="fix16")
            assert f.write_at(0, data) == 4
            assert np.array_equal(f.read_at(0, 4), data)
        # on disk: int16 big-endian at STRIDED positions (0, 2, 4, 6)*2B;
        # the skipped odd positions are unwritten holes (read back as 0)
        raw = np.fromfile(path, dtype=">i2")
        assert np.array_equal(raw[::2] / scale, data)
        assert not np.any(raw[1::2])
    finally:
        del mio._DATAREPS["fix16"]


def test_positional_datarep_gets_view_offsets(tmp_path):
    """ADVICE r4 #3: a conversion callback declaring the optional
    trailing ``position`` parameter receives the VIEW-relative etype
    index of its batch's first element — correct through strided
    filetype views (where file bytes are scattered) and through
    seek-based and _all spellings (which compute offsets)."""
    key = 7

    def rd(raw, et, n, extra, position):
        vals = np.frombuffer(raw, dtype=np.int32, count=n).copy()
        return (vals - (np.arange(n) + position) * extra).astype(et)

    def wr(arr, et, extra, position):
        idx = np.arange(arr.size) + position
        return (arr.astype(np.int32) + idx * extra).astype(
            np.int32).tobytes()

    mio.register_datarep("poskey", rd, wr, extra_state=key)
    try:
        path = str(tmp_path / "poskey.bin")
        data = np.asarray([10, 20, 30, 40, 50, 60], np.int32)
        with mio.file_open(_self(), path,
                           mio.MODE_CREATE | mio.MODE_RDWR) as f:
            ft = dt.type_vector(6, 1, 2, np.int32)  # every other element
            f.set_view(etype=np.int32, filetype=ft, datarep="poskey")
            assert f.write_at(0, data) == 6
            # whole-view read and an OFFSET read both decode correctly
            assert np.array_equal(f.read_at(0, 6), data)
            assert np.array_equal(f.read_at(2, 3), data[2:5])
            # seek-based path feeds the file pointer as the position
            f.seek(4)
            assert np.array_equal(f.read(2), data[4:6])
        # on disk each element i is stored value + i*key at strided slots
        raw = np.fromfile(path, dtype=np.int32)
        assert np.array_equal(raw[::2] - np.arange(6) * key, data)
    finally:
        del mio._DATAREPS["poskey"]


def test_datarep_errors(tmp_path):
    path = str(tmp_path / "err.bin")
    with mio.file_open(_self(), path, mio.MODE_CREATE | mio.MODE_RDWR) as f:
        with pytest.raises(ValueError, match="unknown datarep"):
            f.set_view(etype=np.float32, datarep="no-such-rep")
    # duplicate registration (incl. predefined names) is erroneous
    with pytest.raises(ValueError, match="already registered"):
        mio.register_datarep("native", lambda *a: None, lambda *a: None)
    # a lying write conversion is caught at the choke point
    mio.register_datarep("liar", lambda raw, et, n, _: np.zeros(n, et),
                         lambda arr, et, _: b"x")
    try:
        with mio.file_open(_self(), path, mio.MODE_RDWR) as f:
            f.set_view(etype=np.float32, datarep="liar")
            with pytest.raises(ValueError, match="emitted"):
                f.write_at(0, np.zeros(3, np.float32))
    finally:
        del mio._DATAREPS["liar"]


def test_datarep_through_flat_api_and_shared_pointer(tmp_path):
    """MPI_Register_datarep + MPI_File_set_view(datarep=...) through the
    flat layer; shared-pointer writes run the conversion too (write_at
    is the single choke point)."""
    from mpi_tpu.api import (MPI_File_close, MPI_File_open,
                            MPI_File_read_at, MPI_File_set_view,
                            MPI_File_write_at, MPI_Register_datarep)

    MPI_Register_datarep(
        "negate", lambda raw, et, n, _: -np.frombuffer(raw, et, count=n),
        lambda arr, et, _: (-arr).tobytes())
    try:
        path = str(tmp_path / "neg.bin")
        fh = MPI_File_open(path, mio.MODE_CREATE | mio.MODE_RDWR,
                           comm=_self())
        MPI_File_set_view(fh, etype=np.int32, datarep="negate")
        MPI_File_write_at(fh, 0, np.arange(4, dtype=np.int32))
        out = MPI_File_read_at(fh, 0, 4)
        MPI_File_close(fh)
        assert np.array_equal(out, np.arange(4, dtype=np.int32))
        assert np.array_equal(np.fromfile(path, np.int32),
                              -np.arange(4, dtype=np.int32))
    finally:
        del mio._DATAREPS["negate"]


def test_positional_datarep_keyword_only_spelling(tmp_path):
    """The natural ``*, position=0`` keyword-only spelling is honored
    too (review round 5: it must not silently convert with position 0
    everywhere)."""
    def rd(raw, et, n, extra, *, position=0):
        vals = np.frombuffer(raw, dtype=np.int32, count=n).copy()
        return (vals - (np.arange(n) + position)).astype(et)

    def wr(arr, et, extra, *, position=0):
        idx = np.arange(arr.size) + position
        return (arr.astype(np.int32) + idx).astype(np.int32).tobytes()

    mio.register_datarep("poskw", rd, wr)
    try:
        path = str(tmp_path / "poskw.bin")
        data = np.asarray([100, 200, 300, 400], np.int32)
        with mio.file_open(_self(), path,
                           mio.MODE_CREATE | mio.MODE_RDWR) as f:
            f.set_view(etype=np.int32, datarep="poskw")
            f.write_at(0, data)
            # an offset read only decodes right if position reached rd
            assert np.array_equal(f.read_at(1, 3), data[1:4])
            assert np.array_equal(f.read_at(0, 4), data)
    finally:
        del mio._DATAREPS["poskw"]
