"""Near-miss twin: same computed peer, but one side receives first —
the classic safe ordering."""


def main(comm):
    peer = 1 - comm.rank
    if comm.rank == 0:
        comm.send(b"x", peer, tag=3)
        return comm.recv(peer, tag=3)
    if comm.rank == 1:
        got = comm.recv(peer, tag=3)
        comm.send(b"y", peer, tag=3)
        return got
    return None
