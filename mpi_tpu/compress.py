"""Compressed/quantized collectives for gradient traffic (ISSUE 8).

The segmented engine (PRs 1-2) made every collective byte ride zero-copy
raw frames, but large data-parallel payloads are WIRE-BOUND: every byte
still crosses at fold precision.  The standard production answer
(DGC / 1-bit-Adam / PowerSGD-class gradient compression) is to split the
WIRE dtype from the FOLD dtype — transmit a lossy low-precision encoding,
accumulate in full precision.  This module owns that split for the host
backend:

* ``algorithm="compressed"`` (and the explicit spellings
  ``"compressed:bf16"`` / ``"compressed:int8"``) for ``allreduce`` and
  ``reduce_scatter``: every pipeline segment is ENCODED at send time into
  a wire-tagged raw frame (transport/codec.py ``Encoded``) and DECODED at
  its fold site, while the working buffer folds in float32 (float64 for
  f64 payloads).  bf16 halves f32 wire bytes; the fp8-style scaled-int
  format quarters them (per-segment max-abs scale + int8 mantissas).
* ``algorithm="compressed:topk"`` (allreduce, SUM only): each rank ships
  only its ``compress_topk_ratio`` largest-magnitude gradient entries as
  (indices, values) pairs riding the codec's multi-segment raw frames —
  zero pickled array bytes, like every other hot path — accumulated
  densely in f32 on every rank.  ERROR FEEDBACK (the DGC residual): the
  unsent remainder is accumulated per (shape, dtype, op) slot on the
  communicator and added to the NEXT same-geometry gradient, so repeated
  steps converge on the dense sum instead of permanently dropping mass.
  The residual slot defaults to keying by payload geometry — a program
  alternating two same-geometry tensors through topk shares one slot —
  UNLESS the caller names the tensor: ``allreduce(...,
  compress_key=...)`` threads an identity into the slot key, giving
  each logical tensor its own residual (``reset_residuals`` clears
  them either way).

Group coherence: reductions REQUIRE congruent payloads (same dtype and
shape on every rank — the MPI contract the ring folds already lean on),
so the eligibility decision below is a pure function of congruent inputs
plus process-wide cvars and every rank declines (or proceeds) together —
the wire-path analogue of the arena's in-arena meta negotiation, with
the decline counted in the ``compress_fallbacks`` pvar and the caller
landing on the classic ``auto`` policy.  Divergence that the contract
cannot rule out (per-rank cvar skew, one rank passing ``"compressed"``
while another passes ``"ring"``) is caught BEFORE data moves by the
runtime verifier: the collective signature carries the RESOLVED wire
dtype (``"compressed:bf16"``, not the ``"compressed"`` alias), so mixed
groups raise CollectiveMismatchError naming both signatures instead of
desynchronizing the segment exchange.  Without the verifier, a decode of
a mismatched frame raises a typed error rather than misfolding silently.

Error bounds (measured in tests/test_compress.py): the ring re-encodes
PARTIAL SUMS at every one of its hops, so quantization error compounds
~linearly in P — bf16 keeps a relative bound of about ``(P+1) * 2^-8``,
scaled-int about ``(P+1) * amax/127``.  When that is too coarse, don't
compress (see README "when not to use").

Observability: ``bytes_compressed_saved`` (logical fold-dtype bytes
minus wire bytes, accumulated at encode time; negative for a top-k
ratio that overshoots dense) and ``compress_fallbacks`` mpit pvars;
the codec's ``bytes_raw_sent`` keeps counting the actual wire bytes, so
the bf16 halving is assertable exactly like the zero-pickle contract.

The TPU sibling of this seam is the attention backward ring
(tpu/pallas_attention.py): K/V circulate in the input dtype while dK/dV
accumulate and circulate in f32 — same wire-dtype != fold-dtype split,
credit protocol unchanged (VERDICT r5 #5).
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Tuple

import numpy as np

from . import mpit as _mpit
from .transport import codec as _codec

try:  # jax's dtype extension package — round-to-nearest-even bf16 casts
    import ml_dtypes as _ml_dtypes

    _BF16_DTYPE: Optional[np.dtype] = np.dtype(_ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - container ships ml_dtypes
    _ml_dtypes = None
    _BF16_DTYPE = None

# Process-wide knobs (mpit cvars ``compress_wire_dtype`` /
# ``compress_topk_ratio``).  Like every algorithm-steering cvar
# (collective_segment_bytes, the crossovers) these must agree across the
# group; the verifier's resolved-wire signature diagnoses skew.
_WIRE_DTYPE = "bf16"
_TOPK_RATIO = 0.01

# The arena declined / the payload cannot ride compression — the caller
# runs the classic policy (mirrors coll_sm.FALLBACK).
FALLBACK = object()

# resolve() marker for the sparsified path (it is not a WireFormat: the
# exchange is an (indices, values) allgather, not a segment codec).
TOPK = object()

# Input dtypes the quantizers accept.  f16/bf16 inputs fold in f32 (the
# seam's whole point); f64 payloads keep f64 folds.
_FLOAT_DTYPES = {np.dtype(np.float16), np.dtype(np.float32),
                 np.dtype(np.float64)}
if _BF16_DTYPE is not None:
    _FLOAT_DTYPES.add(_BF16_DTYPE)

# Reduction ops the dense wire formats accept: both encodings are
# MONOTONE (rint/clip and RNE preserve <=), so MAX/MIN stay meaningful —
# the result is the true extremum quantized.  Everything else (logical/
# bitwise ops on floats make no sense; PROD compounds relative error
# multiplicatively per hop) declines to the classic path.
_DENSE_OPS = frozenset({"sum", "max", "min"})


def fold_dtype(dtype: Any) -> np.dtype:
    """The accumulation dtype of a compressed collective: f64 payloads
    keep f64 folds, every other float folds in f32."""
    return (np.dtype(np.float64) if np.dtype(dtype) == np.float64
            else np.dtype(np.float32))


# -- bf16 bit conversions -----------------------------------------------------
#
# ml_dtypes (jax's dtype package) provides round-to-nearest-even casts;
# the pure-numpy fallback implements the same RNE via the carry trick,
# with NaNs quieted so a mantissa carry can never turn NaN into inf.
# Parity of the two paths is asserted in tests/test_compress.py.


def f32_to_bf16_bits(x32: np.ndarray) -> np.ndarray:
    """f32 -> uint16 bf16 bit patterns, round-to-nearest-even."""
    x32 = np.ascontiguousarray(x32, dtype=np.float32)
    if _BF16_DTYPE is not None:
        return x32.astype(_BF16_DTYPE).view(np.uint16)
    b = x32.view(np.uint32)
    nan = (b & np.uint32(0x7FFFFFFF)) > np.uint32(0x7F800000)
    r = b + (np.uint32(0x7FFF) + ((b >> np.uint32(16)) & np.uint32(1)))
    r = np.where(nan, b | np.uint32(0x00400000), r)
    return (r >> np.uint32(16)).astype(np.uint16)


def bf16_bits_to_f32(u16: np.ndarray) -> np.ndarray:
    """uint16 bf16 bit patterns -> f32 (exact)."""
    return (np.ascontiguousarray(u16, dtype=np.uint16)
            .astype(np.uint32) << np.uint32(16)).view(np.float32)


# -- wire formats -------------------------------------------------------------


class WireFormat:
    """One dense wire encoding: fold-dtype view -> raw segments and back.

    ``encode`` returns a codec :class:`~mpi_tpu.transport.codec.Encoded`
    (fresh buffers — safe on aliasing transports without a snapshot);
    ``decode`` accepts the Encoded a peer's frame reconstructed (or this
    format's raw segment list, the arena slot path) and returns a flat
    fold-dtype array.  Both are pure numpy passes, no Python loops."""

    name: str = "?"

    def encode_segs(self, x: np.ndarray) -> List[np.ndarray]:
        raise NotImplementedError

    def decode_segs(self, segs: List[np.ndarray]) -> np.ndarray:
        raise NotImplementedError

    def wire_nbytes(self, n: int, itemsize: int) -> int:
        """Encoded payload bytes for ``n`` fold-dtype elements (arena
        slot sizing; must match what encode_segs produces)."""
        raise NotImplementedError

    def encode(self, view: np.ndarray) -> _codec.Encoded:
        segs = self.encode_segs(view)
        _mpit.count(bytes_compressed_saved=int(view.nbytes)
                    - sum(int(s.nbytes) for s in segs))
        return _codec.Encoded(self.name, segs)

    def decode(self, payload: Any) -> np.ndarray:
        """Fold-site decode; a payload that is not this format's frame
        (a peer ran uncompressed, or a different wire dtype slipped past
        a disabled verifier) raises a TYPED error instead of misfolding."""
        if not (type(payload) is _codec.Encoded and payload.wire == self.name):
            raise TypeError(
                f"compressed collective expected a {self.name!r} wire "
                f"frame, got {type(payload).__name__}"
                f"{'' if type(payload) is not _codec.Encoded else ' ' + repr(payload.wire)}"
                " — is every rank running the same algorithm and "
                "compress_wire_dtype? (enable mpi_tpu.verify to diagnose "
                "divergence before data moves)")
        return self.decode_segs(payload.segs)


class _Bf16(WireFormat):
    """bf16 wire: 2 bytes/element, ~8 mantissa bits dropped.  Exact for
    values already representable in bf16 — a bf16 INPUT round-trips its
    first hop bit-identically (no double-convert loss)."""

    name = "bf16"

    def encode_segs(self, x: np.ndarray) -> List[np.ndarray]:
        return [f32_to_bf16_bits(np.asarray(x, dtype=np.float32))]

    def decode_segs(self, segs: List[np.ndarray]) -> np.ndarray:
        return bf16_bits_to_f32(segs[0])

    def wire_nbytes(self, n: int, itemsize: int) -> int:
        return 2 * n


class _Int8(WireFormat):
    """fp8-style scaled-int wire: a per-SEGMENT f32 max-abs scale + int8
    mantissas — 1 byte/element + 4 bytes/segment.  Per-segment scaling
    is what makes the bound usable: each pipeline segment quantizes
    against its OWN dynamic range, so one large outlier only coarsens
    its segment.  The mapping is monotone (MAX/MIN stay meaningful).

    Non-finite segments (an overflowed mixed-precision gradient — the
    loss scaler NEEDS to see the inf/NaN) cannot ride a max-abs scale:
    the scale itself would be non-finite, poisoning every finite value
    in the segment (or silently zeroing NaNs).  Such a segment ships as
    a RAW f32 passthrough instead — the frame is self-describing per
    segment, so the receiver keys on the value segment's dtype and the
    divergence signal propagates exactly, like the classic ring would.
    Non-finiteness is rank-local (not congruent), so this must be an
    in-band frame form, never an eligibility decline."""

    name = "int8"

    def encode_segs(self, x: np.ndarray) -> List[np.ndarray]:
        x32 = np.ascontiguousarray(x, dtype=np.float32)
        amax = float(np.max(np.abs(x32))) if x32.size else 0.0
        if not np.isfinite(amax):
            return [np.array([np.nan], np.float32), x32]
        scale = amax / 127.0 if amax > 0.0 else 1.0
        q = np.clip(np.rint(x32 / scale), -127, 127).astype(np.int8)
        return [np.array([scale], np.float32), q]

    def decode_segs(self, segs: List[np.ndarray]) -> np.ndarray:
        scale, q = segs
        if q.dtype != np.int8:  # non-finite passthrough segment
            return q.astype(np.float32, copy=False)
        return q.astype(np.float32) * np.float32(scale[0])

    def wire_nbytes(self, n: int, itemsize: int) -> int:
        return n + 4


BF16 = _Bf16()
INT8 = _Int8()
FORMATS = {f.name: f for f in (BF16, INT8)}

# The algorithm= spellings the communicator gate accepts.  reduce_scatter
# takes the dense formats only — top-k sparsification has no blockwise
# scatter semantics (absent entries have no per-destination home).
ALLREDUCE_NAMES = ("compressed", "compressed:bf16", "compressed:int8",
                   "compressed:topk")
REDUCE_SCATTER_NAMES = ("compressed", "compressed:bf16", "compressed:int8")


def is_compressed(algorithm: str) -> bool:
    return algorithm == "compressed" or algorithm.startswith("compressed:")


def _decline() -> None:
    _mpit.count(compress_fallbacks=1)


def _array_eligible(arr: np.ndarray) -> bool:
    return (not arr.dtype.hasobject and np.dtype(arr.dtype) in _FLOAT_DTYPES)


def topk_k(n: int) -> int:
    """Selection count for an ``n``-element gradient: ceil(ratio * n),
    at least 1, clamped to n (a ratio >= 1 degrades to dense — the
    k >= n edge case is defined, not an error)."""
    if n <= 0:
        return 0
    return min(n, max(1, int(math.ceil(_TOPK_RATIO * float(n)))))


def resolve(comm, coll: str, payload: np.ndarray, op,
            algorithm: str) -> Tuple[Any, str, Optional[Tuple]]:
    """The ``"compressed"`` half of the algorithm gate: returns
    ``(wire, resolved_algorithm, verify_counts)``.

    ``wire`` is a :class:`WireFormat`, the :data:`TOPK` marker, or None —
    a group-coherent decline (ineligible dtype/op; counted in
    ``compress_fallbacks``) that lands the caller on the classic
    ``"auto"`` policy, exactly like an arena decline.  The RESOLVED name
    (``"compressed:bf16"``, never the ``"compressed"`` alias) is what
    the verifier circulates, so wire-dtype skew across ranks raises
    CollectiveMismatchError before any data moves; for top-k the
    resolved k rides ``verify_counts`` so ratio skew is caught the same
    way (a divergent k would misfold silently otherwise)."""
    kind = algorithm.split(":", 1)[1] if ":" in algorithm else _WIRE_DTYPE
    if kind == "topk":
        if not _array_eligible(payload) or op.name != "sum":
            _decline()
            return None, "auto", None
        return TOPK, "compressed:topk", (topk_k(int(payload.size)),)
    fmt = FORMATS.get(kind)
    if fmt is None:
        raise ValueError(
            f"compress_wire_dtype cvar holds unknown format {kind!r}; "
            f"accepted: {sorted(FORMATS)}")
    if not _array_eligible(payload) or op.name not in _DENSE_OPS:
        _decline()
        return None, "auto", None
    return fmt, "compressed:" + fmt.name, None


# -- top-k sparsified allreduce ----------------------------------------------


def _idx_dtype(n: int) -> np.dtype:
    return np.dtype(np.int32 if n <= np.iinfo(np.int32).max else np.int64)


def reset_residuals(comm) -> None:
    """Drop the communicator's error-feedback residual slots (e.g. at an
    optimizer boundary, or between unrelated same-geometry tensors)."""
    comm.__dict__.pop("_compress_residuals", None)


def topk_allreduce(comm, arr: np.ndarray, op,
                   compress_key: Any = None) -> np.ndarray:
    """Sparsified SUM allreduce: local top-k selection (by magnitude,
    after adding this slot's error-feedback residual), then a P-1 ring
    allgather of every rank's (indices, values) pair — each hop one
    wire-tagged multi-segment raw frame — scatter-added into a dense
    fold-dtype accumulator on every rank.

    Per-rank wire volume is (P-1) * k * (index + value bytes) versus the
    ring's 2(P-1)/P * n * itemsize; the saving is counted (possibly
    negative — an overshooting ratio is honest) into the
    ``bytes_compressed_saved`` pvar.  Ties at the k-th magnitude are
    broken arbitrarily (np.argpartition); ANY valid top-k set yields the
    same bound, and the unselected remainder lands in the residual
    either way.

    ``compress_key`` names the TENSOR the residual belongs to (ISSUE 9
    satellite / PR-8 residual (c)): the slot key is (compress_key,
    geometry), so two logically distinct tensors that happen to share
    (shape, dtype, op) stop sharing one residual the moment the caller
    tells them apart.  None (the default) preserves the geometry-only
    keying."""
    from .communicator import _TAG_COLL

    shape = tuple(arr.shape)
    fdt = fold_dtype(arr.dtype)
    x = np.asarray(arr, dtype=fdt).reshape(-1).copy()
    n = x.size
    k = topk_k(n)
    store = comm.__dict__.setdefault("_compress_residuals", {})
    key = ("allreduce", compress_key, str(arr.dtype), shape, op.name)
    residual = store.get(key)
    if residual is not None and residual.shape == x.shape:
        x += residual
    idt = _idx_dtype(n)
    if k >= n:
        idx = np.arange(n, dtype=idt)
    elif k:
        idx = np.argpartition(np.abs(x), n - k)[n - k:].astype(idt)
    else:
        idx = np.zeros(0, idt)
    vals = x[idx].astype(np.float32)
    residual = x  # our private copy — it BECOMES the residual
    # what peers receive is the f32-cast values, so the residual keeps
    # the cast's remainder too (exactly 0 for f32 folds)
    residual[idx] = residual[idx] - vals.astype(fdt, copy=False)
    store[key] = residual
    out = np.zeros(n, fdt)
    # indices are duplicate-free by construction (argpartition over
    # distinct positions / arange), so fancy-index add is correct and
    # ~10x cheaper than np.add.at's unbuffered loop on this hot path
    out[idx] += vals
    p, r = comm.size, comm.rank
    if p > 1:
        right, left = (r + 1) % p, (r - 1) % p
        payload = _codec.Encoded("topk", [idx, vals])
        dense = 2 * (p - 1) * n * fdt.itemsize // max(1, p)
        _mpit.count(bytes_compressed_saved=dense
                    - (p - 1) * int(payload.nbytes))
        for _ in range(p - 1):
            got = comm._sendrecv_internal(payload, right, left, _TAG_COLL)
            if not (type(got) is _codec.Encoded and got.wire == "topk"):
                raise TypeError(
                    f"compressed:topk expected a 'topk' wire frame, got "
                    f"{type(got).__name__} — is every rank running "
                    f"compressed:topk with the same compress_topk_ratio?")
            gi, gv = got.segs
            out[gi] += gv.astype(fdt, copy=False)
            payload = got  # forward the received pair around the ring
    return out.astype(arr.dtype, copy=False).reshape(shape)
