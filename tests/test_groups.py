"""MPI_Group bookkeeping + MPI_Comm_create_group on both backends
(SURVEY.md §2: rank bookkeeping above the plugin boundary; §4 items 1-2)."""

import numpy as np
import pytest

from mpi_tpu import Group
from mpi_tpu.transport.local import run_local
from mpi_tpu.tpu import SpmdSemanticsError, run_spmd

P = 8


# -- pure group algebra ----------------------------------------------------


def test_group_constructors():
    g = Group(range(6))
    assert g.size == 6
    assert g.incl([4, 1, 0]).ranks == (4, 1, 0)  # ordered as listed
    assert g.excl([0, 5]).ranks == (1, 2, 3, 4)
    with pytest.raises(ValueError):
        Group([1, 1])
    with pytest.raises(ValueError):
        g.incl([6])


def test_group_set_algebra():
    a = Group([0, 2, 4, 6])
    b = Group([4, 5, 6, 7])
    assert a.union(b).ranks == (0, 2, 4, 6, 5, 7)  # a's order first
    assert a.intersection(b).ranks == (4, 6)
    assert a.difference(b).ranks == (0, 2)
    assert b.difference(a).ranks == (5, 7)


def test_group_translate():
    a = Group([3, 5, 7])
    b = Group([7, 3])
    assert a.translate([0, 1, 2], b) == [1, None, 0]
    assert a.rank_of(5) == 1
    assert a.rank_of(4) is None


# -- comm.create on the process backend ------------------------------------


def test_comm_create_local():
    def prog(comm):
        g = comm.group().incl([5, 3, 1])  # odd ranks, reordered
        sub = comm.create(g)
        if comm.rank in (1, 3, 5):
            assert sub is not None
            # group order defines the new ranks: 5->0, 3->1, 1->2
            return sub.rank, float(np.asarray(sub.allreduce(comm.rank)))
        assert sub is None
        return None

    res = run_local(prog, 6)
    assert res[5] == (0, 9.0) and res[3] == (1, 9.0) and res[1] == (2, 9.0)
    assert res[0] is None and res[2] is None and res[4] is None


def test_comm_create_isolated_from_parent():
    def prog(comm):
        sub = comm.create(comm.group().excl([0]))
        if sub is None:
            comm.send("hello", dest=1, tag=3)
            return None
        got = comm.recv(source=0, tag=3) if comm.rank == 1 else None
        sub.barrier()
        return got

    res = run_local(prog, 4)
    assert res[1] == "hello"


# -- comm.create on the SPMD backend ---------------------------------------


def test_comm_create_spmd_halves():
    def prog(comm, _):
        g = comm.group().incl([0, 1, 2, 3])
        sub = comm.create(g)  # complement 4..7 forms the sibling comm
        return sub.allreduce(comm.rank.astype(np.float32))

    out = np.ravel(np.asarray(run_spmd(prog, np.zeros(1, np.float32))))
    assert list(out[:4]) == [6.0] * 4
    assert list(out[4:]) == [22.0] * 4


def test_comm_create_spmd_reorders():
    def prog(comm, _):
        g = comm.group().incl([7, 6, 5, 4, 3, 2, 1, 0])  # full reversal
        sub = comm.create(g)
        return sub.rank.astype(np.float32)

    out = np.ravel(np.asarray(run_spmd(prog, np.zeros(1, np.float32))))
    assert list(out) == [7.0, 6.0, 5.0, 4.0, 3.0, 2.0, 1.0, 0.0]


def test_comm_create_spmd_uneven_rejected():
    def prog(comm, _):
        with pytest.raises(SpmdSemanticsError, match="equal-sized"):
            comm.create(comm.group().incl([0, 1, 2]))  # 3 vs 5 complement
        return comm.allreduce(np.float32(0))

    run_spmd(prog, np.zeros(1, np.float32))


def test_api_group_exports():
    from mpi_tpu import api

    g = api.MPI_Group_incl(Group(range(4)), [3, 0])
    assert g.ranks == (3, 0)
    assert api.MPI_Group_size(g) == 2
    assert api.MPI_Group_translate_ranks(g, [0], Group([3])) == [0]


def test_group_rank_of_traced_rank_raises_loudly():
    def prog(comm, _):
        with pytest.raises(TypeError, match="concrete integer rank"):
            Group([0, 1]).rank_of(comm.rank)
        return comm.allreduce(np.float32(0))

    run_spmd(prog, np.zeros(1, np.float32))


def test_comm_create_out_of_range_rank_rejected():
    def prog(comm):
        from mpi_tpu.group import Group

        with pytest.raises(ValueError):
            comm.create(Group([0, 1, 99]))

    run_local(prog, 4)


def test_comm_create_spmd_out_of_range_rank_rejected():
    from mpi_tpu.group import Group
    from mpi_tpu.tpu import TpuCommunicator, default_mesh

    comm = TpuCommunicator("world", default_mesh(8))
    with pytest.raises(ValueError):
        comm.create(Group([0, 1, 99]))


def test_comm_create_empty_group_rejected():
    def prog(comm):
        with pytest.raises(ValueError, match="non-empty"):
            comm.create(Group([]))
        return True

    assert all(run_local(prog, 2))

    def sprog(comm):
        try:
            comm.create(Group([]))
        except ValueError:
            return comm.rank * 0 + 1
        return comm.rank * 0

    assert np.all(np.asarray(run_spmd(sprog, nranks=4)) == 1)
