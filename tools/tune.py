#!/usr/bin/env python
"""Tuning-table sweep generator + validator (ISSUE 9 tentpole).

Sweep mode (default): runs the shipping OSU benchmark
(benchmarks/osu.py) under the real launcher over the grid

    transport (socket, shm) x nranks {2, 3, 4} x collective
    {allreduce, reduce_scatter, alltoall} x payload size x algorithm

— including the shared-memory arena ("sm") as a measured ALGORITHM
wherever the payload fits a slot, which is exactly the arena-vs-wire
axis the host-engine residuals (a)/(c) left open (P>2 rows, the >=1MB
band) — and emits a per-machine tuning table (mpi_tpu/tuning format)
under benchmarks/results/tuning/.  Every row is trust-stamped from the
leg's own oversubscription (nranks + the driver vs cpu cores), so a
noisy 2-core box produces an honest all-untrusted table that a quiet
box's regeneration upgrades row by row.

The winner of each cell keeps a STABILITY BIAS toward the seed policy:
when the algorithm the built-in constants would pick is within
--tie-factor (default 1.10) of the fastest p50, the row records the
seed's choice — on a box whose mid-size cells swing 2-3x between runs,
only a reproducible margin should flip dispatch away from the measured
defaults.  Both p50s land in the row for introspection.

Check mode (``--check table.json ...``): strict schema/version/
fingerprint-shape validation of committed tables — chained into
tools/check.sh so a malformed or stale-version table fails the CI gate
(fingerprint EQUALITY is deliberately not checked: committed tables are
per-machine artifacts that the resolver refuses at load time on any
other box).

Usage::

    python tools/tune.py                      # full sweep -> default path
    python tools/tune.py --quick              # smoke: 1KB, P=2, 1 sample
    python tools/tune.py --out my_table.json
    python tools/tune.py --check benchmarks/results/tuning/*.json
    python bench.py --tune [--quick]          # the CI spellings
"""

from __future__ import annotations

import argparse
import json
import os
import socket as _socket
import sys
import tempfile
import time
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_tpu.tuning import table as _table  # noqa: E402

TRANSPORTS = ("socket", "shm")
RANKS = (2, 3, 4)
# The measured size grid: 1KB/16KB (the latency band), 128KB/512KB (the
# ring-vs-halving crossover band), 1MB/2MB (the bandwidth band and the
# arena-vs-wire axis — 2MB is the largest size that still fits a P<=3
# arena slot, see _arena_capacity).  Bands in the emitted table follow
# mpi_tpu.tuning.table.band_edges: size k governs [k, next) with the
# first band reaching 0 and the last open-ended.
SIZES = (1 << 10, 16 << 10, 128 << 10, 512 << 10, 1 << 20, 2 << 20)
QUICK_SIZES = (1 << 10,)
COLLECTIVES = ("allreduce", "reduce_scatter", "alltoall")

# prefer the seed policy unless the measured winner beats it by >10%
TIE_FACTOR = 1.10


def _arena_capacity(p: int) -> int:
    """coll_sm's REAL slot arithmetic (its own constants, not a copy):
    the largest payload algorithm="sm" actually serves for a P-rank
    group — sweeping "sm" above it would silently measure the wire
    fallback and emit a lie.  (tests/test_tuning.py pins the formula.)"""
    from mpi_tpu import coll_sm as _sm

    slot = ((_sm._ARENA_BYTES - _sm._LINE * p) // p) \
        // _sm._LINE * _sm._LINE
    return slot - _sm._META_MAX


def _seed_policy(transport: str, p: int, coll: str, nbytes: int) -> str:
    """What today's constants pick for one cell — the fallback the table
    replaces, and the tie-bias incumbent.  The wire half is literally
    communicator.seed_allreduce_algorithm (not a copy — a structural
    reorder of the real auto block can never leave this anchoring the
    tie-bias to a phantom incumbent); the arena-first tier is the one
    boolean the shm transports add on top."""
    from mpi_tpu import communicator as _comm

    sm_ok = transport == "shm" and nbytes <= _arena_capacity(p)
    if coll == "alltoall":
        return "sm" if sm_ok else "pairwise"
    if coll == "reduce_scatter":
        return "sm" if sm_ok else "ring"
    # allreduce
    if sm_ok:
        return "sm"
    return _comm.seed_allreduce_algorithm(nbytes, p)


def _payload_bytes(nominal: int, p: int, coll: str) -> int:
    """The size actually REQUESTED for one cell: reduce_scatter and
    alltoall split the payload into P blocks (np.array_split in
    benchmarks/osu.py), and ragged blocks never ride the arena OR the
    segmented working buffer — at P=3 every pow2 size splits 86/85/85,
    so an unadjusted sweep would measure the decline path under the
    'sm' label.  Shaving the element count to a multiple of P (< 0.4%
    of the payload) keeps blocks congruent; rows stay keyed by the
    nominal size."""
    if coll in ("reduce_scatter", "alltoall"):
        elems = max(1, nominal // 4)  # f32 elements (osu.py's payload)
        elems -= elems % p
        if elems:
            return elems * 4
    return nominal


# Arena-gate sweep legs (ISSUE 11 satellite, closes PR-9's
# consult-only residual): the coll_sm INTERNAL gates — flat-vs-chunked
# allreduce folds, arena-vs-tree reduce — were tuned-table consumers
# with no generator emitting their rows, so they always fell back to
# the coll_sm_eager_bytes seed constant.  Each entry is
# (row collective, osu bench, {osu algorithm spelling -> row
# algorithm}): the spellings force the gate via benchmarks/osu.py
# _GATE_LEGS; "tree" is the plain wire algorithm, measured as itself.
GATES = (
    ("sm_allreduce", "allreduce",
     {"sm_flat": "flat", "sm_chunked": "chunked"}),
    ("sm_reduce", "reduce",
     {"sm_arena": "arena", "tree": "tree"}),
)


def _gate_seed(coll: str, nbytes: int) -> str:
    """The seed side of one arena gate — coll_sm's real eager constant,
    read live (not a copy)."""
    from mpi_tpu import coll_sm as _sm

    eager = nbytes <= _sm._EAGER_BYTES
    if coll == "sm_allreduce":
        return "flat" if eager else "chunked"
    return "arena" if eager else "tree"


def _algorithms(transport: str, p: int, coll: str) -> List[str]:
    """The wire algorithms measured for one (transport, P, collective)
    leg; "sm" is swept separately (size-capped by the arena slot)."""
    if coll == "allreduce":
        algos = ["ring", "rabenseifner"]
        if p & (p - 1) == 0:
            algos.append("recursive_halving")
        return algos
    if coll == "reduce_scatter":
        return ["ring"]
    return ["pairwise"]


def _osu_rows(backend: str, bench: str, nranks: int, sizes: List[int],
              algos: List[str], iters: int, warmup: int) -> List[Dict]:
    """One launcher invocation of benchmarks/osu.py — the measured
    program is exactly the shipping benchmark (host_sweep's recipe).

    The measuring ranks must be TABLE-BLIND: wire algorithms are
    forced by name, but the coll_sm INTERNAL gates (the sm_allreduce/
    sm_reduce legs this tool now sweeps) consult an active tuned table
    BEFORE the eager constant — with MPI_TPU_TUNING_TABLE inherited,
    both spellings of a gate leg would measure the already-dispatched
    path and the emitted rows would be noise-decided and
    self-reinforcing.  Rank processes inherit os.environ, so the var
    is stripped for the launch and restored after."""
    from mpi_tpu.launcher import launch

    saved_table = os.environ.pop("MPI_TPU_TUNING_TABLE", None)
    try:
        with tempfile.TemporaryDirectory() as td:
            out = os.path.join(td, "rows.jsonl")
            argv = [os.path.join(REPO, "benchmarks", "osu.py"),
                    "--bench", bench, "--backend", backend,
                    "-n", str(nranks),
                    "--sizes", ",".join(str(s) for s in sizes),
                    "--iters", str(iters), "--warmup", str(warmup),
                    "--algorithms", ",".join(algos), "--out", out]
            rc = launch(nranks, argv, timeout=1800.0, backend=backend)
            if rc != 0:
                raise RuntimeError(
                    f"{backend} {bench} P={nranks} tune leg exited {rc}")
            with open(out) as f:
                return [json.loads(line) for line in f if line.strip()]
    finally:
        if saved_table is not None:
            os.environ["MPI_TPU_TUNING_TABLE"] = saved_table


def _iters_for(nbytes: int, quick: bool) -> Tuple[int, int]:
    if quick:
        return 1, 0
    if nbytes <= 64 << 10:
        return 30, 5
    if nbytes <= 512 << 10:
        return 12, 2
    return 6, 1


def sweep(quick: bool = False,
          transports: Tuple[str, ...] = TRANSPORTS,
          ranks: Tuple[int, ...] = RANKS,
          tie_factor: float = TIE_FACTOR) -> Dict:
    """Run the grid and assemble the table document."""
    sizes = list(QUICK_SIZES if quick else SIZES)
    ranks = (2,) if quick else tuple(ranks)
    ncpu = os.cpu_count() or 1
    t0 = time.time()
    rows: List[_table.Row] = []
    measured: List[Dict] = []
    for transport in transports:
        for p in ranks:
            trusted = (p + 1) <= ncpu  # rank procs + the sweep driver
            for coll in COLLECTIVES:
                # cells: size -> algorithm -> p50_us
                cells: Dict[int, Dict[str, float]] = {s: {} for s in sizes}
                by_iters: Dict[Tuple[int, int], List[int]] = {}
                for s in sizes:
                    by_iters.setdefault(_iters_for(s, quick), []).append(s)
                for (iters, warmup), szs in by_iters.items():
                    # requested -> nominal band key (block-splitting
                    # collectives get P-congruent element counts)
                    req = {_payload_bytes(s, p, coll): s for s in szs}
                    for r in _osu_rows(transport, coll, p, sorted(req),
                                       _algorithms(transport, p, coll),
                                       iters, warmup):
                        if "p50_us" in r:
                            cells[req[r["bytes"]]][r["algorithm"]] = \
                                r["p50_us"]
                            measured.append(r)
                if transport == "shm":
                    cap = _arena_capacity(p)
                    sm_sizes = [s for s in sizes if s <= cap]
                    for (iters, warmup), szs in by_iters.items():
                        req = {_payload_bytes(s, p, coll): s
                               for s in szs if s in sm_sizes}
                        if not req:
                            continue
                        for r in _osu_rows(transport, coll, p,
                                           sorted(req), ["sm"], iters,
                                           warmup):
                            if "p50_us" in r:
                                cells[req[r["bytes"]]]["sm"] = r["p50_us"]
                                measured.append(r)
                for lo, hi, s in _table.band_edges(sizes):
                    algs = cells.get(s) or {}
                    if not algs:
                        continue
                    winner = min(algs, key=algs.get)
                    seed = _seed_policy(transport, p, coll, s)
                    chosen = winner
                    if (seed in algs and winner != seed
                            and algs[seed] <= tie_factor * algs[winner]):
                        chosen = seed  # stability bias: noise never flips
                    rows.append(_table.Row(
                        transport, p, coll, lo, hi, chosen,
                        trusted, extra={
                            "measured_bytes": s,
                            "p50_us": {a: round(v, 1)
                                       for a, v in sorted(algs.items())},
                            "seed": seed,
                        }))
            if transport != "shm":
                continue
            # arena-gate rows (ISSUE 11): swept only where the payload
            # fits a slot — the gates are never consulted above it
            cap = _arena_capacity(p)
            gate_sizes = [s for s in sizes if s <= cap]
            if not gate_sizes:
                continue
            for gate_coll, bench, spell in GATES:
                cells = {s: {} for s in gate_sizes}
                by_iters = {}
                for s in gate_sizes:
                    by_iters.setdefault(_iters_for(s, quick),
                                        []).append(s)
                for (iters, warmup), szs in by_iters.items():
                    for r in _osu_rows(transport, bench, p, sorted(szs),
                                       sorted(spell), iters, warmup):
                        if "p50_us" in r:
                            cells[r["bytes"]][spell[r["algorithm"]]] = \
                                r["p50_us"]
                            measured.append(r)
                for lo, hi, s in _table.band_edges(gate_sizes):
                    algs = cells.get(s) or {}
                    if not algs:
                        continue
                    winner = min(algs, key=algs.get)
                    seed = _gate_seed(gate_coll, s)
                    chosen = winner
                    if (seed in algs and winner != seed
                            and algs[seed] <= tie_factor * algs[winner]):
                        chosen = seed
                    rows.append(_table.Row(
                        transport, p, gate_coll, lo, hi, chosen,
                        trusted, extra={
                            "measured_bytes": s,
                            "p50_us": {a: round(v, 1)
                                       for a, v in sorted(algs.items())},
                            "seed": seed,
                        }))
    doc = _table.new_doc(rows, transports, generated={
        "tool": "tools/tune.py",
        "quick": quick,
        "ranks": list(ranks),
        "sizes": sizes,
        "tie_factor": tie_factor,
        "cpus": ncpu,
        # ANY leg oversubscribed -> the artifact-level stamp (per-row
        # trust is the finer-grained truth)
        "oversubscribed": any((p + 1) > ncpu for p in ranks),
        "wall_s": round(time.time() - t0, 1),
    })
    return doc


def default_table_name() -> str:
    return f"{_socket.gethostname()}_{os.cpu_count() or 1}cpu.json"


def check(paths: List[str]) -> int:
    """--check: strict validation; nonzero exit + message on the first
    malformed/stale table (the CI gate tools/check.sh runs)."""
    rc = 0
    for path in paths:
        try:
            tab = _table.TuningTable.load(path)
        except _table.TuningTableError as e:
            print(f"tune.py --check: FAIL {e}")
            rc = 1
            continue
        trusted = sum(1 for r in tab.rows if r.trusted)
        active = "active here" if tab.matches_machine() else \
            "inactive here (other machine's fingerprint — expected for " \
            "committed per-machine tables)"
        print(f"tune.py --check: OK {path}: {len(tab.rows)} rows "
              f"({trusted} trusted), fingerprint "
              f"{tab.fingerprint.get('hostname')}/"
              f"{tab.fingerprint.get('cpu_count')}cpu — {active}")
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--check", nargs="+", metavar="TABLE", default=None,
                    help="validate committed table(s) instead of sweeping")
    ap.add_argument("--quick", action="store_true",
                    help="smoke mode: 1KB, P=2, 1 sample, stdout only")
    ap.add_argument("--out", default=None,
                    help="output path (default: benchmarks/results/"
                         "tuning/<hostname>_<ncpu>cpu.json; --quick "
                         "never writes)")
    ap.add_argument("--transports", default=",".join(TRANSPORTS))
    ap.add_argument("--ranks", default=",".join(str(r) for r in RANKS))
    ap.add_argument("--tie-factor", type=float, default=TIE_FACTOR)
    args = ap.parse_args(argv)
    if args.check is not None:
        return check(args.check)
    doc = sweep(quick=args.quick,
                transports=tuple(args.transports.split(",")),
                ranks=tuple(int(r) for r in args.ranks.split(",")),
                tie_factor=args.tie_factor)
    _table.validate(doc)  # the generator must never emit a bad table
    text = json.dumps(doc, indent=2)
    if not args.quick:
        out = args.out or os.path.join(REPO, "benchmarks", "results",
                                       "tuning", default_table_name())
        os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
        with open(out, "w") as f:
            f.write(text + "\n")
        print(f"tune.py: wrote {out}")
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
