"""mpi_tpu — a TPU-native message-passing framework.

Capability-parity rebuild of the reference MPI-in-Python library
(mgawino/mpi; see SURVEY.md — the reference checkout itself was empty this
session, so SURVEY.md §0's contract extraction from BASELINE.json is the
blueprint).  Two backends behind one Communicator plugin boundary
(BASELINE.json:5):

* ``backend=socket`` — TCP/pickle CPU transport + mpirun-alike launcher; the
  source-compatibility proof and CPU baseline (SURVEY.md §7 Milestone 0).
* ``backend=tpu`` — MPI_COMM_WORLD bound to a ``jax.sharding.Mesh``; p2p
  lowers to ``lax.ppermute``; collectives re-emit as ``lax.psum`` /
  ``lax.all_gather`` / ``lax.all_to_all`` over ICI, with hand-scheduled
  ring / recursive-halving / tree algorithm variants (Milestones 1-2).

Also ``backend=local`` (threads, in-process) for fast tests and fault
injection.

Portable programs are written as ``def main(comm): ...`` and dispatched with
:func:`run`; classic per-process MPI scripts use :data:`COMM_WORLD` or the
flat ``MPI_*`` layer in :mod:`mpi_tpu.api`.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Optional

from .version import __version__
from . import ops
from .ops import SUM, PROD, MAX, MIN, LAND, LOR, LXOR, BAND, BOR, BXOR, ReduceOp
from .communicator import Communicator, Message, P2PCommunicator, Request, Status
from .transport.base import ANY_SOURCE, ANY_TAG
from .transport.local import run_local
from . import datatypes, errors, ft, io, membership, mpi4, progress, schedules, checker, checkpoint, profiling, telemetry, trace, verify
from .intercomm import InterComm, create_intercomm
from .topology import (CartComm, GraphComm, HierarchicalComm, cart_create,
                       dims_create, dist_graph_create_adjacent,
                       graph_create, split_hierarchical)
from .group import Group
from .spawn import (comm_accept, comm_connect, comm_get_parent, comm_spawn,
                    comm_spawn_multiple, close_port, lookup_name, open_port,
                    publish_name, unpublish_name)
from .shmwin import SharedWindow, win_allocate_shared
from .window import GetFuture, P2PWindow
from .membership import rejoin


def connect(addr, timeout: float = 30.0, priority: int = 0):
    """Connect to a resident world server (mpi_tpu/serve.py): returns a
    :class:`~mpi_tpu.serve.ServerClient` whose ``acquire(nranks)``
    leases a warm world in one round-trip.  ``addr`` is "host:port", a
    (host, port) tuple, an in-process WorldServer, or the path to a
    ``serve --addr-file`` file (a missing/partially-written file is
    retried within the connect budget).  A path to a DIRECTORY (a
    ``serve --federation`` namespace) or a list of "host:port" strings
    returns a :class:`~mpi_tpu.federation.FederatedClient` instead,
    which resolves live servers and fails over on server death.
    ``priority`` feeds the server's fair-share lease scheduler.  Lazy
    import: the serve module is also the worker entry point
    (``python -m mpi_tpu.serve``), so the package must not pre-import
    it."""
    from . import serve as _serve

    return _serve.connect(addr, timeout=timeout, priority=priority)

__all__ = [
    "__version__", "ops", "ReduceOp",
    "SUM", "PROD", "MAX", "MIN", "LAND", "LOR", "LXOR", "BAND", "BOR", "BXOR",
    "Communicator", "Message", "P2PCommunicator", "Request", "Status", "ANY_SOURCE", "ANY_TAG",
    "init", "finalize", "is_initialized", "run", "run_local",
    "schedules", "checker", "checkpoint", "ft", "membership", "profiling", "progress", "telemetry", "trace", "verify", "COMM_WORLD", "io", "mpi4",
    "connect", "rejoin", "serve",
    "CartComm", "GraphComm", "HierarchicalComm", "InterComm",
    "create_intercomm", "cart_create", "graph_create", "split_hierarchical",
    "dist_graph_create_adjacent", "dims_create", "Group",
    "GetFuture", "P2PWindow", "SharedWindow", "win_allocate_shared",
    "comm_spawn", "comm_spawn_multiple", "comm_get_parent",
    "open_port", "close_port", "comm_accept", "comm_connect",
    "publish_name", "unpublish_name", "lookup_name",
]

_ENV_RANK = "MPI_TPU_RANK"
_ENV_SIZE = "MPI_TPU_SIZE"
_ENV_RDV = "MPI_TPU_RDV"
_ENV_BACKEND = "MPI_TPU_BACKEND"

_world: Optional[P2PCommunicator] = None
_world_lock = threading.Lock()


def is_initialized() -> bool:
    return _world is not None


def init(backend: Optional[str] = None) -> Communicator:
    """Create (or return) the world communicator — MPI_Init + MPI_COMM_WORLD
    (SURVEY.md §2 component #10).

    Under the launcher (``python -m mpi_tpu.launcher -n N script.py``) this
    builds the socket transport from the launcher-provided environment;
    standalone it returns a single-rank world.
    """
    global _world
    with _world_lock:
        if _world is not None:
            return _world
        backend = backend or os.environ.get(_ENV_BACKEND) or (
            "socket" if _ENV_RANK in os.environ else "self"
        )
        if backend in ("socket", "shm"):
            rank = int(os.environ[_ENV_RANK])
            size = int(os.environ[_ENV_SIZE])
            rdv = os.environ[_ENV_RDV]
            if backend == "socket":
                from .transport.socket import SocketTransport as _T
            else:
                from .transport.shm import ShmTransport as _T

            t = _T(rank, size, rdv)
            # flight recorder (mpi_tpu/telemetry, ISSUE 13):
            # MPI_TPU_TRACE=1 / launcher --trace-dir — enabled before
            # the first collective so world-construction traffic is on
            # the timeline too
            telemetry.enable_from_env(rank=rank)
            # record which incarnation holds this world slot: the
            # elastic-membership layer's identity file (membership.py)
            # — accept_rejoin reads it to refuse an ousted-but-live
            # incarnation re-entering before failure_ack
            membership.publish_incarnation(rdv, rank)
            _world = P2PCommunicator(t, range(size))._mark_generation()
            if os.environ.get("MPI_TPU_FT", "") not in ("", "0"):
                # ULFM fault tolerance (mpi_tpu/ft.py): heartbeat files
                # under the rendezvous dir + a detector thread, so a
                # dead rank surfaces as ProcFailedError within the
                # fault_detect_timeout_s cvar instead of a stall
                from . import ft as _ft

                _ft.enable(_world, rdv_dir=rdv)
            if os.environ.get("MPI_TPU_VERIFY", "") not in ("", "0"):
                # runtime correctness verifier (mpi_tpu/verify):
                # pending-op files under the rendezvous dir — deadlocks
                # surface as DeadlockError within verify_stall_timeout_s
                # + one analysis slice, divergent collectives as
                # CollectiveMismatchError before their data moves
                verify.enable(_world, rdv_dir=rdv)
            if progress.resolve_mode() == "thread":
                # async progress engine (mpi_tpu/progress.py): one
                # daemon thread per world — background completion for
                # nonblocking ops, doorbell-parked transport draining
                # (MPI_TPU_PROGRESS=thread / launcher --progress /
                # the ``progress`` cvar)
                progress.enable(_world)
        elif backend in ("self", "local"):
            from .transport.local import LocalTransport, LocalWorld

            telemetry.enable_from_env(rank=0)
            t = LocalTransport(LocalWorld(1), 0)
            _world = P2PCommunicator(t, range(1))
        else:
            raise ValueError(
                f"unknown backend {backend!r} for process-world init; "
                "the TPU backend is entered via mpi_tpu.run(fn, backend='tpu') "
                "or mpi_tpu.tpu.run_spmd (it is an SPMD program, not a process world)"
            )
        return _world


def finalize() -> None:
    """MPI_Finalize: synchronize, close the transport, and report unexpected
    pending messages (the finalize-time sanitizer check, SURVEY.md §5)."""
    global _world
    with _world_lock:
        if _world is None:
            return
        _world.barrier()
        verified = _world._verify is not None
        if verified:
            _world._verify.world.mark_exited()
        rec = telemetry.REC
        if rec is not None and rec.trace_dir:
            # export at the orderly exit too (atexit covers sys.exit
            # paths; same filename, atomic replace — double export is
            # idempotent)
            rec.export_to_dir()
        pending = _world.close_transport()
        _world = None
    from . import mpi4 as _mpi4

    _mpi4._cfg_prune_all()  # session generation counters die with the world
    if pending:
        import warnings

        warnings.warn(f"MPI_Finalize: {len(pending)} unreceived message(s): {pending[:8]}")
    if verified:
        # finalize-time verifier report (SURVEY.md §5 sanitizer story):
        # leaked requests, unfreed communicators, recorded lints
        problems = verify.finalize_report()
        if problems:
            import warnings

            warnings.warn("MPI_Finalize: verifier report:\n  "
                          + "\n  ".join(problems))


def run(
    fn: Callable,
    *args: Any,
    backend: Optional[str] = None,
    nranks: Optional[int] = None,
    **kwargs: Any,
):
    """Run a portable MPI program ``fn(comm, *args, **kwargs)``.

    * ``backend='socket'`` (or under the launcher): calls ``fn`` with this
      process's world communicator; returns its local result.
    * ``backend='local'``: spawns ``nranks`` threads in-process; returns the
      list of per-rank results.
    * ``backend='tpu'``: traces ``fn`` once as an SPMD program over a device
      mesh (shard_map) and executes it on all devices; returns the stacked
      per-rank results (SURVEY.md §7 Milestone 1).
    """
    backend = backend or os.environ.get(_ENV_BACKEND) or (
        "socket" if _ENV_RANK in os.environ else "local"
    )
    if backend in ("socket", "shm", "self"):
        return fn(init(backend), *args, **kwargs)
    if backend == "local":
        if nranks is None:
            nranks = int(os.environ.get(_ENV_SIZE, "1"))
        return run_local(fn, nranks, args=args, kwargs=kwargs)
    if backend == "tpu":
        from .tpu import run_spmd

        return run_spmd(fn, *args, nranks=nranks, **kwargs)
    raise ValueError(f"unknown backend {backend!r}")


_self_store = threading.local()


def comm_self() -> P2PCommunicator:
    """MPI_COMM_SELF [S]: the size-1 communicator containing only this
    process — independent of (and usable alongside) any world backend.
    Collectives on it are identities; it is the conventional home for
    per-process libraries (e.g. opening an MPI-IO file privately).

    Per-THREAD, not per-process: the local backend simulates ranks as
    threads, and a process-global SELF would share one mailbox across
    those ranks (self-sends could be stolen cross-rank).  For an ordinary
    single-threaded rank process the two scopes coincide."""
    comm = getattr(_self_store, "comm", None)
    if comm is None:
        from .transport.local import LocalTransport, LocalWorld

        comm = P2PCommunicator(LocalTransport(LocalWorld(1), 0), range(1))
        _self_store.comm = comm
    return comm


def __getattr__(name: str):
    if name == "COMM_WORLD":
        return init()
    if name == "COMM_SELF":
        return comm_self()
    if name == "serve":
        # lazy: mpi_tpu.serve doubles as the worker's ``-m`` entry
        # point, and an eager import here would shadow runpy's execution
        import importlib

        return importlib.import_module(".serve", __name__)
    raise AttributeError(f"module 'mpi_tpu' has no attribute {name!r}")
