"""Jacobi stencil with halo exchange (SURVEY.md §2 component #14, §3.5;
BASELINE.json:11) — the Send/Recv stress test.

2-D heat problem: the global top edge is held at 1.0, every other boundary
at 0.0; the grid is decomposed by rows across ranks.  Each iteration
exchanges one-row halos with both neighbors (``comm.shift`` — a sendrecv
pair on the CPU backends, exactly one ``lax.ppermute`` each way on TPU) and
sweeps a 5-point stencil; the convergence norm is an ``allreduce(MAX)``.

    python -m mpi_tpu.launcher -n 4 examples/jacobi.py
    python examples/jacobi.py --backend local -n 4
    python examples/jacobi.py --backend tpu -n 8
"""

import argparse
import os
import sys

try:
    import mpi_tpu
except ModuleNotFoundError:  # running from a fresh checkout without install
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import mpi_tpu

import jax
import jax.numpy as jnp
import numpy as np

from mpi_tpu import ops


def jacobi_step(comm, local):
    """One halo exchange + 5-point sweep on this rank's row block."""
    # my last row goes down to rank+1; their last row arrives from rank-1
    above = comm.shift(local[-1], offset=1, wrap=False, fill=0.0)
    above = jnp.where(comm.rank == 0, jnp.ones_like(above), above)  # hot top edge
    below = comm.shift(local[0], offset=-1, wrap=False, fill=0.0)
    padded = jnp.concatenate([above[None], local, below[None]], axis=0)
    north, south = padded[:-2], padded[2:]
    west = jnp.pad(local[:, :-1], ((0, 0), (1, 0)))
    east = jnp.pad(local[:, 1:], ((0, 0), (0, 1)))
    new = 0.25 * (north + south + west + east)
    # vertical side walls are fixed at 0
    return new.at[:, 0].set(0.0).at[:, -1].set(0.0)


def jacobi_program(comm, rows_per_rank: int = 16, cols: int = 32, iters: int = 100):
    """Returns (final local block, global max-residual of the last sweep)."""
    local = jnp.zeros((rows_per_rank, cols), jnp.float32)
    for _ in range(iters):
        new = jacobi_step(comm, local)
        local, prev = new, local
    residual = comm.allreduce(jnp.max(jnp.abs(local - prev)), op=ops.MAX)
    return local, residual


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--backend", default=None, choices=[None, "socket", "local", "tpu"])
    ap.add_argument("-n", "--nranks", type=int, default=None)
    ap.add_argument("--rows", type=int, default=16, help="rows per rank")
    ap.add_argument("--cols", type=int, default=32)
    ap.add_argument("--iters", type=int, default=100)
    args = ap.parse_args()

    out = mpi_tpu.run(jacobi_program, backend=args.backend, nranks=args.nranks,
                      rows_per_rank=args.rows, cols=args.cols, iters=args.iters)
    # per-rank results: socket → (block, res); local → list of those; tpu → stacked
    if isinstance(out, list):
        res = float(np.asarray(out[0][1]))
    else:
        res = float(np.ravel(np.asarray(jax.device_get(out[1])))[0])
    print(f"jacobi: {args.iters} iters, last-sweep max residual {res:.3e}")


if __name__ == "__main__":
    main()
