"""Property tests for RMA epoch semantics: random op sequences against a
pure-python oracle that applies the documented deterministic order (issue
order; writes before gets; see mpi_tpu/window.py module docstring) — on
BOTH the thread backend and the SPMD backend."""

import numpy as np
import pytest

pytest.importorskip(
    "hypothesis",
    reason="property tests need hypothesis, absent from this environment")
from hypothesis import given, settings
from hypothesis import strategies as st

from mpi_tpu import ops
from mpi_tpu.transport.local import run_local
from mpi_tpu.tpu import run_spmd

P = 3


def perm_strategy():
    """A random partial permutation over P ranks as (src, dst) pairs."""
    return st.permutations(range(P)).flatmap(
        lambda dsts: st.lists(st.booleans(), min_size=P, max_size=P).map(
            lambda keep: [(s, d) for s, d in enumerate(dsts) if keep[s]]))


op_strategy = st.tuples(st.sampled_from(["put", "acc"]), perm_strategy())
epoch_strategy = st.lists(op_strategy, min_size=0, max_size=4)
program_strategy = st.lists(epoch_strategy, min_size=1, max_size=3)


def _data(src: int, epoch_i: int, op_i: int) -> float:
    return float(src * 100 + epoch_i * 10 + op_i + 1)


def oracle(program):
    wins = [np.zeros(2) for _ in range(P)]
    for ei, epoch in enumerate(program):
        for oi, (kind, pairs) in enumerate(epoch):  # issue order
            for s, d in pairs:
                v = _data(s, ei, oi)
                if kind == "put":
                    wins[d][...] = v
                else:
                    wins[d][...] += v
    return np.stack(wins)


@given(program=program_strategy)
@settings(max_examples=20, deadline=None)
def test_rma_random_epochs_match_oracle_local(program):
    def prog(comm):
        win = comm.win_create(np.zeros(2))
        for ei, epoch in enumerate(program):
            for oi, (kind, pairs) in enumerate(epoch):
                data = np.full(2, _data(comm.rank, ei, oi))
                if kind == "put":
                    win.put(data, pairs)
                else:
                    win.accumulate(data, pairs, op=ops.SUM)
            win.fence()
        return win.local

    got = np.stack([np.asarray(w) for w in run_local(prog, P)])
    np.testing.assert_allclose(got, oracle(program))


@given(program=program_strategy)
@settings(max_examples=10, deadline=None)
def test_rma_random_epochs_match_oracle_spmd(program):
    import jax.numpy as jnp

    def prog(comm):
        win = comm.win_create(jnp.zeros(2, jnp.float32))
        for ei, epoch in enumerate(program):
            for oi, (kind, pairs) in enumerate(epoch):
                data = jnp.zeros(2, jnp.float32) + (
                    comm.rank * 100.0 + ei * 10.0 + oi + 1.0)
                if kind == "put":
                    win.put(data, pairs)
                else:
                    win.accumulate(data, pairs, op=ops.SUM)
            win.fence()
        return win.local

    got = np.asarray(run_spmd(prog, nranks=P))
    np.testing.assert_allclose(got, oracle(program))
