"""Reduction operators for mpi_tpu collectives.

Capability contract: SURVEY.md §2 (components #6, #7) — the reference's
collective layer reduces with SUM at minimum; MPI-1.x additionally defines
MAX / MIN / PROD and the logical / bitwise ops [S].  (The reference checkout
at /root/reference is empty this session — see SURVEY.md §0 — so the MPI
standard is the behavioral contract.)

Each op carries an elementwise ``combine`` (works on numpy arrays, python
scalars, and jax tracers alike) plus a dtype-aware ``identity`` so tree /
masked-ppermute schedules can pad with neutral elements
(mpi_tpu/tpu/collectives.py).
"""

from __future__ import annotations

import operator
from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

from . import bufpool as _bufpool


def _is_jax(x: Any) -> bool:
    mod = type(x).__module__
    return mod.startswith("jax") or mod.startswith("jaxlib")


def _maximum(a, b):
    if _is_jax(a) or _is_jax(b):
        import jax.numpy as jnp

        return jnp.maximum(a, b)
    return np.maximum(a, b)


def _minimum(a, b):
    if _is_jax(a) or _is_jax(b):
        import jax.numpy as jnp

        return jnp.minimum(a, b)
    return np.minimum(a, b)


@dataclass(frozen=True)
class ReduceOp:
    """An MPI reduction operator: elementwise combiner + dtype-aware identity.

    ``ufunc`` (builtin ops only) is the numpy ufunc equivalent of
    ``combine``, used by the host collective engine's in-place
    accumulation; ``combine`` remains the portable spelling that also
    works on jax tracers."""

    name: str
    combine: Callable[[Any, Any], Any]
    identity: Callable[[Any], Any]  # np.dtype -> neutral scalar
    commutative: bool = True
    ufunc: Any = None  # numpy ufunc for in-place host accumulation

    def combine_into(self, acc: np.ndarray, value: Any,
                     decode: Callable[[Any], Any] = None) -> np.ndarray:
        """Accumulate ``value`` into ndarray ``acc`` IN PLACE (host data
        plane only — numpy, never tracers): zero result allocations for
        builtin ops, one temporary for user ops.  Always preserves acc's
        dtype — MPI reduces in the datatype, so a user combine that
        upcasts is cast back at every fold, not once at the end.

        ``decode`` is the wire-dtype != fold-dtype seam (ISSUE 8,
        mpi_tpu/compress.py): when set, ``value`` arrived in a WIRE
        encoding and is decoded to the fold dtype HERE — the one point
        where the two dtypes meet — so every fold site (segmented
        exchanges, arena slots) splits the dtypes identically."""
        if decode is not None:
            value = decode(value)
        # buffer-ownership notification (mpi_tpu/bufpool.py, ISSUE 11):
        # every fold mutates ``acc`` in place, and ``acc`` may still be
        # RETAINED by reference in a resilient link's unacked replay
        # window (ring/halving exchanges send the working buffer they
        # then fold into) — snapshot any overlapping retained frame
        # BEFORE the write lands so a replay stays bit-exact.  One int
        # compare when nothing is retained anywhere in the process.
        _bufpool.touch(acc)
        if self.ufunc is not None:
            self.ufunc(acc, value, out=acc)
            return acc
        out = self.combine(acc, value)
        if out is not acc:
            acc[...] = out
        return acc

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return f"ReduceOp({self.name})"


def _id_sum(dtype):
    return np.zeros((), dtype=dtype)[()]


def _id_prod(dtype):
    return np.ones((), dtype=dtype)[()]


def _id_max(dtype):
    dtype = np.dtype(dtype)
    if dtype.kind == "f":
        return dtype.type(-np.inf)
    if dtype.kind in "iu":
        return dtype.type(np.iinfo(dtype).min)
    if dtype.kind == "b":
        return False
    raise TypeError(f"MAX has no identity for dtype {dtype}")


def _id_min(dtype):
    dtype = np.dtype(dtype)
    if dtype.kind == "f":
        return dtype.type(np.inf)
    if dtype.kind in "iu":
        return dtype.type(np.iinfo(dtype).max)
    if dtype.kind == "b":
        return True
    raise TypeError(f"MIN has no identity for dtype {dtype}")


def _id_band(dtype):
    dtype = np.dtype(dtype)
    if dtype.kind == "b":
        return True
    if dtype.kind in "iu":
        return dtype.type(-1) if dtype.kind == "i" else dtype.type(np.iinfo(dtype).max)
    raise TypeError(f"BAND has no identity for dtype {dtype}")


def _id_false(dtype):
    dtype = np.dtype(dtype)
    if dtype.kind == "b":
        return False
    if dtype.kind in "iu":
        return dtype.type(0)
    raise TypeError(f"bitwise/logical op has no identity for dtype {dtype}")


def _id_true(dtype):
    dtype = np.dtype(dtype)
    if dtype.kind == "b":
        return True
    if dtype.kind in "iu":
        return dtype.type(1)
    raise TypeError(f"LAND has no identity for dtype {dtype}")


def make_op(combine: Callable[[Any, Any], Any], identity: Any,
            name: str = "user", commutative: bool = True) -> ReduceOp:
    """MPI_Op_create analogue: build a user-defined reduction operator.

    ``combine(a, b)`` must be associative (elementwise over arrays) and work
    on both numpy arrays and jax tracers if the op is to run on the TPU
    backend's hand-scheduled algorithms (they inline ``combine`` into the
    traced program; the 'fused' path reduces locally after an all_gather).
    ``identity`` is either a scalar or a callable ``np.dtype -> scalar``
    giving the neutral element (used to pad masked / boundary exchanges).
    """
    ident_fn = identity if callable(identity) else (
        lambda dtype, _v=identity: np.dtype(dtype).type(_v))
    return ReduceOp(name, combine, ident_fn, commutative)


SUM = ReduceOp("sum", operator.add, _id_sum, ufunc=np.add)
PROD = ReduceOp("prod", operator.mul, _id_prod, ufunc=np.multiply)
MAX = ReduceOp("max", _maximum, _id_max, ufunc=np.maximum)
MIN = ReduceOp("min", _minimum, _id_min, ufunc=np.minimum)
# Logical ops are defined on bool payloads (MPI's int-as-logical is not
# replicated; pass bool arrays).  Bitwise ops are defined on bool/int
# payloads.  The ufuncs mirror the operator spellings exactly (operator
# `&`/`|`/`^` on arrays ARE the bitwise ufuncs), so the in-place and
# allocating paths can never disagree.
LAND = ReduceOp("land", operator.and_, _id_true, ufunc=np.bitwise_and)
LOR = ReduceOp("lor", operator.or_, _id_false, ufunc=np.bitwise_or)
LXOR = ReduceOp("lxor", operator.xor, _id_false, ufunc=np.bitwise_xor)
BAND = ReduceOp("band", operator.and_, _id_band, ufunc=np.bitwise_and)
BOR = ReduceOp("bor", operator.or_, _id_false, ufunc=np.bitwise_or)
BXOR = ReduceOp("bxor", operator.xor, _id_false, ufunc=np.bitwise_xor)

ALL_OPS = (SUM, PROD, MAX, MIN, LAND, LOR, LXOR, BAND, BOR, BXOR)
BY_NAME = {op.name: op for op in ALL_OPS}
