"""Seeded bug: rank 0 alone enters a collective (literal guard)."""


def main(comm):
    if comm.rank == 0:
        comm.barrier()
