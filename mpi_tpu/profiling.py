"""Profiling / observability helpers (SURVEY.md §5: tracing row).

* :func:`trace` — context manager around ``jax.profiler`` emitting a
  Perfetto/XProf trace directory for the enclosed collectives.
* :func:`timeit` — robust wall-clock timing of a jax callable
  (``block_until_ready`` fencing, warmup, median/percentiles) — the
  measurement core shared by bench.py and benchmarks/osu.py conventions.
* :class:`CommStats` — per-op counters (counts + bytes).  Since ISSUE
  13 this is no longer dead API waiting for a wrapper that never came:
  the flight recorder (mpi_tpu/telemetry) fills one per traced run —
  every traced collective records (op, payload bytes) — and
  :func:`comm_stats` returns it.
"""

from __future__ import annotations

import contextlib
import json
import statistics
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List


@contextlib.contextmanager
def trace(log_dir: str):
    """Profile the enclosed block with jax.profiler (XProf/Perfetto trace in
    ``log_dir``); works on TPU and CPU."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@dataclass
class Timing:
    p50_s: float
    p10_s: float
    p90_s: float
    n: int

    @property
    def p50_us(self) -> float:
        return self.p50_s * 1e6


def timeit(fn: Callable[[], Any], iters: int = 50, warmup: int = 5) -> Timing:
    """Median wall-clock of ``fn()`` with device-fence per call: any returned
    jax arrays are blocked on, so async dispatch doesn't fake the numbers."""
    import jax

    def call():
        out = fn()
        jax.block_until_ready(out)
        return out

    for _ in range(warmup):
        call()
    samples: List[float] = []
    for _ in range(iters):
        t0 = time.perf_counter()
        call()
        samples.append(time.perf_counter() - t0)
    samples.sort()
    n = len(samples)
    return Timing(
        p50_s=statistics.median(samples),
        p10_s=samples[round(0.1 * (n - 1))],
        p90_s=samples[round(0.9 * (n - 1))],
        n=n,
    )


@dataclass
class CommStats:
    """Structured per-op counters (counts + bytes), JSON-able for logs.
    The live instance of a traced run hangs off the flight recorder
    (``telemetry.Recorder.stats``); :func:`comm_stats` fetches it."""

    ops: Dict[str, int] = field(default_factory=dict)
    bytes: Dict[str, int] = field(default_factory=dict)

    def record(self, op: str, nbytes: int = 0) -> None:
        self.ops[op] = self.ops.get(op, 0) + 1
        self.bytes[op] = self.bytes.get(op, 0) + nbytes

    def to_json(self) -> str:
        return json.dumps({"ops": self.ops, "bytes": self.bytes})


def comm_stats() -> "CommStats | None":
    """The per-op counters of the active (or last) traced run — filled
    by every collective while the flight recorder is enabled
    (``MPI_TPU_TRACE=1`` / ``run_local(trace=True)`` /
    ``telemetry.enable()``).  None when nothing was ever traced."""
    from . import telemetry as _telemetry

    rec = _telemetry.recorder()
    return rec.stats if rec is not None else None
