"""Hand-scheduled collective algorithms over ``lax.ppermute`` (L3 on TPU).

SURVEY.md §7 Milestone 2: the same pure schedule generators that drive the
CPU transports (mpi_tpu/schedules.py) are re-emitted here as ppermute step
sequences, so the reference's algorithm-vs-algorithm benchmark dimension
(ring vs recursive-halving, BASELINE.json:10; tree bcast/reduce,
BASELINE.json:8) exists on TPU alongside the fused XLA collectives
(SURVEY.md §3.3: "both required").

Every function takes group-level geometry:
* ``axis_name`` — the mesh axis the SPMD program runs over,
* ``size`` — ranks per group (static),
* ``grank`` — this shard's group-local rank (traced scalar),
* ``world_pairs(group_pairs)`` — expands group-level (src, dst) pairs to
  world-level ppermute pairs across all sibling groups (built by
  TpuCommunicator; validated by mpi_tpu.checker at trace time).

All control flow is trace-friendly: static round counts (unrolled Python
loops or ``lax.fori_loop`` where the permutation is step-invariant), dynamic
chunk indices via ``lax.dynamic_*_in_dim`` with traced ``grank`` — no
data-dependent Python branching (SURVEY.md §7 hard part 1).
"""

from __future__ import annotations

import functools
from typing import Callable, List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import ops as _ops
from .. import schedules

Pair = Tuple[int, int]
WorldPairs = Callable[[Sequence[Pair]], List[Pair]]


def _pad_flat(x: jnp.ndarray, size: int) -> Tuple[jnp.ndarray, int]:
    """Flatten and zero-pad to a multiple of ``size`` (equal ppermute chunks)."""
    flat = x.reshape(-1)
    n = flat.shape[0]
    padded = -(-n // size) * size if n else size
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    return flat, n


def _ensure_varying(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Mark ``x`` manual-varying over ``axis_name`` if it isn't already.

    Loop carries fed to ppermute inside fori_loop must enter the loop with
    the same varying-axes type they leave with; inputs that are replicated
    (e.g. broadcast operands) need an explicit pvary."""
    try:
        vma = jax.typeof(x).vma
    except AttributeError:  # pragma: no cover - non-shard_map tracing
        return x
    if axis_name in vma:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, (axis_name,), to="varying")
    return lax.pvary(x, (axis_name,))  # pragma: no cover - pre-0.9 jax


def _mask_of(ranks: Sequence[int], axis_size: int, axis_name: str):
    """Traced bool: is this shard's world axis-index in ``ranks``?"""
    table = np.zeros(axis_size, dtype=bool)
    table[list(ranks)] = True
    return jnp.asarray(table)[lax.axis_index(axis_name)]


def tree_reduce_local(op: _ops.ReduceOp, stacked: jnp.ndarray) -> jnp.ndarray:
    """Reduce a stacked [P, ...] array along axis 0 with op.combine (static P)."""
    parts = [stacked[i] for i in range(stacked.shape[0])]
    return functools.reduce(op.combine, parts)


# ---------------------------------------------------------------------------
# Ring allreduce — the north-star schedule (BASELINE.json:5,10; SURVEY.md §3.3)
# ---------------------------------------------------------------------------


def ring_allreduce(
    x: jnp.ndarray,
    axis_name: str,
    size: int,
    grank,
    world_pairs: WorldPairs,
    op: _ops.ReduceOp = _ops.SUM,
) -> jnp.ndarray:
    """Reduce-scatter ring + allgather ring: 2(P-1) ppermute steps, each
    moving 1/P of the buffer — bandwidth-optimal.  The ring permutation is
    step-invariant, so both phases run under ``lax.fori_loop`` (compile size
    independent of P); only the chunk index depends on the (traced) step."""
    if size == 1:
        return x
    shape, dtype = x.shape, x.dtype
    flat, n = _pad_flat(x, size)
    chunks = flat.reshape(size, -1)
    # the loop carry becomes axis-varying after the first ppermute; mark the
    # initial carry accordingly or shard_map's VMA check rejects the fori_loop
    chunks = _ensure_varying(chunks, axis_name)
    perm = world_pairs(schedules.ring_perm(size, 1))

    def rs_step(s, chunks):
        si = schedules.ring_rs_send_chunk(grank, s, size)
        ri = schedules.ring_rs_recv_chunk(grank, s, size)
        send = lax.dynamic_index_in_dim(chunks, si, 0, keepdims=False)
        recvd = lax.ppermute(send, axis_name, perm)
        cur = lax.dynamic_index_in_dim(chunks, ri, 0, keepdims=False)
        return lax.dynamic_update_index_in_dim(chunks, op.combine(cur, recvd), ri, 0)

    chunks = lax.fori_loop(0, size - 1, rs_step, chunks)

    def ag_step(s, chunks):
        si = schedules.ring_ag_send_chunk(grank, s, size)
        ri = schedules.ring_ag_recv_chunk(grank, s, size)
        send = lax.dynamic_index_in_dim(chunks, si, 0, keepdims=False)
        recvd = lax.ppermute(send, axis_name, perm)
        return lax.dynamic_update_index_in_dim(chunks, recvd, ri, 0)

    chunks = lax.fori_loop(0, size - 1, ag_step, chunks)
    return chunks.reshape(-1)[:n].reshape(shape).astype(dtype)


# ---------------------------------------------------------------------------
# Recursive halving/doubling allreduce (BASELINE.json:10)
# ---------------------------------------------------------------------------


def halving_allreduce(
    x: jnp.ndarray,
    axis_name: str,
    size: int,
    grank,
    world_pairs: WorldPairs,
    op: _ops.ReduceOp = _ops.SUM,
) -> jnp.ndarray:
    """Recursive-halving reduce-scatter + recursive-doubling allgather:
    2·log2(P) ppermute steps, latency-optimal; power-of-two groups only.
    Rounds are unrolled — each halves the live buffer, so shapes stay static."""
    if size == 1:
        return x
    masks = schedules.halving_masks(size)  # raises for non-pow2
    shape, dtype = x.shape, x.dtype
    buf, n = _pad_flat(x, size)
    for mask in masks:
        perm = world_pairs(schedules.xor_perm(size, mask))
        half = buf.shape[0] // 2
        lower, upper = buf[:half], buf[half:]
        bit = (grank & mask) != 0
        # bit set → my half is the upper one; send the lower half away
        send = jnp.where(bit, lower, upper)
        keep = jnp.where(bit, upper, lower)
        recvd = lax.ppermute(send, axis_name, perm)
        buf = op.combine(keep, recvd)
    # buf is now the fully reduced chunk number ``grank``
    for mask in schedules.doubling_masks(size):
        perm = world_pairs(schedules.xor_perm(size, mask))
        recvd = lax.ppermute(buf, axis_name, perm)
        bit = (grank & mask) != 0
        buf = jnp.where(
            bit,
            jnp.concatenate([recvd, buf]),
            jnp.concatenate([buf, recvd]),
        )
    return buf[:n].reshape(shape).astype(dtype)


# ---------------------------------------------------------------------------
# Binomial tree bcast / reduce (BASELINE.json:8)
# ---------------------------------------------------------------------------


def tree_bcast(
    x: jnp.ndarray,
    axis_name: str,
    size: int,
    grank,
    world_pairs: WorldPairs,
    axis_size: int,
    root: int = 0,
) -> jnp.ndarray:
    """Binomial-tree broadcast as log2(P) masked ppermute rounds.  Ranks not
    yet reached hold 0; ppermute delivers 0 to non-destinations, so
    ``buf + recvd`` is exact (each rank receives at most once)."""
    if size == 1:
        return x
    if x.dtype == jnp.bool_:
        return tree_bcast(x.astype(jnp.uint8), axis_name, size, grank,
                          world_pairs, axis_size, root).astype(jnp.bool_)
    buf = jnp.where(grank == root, x, jnp.zeros_like(x))
    for pairs in schedules.binomial_bcast_rounds(size, root):
        wp = world_pairs(pairs)
        recvd = lax.ppermute(buf, axis_name, wp)
        is_dst = _mask_of([d for _, d in wp], axis_size, axis_name)
        buf = buf + jnp.where(is_dst, recvd, jnp.zeros_like(recvd))
    return buf


def tree_reduce(
    x: jnp.ndarray,
    axis_name: str,
    size: int,
    grank,
    world_pairs: WorldPairs,
    axis_size: int,
    op: _ops.ReduceOp = _ops.SUM,
    root: int = 0,
) -> jnp.ndarray:
    """Binomial-tree reduction to ``root``: children send their accumulator
    up the tree; non-root ranks end holding the op identity.  ppermute's
    zero-fill at non-destinations is replaced with the op identity so MAX/MIN
    stay correct."""
    if size == 1:
        return x
    ident = jnp.full(x.shape, op.identity(np.dtype(x.dtype)), dtype=x.dtype)
    buf = x
    for pairs in schedules.binomial_reduce_rounds(size, root):
        wp = world_pairs(pairs)
        recvd = lax.ppermute(buf, axis_name, wp)
        is_dst = _mask_of([d for _, d in wp], axis_size, axis_name)
        buf = op.combine(buf, jnp.where(is_dst, recvd, ident))
    return jnp.where(grank == root, buf, ident)


# ---------------------------------------------------------------------------
# Allgather: ring and recursive doubling
# ---------------------------------------------------------------------------


def ring_allgather(
    x: jnp.ndarray,
    axis_name: str,
    size: int,
    grank,
    world_pairs: WorldPairs,
) -> jnp.ndarray:
    """P-1 ring steps; returns stacked [P, ...] in rank order."""
    out = jnp.zeros((size,) + x.shape, x.dtype)
    out = lax.dynamic_update_index_in_dim(out, x, grank, 0)
    if size == 1:
        return out
    out = _ensure_varying(out, axis_name)  # see ring_allreduce carry note
    perm = world_pairs(schedules.ring_perm(size, 1))

    def step(s, out):
        si = (grank - s) % size
        ri = (grank - s - 1) % size
        send = lax.dynamic_index_in_dim(out, si, 0, keepdims=False)
        recvd = lax.ppermute(send, axis_name, perm)
        return lax.dynamic_update_index_in_dim(out, recvd, ri, 0)

    return lax.fori_loop(0, size - 1, step, out)


def doubling_allgather(
    x: jnp.ndarray,
    axis_name: str,
    size: int,
    grank,
    world_pairs: WorldPairs,
) -> jnp.ndarray:
    """Recursive doubling: log2(P) steps, buffer doubles each step; returns
    stacked [P, ...] in rank order (power-of-two groups only)."""
    buf = x[None]
    if size == 1:
        return buf
    for mask in schedules.doubling_masks(size):
        perm = world_pairs(schedules.xor_perm(size, mask))
        recvd = lax.ppermute(buf, axis_name, perm)
        bit = (grank & mask) != 0
        buf = jnp.where(
            bit,
            jnp.concatenate([recvd, buf], axis=0),
            jnp.concatenate([buf, recvd], axis=0),
        )
    return buf


def ring_reduce_scatter(
    x: jnp.ndarray,
    axis_name: str,
    size: int,
    grank,
    world_pairs: WorldPairs,
    op: _ops.ReduceOp = _ops.SUM,
) -> jnp.ndarray:
    """Reduce-scatter ring on stacked [P, ...] blocks: P-1 ppermute steps;
    rank r ends holding the fully reduced block r (the rs-to-rank chunk
    indexing of mpi_tpu/schedules.py)."""
    if x.shape[0] != size:
        raise ValueError(f"need leading dim == {size}, got {x.shape}")
    chunks = _ensure_varying(x, axis_name)
    perm = world_pairs(schedules.ring_perm(size, 1))

    def step(s, chunks):
        si = schedules.ring_rs_block_send_chunk(grank, s, size)
        ri = schedules.ring_rs_block_recv_chunk(grank, s, size)
        send = lax.dynamic_index_in_dim(chunks, si, 0, keepdims=False)
        recvd = lax.ppermute(send, axis_name, perm)
        cur = lax.dynamic_index_in_dim(chunks, ri, 0, keepdims=False)
        return lax.dynamic_update_index_in_dim(chunks, op.combine(cur, recvd), ri, 0)

    chunks = lax.fori_loop(0, size - 1, step, chunks)
    return lax.dynamic_index_in_dim(chunks, grank, 0, keepdims=False)


# ---------------------------------------------------------------------------
# Pairwise alltoall (BASELINE.json:9)
# ---------------------------------------------------------------------------


def pairwise_alltoall(
    x: jnp.ndarray,
    axis_name: str,
    size: int,
    grank,
    world_pairs: WorldPairs,
) -> jnp.ndarray:
    """P-1 rounds; round k sends block (grank+k)%P to neighbor at distance k
    and receives the block slot (grank-k)%P.  Input/output: stacked [P, ...].
    Rounds are unrolled because each has a distinct (static) permutation."""
    if x.shape[0] != size:
        raise ValueError(
            f"alltoall payload must have leading dim == group size {size}, "
            f"got {x.shape}"
        )
    out = jnp.zeros_like(x)
    own = lax.dynamic_index_in_dim(x, grank, 0, keepdims=False)
    out = lax.dynamic_update_index_in_dim(out, own, grank, 0)
    for k in schedules.alltoall_rounds(size):
        perm = world_pairs(schedules.ring_perm(size, k))
        send = lax.dynamic_index_in_dim(x, (grank + k) % size, 0, keepdims=False)
        recvd = lax.ppermute(send, axis_name, perm)
        out = lax.dynamic_update_index_in_dim(out, recvd, (grank - k) % size, 0)
    return out
