"""mpilint v2 — corpus, engine, CLI, and baseline-gate coverage.

The seeded-bug corpus (tests/lint_corpus/) is the engine's acceptance
spec: per rule MPL001–MPL009 a literal variant, a SYMBOLIC variant the
v1 literal-pattern linter was blind to, and a clean near-miss twin.
Each buggy file must yield findings of exactly its rule; each twin
must lint clean — both directions, so the corpus pins false-negative
AND false-positive behaviour.

The CLI/baseline tests cover the check.sh workflow: --format json,
--baseline subtraction (new findings fail, baselined ones pass, stale
entries warn), and the tier-1 smoke that holds the SHIPPED tree to the
committed allowance.
"""

import ast
import glob
import json
import os
import subprocess
import sys

import pytest

from mpi_tpu.verify.lint import _rank_eq_literal, lint_file, lint_source

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = os.path.join(REPO, "tests", "lint_corpus")
MPILINT = os.path.join(REPO, "tools", "mpilint.py")
BASELINE = os.path.join(REPO, "tools", "lint_baseline.json")

_BUGGY = sorted(glob.glob(os.path.join(CORPUS, "mpl*_literal.py"))
                + glob.glob(os.path.join(CORPUS, "mpl*_symbolic.py")))
_CLEAN = sorted(glob.glob(os.path.join(CORPUS, "mpl*_clean.py")))


def _expected_rule(path: str) -> str:
    # mpl007_symbolic.py -> MPL007
    return os.path.basename(path).split("_")[0].upper()


def test_corpus_is_complete():
    """Literal + symbolic + clean twin for every rule MPL001–MPL009."""
    assert len(_BUGGY) == 18, _BUGGY
    assert len(_CLEAN) == 9, _CLEAN
    rules = {_expected_rule(p) for p in _BUGGY}
    assert rules == {f"MPL00{i}" for i in range(1, 10)}


@pytest.mark.parametrize("path", _BUGGY,
                         ids=[os.path.basename(p) for p in _BUGGY])
def test_seeded_bug_yields_exactly_its_rule(path):
    findings = lint_file(path)
    assert len(findings) == 1, [f.render() for f in findings]
    assert findings[0].code == _expected_rule(path), findings[0].render()


@pytest.mark.parametrize("path", _CLEAN,
                         ids=[os.path.basename(p) for p in _CLEAN])
def test_clean_twin_yields_nothing(path):
    findings = lint_file(path)
    assert findings == [], [f.render() for f in findings]


# -- v1-blind / v2-caught ----------------------------------------------------
#
# The v1 linter keyed every rank-conditional rule on the literal
# pattern ``<name>.rank == <int>`` (the predicate survives as
# lint._rank_eq_literal).  The symbolic corpus variants contain NO
# such test — a v1 scan finds nothing to key on — yet v2 resolves
# them through the dataflow engine.  Asserted for MPL001 and MPL002
# per the issue's acceptance bar.


def _v1_trigger_count(path: str) -> int:
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read())
    return sum(1 for node in ast.walk(tree)
               if isinstance(node, ast.If)
               and _rank_eq_literal(node.test) is not None)


@pytest.mark.parametrize("rule", ["mpl001", "mpl002"])
def test_symbolic_variant_is_v1_blind_v2_caught(rule):
    sym = os.path.join(CORPUS, f"{rule}_symbolic.py")
    lit = os.path.join(CORPUS, f"{rule}_literal.py")
    # the literal variant is v1 territory: the legacy predicate fires
    assert _v1_trigger_count(lit) > 0
    # the symbolic variant offers v1 nothing to key on...
    assert _v1_trigger_count(sym) == 0
    # ...and v2 still resolves the bug
    (f,) = lint_file(sym)
    assert f.code == rule.upper()


def test_symbolic_alias_revoke_caught():
    """MPL004 through a communicator alias (c2 = comm): the revoke and
    the later operation use different names for the same comm."""
    (f,) = lint_file(os.path.join(CORPUS, "mpl004_symbolic.py"))
    assert f.code == "MPL004" and "Revoked" in f.msg


def test_path_sensitive_leak_caught():
    """MPL005 on a request waited on only ONE CFG path — the wait is
    textually present, so any literal 'no wait() anywhere' scan stays
    silent; only path-sensitive request flow sees the leak."""
    src = open(os.path.join(CORPUS, "mpl005_symbolic.py")).read()
    assert ".wait()" in src  # the wait IS there — just not on all paths
    (f,) = lint_source(src, "mpl005_symbolic.py")
    assert f.code == "MPL005"


# -- CLI: --format json + --baseline -----------------------------------------


def _run_cli(*argv, cwd=REPO):
    return subprocess.run([sys.executable, MPILINT, *argv],
                          capture_output=True, text=True, timeout=120,
                          cwd=cwd)


def test_cli_json_format_over_corpus():
    proc = _run_cli("--format", "json", "tests/lint_corpus")
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert not doc["ok"]
    assert len(doc["findings"]) == 18
    assert {f["code"] for f in doc["findings"]} == {
        f"MPL00{i}" for i in range(1, 10)}
    # every finding carries the machine-readable fields
    for f in doc["findings"]:
        assert set(f) == {"file", "line", "code", "msg"}


def test_cli_baseline_subtraction(tmp_path):
    bad = tmp_path / "prog.py"
    bad.write_text("def main(comm):\n"
                   "    if comm.rank == 0:\n"
                   "        comm.barrier()\n")
    # no baseline: the finding fails the gate
    proc = _run_cli(str(bad), cwd=str(tmp_path))
    assert proc.returncode == 1 and "MPL001" in proc.stdout
    # baselined (with rationale): the gate passes
    base = tmp_path / "base.json"
    base.write_text(json.dumps({"entries": [
        {"file": "prog.py", "code": "MPL001", "count": 1,
         "why": "fixture"}]}))
    proc = _run_cli("--baseline", str(base), str(bad), cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "1 baselined" in proc.stdout
    # a SECOND instance of the same (file, code) exceeds the count
    bad.write_text("def main(comm):\n"
                   "    if comm.rank == 0:\n"
                   "        comm.barrier()\n"
                   "def other(comm):\n"
                   "    if comm.rank == 1:\n"
                   "        comm.barrier()\n")
    proc = _run_cli("--baseline", str(base), str(bad), cwd=str(tmp_path))
    assert proc.returncode == 1 and "new finding" in proc.stdout


def test_cli_stale_baseline_entry_warns(tmp_path):
    ok = tmp_path / "prog.py"
    ok.write_text("def main(comm):\n    comm.barrier()\n")
    base = tmp_path / "base.json"
    base.write_text(json.dumps({"entries": [
        {"file": "prog.py", "code": "MPL001", "count": 1,
         "why": "was fixed since"}]}))
    proc = _run_cli("--baseline", str(base), str(ok), cwd=str(tmp_path))
    assert proc.returncode == 0
    assert "stale baseline entry" in proc.stdout
    # json mode reports it structurally
    proc = _run_cli("--format", "json", "--baseline", str(base), str(ok),
                    cwd=str(tmp_path))
    doc = json.loads(proc.stdout)
    assert doc["stale_baseline"] == [{"file": "prog.py", "code": "MPL001"}]


# -- tier-1 smoke: the shipped tree holds to the committed baseline ----------


def test_shipped_tree_matches_committed_baseline():
    """The check.sh lint gate, exactly as CI runs it: corpus + shipped
    tree + tests + benchmarks vs tools/lint_baseline.json — zero new
    findings, zero stale entries (the baseline is in sync)."""
    proc = _run_cli("--format", "json", "--baseline", BASELINE,
                    "examples", "mpi_tpu", "tests", "benchmarks")
    doc = json.loads(proc.stdout)
    assert proc.returncode == 0, json.dumps(doc.get("new"), indent=2)
    assert doc["ok"] and doc["new"] == []
    assert doc["stale_baseline"] == [], doc["stale_baseline"]
    # examples/ and mpi_tpu/ carry no allowance at all: clean outright
    assert not any(f["file"].startswith(("examples/", "mpi_tpu/"))
                   for f in doc["findings"])
