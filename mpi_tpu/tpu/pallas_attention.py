"""Fused ring attention as a Pallas TPU kernel (RDMA over ICI).

Long-context exact attention over a sequence-sharded axis (SURVEY.md §2
strategy table — the long-context strategy is first-class).  The
ppermute spelling lives in ``examples/ring_attention.py``; this module
is its TPU-first hot path: ONE kernel in which the K/V blocks circulate
the ring as RDMAs while the MXU computes attention against the block
that just landed — transfer hidden behind compute, the same
communication/compute overlap argument as ``pallas_ring``.

Protocol (a sibling of pallas_ring's — verified by the discrete-event
model ``ring_model.AttentionSim``, tests/test_pallas_protocol.py):

* Each device holds Q, K, V blocks of the sequence ([Sb, d] each).  At
  step 0 it computes attention of its Q against its OWN K/V and starts
  forwarding that K/V (one stacked [2*Sb, d] RDMA) to its right
  neighbor's landing slot.
* Arrival ``a`` (1..P-1) lands K/V block ``(rank - a) mod P`` in the
  double-buffered comm slot ``a % 2``; the device copies it to VMEM,
  folds it into the online-softmax state (running rowmax ``m``,денom
  ``l``, weighted accumulator ``o`` — all f32), and, while the fold
  runs, forwards the same block from the slot to the next neighbor.
* **Credit flow control** recycles the slots: arrival ``a+2`` re-uses
  slot ``a % 2``, so after consuming arrival ``a`` (VMEM copy done AND
  the forwarding RDMA has left the slot — ``wait_send`` precedes the
  credit) the device signals one credit to its LEFT neighbor, which
  gates that neighbor's send ``a+1``.  Sends 0 and 1 are credit-free
  (their target slots are virgin).
* Entry/exit neighbor barriers bracket the kernel, as in pallas_ring.

Numerics: the online-softmax recurrence
``m' = max(m, rowmax(S)); l' = l·e^{m-m'} + rowsum(e^{S-m'});
o' = o·e^{m-m'} + e^{S-m'}·V`` is an exact (not approximate) attention
— the standard flash/ring-attention algebra.  Accumulation is float32
for bf16 inputs.  Full OR causal attention (``causal=True`` masks by
global position — block indices come from the SMEM params, so the same
compiled kernel serves every rank); scale = 1/sqrt(d) by default.

Under the interpreter (CPU tier) RDMAs run serially (start+wait, no
credits/barriers) — same data path, no overlap; under vma typing or a
multi-axis mesh the interpreter executes a ppermute ring fallback
(same online-softmax algebra as jax ops) with the shared loud-fallback
warning.  The compiled multi-axis path addresses neighbors by mesh
coordinate exactly like pallas_ring.

Restrictions (diagnosed): f32/bf16; head dim ``d`` a multiple of 128
(lane width); block rows ``Sb`` a multiple of 8; the per-device K/V
block must fit VMEM twice over (double buffer) — tens of thousands of
rows at d=128.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_ring import _check_args, _fallback, _world_pairs_of

_LANES = 128


_MASKED = -1e30  # large-negative finite (an -inf mask would NaN through exp)


def _online_fold(q, k, v, m, l, o, scale, mask=None):
    """One block's online-softmax fold (shared by kernel and fallback).
    q:[Sq,d] k,v:[Sb,d] m,l:[Sq,1] o:[Sq,d] (f32 state) → new (m,l,o).
    ``mask``: optional [Sq,Sb] bool, True = attend (False → _MASKED;
    a fully-masked block folds as exactly zero contribution)."""
    s = jnp.dot(q.astype(jnp.float32), k.astype(jnp.float32).T,
                preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, _MASKED)
    m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
    o_new = o * alpha + jnp.dot(p, v.astype(jnp.float32),
                                preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


def _causal_mask(my, kv_idx, sb: int):
    """[Sb,Sb] causal mask for query block ``my`` vs key block
    ``kv_idx`` (both traced block indices): global key position must
    not exceed global query position."""
    qi = my * sb + lax.broadcasted_iota(jnp.int32, (sb, sb), 0)
    kj = kv_idx * sb + lax.broadcasted_iota(jnp.int32, (sb, sb), 1)
    return kj <= qi


def _kernel(params_smem, q_hbm, kv_hbm, out_hbm, comm_hbm, q_vmem, kv_vmem,
            m_vmem, l_vmem, o_vmem, copy_sem, send_sem, recv_sem,
            credit_sem, *, axis_name: str, size: int, sb: int, d: int,
            scale: float, pipelined: bool, mesh_ids: bool,
            causal: bool = False, hq: int = 1, hkv: int = 1):
    """See module docstring for the step/slot/credit schedule.

    Multi-head layout (``hq`` query heads, ``hkv`` K/V heads — GQA when
    hkv < hq): the per-head [Sb, dh] planes are stacked along rows —
    q/out/m/l/o rows [h*Sb, (h+1)*Sb) belong to query head h; the
    circulating buffer stacks all K planes then all V planes
    ([hkv*Sb] + [hkv*Sb] rows), so ONE RDMA moves every head's K/V and
    the circulation/credit protocol is byte-identical to the
    single-head case (pure payload relabeling — AttentionSim's
    verification carries over unchanged)."""
    left = params_smem[0]
    right = params_smem[1]
    my = params_smem[2]
    P = size

    def dev_kw(target):
        if mesh_ids:
            return dict(device_id={axis_name: target},
                        device_id_type=pltpu.DeviceIdType.MESH)
        return dict(device_id=target,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)

    def fwd_rdma(u):
        """Send ``u`` (0..P-2): the block computed at step ``u`` moves
        to the right neighbor's slot ``(u+1) % 2``."""
        dst_slot = (u + 1) % 2
        src = kv_hbm if u == 0 else comm_hbm.at[u % 2]
        return pltpu.make_async_remote_copy(
            src_ref=src, dst_ref=comm_hbm.at[dst_slot],
            send_sem=send_sem.at[dst_slot], recv_sem=recv_sem.at[dst_slot],
            **dev_kw(right))

    def neighbor_barrier():
        if not pipelined:
            return
        bar = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(bar, inc=1, **dev_kw(left))
        pltpu.semaphore_signal(bar, inc=1, **dev_kw(right))
        pltpu.semaphore_wait(bar, 2)

    def load_kv(src_ref):
        cp = pltpu.make_async_copy(src_ref, kv_vmem, copy_sem)
        cp.start()
        cp.wait()

    def fold(a):
        def body(mask):
            g = hq // hkv  # query heads per K/V head (GQA group size)
            for h in range(hq):
                kvh = h // g
                rows = pl.ds(h * sb, sb)
                k = kv_vmem[pl.ds(kvh * sb, sb), :]
                v = kv_vmem[pl.ds((hkv + kvh) * sb, sb), :]
                m, l, o = _online_fold(q_vmem[rows, :], k, v,
                                       m_vmem[rows, :], l_vmem[rows, :],
                                       o_vmem[rows, :], scale, mask)
                m_vmem[rows, :] = m
                l_vmem[rows, :] = l
                o_vmem[rows, :] = o

        if not causal:
            body(None)
            return
        # arrival a carries K/V block (my - a) mod P; the first fold
        # (a=0, own block) always has its diagonal unmasked, so the
        # running max is finite from step 0 on.  Blocks entirely in the
        # future (kv_idx > my) contribute exactly zero — skip their MXU
        # passes outright (the circulation/credit schedule above is
        # untouched, so the model-checked protocol is unchanged).
        kv_idx = lax.rem(my - a + P, P)

        @pl.when(kv_idx <= my)
        def _():
            body(_causal_mask(my, kv_idx, sb))

    # init: Q to VMEM; online-softmax state
    cp_q = pltpu.make_async_copy(q_hbm, q_vmem, copy_sem)
    cp_q.start()
    cp_q.wait()
    m_vmem[:] = jnp.full((hq * sb, 1), -jnp.inf, jnp.float32)
    l_vmem[:] = jnp.zeros((hq * sb, 1), jnp.float32)
    o_vmem[:] = jnp.zeros((hq * sb, d), jnp.float32)

    neighbor_barrier()

    # step 0: my own block computes and starts circulating
    load_kv(kv_hbm)
    fold(0)
    if P >= 2:
        fwd_rdma(0).start()
        if pipelined:
            fwd_rdma(0).wait_send()  # sem hygiene, as in attention_program
        else:
            fwd_rdma(0).wait()

    for a in range(1, P):
        slot = a % 2
        if pipelined:
            fwd_rdma(a - 1).wait_recv()  # arrival a lands in comm[slot]
        load_kv(comm_hbm.at[slot])
        if a <= P - 2:
            # forward while the fold below runs; send a >= 2 first
            # waits for the credit arming its destination slot
            if pipelined:
                if a >= 2:
                    pltpu.semaphore_wait(credit_sem.at[(a + 1) % 2], 1)
                fwd_rdma(a).start()
            else:
                fwd_rdma(a).start()
                fwd_rdma(a).wait()
        fold(a)
        if pipelined and a <= P - 2:
            # slot free only after the forward READ it out (wait_send),
            # then credit the writer for arrival a+2's reuse
            fwd_rdma(a).wait_send()
        if pipelined and a + 2 <= P - 1:
            pltpu.semaphore_signal(credit_sem.at[slot], inc=1,
                                   **dev_kw(left))

    out = o_vmem[:] / l_vmem[:]
    out_vmem_cp = pltpu.make_async_copy(o_vmem, out_hbm, copy_sem)
    o_vmem[:] = out.astype(jnp.float32)
    out_vmem_cp.start()
    out_vmem_cp.wait()

    neighbor_barrier()


def _ring_neighbors(axis_name: str, size: int) -> jnp.ndarray:
    """[left, right, my] int32 SMEM params (my = causal block index)."""
    idx = lax.axis_index(axis_name)
    return jnp.stack([lax.rem(idx - 1 + size, size),
                      lax.rem(idx + 1, size), idx]).astype(jnp.int32)


def _fallback_attention(q, k, v, axis_name: str, size: int, scale: float,
                        causal: bool = False):
    """The same online-softmax ring as jax ops over ppermute — the
    vma/multi-axis interpreter path, and the recompute body of the
    custom-vjp backward.  Accepts both layouts ([Sb, d] and
    [H, Sb, d]); the multi-head ring rotates the WHOLE [Hkv, Sb, d]
    K/V stacks once per step (one ppermute pair per step, exactly like
    the kernel's single circulating RDMA) with per-head folds inside —
    NOT one ring per head (review round 4)."""
    multihead = q.ndim == 3
    q3 = q if multihead else q[None]
    k3 = k if multihead else k[None]
    v3 = v if multihead else v[None]
    hq, sb, d = q3.shape
    hkv = k3.shape[0]
    g = hq // hkv
    world_pairs = _world_pairs_of(size, None)
    perm = world_pairs([(r, (r + 1) % size) for r in range(size)])
    my = lax.axis_index(axis_name)
    m = [jnp.full((sb, 1), -jnp.inf, jnp.float32) for _ in range(hq)]
    l = [jnp.zeros((sb, 1), jnp.float32) for _ in range(hq)]
    o = [jnp.zeros((sb, d), jnp.float32) for _ in range(hq)]
    kb, vb = k3, v3
    for step in range(size):
        mask = None
        if causal:
            kv_idx = lax.rem(my - step + size, size)
            mask = _causal_mask(my, kv_idx, sb)  # shared by every head
        for h in range(hq):
            m[h], l[h], o[h] = _online_fold(q3[h], kb[h // g], vb[h // g],
                                            m[h], l[h], o[h], scale, mask)
        if step < size - 1:  # the last fold's blocks need no rotation
            kb = lax.ppermute(kb, axis_name, perm)
            vb = lax.ppermute(vb, axis_name, perm)
    out = jnp.stack([(o[h] / l[h]) for h in range(hq)]).astype(q.dtype)
    return out if multihead else out[0]


def pallas_ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          axis_name: str, size: int, *,
                          scale: float = None, causal: bool = False,
                          interpret: bool = False) -> jnp.ndarray:
    """Exact attention (full, or causal with ``causal=True``) over a
    sequence-sharded axis.  Two shapes:

    * single-head: ``q``/``k``/``v`` = this device's [Sb, dh] blocks;
    * multi-head / GQA: ``q`` = [Hq, Sb, dh], ``k``/``v`` =
      [Hkv, Sb, dh] with ``Hq % Hkv == 0`` — query head h attends K/V
      head ``h // (Hq//Hkv)`` (Hkv == Hq is classic multi-head,
      Hkv == 1 is MQA).  ALL heads ride ONE circulating RDMA per step.

    Returns this device's output block, shaped like ``q``.  Call inside
    shard_map over a mesh with ``axis_name``; the global sequence is
    the concatenation of the blocks in rank order.

    The compiled path is the in-kernel RDMA circulation described in
    the module docstring; ``interpret=True`` (the CPU tier) runs the
    serial same-kernel path, or — under vma typing / a multi-axis mesh
    — the ppermute fallback with the shared loud warning."""
    if q.ndim not in (2, 3):
        raise ValueError(
            f"ring attention wants [Sb, dh] or [H, Sb, dh] blocks, got "
            f"q {q.shape}")
    if k.shape != v.shape or q.shape[-2:] != k.shape[-2:] or \
            q.ndim != k.ndim:
        raise ValueError(
            f"ring attention wants equal [.., rows, d] blocks for q/k/v "
            f"(k/v may differ from q only in the HEAD count), got "
            f"{q.shape}/{k.shape}/{v.shape}")
    if k.dtype != q.dtype or v.dtype != q.dtype:
        raise ValueError(
            f"ring attention wants one dtype for q/k/v (the circulating "
            f"K/V buffer is allocated as q's), got "
            f"{q.dtype}/{k.dtype}/{v.dtype}")
    multihead = q.ndim == 3
    hq = q.shape[0] if multihead else 1
    hkv = k.shape[0] if multihead else 1
    if hkv < 1 or hq % hkv or hkv > hq:
        raise ValueError(
            f"GQA wants Hq a positive multiple of Hkv, got Hq={hq} "
            f"Hkv={hkv}")
    sb, d = q.shape[-2:]
    if d % _LANES:
        raise NotImplementedError(
            f"head dim must be a multiple of {_LANES} (lane width), got {d}")
    from .pallas_ring import _SUBLANES

    sub = _SUBLANES.get(jnp.dtype(q.dtype), 8)
    if sb % sub:
        raise NotImplementedError(
            f"block rows must be a multiple of {sub} ({jnp.dtype(q.dtype)} "
            f"sublane tile), got {sb}")
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    # shared dtype/vma/mesh probing with the ring collectives (f32/bf16)
    vma_on, multi_axis = _check_args(q, axis_name, size, sub, "sum")

    def _per_head(fn, q_, k_, v_):
        """Apply a [Sb,dh]-block function per query head (GQA maps
        query head h to K/V head h // (Hq//Hkv))."""
        if not multihead:
            return fn(q_, k_, v_)
        g = hq // hkv
        return jnp.stack([fn(q_[h], k_[h // g], v_[h // g])
                          for h in range(hq)])

    def _local_one(qh, kh, vh):
        m0 = jnp.full((sb, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((sb, 1), jnp.float32)
        o0 = jnp.zeros((sb, d), jnp.float32)
        mask = (_causal_mask(jnp.int32(0), jnp.int32(0), sb)
                if causal else None)
        _, l1, o1 = _online_fold(qh, kh, vh, m0, l0, o0, scale, mask)
        return (o1 / l1).astype(q.dtype)

    def _reference(q_, k_, v_):
        """Pure-jax ring (differentiable) — primal-identical to the
        kernel; the custom-vjp backward recomputes through it.  Only
        reached with size >= 2 (size == 1 returns below, before any
        _reference call site)."""
        return _fallback_attention(q_, k_, v_, axis_name, size, scale,
                                   causal)

    if size == 1:
        return _per_head(_local_one, q, k, v)
    if (vma_on or multi_axis) and interpret:
        _fallback("ring_attention", axis_name, vma_on, multi_axis)
        return _reference(q, k, v)

    def _kernel_call(q_, k_, v_):
        # flat multi-head layout (see _kernel docstring): q/out stack
        # query heads along rows; the circulating buffer stacks all K
        # planes then all V planes so one RDMA carries every head
        qf = q_.reshape(hq * sb, d) if multihead else q_
        kf = k_.reshape(hkv * sb, d) if multihead else k_
        vf = v_.reshape(hkv * sb, d) if multihead else v_
        kv = jnp.concatenate([kf, vf], axis=0)
        params = _ring_neighbors(axis_name, size)
        kern = functools.partial(
            _kernel, axis_name=axis_name, size=size, sb=sb, d=d,
            scale=scale, pipelined=not interpret, mesh_ids=multi_axis,
            causal=causal, hq=hq, hkv=hkv)
        compiler_params = None if interpret else pltpu.CompilerParams(
            collective_id=16, has_side_effects=True)
        if vma_on:
            try:
                in_vma = frozenset(jax.typeof(q_).vma)
            except (AttributeError, NameError):
                in_vma = frozenset()
            out_shape = jax.ShapeDtypeStruct((hq * sb, d), jnp.float32,
                                             vma=in_vma | {axis_name})
        else:
            out_shape = jax.ShapeDtypeStruct((hq * sb, d), jnp.float32)
        out = pl.pallas_call(
            kern,
            out_shape=out_shape,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pl.ANY),
                      pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pl.ANY((2, 2 * hkv * sb, d), q.dtype),   # landing slots
                pltpu.VMEM((hq * sb, d), q.dtype),       # Q (all heads)
                pltpu.VMEM((2 * hkv * sb, d), q.dtype),  # K/V staging
                pltpu.VMEM((hq * sb, 1), jnp.float32),   # m
                pltpu.VMEM((hq * sb, 1), jnp.float32),   # l
                pltpu.VMEM((hq * sb, d), jnp.float32),   # o
                pltpu.SemaphoreType.DMA(()),
                pltpu.SemaphoreType.DMA((2,)),           # send (parity)
                pltpu.SemaphoreType.DMA((2,)),           # recv (parity)
                pltpu.SemaphoreType.REGULAR((2,)),       # slot credits
            ],
            compiler_params=compiler_params,
            interpret=interpret,
        )(params, qf, kv)
        out = out.astype(q_.dtype)
        return out.reshape(hq, sb, d) if multihead else out

    # Differentiable wrapper: jax cannot autodiff through the kernel's
    # remote DMAs, so the backward RECOMPUTES through the pure-jax ring
    # (the flash-attention recompute strategy; ppermutes transpose to
    # the inverse rotation) — the fused kernel stays the forward hot
    # path and training can jax.grad straight through it.
    attn = jax.custom_vjp(_kernel_call)

    def _fwd(q_, k_, v_):
        return _kernel_call(q_, k_, v_), (q_, k_, v_)

    def _bwd(res, ct):
        q_, k_, v_ = res
        _, vjp = jax.vjp(_reference, q_, k_, v_)
        return vjp(ct)

    attn.defvjp(_fwd, _bwd)
    return attn(q, k, v)
