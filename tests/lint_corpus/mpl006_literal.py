"""Seeded bug: the send buffer is overwritten while the isend that
posted it may still be on the wire."""


def main(comm, buf):
    req = comm.isend(buf, 1, tag=0)
    buf[0] = 9.9
    req.wait()
