"""Near-miss twin: same variable tags, and they agree."""


def main(comm):
    t = 5
    if comm.rank == 0:
        comm.send(b"m", 1, tag=t)
    elif comm.rank == 1:
        return comm.recv(0, tag=t)
    return None
