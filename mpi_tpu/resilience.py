"""Link resilience: sequenced frames, cumulative acks, bounded replay.

The socket transport's failure story used to conflate two different
faults: a mid-send ``OSError`` (a *link* fault — TCP reset, a dropped
connection, an injected chaos event) raised the same ``TransportError``
as a dead peer, so a transient reset either killed the sender or, under
fault tolerance, shrank a perfectly healthy rank out of the world.
Production collective stacks separate the two (NCCL's transport retry,
UCX's error-handling endpoints): a **link** fault is healed
transparently by reconnecting and replaying what the peer did not
receive, while a **peer** fault keeps today's diagnosed
``ProcFailedError`` path.  This module is the transport-agnostic state
machine for the healing half; transport/socket.py does the wire surgery.

Design (the user-space analogue of the kernel TCP send buffer):

* every data frame to a destination carries a **per-destination
  sequence number** (monotone from 1, assigned in wire order under the
  per-dest send lock);
* the sender **retains each in-flight frame BY REFERENCE** in a bounded
  window (``link_window_bytes`` mpit cvar) until the receiver's
  **cumulative ack** covers it.  Acks are piggybacked on every data
  frame headed the other way and flushed by a per-transport idle
  flusher, so one-way streams are acked too.  ISSUE 10 snapshotted
  every body into flat ``bytes`` here (a full memcpy per frame — the
  resilience price the zero-copy plane paid on its default path);
  ISSUE 11 replaced the snapshot with a refcounted
  :class:`mpi_tpu.bufpool.BufRef` over the caller's buffers,
  **copy-on-write** only when the ownership layer sees the region
  reused while unacked (fold sites, conflicting sends, write-buffer
  posts — see bufpool.py for the borrow contract and the
  ``link_retain_copy`` cvar that restores eager snapshots).
  ``link_bytes_retained`` still counts every retained byte (retention
  pins memory and bounds replay time whether or not it copied);
  ``link_cow_snapshots``/``link_cow_bytes`` price exactly the copies
  reuse forced;
* the receiver **dedups by (src, seq)**: only the next contiguous
  sequence is delivered, anything at-or-below the high-water mark is a
  replay duplicate and dropped, and a *gap* is a protocol error (TCP
  FIFO + replay-from-last-delivered make it impossible in a healthy
  stream), answered loudly rather than by silent reordering;
* the connection handshake's hello-ack carries ``resume(last
  delivered seq)``, so a rebuilt connection prunes the acked prefix of
  the retained window and **replays only unacked frames** — frames are
  neither lost nor duplicated across a teardown.

What this module does NOT decide: when to reconnect and what a fault
means.  Classification lives with the transport (transport/socket.py
``_heal_link_locked``): a peer in the FT suspect set — or past its
heartbeat bound, ``mpi_tpu.ft.WorldFT.link_suspect`` — keeps the
ProcFailedError path unchanged; everything else enters a reconnect
loop with exponential backoff + jitter bounded by the
``link_retry_timeout_s`` cvar, whose default sits BELOW
``fault_detect_timeout_s`` so a genuinely dead peer still resolves to
``ProcFailedError`` and is never masked into a hang.

The shm transport has no link-fault class on purpose: its "link" is a
mapped shared-memory ring — memory does not reset mid-frame, and every
shm fault is already a peer/process fault (README "Failure semantics").
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, Iterator, List, Optional, Tuple

from . import bufpool as _bufpool
from . import mpit as _mpit
from . import telemetry as _telemetry
from .transport.base import TransportError

# Reconnect budget for ONE link fault: total time the sender may spend
# re-establishing a torn connection (and the no-ack-progress bound of a
# full retained window) before the fault is promoted to a peer fault
# (TransportError -> ProcFailedError under FT).  Deliberately below the
# fault_detect_timeout_s default (5s): a dead peer must resolve to the
# DIAGNOSED path, never to a masked retry hang.  0 disables healing
# entirely (every link fault is terminal — the pre-resilience behavior,
# and the honest "pre" leg of bench.py --chaos --links).
# mpit cvar: link_retry_timeout_s; env default: MPI_TPU_LINK_RETRY_S.
_RETRY_TIMEOUT_S = float(os.environ.get("MPI_TPU_LINK_RETRY_S", "4.0"))

# Retained-window ceiling per destination: sends block (in FT-checked
# slices) once this many unacked bytes are outstanding, and a window
# that makes no ack progress for link_retry_timeout_s is itself a link
# verdict.  A single frame larger than the window is allowed once the
# window is otherwise empty (the classic streaming-window rule).
# mpit cvar: link_window_bytes; env default: MPI_TPU_LINK_WINDOW_BYTES.
_WINDOW_BYTES = int(os.environ.get("MPI_TPU_LINK_WINDOW_BYTES",
                                   str(64 << 20)))

# Eager-snapshot escape hatch (ISSUE 11): 1 restores ISSUE 10's
# copy-at-retain semantics wholesale — strict MPI buffered-send
# reusability with zero caller obligations, at one memcpy per frame.
# Default 0: retain by reference, copy-on-write on proven reuse.
# mpit cvar: link_retain_copy; env default: MPI_TPU_LINK_RETAIN_COPY.
_RETAIN_COPY = int(os.environ.get("MPI_TPU_LINK_RETAIN_COPY", "0"))

# Idle-link keepalive cadence (ISSUE 11 satellite, closes PR-10
# residual (b)): the ack flusher probes every cached connection that
# has sent nothing for this long with a header-only ack frame, so a
# link torn while IDLE (peer-side reset after our last sendall
# returned) is discovered and healed by the probe instead of adding a
# reconnect latency spike to the next real send.  0 disables probing.
# Only meaningful with healing enabled (link_retry_timeout_s > 0).
# mpit cvar: link_keepalive_s; env default: MPI_TPU_LINK_KEEPALIVE_S.
_KEEPALIVE_S = float(os.environ.get("MPI_TPU_LINK_KEEPALIVE_S", "1.0"))

# Initial-connect retry budget for control-plane clients
# (serve.ServerClient / mpi_tpu.connect): ConnectionRefusedError is
# retried with the same backoff schedule for this long — the server may
# simply still be binding.  0 restores first-failure raise.
# mpit cvar: connect_retry_timeout_s; env: MPI_TPU_CONNECT_RETRY_S.
_CONNECT_RETRY_TIMEOUT_S = float(
    os.environ.get("MPI_TPU_CONNECT_RETRY_S", "10.0"))

# Backoff schedule shape (shared by link reconnect and client connect):
# exponential with full jitter, capped.  Values are generous for a
# loopback box; the cap keeps a long outage polling at a human cadence.
_BACKOFF_BASE_S = 0.02
_BACKOFF_FACTOR = 2.0
_BACKOFF_CAP_S = 0.5

_WINDOW_POLL_S = 0.05  # slice of the window-full wait (FT re-checks)


def backoff_delays(base: float = _BACKOFF_BASE_S,
                   factor: float = _BACKOFF_FACTOR,
                   cap: float = _BACKOFF_CAP_S,
                   rng: Optional[random.Random] = None) -> Iterator[float]:
    """Endless exponential-backoff-with-full-jitter schedule: the k-th
    delay is uniform in [0, min(cap, base * factor**k)].  Full jitter
    (AWS-style) rather than +/- fuzz: simultaneous retriers (every rank
    of a world saw the same reset) must not reconverge on the same
    retry instants."""
    rng = rng or random
    ceiling = base
    while True:
        yield rng.uniform(0.0, ceiling)
        ceiling = min(cap, ceiling * factor)


# Transient dial failures retry_connect heals by waiting (ISSUE 15
# satellite, extending the refused-only ISSUE 10 rule):
# * ConnectionRefusedError — the server is still binding (or, in a
#   federation failover, a just-elected leader has not accept()ed yet);
# * TimeoutError (socket.timeout) — the connect itself timed out, the
#   SYN-swallowed flavor of the same race (a dying server's listener
#   can absorb the handshake without completing it).
# Anything else (unroutable host, protocol error, reset mid-dial with
# no listener coming back) propagates immediately — not healed by
# patience.
TRANSIENT_DIAL_ERRORS = (ConnectionRefusedError, TimeoutError)


def retry_connect(dial: Callable[[], "object"],
                  timeout_s: Optional[float] = None,
                  rng: Optional[random.Random] = None,
                  retry_on: Tuple[type, ...] = TRANSIENT_DIAL_ERRORS):
    """Run ``dial()`` (a socket factory) retrying the transient dial
    failures in ``retry_on`` (default :data:`TRANSIENT_DIAL_ERRORS`)
    with backoff + jitter for up to ``timeout_s`` (default: the
    connect_retry_timeout_s cvar).  A refused or timed-out connect is
    the server-still-binding race — during federation failover a
    just-elected server publishing its endpoint record loses that race
    routinely; any OTHER failure propagates immediately (an unroutable
    host or a protocol error is not healed by patience)."""
    budget = _CONNECT_RETRY_TIMEOUT_S if timeout_s is None else timeout_s
    deadline = time.monotonic() + budget
    delays = backoff_delays(rng=rng)
    while True:
        try:
            return dial()
        except retry_on:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise
            time.sleep(min(next(delays), remaining))


class _TxState:
    """Per-destination sender stream: next seq, the retained unacked
    frames (seq, header word, body :class:`bufpool.BufRef`), and the
    cumulative ack high-water mark received back from the peer."""

    __slots__ = ("seq", "acked", "retained", "retained_bytes",
                 "was_connected")

    def __init__(self) -> None:
        self.seq = 0          # last sequence number assigned
        self.acked = 0        # highest cumulative ack received
        self.retained: Deque[Tuple[int, int, _bufpool.BufRef]] = deque()
        self.retained_bytes = 0
        # whether a connection to this destination was ever established:
        # distinguishes a RE-connect (counted in link_reconnects) from
        # the world's initial connection setup
        self.was_connected = False


class _RxState:
    """Per-source receiver stream: the contiguous-delivery high-water
    mark and the ack bookkeeping the flusher consults."""

    __slots__ = ("delivered", "ack_sent")

    def __init__(self) -> None:
        self.delivered = 0    # highest contiguously delivered seq
        self.ack_sent = 0     # highest ack value put on the wire


class LinkState:
    """The per-transport resilience state: one tx stream per
    destination, one rx stream per source, a condition variable for the
    retained-window waiters and the ack flusher.  All methods are
    thread-safe; wire-order-sensitive ones (seq assignment, resume)
    additionally require the transport's per-dest send lock, which is
    what serializes writes to one connection anyway."""

    def __init__(self, world_size: int) -> None:
        self._tx: Dict[int, _TxState] = {}
        self._rx: Dict[int, _RxState] = {}
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # sources with delivered > ack_sent (the flusher's work list)
        self._ack_pending: set = set()
        # per-peer STREAM GENERATION, bumped by purge_peer: a reader
        # thread still draining a replaced slot's old connection
        # captures the generation at handshake time, and its acks/
        # frames are dropped once the slot was purged — otherwise one
        # stale piggybacked ack (e.g. 57) applied to the replacement's
        # fresh tx stream would make every real ack (1, 2, ...) read
        # as stale, the retained window would never prune, and a
        # HEALTHY rejoiner would be declared link-dead.
        self._gen: Dict[int, int] = {}
        self._closed = False

    # -- tiny accessors ----------------------------------------------------

    def _tx_of(self, dest: int) -> _TxState:
        st = self._tx.get(dest)
        if st is None:
            st = self._tx[dest] = _TxState()
        return st

    def _rx_of(self, src: int) -> _RxState:
        st = self._rx.get(src)
        if st is None:
            st = self._rx[src] = _RxState()
        return st

    def delivered(self, src: int) -> int:
        """Contiguous-delivery high-water mark for ``src`` — what the
        hello-ack's resume field reports to a (re)connecting peer."""
        with self._lock:
            return self._rx_of(src).delivered

    def peer_gen(self, rank: int) -> int:
        """Current stream generation of ``rank`` (see ``_gen``): reader
        threads capture it at handshake and present it with every
        ack/frame, so a purge invalidates them wholesale."""
        with self._lock:
            return self._gen.get(rank, 0)

    def rx_fresh(self, src: int, seq: int, gen: int) -> bool:
        """True iff a data frame ``(seq, gen)`` from ``src`` is the next
        in-sequence frame of the CURRENT stream generation — exactly the
        frames ``rx_gate`` will deliver, in delivery order.  The recv-
        steering registry (mpi_tpu/recvpool.py) gates its arrival
        counting on this so duplicates, stale generations, and gap
        frames never advance a channel's pairing index; its per-channel
        watermark closes the remaining race of two connections
        presenting the same fresh frame concurrently."""
        with self._lock:
            if gen != self._gen.get(src, 0):
                return False
            st = self._rx.get(src)
            return seq == (st.delivered if st is not None else 0) + 1

    def retained_bytes(self, dest: int) -> int:
        with self._lock:
            return self._tx_of(dest).retained_bytes

    def mark_connected(self, dest: int) -> bool:
        """Record an established connection; True iff this replaced an
        EARLIER established one (i.e. a reconnect, not initial setup)."""
        with self._lock:
            st = self._tx_of(dest)
            was = st.was_connected
            st.was_connected = True
            return was

    # -- sender side -------------------------------------------------------

    def wait_window(self, dest: int, nbytes: int,
                    suspect: Callable[[int], bool],
                    closing: Callable[[], bool]) -> None:
        """Block until ``nbytes`` more retained bytes fit the window (or
        the window is empty — one oversized frame may always proceed).
        Re-checks the FT suspect verdict every slice and bounds the
        no-ack-progress wait by link_retry_timeout_s: a peer that stops
        acking for that long IS a link verdict, promoted to
        TransportError here (-> ProcFailedError under FT).

        With healing DISABLED (link_retry_timeout_s = 0) there is no
        window at all: frames are not retained (socket.py streams them
        directly, the pre-resilience path), so enforcing a floor here
        would declare a healthy link dead on any 100ms receiver stall
        — the kernel socket buffer is the only backpressure, exactly
        as before this layer existed."""
        if _RETRY_TIMEOUT_S <= 0:
            return
        deadline = time.monotonic() + _RETRY_TIMEOUT_S
        with self._cv:
            while True:
                st = self._tx_of(dest)
                if (st.retained_bytes == 0
                        or st.retained_bytes + nbytes <= _WINDOW_BYTES):
                    return
                if self._closed or closing():
                    raise TransportError(
                        "transport closed while waiting for link window")
                progress_mark = st.acked
                self._cv.wait(_WINDOW_POLL_S)
                if st.acked > progress_mark:
                    deadline = time.monotonic() + _RETRY_TIMEOUT_S
                    continue
                if suspect(dest):
                    raise TransportError(
                        f"peer {dest} declared failed while its link "
                        f"window was full ({st.retained_bytes} unacked "
                        f"bytes)")
                if time.monotonic() > deadline:
                    rec = _telemetry.REC
                    if rec is not None:
                        rec.emit("link", "window_stall",
                                 attrs={"peer": dest,
                                        "retained_bytes":
                                        st.retained_bytes})
                    raise TransportError(
                        f"link to rank {dest}: no ack progress for "
                        f"{_RETRY_TIMEOUT_S}s with {st.retained_bytes} "
                        f"retained bytes (window {_WINDOW_BYTES}); "
                        f"declaring the link dead")

    def tx_retain(self, dest: int, word: int, body) -> int:
        """Assign the next sequence number for ``dest`` and retain the
        frame body — a :class:`bufpool.BufRef` (by-reference views of
        the caller's buffers, ISSUE 11) or raw ``bytes`` (wrapped into
        an immutable ref; unit tests and pickle blobs) — until acked.
        Caller holds the per-dest send lock (seq order must equal wire
        order)."""
        if not isinstance(body, _bufpool.BufRef):
            body = _bufpool.BufRef([bytes(body)], register=False)
        with self._lock:
            st = self._tx_of(dest)
            st.seq += 1
            st.retained.append((st.seq, word, body))
            st.retained_bytes += body.nbytes
            _mpit.count(link_bytes_retained=body.nbytes)
            return st.seq

    def tx_next_seq(self, dest: int) -> int:
        """Sequence-only assignment (healing disabled): the receiver
        still requires contiguous seqs, but nothing is retained —
        there is no replay to feed.  Caller holds the send lock."""
        with self._lock:
            st = self._tx_of(dest)
            st.seq += 1
            return st.seq

    def tx_ack(self, dest: int, ack: int,
               gen: Optional[int] = None) -> None:
        """Apply a cumulative ack from ``dest`` (piggybacked or
        standalone): prune the retained prefix, wake window waiters.
        Acks are monotone; a stale value (a replayed header) is a
        no-op.  ``gen`` is the reader's captured stream generation —
        an ack arriving on a connection from a since-purged (replaced)
        incarnation is dropped whole, not applied to the
        replacement's fresh stream."""
        with self._cv:
            if gen is not None and gen != self._gen.get(dest, 0):
                return
            st = self._tx_of(dest)
            if ack <= st.acked:
                return
            st.acked = ack
            retained = st.retained
            while retained and retained[0][0] <= ack:
                _, _, body = retained.popleft()
                st.retained_bytes -= body.nbytes
                body.release()  # unpins the caller's buffer + ranges
            self._cv.notify_all()

    def resume(self, dest: int, last_delivered: int
               ) -> List[Tuple[int, int, _bufpool.BufRef]]:
        """Reconnect-time resume: the peer reported the last seq it
        delivered from us — treat it as an ack (frames at or below it
        arrived; replaying them would only be dropped as dups) and
        return the retained frames BEYOND it for replay, in seq order.
        Caller holds the per-dest send lock."""
        self.tx_ack(dest, last_delivered)
        with self._lock:
            return list(self._tx_of(dest).retained)

    # -- receiver side -----------------------------------------------------

    def rx_gate(self, src: int, seq: int, deliver: Callable[[], None],
                gen: Optional[int] = None) -> bool:
        """Deliver-or-drop decision for an arriving data frame, atomic
        with the delivery itself (two reader threads of one src — the
        dying connection's and its replacement's — may race here, and
        FIFO into the mailbox must follow seq order).  Returns True iff
        delivered.  A frame arriving on a since-purged (replaced)
        incarnation's connection (``gen`` mismatch) is dropped whole —
        its stream died with the slot.  A seq GAP is a protocol
        violation (impossible under TCP FIFO + resume-replay): raised
        loudly, never reordered around."""
        with self._cv:
            if gen is not None and gen != self._gen.get(src, 0):
                return False
            st = self._rx_of(src)
            if seq <= st.delivered:
                return False  # replay duplicate: already delivered
            if seq != st.delivered + 1:
                raise TransportError(
                    f"sequence gap from rank {src}: got frame {seq}, "
                    f"expected {st.delivered + 1} — sequenced-link "
                    f"protocol violation")
            deliver()
            st.delivered = seq
            if st.delivered > st.ack_sent:
                self._ack_pending.add(src)
                self._cv.notify_all()
            return True

    def peek_ack(self, src: int) -> Optional[int]:
        """The ack value a standalone ACK frame to ``src`` should carry
        right now, or None when the peer already has it."""
        with self._lock:
            st = self._rx_of(src)
            return st.delivered if st.delivered > st.ack_sent else None

    def note_ack_sent(self, src: int, value: int) -> None:
        """Record ``value`` as on the wire (call AFTER the send
        succeeded — an optimistic mark on a failed send would starve
        the peer's window)."""
        with self._lock:
            st = self._rx_of(src)
            if value > st.ack_sent:
                st.ack_sent = value
            if st.ack_sent >= st.delivered:
                self._ack_pending.discard(src)

    def piggyback_ack(self, src: int) -> int:
        """Ack value to stamp into a data frame headed to ``src``.
        Deliberately does NOT mark it sent — the frame may still fail
        and be replayed with a fresher value; the flusher's standalone
        ack is simply skipped by the peer's monotone tx_ack if the
        piggyback beat it."""
        with self._lock:
            return self._rx_of(src).delivered

    def wait_ack_pending(self, timeout: float) -> List[int]:
        """Flusher park: block until some source has undelivered acks
        (or timeout); returns the pending sources (cleared lazily by
        note_ack_sent)."""
        with self._cv:
            if not self._ack_pending and not self._closed:
                self._cv.wait(timeout)
            return sorted(self._ack_pending)

    # -- membership / lifecycle -------------------------------------------

    def purge_peer(self, rank: int) -> None:
        """Slot replacement (membership_invalidate): the old
        incarnation's sequenced streams die with it.  Dropping the tx
        state discards its retained replay window (a rejoiner must
        NEVER see a stale replay: its streams start at seq 1) and
        resets our seq; dropping the rx state accepts the
        replacement's fresh stream from 1.  The generation bump
        invalidates every reader thread still draining the OLD
        incarnation's connections (their captured gen goes stale, so
        their acks/frames no-op instead of poisoning the fresh
        streams)."""
        with self._cv:
            st = self._tx.pop(rank, None)
            self._rx.pop(rank, None)
            self._ack_pending.discard(rank)
            self._gen[rank] = self._gen.get(rank, 0) + 1
            gen = self._gen[rank]
            self._cv.notify_all()
        rec = _telemetry.REC
        if rec is not None:
            rec.emit("link", "purge", attrs={"peer": rank, "gen": gen})
        if st is not None:
            for _, _, body in st.retained:
                body.release()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            states = list(self._tx.values())
            self._cv.notify_all()
        # free the retained windows: the refs pin caller buffers (and
        # veto codec.RECV_POOL recycling) for exactly as long as a
        # replay could still need them — which is never, once closed
        for st in states:
            for _, _, body in st.retained:
                body.release()
