#!/usr/bin/env python
"""Mechanical "no worse than seed" guard for the tier-1 suite.

The ROADMAP's tier-1 verify line already computes ``DOTS_PASSED`` (the
count of passed-test dots in the pytest progress output); this tool turns
the eyeball comparison into an exit code: parse a tier-1 log, count the
dots exactly the way the verify line does, and fail if the count dropped
below the committed baseline in ``tests/baseline_count.json``.

Usage::

    # after running the tier-1 verify line with `tee /tmp/_t1.log`:
    python tools/tier1_guard.py /tmp/_t1.log            # enforce
    python tools/tier1_guard.py /tmp/_t1.log --update   # re-baseline

``--update`` rewrites the baseline from the given log — run it only when
a PR legitimately grows the suite (the new count becomes the next PR's
floor).  The baseline file also records the failed count for context,
but only the passed floor is enforced: a PR that adds tests may add
known-drift failures to the environment-dependent tail, while losing
previously-passing tests is always a regression.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

BASELINE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "baseline_count.json")

# the verify line's grep: progress lines are runs of . F E s x, optionally
# suffixed by a [ NN%] marker
_PROGRESS = re.compile(r"^[.FEsx]+( *\[ *[0-9]+%\])?$")


def count_dots(log_path: str) -> dict:
    passed = failed = errors = skipped = 0
    with open(log_path, "rb") as f:
        for raw in f:
            line = raw.decode("utf-8", "replace").rstrip("\n")
            if _PROGRESS.match(line):
                passed += line.count(".")
                failed += line.count("F")
                errors += line.count("E")
                skipped += line.count("s") + line.count("x")
    return {"dots_passed": passed, "dots_failed": failed,
            "dots_errors": errors, "dots_skipped": skipped}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("log", help="tier-1 pytest log (the tee'd verify output)")
    ap.add_argument("--baseline", default=BASELINE)
    ap.add_argument("--update", action="store_true",
                    help="rewrite the baseline from this log")
    args = ap.parse_args(argv)

    counts = count_dots(args.log)
    if counts["dots_passed"] == 0:
        print(f"tier1_guard: no pytest progress lines found in {args.log} "
              f"(wrong file, or the run crashed before collecting?)")
        return 2
    if args.update:
        with open(args.baseline, "w") as f:
            json.dump(counts, f, indent=2)
            f.write("\n")
        print(f"tier1_guard: baseline updated: {counts}")
        return 0
    try:
        with open(args.baseline) as f:
            base = json.load(f)
    except FileNotFoundError:
        print(f"tier1_guard: no baseline at {args.baseline}; run with "
              f"--update once to record one")
        return 2
    floor = int(base["dots_passed"])
    got = counts["dots_passed"]
    print(f"tier1_guard: DOTS_PASSED={got} (baseline floor {floor}, "
          f"failed {counts['dots_failed']})")
    if got < floor:
        print(f"tier1_guard: FAIL — passed count dropped below the "
              f"committed baseline ({got} < {floor})")
        return 1
    print("tier1_guard: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
