"""Shared-memory collective arena (coll/sm) — one-copy intra-node collectives.

The segmented zero-copy engine (ISSUE 1-2) made every collective byte ride
raw frames, but on the shm transport each byte still takes TWO memcpys
through a per-pair SPSC ring plus a futex doorbell per frame.  Between
co-located ranks the interconnect IS shared memory, so the proven fix from
production MPI stacks (MPICH's ``coll/sm``, Open MPI's HAN hierarchy) is to
map one POSIX shared-memory **arena** per communicator that ranks load and
store directly:

* layout — P per-rank 64-byte **flag lines** (a monotone sequence counter
  per rank: the generalized sense-reversing barrier, posted with release
  semantics and awaited with acquire semantics by the native ``shmflag_*``
  ops in native/shmring.cpp) followed by P data **slots** (a tiny
  length-prefixed meta pickle, then raw payload bytes at a 64-byte-aligned
  offset);
* small payloads (≤ the ``coll_sm_eager_bytes`` cvar) take the **flat**
  single-copy path: write own slot → barrier → read peers' slots in place
  (bcast/reduce/allreduce/allgather; barrier is the flag round alone) — no
  frames, no pickling of payload bytes, no doorbells;
* large allreduce/reduce_scatter take the **block in-place** path: write
  own payload → barrier → each rank folds its assigned chunk (the shared
  ``schedules.chunk_offsets`` table) reading peers' blocks *in place from
  the arena* with ``op.combine_into`` — one copy in, one copy out, versus
  the ring's per-hop memcpys;
* every payload-bearing entry writes a meta word first and the whole group
  **negotiates inside the arena**: if any rank's payload cannot ride it
  (not a plain ndarray, larger than a slot, mismatched geometry for a
  reduction), all ranks observe the same metas after the entry barrier and
  fall back to the classic wire algorithms together (counted in the
  ``coll_sm_fallbacks`` pvar) — which is what lets ``algorithm="auto"``
  route to the arena even for bcast (payload known only at the root) and
  ragged allgather without any rank-divergent choice;
* arena waits run in the same ~50ms slices as the segmented engine's
  ``_seg_exchange``: with fault tolerance enabled a dead rank surfaces as
  ``ProcFailedError`` inside ``fault_detect_timeout_s`` (and a revocation
  as ``RevokedError``) instead of deadlocking a barrier; without FT the
  wait is bounded by ``recv_timeout`` / the shm stall constant.

Observability: ``coll_sm_hits`` / ``coll_sm_bytes`` / ``coll_sm_fallbacks``
mpit pvars; arena copy-in/copy-out passes count into ``payload_copies`` so
the ≤2-copies-per-rank contract is assertable, and zero ring frames /
zero pickled payload bytes are provable from the untouched ``msgs_sent`` /
``bytes_pickled_sent`` counters (tests/test_coll_sm.py).

Lifecycle: the communicator's rank 0 creates the segment (named from the
transport session + communicator context, so the launcher's crash-path
glob sweeps orphans), peers open-and-wait like the ring handshake; handles
are refcounted in the module ``_LIVE`` registry (pruned like mpi4's
``_CFG_GENERATIONS``) and closed — creator unlinking the name — when the
transport closes at world finalize.
"""

from __future__ import annotations

import ctypes
import functools
import hashlib
import os
import pickle
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import mpit as _mpit
from . import schedules
from . import telemetry as _telemetry
from . import tuning as _tuning
from .transport import codec as _codec
from .transport.base import ANY_SOURCE, RecvTimeout, TransportError

# Sentinel: the arena declined this payload (after keeping the group in
# lockstep); the caller runs the classic wire algorithm.
FALLBACK = object()

# Arena size per communicator (mpit cvar ``coll_sm_arena_bytes``; 0
# disables the arena entirely — the kill switch).  Each rank's slot is
# the P-th share after the flag lines, so the largest payload the
# in-place block paths take is ~arena/P.
_ARENA_BYTES = 8 << 20
# Flat-path gate (mpit cvar ``coll_sm_eager_bytes``): reductions at or
# below this read every peer's slot whole (latency-optimal, P·N loads);
# above it allreduce folds per-chunk in place (bandwidth-optimal).
_EAGER_BYTES = 32 << 10

_LINE = 64          # flag line stride (cache-line separation)
_META_MAX = 256     # per-slot meta region: u32 length + meta pickle
_META_LEN = struct.Struct("<I")
_SLICE_S = 0.05     # FT/teardown re-check cadence of arena waits
_OPEN_TIMEOUT = 60.0

_KIND_NONE = 0      # "my payload cannot ride the arena" (or no payload)
_KIND_DATA = 1
_KIND_WIRE = 2      # wire-encoded payload (ISSUE 8: compressed slot
#                     writes, fold-dtype folds — see allreduce_wire)

# name -> {"refs": int, "creator": bool} — the _CFG_GENERATIONS-style
# registry: locked, refcounted, pruned as handles close; lets tests
# assert unlink-at-finalize and makes accidental double-creation loud.
_LIVE: Dict[str, Dict[str, Any]] = {}
_LIVE_LOCK = threading.Lock()


def gate(comm) -> Tuple[str, ...]:
    """The extra ``algorithm=`` names this communicator's transport
    earns: ``("sm",)`` on an arena-capable (shm) transport, ``()``
    otherwise — so socket worlds reject ``"sm"`` with the standard
    unknown-algorithm gate error."""
    return ("sm",) if getattr(comm._t, "supports_coll_sm", False) else ()


def arena_for(comm) -> Optional["Arena"]:
    """This communicator's arena, created collectively on first use; None
    when the arena cannot serve it (socket/local transport, size-1 group,
    a nonblocking-collective clone, or the cvar kill switch).  A
    communicator stamped with ``_coll_sm_pool_ctx`` (serve lease comms,
    ISSUE 11) resolves through the transport-level POOL instead: one
    epoch-stamped arena per worker set, reused across leases."""
    if _ARENA_BYTES <= 0 or comm.size < 2:
        return None
    if not getattr(comm._t, "supports_coll_sm", False):
        return None
    if getattr(comm, "_no_coll_sm", False):
        return None
    arena = comm.__dict__.get("_coll_sm_arena")
    if arena is None:
        pool_ctx = getattr(comm, "_coll_sm_pool_ctx", None)
        if pool_ctx is not None:
            arena = _pooled_arena(comm, pool_ctx)
        else:
            arena = Arena(comm)
        comm._coll_sm_arena = arena
    return arena


def _pooled_arena(comm, pool_ctx: Tuple) -> "Arena":
    """Arena reuse across serve leases (ISSUE 11 tentpole #3, closes
    PR-7 residual (a)): lease communicators get fresh contexts per job,
    so routing them through the per-communicator path would map (and
    unlink) a multi-MB /dev/shm segment PER LEASE — which is why leases
    skipped the arena tier entirely.  Instead the arena is keyed
    ``(pool_ctx, worker set)`` in the transport's ``_coll_arenas``
    registry (the same dict world finalize already tears down) and
    survives lease teardown: the next lease over the same workers
    remaps NOTHING and rides the warm one-copy tier.

    ``pool_ctx`` carries the pool's membership EPOCH as granted by the
    server with the lease (one value for the whole group — a local
    ``t.epoch`` read could race a concurrent transition broadcast and
    split the group across two segment names).  An epoch bump after a
    worker death retires the old segment: the first same-group lease
    under the new epoch closes the stale arena (the creator unlinks)
    and builds a fresh one the replacement worker can map.  Barrier
    sequence state lives in the mapped flag lines themselves (each
    rank resumes from its own posted value — see Arena.__init__), so a
    rank that re-attaches stays in lockstep with peers that kept their
    handles."""
    t = comm._t
    pool = t._coll_arenas = getattr(t, "_coll_arenas", {})
    key = (pool_ctx, comm._group)
    arena = pool.get(key)
    if arena is not None and not arena._closed:
        return arena
    # retire stale same-group arenas from older epochs: survivors hold
    # handles to a segment the replacement worker must never map
    for (ctx2, grp2) in list(pool):
        if (grp2 == comm._group and ctx2 != pool_ctx
                and isinstance(ctx2, tuple) and ctx2[:1] == pool_ctx[:1]):
            # force_unlink: the stale segment's CREATOR may be exactly
            # the dead worker this epoch bump mourned — without it the
            # multi-MB /dev/shm segment would outlive every handle
            pool.pop((ctx2, grp2)).close(force_unlink=True)
    return Arena(comm, ctx=pool_ctx)


def _arena_name(session: str, ctx, group) -> str:
    """/dev/shm name of one communicator's arena.  Digest of the context
    AND the member group (contexts are nested tuples — deterministic repr
    across ranks): disjoint split() children deliberately share a context
    (the mailbox disambiguates by source, so the wire never collides),
    but each needs its OWN arena — the group is what tells node 0's intra
    communicator from node 1's.  The session prefix keeps the name inside
    the launcher's crash-cleanup glob (transport/shm.py shm_prefix)."""
    from .transport.shm import shm_prefix

    digest = hashlib.sha1(repr((ctx, tuple(group))).encode()).hexdigest()[:16]
    return f"/{shm_prefix(session)}arena_{digest}"


class Arena:
    """One mapped collective arena: flag lines + data slots + the sliced
    flag-wait that converts peer death into ProcFailedError."""

    def __init__(self, comm, ctx=None):
        from .native import load_shmring

        t = comm._t
        self._lib = load_shmring()
        p = comm.size
        self._p = p
        self._rank = comm.rank
        slot = ((_ARENA_BYTES - _LINE * p) // p) // _LINE * _LINE
        if slot < _META_MAX + _LINE:
            raise TransportError(
                f"coll_sm_arena_bytes={_ARENA_BYTES} too small for {p} "
                f"ranks (slot would be {slot} bytes)")
        self.slot_bytes = slot
        self.capacity = slot - _META_MAX  # payload bytes per slot
        nbytes = _LINE * p + slot * p
        # ``ctx`` overrides the naming/registration context: pooled
        # lease arenas (ISSUE 11) must share one name across leases
        # whose communicator contexts differ per job.  Pooled arenas
        # also retire differently at finalize (retire_pooled): their
        # creator may be a long-dead worker, so EVERY closing handle
        # unlinks, not just the creator's.
        self._pooled = ctx is not None
        if ctx is None:
            ctx = comm._ctx
        self.name = _arena_name(t._session, ctx, comm._group)
        self._creator = comm.rank == 0
        with _LIVE_LOCK:
            ent = _LIVE.setdefault(self.name, {"refs": 0, "creator": False})
            if self._creator:
                if ent["creator"]:
                    raise RuntimeError(
                        f"concurrent creation of arena {self.name!r} "
                        f"(two communicators resolved the same context?)")
                ent["creator"] = True
            ent["refs"] += 1
        name_b = self.name.encode()
        # Rendezvous handshake, exactly like the rings (shm.py
        # _out_ring_locked): the creator publishes a readiness file in
        # the rendezvous dir AFTER creating the segment, and openers
        # wait for THAT file, not for the name to appear in /dev/shm.
        # Without it an opener can map a STALE segment (a crashed
        # earlier run with the same session basename — ranks that died
        # without closing leave the name behind) in the window before
        # the creator's unlink+recreate, leaving the group split across
        # two segments that share one name: a silent barrier deadlock.
        rdv = getattr(t, "_rdv", None)
        flag = (None if rdv is None else
                os.path.join(rdv, "arena." + self.name.rsplit("_", 1)[-1]))
        timeout = getattr(t, "_connect_timeout", _OPEN_TIMEOUT)
        self._flag_file = flag if comm.rank == 0 else None
        if self._creator:
            self._ptr = self._lib.shmarena_create(name_b, nbytes)
            if self._ptr and flag is not None:
                try:
                    tmp = flag + f".tmp.{os.getpid()}"
                    with open(tmp, "w") as f:
                        f.write("ready")
                    os.replace(tmp, flag)
                except OSError:
                    pass  # rdv dir tearing down — openers wait on magic
        else:
            if flag is not None:
                deadline = time.monotonic() + timeout
                while not os.path.exists(flag):
                    if time.monotonic() > deadline:
                        break  # fall through: open-by-magic still bounded
                    time.sleep(0.002)
            self._ptr = self._lib.shmarena_open(name_b, timeout)
        if not self._ptr:
            with _LIVE_LOCK:
                ent = _LIVE.get(self.name)
                if ent:
                    ent["refs"] -= 1
                    if self._creator:
                        ent["creator"] = False
                    if ent["refs"] <= 0:
                        _LIVE.pop(self.name, None)
            raise TransportError(
                f"rank {comm.rank}: arena "
                f"{'create' if self._creator else 'open'}({self.name!r}) "
                f"failed")
        self._base = int(self._lib.shmarena_addr(self._ptr))
        cbuf = (ctypes.c_ubyte * nbytes).from_address(self._base)
        self._cbuf = cbuf  # keeps the mapping's python view alive
        self._mem: Optional[np.ndarray] = np.frombuffer(cbuf, np.uint8)
        self._slots_off = _LINE * p
        # Barrier sequence resumes from THIS RANK'S OWN FLAG LINE: a
        # fresh segment reads 0 (created zero-filled — identical to the
        # old constant), and a pooled-arena rank that dropped and
        # re-attached its handle (ISSUE 11 lease pooling) resumes in
        # lockstep with peers that kept theirs — the mapped flags, not
        # per-handle counters, are the authoritative barrier state.
        self.seq = int(self._lib.shmflag_read(self._flag_addr(self._rank)))
        self._closed = False
        self._active = 0  # collectives currently touching the mapping
        # registered on the TRANSPORT (arenas of sub-communicators share
        # it), closed by ShmTransport.close() at world finalize
        t._coll_arenas = getattr(t, "_coll_arenas", {})
        t._coll_arenas[(ctx, comm._group)] = self

    # -- slots -------------------------------------------------------------

    def _slot(self, rank: int) -> np.ndarray:
        off = self._slots_off + rank * self.slot_bytes
        return self._mem[off:off + self.slot_bytes]

    def write_meta(self, kind: int, arr: Optional[np.ndarray]) -> int:
        """Write this rank's meta word (+payload bytes when ``kind`` is
        data); returns the kind actually written (a meta pickle that
        overflows its region degrades to _KIND_NONE)."""
        desc = None if arr is None else (arr.dtype.str, arr.shape)
        meta = pickle.dumps((kind, desc), protocol=pickle.HIGHEST_PROTOCOL)
        if len(meta) > _META_MAX - _META_LEN.size:  # absurd ndim: decline
            kind, meta = _KIND_NONE, pickle.dumps(
                (_KIND_NONE, None), protocol=pickle.HIGHEST_PROTOCOL)
        slot = self._slot(self._rank)
        slot[:_META_LEN.size] = np.frombuffer(
            _META_LEN.pack(len(meta)), np.uint8)
        slot[_META_LEN.size:_META_LEN.size + len(meta)] = np.frombuffer(
            meta, np.uint8)
        if kind == _KIND_DATA and arr is not None and arr.nbytes:
            dst = slot[_META_MAX:_META_MAX + arr.nbytes].view(arr.dtype)
            dst[...] = arr.reshape(-1)
        return kind

    def read_meta(self, rank: int):
        slot = self._slot(rank)
        (mlen,) = _META_LEN.unpack(slot[:_META_LEN.size].tobytes())
        return pickle.loads(slot[_META_LEN.size:_META_LEN.size + mlen]
                            .tobytes())

    def data(self, rank: int, dtype, nelems: int) -> np.ndarray:
        """Rank ``rank``'s payload as a flat IN-PLACE view of the arena —
        valid only between the entry barrier and the exit barrier; never
        returned to the caller (results are private copies)."""
        dtype = np.dtype(dtype)
        slot = self._slot(rank)
        return slot[_META_MAX:_META_MAX + nelems * dtype.itemsize].view(dtype)

    # -- synchronization ---------------------------------------------------

    def _flag_addr(self, rank: int) -> int:
        return self._base + rank * _LINE

    def barrier(self, comm) -> None:
        """One flag round: post my next sequence value, wait until every
        peer has posted it too.  All collectives on a communicator are
        issued in the same order on every rank (the MPI requirement the
        wire algorithms already lean on), so the local counters stay in
        lockstep with zero arena traffic beyond the flags."""
        self.seq += 1
        target = self.seq & 0xFFFFFFFF
        self._lib.shmflag_post(self._flag_addr(self._rank), target)
        for q in range(self._p):
            if q != self._rank:
                self._wait_flag(comm, q, target)

    def _wait_flag(self, comm, peer: int, target: int) -> None:
        """Sliced flag wait — the arena's analogue of the segmented
        engine's FT-gated irecv drain: between ~50ms native waits a
        queued revocation raises RevokedError and a detector hit raises
        ProcFailedError naming the collective, so a dead rank never
        deadlocks a barrier; without FT the wait is bounded by the
        communicator's recv_timeout (RecvTimeout) or the shm transport's
        stall constant (TransportError)."""
        from .transport import shm as _shm

        addr = self._flag_addr(peer)
        timeout = comm.recv_timeout
        bound = _shm._WRITE_TIMEOUT if timeout is None else timeout
        deadline = time.monotonic() + bound
        while True:
            cur = self._lib.shmflag_wait_ge(addr, target, _SLICE_S)
            if ((cur - target) & 0xFFFFFFFF) < 0x80000000:  # wrap-safe >=
                return
            if self._closed:
                raise TransportError(
                    f"rank {self._rank}: arena closed while waiting for "
                    f"rank {peer} in {comm._coll_name!r}")
            # FT parity with _seg_exchange: detector hit / revocation
            # surfaces here, inside the detection bound
            comm._ft_poll_check(ANY_SOURCE, -2)
            if time.monotonic() > deadline:
                what = (f"arena wait on rank {peer} in collective "
                        f"{comm._coll_name!r}")
                if timeout is not None:
                    raise RecvTimeout(
                        f"{what} timed out after {timeout}s")
                raise TransportError(
                    f"rank {self._rank}: {what} made no progress for "
                    f"{bound}s — is the peer alive?")

    # -- lifecycle ---------------------------------------------------------

    def _begin(self) -> None:
        if self._closed:
            raise TransportError(
                f"rank {self._rank}: collective arena {self.name!r} is "
                f"closed")
        self._active += 1

    def _end(self) -> None:
        self._active -= 1

    def close(self, force_unlink: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        if self._active == 0:
            # quiescent: release the mapping now.  A close racing an
            # in-flight collective (crash-path teardown) instead LEAKS
            # the mapping until process exit — the doorbell pattern from
            # transport/shm.py: never hand freed pages to a thread still
            # inside a fold or a native flag wait.
            self._mem = None
            self._cbuf = None
            self._lib.shmarena_close(self._ptr)
            self._ptr = None
        with _LIVE_LOCK:
            ent = _LIVE.get(self.name)
            if ent:
                ent["refs"] -= 1
                if ent["refs"] <= 0:
                    _LIVE.pop(self.name, None)
        # ``force_unlink``: pooled lease arenas retired by an epoch
        # bump (ISSUE 11) may have lost their creator with the dead
        # worker — every survivor unlinks; shm_unlink of an
        # already-gone name is a harmless ENOENT (return unchecked,
        # like the creator path always was)
        if self._creator or force_unlink:
            self._lib.shmarena_unlink(self.name.encode())
            if self._flag_file is not None:
                try:
                    os.unlink(self._flag_file)
                except OSError:
                    pass


def live_arenas() -> Dict[str, int]:
    """name -> live handle count (test/tool introspection)."""
    with _LIVE_LOCK:
        return {k: v["refs"] for k, v in _LIVE.items()}


def retire_pooled(transport) -> int:
    """World-finalize sweep over the POOLED lease arenas (ISSUE 12
    satellite, closing PR-11 residual (d)): a pooled arena whose worker
    set never re-leases is retired by nothing — the epoch-bump sweep in
    ``_pooled_arena`` only runs when a NEW same-group lease arrives — so
    until this sweep it held its multi-MB /dev/shm segment mapped for
    the life of the worker process, and if its creator was the dead
    worker an epoch bump mourned, the segment outlived the process too
    (only the creator unlinks on the plain close path).  Called when a
    serve worker drains its job loop at pool shutdown; ``force_unlink``
    makes every surviving handle unlink (double-unlink is a harmless
    ENOENT).  Returns the number of arenas retired."""
    pool = getattr(transport, "_coll_arenas", None) or {}
    retired = 0
    for key, arena in list(pool.items()):
        if getattr(arena, "_pooled", False):
            pool.pop(key, None)
            arena.close(force_unlink=True)
            retired += 1
    return retired


# -- the collectives ---------------------------------------------------------
#
# Every entry point returns FALLBACK (after keeping the group's flag
# sequence in lockstep) when the arena cannot serve the call, and the
# result otherwise.  Copy accounting: each payload pass counts ONE
# ``payload_copies`` tick per rank — the copy-in at write_meta time and
# the copy-out/fold pass — so an arena collective is provably ≤2 copies.


def _sm_coll(fn):
    """Entry-point wrapper: resolve the arena (FALLBACK + pvar when this
    communicator has none) and hold the active-use guard across every
    arena touch, so a crash-path transport close never unmaps pages a
    collective is still reading."""
    @functools.wraps(fn)
    def run(comm, *args):
        arena = arena_for(comm)
        if arena is None:
            # Count a fallback only when the transport HAS an arena tier
            # (nbc clone, kill-switch cvar): on socket/local worlds the
            # pvar must stay 0 — it diagnoses real shm-arena declines,
            # and the non-shm hot path skips the counter lock entirely.
            if getattr(comm._t, "supports_coll_sm", False):
                _mpit.count(coll_sm_fallbacks=1)
            return FALLBACK
        arena._begin()
        try:
            out = fn(arena, comm, *args)
        finally:
            arena._end()
        rec = _telemetry.REC
        if rec is not None:
            # flight recorder (ISSUE 13): one event per arena attempt —
            # hit (served by load/store) or fallback (declined to the
            # wire algorithms inside the meta negotiation); a hit is
            # also the collective span's final concrete algorithm
            if out is not FALLBACK:
                rec.note_algorithm("sm")
            rec.emit("arena",
                     "hit" if out is not FALLBACK else "fallback",
                     attrs={"coll": fn.__name__})
        return out
    return run


def _eligible(arena: Arena, payload: Any) -> Optional[np.ndarray]:
    """The contiguous array to place in this rank's slot, or None — the
    local half of the in-arena negotiation."""
    arr = _codec.as_raw_array(payload)
    if arr is None or arr.nbytes > arena.capacity:
        return None
    return arr


def _enter(arena: Arena, comm, payload: Any) -> Optional[np.ndarray]:
    """Write this rank's meta (+data when eligible) and cross the entry
    barrier; returns the placed array or None."""
    mine = _eligible(arena, payload)
    kind = arena.write_meta(
        _KIND_DATA if mine is not None else _KIND_NONE, mine)
    if kind != _KIND_DATA:
        mine = None
    if mine is not None:
        _mpit.count(copies=1, coll_sm_bytes=int(mine.nbytes))
    arena.barrier(comm)
    return mine


def _metas(arena: Arena) -> List[Tuple[int, Any]]:
    return [arena.read_meta(q) for q in range(arena._p)]


def _decline(arena: Arena, comm) -> Any:
    """Uniform fallback exit: one more barrier keeps every rank's flag
    sequence in lockstep, then the caller runs the wire algorithm."""
    arena.barrier(comm)
    _mpit.count(coll_sm_fallbacks=1)
    return FALLBACK


def _congruent(metas: List[Tuple[int, Any]]) -> bool:
    """True iff every rank placed data of identical (dtype, shape) — the
    precondition of an in-place reduction fold."""
    kind0, desc0 = metas[0]
    return kind0 == _KIND_DATA and all(
        kind == _KIND_DATA and desc == desc0 for kind, desc in metas)


@_sm_coll
def barrier(arena: Arena, comm) -> Any:
    arena.barrier(comm)
    _mpit.count(coll_sm_hits=1)
    return None


@_sm_coll
def bcast(arena: Arena, comm, obj: Any, root: int) -> Any:
    me = comm.rank == root
    _enter(arena, comm, obj if me else None)
    kind, desc = arena.read_meta(root)
    if kind != _KIND_DATA:
        return _decline(arena, comm)
    if me:
        arena.barrier(comm)
        _mpit.count(coll_sm_hits=1)
        return obj
    dtype_str, shape = desc
    out = _codec.RECV_POOL.empty(shape, np.dtype(dtype_str))
    if out.size:
        out.reshape(-1)[...] = arena.data(root, out.dtype, out.size)
    arena.barrier(comm)  # root's slot free for the next collective
    _mpit.count(copies=1, coll_sm_hits=1, coll_sm_bytes=int(out.nbytes))
    return out


@_sm_coll
def allreduce(arena: Arena, comm, arr: np.ndarray, op) -> Any:
    mine = _enter(arena, comm, arr)
    if not _congruent(_metas(arena)):
        return _decline(arena, comm)
    p, r = arena._p, comm.rank
    out = np.empty(mine.shape, mine.dtype)
    flat = out.reshape(-1)
    n = flat.size
    # flat-vs-chunked is a tuned decision (mpi_tpu/tuning "sm_allreduce"
    # rows): the table overrides the coll_sm_eager_bytes constant where
    # the sweep measured this machine; payloads are congruent, so every
    # rank picks the same side.  No row: the seed constant.
    eager = mine.nbytes <= _EAGER_BYTES
    pick = _tuning.pick(comm, "sm_allreduce", int(mine.nbytes),
                        ("flat", "chunked"))
    if pick is not None:
        eager = pick == "flat"
    if eager:
        # flat: every rank folds every slot, in rank order — the result
        # is deterministic and bit-identical on every rank
        if n:
            flat[...] = arena.data(0, mine.dtype, n)
            for q in range(1, p):
                op.combine_into(flat, arena.data(q, mine.dtype, n))
        arena.barrier(comm)
        _mpit.count(copies=1, coll_sm_hits=1)
        return out
    # block in-place: fold my chunk reading peers' blocks straight from
    # the arena, publish the reduced chunk in my own slot, then gather
    # every reduced chunk — one copy in, one copy out per rank
    offs = schedules.chunk_offsets(n, p)
    lo, hi = offs[r], offs[r + 1]
    if hi > lo:
        flat[lo:hi] = arena.data(0, mine.dtype, n)[lo:hi]
        for q in range(1, p):
            op.combine_into(flat[lo:hi], arena.data(q, mine.dtype, n)[lo:hi])
        arena.data(r, mine.dtype, n)[lo:hi] = flat[lo:hi]
    arena.barrier(comm)  # every reduced chunk published
    for q in range(p):
        if q != r and offs[q + 1] > offs[q]:
            flat[offs[q]:offs[q + 1]] = \
                arena.data(q, mine.dtype, n)[offs[q]:offs[q + 1]]
    arena.barrier(comm)  # slots free for the next collective
    _mpit.count(copies=1, coll_sm_hits=1)
    return out


# -- compressed eager path (ISSUE 8) -----------------------------------------
#
# algorithm="compressed" on an shm world routes HERE first, exactly like
# auto's arena tier, so compression and the arena stay one coherent
# policy: each rank writes its payload ENCODED (the wire dtype — bf16
# bits / scale+int8, laid segment-by-segment 8-byte-aligned after the
# meta region) and every rank decodes all P slots and folds in the FOLD
# dtype.  The meta word carries (wire name, payload desc, segment
# descs), so mixing compressed/uncompressed (or bf16/int8) entries is
# non-congruent and the whole group declines to the wire algorithms
# together — the same negotiation the plain entries use.  Eager sizes
# only: above ``coll_sm_eager_bytes`` (encoded) the segmented compressed
# ring wins like the plain block path would, so the arena declines.

_WIRE_ALIGN = 8


def _wire_slot_layout(seg_descs) -> List[int]:
    """Byte offsets (within the slot, after the meta region) where each
    encoded segment lives — one rule for writer and readers."""
    offs, off = [], _META_MAX
    for dtype_str, shape in seg_descs:
        off = (off + _WIRE_ALIGN - 1) & ~(_WIRE_ALIGN - 1)
        offs.append(off)
        n = 1
        for s in shape:
            n *= int(s)
        off += n * np.dtype(dtype_str).itemsize
    offs.append(off)  # total extent (capacity check)
    return offs


def _read_wire_segs(arena: Arena, rank: int, seg_descs) -> List[np.ndarray]:
    """Rank ``rank``'s encoded segments as in-place views of its slot."""
    slot = arena._slot(rank)
    offs = _wire_slot_layout(seg_descs)
    out = []
    for (dtype_str, shape), off in zip(seg_descs, offs):
        dt = np.dtype(dtype_str)
        n = 1
        for s in shape:
            n *= int(s)
        out.append(slot[off:off + n * dt.itemsize].view(dt))
    return out


@_sm_coll
def allreduce_wire(arena: Arena, comm, arr: np.ndarray, op, wire) -> Any:
    """Compressed eager allreduce: write own ENCODED payload → barrier →
    decode every slot and fold in the fold dtype (rank order — bit-
    identical on every rank) → barrier.  Returns the result in the
    payload's dtype, or FALLBACK (group-coherent) when the encoded
    payload cannot ride — the caller runs the compressed wire ring."""
    from . import compress as _compress

    fdt = _compress.fold_dtype(arr.dtype)
    flat = np.ascontiguousarray(arr, dtype=fdt).reshape(-1)
    est = wire.wire_nbytes(flat.size, fdt.itemsize) + _META_MAX \
        + _WIRE_ALIGN * 4
    desc = None
    if not arr.dtype.hasobject and est <= min(arena.capacity, _EAGER_BYTES):
        enc = wire.encode(flat)
        seg_descs = [(s.dtype.str, s.shape) for s in enc.segs]
        offs = _wire_slot_layout(seg_descs)
        desc = (wire.name, arr.dtype.str, tuple(arr.shape), seg_descs)
        meta = pickle.dumps((_KIND_WIRE, desc),
                            protocol=pickle.HIGHEST_PROTOCOL)
        if (len(meta) > _META_MAX - _META_LEN.size
                or offs[-1] > arena.slot_bytes):
            desc = None
    if desc is None:
        arena.write_meta(_KIND_NONE, None)
    else:
        slot = arena._slot(comm.rank)
        slot[:_META_LEN.size] = np.frombuffer(
            _META_LEN.pack(len(meta)), np.uint8)
        slot[_META_LEN.size:_META_LEN.size + len(meta)] = np.frombuffer(
            meta, np.uint8)
        for s, off in zip(enc.segs, offs):
            if s.nbytes:
                slot[off:off + s.nbytes].view(s.dtype)[...] = s.reshape(-1)
        _mpit.count(copies=1,
                    coll_sm_bytes=sum(int(s.nbytes) for s in enc.segs))
    arena.barrier(comm)
    metas = _metas(arena)
    kind0, desc0 = metas[0]
    if not (kind0 == _KIND_WIRE and all(
            kind == _KIND_WIRE and d == desc0 for kind, d in metas)):
        return _decline(arena, comm)
    seg_descs = desc0[3]
    # private fold buffer (slot views die at the exit barrier)
    out = np.array(wire.decode_segs(_read_wire_segs(arena, 0, seg_descs)),
                   dtype=fdt)
    for q in range(1, arena._p):
        op.combine_into(out, _read_wire_segs(arena, q, seg_descs),
                        wire.decode_segs)
    arena.barrier(comm)  # slots free for the next collective
    _mpit.count(copies=1, coll_sm_hits=1)
    return out.astype(arr.dtype, copy=False).reshape(arr.shape)


@_sm_coll
def reduce(arena: Arena, comm, arr: np.ndarray, op, root: int) -> Any:
    # Above eager the binomial tree's distributed folds beat a flat P·N
    # fold at the root; reduction payloads are congruent, so every rank
    # gates identically without consulting the metas.  The gate is a
    # tuned decision (mpi_tpu/tuning "sm_reduce" rows: "arena"/"tree")
    # falling back to the coll_sm_eager_bytes constant.
    use_arena = arr.nbytes <= _EAGER_BYTES
    pick = _tuning.pick(comm, "sm_reduce", int(arr.nbytes),
                        ("arena", "tree"))
    if pick is not None:
        use_arena = pick == "arena"
    if not use_arena:
        arena.write_meta(_KIND_NONE, None)
        arena.barrier(comm)
        mine = None
    else:
        mine = _enter(arena, comm, arr)
    if not _congruent(_metas(arena)):
        return _decline(arena, comm)
    out = None
    if comm.rank == root:
        out = np.empty(mine.shape, mine.dtype)
        flat = out.reshape(-1)
        if flat.size:
            flat[...] = arena.data(0, mine.dtype, flat.size)
            for q in range(1, arena._p):
                op.combine_into(flat, arena.data(q, mine.dtype, flat.size))
        _mpit.count(copies=1)
    arena.barrier(comm)
    _mpit.count(coll_sm_hits=1)
    return (out,)


@_sm_coll
def allgather(arena: Arena, comm, obj: Any) -> Any:
    _enter(arena, comm, obj)
    metas = _metas(arena)
    if any(kind != _KIND_DATA for kind, _ in metas):
        return _decline(arena, comm)
    items: List[Any] = [None] * arena._p
    for q, (_, (dtype_str, shape)) in enumerate(metas):
        if q == comm.rank:
            items[q] = obj
            continue
        dst = _codec.RECV_POOL.empty(shape, np.dtype(dtype_str))
        if dst.size:
            dst.reshape(-1)[...] = arena.data(q, dst.dtype, dst.size)
        items[q] = dst
    arena.barrier(comm)
    _mpit.count(copies=1, coll_sm_hits=1)
    return (items,)


@_sm_coll
def alltoall(arena: Arena, comm, arr: Optional[np.ndarray]) -> Any:
    """``arr`` is the stacked [P, ...] block array (the communicator's
    ``_blocks_as_array`` eligibility view, None when the local payload
    cannot ride): write ALL blocks into own slot → one flag round →
    read your COLUMN (peer q's block ``rank``) in place.  One copy in,
    one copy out per rank, versus the wire path's P-1 windowed
    send/recv round trips.  Congruence is negotiated in-arena like the
    reductions: any rank whose stack differs (object payloads, ragged
    blocks, oversized) lands the whole group on the pairwise wire
    exchange together."""
    mine = _enter(arena, comm, arr)
    if not _congruent(_metas(arena)):
        return _decline(arena, comm)
    p, r = arena._p, comm.rank
    if mine.shape[0] != p:
        return _decline(arena, comm)  # [P, ...] stacks only
    n = mine.size
    bn = n // p
    items: List[np.ndarray] = [None] * p  # type: ignore[list-item]
    for q in range(p):
        dst = _codec.RECV_POOL.empty(mine.shape[1:], mine.dtype)
        if bn:
            lo = r * bn
            dst.reshape(-1)[...] = arena.data(q, mine.dtype, n)[lo:lo + bn]
        items[q] = dst
    arena.barrier(comm)
    _mpit.count(copies=1, coll_sm_hits=1)
    return (items,)


@_sm_coll
def scan(arena: Arena, comm, arr: np.ndarray, op) -> Any:
    """Inclusive prefix reduction: write own payload → one flag round →
    rank r folds slots 0..r in rank order, in place from the arena —
    every rank's P·N loads happen concurrently, versus the wire path's
    log P serialized distance-doubling rounds."""
    mine = _enter(arena, comm, arr)
    if not _congruent(_metas(arena)):
        return _decline(arena, comm)
    out = np.empty(mine.shape, mine.dtype)
    flat = out.reshape(-1)
    if flat.size:
        flat[...] = arena.data(0, mine.dtype, flat.size)
        for q in range(1, comm.rank + 1):
            op.combine_into(flat, arena.data(q, mine.dtype, flat.size))
    arena.barrier(comm)
    _mpit.count(copies=1, coll_sm_hits=1)
    return (out,)


@_sm_coll
def reduce_scatter(arena: Arena, comm, arr: np.ndarray, op) -> Any:
    """``arr`` is the stacked [P, ...] block array (the communicator's
    ``_blocks_as_array`` eligibility view): write the whole input, one
    barrier, fold only block ``rank`` reading peers' blocks in place —
    no writeback or gather phase, the result is private."""
    mine = _enter(arena, comm, arr)
    if not _congruent(_metas(arena)):
        return _decline(arena, comm)
    p, r = arena._p, comm.rank
    n = mine.size
    bn = n // p
    out = np.empty(mine.shape[1:], mine.dtype)
    flat = out.reshape(-1)
    if bn:
        lo = r * bn
        flat[...] = arena.data(0, mine.dtype, n)[lo:lo + bn]
        for q in range(1, p):
            op.combine_into(flat, arena.data(q, mine.dtype, n)[lo:lo + bn])
    arena.barrier(comm)
    _mpit.count(copies=1, coll_sm_hits=1)
    return (out,)
