"""Static MPI lint v2 (MUST / MPI-Checker style), grounded on a dataflow
engine instead of literal pattern-matching.

v1 (PR 5) matched the literal ``if c.rank == 0:`` shape and nothing
else.  v2 runs every program through :mod:`mpi_tpu.verify.dataflow`
(guard chains + constant/rank propagation + a one-level call graph) and
:mod:`mpi_tpu.verify.commgraph` (per-model-rank schedules + match
rules), so ``r = c.rank; if r == 0:``, ``peer = (c.rank + 1) % c.size``
and rank-guarded helper functions resolve exactly.  Undecidable facts
never fire a rule — every finding is still something a reviewer can
confirm by reading the flagged lines.  Suppress a deliberate one with
``# mpilint: ok`` on the flagged line or the line above.

The rules:

* **MPL001 — collective schedule divergence**: under the resolved rank
  conditions, some rank reaches a collective on ``c`` that other ranks
  never post (or posts a different one at the same position) — the
  divergent-order hang the runtime matcher catches dynamically.
* **MPL002 — send-send cycle**: two ranks whose first operation toward
  each other is a blocking send, both later receiving — legal under
  this library's buffered sends, but a deadlock under MPI's
  synchronous/rendezvous sends and any bounded-buffer transport; use
  ``sendrecv``.
* **MPL003 — count truncation**: a matched send/recv pair whose receive
  count is smaller than the send count — the receive silently
  truncates.
* **MPL004 — revoked comm without an error handler**: a p2p/collective
  call on a comm after ``c.revoke()`` appears, with no
  ``set_errhandler`` on it and outside any ``try``: every post-revoke
  call raises RevokedError, so unhandled it just moves the crash.
* **MPL005 — unwaited nonblocking request**: an ``isend/irecv/i*``
  request that reaches a function exit without ``wait()``/``test()``
  along at least one CFG path (branch joins are may-unions, so a
  request waited on only one side of an ``if`` still fires).
* **MPL006 — buffer reuse under a live request**: a write into a
  buffer while a nonblocking operation on it may still be in flight.
* **MPL007 — unmatchable tag pair**: a send and an exact-tag receive on
  the same channel whose tags can never match each other.
* **MPL008 — rank-dependent collective loop**: a collective inside a
  loop whose trip count depends on the rank — ranks execute different
  numbers of collectives.
* **MPL009 — racy ANY_SOURCE receive**: a wildcard receive with two or
  more eligible same-tag senders; the match order is nondeterministic
  (the runtime wildcard-race detector observes the same race via
  vector clocks — see ``mpi_tpu.verify.vclock``).

``lint_source``/``lint_paths`` return :class:`Finding` lists; the CLI is
``tools/mpilint.py`` (``--format json``, ``--baseline``), wired into
``tools/check.sh`` over ``examples/``, ``mpi_tpu/``, ``tests/`` and
``benchmarks/`` against ``tools/lint_baseline.json``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

from . import commgraph, dataflow

COLLECTIVES = dataflow.COLLECTIVES
_P2P_OR_COLL = COLLECTIVES | frozenset({
    "send", "recv", "sendrecv", "isend", "irecv", "probe", "iprobe",
    "shift", "exchange", "split", "dup",
})


class Finding(NamedTuple):
    file: str
    line: int
    code: str
    msg: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.code} {self.msg}"


def _method_call(node: ast.AST) -> Optional[Tuple[str, str, ast.Call]]:
    """(receiver-name, method, call) for ``name.method(...)`` nodes."""
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)):
        return node.func.value.id, node.func.attr, node
    return None


def _rank_eq_literal(test: ast.AST) -> Optional[Tuple[str, int]]:
    """(name, K) for a test of the exact form ``name.rank == K``.

    This was the ONLY guard shape v1 resolved; it is kept as the legacy
    reference predicate so tests can demonstrate v1-blind/v2-caught on
    the symbolic corpus variants."""
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.ops[0], ast.Eq)):
        return None
    sides = [test.left, test.comparators[0]]
    name = lit = None
    for s in sides:
        if (isinstance(s, ast.Attribute) and s.attr == "rank"
                and isinstance(s.value, ast.Name)):
            name = s.value.id
        elif isinstance(s, ast.Constant) and isinstance(s.value, int):
            lit = s.value
    return (name, lit) if name is not None and lit is not None else None


def _calls_in(nodes: Sequence[ast.AST], *, into_defs: bool = False):
    """Every Call in the given statement subtrees, skipping nested
    function/class bodies unless asked (their execution time is
    unrelated to the enclosing branch)."""
    stack = list(nodes)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)) and not into_defs:
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def _suppressed(src: str) -> set:
    out = set()
    for i, line in enumerate(src.splitlines(), 1):
        if "mpilint: ok" in line:
            out.add(i)
            out.add(i + 1)
    return out


def lint_source(src: str, filename: str = "<string>") -> List[Finding]:
    try:
        tree = ast.parse(src, filename)
    except SyntaxError as e:
        return [Finding(filename, e.lineno or 0, "MPL000",
                        f"syntax error: {e.msg}")]
    findings: List[Finding] = []

    # engine-grounded rules: MPL001/002/003/007/009 off the match graph,
    # MPL008 off the loop evidence the op walk collects
    roots, rank_loops = dataflow.collect_roots(tree)
    for cg in commgraph.analyze(roots):
        findings.append(Finding(filename, cg.line, cg.code, cg.msg))
    for rl in rank_loops:
        findings.append(Finding(
            filename, rl.line, "MPL008",
            f"collective {rl.comm}.{rl.name}() inside a loop (line "
            f"{rl.loop_line}) whose trip count depends on {rl.comm}.rank: "
            f"ranks execute different numbers of collectives and the "
            f"schedule diverges"))

    # per-function local rules
    findings += _check_revoked_unhandled(tree, filename)
    findings += _check_request_flow(tree, filename)

    sup = _suppressed(src)
    seen = set()
    out = []
    for f in sorted((f for f in findings if f.line not in sup),
                    key=lambda f: (f.line, f.code)):
        key = (f.line, f.code)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


# -- MPL004 ------------------------------------------------------------------

def _alias_map(tree: ast.Module) -> Dict[str, str]:
    """Simple name-to-name bindings (``c2 = comm``), so a comm revoked
    under an alias still pairs with calls through the original name."""
    out: Dict[str, str] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name) \
                and isinstance(n.value, ast.Name):
            out[n.targets[0].id] = n.value.id
    return out


def _canon(name: str, aliases: Dict[str, str]) -> str:
    seen = set()
    while name in aliases and name not in seen:
        seen.add(name)
        name = aliases[name]
    return name


def _check_revoked_unhandled(tree, filename) -> List[Finding]:
    aliases = _alias_map(tree)
    revoked: Dict[str, int] = {}
    handled: set = set()
    in_try: set = set()

    def mark_try(node, inside):
        inside = inside or isinstance(node, ast.Try)
        if inside:
            in_try.add(id(node))
        for c in ast.iter_child_nodes(node):
            mark_try(c, inside)

    mark_try(tree, False)
    for call in _calls_in([tree], into_defs=True):
        mc = _method_call(call)
        if mc is None:
            continue
        name, meth, _ = mc
        name = _canon(name, aliases)
        if meth == "revoke":
            revoked.setdefault(name, call.lineno)
        elif meth == "set_errhandler":
            handled.add(name)
    findings = []
    if not revoked:
        return findings
    flagged = set()
    for call in _calls_in([tree], into_defs=True):
        mc = _method_call(call)
        if mc is None:
            continue
        name, meth, _ = mc
        name = _canon(name, aliases)
        if (name in revoked and name not in handled and name not in flagged
                and meth in _P2P_OR_COLL and call.lineno > revoked[name]
                and id(call) not in in_try):
            flagged.add(name)
            findings.append(Finding(
                filename, call.lineno, "MPL004",
                f"{name}.{meth}() after {name}.revoke() (line "
                f"{revoked[name]}) with no error handler and outside "
                f"try: every operation on a revoked comm raises "
                f"RevokedError — install set_errhandler or shrink() "
                f"first"))
    return findings


# -- MPL005 / MPL006 ---------------------------------------------------------

def _check_request_flow(tree, filename) -> List[Finding]:
    findings = []
    module_stmts = [s for s in tree.body
                    if not isinstance(s, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef))]
    bodies = [module_stmts] + [fn.body for fn in dataflow.all_functions(tree)]
    for body in bodies:
        for issue in dataflow.request_flow(body):
            if issue.code == "MPL005":
                findings.append(Finding(
                    filename, issue.line, "MPL005",
                    f"nonblocking {issue.op_name}() request is never "
                    f"completed along at least one path to exit (no "
                    f"wait/test reaches it): the operation may never "
                    f"finish and its resources leak"))
            else:
                findings.append(Finding(
                    filename, issue.line, "MPL006",
                    f"buffer '{issue.buf}' is written while the "
                    f"{issue.op_name}() request from line {issue.op_line} "
                    f"may still be live: complete the request before "
                    f"reusing its buffer"))
    return findings


# -- driver ------------------------------------------------------------------

def lint_file(path: str) -> List[Finding]:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return lint_source(f.read(), path)


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    findings: List[Finding] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        findings += lint_file(os.path.join(root, fn))
        elif p.endswith(".py"):
            findings += lint_file(p)
    return findings
