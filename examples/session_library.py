"""MPI-4 sessions demo (mpi_tpu/mpi4.py Session; MPI-4 ch.11).

The sessions model solves the library-composition problem: two
independently-written libraries inside one application each acquire their
OWN handle to the runtime, derive their own communicators, and can never
collide with each other's (or the application's) traffic — without
anybody calling MPI_Init or agreeing on tag ranges.

Here ``stats_lib`` and ``sum_lib`` both follow the canonical sessions
recipe — session → pset → group → communicator — and deliberately
exchange with the SAME tags at the same time; the (group, stringtag)
contexts keep every exchange private.  The application meanwhile uses
its own communicator for a barrier + broadcast, untouched.

Run on any process backend:

    python -m mpi_tpu.launcher -n 4 examples/session_library.py
"""

import os
import sys

import numpy as np

try:
    from mpi_tpu import mpi4
except ModuleNotFoundError:  # running from a fresh checkout without install
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from mpi_tpu import mpi4


def stats_lib(base_comm):
    """A 'library': global mean of a per-rank value, on a private comm."""
    with mpi4.session_init(base_comm=base_comm) as s:
        g = s.group_from_pset("mpi://WORLD")
        c = s.comm_create_from_group(g, stringtag="example.stats")
        x = float(c.rank + 1)
        return c.allreduce(x) / c.size


def sum_lib(base_comm):
    """A second library, same group, different stringtag — its ring
    exchange (tag 0, like anything else) cannot cross-match stats_lib's."""
    with mpi4.session_init(base_comm=base_comm) as s:
        g = s.group_from_pset("mpi://WORLD")
        c = s.comm_create_from_group(g, stringtag="example.sum")
        left = c.shift(np.asarray([c.rank], np.float32), offset=1)
        return float(c.allreduce(left[0]))


def session_program(comm):
    """The application: uses ITS communicator while both libraries run
    their session-derived exchanges.  Returns (mean, ringsum, app_token)
    — identical on every rank."""
    mean = stats_lib(comm)
    ringsum = sum_lib(comm)
    token = comm.bcast("app", 0)  # application traffic, unaffected
    return mean, ringsum, token


def main(comm):
    mean, ringsum, token = session_program(comm)
    print(f"rank {comm.rank}: mean={mean} ringsum={ringsum} token={token}")
    return mean, ringsum, token


if __name__ == "__main__":
    import mpi_tpu

    main(mpi_tpu.COMM_WORLD)
