"""The segmented zero-copy host collective engine (ISSUE 1 tentpole).

Parity: every segmented algorithm must produce bit-identical results to a
single-process numpy oracle — across ops (builtin and user), dtypes (incl.
bf16 shipped as u16), non-pow2 group sizes, scalar/0-dim payloads, and
segment boundaries forced down to a few elements via the
``collective_segment_bytes`` cvar.

Zero-copy proof: the byte counters added with the engine
(``bytes_raw_sent`` / ``bytes_pickled_sent`` mpit pvars) must show ZERO
pickled array bytes on the recursive-halving and ring hot paths at
bandwidth sizes — the acceptance criterion that the halving path's chunk
payloads no longer fall off the raw-frame plane into pickle."""

import numpy as np
import pytest

from mpi_tpu import mpit, ops
from mpi_tpu.transport.local import run_local
from tests.test_socket_backend import run_socket_world

NRANKS = [1, 2, 3, 4, 5, 8]
POW2 = [2, 4, 8]


@pytest.fixture
def small_segments():
    """Force multi-segment pipelines at test-sized payloads: 64-byte
    segments make a 1000-element f64 buffer ~125 segments."""
    old = mpit.cvar_read("collective_segment_bytes")
    mpit.cvar_write("collective_segment_bytes", 64)
    yield
    mpit.cvar_write("collective_segment_bytes", old)


def _payloads(n, dtype, shape, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        a = rng.randint(1, 100, size=shape or (1,)).astype(dtype)
        out.append(a.reshape(shape))
    return out


# -- allreduce parity -------------------------------------------------------


@pytest.mark.parametrize("algo", ["ring", "recursive_halving"])
@pytest.mark.parametrize("op,oracle", [
    (ops.SUM, lambda xs: sum(x.astype(np.float64) for x in xs)),
    (ops.MAX, lambda xs: np.maximum.reduce(xs)),
])
def test_allreduce_parity_ops_sizes(algo, op, oracle, small_segments):
    for n in NRANKS:
        if algo == "recursive_halving" and (n & (n - 1) or n == 1):
            continue
        for shape in [(), (1,), (7,), (250,), (13, 11)]:
            data = _payloads(n, np.float64, shape, seed=n)
            want = np.asarray(oracle(data)).astype(np.float64)
            res = run_local(
                lambda c: c.allreduce(data[c.rank], op, algorithm=algo), n)
            for r in res:
                got = np.asarray(r, dtype=np.float64)
                np.testing.assert_allclose(got.reshape(shape), want,
                                           err_msg=f"n={n} shape={shape}")


@pytest.mark.parametrize("algo", ["ring", "recursive_halving"])
def test_allreduce_parity_dtypes(algo, small_segments):
    """f32/f64/int32/bf16-as-u16: the engine must preserve the payload
    dtype exactly (MPI reduces IN the datatype)."""
    n = 4
    for dtype in (np.float32, np.float64, np.int32, np.uint16):
        data = _payloads(n, dtype, (101,), seed=7)
        # MAX avoids overflow/rounding questions on the narrow dtypes —
        # elementwise max is exact in every one of them (for bf16-as-u16
        # payloads of non-negative floats, the u16 bit patterns even
        # order correctly, which is why that convention works at all)
        want = np.maximum.reduce(data)
        res = run_local(
            lambda c: c.allreduce(data[c.rank], ops.MAX, algorithm=algo), n)
        for r in res:
            assert np.asarray(r).dtype == dtype
            np.testing.assert_array_equal(np.asarray(r), want)


def test_allreduce_user_op_in_place_fold(small_segments):
    """User ops take the combine_into path (one temporary per fold, cast
    back to the accumulator dtype) — results must match folding the
    combine left-to-right in rank order on the halving tree's own
    operand order.  A commutative-and-associative user op keeps the
    order question out of the parity check."""
    n = 4
    user_max = ops.make_op(np.maximum, -np.inf, name="umax")
    data = _payloads(n, np.float32, (57,), seed=3)
    want = np.maximum.reduce(data)
    for algo in ("ring", "recursive_halving"):
        res = run_local(
            lambda c: c.allreduce(data[c.rank], user_max, algorithm=algo), n)
        for r in res:
            assert np.asarray(r).dtype == np.float32
            np.testing.assert_array_equal(np.asarray(r), want)


def test_allreduce_scalar_and_tiny_nonpow2(small_segments):
    """Scalars and payloads smaller than the group: most chunks are
    empty, spans collapse to zero messages, results still exact."""
    for n in NRANKS:
        res = run_local(lambda c: c.allreduce(float(c.rank + 1)), n)
        for r in res:
            assert np.isscalar(r) or np.asarray(r).ndim == 0
            assert float(r) == sum(range(1, n + 1))
        res = run_local(
            lambda c: c.allreduce(np.arange(2.0) + c.rank,
                                  algorithm="ring"), n)
        for r in res:
            np.testing.assert_allclose(
                np.asarray(r), np.arange(2.0) * n + sum(range(n)))


def test_reduce_parity_in_place_tree(small_segments):
    for n in NRANKS:
        data = _payloads(n, np.float64, (63,), seed=n)
        res = run_local(lambda c: c.reduce(data[c.rank], ops.SUM, root=0), n)
        np.testing.assert_allclose(np.asarray(res[0]), sum(data))
        assert all(r is None for r in res[1:])


# -- segmented bcast / allgather -------------------------------------------

def test_bcast_segmented_tree_parity():
    """Above _BCAST_SEGMENT_MIN_BYTES the pipelined cut-through tree runs;
    every rank must see the root's exact bytes (and the small-payload
    path still handles arbitrary objects)."""
    big = np.random.RandomState(5).randn(1 << 18)  # 2MB: segmented path
    for n in NRANKS:
        res = run_local(
            lambda c: c.bcast(big if c.rank == 0 else None, root=0), n)
        for r in res:
            np.testing.assert_array_equal(np.asarray(r), big)
    # non-array payloads keep the object tree
    res = run_local(
        lambda c: c.bcast({"k": [1, 2]} if c.rank == 2 else None, root=2), 4)
    assert all(r == {"k": [1, 2]} for r in res)


def test_bcast_segmented_from_nonzero_root():
    big = np.random.RandomState(6).randn(1 << 18)
    res = run_local(
        lambda c: c.bcast(big * (c.rank + 1) if c.rank == 3 else None,
                          root=3), 5)
    for r in res:
        np.testing.assert_array_equal(np.asarray(r), big * 4)


def test_allgather_row_buffer_parity(small_segments):
    for n in NRANKS:
        data = _payloads(n, np.float32, (41,), seed=n)
        res = run_local(
            lambda c: c.allgather(data[c.rank], algorithm="ring"), n)
        for r in res:
            np.testing.assert_array_equal(np.asarray(r), np.stack(data))


def test_allgather_ragged_payloads_still_work():
    """Mismatched shapes across ranks must fall back to list results on
    the very same wire protocol (interop between the row-buffer fast
    path and arbitrary payloads)."""
    def prog(comm):
        payload = np.arange(comm.rank + 1, dtype=np.float64)
        return comm.allgather(payload, algorithm="ring")

    for n in [2, 3, 4]:
        res = run_local(prog, n)
        for r in res:
            assert isinstance(r, list) and len(r) == n
            for i, item in enumerate(r):
                np.testing.assert_array_equal(
                    np.asarray(item), np.arange(i + 1, dtype=np.float64))


# -- the zero-copy proof (ISSUE 1 acceptance) ------------------------------

def _pickled_array_bytes_during(prog, nranks):
    """Run ``prog`` over real sockets in-process; return the pickled-bytes
    and raw-bytes deltas across the whole run (thread-backed ranks share
    the process-global counters, so this sums all ranks)."""
    p0 = mpit.counters.bytes_pickled
    r0 = mpit.counters.bytes_raw
    assert all(run_socket_world(prog, nranks))
    return (mpit.counters.bytes_pickled - p0, mpit.counters.bytes_raw - r0)


@pytest.mark.parametrize("algo", ["recursive_halving", "ring"])
def test_allreduce_zero_pickled_bytes_at_1mb(algo):
    """THE acceptance criterion: at >=1MB every array payload of the
    allreduce hot paths rides raw frames — 0 pickled array bytes.  The
    halving path is the one that used to pickle a list of chunk arrays
    every round (tentpole motivation); the counter now proves it can't
    regress silently."""
    n = 4
    data = [np.random.RandomState(i).randn(1 << 17) for i in range(n)]  # 1MB
    want = sum(data)

    def prog(comm):
        out = comm.allreduce(data[comm.rank], ops.SUM, algorithm=algo)
        np.testing.assert_allclose(out, want)
        return True

    pickled, raw = _pickled_array_bytes_during(prog, n)
    assert pickled == 0, (
        f"{algo} allreduce pickled {pickled} bytes at 1MB — payloads fell "
        f"off the raw-frame plane")
    # and the payload bytes actually moved raw (2(P-1)/P volume per rank
    # lower-bounds well above one buffer's worth for both schedules)
    assert raw >= data[0].nbytes


def test_bcast_and_allgather_zero_pickled_payload_bytes():
    """The segmented bcast ships one tiny pickled header per tree edge
    (bounded, payload-independent); the allgather row path ships none.
    Array bytes must all be raw."""
    n = 4
    big = np.random.RandomState(9).randn(1 << 17)  # 1MB

    def prog(comm):
        got = comm.bcast(big if comm.rank == 0 else None, root=0)
        np.testing.assert_array_equal(got, big)
        ag = comm.allgather(np.asarray(got) + comm.rank, algorithm="ring")
        assert np.asarray(ag).shape == (n, big.size)
        return True

    pickled, raw = _pickled_array_bytes_during(prog, n)
    # _SegHeader pickles are O(100) bytes per tree edge; nothing
    # payload-sized may ride pickle
    assert pickled < 4096, f"payload-sized pickle traffic: {pickled} bytes"
    assert raw >= big.nbytes * (n - 1)


def test_allgather_doubling_zero_pickled_payload_bytes():
    """Doubling's keyed-list batches ([rank-index array, *values]) ride
    the multi-segment raw frame — auto on pow2 groups stays zero-copy
    for array payloads at bandwidth sizes."""
    n = 4
    big = np.random.RandomState(11).randn(1 << 17)  # 1MB

    def prog(comm):
        ag = comm.allgather(big * (comm.rank + 1))  # auto -> doubling
        assert np.asarray(ag).shape == (n, big.size)
        for i in range(n):
            np.testing.assert_array_equal(np.asarray(ag)[i], big * (i + 1))
        return True

    pickled, raw = _pickled_array_bytes_during(prog, n)
    assert pickled == 0, f"doubling batches pickled {pickled} bytes"
    assert raw >= big.nbytes * (n - 1)


def test_allgather_doubling_mixed_payloads_fall_back():
    """A non-array payload on ONE rank only demotes that rank's batches
    to the dict form — the two batch forms interoperate per message."""
    def prog(comm):
        payload = {"r": comm.rank} if comm.rank == 1 else np.arange(
            3.0) + comm.rank
        got = comm.allgather(payload)  # auto -> doubling on 4 ranks
        assert got[1] == {"r": 1}
        for i in (0, 2, 3):
            np.testing.assert_array_equal(
                np.asarray(got[i]), np.arange(3.0) + i)
        return True

    assert all(run_socket_world(prog, 4))


def test_segment_cvar_steers_engine(small_segments):
    """collective_segment_bytes is live: a 64-byte segment turns a single
    1000-element exchange into many raw frames (visible as a message
    count increase), with identical results."""
    n = 2
    data = [np.arange(1000.0) * (i + 1) for i in range(n)]
    want = sum(data)

    sends_before = mpit.counters.sends

    def prog(comm):
        out = comm.allreduce(data[comm.rank], ops.SUM, algorithm="ring")
        np.testing.assert_allclose(out, want)
        return True

    assert all(run_socket_world(prog, n))
    # 1000 f64 elements / 8-element segments = 125 spans per half-buffer
    # exchange; 2 ranks x 2 phases => hundreds of messages, far above the
    # seed engine's 2(P-1) = 2
    assert mpit.counters.sends - sends_before > 100
