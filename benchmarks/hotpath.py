"""Zero-copy hot-path benchmark (ISSUE 11 acceptance: ``bench.py
--hotpath [--quick]``).

Two legs:

* **16MB socket allreduce** (2 rank processes over loopback TCP, ring
  algorithm) under three retention modes of the resilient link layer:

  - ``healing_off`` — ``link_retry_timeout_s = 0``: no window, no
    retention, the pre-resilience floor;
  - ``healing_on_retain_copy`` — ``link_retain_copy = 1``: ISSUE 10's
    eager per-frame snapshot (one full memcpy of every frame body into
    the retained window) — the committed "pre" cost;
  - ``healing_on_zero_copy`` — the ISSUE 11 default: retention BY
    REFERENCE with copy-on-write on proven reuse.

  Each mode records rank 0's p50 plus the pvar deltas that prove the
  decoupling: ``link_bytes_retained`` > 0 with ``link_cow_snapshots``
  == 0 on the no-reuse path (retention without copy), and
  ``link_send_syscalls / frames`` ~= 1 (one vectored sendmsg per frame
  where the pre-sendmsg path took one write per header/meta/segment).

* **lease arena hit** (shm pool): two consecutive ``lease.run``
  allreduces on a resident world server must ride the POOLED collective
  arena — ``coll_sm_hits > 0`` inside the lease, same arena segment
  both times (the PR-7 "leases skip the arena" residual, closed).

Usage::

    python benchmarks/hotpath.py [--quick] [--out-pre F] [--out-post F]
    python bench.py --hotpath [--quick]     # the CI spelling
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_PVARS = ("link_bytes_retained", "link_cow_snapshots", "link_cow_bytes",
          "payload_copies", "link_send_syscalls", "msgs_sent")

_PROG = """
import json, os, statistics, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import mpi_tpu
from mpi_tpu import mpit

nbytes = int(os.environ["HOTPATH_NBYTES"])
iters = int(os.environ["HOTPATH_ITERS"])
warmup = int(os.environ["HOTPATH_WARMUP"])
comm = mpi_tpu.init()
x = np.ones(max(1, nbytes // 4), np.float32)
for _ in range(warmup):
    comm.allreduce(x, algorithm="ring")
names = {pvars!r}
before = {{n: mpit.pvar_read(n) for n in names}}
ts = []
for _ in range(iters):
    t0 = time.perf_counter()
    comm.allreduce(x, algorithm="ring")
    ts.append(time.perf_counter() - t0)
after = {{n: mpit.pvar_read(n) for n in names}}
if comm.rank == 0:
    print(json.dumps({{
        "p50_us": statistics.median(ts) * 1e6,
        "pvars": {{n: after[n] - before[n] for n in names}}}}))
mpi_tpu.finalize()
"""


def _run_world(script: str, env_extra: Dict, nranks: int = 2,
               timeout: float = 300.0) -> Dict:
    """One 2-rank socket world; returns rank 0's JSON report."""
    from mpi_tpu import membership

    rdv = membership.new_rendezvous_dir(prefix="mpi_tpu_hotpath_")
    procs = []
    try:
        for r in range(nranks):
            env = dict(os.environ)
            env.update({"MPI_TPU_RANK": str(r),
                        "MPI_TPU_SIZE": str(nranks),
                        "MPI_TPU_RDV": rdv,
                        "MPI_TPU_BACKEND": "socket",
                        "JAX_PLATFORMS": "cpu"})
            env.update(env_extra)
            procs.append(subprocess.Popen(
                [sys.executable, script], env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True))
        rec: Dict = {}
        for r, p in enumerate(procs):
            try:
                stdout, stderr = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                stdout, stderr = p.communicate()
                raise RuntimeError(
                    f"hotpath rank {r} hung: {stderr[-400:]}")
            if p.returncode != 0:
                raise RuntimeError(f"hotpath rank {r} exited "
                                   f"{p.returncode}: {stderr[-400:]}")
            if r == 0:
                rec = json.loads(stdout.strip().splitlines()[-1])
        return rec
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        membership.cleanup_rendezvous(rdv)


_MODES = {
    # mode -> resilience env overrides
    "healing_off": {"MPI_TPU_LINK_RETRY_S": "0",
                    "MPI_TPU_LINK_RETAIN_COPY": "0"},
    "healing_on_retain_copy": {"MPI_TPU_LINK_RETRY_S": "4.0",
                               "MPI_TPU_LINK_RETAIN_COPY": "1"},
    "healing_on_zero_copy": {"MPI_TPU_LINK_RETRY_S": "4.0",
                             "MPI_TPU_LINK_RETAIN_COPY": "0"},
}


def _allreduce_legs(quick: bool) -> Dict[str, Dict]:
    nbytes = (1 << 20) if quick else (16 << 20)
    iters = 4 if quick else 15
    warmup = 1 if quick else 3
    samples = 1 if quick else 3
    legs: Dict[str, Dict] = {}
    with tempfile.TemporaryDirectory(prefix="mpi_tpu_hotpath_") as td:
        script = os.path.join(td, "hotpath_rank.py")
        with open(script, "w") as f:
            f.write(_PROG.format(repo=REPO, pvars=tuple(_PVARS)))
        base = {"HOTPATH_NBYTES": str(nbytes),
                "HOTPATH_ITERS": str(iters),
                "HOTPATH_WARMUP": str(warmup)}
        for mode, overrides in _MODES.items():
            runs = [_run_world(script, dict(base, **overrides))
                    for _ in range(samples)]
            best = min(runs, key=lambda r: r["p50_us"])
            pv = best["pvars"]
            frames = max(1, pv["msgs_sent"])
            legs[mode] = {
                "nbytes": nbytes, "iters": iters, "samples": samples,
                "p50_us": round(best["p50_us"], 1),
                "p50_us_samples": [round(r["p50_us"], 1) for r in runs],
                "pvars": pv,
                "syscalls_per_frame": round(
                    pv["link_send_syscalls"] / frames, 3),
            }
    return legs


def _lease_arena_leg(quick: bool) -> Dict:
    from mpi_tpu import serve

    with serve.WorldServer(pool_size=2, backend="shm",
                           detect_timeout_s=2.0,
                           heartbeat_s=0.25) as srv:
        client = serve.connect(srv)
        try:
            n = 4096 if quick else 65536
            v1, hits1, names1 = client.run(serve.job_allreduce_arena, n,
                                           nranks=2, timeout=60.0)
            v2, hits2, names2 = client.run(serve.job_allreduce_arena, n,
                                           nranks=2, timeout=60.0)
        finally:
            client.close()
    return {"value": v1, "expect": 3.0,
            "coll_sm_hits_first": hits1, "coll_sm_hits_second": hits2,
            "arena_reused": bool(names1 and names1 == names2),
            "ok": (v1 == 3.0 and v2 == 3.0 and hits1 > 0 and hits2 > 0
                   and bool(names1) and names1 == names2)}


def run_hotpath(quick: bool = False) -> Dict:
    t0 = time.time()
    legs = _allreduce_legs(quick)
    lease = _lease_arena_leg(quick)
    zc = legs["healing_on_zero_copy"]
    off = legs["healing_off"]
    zc_pv, off_pv = zc["pvars"], off["pvars"]
    # the decoupling acceptance: retention priced WITHOUT copies on the
    # no-reuse path, and payload_copies identical to the no-retention
    # floor (retention never leaks into the codec plane's number)
    decoupled = (zc_pv["link_bytes_retained"] > 0
                 and zc_pv["link_cow_snapshots"] == 0
                 and zc_pv["payload_copies"] == off_pv["payload_copies"])
    result = {
        "quick": quick,
        "legs": legs,
        "healing_on_over_off_p50": round(
            zc["p50_us"] / off["p50_us"], 3),
        "retain_copy_over_off_p50": round(
            legs["healing_on_retain_copy"]["p50_us"] / off["p50_us"], 3),
        "retention_without_copy": decoupled,
        "lease_arena": lease,
        "oversubscribed": 3 > (os.cpu_count() or 1),
        "wall_s": round(time.time() - t0, 1),
    }
    result["ok"] = (
        decoupled and lease["ok"]
        # one vectored sendmsg per frame (a 16MB ring frame is 3+ wire
        # parts; pre-sendmsg this ratio was >= 2)
        and zc["syscalls_per_frame"] <= 1.25
        # "within this box's noise": generous on an oversubscribed
        # 2-core host whose cells swing 2-3x — the structural pvars
        # above are the sharp acceptance, the ratio is the honest
        # story.  Quick mode (1 sample, tier-1 smoke) stays
        # structural-only: a single contended sample must not flake CI.
        and (quick or result["healing_on_over_off_p50"] < 1.6))
    return result


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="smoke mode: 1MB payload, 1 sample per mode")
    ap.add_argument("--out-pre", default=None,
                    help="write the eager-retain (ISSUE 10) doc here")
    ap.add_argument("--out-post", default=None,
                    help="write the zero-copy (ISSUE 11) doc here")
    args = ap.parse_args(argv)
    result = run_hotpath(quick=args.quick)
    if args.out_pre:
        pre = {"mode": "eager-retain (ISSUE 10 semantics: "
                       "link_retain_copy=1)",
               "quick": result["quick"],
               "legs": {k: result["legs"][k] for k in
                        ("healing_off", "healing_on_retain_copy")},
               "healing_on_over_off_p50":
                   result["retain_copy_over_off_p50"],
               "oversubscribed": result["oversubscribed"]}
        with open(args.out_pre, "w") as f:
            json.dump(pre, f, indent=2)
    if args.out_post:
        post = {"mode": "zero-copy (ISSUE 11: retention by reference "
                        "+ CoW + sendmsg)",
                "quick": result["quick"],
                "legs": {k: result["legs"][k] for k in
                         ("healing_off", "healing_on_zero_copy")},
                "healing_on_over_off_p50":
                    result["healing_on_over_off_p50"],
                "retention_without_copy":
                    result["retention_without_copy"],
                "lease_arena": result["lease_arena"],
                "oversubscribed": result["oversubscribed"]}
        with open(args.out_post, "w") as f:
            json.dump(post, f, indent=2)
    print(json.dumps(result, indent=2))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
