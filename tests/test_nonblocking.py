"""Nonblocking p2p (Isend/Irecv/Request), Probe/Iprobe semantics."""

import time

import numpy as np
import pytest

from mpi_tpu import Status
from mpi_tpu.transport.local import run_local


def test_irecv_wait():
    def prog(comm):
        if comm.rank == 0:
            req = comm.isend({"k": 1}, dest=1, tag=3)
            assert req.test() == (True, None)
            assert req.wait() is None
            return None
        req = comm.irecv(source=0, tag=3)
        return req.wait()

    res = run_local(prog, 2)
    assert res[1] == {"k": 1}


def test_irecv_test_polls_without_blocking():
    def prog(comm):
        if comm.rank == 0:
            time.sleep(0.15)
            comm.send("late", dest=1, tag=1)
            return None
        req = comm.irecv(source=0, tag=1)
        done, _ = req.test()
        assert not done, "message cannot have arrived yet"
        deadline = time.monotonic() + 5
        while True:
            done, val = req.test()
            if done:
                return val
            assert time.monotonic() < deadline
            time.sleep(0.01)

    res = run_local(prog, 2)
    assert res[1] == "late"


def test_multiple_outstanding_irecvs_fifo():
    def prog(comm):
        if comm.rank == 0:
            for i in range(3):
                comm.isend(i, dest=1, tag=7)
            return None
        reqs = [comm.irecv(source=0, tag=7) for _ in range(3)]
        return [r.wait() for r in reqs]

    res = run_local(prog, 2)
    assert res[1] == [0, 1, 2]


def test_probe_status_then_recv():
    def prog(comm):
        if comm.rank == 0:
            comm.send(np.arange(5), dest=1, tag=42)
            return None
        st = Status()
        comm.probe(source=-1, tag=-1, status=st)
        assert (st.source, st.tag) == (0, 42)
        # probe must not consume
        got = comm.recv(source=st.source, tag=st.tag)
        return got.sum()

    res = run_local(prog, 2)
    assert res[1] == 10


def test_iprobe_preserves_fifo():
    def prog(comm):
        if comm.rank == 0:
            comm.send("first", dest=1, tag=1)
            comm.send("second", dest=1, tag=1)
            return None
        # wait for both to arrive
        deadline = time.monotonic() + 5
        while not comm.iprobe(source=0, tag=1):
            assert time.monotonic() < deadline
            time.sleep(0.005)
        time.sleep(0.05)  # let the second arrive too
        st = Status()
        assert comm.iprobe(source=0, tag=1, status=st)
        assert st.source == 0
        a = comm.recv(source=0, tag=1)
        b = comm.recv(source=0, tag=1)
        return a, b

    res = run_local(prog, 2)
    assert res[1] == ("first", "second")


def test_posted_order_completion_out_of_order_test():
    """MPI matching rule: the first-POSTED request gets the first message,
    even when a later request is tested/completed first."""

    def prog(comm):
        if comm.rank == 0:
            comm.send("a", dest=1, tag=7)
            comm.send("b", dest=1, tag=7)
            return None
        r1 = comm.irecv(source=0, tag=7)
        r2 = comm.irecv(source=0, tag=7)
        deadline = time.monotonic() + 5
        while not r2.test()[0]:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        done, v1 = r1.test()
        assert done
        return v1, r2.wait()

    res = run_local(prog, 2)
    assert res[1] == ("a", "b")


def test_trace_records_polled_receives():
    """Receives completed via Request.test() polling must be visible to the
    matching verifier (they flow through Transport.poll, not the mailbox)."""
    from mpi_tpu.trace import verify_run

    def prog(comm):
        if comm.rank == 0:
            comm.send(1, dest=1, tag=0)
            return None
        req = comm.irecv(source=0, tag=0)
        while not req.test()[0]:
            time.sleep(0.002)

    _, problems = verify_run(prog, 2)
    assert problems == []


def test_poll_on_closed_transport_raises():
    from mpi_tpu.transport.base import Mailbox, TransportError

    mb = Mailbox()
    mb.close()
    with pytest.raises(TransportError):
        mb.poll(0, 0, 1)
    with pytest.raises(TransportError):
        mb.peek_nowait(0, 0, 1)


def test_tpu_nonblocking_diagnostics():
    from mpi_tpu.tpu import SpmdSemanticsError, TpuCommunicator, default_mesh

    comm = TpuCommunicator("world", default_mesh())
    for call in (lambda: comm.isend(1, 0), comm.irecv, comm.probe, comm.iprobe):
        with pytest.raises(SpmdSemanticsError):
            call()


def test_iprobe_false_when_empty():
    def prog(comm):
        assert not comm.iprobe(source=-1, tag=-1)
        comm.barrier()

    run_local(prog, 2)
